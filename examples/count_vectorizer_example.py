"""CountVectorizer vocabulary learning + term counts (reference:
pyflink/examples/ml/feature/countvectorizer_example.py)."""

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature.countvectorizer import CountVectorizer

docs = [["a", "b", "c"], ["a", "b", "b", "c", "a"]]
t = Table({"input": docs})
model = CountVectorizer().set_input_col("input").set_output_col("vector").fit(t)
out = model.transform(t)[0]
print("vocabulary:", model.vocabulary)
for row in out.collect():
    print(row["vector"])
assert set(model.vocabulary) == {"a", "b", "c"}
