"""Train-while-serving with versioned zero-pause model hot-swap.

An OnlineLogisticRegression trainer publishes validated model versions
through `lifecycle.ModelLifecycle` while a `MicroBatchServer` serves the
SAME model through the fused pipeline path — each publication is an
atomic pointer swap the next batch picks up, with zero recompiles and no
serving pause. A NaN-poisoned update is refused at the promotion gate,
and a simulated bad rollout is rolled back bit-exactly to the last-good
version (docs/model_lifecycle.md).
"""

import numpy as np

from flink_ml_tpu import flow
from flink_ml_tpu.lifecycle import ModelLifecycle, PromotionRejected
from flink_ml_tpu.models.classification.onlinelogisticregression import (
    OnlineLogisticRegressionModel,
)
from flink_ml_tpu.pipeline import PipelineModel
from flink_ml_tpu.serving import MicroBatchServer
from flink_ml_tpu.table import Table

DIM = 8
rng = np.random.RandomState(42)
truth = np.linspace(1.0, -1.0, DIM)

model = OnlineLogisticRegressionModel()
model.publish_model_arrays((np.zeros(DIM),), 0)
model.set_features_col("features").set_prediction_col("pred")

lifecycle = ModelLifecycle(model, retained=4, health_window=4, error_rate_trigger=0.5)
server = MicroBatchServer(PipelineModel([model]), in_flight=2, lifecycle=lifecycle)


def trainer():
    """Promote progressively-better coefficients; one poisoned update."""
    for i in range(1, 9):
        candidate = truth * (i / 8.0)
        if i == 4:  # a diverged step: the gate must refuse it
            poisoned = candidate.copy()
            poisoned[0] = np.nan
            try:
                lifecycle.promote((poisoned,))
            except PromotionRejected as e:
                print(f"gate refused update {i}: {e.reason}")
            continue
        entry = lifecycle.promote((candidate,))
        print(f"promoted version {entry.version_id}")


worker = flow.spawn(trainer, name="example.trainer")


def stream(n=12):
    for _ in range(n):
        yield Table({"features": rng.randn(16, DIM).astype(np.float32)})


served_versions = []
for out in server.serve(stream()):
    versions = np.unique(np.asarray(out.column("modelVersion")))
    assert len(versions) == 1, "one batch must be served by exactly one version"
    served_versions.append(int(versions[0]))
worker.join(timeout=60)
assert served_versions == sorted(served_versions), "versions serve monotonically"

lifecycle.record_serve_ok()
good = model.model_version
lifecycle.promote((truth * 100.0,))  # finite but bad: slips the gate...
for _ in range(4):
    lifecycle.record_guard_error(ValueError("downstream guard fired"))
assert model.model_version == good, "rollback restored the last-good version"
print(
    f"served versions {served_versions}; "
    f"{lifecycle.swap_count} swaps, {lifecycle.promote_rejected} refused, "
    f"rolled back to version {model.model_version} after the bad rollout"
)
