"""Normalizer p-norm row scaling (reference:
pyflink/examples/ml/feature/normalizer_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature.normalizer import Normalizer

X = np.array([[3.0, 4.0], [0.0, 5.0], [6.0, 8.0]])
out = (
    Normalizer().set_p(2.0).set_input_col("input").set_output_col("output")
    .transform(Table({"input": X}))[0]
)
normalized = np.asarray(out.column("output"))
print(normalized)
np.testing.assert_allclose(np.linalg.norm(normalized, axis=1), 1.0, atol=1e-6)
