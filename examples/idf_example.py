"""IDF inverse-document-frequency weighting over term-count vectors
(reference: pyflink/examples/ml/feature/idf_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature.idf import IDF

# rows are documents, columns are terms (e.g. HashingTF / CountVectorizer
# output); term 0 appears in every document, term 2 in only one
counts = np.array(
    [
        [1.0, 1.0, 0.0],
        [1.0, 0.0, 0.0],
        [2.0, 1.0, 1.0],
    ]
)
t = Table({"input": counts})
model = IDF().set_input_col("input").set_output_col("output").fit(t)
out = model.transform(t)[0]
print("idf:", model.idf)
print(np.asarray(out.column("output")))
# IDF(t) = log((n+1) / (df+1)): the everywhere-term gets the smallest
# weight, the rarest term the largest
expected = np.log((3.0 + 1.0) / (np.array([3.0, 2.0, 1.0]) + 1.0))
np.testing.assert_allclose(model.idf, expected, atol=1e-12)
np.testing.assert_allclose(np.asarray(out.column("output")), counts * expected)
