"""Out-of-core training: a one-pass StreamTable trains through the native
spillable data cache with the same batch schedule as in-memory fits
(reference: the ReplayOperator cache-then-replay contract,
flink-ml-iteration/.../operator/ReplayOperator.java:125-246)."""

import numpy as np

from flink_ml_tpu import StreamTable, Table
from flink_ml_tpu.models.classification.logisticregression import LogisticRegression

rng = np.random.default_rng(11)
truth = np.array([1.0, -1.0, 0.5, 2.0])

def chunk(n=64):
    X = rng.random((n, 4))
    return Table({"features": X, "label": (X @ truth > 1.2).astype(float)})

stream = StreamTable(iter([chunk() for _ in range(8)]))
model = LogisticRegression().set_max_iter(200).set_learning_rate(0.5).set_global_batch_size(128).fit(stream)
test = chunk(256)
pred = np.asarray(model.transform(test)[0].column("prediction"))
acc = (pred == np.asarray(test.column("label"))).mean()
print("accuracy:", acc)
assert acc > 0.8
