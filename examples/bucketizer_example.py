"""Bucketizer split-based binning (reference:
pyflink/examples/ml/feature/bucketizer_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature.bucketizer import Bucketizer

t = Table({"f1": [-0.5, 0.5, 1.5]})
out = (
    Bucketizer()
    .set_input_cols("f1")
    .set_output_cols("b1")
    .set_splits_array([[-float("inf"), 0.0, 1.0, float("inf")]])
    .transform(t)[0]
)
print(np.asarray(out.column("b1")))
np.testing.assert_array_equal(np.asarray(out.column("b1")), [0.0, 1.0, 2.0])
