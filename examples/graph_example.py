"""GraphBuilder DAG of stages (reference:
flink-ml-examples/.../GraphExample.java, builder/GraphBuilder.java)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.graph import GraphBuilder
from flink_ml_tpu.models.classification.logisticregression import LogisticRegression
from flink_ml_tpu.models.feature.standardscaler import StandardScaler

builder = GraphBuilder()
source = builder.create_table_id()
scaler = (
    StandardScaler().set_input_col("features").set_output_col("scaled")
)
lr = LogisticRegression().set_features_col("scaled").set_max_iter(20)
scaled = builder.add_estimator(scaler, [source])
outputs = builder.add_estimator(lr, [scaled[0]])
graph = builder.build_estimator([source], [outputs[0]])

rng = np.random.default_rng(10)
X = np.vstack([rng.normal(1, 0.3, (40, 4)), rng.normal(-1, 0.3, (40, 4))])
y = np.array([1.0] * 40 + [0.0] * 40)
model = graph.fit(Table({"features": X, "label": y}))
out = model.transform(Table({"features": X, "label": y}))[0]
acc = (np.asarray(out.column("prediction")) == y).mean()
print("accuracy:", acc)
assert acc > 0.9
