"""vector_to_array / array_to_vector column conversions (reference:
pyflink/examples/ml/vectortoarray_example.py, Functions.java:10-38)."""

import numpy as np

from flink_ml_tpu import Table, array_to_vector, vector_to_array
from flink_ml_tpu.linalg import Vectors

t = Table({"vec": [Vectors.sparse(3, [1], [5.0]), Vectors.dense(1.0, 2.0, 3.0)]})
arrays = vector_to_array(t.column("vec"))
print(arrays)
back = array_to_vector(arrays)
round_tripped = Table({"vec": back})
np.testing.assert_array_equal(arrays, [[0.0, 5.0, 0.0], [1.0, 2.0, 3.0]])
assert round_tripped.num_rows == 2
