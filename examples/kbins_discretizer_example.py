"""KBinsDiscretizer quantile binning (reference:
pyflink/examples/ml/feature/kbinsdiscretizer_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature.kbinsdiscretizer import KBinsDiscretizer

X = np.linspace(0, 10, 50)[:, None]
model = KBinsDiscretizer().set_strategy("uniform").set_num_bins(5).fit(
    Table({"input": X})
)
out = model.transform(Table({"input": X}))[0]
bins = np.asarray(out.column("output"))
print(sorted(set(bins.ravel())))
assert set(bins.ravel()) == {0.0, 1.0, 2.0, 3.0, 4.0}
