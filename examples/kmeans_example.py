"""KMeans clustering — Lloyd iterations as MXU matmuls (reference:
pyflink/examples/ml/clustering/kmeans_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.clustering.kmeans import KMeans

rng = np.random.default_rng(3)
X = np.vstack([rng.normal(0, 0.2, (50, 2)), rng.normal(5, 0.2, (50, 2))])
model = KMeans().set_k(2).set_seed(7).fit(Table({"features": X}))
out = model.transform(Table({"features": X}))[0]
pred = np.asarray(out.column("prediction"))
print("cluster sizes:", np.bincount(pred.astype(int)))
assert len(set(pred[:50])) == 1 and len(set(pred[50:])) == 1 and pred[0] != pred[-1]
