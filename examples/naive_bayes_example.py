"""Multinomial NaiveBayes (reference:
pyflink/examples/ml/classification/naivebayes_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.linalg import Vectors
from flink_ml_tpu.models.classification.naivebayes import NaiveBayes

train = Table(
    {
        "features": [Vectors.dense(0, 0), Vectors.dense(0, 1),
                     Vectors.dense(1, 0), Vectors.dense(1, 1)],
        "label": [11.0, 11.0, 22.0, 22.0],
    }
)
model = NaiveBayes().set_smoothing(1.0).fit(train)
out = model.transform(train)[0]
print(np.asarray(out.column("prediction")))
assert (np.asarray(out.column("prediction")) == [11.0, 11.0, 22.0, 22.0]).all()
