"""LogisticRegression train + inference (reference:
pyflink/examples/ml/classification/logisticregression_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.linalg import Vectors
from flink_ml_tpu.models.classification.logisticregression import LogisticRegression

train = Table(
    {
        "features": [Vectors.dense(1, 2, 3, 4), Vectors.dense(2, 2, 3, 4),
                     Vectors.dense(3, 2, 3, 4), Vectors.dense(4, 2, 3, 4),
                     Vectors.dense(11, 3, 4, 5), Vectors.dense(12, 3, 4, 5),
                     Vectors.dense(13, 3, 4, 5), Vectors.dense(14, 3, 4, 5)],
        "label": [0.0, 0.0, 0.0, 0.0, 1.0, 1.0, 1.0, 1.0],
        "weight": [1.0, 2.0, 3.0, 4.0, 1.0, 2.0, 3.0, 4.0],
    }
)
lr = LogisticRegression().set_weight_col("weight").set_max_iter(60)
model = lr.fit(train)
out = model.transform(train)[0]
for row in out.collect():
    print(row["features"], "->", row["prediction"])
pred = np.asarray(out.column("prediction"))
assert (pred == np.asarray(train.column("label"))).all()
