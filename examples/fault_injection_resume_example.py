"""Preemption-safe training — kill a LogisticRegression fit mid-training
with the fault-injection harness (the reference's FailingMap idiom,
BoundedAllRoundCheckpointITCase.java), then resume from the JobSnapshot
and land on the uninterrupted run's EXACT coefficients. See
docs/fault_tolerance.md for the snapshot format and contracts."""

import tempfile

import numpy as np

from flink_ml_tpu import Table, config
from flink_ml_tpu.ckpt import InjectedFault, faults
from flink_ml_tpu.models.classification.logisticregression import LogisticRegression

rng = np.random.default_rng(3)
X = rng.standard_normal((2_000, 16)).astype(np.float32)
y = (X @ rng.standard_normal(16).astype(np.float32) > 0).astype(np.float32)
train = Table({"features": X, "label": y})


def estimator():
    return (
        LogisticRegression().set_max_iter(30).set_global_batch_size(500).set_tol(0.0)
    )


ckpt_dir = tempfile.mkdtemp() + "/job_ckpt"
with config.iteration_checkpointing(ckpt_dir):
    # a reference run in the same (checkpointed, chunked) configuration
    expected = estimator().fit(train).coefficient
    import os, shutil  # noqa: E401

    shutil.rmtree(ckpt_dir)  # forget the reference's snapshots

    # "preemption": the harness kills the fit at the 10th epoch chunk —
    # AFTER that boundary's snapshot was committed (temp + os.replace)
    try:
        with faults.inject("chunk", after=10):
            estimator().fit(train)
    except InjectedFault as e:
        print(f"fit killed by the harness: {e}")

    # restart: the fit restores the JobSnapshot (model carry, optimizer
    # state, epoch, batch-schedule cursors) and finishes the job
    resumed = estimator().fit(train).coefficient

np.testing.assert_array_equal(np.asarray(resumed), np.asarray(expected))
print("kill -> resume reproduced the uninterrupted run bit-for-bit")
