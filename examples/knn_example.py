"""Knn brute-force classifier (reference:
pyflink/examples/ml/classification/knn_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.classification.knn import Knn

train = Table(
    {
        "features": [[0.0, 0.0], [0.2, 0.1], [9.0, 9.0], [9.2, 9.1]],
        "label": [1.0, 1.0, 2.0, 2.0],
    }
)
model = Knn().set_k(3).fit(train)
out = model.transform(Table({"features": [[0.1, 0.0], [9.1, 9.0]]}))[0]
print(np.asarray(out.column("prediction")))
assert (np.asarray(out.column("prediction")) == [1.0, 2.0]).all()
