"""LinearRegression least-squares fit (reference:
pyflink/examples/ml/regression/linearregression_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.regression.linearregression import LinearRegression

rng = np.random.default_rng(2)
X = rng.random((200, 4))
truth = np.array([1.0, -2.0, 3.0, 0.5])
y = X @ truth
model = (
    LinearRegression().set_max_iter(300).set_learning_rate(0.5).fit(
        Table({"features": X, "label": y})
    )
)
out = model.transform(Table({"features": X}))[0]
mse = float(np.mean((np.asarray(out.column("prediction")) - y) ** 2))
print("mse:", mse)
assert mse < 0.05
