"""Quick start: scale features, train logistic regression, save/load,
evaluate — the v0 pipeline (reference:
docs/content/docs/try-flink-ml/python/quick-start.md,
flink-ml-examples LogisticRegressionExample.java)."""

import shutil

import numpy as np

from flink_ml_tpu import Pipeline, PipelineModel, Table
from flink_ml_tpu.models.classification.logisticregression import LogisticRegression
from flink_ml_tpu.models.evaluation.binaryclassification import (
    BinaryClassificationEvaluator,
)
from flink_ml_tpu.models.feature.standardscaler import StandardScaler

rng = np.random.default_rng(0)
X = np.vstack([rng.normal(2.0, 1.0, (500, 8)), rng.normal(-2.0, 1.0, (500, 8))])
y = np.array([1.0] * 500 + [0.0] * 500)
train = Table({"features": X, "label": y})

pipeline = Pipeline(
    [
        StandardScaler().set_input_col("features").set_output_col("scaled"),
        LogisticRegression().set_features_col("scaled").set_max_iter(30),
    ]
)
model = pipeline.fit(train)

shutil.rmtree("/tmp/quickstart_model", ignore_errors=True)
model.save("/tmp/quickstart_model")
model = PipelineModel.load("/tmp/quickstart_model")

scored = model.transform(train)[0]
metrics = (
    BinaryClassificationEvaluator()
    .set_metrics_names("areaUnderROC", "ks")
    .transform(scored)[0]
    .collect()[0]
)
accuracy = float((np.asarray(scored.column("prediction")) == y).mean())
print(f"accuracy={accuracy:.3f} auc={metrics['areaUnderROC']:.3f} ks={metrics['ks']:.3f}")
assert accuracy > 0.95 and metrics["areaUnderROC"] > 0.95
