"""ChiSqTest independence statistics (reference:
pyflink/examples/ml/stats/chisqtest_example.py)."""

from flink_ml_tpu import Table
from flink_ml_tpu.linalg import Vectors
from flink_ml_tpu.models.stats.chisqtest import ChiSqTest

t = Table(
    {
        "features": [Vectors.dense(0, 1), Vectors.dense(0, 2),
                     Vectors.dense(1, 1), Vectors.dense(1, 2)] * 5,
        "label": [0.0, 1.0, 0.0, 1.0] * 5,
    }
)
out = ChiSqTest().transform(t)[0]
row = out.collect()[0]
print("pValues:", row["pValues"])
assert row["pValues"].size() == 2
