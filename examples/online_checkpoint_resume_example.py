"""Online training checkpoint/resume — kill an OnlineLogisticRegression
fit mid-stream and resume from the checkpoint against a replayed source,
reproducing the uninterrupted run exactly (reference semantics:
flink-ml-iteration/src/main/java/org/apache/flink/iteration/checkpoint/
Checkpoints.java — unbounded iterations ride exactly-once checkpointing)."""

import tempfile

import numpy as np

from flink_ml_tpu import StreamTable, Table, config
from flink_ml_tpu.linalg import DenseVector
from flink_ml_tpu.models.classification.onlinelogisticregression import (
    OnlineLogisticRegression,
)

rng = np.random.default_rng(0)
truth = np.array([1.5, -2.0, 0.5, 1.0])
X = rng.random((640, 4)) * 2 - 1
y = (X @ truth > 0).astype(float)


def replayed_stream():
    """The same batches every time — a replayable source (file, log, ...)."""
    return StreamTable.from_batches(
        [Table({"features": X[i : i + 64], "label": y[i : i + 64]}) for i in range(0, 640, 64)]
    )


def estimator():
    return (
        OnlineLogisticRegression()
        .set_global_batch_size(128)
        .set_initial_model_data(Table({"coefficient": [DenseVector(np.zeros(4))]}))
    )


# uninterrupted run: 5 global batches of 128
full = estimator().fit(replayed_stream())
full.process_updates()

ckpt_dir = tempfile.mkdtemp() + "/online_ckpt"
with config.iteration_checkpointing(ckpt_dir):
    # train, but "crash" after only 2 of the 5 global batches
    interrupted = estimator().fit(replayed_stream())
    interrupted.process_updates(max_batches=2)
    print("crashed at model version", interrupted.model_version)

    # restart: the checkpoint restores (model, FTRL state, stream position);
    # the already-consumed prefix of the replayed source is skipped
    resumed = estimator().fit(replayed_stream())
    resumed.process_updates()

print("resumed to version", resumed.model_version, "(uninterrupted:", full.model_version, ")")
assert resumed.model_version == full.model_version == 5
np.testing.assert_array_equal(resumed.coefficient, full.coefficient)
print("resumed coefficients identical to the uninterrupted run")
