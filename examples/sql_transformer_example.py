"""SQLTransformer — SELECT/WHERE over a table with vector columns
(reference: feature/sqltransformer/SQLTransformer.java; statements run
against `__THIS__`). Projections and WHERE filters over vector columns
evaluate columnwise on whole arrays — no row-at-a-time SQL engine."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature.sqltransformer import SQLTransformer

rng = np.random.default_rng(0)
t = Table(
    {
        "features": rng.standard_normal((8, 3)),
        "score": np.round(rng.random(8), 2),
        "id": np.arange(8.0),
    }
)

out = (
    SQLTransformer()
    .set_statement(
        "SELECT id, features * 2 AS scaled, SQRT(score) AS conf "
        "FROM __THIS__ WHERE score >= 0.4 AND NOT id = 3"
    )
    .transform(t)[0]
)

kept = np.asarray(out.column("id"))
print("kept rows:", kept)
mask = (np.asarray(t.column("score")) >= 0.4) & (np.arange(8.0) != 3)
np.testing.assert_array_equal(kept, np.arange(8.0)[mask])
np.testing.assert_allclose(
    np.asarray(out.column("scaled")), np.asarray(t.column("features"))[mask] * 2
)

# aggregations fall back to a SQL engine transparently
agg = (
    SQLTransformer()
    .set_statement("SELECT COUNT(*) AS n, AVG(score) AS mean_score FROM __THIS__")
    .transform(t)[0]
)
print("count:", agg.collect()[0]["n"], "mean score:", round(agg.collect()[0]["mean_score"], 3))
assert agg.collect()[0]["n"] == 8
