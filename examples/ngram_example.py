"""NGram sliding-window token joining (reference:
pyflink/examples/ml/feature/ngram_example.py)."""

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature.ngram import NGram

t = Table(
    {
        "input": [
            [],
            ["a", "b", "c"],
            ["a", "b", "c", "d"],
        ]
    }
)
out = NGram().set_n(2).set_input_col("input").set_output_col("output").transform(t)[0]
for row in out.collect():
    print(list(row["input"]), "->", list(row["output"]))
rows = out.collect()
assert list(rows[0]["output"]) == []
assert list(rows[1]["output"]) == ["a b", "b c"]
assert list(rows[2]["output"]) == ["a b", "b c", "c d"]
