"""Fused micro-batch serving: a device pipeline driven over a batch
stream by MicroBatchServer (docs/performance.md §5-6). The three stages
compile into ONE device program; batches pad to power-of-two buckets so
two of the three batch sizes share a compiled shape."""

import numpy as np

import jax

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature.normalizer import Normalizer
from flink_ml_tpu.models.feature.standardscaler import StandardScalerModel
from flink_ml_tpu.models.feature.vectorassembler import VectorAssembler
from flink_ml_tpu.pipeline import PipelineModel
from flink_ml_tpu.serving import MicroBatchServer
from flink_ml_tpu.table import StreamTable
from flink_ml_tpu.utils import metrics

rng = np.random.RandomState(0)

scaler = StandardScalerModel()
scaler.mean = rng.randn(5)
scaler.std = np.abs(rng.randn(5)) + 0.1
scaler.set_input_col("assembled").set_output_col("scaled")

model = PipelineModel(
    [
        VectorAssembler().set_input_cols("a", "b").set_output_col("assembled"),
        scaler,
        Normalizer().set_p(2.0).set_input_col("scaled").set_output_col("norm"),
    ]
)

batches = [
    Table({"a": rng.randn(n, 2).astype(np.float32), "b": rng.randn(n, 3).astype(np.float32)})
    for n in (6, 8, 21)
]
server = MicroBatchServer(model, in_flight=2)
for i, out in enumerate(server.serve(StreamTable.from_batches(batches))):
    norm = np.asarray(out.column("norm"))
    print(f"batch {i}: {norm.shape[0]} rows served, first row norm {np.linalg.norm(norm[0]):.4f}")
    assert norm.shape[0] == batches[i].num_rows  # padding sliced back off
    np.testing.assert_allclose(np.linalg.norm(norm, axis=1), 1.0, atol=1e-5)

assert metrics.get_gauge("pipeline.fused_stages") == 3  # whole pipeline fused
assert metrics.get_gauge("serving.buckets") == 2  # {8, 32}: sizes 6+8 share one shape
