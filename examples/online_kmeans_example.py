"""OnlineKMeans — decayed centroid updates over an unbounded stream
(reference: pyflink/examples/ml/clustering/onlinekmeans_example.py)."""

import numpy as np

from flink_ml_tpu import StreamTable, Table
from flink_ml_tpu.models.clustering.onlinekmeans import (
    OnlineKMeans,
    generate_random_model_data,
)

rng = np.random.default_rng(4)
batches = [
    Table({"features": np.vstack([rng.normal(0, 0.1, (8, 2)),
                                  rng.normal(8, 0.1, (8, 2))])})
    for _ in range(5)
]
okm = (
    OnlineKMeans()
    .set_global_batch_size(16)
    .set_initial_model_data(generate_random_model_data(2, 2, 0.0, seed=5))
)
model = okm.fit(StreamTable.from_batches(batches))
model.process_updates()
print("model version:", model.model_version)
assert model.model_version == 5
