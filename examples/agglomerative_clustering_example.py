"""AgglomerativeClustering with a merge-log side output (reference:
pyflink/examples/ml/clustering/agglomerativeclustering_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.clustering.agglomerativeclustering import (
    AgglomerativeClustering,
)

X = np.array([[0.0, 0.0], [0.1, 0.0], [5.0, 5.0], [5.1, 5.0], [10.0, 0.0]])
outputs, merge_log = (
    AgglomerativeClustering().set_num_clusters(3).set_linkage("average").transform(
        Table({"features": X})
    )
)
pred = np.asarray(outputs.column("prediction"))
print("labels:", pred)
print("merges:", merge_log.collect())
assert pred[0] == pred[1] and pred[2] == pred[3] and pred[4] not in (pred[0], pred[2])
