"""LinearSVC hinge-loss binary classifier (reference:
pyflink/examples/ml/classification/linearsvc_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.classification.linearsvc import LinearSVC

rng = np.random.default_rng(1)
X = np.vstack([rng.normal(2, 0.5, (60, 3)), rng.normal(-2, 0.5, (60, 3))])
y = np.array([1.0] * 60 + [0.0] * 60)
model = LinearSVC().set_max_iter(50).fit(Table({"features": X, "label": y}))
out = model.transform(Table({"features": X}))[0]
pred = np.asarray(out.column("prediction"))
print("accuracy:", (pred == y).mean())
assert (pred == y).mean() > 0.95
