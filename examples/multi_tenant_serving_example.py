"""Multi-tenant serving from one mesh: HBM-paged models + live hot-swap.

Three tenants each serve their own model from ONE `MicroBatchServer`
with continuous batching, routed through a `data.modelstore.ModelStore`
whose byte budget deliberately fits only two of the three models — the
store pages model constants host<->HBM under LRU, with every resident
byte on the `hbm.live.model` ledger and ZERO recompiles on page-in
(model tensors are runtime operands of the compiled plan). Mid-load,
tenant "b"'s model is hot-swapped through the store's lifecycle ring
without pausing the server (docs/serving.md).
"""

import time

import numpy as np

from flink_ml_tpu import flow
from flink_ml_tpu.data.modelstore import ModelStore
from flink_ml_tpu.lifecycle import ModelLifecycle
from flink_ml_tpu.models.classification.onlinelogisticregression import (
    OnlineLogisticRegressionModel,
)
from flink_ml_tpu.obs import memledger
from flink_ml_tpu.pipeline import PipelineModel
from flink_ml_tpu.serving import MicroBatchServer, ServerOverloaded
from flink_ml_tpu.table import Table

DIM = 64
TENANTS = ("a", "b", "c")
rng = np.random.RandomState(7)


def make_model(seed):
    m = OnlineLogisticRegressionModel()
    m.publish_model_arrays((np.random.RandomState(seed).randn(DIM),), 0)
    m.set_features_col("features").set_prediction_col("pred")
    return PipelineModel([m])


models = {t: make_model(i) for i, t in enumerate(TENANTS)}
olr = {t: pm.stages[0] for t, pm in models.items()}  # the swap-capable stage

# budget for ~2 of the 3 models: serving all three MUST page
probe = ModelStore(budget_bytes=None)
probe.register("a", models["a"])
per_model = probe.estimated_nbytes("a")
budget = int(per_model * 2.3)
store = ModelStore(budget_bytes=budget)
for t in TENANTS:
    lc = ModelLifecycle(olr[t]) if t == "b" else None
    store.register(t, models[t], lifecycle=lc, quota=8)
print(f"3 models x {per_model} bytes (est) into a {budget}-byte budget")

server = MicroBatchServer(
    store=store, batching="continuous", form_rows=16, buckets=(16,), admission=32
)
results = []
collector = flow.spawn(lambda: results.extend(server.results()), name="example.collect")


def submit_round_robin(count):
    peak = 0
    for i in range(count):
        batch = Table({"features": rng.randn(4, DIM).astype(np.float32)})
        while True:  # closed-loop: wait out transient overload
            try:
                server.submit(batch, tenant=TENANTS[i % len(TENANTS)])
                break
            except ServerOverloaded:
                time.sleep(0.002)
        peak = max(peak, memledger.live_bytes("model"))
    return peak


peak = submit_round_robin(15)

# live hot-swap: tenant b's new version promotes through the store's
# lifecycle ring (validation gate + version ring) and restages its
# residency — the server never pauses and the plan never recompiles
new_coeff = np.linspace(1.0, -1.0, DIM)
mv = store.promote("b", (new_coeff,))
print(f"hot-swapped tenant b to version {mv.version_id} mid-load")

peak = max(peak, submit_round_robin(15))
server.close()
collector.join(timeout=60)
assert not collector.is_alive()

assert len(results) == 30 and all(r.status == "ok" for r in results)
assert peak <= budget, f"hbm.live.model peaked at {peak} over {budget}"
stats = store.stats
assert stats["evictions"] > 0, "three models in a two-model budget must evict"
store.check_ledger_parity()
store.page_in("b")
swapped = np.asarray(olr["b"].device_constants()["coefficient"])
np.testing.assert_array_equal(swapped, new_coeff.astype(swapped.dtype))

by_tenant = {t: sum(1 for r in results if r.tenant == t) for t in TENANTS}
print(f"served {by_tenant} requests; store stats {stats}")
print(f"peak model bytes {peak} <= budget {budget}; coefficients live-swapped")
