"""Wide sparse logistic regression — Criteo-style dims train natively on
padded-CSR batches, never densified (dense at dim 100k would already be
GBs per 1k rows). SURVEY §2.3's feature-sharded TP layout is the same
engine with shard_features=True on a (data, model) mesh."""

import numpy as np

from flink_ml_tpu import SparseBatch, Table
from flink_ml_tpu.models.classification.logisticregression import LogisticRegression

DIM = 100_000
rng = np.random.default_rng(9)
n, nnz = 2048, 10
indices = rng.integers(0, DIM, size=(n, nnz)).astype(np.int32)
values = rng.random((n, nnz))
hot = rng.choice(DIM, 500, replace=False)
y = np.isin(indices, hot).any(axis=1).astype(float)

t = Table({"features": SparseBatch(DIM, indices, values), "label": y})
model = LogisticRegression().set_max_iter(10).set_global_batch_size(512).fit(t)
out = model.transform(t)[0]
print("model dim:", model.coefficient.shape, "predictions:", out.num_rows)
assert model.coefficient.shape == (DIM,)
