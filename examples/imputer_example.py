"""Imputer missing-value completion (reference:
pyflink/examples/ml/feature/imputer_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature.imputer import Imputer

t = Table({"f1": [1.0, 2.0, float("nan"), 5.0]})
model = Imputer().set_input_cols("f1").set_output_cols("o1").fit(t)
out = model.transform(t)[0]
o = np.asarray(out.column("o1"))
print(o)
np.testing.assert_allclose(o[2], (1.0 + 2.0 + 5.0) / 3)
