"""FeatureHasher mixed numeric/categorical hashing into a fixed-width
vector (reference: pyflink/examples/ml/feature/featurehasher_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature.featurehasher import FeatureHasher

t = Table(
    {
        "f0": ["a", "b", "a"],
        "f1": [1.1, 0.0, 2.5],
        "f2": [True, False, True],
    }
)
out = (
    FeatureHasher()
    .set_input_cols("f0", "f1", "f2")
    .set_categorical_cols("f0")
    .set_output_col("vec")
    .set_num_features(64)
    .transform(t)[0]
)
vecs = np.stack([np.asarray(row["vec"].to_array()) for row in out.collect()])
for v in vecs:
    print(np.nonzero(v)[0], v[np.nonzero(v)[0]])
assert vecs.shape == (3, 64)
# rows 0 and 2 share the categorical bucket for f0=a and the boolean
# bucket for f2=true; each row hashes at most one bucket per column
assert (np.count_nonzero(vecs, axis=1) <= 3).all()
np.testing.assert_array_equal(
    np.nonzero(vecs[0])[0][:1], np.nonzero(vecs[2])[0][:1]
)
