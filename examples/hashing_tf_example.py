"""Tokenizer -> HashingTF term-frequency pipeline (reference:
pyflink/examples/ml/feature/hashingtf_example.py)."""

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature.hashingtf import HashingTF
from flink_ml_tpu.models.feature.tokenizer import Tokenizer

t = Table({"sentence": ["hashingTF is a transformer", "it hashes terms"]})
tokens = Tokenizer().set_input_col("sentence").set_output_col("words").transform(t)[0]
out = (
    HashingTF().set_input_col("words").set_output_col("tf").set_num_features(128)
    .transform(tokens)[0]
)
for row in out.collect():
    print(row["words"], "->", row["tf"])
assert out.collect()[0]["tf"].size() == 128
