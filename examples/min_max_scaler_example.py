"""MinMaxScaler range normalization (reference:
pyflink/examples/ml/feature/minmaxscaler_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature.minmaxscaler import MinMaxScaler

X = np.array([[0.0, 3.0], [2.1, 0.0], [4.1, 5.1]])
model = MinMaxScaler().fit(Table({"input": X}))
out = model.transform(Table({"input": X}))[0]
scaled = np.asarray(out.column("output"))
print(scaled)
assert scaled.min() >= -1e-6 and scaled.max() <= 1.0 + 1e-6
