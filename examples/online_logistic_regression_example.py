"""OnlineLogisticRegression — FTRL-Proximal over a stream (reference:
pyflink/examples/ml/classification/onlinelogisticregression_example.py)."""

import numpy as np

from flink_ml_tpu import StreamTable, Table
from flink_ml_tpu.linalg import DenseVector
from flink_ml_tpu.models.classification.onlinelogisticregression import (
    OnlineLogisticRegression,
)

rng = np.random.default_rng(5)
truth = np.array([2.0, -3.0, 1.0])

def batch(n=32):
    X = rng.random((n, 3)) * 2 - 1
    y = (X @ truth > 0).astype(float)
    return Table({"features": X, "label": y})

olr = (
    OnlineLogisticRegression()
    .set_global_batch_size(32)
    .set_initial_model_data(Table({"coefficient": [DenseVector(np.zeros(3))]}))
)
model = olr.fit(StreamTable.from_batches([batch() for _ in range(40)]))
model.process_updates()
test = batch(200)
pred = np.asarray(model.transform(test)[0].column("prediction"))
acc = (pred == np.asarray(test.column("label"))).mean()
print("model version:", model.model_version, "accuracy:", acc)
assert acc > 0.9
