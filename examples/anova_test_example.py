"""ANOVATest F-statistics (reference:
pyflink/examples/ml/stats/anovatest_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.stats.anovatest import ANOVATest

rng = np.random.default_rng(8)
X = rng.random((40, 3))
y = (X[:, 0] > 0.5).astype(float)
out = ANOVATest().transform(Table({"features": X, "label": y}))[0]
row = out.collect()[0]
print("pValues:", row["pValues"])
assert row["pValues"].size() == 3
