"""VectorAssembler column concatenation (reference:
pyflink/examples/ml/feature/vectorassembler_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature.vectorassembler import VectorAssembler

t = Table({"a": [1.0, 2.0], "b": np.array([[10.0, 11.0], [20.0, 21.0]])})
out = (
    VectorAssembler().set_input_cols("a", "b").set_output_col("vec").transform(t)[0]
)
vec = np.asarray(out.column("vec"))
print(vec)
np.testing.assert_array_equal(vec, [[1.0, 10.0, 11.0], [2.0, 20.0, 21.0]])
