"""StringIndexer + IndexToString round trip (reference:
pyflink/examples/ml/feature/stringindexer_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature.stringindexer import StringIndexer

t = Table({"color": ["red", "blue", "red", "green"]})
model = (
    StringIndexer()
    .set_input_cols("color")
    .set_output_cols("color_idx")
    .set_string_order_type("alphabetAsc")
    .fit(t)
)
out = model.transform(t)[0]
print(np.asarray(out.column("color_idx")))
np.testing.assert_array_equal(np.asarray(out.column("color_idx")), [2.0, 0.0, 2.0, 1.0])
