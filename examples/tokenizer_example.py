"""Tokenizer lowercase whitespace splitting (reference:
pyflink/examples/ml/feature/tokenizer_example.py)."""

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature.tokenizer import Tokenizer

t = Table({"input": ["Test of Tokenize", "Another Test"]})
out = Tokenizer().set_input_col("input").set_output_col("output").transform(t)[0]
for row in out.collect():
    print(row["input"], "->", list(row["output"]))
rows = out.collect()
assert list(rows[0]["output"]) == ["test", "of", "tokenize"]
assert list(rows[1]["output"]) == ["another", "test"]
