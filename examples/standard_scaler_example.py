"""StandardScaler mean/std normalization (reference:
pyflink/examples/ml/feature/standardscaler_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature.standardscaler import StandardScaler

X = np.array([[1.0, 10.0], [2.0, 20.0], [3.0, 30.0]])
model = StandardScaler().set_with_mean(True).set_input_col("input").set_output_col("output").fit(
    Table({"input": X})
)
out = model.transform(Table({"input": X}))[0]
scaled = np.asarray(out.column("output"))
print(scaled)
np.testing.assert_allclose(scaled.mean(axis=0), 0.0, atol=1e-7)
