"""BinaryClassificationEvaluator — AUC/AUPR/KS/Lorenz on device (reference:
pyflink/examples/ml/evaluation/binaryclassificationevaluator_example.py)."""

import numpy as np

from flink_ml_tpu import Table
from flink_ml_tpu.models.evaluation.binaryclassification import (
    BinaryClassificationEvaluator,
)

rng = np.random.default_rng(6)
scores = rng.random(1000)
labels = (rng.random(1000) < scores).astype(float)
raw = np.stack([1 - scores, scores], axis=1)
result = (
    BinaryClassificationEvaluator()
    .set_metrics_names("areaUnderROC", "areaUnderPR", "ks", "areaUnderLorenz")
    .transform(Table({"label": labels, "rawPrediction": raw}))[0]
    .collect()[0]
)
print({k: round(v, 4) for k, v in result.items()})
assert 0.7 < result["areaUnderROC"] < 1.0
