"""OneHotEncoder to sparse vectors (reference:
pyflink/examples/ml/feature/onehotencoder_example.py)."""

from flink_ml_tpu import Table
from flink_ml_tpu.models.feature.onehotencoder import OneHotEncoder

t = Table({"input": [0.0, 1.0, 2.0, 0.0]})
model = OneHotEncoder().set_input_cols("input").set_output_cols("output").fit(t)
out = model.transform(t)[0]
for row in out.collect():
    print(row["input"], "->", row["output"])
first = out.collect()[0]["output"]
assert first.size() == 2  # drop-last leaves 2 of 3 categories
