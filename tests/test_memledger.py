"""HBM ledger battery (docs/observability.md "Device memory"): ownership
and tracked accounting, peak watermarks, the `memory` timeline lane,
budget admission (typed rejection + loose-vs-off bit-identity), OOM
forensics, and the fit-end ledger-parity acceptance criterion."""

import gc
import json
import os
import sys

import numpy as np
import pytest

import jax

from flink_ml_tpu import config
from flink_ml_tpu.data.devicecache import DeviceEpochCache
from flink_ml_tpu.linalg import Vectors
from flink_ml_tpu.obs import memledger, timeline
from flink_ml_tpu.parallel import prefetch
from flink_ml_tpu.table import Table
from flink_ml_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _clean_ledger():
    memledger.reset()
    yield
    memledger.reset()


def _nbytes(tree):
    return sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(tree)
        if isinstance(leaf, jax.Array)
    )


# ---------------------------------------------------------------------------
# core accounting: ownership + tracked modes
# ---------------------------------------------------------------------------

def test_register_release_gauges_and_categories():
    h = memledger.register("model", 4096, (32, 32), "float32", "x.py:1")
    assert memledger.live_bytes() == 4096
    assert memledger.live_bytes("model") == 4096
    assert metrics.snapshot()["gauges"]["hbm.live.model"] == 4096
    assert metrics.snapshot()["gauges"]["hbm.live"] == 4096
    memledger.release(h)
    assert memledger.live_bytes() == 0
    assert metrics.snapshot()["gauges"]["hbm.live.model"] == 0
    # double release and None are no-ops
    memledger.release(h)
    memledger.release(None)
    assert memledger.live_bytes() == 0
    with pytest.raises(ValueError, match="unknown ledger category"):
        memledger.register("heap", 1)


def test_track_releases_on_gc_and_never_double_counts():
    arr = jax.device_put(np.ones((10, 10), np.float32))
    memledger.track(arr, "scratch")
    assert memledger.live_bytes("scratch") == arr.nbytes
    assert memledger.tracked_nbytes(arr) == arr.nbytes
    # re-tracking the same object (any category) is a no-op
    memledger.track(arr, "scratch")
    memledger.track({"again": arr}, "model")
    assert memledger.live_bytes() == arr.nbytes
    del arr
    gc.collect()
    assert memledger.live_bytes() == 0


def test_stage_to_device_tracks_only_with_category():
    uncategorized = prefetch.stage_to_device(np.ones(16, np.float32))
    assert memledger.live_bytes() == 0  # cache-fed batches: cache owns them
    tracked = prefetch.stage_to_device(
        np.ones((8, 4), np.float32), category="optimizer"
    )
    assert memledger.live_bytes("optimizer") == tracked.nbytes
    snap = memledger.snapshot()
    assert snap["topEntries"][0]["category"] == "optimizer"
    assert snap["topEntries"][0]["site"]  # allocation site recorded
    del uncategorized, tracked
    gc.collect()
    assert memledger.live_bytes() == 0


# ---------------------------------------------------------------------------
# peaks and watermarks
# ---------------------------------------------------------------------------

def test_peak_watermarks_and_marks():
    tok = memledger.mark_peak()
    h1 = memledger.register("model", 1000)
    h2 = memledger.register("serving", 500)
    memledger.release(h1)
    h3 = memledger.register("scratch", 100)
    assert memledger.peak_bytes() == 1500  # global watermark sticks
    assert memledger.peak_since(tok) == 1500
    # a mark opened after the spike only sees what it observed
    tok2 = memledger.mark_peak()
    memledger.release(h2)
    assert memledger.peak_since(tok2) == 600
    memledger.release(h3)


def test_fit_peak_scope_sets_gauge():
    with memledger.fit_peak_scope():
        h = memledger.register("streamSegments", 2048)
        memledger.release(h)
    assert metrics.snapshot()["gauges"]["hbm.peak.fit"] == 2048


# ---------------------------------------------------------------------------
# the `memory` timeline lane (the Perfetto HBM track)
# ---------------------------------------------------------------------------

def test_memory_lane_counter_events():
    timeline.configure(ring_size=4096)
    try:
        h = memledger.register("batchCache", 777)
        memledger.release(h)
        events = timeline.drain()
    finally:
        timeline.configure()
    mem = [e for e in events if e["lane"] == timeline.LANE_MEMORY]
    assert len(mem) == 2  # one counter sample per live-bytes change
    assert all(e["ph"] == "C" and e["name"] == "hbm" for e in mem)
    assert mem[0]["args"] == {"batchCache": 777}
    # Chrome export keeps ph "C" so Perfetto renders a counter track
    chrome = timeline.to_chrome(mem)
    phases = {ev["ph"] for ev in chrome["traceEvents"] if ev["name"] == "hbm"}
    assert phases == {"C"}


# ---------------------------------------------------------------------------
# budget admission
# ---------------------------------------------------------------------------

def test_budget_admission_typed_error_with_breakdown():
    h = memledger.register("model", 900)
    with config.hbm_budget_mode(1000):
        memledger.admit(50)  # under budget: silent
        with pytest.raises(memledger.HbmBudgetExceeded) as ei:
            prefetch.stage_to_device(
                np.ones(1000, np.float32), category="serving"
            )
    e = ei.value
    assert e.requested_bytes == 4000
    assert e.budget_bytes == 1000
    assert e.breakdown == {"model": 900}  # zero categories filtered out
    assert e.category == "serving"
    assert "model=900" in str(e)
    # the rejection happened BEFORE dispatch: nothing was ledgered
    assert memledger.live_bytes() == 900
    memledger.release(h)


def test_budget_admission_deterministic_and_env_off_by_default():
    assert config.hbm_budget_bytes is None  # default: admission off
    memledger.admit(1 << 60)  # no budget -> always admits
    with config.hbm_budget_mode(64):
        for _ in range(3):  # deterministic: same request, same rejection
            with pytest.raises(memledger.HbmBudgetExceeded):
                memledger.admit(65, "scratch")
        assert metrics.snapshot()["counters"]["hbm.budget.rejected"] >= 3


def test_loose_budget_bit_identical_to_off():
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression,
    )

    table = Table({
        "features": [Vectors.dense(i, 2, 3, 4) for i in range(1, 11)],
        "label": [0.0] * 5 + [1.0] * 5,
    })

    def coeffs():
        model = LogisticRegression().set_max_iter(10).fit(table)
        return np.asarray(model.coefficient)

    base = coeffs()
    with config.hbm_budget_mode(1 << 40):
        loose = coeffs()
    assert base.tobytes() == loose.tobytes()  # bit-identical, not approx


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

def test_wrap_oom_builds_typed_error_with_snapshot(tmp_path, monkeypatch):
    memledger.register("streamSegments", 5000, (50, 25), "float32", "opt.py:9")
    dump_path = str(tmp_path / "hbm.json")
    monkeypatch.setenv("FLINK_ML_TPU_HBM_DUMP", dump_path)
    backend = RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating 5000")
    wrapped = memledger.wrap_oom(backend)
    assert isinstance(wrapped, memledger.HbmExhausted)
    assert wrapped.snapshot["liveBytes"] == 5000
    assert wrapped.snapshot["topEntries"][0]["site"] == "opt.py:9"
    assert "streamSegments" in str(wrapped)
    # the dump landed and roundtrips through the report renderer
    dump = memledger.load_dump(dump_path)
    assert dump == wrapped.snapshot
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    text = obs_report.render_hbm_dump(dump)
    assert "streamSegments" in text and "opt.py:9" in text
    # non-OOM errors and the already-typed pair pass through as None
    assert memledger.wrap_oom(ValueError("shape mismatch")) is None
    assert memledger.wrap_oom(wrapped) is None
    assert (
        memledger.wrap_oom(memledger.HbmBudgetExceeded(1, 1, {})) is None
    )


def test_snapshot_dump_roundtrip(tmp_path):
    memledger.register("model", 10)
    memledger.register("serving", 30)
    path = str(tmp_path / "snap.json")
    snap = memledger.dump_snapshot(path)
    assert memledger.load_dump(path) == snap
    assert list(snap["categories"]) == ["serving", "model"]  # ranked
    assert json.load(open(path))["entryCount"] == 2


# ---------------------------------------------------------------------------
# devicecache ownership parity (satellite 1)
# ---------------------------------------------------------------------------

def _batch(n, seed):
    rng = np.random.RandomState(seed)
    return {
        "X": jax.device_put(rng.randn(n, 4).astype(np.float32)),
        "y": jax.device_put(rng.randn(n).astype(np.float32)),
    }


def test_devicecache_ledger_parity_under_adversarial_sequence():
    one = _nbytes(_batch(10, 0))
    cache = DeviceEpochCache(budget_bytes=3 * one)
    cache.check_ledger_parity()  # empty == empty
    for seed in range(5):  # inserts forcing LRU evictions
        cache.put(seed, _batch(10, seed))
        cache.check_ledger_parity()
    assert len(cache) == 3  # budget holds 3
    assert cache.get(4) is not None and cache.get(0) is None  # hit + miss
    cache.check_ledger_parity()
    # replacement: same key, different payload size
    cache.put(4, _batch(20, 99))
    cache.check_ledger_parity()
    assert metrics.snapshot()["counters"].get("devicecache.replaceBytes", 0) > 0
    # oversized insert is rejected without ledger drift
    assert not cache.put("huge", _batch(1000, 7))
    cache.check_ledger_parity()
    cache.clear()
    cache.check_ledger_parity()
    assert memledger.live_bytes("batchCache") == 0


def test_devicecache_dropped_without_clear_releases_entries():
    cache = DeviceEpochCache(budget_bytes=1 << 20)
    cache.put("k", _batch(10, 1))
    assert memledger.live_bytes("batchCache") > 0
    del cache  # a fit abandoning its loader mid-flight
    gc.collect()
    assert memledger.live_bytes("batchCache") == 0


# ---------------------------------------------------------------------------
# the acceptance criterion: fit-end ledger parity on a chunked LR fit
# ---------------------------------------------------------------------------

def test_chunked_fit_end_ledger_parity():
    """After a chunked LR smoke fit, the sum of live bytes across
    categories equals the bytes of the arrays actually retained (the
    published model constants + cache residue) — transients all closed
    out through GC finalizers."""
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression,
    )

    table = Table({
        "features": [Vectors.dense(i, 2, 3, 4) for i in range(1, 11)],
        "label": [0.0] * 5 + [1.0] * 5,
    })
    prev_chunk = config.iteration_chunk_size
    config.iteration_chunk_size = 4
    try:
        with config.whole_fit_mode("off"):
            model = LogisticRegression().set_max_iter(12).fit(table)
            out = model.transform(table)[0]  # publishes device constants
            np.asarray(out.column("prediction"))
    finally:
        config.iteration_chunk_size = prev_chunk
    gc.collect()
    consts = model.device_constants()
    resident = _nbytes(consts)
    assert resident > 0
    assert memledger.live_bytes("model") == resident
    assert memledger.tracked_nbytes(consts) == resident
    # parity: everything live is exactly the retained model (+ empty cache)
    assert memledger.live_bytes() == resident + memledger.live_bytes("batchCache")
    assert memledger.live_bytes("batchCache") == 0
    assert memledger.peak_bytes() >= resident  # fit transients peaked higher
    # dropping the model closes the last entries
    del model, consts
    gc.collect()
    assert memledger.live_bytes() == 0
