"""Greenwald-Khanna sketch + out-of-core quantile stages + stream utils.

Mirrors the reference's QuantileSummary usage (common/util/
QuantileSummary.java driving RobustScaler / KBinsDiscretizer / Imputer)
and DataStreamUtils.aggregate/sample (:182/:212): sketch rank-error within
epsilon, merge correctness, stream-vs-in-memory stage parity, and a
forced-spill fit through the native data cache.
"""

import numpy as np
import pytest

from flink_ml_tpu.common.quantilesummary import (
    QuantileSummary,
    column_sketches,
    update_column_sketches,
)
from flink_ml_tpu.table import StreamTable, Table
from flink_ml_tpu.utils.datastream import aggregate, sample


def rank_error(data_sorted, value, p):
    rank = np.searchsorted(data_sorted, value, side="left")
    return abs(rank - p * len(data_sorted)) / len(data_sorted)


PS = np.array([0.01, 0.1, 0.25, 0.5, 0.75, 0.9, 0.99])


class TestQuantileSummary:
    def test_rank_error_within_epsilon(self):
        rng = np.random.default_rng(0)
        data = rng.normal(size=200_000)
        eps = 0.001
        s = QuantileSummary(eps)
        for chunk in np.array_split(data, 23):
            s.insert_batch(chunk)
        s.compress()
        sorted_d = np.sort(data)
        for p, v in zip(PS, s.query(PS)):
            assert rank_error(sorted_d, v, p) <= 2 * eps

    def test_single_inserts_match_batch(self):
        rng = np.random.default_rng(1)
        data = rng.random(500)
        a = QuantileSummary(0.01)
        b = QuantileSummary(0.01)
        for x in data:
            a.insert(float(x))
        b.insert_batch(data)
        assert a.compress().query(0.5) == b.compress().query(0.5)

    def test_merge_partitions(self):
        rng = np.random.default_rng(2)
        data = rng.exponential(size=120_000)
        eps = 0.005
        sketches = []
        for part in np.array_split(data, 7):  # uneven partitions
            t = QuantileSummary(eps)
            t.insert_batch(part)
            sketches.append(t.compress())
        merged = sketches[0]
        for t in sketches[1:]:
            merged = merged.merge(t)
        assert merged.count == len(data)
        sorted_d = np.sort(data)
        for p, v in zip(PS, merged.query(PS)):
            assert rank_error(sorted_d, v, p) <= 4 * eps

    def test_merge_empty(self):
        a = QuantileSummary(0.01)
        b = QuantileSummary(0.01)
        b.insert_batch(np.arange(100.0))
        b.compress()
        assert a.merge(b).query(0.5) == b.query(0.5)
        assert b.merge(a).query(0.5) == b.query(0.5)

    def test_endpoint_shortcircuit(self):
        s = QuantileSummary(0.05)
        s.insert_batch(np.arange(1000.0))
        s.compress()
        assert s.query(0.0) == 0.0  # p <= eps -> min
        assert s.query(1.0) == 999.0  # p >= 1-eps -> max

    def test_query_requires_compress_and_data(self):
        s = QuantileSummary(0.01)
        with pytest.raises(ValueError):
            s.query(0.5)
        s.insert_batch(np.arange(10.0))
        with pytest.raises(ValueError):
            s.query(0.5)  # uncompressed head buffer
        s.compress()
        with pytest.raises(ValueError):
            s.query(1.5)

    def test_merge_requires_compressed(self):
        a = QuantileSummary(0.01)
        a.insert_batch(np.arange(10.0))
        b = QuantileSummary(0.01)
        b.insert_batch(np.arange(10.0))
        b.compress()
        with pytest.raises(ValueError):
            a.merge(b)

    def test_space_stays_sublinear(self):
        s = QuantileSummary(0.01)
        rng = np.random.default_rng(3)
        for _ in range(20):
            s.insert_batch(rng.random(60_000))
        s.compress()
        assert s.count == 1_200_000
        assert s._values.size < 5_000  # GK bound ~ O((1/eps) log(eps n))

    def test_column_sketches_with_mask(self):
        X = np.array([[1.0, 10.0], [2.0, np.nan], [3.0, 30.0], [4.0, 40.0]])
        sketches = column_sketches(2, 0.01)
        update_column_sketches(sketches, X, mask=~np.isnan(X))
        assert sketches[0].compress().count == 4
        assert sketches[1].compress().count == 3


def _stream(X, n_batches, extra_cols=None, budget=None):
    """Split X row-wise into a StreamTable, optionally via the native
    spillable cache with a tiny memory budget (forces spill)."""
    batches = []
    for part in np.array_split(np.arange(len(X)), n_batches):
        cols = {"features": X[part]}
        for name, col in (extra_cols or {}).items():
            cols[name] = col[part]
        batches.append(Table(cols))
    if budget is not None:
        from flink_ml_tpu.native.datacache import ReplayableStreamTable

        return StreamTable(ReplayableStreamTable(batches, memory_budget_bytes=budget))
    return StreamTable.from_batches(batches)


class TestStreamQuantileStages:
    def test_robustscaler_stream_parity(self):
        from flink_ml_tpu.models.feature.robustscaler import RobustScaler

        rng = np.random.default_rng(0)
        X = rng.normal(size=(120_000, 3)) * np.array([1.0, 5.0, 0.1])
        scaler = RobustScaler().set_input_col("features").set_output_col("out")
        exact = scaler.fit(Table({"features": X}))
        streamed = scaler.fit(_stream(X, 11))
        # medians/ranges agree to the sketch's rank error translated to value
        # space: on 120k gaussian rows eps=1e-3 rank error ~ tiny value shift
        assert np.all(np.abs(streamed.medians - exact.medians) <= 0.02 * np.abs(exact.ranges))
        np.testing.assert_allclose(streamed.ranges, exact.ranges, rtol=0.05)

    def test_robustscaler_forced_spill(self):
        from flink_ml_tpu.models.feature.robustscaler import RobustScaler

        rng = np.random.default_rng(1)
        X = rng.normal(size=(50_000, 4))
        stream = _stream(X, 10, budget=64 << 10)  # 64KB budget: must spill
        inner = stream._batches
        scaler = RobustScaler().set_input_col("features").set_output_col("out")
        model = scaler.fit(stream)
        assert inner.stats["spilledSegments"] > 0
        exact = scaler.fit(Table({"features": X}))
        np.testing.assert_allclose(model.medians, exact.medians, atol=0.05)

    def test_imputer_stream_median_parity(self):
        from flink_ml_tpu.models.feature.imputer import Imputer

        rng = np.random.default_rng(2)
        a = rng.normal(size=100_000)
        a[rng.random(a.size) < 0.1] = np.nan
        imputer = (
            Imputer()
            .set_input_cols("a")
            .set_output_cols("a_out")
            .set_strategy("median")
        )
        batches = [
            Table({"a": part}) for part in np.array_split(a, 9)
        ]
        streamed = imputer.fit(StreamTable.from_batches(batches))
        exact = imputer.fit(Table({"a": a}))
        assert abs(streamed.surrogates["a"] - exact.surrogates["a"]) < 0.02

    def test_imputer_stream_mean_and_most_frequent_exact(self):
        from flink_ml_tpu.models.feature.imputer import Imputer

        a = np.array([1.0, 2.0, 2.0, 3.0, np.nan, 2.0, 9.0, 1.0])
        for strategy, expected in [("mean", np.nanmean(a)), ("most_frequent", 2.0)]:
            imputer = (
                Imputer()
                .set_input_cols("a")
                .set_output_cols("a_out")
                .set_strategy(strategy)
            )
            batches = [Table({"a": a[:3]}), Table({"a": a[3:]})]
            streamed = imputer.fit(StreamTable.from_batches(batches))
            assert streamed.surrogates["a"] == pytest.approx(expected)

    def test_kbins_stream_quantile_parity(self):
        from flink_ml_tpu.models.feature.kbinsdiscretizer import KBinsDiscretizer

        rng = np.random.default_rng(3)
        X = rng.normal(size=(80_000, 2))
        est = (
            KBinsDiscretizer()
            .set_input_col("features")
            .set_output_col("out")
            .set_strategy("quantile")
            .set_num_bins(4)
            .set_sub_samples(1_000_000)
        )
        exact = est.fit(Table({"features": X}))
        streamed = est.fit(_stream(X, 8))
        for e_exact, e_stream in zip(exact.bin_edges, streamed.bin_edges):
            assert e_exact.size == e_stream.size
            np.testing.assert_allclose(e_stream[1:-1], e_exact[1:-1], atol=0.02)

    def test_kbins_stream_uniform_exact(self):
        from flink_ml_tpu.models.feature.kbinsdiscretizer import KBinsDiscretizer

        rng = np.random.default_rng(4)
        X = rng.random((10_000, 2))
        est = (
            KBinsDiscretizer()
            .set_input_col("features")
            .set_output_col("out")
            .set_strategy("uniform")
            .set_num_bins(5)
        )
        exact = est.fit(Table({"features": X}))
        streamed = est.fit(_stream(X, 7))
        for e_exact, e_stream in zip(exact.bin_edges, streamed.bin_edges):
            np.testing.assert_allclose(e_stream, e_exact)

    def test_kbins_stream_empty_batch_skipped(self):
        from flink_ml_tpu.models.feature.kbinsdiscretizer import KBinsDiscretizer

        rng = np.random.default_rng(6)
        batches = [
            Table({"features": rng.random((10, 3))}),
            Table({"features": np.empty((0, 3))}),
        ]
        for strategy in ("uniform", "quantile"):
            est = (
                KBinsDiscretizer()
                .set_input_col("features")
                .set_output_col("out")
                .set_strategy(strategy)
            )
            model = est.fit(StreamTable.from_batches(batches))
            assert len(model.bin_edges) == 3

    def test_kbins_stream_kmeans_reservoir(self):
        from flink_ml_tpu.models.feature.kbinsdiscretizer import KBinsDiscretizer

        rng = np.random.default_rng(5)
        # two well-separated blobs: sampled kmeans must find the gap
        X = np.concatenate([rng.normal(0, 0.1, 5_000), rng.normal(10, 0.1, 5_000)])[:, None]
        est = (
            KBinsDiscretizer()
            .set_input_col("features")
            .set_output_col("out")
            .set_strategy("kmeans")
            .set_num_bins(2)
            .set_sub_samples(2_000)
        )
        streamed = est.fit(_stream(X, 5))
        edges = streamed.bin_edges[0]
        assert edges.size == 3
        assert 3.0 < edges[1] < 7.0


class TestDataStreamUtils:
    def test_aggregate_sum(self):
        batches = [Table({"x": np.arange(10.0)}), Table({"x": np.arange(10.0, 25.0)})]
        total = aggregate(
            StreamTable.from_batches(batches),
            create_accumulator=lambda: 0.0,
            add=lambda acc, t: acc + float(np.sum(t.column("x"))),
            get_result=lambda acc: acc,
        )
        assert total == pytest.approx(np.arange(25.0).sum())

    def test_aggregate_bounded_table(self):
        total = aggregate(
            Table({"x": np.arange(5.0)}),
            create_accumulator=lambda: 0.0,
            add=lambda acc, t: acc + float(np.sum(t.column("x"))),
            get_result=lambda acc: acc,
        )
        assert total == 10.0

    def test_sample_size_and_membership(self):
        rng = np.random.default_rng(0)
        X = rng.random((5_000, 2))
        batches = [Table({"x": part}) for part in np.array_split(X, 13)]
        out = sample(StreamTable.from_batches(batches), 100, seed=7)
        assert out.num_rows == 100
        flat = {tuple(r) for r in np.asarray(X)}
        for row in np.asarray(out.column("x")):
            assert tuple(row) in flat

    def test_sample_fewer_rows_than_k(self):
        out = sample(Table({"x": np.arange(5.0)}), 100)
        assert out.num_rows == 5

    def test_sample_roughly_uniform(self):
        # each of 200 rows should land in a k=50 sample ~25% of the time
        hits = np.zeros(200)
        for seed in range(120):
            out = sample(Table({"x": np.arange(200.0)}), 50, seed=seed)
            hits[np.asarray(out.column("x"), dtype=int)] += 1
        freq = hits / 120
        assert 0.15 < freq.mean() < 0.35
        assert freq.min() > 0.05  # no starved rows
