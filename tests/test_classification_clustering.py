"""NaiveBayes / Knn / AgglomerativeClustering batteries — mirror
flink-ml-lib tests NaiveBayesTest.java, KnnTest.java,
AgglomerativeClusteringTest.java."""

import numpy as np
import pytest

from flink_ml_tpu.linalg import Vectors
from flink_ml_tpu.table import Table
from flink_ml_tpu.models.classification.naivebayes import NaiveBayes, NaiveBayesModel
from flink_ml_tpu.models.classification.knn import Knn, KnnModel
from flink_ml_tpu.models.clustering.agglomerativeclustering import (
    AgglomerativeClustering,
)


class TestNaiveBayes:
    # NaiveBayesTest.java-style categorical data
    def _train(self):
        return Table(
            {
                "features": [
                    Vectors.dense(0, 0),
                    Vectors.dense(0, 1),
                    Vectors.dense(1, 0),
                    Vectors.dense(1, 1),
                    Vectors.dense(1, 1),
                ],
                "label": [11.0, 11.0, 22.0, 22.0, 22.0],
            }
        )

    def test_param_defaults(self):
        nb = NaiveBayes()
        assert nb.get_smoothing() == 1.0
        assert nb.get_model_type() == "multinomial"

    def test_fit_predict(self):
        model = NaiveBayes().fit(self._train())
        out = model.transform(self._train())[0]
        pred = np.asarray(out.column("prediction"))
        np.testing.assert_array_equal(pred, [11.0, 11.0, 22.0, 22.0, 22.0])

    def test_unseen_value_raises(self):
        model = NaiveBayes().fit(self._train())
        with pytest.raises(ValueError):
            model.transform(Table({"features": [Vectors.dense(9, 0)]}))

    def test_device_fit_transform_matches_host(self):
        """Device-resident input drives the MXU aggregation path; the
        resulting model and predictions must match the host (float64)
        reference path exactly — near-tie rows are host-refined."""
        import jax

        rng = np.random.RandomState(7)
        X = rng.randint(0, 4, size=(3000, 6)).astype(np.float32)
        y = rng.randint(0, 3, size=3000).astype(np.float32)
        host = NaiveBayes().fit(Table({"features": X, "label": y}))
        dev = NaiveBayes().fit(
            Table({"features": jax.device_put(X), "label": jax.device_put(y)})
        )
        np.testing.assert_allclose(dev.pi, host.pi, rtol=1e-12)
        np.testing.assert_array_equal(dev.labels, host.labels)
        for i in range(len(host.labels)):
            for j in range(X.shape[1]):
                assert dev.theta[i][j].keys() == host.theta[i][j].keys()
                for k in host.theta[i][j]:
                    assert abs(dev.theta[i][j][k] - host.theta[i][j][k]) < 1e-12
        ph = np.asarray(host.transform(Table({"features": X}))[0].column("prediction"))
        pd = np.asarray(
            dev.transform(Table({"features": jax.device_put(X)}))[0].column("prediction")
        )
        np.testing.assert_array_equal(ph, pd)

    def test_device_zero_rows_and_inexact_labels_fall_back(self):
        """Edge inputs the device kernels can't serve exactly must route
        through the host path, not crash or round: zero-row tables and
        labels/categories that are not f32-representable."""
        import jax

        rng = np.random.RandomState(1)
        X = rng.randint(0, 3, size=(100, 4)).astype(np.float32)
        y = np.where(rng.randint(0, 2, 100) > 0, 0.1, 0.2)  # not f32-exact
        model = NaiveBayes().fit(Table({"features": jax.device_put(X), "label": y}))
        out = model.transform(Table({"features": jax.device_put(X)}))[0]
        pred = np.asarray(out.column("prediction"))
        assert set(np.unique(pred)) <= {0.1, 0.2}  # exact f64 labels survive
        empty = model.transform(
            Table({"features": jax.device_put(np.zeros((0, 4), np.float32))})
        )[0]
        assert np.asarray(empty.column("prediction")).shape == (0,)

    def test_device_unseen_value_raises(self):
        import jax

        rng = np.random.RandomState(3)
        X = rng.randint(0, 4, size=(500, 3)).astype(np.float32)
        y = rng.randint(0, 2, size=500).astype(np.float32)
        model = NaiveBayes().fit(
            Table({"features": jax.device_put(X), "label": jax.device_put(y)})
        )
        bad = X.copy()
        bad[7, 1] = 99.0
        with pytest.raises(ValueError, match="was not seen during training"):
            model.transform(Table({"features": jax.device_put(bad)}))

    def test_device_nan_label_raises(self):
        import jax

        X = np.zeros((8, 2), np.float32)
        y = np.asarray([0, 1, 0, 1, np.nan, 0, 1, 0], np.float32)
        with pytest.raises(ValueError, match="null/NaN"):
            NaiveBayes().fit(
                Table({"features": jax.device_put(X), "label": jax.device_put(y)})
            )

    def test_nan_feature_raises_device_and_host(self):
        """A NaN feature can never be matched at predict time (NaN != NaN)
        and silently inflates the device category sets — rejected at fit
        time on both paths like NaN labels."""
        import jax

        X = np.zeros((8, 2), np.float32)
        X[3, 1] = np.nan
        y = np.asarray([0, 1] * 4, np.float32)
        with pytest.raises(ValueError, match="Feature column contains null/NaN"):
            NaiveBayes().fit(
                Table({"features": jax.device_put(X), "label": jax.device_put(y)})
            )
        with pytest.raises(ValueError, match="Feature column contains null/NaN"):
            NaiveBayes().fit(
                Table({"features": X.astype(np.float64), "label": y.astype(np.float64)})
            )

    def test_inf_category_stays_exact(self):
        """+inf doubles as the device kernels' category-padding sentinel, so
        a trained +inf category must route fit AND predict through the host
        path instead of co-counting/scoring against padding slots."""
        import jax

        X = np.zeros((12, 2), np.float32)
        X[:, 1] = np.asarray([0, 1, np.inf] * 4, np.float32)
        y = np.asarray([0, 1] * 6, np.float32)
        host = NaiveBayes().fit(
            Table({"features": X.astype(np.float64), "label": y.astype(np.float64)})
        )
        dev = NaiveBayes().fit(
            Table({"features": jax.device_put(X), "label": jax.device_put(y)})
        )
        for i in range(2):
            for j in range(2):
                assert dev.theta[i][j] == pytest.approx(host.theta[i][j])
        pred_h = np.asarray(host.transform(Table({"features": X}))[0].column("prediction"))
        pred_d = np.asarray(
            dev.transform(Table({"features": jax.device_put(X)}))[0].column("prediction")
        )
        np.testing.assert_array_equal(pred_h, pred_d)

    def test_save_load(self, tmp_path):
        model = NaiveBayes().fit(self._train())
        model.save(str(tmp_path / "nb"))
        loaded = NaiveBayesModel.load(str(tmp_path / "nb"))
        np.testing.assert_allclose(loaded.pi, model.pi)
        out = loaded.transform(self._train())[0]
        np.testing.assert_array_equal(
            np.asarray(out.column("prediction")), [11.0, 11.0, 22.0, 22.0, 22.0]
        )

    def test_get_set_model_data(self):
        model = NaiveBayes().fit(self._train())
        other = NaiveBayesModel().set_model_data(model.get_model_data()[0])
        np.testing.assert_allclose(other.pi, model.pi)


class TestKnn:
    def _train(self):
        X = np.vstack([np.zeros((5, 2)), np.ones((5, 2)) * 10])
        y = np.asarray([1.0] * 5 + [2.0] * 5)
        return Table({"features": X, "label": y})

    def test_param_defaults(self):
        assert Knn().get_k() == 5

    def test_fit_predict(self):
        model = Knn().set_k(3).fit(self._train())
        out = model.transform(
            Table({"features": [[0.5, 0.5], [9.0, 9.5]]})
        )[0]
        np.testing.assert_array_equal(np.asarray(out.column("prediction")), [1.0, 2.0])

    def test_k_larger_than_train(self):
        t = Table({"features": [[0.0], [1.0]], "label": [5.0, 5.0]})
        model = Knn().set_k(10).fit(t)
        out = model.transform(t)[0]
        np.testing.assert_array_equal(np.asarray(out.column("prediction")), [5.0, 5.0])

    def test_save_load(self, tmp_path):
        model = Knn().fit(self._train())
        model.save(str(tmp_path / "knn"))
        loaded = KnnModel.load(str(tmp_path / "knn"))
        np.testing.assert_allclose(loaded.features, model.features)
        out = loaded.transform(Table({"features": [[0.0, 0.0]]}))[0]
        assert np.asarray(out.column("prediction"))[0] == 1.0

    def test_get_set_model_data(self):
        model = Knn().fit(self._train())
        other = KnnModel().set_model_data(model.get_model_data()[0])
        np.testing.assert_allclose(other.labels, model.labels)


class TestAgglomerativeClustering:
    # AgglomerativeClusteringTest.java-style data: two well-separated blobs
    def _table(self):
        X = np.asarray(
            [[1.0, 1.0], [1.0, 4.0], [1.0, 0.0], [4.0, 1.5], [4.0, 4.0], [4.0, 0.0]]
        )
        return Table({"features": X})

    def test_two_clusters_ward(self):
        out, merges = AgglomerativeClustering().transform(self._table())
        pred = np.asarray(out.column("prediction"))
        assert len(set(pred)) == 2
        # merge log has n - numClusters entries without full tree
        assert merges.num_rows == 4

    @pytest.mark.parametrize("linkage", ["single", "complete", "average"])
    def test_linkages(self, linkage):
        op = AgglomerativeClustering().set_linkage(linkage)
        out, _ = op.transform(self._table())
        pred = np.asarray(out.column("prediction"))
        assert len(set(pred)) == 2

    def test_distance_threshold(self):
        op = AgglomerativeClustering().set_distance_threshold(1.2)
        out, _ = op.transform(self._table())
        pred = np.asarray(out.column("prediction"))
        # only pairs closer than 1.2 merge -> more than 2 clusters
        assert len(set(pred)) > 2

    def test_full_tree(self):
        op = AgglomerativeClustering().set_compute_full_tree(True)
        out, merges = op.transform(self._table())
        assert merges.num_rows == 5  # n - 1 merges for the full dendrogram
        assert len(set(np.asarray(out.column("prediction")))) == 2

    def test_ward_requires_euclidean(self):
        with pytest.raises(ValueError):
            AgglomerativeClustering().set_distance_measure("cosine").transform(self._table())

    def test_save_load(self, tmp_path):
        op = AgglomerativeClustering().set_num_clusters(3)
        op.save(str(tmp_path / "agg"))
        loaded = AgglomerativeClustering.load(str(tmp_path / "agg"))
        assert loaded.get_num_clusters() == 3

    @pytest.mark.parametrize("linkage", ["ward", "single", "complete", "average"])
    @pytest.mark.parametrize("full", [False, True])
    def test_native_merge_loop_matches_numpy_golden(self, linkage, full, monkeypatch):
        """The C merge loop (native/src/agglomerative.cc) must reproduce the
        numpy loop's merge log and labels BIT for bit — same Lance-Williams
        arithmetic, same first-minimum tie-breaking."""
        import flink_ml_tpu.native as nat
        from flink_ml_tpu.models.clustering import agglomerativeclustering as agg
        from flink_ml_tpu.ops.distance import DistanceMeasure

        if not nat.available():
            pytest.skip("no native toolchain")
        rng = np.random.RandomState(7)
        X = rng.rand(80, 6)
        measure = DistanceMeasure.get_instance("euclidean")
        native = agg._cluster_block(X, linkage, measure, 5, None, full)
        monkeypatch.setattr(agg, "_cluster_block_native", lambda *a, **k: None)
        fallback = agg._cluster_block(X, linkage, measure, 5, None, full)
        assert native[0].tolist() == fallback[0].tolist()
        assert native[1] == fallback[1]

    def test_native_merge_loop_threshold_matches(self, monkeypatch):
        import flink_ml_tpu.native as nat
        from flink_ml_tpu.models.clustering import agglomerativeclustering as agg
        from flink_ml_tpu.ops.distance import DistanceMeasure

        if not nat.available():
            pytest.skip("no native toolchain")
        rng = np.random.RandomState(3)
        X = rng.rand(50, 4)
        measure = DistanceMeasure.get_instance("euclidean")
        native = agg._cluster_block(X, "average", measure, 1, 0.6, True)
        monkeypatch.setattr(agg, "_cluster_block_native", lambda *a, **k: None)
        fallback = agg._cluster_block(X, "average", measure, 1, 0.6, True)
        assert native[0].tolist() == fallback[0].tolist()
        assert native[1] == fallback[1]


class TestAgglomerativeWindows:
    """HasWindows drives per-window LOCAL clustering
    (AgglomerativeClustering.java:122-133 windowAllAndProcess)."""

    def _table(self):
        rng = np.random.RandomState(0)
        # two tight blobs per window-of-4, 12 rows total
        X = rng.rand(12, 3) * 0.01 + (np.arange(12) % 2)[:, None]
        return Table({"features": X})

    def test_count_tumbling_changes_output(self):
        from flink_ml_tpu.common.window import CountTumblingWindows
        from flink_ml_tpu.models.clustering.agglomerativeclustering import (
            AgglomerativeClustering,
        )

        t = self._table()
        base = AgglomerativeClustering().set_num_clusters(2)
        out_global, merges_global = base.transform(t)
        windowed = (
            AgglomerativeClustering()
            .set_num_clusters(2)
            .set_windows(CountTumblingWindows.of(4))
        )
        out_win, merges_win = windowed.transform(t)
        # per-window clustering: labels restart per window, merge log is the
        # concatenation of the 3 local logs (each window of 4 -> 2 merges)
        assert out_win.num_rows == 12 and out_global.num_rows == 12
        pred = np.asarray(out_win.column("prediction"))
        assert set(pred) == {0, 1}
        assert merges_win.num_rows == 3 * 2
        assert merges_win.num_rows != merges_global.num_rows

    def test_ragged_tail_dropped(self):
        from flink_ml_tpu.common.window import CountTumblingWindows
        from flink_ml_tpu.models.clustering.agglomerativeclustering import (
            AgglomerativeClustering,
        )

        t = self._table()  # 12 rows; window 5 -> 2 full windows, 2 rows dropped
        out, _ = (
            AgglomerativeClustering()
            .set_num_clusters(2)
            .set_windows(CountTumblingWindows.of(5))
            .transform(t)
        )
        assert out.num_rows == 10

    def test_event_time_windows_need_timestamp_column(self):
        """Event-time windows are supported (tests/test_time_windows.py)
        but require a 'timestamp' column; a clear error names it."""
        from flink_ml_tpu.common.window import EventTimeTumblingWindows
        from flink_ml_tpu.models.clustering.agglomerativeclustering import (
            AgglomerativeClustering,
        )

        with pytest.raises(ValueError, match="timestamp"):
            AgglomerativeClustering().set_windows(
                EventTimeTumblingWindows.of(1000)
            ).transform(self._table())


class TestWindowedMergeLogDecodable:
    def test_merge_ids_globally_unique(self):
        from flink_ml_tpu.common.window import CountTumblingWindows
        from flink_ml_tpu.models.clustering.agglomerativeclustering import (
            AgglomerativeClustering,
        )

        rng = np.random.RandomState(1)
        X = rng.rand(12, 3) * 0.01 + (np.arange(12) % 2)[:, None]
        out, merges = (
            AgglomerativeClustering()
            .set_num_clusters(2)
            .set_windows(CountTumblingWindows.of(4))
            .transform(Table({"features": X}))
        )
        rows = merges.collect()
        ids = [r["clusterId1"] for r in rows] + [r["clusterId2"] for r in rows]
        assert len(ids) == len(set(ids))  # no collisions across windows
        # every id is either a global row index (< 12) or a merged-cluster
        # id in log order (12 + merge_index)
        merged_ids = sorted(i for i in ids if i >= 12)
        assert all(i < 12 + len(rows) for i in merged_ids)
