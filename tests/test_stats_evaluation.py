"""Stats stages + BinaryClassificationEvaluator batteries. Golden values are
taken from the reference tests (ANOVATestTest.java EXPECTED_OUTPUT_DENSE,
BinaryClassificationEvaluatorTest.java EXPECTED_DATA/_M/_W,
FValueTestTest.java / ChiSqTestTest.java shapes)."""

import jax.numpy as jnp
import numpy as np
import pytest

from flink_ml_tpu.table import Table
from flink_ml_tpu.linalg import Vectors
from flink_ml_tpu.models.evaluation.binaryclassification import (
    BinaryClassificationEvaluator,
)
from flink_ml_tpu.models.stats.anovatest import ANOVATest
from flink_ml_tpu.models.stats.chisqtest import ChiSqTest
from flink_ml_tpu.models.stats.fvaluetest import FValueTest

# ANOVATestTest.java DENSE_INPUT_DATA (20 rows, labels 1..5, 6 features)
ANOVA_LABELS = [3, 2, 1, 5, 4, 4, 5, 4, 2, 1, 1, 2, 3, 4, 5, 1, 5, 3, 1, 1]
ANOVA_X = [
    [0.85956061, 0.1645695, 0.48347596, 0.92102727, 0.42855644, 0.05746009],
    [0.92500743, 0.65760154, 0.13295284, 0.53344893, 0.8994776, 0.24836496],
    [0.03017182, 0.07244715, 0.87416449, 0.55843035, 0.91604736, 0.63346045],
    [0.28325261, 0.36536881, 0.09223386, 0.37251258, 0.34742278, 0.70517077],
    [0.64850904, 0.04090877, 0.21173176, 0.00148992, 0.13897166, 0.21182539],
    [0.02609493, 0.44608735, 0.23910531, 0.95449222, 0.90763182, 0.8624905],
    [0.09158744, 0.97745235, 0.41150139, 0.45830467, 0.52590925, 0.29441554],
    [0.97211594, 0.1814442, 0.30340642, 0.17445413, 0.52756958, 0.02069296],
    [0.06354593, 0.63527231, 0.49620335, 0.0141264, 0.62722219, 0.63497507],
    [0.10814149, 0.8296426, 0.51775217, 0.57068344, 0.54633305, 0.12714921],
    [0.72731796, 0.94010124, 0.45007811, 0.87650674, 0.53735565, 0.49568415],
    [0.41827208, 0.85100628, 0.38685271, 0.60689503, 0.21784097, 0.91294433],
    [0.65843656, 0.5880859, 0.18862706, 0.856398, 0.18029327, 0.94851926],
    [0.3841634, 0.25138793, 0.96746644, 0.77048045, 0.44685196, 0.19813854],
    [0.65982267, 0.23024125, 0.13598434, 0.60144265, 0.57848927, 0.85623564],
    [0.35764189, 0.47623815, 0.5459232, 0.79508298, 0.14462443, 0.01802919],
    [0.38532153, 0.90614554, 0.86629571, 0.13988735, 0.32062385, 0.00179492],
    [0.2142368, 0.28306022, 0.59481646, 0.42567028, 0.52207663, 0.78082401],
    [0.20788283, 0.76861782, 0.59595468, 0.62103642, 0.17781246, 0.77655345],
    [0.1751708, 0.4547537, 0.46187865, 0.79781199, 0.05104487, 0.42406092],
]
ANOVA_EXPECTED_P = [0.64137831, 0.14830724, 0.69858474, 0.28038169, 0.86759161, 0.81608606]
ANOVA_EXPECTED_F = [0.64110932, 1.98689258, 0.55499714, 1.40340562, 0.30881722, 0.3848595]


class TestANOVATest:
    def _table(self):
        return Table({"features": np.asarray(ANOVA_X), "label": [float(l) for l in ANOVA_LABELS]})

    def test_dense(self):
        out = ANOVATest().transform(self._table())[0]
        row = out.collect()[0]
        np.testing.assert_allclose(row["pValues"].to_array(), ANOVA_EXPECTED_P, atol=1e-7)
        np.testing.assert_allclose(row["fValues"].to_array(), ANOVA_EXPECTED_F, atol=1e-7)
        assert list(row["degreesOfFreedom"]) == [19] * 6

    def test_flattened(self):
        out = ANOVATest().set_flatten(True).transform(self._table())[0]
        assert out.num_rows == 6
        np.testing.assert_array_equal(np.asarray(out.column("featureIndex")), np.arange(6))
        np.testing.assert_allclose(np.asarray(out.column("pValue")), ANOVA_EXPECTED_P, atol=1e-7)


class TestFValueTest:
    def test_informative_feature(self):
        rng = np.random.RandomState(0)
        X = rng.rand(50, 3)
        y = 3.0 * X[:, 1] + 0.01 * rng.randn(50)
        out = FValueTest().transform(Table({"features": X, "label": y}))[0]
        row = out.collect()[0]
        p = row["pValues"].to_array()
        assert p[1] < 1e-10 and p[0] > 0.01
        assert list(row["degreesOfFreedom"]) == [48] * 3

    def test_flattened_schema(self):
        X = np.random.RandomState(1).rand(10, 2)
        out = FValueTest().set_flatten(True).transform(Table({"features": X, "label": X[:, 0]}))[0]
        assert out.column_names == ["featureIndex", "pValue", "degreeOfFreedom", "fValue"]


class TestChiSqTest:
    def _table(self):
        # ChiSqTestTest.java-style categorical data
        return Table(
            {
                "features": [
                    Vectors.dense(0, 5),
                    Vectors.dense(1, 6),
                    Vectors.dense(2, 5),
                    Vectors.dense(1, 5),
                    Vectors.dense(0, 5),
                    Vectors.dense(2, 6),
                ],
                "label": [0.0, 1.0, 0.0, 1.0, 0.0, 1.0],
            }
        )

    def test_dense(self):
        out = ChiSqTest().transform(self._table())[0]
        row = out.collect()[0]
        p = row["pValues"].to_array()
        assert p.shape == (2,)
        assert 0.0 <= p[0] <= 1.0 and 0.0 <= p[1] <= 1.0
        # feature 0: contingency {0:(2,0), 1:(0,2), 2:(1,1)} -> stat 4, dof 2,
        # p = exp(-2); dof = (m-1)*(k-1)
        assert list(row["degreesOfFreedom"]) == [2, 1]
        np.testing.assert_allclose(p[0], np.exp(-2.0), atol=1e-10)
        np.testing.assert_allclose(row["statistics"].to_array()[0], 4.0, atol=1e-10)

    def test_flattened(self):
        out = ChiSqTest().set_flatten(True).transform(self._table())[0]
        assert out.num_rows == 2
        assert out.column_names == ["featureIndex", "pValue", "degreeOfFreedom", "statistic"]


class TestBinaryClassificationEvaluator:
    LABELS = [1.0, 1.0, 1.0, 0.0, 0.0, 1.0, 1.0, 0.0, 0.0, 1.0, 0.0, 1.0]
    SCORES = [0.9, 0.8, 0.7, 0.75, 0.6, 0.65, 0.55, 0.4, 0.3, 0.35, 0.2, 0.1]

    def _table(self):
        raw = [Vectors.dense(1 - s, s) for s in self.SCORES]
        return Table({"label": self.LABELS, "rawPrediction": raw})

    def test_param_defaults(self):
        ev = BinaryClassificationEvaluator()
        assert ev.get_label_col() == "label"
        assert ev.get_raw_prediction_col() == "rawPrediction"
        assert ev.get_metrics_names() == ["areaUnderROC", "areaUnderPR"]

    def test_evaluate(self):
        # BinaryClassificationEvaluatorTest.java EXPECTED_DATA
        ev = BinaryClassificationEvaluator().set_metrics_names(
            "areaUnderPR", "ks", "areaUnderROC"
        )
        out = ev.transform(self._table())[0]
        assert out.column_names == ["areaUnderPR", "ks", "areaUnderROC"]
        row = out.collect()[0]
        np.testing.assert_allclose(row["areaUnderPR"], 0.7691481137909708, atol=1e-5)
        np.testing.assert_allclose(row["ks"], 0.3714285714285714, atol=1e-5)
        np.testing.assert_allclose(row["areaUnderROC"], 0.6571428571428571, atol=1e-5)

    def test_evaluate_double_raw(self):
        t = Table({"label": self.LABELS, "rawPrediction": self.SCORES})
        out = BinaryClassificationEvaluator().set_metrics_names("areaUnderROC").transform(t)[0]
        np.testing.assert_allclose(out.collect()[0]["areaUnderROC"], 0.6571428571428571, atol=1e-5)

    def test_evaluate_with_ties(self):
        # EXPECTED_DATA_M: [auc, aupr, ks, lorenz]
        scores = [0.9, 0.9, 0.9, 0.75, 0.6, 0.9, 0.9, 0.4, 0.3, 0.9, 0.2, 0.1]
        raw = [Vectors.dense(1 - s, s) for s in scores]
        t = Table({"label": self.LABELS, "rawPrediction": raw})
        ev = BinaryClassificationEvaluator().set_metrics_names(
            "areaUnderROC", "areaUnderPR", "ks", "areaUnderLorenz"
        )
        row = ev.transform(t)[0].collect()[0]
        np.testing.assert_allclose(row["areaUnderROC"], 0.8571428571428571, atol=1e-5)
        np.testing.assert_allclose(row["areaUnderPR"], 0.9377705627705628, atol=1e-5)
        np.testing.assert_allclose(row["ks"], 0.8571428571428571, atol=1e-5)
        np.testing.assert_allclose(row["areaUnderLorenz"], 0.6488095238095237, atol=1e-5)

    def test_evaluate_weighted(self):
        # EXPECTED_DATA_W
        scores = [0.9, 0.9, 0.9, 0.75, 0.6, 0.9, 0.9, 0.4, 0.3, 0.9, 0.2, 0.1]
        weights = [0.8, 0.7, 0.5, 1.2, 1.3, 1.5, 1.4, 0.3, 0.5, 1.9, 1.2, 1.0]
        raw = [Vectors.dense(1 - s, s) for s in scores]
        t = Table({"label": self.LABELS, "rawPrediction": raw, "weight": weights})
        ev = (
            BinaryClassificationEvaluator()
            .set_metrics_names("areaUnderROC")
            .set_weight_col("weight")
        )
        row = ev.transform(t)[0].collect()[0]
        np.testing.assert_allclose(row["areaUnderROC"], 0.8911680911680911, atol=1e-5)

    def test_invalid_metric_rejected(self):
        with pytest.raises(ValueError):
            BinaryClassificationEvaluator().set_metrics_names("nope")


class TestDeviceEvaluatorParity:
    """The device metric pass must match the numpy oracle (_binary_metrics)
    across weights, heavy score ties, and degenerate label distributions."""

    @pytest.mark.parametrize("weighted", [False, True])
    @pytest.mark.parametrize("tie_levels", [None, 7, 2])
    def test_matches_numpy_oracle(self, weighted, tie_levels):
        from flink_ml_tpu.models.evaluation.binaryclassification import (
            _binary_metrics,
            _binary_metrics_device,
        )

        rng = np.random.default_rng(5)
        n = 4000
        scores = rng.random(n)
        if tie_levels is not None:  # quantize to force tied groups
            scores = np.round(scores * tie_levels) / tie_levels
        labels = (rng.random(n) < scores).astype(np.float64)
        weights = rng.random(n) + 0.1 if weighted else np.ones(n)
        oracle = _binary_metrics(scores, labels, weights)
        packed = np.asarray(
            _binary_metrics_device(
                jnp.asarray(scores, jnp.float32),
                jnp.asarray(labels, jnp.float32),
                jnp.asarray(weights, jnp.float32),
            )
        )
        got = dict(zip(["areaUnderROC", "areaUnderPR", "areaUnderLorenz", "ks"], packed))
        for name, expect in oracle.items():
            assert abs(got[name] - expect) < 2e-4, (name, got[name], expect)

    def test_large_n_float32_deviation_bound(self):
        """At benchmark-like scale (500k rows, heavy ties) the float32
        device path must stay within the documented 1e-3 absolute bound of
        the float64 oracle — pins the cumsum/tie-merge error growth the
        4k-row test cannot see."""
        from flink_ml_tpu.models.evaluation.binaryclassification import (
            _binary_metrics,
            _binary_metrics_device,
        )

        rng = np.random.default_rng(11)
        n = 500_000
        scores = np.round(rng.random(n) * 1000) / 1000  # ~1000 tie groups
        labels = (rng.random(n) < scores).astype(np.float64)
        weights = rng.random(n) + 0.1
        oracle = _binary_metrics(scores, labels, weights)
        packed = np.asarray(
            _binary_metrics_device(
                jnp.asarray(scores, jnp.float32),
                jnp.asarray(labels, jnp.float32),
                jnp.asarray(weights, jnp.float32),
            )
        )
        got = dict(zip(["areaUnderROC", "areaUnderPR", "areaUnderLorenz", "ks"], packed))
        for name, expect in oracle.items():
            assert abs(got[name] - expect) < 1e-3, (name, got[name], expect)

    def test_single_class_nan_auc(self):
        from flink_ml_tpu.models.evaluation.binaryclassification import (
            _binary_metrics_device,
        )

        packed = np.asarray(
            _binary_metrics_device(
                jnp.asarray([0.3, 0.7, 0.5], jnp.float32),
                jnp.asarray([1.0, 1.0, 1.0], jnp.float32),
                jnp.asarray([1.0, 1.0, 1.0], jnp.float32),
            )
        )
        assert np.isnan(packed[0])

    def test_device_scores_stay_on_device(self):
        """LR's device transform output feeds the evaluator without a host
        round trip of the raw predictions."""
        import jax

        from flink_ml_tpu.models.evaluation.binaryclassification import (
            BinaryClassificationEvaluator,
        )
        from flink_ml_tpu.table import Table

        n = 512
        rng = np.random.default_rng(0)
        raw = jnp.asarray(np.stack([1 - rng.random(n), rng.random(n)], axis=1))
        labels = jnp.asarray((rng.random(n) > 0.5).astype(np.float32))
        out = (
            BinaryClassificationEvaluator()
            .set_metrics_names("areaUnderROC", "ks")
            .transform(Table({"label": labels, "rawPrediction": raw}))
        )[0]
        row = out.collect()[0]
        assert 0.0 <= row["areaUnderROC"] <= 1.0 and 0.0 <= row["ks"] <= 1.0
