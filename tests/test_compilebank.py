"""AOT program bank (flink_ml_tpu/compilebank.py, ISSUE 20).

Pins the warm-load contract (a bank hit runs a deserialized executable —
zero traces, zero backend compiles, bit-identical outputs), the refusal
semantics (corrupt entries, stale digests, and fingerprint mismatches
are refused with a loud warning and a `bank.refused` tick, never a
crash), the bank x persistent-XLA-cache interplay, the keyed_jit LRU
bound (eviction must never be observable in results), and the serving
warmup -> bank-hit path.
"""

import json
import logging
import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_ml_tpu import compilebank, config
from flink_ml_tpu.utils import metrics
from flink_ml_tpu.utils.lazyjit import keyed_jit, lazy_jit


def _counter_delta(before, key):
    after = metrics.snapshot()
    return metrics.snapshot_delta(before, after)["counters"].get(key, 0.0)


def _affine(x, scale):
    return x * scale + 1.0


affine_kernel = lazy_jit(_affine, static_argnames=("scale",))


def _make_power(p):
    def power(x):
        return jnp.sum(x ** p)

    return power


power_kernel = keyed_jit(_make_power)


X = np.linspace(-2.0, 3.0, 32, dtype=np.float32)


# ---------------------------------------------------------------------------
# warm-load round trip
# ---------------------------------------------------------------------------

def test_miss_backfills_then_fresh_bank_hits_without_trace(tmp_path):
    bank_dir = str(tmp_path / "bank")
    fresh = np.asarray(affine_kernel(X, scale=2.0))

    with config.program_bank_mode(bank_dir):
        before = metrics.snapshot()
        first = np.asarray(affine_kernel(X, scale=2.0))
        assert _counter_delta(before, "bank.misses") == 1.0
    assert os.path.exists(os.path.join(bank_dir, compilebank.MANIFEST))

    # a NEW bank scope warm-loads the serialized executable from disk:
    # the hit must not trace and must be bit-identical to the fresh run
    with config.program_bank_mode(bank_dir):
        before = metrics.snapshot()
        again = np.asarray(affine_kernel(X, scale=2.0))
        assert _counter_delta(before, "jit.traces") == 0.0
        assert _counter_delta(before, "bank.hits") == 1.0
        assert _counter_delta(before, "jit.bankLoads") == 1.0
        bank = compilebank.active_bank()
        assert bank is not None and bank.stats()["entries"] == 1.0
    assert fresh.tobytes() == first.tobytes() == again.tobytes()


def test_distinct_shapes_and_statics_are_distinct_entries(tmp_path):
    bank_dir = str(tmp_path / "bank")
    with config.program_bank_mode(bank_dir):
        affine_kernel(X, scale=2.0)
        affine_kernel(X, scale=3.0)  # static differs -> new signature
        affine_kernel(X[:8], scale=2.0)  # shape differs -> new signature
        bank = compilebank.active_bank()
        assert bank.stats()["entries"] == 3.0
    with open(os.path.join(bank_dir, compilebank.MANIFEST)) as f:
        manifest = json.load(f)
    assert len(manifest["entries"]) == 3


def test_bank_with_persistent_xla_cache(tmp_path):
    """Both persistence tiers on at once (the production configuration):
    the bank must populate, warm-load, and hit exactly as it does alone,
    and outputs must stay bit-identical."""
    prev_cache = config.compilation_cache_dir
    config.enable_compilation_cache(str(tmp_path / "xla-cache"))
    try:
        bank_dir = str(tmp_path / "bank")
        with config.program_bank_mode(bank_dir):
            first = np.asarray(affine_kernel(X, scale=7.0))
        with config.program_bank_mode(bank_dir):
            before = metrics.snapshot()
            again = np.asarray(affine_kernel(X, scale=7.0))
            assert _counter_delta(before, "bank.hits") == 1.0
            assert _counter_delta(before, "jit.traces") == 0.0
        assert first.tobytes() == again.tobytes()
    finally:
        config.compilation_cache_dir = prev_cache


# ---------------------------------------------------------------------------
# refusal semantics: corrupt / stale / mismatched banks never crash
# ---------------------------------------------------------------------------

def _populated_bank(tmp_path):
    bank_dir = str(tmp_path / "bank")
    with config.program_bank_mode(bank_dir):
        affine_kernel(X, scale=5.0)
    return bank_dir


def test_corrupt_entry_refused_with_warning_not_crash(tmp_path, caplog):
    bank_dir = _populated_bank(tmp_path)
    manifest = json.load(open(os.path.join(bank_dir, compilebank.MANIFEST)))
    (record,) = manifest["entries"].values()
    entry_path = os.path.join(bank_dir, record["file"])
    raw = open(entry_path, "rb").read()
    with open(entry_path, "wb") as f:  # flip payload bytes: digest mismatch
        f.write(raw[:-4] + b"\x00\x00\x00\x00")

    with caplog.at_level(logging.WARNING, logger="flink_ml_tpu.compilebank"):
        with config.program_bank_mode(bank_dir):
            before = metrics.snapshot()
            out = np.asarray(affine_kernel(X, scale=5.0))
            assert _counter_delta(before, "bank.refused") >= 1.0
            assert _counter_delta(before, "jit.bankLoads") == 0.0
    assert any("digest mismatch" in r.message for r in caplog.records)
    assert out.tobytes() == np.asarray(affine_kernel(X, scale=5.0)).tobytes()


def test_undeserializable_payload_refused(tmp_path, caplog):
    bank_dir = _populated_bank(tmp_path)
    manifest_path = os.path.join(bank_dir, compilebank.MANIFEST)
    manifest = json.load(open(manifest_path))
    (sig,) = manifest["entries"]
    record = manifest["entries"][sig]
    garbage = pickle.dumps({"not": "an executable"})
    with open(os.path.join(bank_dir, record["file"]), "wb") as f:
        f.write(garbage)
    import hashlib

    record["sha256"] = hashlib.sha256(garbage).hexdigest()  # digest is "valid"
    with open(manifest_path, "w") as f:
        json.dump(manifest, f)

    with caplog.at_level(logging.WARNING, logger="flink_ml_tpu.compilebank"):
        with config.program_bank_mode(bank_dir):
            before = metrics.snapshot()
            out = np.asarray(affine_kernel(X, scale=5.0))
            assert _counter_delta(before, "bank.refused") >= 1.0
    assert any("deserialize" in r.message for r in caplog.records)
    assert np.isfinite(out).all()


def test_fingerprint_mismatch_refuses_whole_bank(tmp_path, caplog):
    bank_dir = _populated_bank(tmp_path)
    manifest_path = os.path.join(bank_dir, compilebank.MANIFEST)
    manifest = json.load(open(manifest_path))
    manifest["fingerprint"]["jax"] = "0.0.0"
    with open(manifest_path, "w") as f:
        json.dump(manifest, f)

    with caplog.at_level(logging.WARNING, logger="flink_ml_tpu.compilebank"):
        with config.program_bank_mode(bank_dir):
            before = metrics.snapshot()
            out = np.asarray(affine_kernel(X, scale=5.0))
            assert _counter_delta(before, "jit.bankLoads") == 0.0
            assert _counter_delta(before, "bank.refused") >= 1.0
    assert any("fingerprint mismatch" in r.message for r in caplog.records)
    assert np.isfinite(out).all()


def test_torn_manifest_refused(tmp_path, caplog):
    bank_dir = _populated_bank(tmp_path)
    with open(os.path.join(bank_dir, compilebank.MANIFEST), "w") as f:
        f.write('{"fingerprint": {"jax"')  # mid-write truncation
    with caplog.at_level(logging.WARNING, logger="flink_ml_tpu.compilebank"):
        with config.program_bank_mode(bank_dir):
            out = np.asarray(affine_kernel(X, scale=5.0))
    assert any("unreadable manifest" in r.message for r in caplog.records)
    assert np.isfinite(out).all()


# ---------------------------------------------------------------------------
# signature edges
# ---------------------------------------------------------------------------

def test_unbankable_static_falls_through(tmp_path):
    class Opaque:  # no stable cross-process token
        def __hash__(self):
            return id(self)

    wobbly = lazy_jit(lambda x, tag: x + 1.0, static_argnames=("tag",))
    with config.program_bank_mode(str(tmp_path / "bank")):
        before = metrics.snapshot()
        out = np.asarray(wobbly(X, tag=Opaque()))
        assert _counter_delta(before, "bank.unbankable") == 1.0
        assert _counter_delta(before, "bank.misses") == 0.0
    np.testing.assert_allclose(out, X + 1.0)


def test_nested_trace_falls_through_to_inline(tmp_path):
    inner = lazy_jit(lambda x: x * 2.0)

    @jax.jit
    def outer(x):
        return inner(x) + 1.0

    with config.program_bank_mode(str(tmp_path / "bank")):
        before = metrics.snapshot()
        out = np.asarray(outer(jnp.asarray(X)))
        assert _counter_delta(before, "bank.nestedTrace") >= 1.0
    np.testing.assert_allclose(out, X * 2.0 + 1.0, rtol=1e-6)


def test_extras_roundtrip_across_warm_load(tmp_path):
    """Trace-time side state (FusedSegment guard messages ride this)
    persists with the entry and replays on a warm-load hit."""
    bank_dir = str(tmp_path / "bank")
    seen = []

    def run(x):
        return x + 1.0

    traced = lambda x: run(x)  # noqa: E731
    with config.program_bank_mode(bank_dir):
        bank = compilebank.active_bank()
        handled, _ = compilebank.banked_call(
            bank, "test.extras", traced, (jnp.asarray(X),), {}, {},
            extras_fn=lambda: {"guards": ["g1", "g2"]},
            on_extras=lambda e: seen.append(e),
        )
        assert handled
    with config.program_bank_mode(bank_dir):
        bank = compilebank.active_bank()
        handled, out = compilebank.banked_call(
            bank, "test.extras", traced, (jnp.asarray(X),), {}, {},
            on_extras=lambda e: seen.append(e),
        )
        assert handled
    assert seen == [{"guards": ["g1", "g2"]}, {"guards": ["g1", "g2"]}]
    np.testing.assert_allclose(np.asarray(out), X + 1.0)


# ---------------------------------------------------------------------------
# keyed_jit LRU bound (satellite: eviction must never be observable)
# ---------------------------------------------------------------------------

def test_keyed_jit_lru_evicts_and_reconstructs_identically():
    with config.kernel_cache_limit(2):
        before = metrics.snapshot()
        first = {p: np.asarray(power_kernel(p)(jnp.asarray(X))) for p in (1, 2, 3, 4)}
        evicted = _counter_delta(before, "jit.kernelCacheEvict")
        assert evicted >= 2.0
        assert metrics.snapshot()["gauges"]["jit.kernelCacheSize"] <= 2.0
        # touching an evicted key re-traces but the RESULT is identical:
        # eviction is a memory policy, never an observable behavior change
        again = {p: np.asarray(power_kernel(p)(jnp.asarray(X))) for p in (1, 2, 3, 4)}
    for p in (1, 2, 3, 4):
        assert first[p].tobytes() == again[p].tobytes()


def test_keyed_jit_lru_touch_refreshes_recency():
    with config.kernel_cache_limit(2):
        k5, k6 = power_kernel(5), power_kernel(6)
        power_kernel(5)  # touch 5: now 6 is least-recent
        before = metrics.snapshot()
        power_kernel(7)  # evicts 6, not 5
        assert _counter_delta(before, "jit.kernelCacheEvict") == 1.0
        before = metrics.snapshot()
        assert power_kernel(5) is k5  # still cached: no rebuild
        assert _counter_delta(before, "jit.kernels") == 0.0
        assert power_kernel(6) is not k6  # rebuilt after eviction


# ---------------------------------------------------------------------------
# serving warmup -> bank
# ---------------------------------------------------------------------------

def _serving_workload():
    from flink_ml_tpu.models.feature.normalizer import Normalizer
    from flink_ml_tpu.models.feature.standardscaler import StandardScalerModel
    from flink_ml_tpu.pipeline import PipelineModel
    from flink_ml_tpu.table import Table

    rng = np.random.default_rng(11)
    scaler = StandardScalerModel()
    scaler.mean = rng.standard_normal(6)
    scaler.std = np.abs(rng.standard_normal(6)) + 0.1
    scaler.set_input_col("features").set_output_col("scaled")
    norm = Normalizer().set_p(2.0).set_input_col("scaled").set_output_col("norm")
    model = PipelineModel([scaler, norm])
    example = Table({"features": rng.standard_normal((4, 6)).astype(np.float32)})
    return model, example


def test_server_warmup_populates_bank_then_serving_hits(tmp_path):
    from flink_ml_tpu.serving import MicroBatchServer

    bank_dir = str(tmp_path / "bank")
    model, example = _serving_workload()
    with config.program_bank_mode(bank_dir):
        info = MicroBatchServer(model, buckets=(4, 8)).warmup(example)
        assert info["programs"] == 2.0
        assert info["bankMisses"] == 2.0

    model2, _ = _serving_workload()
    with config.program_bank_mode(bank_dir):
        before = metrics.snapshot()
        out = list(
            MicroBatchServer(model2, buckets=(4, 8)).serve(iter([example]))
        )[0]
        delta = metrics.snapshot_delta(before, metrics.snapshot())["counters"]
        assert delta.get("jit.traces", 0) == 0, delta
        assert delta.get("bank.hits", 0) >= 1, delta
    assert np.isfinite(np.asarray(out.column("norm"))).all()


def test_warmup_reports_bank_counters_without_bank():
    from flink_ml_tpu.serving import MicroBatchServer

    model, example = _serving_workload()
    info = MicroBatchServer(model, buckets=(4,)).warmup(example)
    assert info["programs"] == 1.0
    assert info["bankHits"] == 0.0 and info["bankMisses"] == 0.0
    assert info["warmupMs"] >= 0.0


def test_modelstore_warmup_programs(tmp_path):
    from flink_ml_tpu.data.modelstore import ModelStore
    from flink_ml_tpu.serving import MicroBatchServer

    model, example = _serving_workload()
    store = ModelStore(budget_bytes=None)
    store.register("tenant-a", model)
    server = MicroBatchServer(model, buckets=(4,), store=store)
    with config.program_bank_mode(str(tmp_path / "bank")):
        info = store.warmup_programs(server, example)
        assert info["programs"] >= 1.0
