"""Benchmark harness battery — mirrors flink-ml-benchmark BenchmarkTest.java
/ DataGeneratorTest.java: config parsing (incl. the reference's commented
JSON files), generator determinism, result schema."""

import glob
import json
import os

import numpy as np
import pytest

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CONF_DIR = os.path.join(_REPO_ROOT, "conf")

from flink_ml_tpu.benchmark.datagenerator import (
    DenseVectorGenerator,
    DoubleGenerator,
    KMeansModelDataGenerator,
    LabeledPointWithWeightGenerator,
    RandomStringArrayGenerator,
    RandomStringGenerator,
)
from flink_ml_tpu.benchmark.runner import execute_benchmarks, load_config, run_benchmark


class TestGenerators:
    def test_dense_vector_generator(self):
        gen = DenseVectorGenerator().set_col_names(["features"]).set_num_values(100).set_vector_dim(5)
        (table,) = gen.get_data()
        assert table.num_rows == 100
        assert np.asarray(table.column("features")).shape == (100, 5)

    def test_deterministic_by_seed(self):
        def make():
            return (
                DenseVectorGenerator()
                .set_col_names(["f"]).set_num_values(10).set_vector_dim(3).set_seed(7)
            ).get_data()[0]

        np.testing.assert_array_equal(
            np.asarray(make().column("f")), np.asarray(make().column("f"))
        )

    def test_labeled_point_generator(self):
        gen = (
            LabeledPointWithWeightGenerator()
            .set_col_names(["features", "label", "weight"])
            .set_num_values(50).set_vector_dim(4).set_label_arity(3)
        )
        (table,) = gen.get_data()
        labels = np.asarray(table.column("label"))
        assert set(labels).issubset({0.0, 1.0, 2.0})
        assert np.asarray(table.column("features")).shape == (50, 4)

    def test_string_generators(self):
        (t,) = RandomStringGenerator().set_col_names(["s"]).set_num_values(20).get_data()
        assert all(isinstance(v, str) for v in t.column("s"))
        (t2,) = (
            RandomStringArrayGenerator()
            .set_col_names(["s"]).set_num_values(5).set_array_size(3)
        ).get_data()
        assert all(len(v) == 3 for v in t2.column("s"))

    def test_double_generator(self):
        (t,) = DoubleGenerator().set_col_names(["a", "b"]).set_num_values(10).get_data()
        assert t.column_names == ["a", "b"]

    def test_kmeans_model_data_generator(self):
        gen = KMeansModelDataGenerator().set_col_names(["centroids", "weights"])
        gen.set(gen.ARRAY_SIZE, 3).set(gen.VECTOR_DIM, 2)
        (t,) = gen.get_data()
        assert t.num_rows == 1


class TestRunner:
    def test_run_benchmark_schema(self):
        entry = {
            "stage": {
                "className": "org.apache.flink.ml.clustering.kmeans.KMeans",
                "paramMap": {"k": 2, "maxIter": 3},
            },
            "inputData": {
                "className": "org.apache.flink.ml.benchmark.datagenerator.common.DenseVectorGenerator",
                "paramMap": {"seed": 2, "colNames": [["features"]], "numValues": 200, "vectorDim": 5},
            },
        }
        result = run_benchmark("KMeans-1", entry)
        assert set(result) == {
            "name", "totalTimeMs", "inputRecordNum", "inputThroughput",
            "outputRecordNum", "outputThroughput", "phaseTimesMs", "metrics",
            "hostSyncCount", "dispatchDepth", "fusedSegments", "collectiveBreakdown",
            "wholeFitCount", "wholeFitFallbacks",
            "fleetSize", "modelsPerSecond",
            "offeredQps", "goodputQps", "saturationQps", "pageInCount",
            "hostDispatchMs", "dispatchGapMs", "gapCount", "dispatchAttribution",
            "h2dBytes", "h2dCount", "deviceCacheHits", "deviceCacheMisses",
            "checkpointCount", "checkpointBytes",
            "retryCount", "shedCount", "rejectCount", "peakQueueDepth",
            "peakHbmBytes", "residentModelBytes",
            "swapCount", "rollbackCount", "promoteRejected",
        }
        # the HBM ledger fields: a KMeans fit stages centroids/batches
        # through the accounted funnels, so the peak watermark is nonzero
        # and the published model constants are resident after transform
        # fleet fields stay zero for a solo (non-fleet) fit
        assert result["fleetSize"] == 0
        assert result["modelsPerSecond"] == 0.0
        # serving fields stay zero for a non-serving entry (no load
        # generator set the serving.* gauges, no model store paged)
        assert result["offeredQps"] == 0.0
        assert result["goodputQps"] == 0.0
        assert result["saturationQps"] == 0.0
        assert result["pageInCount"] == 0
        assert result["peakHbmBytes"] > 0
        assert 0 <= result["residentModelBytes"] <= result["peakHbmBytes"]
        assert result["hostSyncCount"] >= 1  # the packed fit readback
        # dispatch-wall attribution fields: the Lloyd program launch rides
        # the timed_dispatch funnel, and the gap is bounded by the work wall
        assert result["gapCount"] >= 1
        assert result["hostDispatchMs"] > 0
        work_ms = (
            result["phaseTimesMs"]["fit"] + result["phaseTimesMs"]["transform"]
        )
        assert 0.0 <= result["dispatchGapMs"] <= work_ms + 1e-6
        assert result["dispatchAttribution"] is None  # timeline off here
        # flow-control fields: a clean run pays no retries/sheds/rejects
        assert result["retryCount"] == 0
        assert result["shedCount"] == 0
        assert result["rejectCount"] == 0
        assert set(result["phaseTimesMs"]) == {"datagen", "fit", "transform", "collect"}
        assert result["inputRecordNum"] == 200
        assert result["totalTimeMs"] > 0

    def test_model_transform_benchmark(self):
        entry = {
            "stage": {
                "className": "org.apache.flink.ml.clustering.kmeans.KMeansModel",
                "paramMap": {},
            },
            "modelData": {
                "className": "org.apache.flink.ml.benchmark.datagenerator.clustering.KMeansModelDataGenerator",
                "paramMap": {"colNames": [["centroids", "weights"]], "arraySize": 3, "vectorDim": 5},
            },
            "inputData": {
                "className": "org.apache.flink.ml.benchmark.datagenerator.common.DenseVectorGenerator",
                "paramMap": {"colNames": [["features"]], "numValues": 100, "vectorDim": 5},
            },
        }
        result = run_benchmark("KMeansModel-1", entry)
        assert result["outputRecordNum"] == 100

    def test_load_reference_config(self):
        """The reference's shipped configs (with // license headers) parse.
        Environments without the reference checkout fall back to the conf/
        mirror of the same file (test_conf_mirrors_reference pins the
        mirroring), with a synthetic // header standing in for the
        reference's license banner."""
        ref = "/root/reference/flink-ml-benchmark/src/main/resources/kmeans-benchmark.json"
        if os.path.exists(ref):
            cfg = load_config(ref)
        else:
            import tempfile

            with open(os.path.join(_CONF_DIR, "kmeans-benchmark.json")) as f:
                text = "// mirrored reference config\n" + f.read()
            with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as tmp:
                tmp.write(text)
            cfg = load_config(tmp.name)
            os.unlink(tmp.name)
        assert "KMeans" in cfg
        assert cfg["KMeans"]["stage"]["className"].endswith("KMeans")

    def test_shipped_demo_config(self, tmp_path):
        cfg = load_config(os.path.join(_CONF_DIR, "benchmark-demo.json"))
        # shrink to keep the test fast
        small = {"version": 1, "StandardScaler-1": cfg["StandardScaler-1"]}
        small["StandardScaler-1"]["inputData"]["paramMap"]["numValues"] = 100
        results = execute_benchmarks(small)
        assert "StandardScaler-1" in results

    def test_conf_mirrors_reference(self):
        """conf/ carries every benchmark config the reference ships
        (flink-ml-benchmark/src/main/resources/*.json, 36 files)."""
        ref = {
            os.path.basename(p)
            for p in glob.glob(
                "/root/reference/flink-ml-benchmark/src/main/resources/*.json"
            )
        }
        if not ref:
            pytest.skip("reference tree not available")
        have = set(os.listdir(_CONF_DIR))
        missing = ref - have
        assert not missing, f"configs missing from conf/: {sorted(missing)}"


# Five configs the reference ships are broken upstream: the generator
# emits a column literally named "featuresCol" while the stage keeps its
# default input column ("features" for HasFeaturesCol, "input" for
# HasInputCol — see the reference's Has*Col defaults), so the reference's
# own Benchmark CLI would fail to resolve the column too. We mirror the
# files 1:1 and point the stage at the generated column only here.
_UPSTREAM_COL_FIXES = {
    "elementwiseproduct-benchmark.json": {"inputCol": "featuresCol"},
    "maxabsscaler-benchmark.json": {"inputCol": "featuresCol"},
    "normalizer-benchmark.json": {"inputCol": "featuresCol"},
    "polynoimalexpansion-benchmark.json": {"inputCol": "featuresCol"},
    "vectorslicer-benchmark.json": {"inputCol": "featuresCol"},
}


def _shrunk(entry, config_name):
    """Scale a shipped benchmark entry down to smoke-test size."""
    entry = json.loads(json.dumps(entry))  # deep copy
    for gen_key in ("inputData", "modelData"):
        pm = entry.get(gen_key, {}).get("paramMap", {})
        if "numValues" in pm:
            pm["numValues"] = min(pm["numValues"], 200)
    spm = entry.setdefault("stage", {}).setdefault("paramMap", {})
    if "maxIter" in spm:
        spm["maxIter"] = min(spm["maxIter"], 2)
    if "globalBatchSize" in spm:
        spm["globalBatchSize"] = min(spm["globalBatchSize"], 100)
    spm.update(_UPSTREAM_COL_FIXES.get(config_name, {}))
    return entry


@pytest.mark.parametrize(
    "config_path",
    sorted(glob.glob(os.path.join(_CONF_DIR, "*-benchmark.json"))),
    ids=os.path.basename,
)
def test_all_shipped_configs_execute(config_path):
    """Every shipped config (the reference's 36 + knn) runs end to end at
    smoke size through the JSON-driven harness."""
    cfg = load_config(config_path)
    for name, entry in cfg.items():
        if name == "version":
            continue
        result = run_benchmark(name, _shrunk(entry, os.path.basename(config_path)))
        assert result["totalTimeMs"] > 0
        assert result["outputRecordNum"] > 0
