"""Benchmark harness battery — mirrors flink-ml-benchmark BenchmarkTest.java
/ DataGeneratorTest.java: config parsing (incl. the reference's commented
JSON files), generator determinism, result schema."""

import json

import numpy as np

from flink_ml_tpu.benchmark.datagenerator import (
    DenseVectorGenerator,
    DoubleGenerator,
    KMeansModelDataGenerator,
    LabeledPointWithWeightGenerator,
    RandomStringArrayGenerator,
    RandomStringGenerator,
)
from flink_ml_tpu.benchmark.runner import execute_benchmarks, load_config, run_benchmark


class TestGenerators:
    def test_dense_vector_generator(self):
        gen = DenseVectorGenerator().set_col_names(["features"]).set_num_values(100).set_vector_dim(5)
        (table,) = gen.get_data()
        assert table.num_rows == 100
        assert np.asarray(table.column("features")).shape == (100, 5)

    def test_deterministic_by_seed(self):
        def make():
            return (
                DenseVectorGenerator()
                .set_col_names(["f"]).set_num_values(10).set_vector_dim(3).set_seed(7)
            ).get_data()[0]

        np.testing.assert_array_equal(
            np.asarray(make().column("f")), np.asarray(make().column("f"))
        )

    def test_labeled_point_generator(self):
        gen = (
            LabeledPointWithWeightGenerator()
            .set_col_names(["features", "label", "weight"])
            .set_num_values(50).set_vector_dim(4).set_label_arity(3)
        )
        (table,) = gen.get_data()
        labels = np.asarray(table.column("label"))
        assert set(labels).issubset({0.0, 1.0, 2.0})
        assert np.asarray(table.column("features")).shape == (50, 4)

    def test_string_generators(self):
        (t,) = RandomStringGenerator().set_col_names(["s"]).set_num_values(20).get_data()
        assert all(isinstance(v, str) for v in t.column("s"))
        (t2,) = (
            RandomStringArrayGenerator()
            .set_col_names(["s"]).set_num_values(5).set_array_size(3)
        ).get_data()
        assert all(len(v) == 3 for v in t2.column("s"))

    def test_double_generator(self):
        (t,) = DoubleGenerator().set_col_names(["a", "b"]).set_num_values(10).get_data()
        assert t.column_names == ["a", "b"]

    def test_kmeans_model_data_generator(self):
        gen = KMeansModelDataGenerator().set_col_names(["centroids", "weights"])
        gen.set(gen.ARRAY_SIZE, 3).set(gen.VECTOR_DIM, 2)
        (t,) = gen.get_data()
        assert t.num_rows == 1


class TestRunner:
    def test_run_benchmark_schema(self):
        entry = {
            "stage": {
                "className": "org.apache.flink.ml.clustering.kmeans.KMeans",
                "paramMap": {"k": 2, "maxIter": 3},
            },
            "inputData": {
                "className": "org.apache.flink.ml.benchmark.datagenerator.common.DenseVectorGenerator",
                "paramMap": {"seed": 2, "colNames": [["features"]], "numValues": 200, "vectorDim": 5},
            },
        }
        result = run_benchmark("KMeans-1", entry)
        assert set(result) == {
            "name", "totalTimeMs", "inputRecordNum", "inputThroughput",
            "outputRecordNum", "outputThroughput",
        }
        assert result["inputRecordNum"] == 200
        assert result["totalTimeMs"] > 0

    def test_model_transform_benchmark(self):
        entry = {
            "stage": {
                "className": "org.apache.flink.ml.clustering.kmeans.KMeansModel",
                "paramMap": {},
            },
            "modelData": {
                "className": "org.apache.flink.ml.benchmark.datagenerator.clustering.KMeansModelDataGenerator",
                "paramMap": {"colNames": [["centroids", "weights"]], "arraySize": 3, "vectorDim": 5},
            },
            "inputData": {
                "className": "org.apache.flink.ml.benchmark.datagenerator.common.DenseVectorGenerator",
                "paramMap": {"colNames": [["features"]], "numValues": 100, "vectorDim": 5},
            },
        }
        result = run_benchmark("KMeansModel-1", entry)
        assert result["outputRecordNum"] == 100

    def test_load_reference_config(self):
        """The reference's shipped configs (with // license headers) parse."""
        cfg = load_config(
            "/root/reference/flink-ml-benchmark/src/main/resources/kmeans-benchmark.json"
        )
        assert "KMeans" in cfg
        assert cfg["KMeans"]["stage"]["className"].endswith("KMeans")

    def test_shipped_demo_config(self, tmp_path):
        cfg = load_config("conf/benchmark-demo.json")
        # shrink to keep the test fast
        small = {"version": 1, "StandardScaler-1": cfg["StandardScaler-1"]}
        small["StandardScaler-1"]["inputData"]["paramMap"]["numValues"] = 100
        results = execute_benchmarks(small)
        assert "StandardScaler-1" in results
