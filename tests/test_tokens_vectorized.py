"""Columnar (fixed-width unicode matrix) vs per-row (object array) parity
for the string feature stages — both layouts must produce identical
outputs, mirroring the reference's single row-at-a-time semantics
(feature/countvectorizer/CountVectorizer.java, hashingtf/HashingTF.java,
ngram/NGram.java, stopwordsremover/StopWordsRemover.java,
stringindexer/StringIndexer.java)."""

import numpy as np
import pytest

from flink_ml_tpu.table import SparseBatch, Table


def _object_col(matrix):
    out = np.empty(matrix.shape[0], dtype=object)
    for i, row in enumerate(matrix):
        out[i] = [str(t) for t in row]
    return out


def _rand_matrix(n=50, k=8, m=12, seed=0):
    rng = np.random.RandomState(seed)
    vocab = np.arange(m).astype(str)
    return vocab[rng.randint(0, m, size=(n, k))]


def _sparse_rows(col):
    assert isinstance(col, SparseBatch)
    rows = []
    for i in range(col.n):
        mask = col.indices[i] >= 0
        rows.append(
            (col.indices[i][mask].tolist(), col.values[i][mask].tolist())
        )
    return rows


class TestCountVectorizerParity:
    @pytest.mark.parametrize("binary", [False, True])
    @pytest.mark.parametrize("min_tf", [1.0, 2.0, 0.2])
    def test_fit_transform(self, binary, min_tf):
        from flink_ml_tpu.models.feature.countvectorizer import CountVectorizer

        A = _rand_matrix()
        cv = (
            CountVectorizer()
            .set_input_col("tokens")
            .set_output_col("vec")
            .set_binary(binary)
            .set_min_tf(min_tf)
            .set_min_df(2.0)
        )
        m_mat = cv.fit(Table({"tokens": A}))
        m_obj = cv.fit(Table({"tokens": _object_col(A)}))
        assert m_mat.vocabulary == m_obj.vocabulary
        out_mat = m_mat.transform(Table({"tokens": A}))[0].column("vec")
        out_obj = m_obj.transform(Table({"tokens": _object_col(A)}))[0].column("vec")
        assert _sparse_rows(out_mat) == _sparse_rows(out_obj)


class TestHashingTFParity:
    @pytest.mark.parametrize("binary", [False, True])
    def test_transform(self, binary):
        from flink_ml_tpu.models.feature.hashingtf import HashingTF

        A = _rand_matrix(seed=1)
        tf = (
            HashingTF()
            .set_input_col("tokens")
            .set_output_col("vec")
            .set_binary(binary)
            .set_num_features(64)  # small: force collisions
        )
        out_mat = tf.transform(Table({"tokens": A}))[0].column("vec")
        out_obj = tf.transform(Table({"tokens": _object_col(A)}))[0].column("vec")
        assert _sparse_rows(out_mat) == _sparse_rows(out_obj)


class TestNGramParity:
    @pytest.mark.parametrize("n", [1, 2, 3, 9])  # 9 > k: empty outputs
    def test_transform(self, n):
        from flink_ml_tpu.models.feature.ngram import NGram

        A = _rand_matrix(seed=2)
        ng = NGram().set_input_col("tokens").set_output_col("grams").set_n(n)
        out_mat = ng.transform(Table({"tokens": A}))[0].column("grams")
        out_obj = ng.transform(Table({"tokens": _object_col(A)}))[0].column("grams")
        mat_lists = (
            [list(r) for r in out_mat]
            if isinstance(out_mat, np.ndarray) and out_mat.ndim == 2
            else [list(r) for r in out_mat]
        )
        assert mat_lists == [list(r) for r in out_obj]


class TestStopWordsRemoverParity:
    @pytest.mark.parametrize("case_sensitive", [False, True])
    def test_transform(self, case_sensitive):
        from flink_ml_tpu.models.feature.stopwordsremover import StopWordsRemover

        A = _rand_matrix(seed=3)
        sw = (
            StopWordsRemover()
            .set_input_cols("tokens")
            .set_output_cols("kept")
            .set_stop_words("1", "5", "7")
            .set_case_sensitive(case_sensitive)
        )
        out_mat = sw.transform(Table({"tokens": A}))[0].column("kept")
        out_obj = sw.transform(Table({"tokens": _object_col(A)}))[0].column("kept")
        assert [list(r) for r in out_mat] == [list(r) for r in out_obj]


class TestTokenizerParity:
    def test_transform(self):
        from flink_ml_tpu.models.feature.tokenizer import Tokenizer

        strings = np.asarray(
            ["A b  c", "a B", "", "x\ty z ", "a B"], dtype="<U8"
        )
        obj = np.empty(len(strings), dtype=object)
        obj[:] = [str(s) for s in strings]
        tk = Tokenizer().set_input_col("s").set_output_col("t")
        out_mat = tk.transform(Table({"s": strings}))[0].column("t")
        out_obj = tk.transform(Table({"s": obj}))[0].column("t")
        assert [list(r) for r in out_mat] == [list(r) for r in out_obj]


class TestRegexTokenizerParity:
    @pytest.mark.parametrize("gaps", [True, False])
    def test_transform(self, gaps):
        from flink_ml_tpu.models.feature.regextokenizer import RegexTokenizer

        strings = np.asarray(["Aa1 bb2", "c33 D", "e", "c33 D"], dtype="<U8")
        obj = np.empty(len(strings), dtype=object)
        obj[:] = [str(s) for s in strings]
        rt = (
            RegexTokenizer()
            .set_input_col("s")
            .set_output_col("t")
            .set_gaps(gaps)
            .set_pattern(r"\s+" if gaps else r"[a-z]+")
        )
        out_mat = rt.transform(Table({"s": strings}))[0].column("t")
        out_obj = rt.transform(Table({"s": obj}))[0].column("t")
        assert [list(r) for r in out_mat] == [list(r) for r in out_obj]


class TestStringIndexerParity:
    @pytest.mark.parametrize(
        "order", ["arbitrary", "alphabetAsc", "alphabetDesc", "frequencyDesc", "frequencyAsc"]
    )
    def test_fit_transform(self, order):
        from flink_ml_tpu.models.feature.stringindexer import StringIndexer

        rng = np.random.RandomState(4)
        vocab = np.array(["aa", "b", "cc", "d", "e"])
        S = vocab[rng.randint(0, 5, size=200)]
        obj = np.empty(len(S), dtype=object)
        obj[:] = [str(s) for s in S]
        si = (
            StringIndexer()
            .set_input_cols("s")
            .set_output_cols("idx")
            .set_string_order_type(order)
        )
        m_mat = si.fit(Table({"s": S}))
        m_obj = si.fit(Table({"s": obj}))
        if order.startswith("frequency"):
            # tie order may differ between Counter and np.unique; compare the
            # (string -> frequency-rank-class) assignment instead
            assert sorted(m_mat.string_arrays[0]) == sorted(m_obj.string_arrays[0])
        else:
            assert m_mat.string_arrays == m_obj.string_arrays
        out_mat = np.asarray(m_mat.transform(Table({"s": S}))[0].column("idx"))
        out_ref = np.asarray(m_mat.transform(Table({"s": obj}))[0].column("idx"))
        np.testing.assert_array_equal(out_mat, out_ref)

    def test_unseen_raises(self):
        from flink_ml_tpu.models.feature.stringindexer import StringIndexer

        si = StringIndexer().set_input_cols("s").set_output_cols("idx")
        model = si.fit(Table({"s": np.asarray(["a", "b"], dtype="<U2")}))
        with pytest.raises(ValueError, match="unseen string"):
            model.transform(Table({"s": np.asarray(["a", "zz"], dtype="<U2")}))

    def test_skip_invalid_drops_rows(self):
        from flink_ml_tpu.models.feature.stringindexer import StringIndexer

        si = (
            StringIndexer()
            .set_input_cols("s")
            .set_output_cols("idx")
            .set_handle_invalid("skip")
        )
        model = si.fit(Table({"s": np.asarray(["a", "b"], dtype="<U2")}))
        out = model.transform(Table({"s": np.asarray(["a", "zz", "b"], dtype="<U2")}))[0]
        assert out.num_rows == 2


def _dict_col(matrix):
    """Dictionary-encode an object/unicode token matrix for the device path."""
    from flink_ml_tpu.models.feature import _tokens
    from flink_ml_tpu.table import DictTokenMatrix

    uniq, ids = _tokens.encode(matrix)
    return DictTokenMatrix(uniq, ids)


class TestDictTokenMatrixParity:
    """The dictionary-encoded (device) paths must agree with the per-row
    object-array paths for every string stage that has one."""

    @pytest.mark.parametrize("binary", [False, True])
    @pytest.mark.parametrize("min_tf", [1.0, 2.0, 0.2])
    def test_countvectorizer(self, binary, min_tf):
        from flink_ml_tpu.models.feature.countvectorizer import CountVectorizer

        A = _rand_matrix(seed=7)
        cv = (
            CountVectorizer()
            .set_input_col("tokens")
            .set_output_col("vec")
            .set_binary(binary)
            .set_min_tf(min_tf)
            .set_min_df(2.0)
        )
        m_obj = cv.fit(Table({"tokens": _object_col(A)}))
        m_dict = cv.fit(Table({"tokens": _dict_col(A)}))
        assert m_dict.vocabulary == m_obj.vocabulary
        out_obj = m_obj.transform(Table({"tokens": _object_col(A)}))[0].column("vec")
        out_dict = m_dict.transform(Table({"tokens": _dict_col(A)}))[0].column("vec")
        obj_rows = _sparse_rows(out_obj)
        dict_rows = [
            (
                [int(i) for i in np.asarray(out_dict.indices[r]) if i >= 0],
                [
                    float(v)
                    for i, v in zip(
                        np.asarray(out_dict.indices[r]), np.asarray(out_dict.values[r])
                    )
                    if i >= 0
                ],
            )
            for r in range(out_dict.n)
        ]
        assert dict_rows == obj_rows

    def test_hashingtf(self):
        from flink_ml_tpu.models.feature.hashingtf import HashingTF

        A = _rand_matrix(seed=8)
        tf = (
            HashingTF().set_input_col("tokens").set_output_col("vec").set_num_features(64)
        )
        out_obj = tf.transform(Table({"tokens": _object_col(A)}))[0].column("vec")
        out_dict = tf.transform(Table({"tokens": _dict_col(A)}))[0].column("vec")
        obj_rows = _sparse_rows(out_obj)
        for r in range(out_dict.n):
            idx = np.asarray(out_dict.indices[r])
            val = np.asarray(out_dict.values[r])
            mask = idx >= 0
            assert ([int(i) for i in idx[mask]], [float(v) for v in val[mask]]) == obj_rows[r]

    @pytest.mark.parametrize("n", [1, 2, 3])
    def test_ngram(self, n):
        from flink_ml_tpu.models.feature.ngram import NGram

        A = _rand_matrix(seed=9, k=5)
        ng = NGram().set_input_col("tokens").set_output_col("grams").set_n(n)
        out_obj = ng.transform(Table({"tokens": _object_col(A)}))[0].column("grams")
        out_dict = ng.transform(Table({"tokens": _dict_col(A)}))[0].column("grams")
        from flink_ml_tpu.table import DictTokenMatrix

        assert isinstance(out_dict, DictTokenMatrix)
        assert [out_dict.row(i) for i in range(len(out_dict))] == [
            list(r) for r in out_obj
        ]

    @pytest.mark.parametrize("case_sensitive", [False, True])
    def test_stopwordsremover(self, case_sensitive):
        from flink_ml_tpu.models.feature.stopwordsremover import StopWordsRemover

        A = _rand_matrix(seed=10)
        sw = (
            StopWordsRemover()
            .set_input_cols("tokens")
            .set_output_cols("kept")
            .set_stop_words("1", "5", "7")
            .set_case_sensitive(case_sensitive)
        )
        out_obj = sw.transform(Table({"tokens": _object_col(A)}))[0].column("kept")
        out_dict = sw.transform(Table({"tokens": _dict_col(A)}))[0].column("kept")
        assert [out_dict.row(i) for i in range(len(out_dict))] == [
            list(r) for r in out_obj
        ]


class TestTokenColumnTablePlumbing:
    """Table.rows()/collect()/concat must handle the token column layouts
    (review findings: DenseVector coercion crash, concat crashes)."""

    def test_collect_unicode_matrix(self):
        t = Table({"tok": np.asarray([["a", "b"], ["c", "d"]])})
        assert [r["tok"] for r in t.collect()] == [["a", "b"], ["c", "d"]]

    def test_collect_dict_tokens(self):
        A = _rand_matrix(n=4, k=3)
        t = Table({"tok": _dict_col(A)})
        assert [r["tok"] for r in t.collect()] == [list(r) for r in A]

    def test_concat_dict_tokens_different_vocabs(self):
        a = _dict_col(np.asarray([["a", "b"], ["b", "a"]]))
        b = _dict_col(np.asarray([["c", "a", "c"], ["a", "c", "b"]]))
        merged = Table({"tok": a}).concat(Table({"tok": b}))
        assert [r["tok"] for r in merged.collect()] == [
            ["a", "b"],
            ["b", "a"],
            ["c", "a", "c"],
            ["a", "c", "b"],
        ]

    def test_concat_unicode_matrices_different_widths(self):
        a = np.asarray([["a", "b"]])
        b = np.asarray([["c", "d", "e"]])
        merged = Table({"tok": a}).concat(Table({"tok": b}))
        assert [r["tok"] for r in merged.collect()] == [["a", "b"], ["c", "d", "e"]]

    def test_reservoir_sample_token_table(self):
        from flink_ml_tpu.utils.datastream import sample

        tables = [
            Table({"tok": _dict_col(_rand_matrix(n=20, k=3, seed=s))})
            for s in range(3)
        ]
        out = sample(tables, 10, seed=0)
        assert out.num_rows == 10


class TestMixedLayoutConcat:
    def test_matrix_concat_object(self):
        a = np.asarray([["a", "b"]])
        obj = np.empty(1, dtype=object)
        obj[0] = ["c"]
        merged = Table({"tok": a}).concat(Table({"tok": obj}))
        assert [r["tok"] for r in merged.collect()] == [["a", "b"], ["c"]]

    def test_object_concat_dict(self):
        obj = np.empty(2, dtype=object)
        obj[0] = ["x", "y"]
        obj[1] = []
        d = _dict_col(np.asarray([["a", "x"]]))
        merged = Table({"tok": obj}).concat(Table({"tok": d}))
        assert [r["tok"] for r in merged.collect()] == [["x", "y"], [], ["a", "x"]]


class TestGatherFreeMapKernels:
    """The gather-free mapping kernels (preimage counts, compare-map,
    dropset filter) must agree exactly with the gather forms they replace
    — the gather form stays the reference semantics for big dictionaries."""

    def _ids(self, n=500, k=16, u=40, seed=0):
        import jax

        rng = np.random.RandomState(seed)
        ids = rng.randint(0, u, size=(n, k)).astype(np.int32)
        ids[rng.random(ids.shape) < 0.1] = -1  # absent tokens
        return jax.device_put(ids)

    def test_preimage_counts_match_gather(self):
        import jax
        from flink_ml_tpu.ops import tokens as T

        u, V = 40, 30
        rng = np.random.RandomState(1)
        # injective partial map: 30 of 40 dict ids keep a vocab slot
        lut = np.full(u, -1, np.int32)
        lut[rng.permutation(u)[:V]] = np.arange(V, dtype=np.int32)
        ids = self._ids(u=u)
        thr = np.ones(ids.shape[0], np.float32)
        pre = T.lut_preimage(lut, V)
        assert pre is not None
        gi, gv = T._map_and_counts_dense(ids, jax.device_put(lut), thr, V)
        pi, pv = T._counts_dense_preimage(ids, jax.device_put(pre), thr, V)
        np.testing.assert_array_equal(np.asarray(gi), np.asarray(pi))
        np.testing.assert_array_equal(np.asarray(gv), np.asarray(pv))

    def test_preimage_rejects_collisions_and_range(self):
        from flink_ml_tpu.ops import tokens as T

        assert T.lut_preimage(np.asarray([0, 1, 1], np.int32), 4) is None
        assert T.lut_preimage(np.asarray([0, 5], np.int32), 4) is None
        assert T.lut_preimage(np.asarray([-1, 2, 0], np.int32), 3) is not None

    def test_compare_map_matches_gather_with_collisions(self):
        import jax
        from flink_ml_tpu.ops import tokens as T

        u = 40
        lut = (np.arange(u, dtype=np.int32) * 7) % 13  # many collisions
        lut[5] = -1  # dropped dict entry
        ids = self._ids(u=u)
        got = np.asarray(T.compare_map(ids, jax.device_put(lut)))
        exp = np.asarray(T.gather_map(ids, jax.device_put(lut)))
        np.testing.assert_array_equal(got, exp)

    def test_map_term_runs_host_lut_matches_device_lut(self):
        import jax
        from flink_ml_tpu.ops import tokens as T

        u, V = 40, 13
        lut = ((np.arange(u, dtype=np.int32) * 7) % V).astype(np.int32)
        ids = self._ids(u=u)
        thr = np.ones(ids.shape[0], np.float32)
        hi, hv = T.map_term_runs_chunked(ids, lut, thr, num_terms=V)
        di, dv = T.map_term_runs_chunked(ids, jax.device_put(lut), thr, num_terms=V)
        np.testing.assert_array_equal(np.asarray(hi), np.asarray(di))
        np.testing.assert_array_equal(np.asarray(hv), np.asarray(dv))

    def test_dropset_filter_matches_mask_gather(self):
        import jax
        from flink_ml_tpu.ops import tokens as T

        u = 40
        keep = np.ones(u, bool)
        keep[[3, 7, 21]] = False
        ids = self._ids(u=u)
        got = np.asarray(T.filter_tokens_chunked(ids, keep))
        exp = np.asarray(T.filter_tokens(ids, jax.device_put(keep)))
        np.testing.assert_array_equal(got, exp)
        # nothing dropped: identity
        all_keep = np.ones(u, bool)
        same = T.filter_tokens_chunked(ids, all_keep)
        np.testing.assert_array_equal(np.asarray(same), np.asarray(ids))
