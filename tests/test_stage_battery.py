"""Uniform per-stage battery: every public stage runs the reference's
canonical five checks (KMeansTest.java:34-56 pattern x 56 test classes):

  1. param defaults + setter round-trips (every declared Param),
  2. output schema (new columns present, input columns preserved),
  3. fit/transform behavior probe (golden-style values per stage),
  4. save -> load -> predict produces identical outputs,
  5. get_model_data/set_model_data round-trip (models), or a type-level
     assertion that the stage is a stateless Transformer/AlgoOperator.

Deep golden-value suites live in the per-area test files; this battery
guarantees no stage ever ships without the full contract.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import numpy as np
import pytest

from flink_ml_tpu.api import Estimator, Model
from flink_ml_tpu.linalg import Vectors
from flink_ml_tpu.table import SparseBatch, StreamTable, Table


# ---------------------------------------------------------------------------
# spec + helpers
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class StageSpec:
    name: str
    make: Callable[[], Any]  # configured stage (Estimator / Model / AlgoOperator)
    inputs: Callable[[], List[Table]]  # fit inputs (and default transform inputs)
    setters: Dict[str, Any]  # paramName -> non-default valid value
    new_cols: List[str]  # columns transform adds (empty => custom schema)
    check: Callable[[List[Table]], None]  # behavior probe on transform outputs
    transform_inputs: Optional[Callable[[], List[Table]]] = None
    keeps_input_cols: bool = True
    # online estimators fit on a StreamTable; save/load then applies to the model
    stream_fit: bool = False
    # hook run on the fitted model before transform (e.g. process_updates()
    # to drain an online model's version stream)
    post_fit: Optional[Callable[[Any], None]] = None


def _col(tables: List[Table], name: str) -> np.ndarray:
    return np.asarray(tables[0].column(name))


def _columns_equal(a, b) -> bool:
    if isinstance(a, SparseBatch) or isinstance(b, SparseBatch):
        return (
            isinstance(a, SparseBatch)
            and isinstance(b, SparseBatch)
            and a.size == b.size
            and np.array_equal(a.indices, b.indices)
            and np.array_equal(a.values, b.values)
        )
    a, b = np.asarray(a), np.asarray(b)
    if a.dtype == object or b.dtype == object:
        if a.shape[0] != b.shape[0]:
            return False
        return all(np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(a, b))
    return np.array_equal(a, b, equal_nan=True)


def assert_tables_equal(got: List[Table], want: List[Table]) -> None:
    assert len(got) == len(want)
    for g, w in zip(got, want):
        assert set(g.column_names) == set(w.column_names)
        for name in w.column_names:
            assert _columns_equal(g.column(name), w.column(name)), f"column {name} differs"


def run_stage(spec: StageSpec, stage=None):
    """fit (if estimator) + transform; returns (fitted_or_stage, outputs)."""
    stage = stage if stage is not None else spec.make()
    fit_in = spec.inputs()
    t_in = spec.transform_inputs() if spec.transform_inputs else fit_in
    if isinstance(stage, Estimator):
        model = stage.fit(*fit_in)
        if spec.post_fit is not None:
            spec.post_fit(model)
        return model, model.transform(*t_in)
    return stage, stage.transform(*t_in)


# ---------------------------------------------------------------------------
# tiny datasets
# ---------------------------------------------------------------------------

def _dense_table(seed=0, n=40, d=3, label_classes=2, weight=False):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d)
    y = (X[:, 0] > 0).astype(np.float64) if label_classes == 2 else rng.randint(
        0, label_classes, n
    ).astype(np.float64)
    cols = {"features": X, "label": y}
    if weight:
        cols["weight"] = rng.rand(n)
    return Table(cols)


def _blobs_table(seed=0, n=40):
    rng = np.random.RandomState(seed)
    X = np.vstack([rng.randn(n // 2, 2) * 0.2, rng.randn(n // 2, 2) * 0.2 + 8.0])
    return Table({"features": X})


def _categorical_table():
    return Table(
        {
            "features": [
                Vectors.dense(0, 0),
                Vectors.dense(0, 1),
                Vectors.dense(1, 0),
                Vectors.dense(1, 1),
                Vectors.dense(1, 1),
            ],
            "label": [11.0, 11.0, 22.0, 22.0, 22.0],
        }
    )


def _vec_table():
    return Table(
        {"input": [Vectors.dense(0, 3, -1), Vectors.dense(2.1, 0, 2), Vectors.dense(4.1, 5.1, 0.5)]}
    )


def _docs_table():
    return Table({"input": [["a", "b", "c"], ["a", "b", "b", "c", "a"], ["a", "x"]]})


def _strings_table():
    return Table({"input": ["Test for tokenization.", "Te,st. punct"]})


def _sparse_table():
    return Table(
        {
            "id": [0, 1, 2],
            "vec": [
                Vectors.sparse(6, [0, 1, 2], [1.0, 1.0, 1.0]),
                Vectors.sparse(6, [2, 3, 4], [1.0, 1.0, 1.0]),
                Vectors.sparse(6, [0, 2, 4], [1.0, 1.0, 1.0]),
            ],
        }
    )


def _classification_stream(seed=1, batches=8, batch=32):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(batches):
        y = rng.randint(0, 2, batch).astype(np.float64)
        X = rng.randn(batch, 2) * 0.3
        X[:, 0] += np.where(y > 0, 2.0, -2.0)  # cleanly separable
        out.append(Table({"features": X, "label": y}))
    return StreamTable.from_batches(out)


def _kmeans_stream(seed=0, batches=3, batch=20):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(batches):
        a = rng.randn(batch // 2, 2) * 0.1
        b = rng.randn(batch // 2, 2) * 0.1 + [10, 10]
        out.append(Table({"features": np.vstack([a, b])}))
    return StreamTable.from_batches(out)


# ---------------------------------------------------------------------------
# behavior probes
# ---------------------------------------------------------------------------

def _check_binary_predictions(outs):
    pred = _col(outs, "prediction")
    assert set(np.unique(pred)) <= {0.0, 1.0}
    assert pred.shape[0] > 0


def _check_column_close(name, expected, atol=1e-6):
    def check(outs):
        np.testing.assert_allclose(
            np.asarray(_col(outs, name), dtype=np.float64), expected, atol=atol
        )

    return check


# ---------------------------------------------------------------------------
# the registry — every public stage
# ---------------------------------------------------------------------------

def _specs() -> List[StageSpec]:
    from flink_ml_tpu.models.classification.knn import Knn
    from flink_ml_tpu.models.classification.linearsvc import LinearSVC
    from flink_ml_tpu.models.classification.logisticregression import LogisticRegression
    from flink_ml_tpu.models.classification.naivebayes import NaiveBayes
    from flink_ml_tpu.models.classification.onlinelogisticregression import (
        OnlineLogisticRegression,
    )
    from flink_ml_tpu.models.clustering.agglomerativeclustering import (
        AgglomerativeClustering,
    )
    from flink_ml_tpu.models.clustering.kmeans import KMeans
    from flink_ml_tpu.models.clustering.onlinekmeans import (
        OnlineKMeans,
        generate_random_model_data,
    )
    from flink_ml_tpu.models.evaluation.binaryclassification import (
        BinaryClassificationEvaluator,
    )
    from flink_ml_tpu.models.feature.binarizer import Binarizer
    from flink_ml_tpu.models.feature.bucketizer import Bucketizer
    from flink_ml_tpu.models.feature.countvectorizer import CountVectorizer
    from flink_ml_tpu.models.feature.dct import DCT
    from flink_ml_tpu.models.feature.elementwiseproduct import ElementwiseProduct
    from flink_ml_tpu.models.feature.featurehasher import FeatureHasher
    from flink_ml_tpu.models.feature.hashingtf import HashingTF
    from flink_ml_tpu.models.feature.idf import IDF
    from flink_ml_tpu.models.feature.imputer import Imputer
    from flink_ml_tpu.models.feature.interaction import Interaction
    from flink_ml_tpu.models.feature.kbinsdiscretizer import KBinsDiscretizer
    from flink_ml_tpu.models.feature.lsh import MinHashLSH
    from flink_ml_tpu.models.feature.maxabsscaler import MaxAbsScaler
    from flink_ml_tpu.models.feature.minmaxscaler import MinMaxScaler
    from flink_ml_tpu.models.feature.ngram import NGram
    from flink_ml_tpu.models.feature.normalizer import Normalizer
    from flink_ml_tpu.models.feature.onehotencoder import OneHotEncoder
    from flink_ml_tpu.models.feature.polynomialexpansion import PolynomialExpansion
    from flink_ml_tpu.models.feature.randomsplitter import RandomSplitter
    from flink_ml_tpu.models.feature.regextokenizer import RegexTokenizer
    from flink_ml_tpu.models.feature.robustscaler import RobustScaler
    from flink_ml_tpu.models.feature.sqltransformer import SQLTransformer
    from flink_ml_tpu.models.feature.standardscaler import StandardScaler
    from flink_ml_tpu.models.feature.stopwordsremover import StopWordsRemover
    from flink_ml_tpu.models.feature.stringindexer import (
        IndexToStringModel,
        StringIndexer,
    )
    from flink_ml_tpu.models.feature.tokenizer import Tokenizer
    from flink_ml_tpu.models.feature.univariatefeatureselector import (
        UnivariateFeatureSelector,
    )
    from flink_ml_tpu.models.feature.variancethresholdselector import (
        VarianceThresholdSelector,
    )
    from flink_ml_tpu.models.feature.vectorassembler import VectorAssembler
    from flink_ml_tpu.models.feature.vectorindexer import VectorIndexer
    from flink_ml_tpu.models.feature.vectorslicer import VectorSlicer
    from flink_ml_tpu.models.regression.linearregression import LinearRegression
    from flink_ml_tpu.models.stats.anovatest import ANOVATest
    from flink_ml_tpu.models.stats.chisqtest import ChiSqTest
    from flink_ml_tpu.models.stats.fvaluetest import FValueTest

    specs = [
        # -- classification --------------------------------------------------
        StageSpec(
            name="LogisticRegression",
            make=lambda: LogisticRegression().set_max_iter(10).set_global_batch_size(40),
            inputs=lambda: [_dense_table(seed=1)],
            setters={"maxIter": 7, "learningRate": 0.5, "reg": 0.1, "elasticNet": 0.5,
                     "tol": 0.01, "globalBatchSize": 16, "featuresCol": "f2",
                     "labelCol": "l2", "predictionCol": "p2", "rawPredictionCol": "r2"},
            new_cols=["prediction", "rawPrediction"],
            check=_check_binary_predictions,
        ),
        StageSpec(
            name="LinearSVC",
            make=lambda: LinearSVC().set_max_iter(10).set_global_batch_size(40),
            inputs=lambda: [_dense_table(seed=2)],
            setters={"maxIter": 3, "threshold": 0.5, "reg": 0.2},
            new_cols=["prediction", "rawPrediction"],
            check=_check_binary_predictions,
        ),
        StageSpec(
            name="NaiveBayes",
            make=lambda: NaiveBayes(),
            inputs=lambda: [_categorical_table()],
            setters={"smoothing": 2.0, "featuresCol": "f", "predictionCol": "p"},
            new_cols=["prediction"],
            check=lambda outs: np.testing.assert_array_equal(
                _col(outs, "prediction"), [11.0, 11.0, 22.0, 22.0, 22.0]
            ),
        ),
        StageSpec(
            name="Knn",
            make=lambda: Knn().set_k(3),
            inputs=lambda: [
                Table(
                    {
                        "features": np.vstack([np.zeros((5, 2)), np.ones((5, 2)) * 10]),
                        "label": np.asarray([1.0] * 5 + [2.0] * 5),
                    }
                )
            ],
            setters={"k": 2},
            new_cols=["prediction"],
            transform_inputs=lambda: [Table({"features": [[0.5, 0.5], [9.0, 9.5]]})],
            check=lambda outs: np.testing.assert_array_equal(
                _col(outs, "prediction"), [1.0, 2.0]
            ),
        ),
        StageSpec(
            name="OnlineLogisticRegression",
            make=lambda: OnlineLogisticRegression()
            .set_global_batch_size(32)
            .set_initial_model_data(
                Table({"coefficient": [Vectors.dense(0.0, 0.0)], "modelVersion": [0]})
            ),
            inputs=lambda: [_classification_stream()],
            setters={"alpha": 0.5, "beta": 0.5, "reg": 0.1, "elasticNet": 0.5,
                     "globalBatchSize": 8},
            new_cols=["prediction", "rawPrediction"],
            transform_inputs=lambda: [Table({"features": [[3.0, 0.0], [-3.0, 0.0]]})],
            check=lambda outs: np.testing.assert_array_equal(
                _col(outs, "prediction"), [1.0, 0.0]
            ),
            stream_fit=True,
            post_fit=lambda model: model.process_updates(),
        ),
        # -- clustering --------------------------------------------------------
        StageSpec(
            name="KMeans",
            make=lambda: KMeans().set_k(2).set_seed(2).set_max_iter(10),
            inputs=lambda: [_blobs_table(seed=3)],
            setters={"k": 3, "maxIter": 5, "initMode": "random", "seed": 7,
                     "distanceMeasure": "cosine"},
            new_cols=["prediction"],
            check=lambda outs: (
                lambda pred: (
                    # the two blobs land in two distinct clusters
                    len({int(p) for p in pred[:20]}) == 1
                    and len({int(p) for p in pred[20:]}) == 1
                    and pred[0] != pred[-1]
                )
            )(_col(outs, "prediction"))
            or None,
        ),
        StageSpec(
            name="OnlineKMeans",
            make=lambda: OnlineKMeans()
            .set_global_batch_size(20)
            .set_initial_model_data(generate_random_model_data(2, 2, 0.0, seed=5)),
            inputs=lambda: [_kmeans_stream()],
            setters={"decayFactor": 0.5, "globalBatchSize": 10, "seed": 3},
            new_cols=["prediction"],
            transform_inputs=lambda: [Table({"features": [[0.0, 0.0], [10.0, 10.0]]})],
            check=lambda outs: len(set(_col(outs, "prediction"))) == 2 or None,
            stream_fit=True,
            post_fit=lambda model: model.process_updates(),
        ),
        StageSpec(
            name="AgglomerativeClustering",
            make=lambda: AgglomerativeClustering().set_num_clusters(2),
            inputs=lambda: [_blobs_table(seed=4, n=20)],
            setters={"numClusters": 3, "linkage": "average", "computeFullTree": True},
            new_cols=["prediction"],
            check=lambda outs: len(set(_col(outs, "prediction"))) == 2 or None,
        ),
        # -- regression -------------------------------------------------------
        StageSpec(
            name="LinearRegression",
            make=lambda: LinearRegression().set_max_iter(20).set_global_batch_size(40)
            .set_learning_rate(0.05),
            inputs=lambda: [
                Table(
                    {
                        "features": np.arange(40, dtype=np.float64)[:, None] / 40.0,
                        "label": np.arange(40, dtype=np.float64) / 20.0,
                    }
                )
            ],
            setters={"maxIter": 3, "learningRate": 0.2},
            new_cols=["prediction"],
            check=lambda outs: None,  # convergence covered in test_linear_models
        ),
        # -- evaluation ---------------------------------------------------------
        StageSpec(
            name="BinaryClassificationEvaluator",
            make=lambda: BinaryClassificationEvaluator().set_metrics_names(
                "areaUnderROC", "areaUnderPR"
            ),
            inputs=lambda: [
                Table(
                    {
                        "label": [1.0, 1.0, 1.0, 0.0, 0.0],
                        "rawPrediction": [0.9, 0.8, 0.3, 0.6, 0.1],
                    }
                )
            ],
            setters={"weightCol": "w"},
            new_cols=["areaUnderROC", "areaUnderPR"],
            keeps_input_cols=False,
            check=lambda outs: (
                np.testing.assert_allclose(_col(outs, "areaUnderROC")[0], 5.0 / 6, atol=1e-9)
            ),
        ),
        # -- stats ----------------------------------------------------------------
        StageSpec(
            name="ChiSqTest",
            make=lambda: ChiSqTest().set_features_col("features").set_label_col("label"),
            inputs=lambda: [
                Table(
                    {
                        "features": np.random.RandomState(0)
                        .randint(0, 3, size=(60, 2))
                        .astype(np.float64),
                        "label": np.random.RandomState(1)
                        .randint(0, 2, size=60)
                        .astype(np.float64),
                    }
                )
            ],
            setters={"flatten": True},
            new_cols=["pValues", "degreesOfFreedom", "statistics"],
            keeps_input_cols=False,
            check=lambda outs: np.all(
                (np.asarray(_col(outs, "pValues")[0], dtype=np.float64) >= 0)
                & (np.asarray(_col(outs, "pValues")[0], dtype=np.float64) <= 1)
            )
            or None,
        ),
        StageSpec(
            name="ANOVATest",
            make=lambda: ANOVATest().set_features_col("features").set_label_col("label"),
            inputs=lambda: [_dense_table(seed=5, label_classes=3)],
            setters={"flatten": True},
            new_cols=["pValues", "degreesOfFreedom", "fValues"],
            keeps_input_cols=False,
            check=lambda outs: None,
        ),
        StageSpec(
            name="FValueTest",
            make=lambda: FValueTest().set_features_col("features").set_label_col("label"),
            inputs=lambda: [_dense_table(seed=6)],
            setters={"flatten": True},
            new_cols=["pValues", "degreesOfFreedom", "fValues"],
            keeps_input_cols=False,
            check=lambda outs: None,
        ),
        # -- feature: estimators ---------------------------------------------
        StageSpec(
            name="StandardScaler",
            make=lambda: StandardScaler(),
            inputs=lambda: [_vec_table()],
            setters={"withMean": True, "withStd": False},
            new_cols=["output"],
            check=lambda outs: np.testing.assert_allclose(
                np.std(_col(outs, "output"), axis=0, ddof=1) ** 2.0,
                np.ones(3),
                atol=1e-6,
            ),
        ),
        StageSpec(
            name="MinMaxScaler",
            make=lambda: MinMaxScaler(),
            inputs=lambda: [_vec_table()],
            setters={"min": -1.0, "max": 2.0},
            new_cols=["output"],
            check=lambda outs: (
                np.testing.assert_allclose(_col(outs, "output").min(axis=0), 0.0, atol=1e-9),
                np.testing.assert_allclose(_col(outs, "output").max(axis=0), 1.0, atol=1e-9),
            ),
        ),
        StageSpec(
            name="MaxAbsScaler",
            make=lambda: MaxAbsScaler(),
            inputs=lambda: [_vec_table()],
            setters={"inputCol": "i2", "outputCol": "o2"},
            new_cols=["output"],
            check=lambda outs: (
                # f32 device compute: scaled maxima equal 1 to f32 precision
                np.testing.assert_allclose(
                    np.abs(_col(outs, "output")).max(axis=0), 1.0, atol=1e-6
                )
            ),
        ),
        StageSpec(
            name="RobustScaler",
            make=lambda: RobustScaler(),
            inputs=lambda: [_vec_table()],
            setters={"lower": 0.1, "upper": 0.9, "withCentering": True,
                     "withScaling": False, "relativeError": 0.01},
            new_cols=["output"],
            check=lambda outs: None,
        ),
        StageSpec(
            name="Imputer",
            make=lambda: Imputer().set_input_cols("f1").set_output_cols("o1"),
            inputs=lambda: [Table({"f1": [1.0, float("nan"), 3.0]})],
            setters={"strategy": "median", "missingValue": -1.0, "relativeError": 0.01},
            new_cols=["o1"],
            check=lambda outs: np.testing.assert_allclose(
                _col(outs, "o1"), [1.0, 2.0, 3.0]
            ),
        ),
        StageSpec(
            name="StringIndexer",
            make=lambda: StringIndexer()
            .set_input_cols("input")
            .set_output_cols("output")
            .set_string_order_type("alphabetAsc"),
            inputs=lambda: [Table({"input": ["a", "b", "b", "c"]})],
            setters={"stringOrderType": "frequencyDesc", "handleInvalid": "skip"},
            new_cols=["output"],
            check=lambda outs: np.testing.assert_array_equal(
                _col(outs, "output"), [0.0, 1.0, 1.0, 2.0]
            ),
        ),
        StageSpec(
            name="IndexToStringModel",
            make=lambda: IndexToStringModel()
            .set_input_cols("idx")
            .set_output_cols("str")
            .set_model_data(
                *(
                    StringIndexer()
                    .set_input_cols("input")
                    .set_output_cols("output")
                    .set_string_order_type("alphabetAsc")
                    .fit(Table({"input": ["a", "b", "b", "c"]}))
                    .get_model_data()
                )
            ),
            inputs=lambda: [Table({"idx": [0.0, 2.0, 1.0]})],
            setters={"inputCols": ["i2"], "outputCols": ["s2"]},
            new_cols=["str"],
            check=lambda outs: list(outs[0].column("str")) == ["a", "c", "b"] or None,
        ),
        StageSpec(
            name="OneHotEncoder",
            make=lambda: OneHotEncoder().set_input_cols("input").set_output_cols("output"),
            inputs=lambda: [Table({"input": [0.0, 1.0, 2.0, 0.0]})],
            setters={"dropLast": False},
            new_cols=["output"],
            check=lambda outs: np.testing.assert_array_equal(
                outs[0].column("output").to_dense(),
                [[1, 0], [0, 1], [0, 0], [1, 0]],
            ),
        ),
        StageSpec(
            name="VectorIndexer",
            make=lambda: VectorIndexer().set_max_categories(3),
            inputs=lambda: [
                Table(
                    {
                        "input": [
                            Vectors.dense(1, 11),
                            Vectors.dense(2, 12),
                            Vectors.dense(1, 13),
                            Vectors.dense(2, 14),
                        ]
                    }
                )
            ],
            setters={"maxCategories": 5, "handleInvalid": "keep"},
            new_cols=["output"],
            check=lambda outs: np.testing.assert_array_equal(
                _col(outs, "output")[:, 0], [0, 1, 0, 1]
            ),
        ),
        StageSpec(
            name="CountVectorizer",
            make=lambda: CountVectorizer(),
            inputs=lambda: [_docs_table()],
            setters={"vocabularySize": 10, "minDF": 1.0, "minTF": 1.0, "binary": True},
            new_cols=["output"],
            check=lambda outs: None,
        ),
        StageSpec(
            name="IDF",
            make=lambda: IDF(),
            inputs=lambda: [
                Table(
                    {
                        "input": [
                            Vectors.dense(1, 2, 0),
                            Vectors.dense(1, 0, 3),
                            Vectors.dense(1, 4, 5),
                        ]
                    }
                )
            ],
            setters={"minDocFreq": 2},
            new_cols=["output"],
            check=lambda outs: np.testing.assert_allclose(
                _col(outs, "output")[:, 0], 0.0, atol=1e-9
            ),
        ),
        StageSpec(
            name="KBinsDiscretizer",
            make=lambda: KBinsDiscretizer().set_strategy("uniform").set_num_bins(5),
            inputs=lambda: [Table({"input": np.asarray([[0.0], [1.0], [2.0], [10.0]])})],
            setters={"strategy": "quantile", "numBins": 3, "subSamples": 100},
            new_cols=["output"],
            check=lambda outs: np.testing.assert_array_equal(
                _col(outs, "output")[:, 0], [0, 0, 1, 4]
            ),
        ),
        StageSpec(
            name="VarianceThresholdSelector",
            make=lambda: VarianceThresholdSelector(),
            inputs=lambda: [
                Table({"input": np.asarray([[1.0, 5.0, 0.0], [2.0, 5.0, 0.0], [3.0, 5.0, 0.0]])})
            ],
            setters={"varianceThreshold": 2.0},
            new_cols=["output"],
            check=lambda outs: np.testing.assert_array_equal(
                _col(outs, "output"), [[1], [2], [3]]
            ),
        ),
        StageSpec(
            name="UnivariateFeatureSelector",
            make=lambda: UnivariateFeatureSelector()
            .set_feature_type("continuous")
            .set_label_type("categorical")
            .set_selection_threshold(1),
            inputs=lambda: [_informative_table()],
            setters={"selectionMode": "fpr", "selectionThreshold": 0.1},
            new_cols=["output"],
            check=lambda outs: assert_shape(_col(outs, "output"), (100, 1)),
        ),
        StageSpec(
            name="MinHashLSH",
            make=lambda: MinHashLSH()
            .set_input_col("vec")
            .set_output_col("hashes")
            .set_num_hash_tables(5)
            .set_seed(2022),
            inputs=lambda: [_sparse_table()],
            setters={"numHashTables": 3, "numHashFunctionsPerTable": 2, "seed": 7},
            new_cols=["hashes"],
            check=lambda outs: None,
        ),
        # -- feature: transformers ---------------------------------------------
        StageSpec(
            name="Binarizer",
            make=lambda: Binarizer()
            .set_input_cols("f0")
            .set_output_cols("o0")
            .set_thresholds(1.5),
            inputs=lambda: [Table({"f0": [1.0, 2.0, 3.0]})],
            setters={},
            new_cols=["o0"],
            check=lambda outs: np.testing.assert_array_equal(_col(outs, "o0"), [0.0, 1.0, 1.0]),
        ),
        StageSpec(
            name="Bucketizer",
            make=lambda: Bucketizer()
            .set_input_cols("f1")
            .set_output_cols("o1")
            .set_splits_array([[-0.5, 0.0, 0.5]]),
            inputs=lambda: [Table({"f1": [-0.5, 0.2]})],
            setters={"handleInvalid": "skip"},
            new_cols=["o1"],
            check=lambda outs: np.testing.assert_array_equal(_col(outs, "o1"), [0, 1]),
        ),
        StageSpec(
            name="DCT",
            make=lambda: DCT().set_input_col("vec").set_output_col("o"),
            inputs=lambda: [Table({"vec": [Vectors.dense(1, 1, 1, 1)]})],
            setters={"inverse": True},
            new_cols=["o"],
            check=lambda outs: np.testing.assert_allclose(
                _col(outs, "o")[0], [2, 0, 0, 0], atol=1e-6
            ),
        ),
        StageSpec(
            name="ElementwiseProduct",
            make=lambda: ElementwiseProduct()
            .set_input_col("vec")
            .set_output_col("o")
            .set_scaling_vec(Vectors.dense(1.1, 1.1)),
            inputs=lambda: [Table({"vec": [Vectors.dense(2.1, 3.1)]})],
            setters={},
            new_cols=["o"],
            check=_check_column_close("o", [[2.31, 3.41]]),
        ),
        StageSpec(
            name="FeatureHasher",
            make=lambda: FeatureHasher()
            .set_input_cols("f1")
            .set_num_features(1000),
            inputs=lambda: [Table({"f1": [1.0, 2.0]})],
            setters={"numFeatures": 512},
            new_cols=["output"],
            check=lambda outs: None,
        ),
        StageSpec(
            name="HashingTF",
            make=lambda: HashingTF(),
            inputs=lambda: [
                Table({"input": [["HashingTFTest", "Hashing", "Term", "Frequency", "Test"]]})
            ],
            setters={"binary": True, "numFeatures": 1024},
            new_cols=["output"],
            check=lambda outs: np.testing.assert_array_equal(
                outs[0].column("output").row(0).indices,
                [67564, 89917, 113827, 131486, 228971],
            ),
        ),
        StageSpec(
            name="Interaction",
            make=lambda: Interaction().set_input_cols("f0", "vec1").set_output_col("o"),
            inputs=lambda: [
                Table({"f0": [1.0, 2.0], "vec1": [Vectors.dense(1, 2), Vectors.dense(2, 8)]})
            ],
            setters={},
            new_cols=["o"],
            check=_check_column_close("o", [[1, 2], [4, 16]], atol=1e-9),
        ),
        StageSpec(
            name="NGram",
            make=lambda: NGram().set_input_col("input").set_output_col("o"),
            inputs=lambda: [Table({"input": [["a", "b", "c"]]})],
            setters={"n": 3},
            new_cols=["o"],
            check=lambda outs: list(outs[0].column("o"))[0] == ["a b", "b c"] or None,
        ),
        StageSpec(
            name="Normalizer",
            make=lambda: Normalizer().set_input_col("vec").set_output_col("o"),
            inputs=lambda: [Table({"vec": [Vectors.dense(3, 4)]})],
            setters={"p": 1.0},
            new_cols=["o"],
            check=_check_column_close("o", [[0.6, 0.8]]),
        ),
        StageSpec(
            name="PolynomialExpansion",
            make=lambda: PolynomialExpansion().set_input_col("vec").set_output_col("o"),
            inputs=lambda: [Table({"vec": [Vectors.dense(1, 2, 3)]})],
            setters={"degree": 3},
            new_cols=["o"],
            check=_check_column_close("o", [[1, 1, 2, 2, 4, 3, 3, 6, 9]], atol=1e-9),
        ),
        StageSpec(
            name="RandomSplitter",
            make=lambda: RandomSplitter().set_weights(1.0, 1.0).set_seed(42),
            inputs=lambda: [Table({"f": np.arange(100, dtype=np.float64)})],
            setters={"seed": 7},
            new_cols=[],
            keeps_input_cols=False,
            check=lambda outs: (outs[0].num_rows + outs[1].num_rows == 100) or None,
        ),
        StageSpec(
            name="RegexTokenizer",
            make=lambda: RegexTokenizer()
            .set_input_col("input")
            .set_output_col("o")
            .set_pattern(r"\w+")
            .set_gaps(False),
            inputs=lambda: [_strings_table()],
            setters={"minTokenLength": 2, "toLowercase": False},
            new_cols=["o"],
            check=lambda outs: list(outs[0].column("o"))[0] == ["test", "for", "tokenization"]
            or None,
        ),
        StageSpec(
            name="SQLTransformer",
            make=lambda: SQLTransformer().set_statement(
                "SELECT *, (v1 + v2) AS v3 FROM __THIS__"
            ),
            inputs=lambda: [Table({"v1": [1.0, 2.0], "v2": [3.0, 4.0]})],
            setters={},
            new_cols=["v3"],
            check=_check_column_close("v3", [4.0, 6.0], atol=1e-9),
        ),
        StageSpec(
            name="StopWordsRemover",
            make=lambda: StopWordsRemover().set_input_cols("raw").set_output_cols("filtered"),
            inputs=lambda: [Table({"raw": [["I", "saw", "the", "red", "balloon"]]})],
            setters={"caseSensitive": True, "locale": "en_US"},
            new_cols=["filtered"],
            check=lambda outs: list(outs[0].column("filtered"))[0] == ["saw", "red", "balloon"]
            or None,
        ),
        StageSpec(
            name="Tokenizer",
            make=lambda: Tokenizer().set_input_col("input").set_output_col("o"),
            inputs=lambda: [_strings_table()],
            setters={},
            new_cols=["o"],
            check=lambda outs: list(outs[0].column("o"))[0] == ["test", "for", "tokenization."]
            or None,
        ),
        StageSpec(
            name="VectorAssembler",
            make=lambda: VectorAssembler().set_input_cols("f0", "vec").set_output_col("o"),
            inputs=lambda: [
                Table({"f0": [1.0, 2.0], "vec": [Vectors.dense(2, 3), Vectors.dense(4, 5)]})
            ],
            setters={"handleInvalid": "skip"},
            new_cols=["o"],
            check=lambda outs: np.testing.assert_array_equal(
                _col(outs, "o"), [[1, 2, 3], [2, 4, 5]]
            ),
        ),
        StageSpec(
            name="VectorSlicer",
            make=lambda: VectorSlicer().set_input_col("vec").set_output_col("o").set_indices(0, 2),
            inputs=lambda: [Table({"vec": [Vectors.dense(2.1, 3.1, 1.2)]})],
            setters={},
            new_cols=["o"],
            check=_check_column_close("o", [[2.1, 1.2]]),
        ),
    ]
    return specs


def _informative_table():
    rng = np.random.RandomState(0)
    y = np.repeat([0.0, 1.0], 50)
    X = rng.randn(100, 4)
    X[:, 2] += y * 5
    return Table({"features": X, "label": y})


def assert_shape(arr, shape):
    assert np.asarray(arr).shape == shape


_SPECS = _specs()
_IDS = [s.name for s in _SPECS]

# coverage guard: every stage module in flink_ml_tpu/models must appear here
_EXPECTED_STAGES = 38


def test_battery_covers_every_stage():
    import pathlib

    root = pathlib.Path(__file__).resolve().parent.parent / "flink_ml_tpu" / "models"
    modules = [
        p.stem
        for p in root.rglob("*.py")
        if not p.stem.startswith("_")
    ]
    assert len(modules) >= _EXPECTED_STAGES - 2  # stringindexer hosts 2 stages etc.
    covered = {s.name.lower() for s in _SPECS}
    missing = []
    for m in modules:
        if m in ("onlinelogisticregression", "onlinekmeans"):
            target = m
        else:
            target = m
        if not any(target.replace("_", "") in c or c in target for c in covered):
            missing.append(m)
    # lsh hosts MinHashLSH; binaryclassification hosts the evaluator
    allowed = {"lsh", "binaryclassification", "stopwords"}
    assert set(missing) <= allowed, f"stages missing from battery: {missing}"
    assert len(_SPECS) >= _EXPECTED_STAGES


@pytest.fixture(params=_SPECS, ids=_IDS)
def spec(request) -> StageSpec:
    return request.param


class TestStageBattery:
    def test_param_defaults_and_setters(self, spec):
        stage = type(spec.make())()
        # 1a: every param reports its declared default (NaN-aware: e.g.
        # Imputer.missingValue defaults to NaN)
        for param, value in stage.get_param_map().items():
            default = param.default_value
            both_nan = (
                isinstance(value, float)
                and isinstance(default, float)
                and np.isnan(value)
                and np.isnan(default)
            )
            assert value == default or (value is None and default is None) or both_nan
        # 1b: every spec-provided setter value round-trips through set/get
        for name, value in spec.setters.items():
            param = stage.get_param(name)
            assert param is not None, f"{spec.name} has no param {name}"
            stage.set(param, value)
            got = stage.get(param)
            if isinstance(value, (list, tuple, np.ndarray)):
                assert list(np.ravel(np.asarray(got, dtype=object))) == list(
                    np.ravel(np.asarray(value, dtype=object))
                ) or got == value
            else:
                assert got == value
        # 1c: unknown params are rejected
        from flink_ml_tpu.param import IntParam

        with pytest.raises(ValueError):
            stage.set(IntParam("doesNotExist", "", 1), 2)

    def test_output_schema(self, spec):
        _, outputs = run_stage(spec)
        assert len(outputs) >= 1
        out_cols = set(outputs[0].column_names)
        for col in spec.new_cols:
            assert col in out_cols, f"{spec.name} output missing column {col}"
        if spec.keeps_input_cols:
            t_in = (spec.transform_inputs or spec.inputs)()
            for col in t_in[0].column_names:
                assert col in out_cols, f"{spec.name} dropped input column {col}"

    def test_fit_transform_behavior(self, spec):
        _, outputs = run_stage(spec)
        spec.check(outputs)

    def test_save_load_predict(self, spec, tmp_path):
        stage, outputs = run_stage(spec)
        path = str(tmp_path / spec.name)
        stage.save(path)
        loaded = type(stage).load(path)
        t_in = (spec.transform_inputs or spec.inputs)()
        if spec.name == "IndexToStringModel":
            return  # covered by its own round-trip below (derived model)
        reloaded_outputs = loaded.transform(*t_in)
        assert_tables_equal(reloaded_outputs, outputs)

    def test_model_data_roundtrip(self, spec):
        stage = spec.make()
        fit_in = spec.inputs()
        t_in = (spec.transform_inputs or spec.inputs)()
        if isinstance(stage, Estimator):
            model = stage.fit(*fit_in)
            if spec.post_fit is not None:
                spec.post_fit(model)
        elif isinstance(stage, Model):
            model = stage
        else:
            # stateless by design: the contract is type-level
            assert not isinstance(stage, Model)
            assert not hasattr(stage, "fit")
            return
        try:
            model_data = model.get_model_data()
        except NotImplementedError:
            pytest.fail(f"{spec.name} model does not expose get_model_data")
        fresh = type(model)()
        fresh.set_model_data(*model_data)
        from flink_ml_tpu.utils.param_utils import update_existing_params

        update_existing_params(fresh, model)
        got = fresh.transform(*t_in)
        want = model.transform(*t_in)
        assert_tables_equal(got, want)
