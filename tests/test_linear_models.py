"""LinearRegression + LinearSVC batteries — mirror
flink-ml-lib/src/test/java/org/apache/flink/ml/regression/LinearRegressionTest.java
and .../classification/LinearSVCTest.java: params, fit+transform, save/load,
get/set model data."""

import numpy as np
import pytest

from flink_ml_tpu.linalg import Vectors
from flink_ml_tpu.models.classification.linearsvc import LinearSVC, LinearSVCModel
from flink_ml_tpu.models.regression.linearregression import (
    LinearRegression,
    LinearRegressionModel,
)
from flink_ml_tpu.table import Table

# LinearRegressionTest.java trainData: label = 1*f0 + 2*f1 + 3.
REG_FEATURES = [
    Vectors.dense(2, 1),
    Vectors.dense(3, 2),
    Vectors.dense(4, 3),
    Vectors.dense(2, 4),
    Vectors.dense(2, 2),
    Vectors.dense(4, 3),
    Vectors.dense(1, 2),
    Vectors.dense(5, 3),
]
REG_LABELS = [4.0, 7.0, 10.0, 10.0, 6.0, 10.0, 5.0, 11.0]

SVC_FEATURES = [
    Vectors.dense(1, 2, 3, 4),
    Vectors.dense(2, 2, 3, 4),
    Vectors.dense(3, 2, 3, 4),
    Vectors.dense(4, 2, 3, 4),
    Vectors.dense(5, 2, 3, 4),
    Vectors.dense(11, 2, 3, 4),
    Vectors.dense(12, 2, 3, 4),
    Vectors.dense(13, 2, 3, 4),
    Vectors.dense(14, 2, 3, 4),
    Vectors.dense(15, 2, 3, 4),
]
SVC_LABELS = [0.0, 0.0, 0.0, 0.0, 0.0, 1.0, 1.0, 1.0, 1.0, 1.0]


def _reg_table():
    return Table({"features": REG_FEATURES, "label": REG_LABELS, "weight": [1.0] * 8})


def _svc_table():
    return Table({"features": SVC_FEATURES, "label": SVC_LABELS})


class TestLinearRegression:
    def test_param_defaults(self):
        lr = LinearRegression()
        assert lr.get_label_col() == "label"
        assert lr.get_weight_col() is None
        assert lr.get_max_iter() == 20
        assert lr.get_reg() == 0.0
        assert lr.get_elastic_net() == 0.0
        assert lr.get_learning_rate() == 0.1
        assert lr.get_global_batch_size() == 32
        assert lr.get_tol() == 1e-6
        assert lr.get_prediction_col() == "prediction"

    def test_fit_and_predict(self):
        lr = LinearRegression().set_weight_col("weight").set_max_iter(300).set_learning_rate(0.01)
        model = lr.fit(_reg_table())
        out = model.transform(_reg_table())[0]
        pred = np.asarray(out.column("prediction"))
        # The reference test allows loose tolerance (predictions near labels).
        np.testing.assert_allclose(pred, REG_LABELS, rtol=0.3)

    def test_save_load(self, tmp_path):
        model = LinearRegression().set_max_iter(50).set_learning_rate(0.01).fit(_reg_table())
        path = str(tmp_path / "linreg")
        model.save(path)
        loaded = LinearRegressionModel.load(path)
        np.testing.assert_allclose(loaded.coefficient, model.coefficient)
        out1 = np.asarray(model.transform(_reg_table())[0].column("prediction"))
        out2 = np.asarray(loaded.transform(_reg_table())[0].column("prediction"))
        np.testing.assert_allclose(out1, out2)

    def test_get_set_model_data(self):
        model = LinearRegression().set_max_iter(20).set_learning_rate(0.01).fit(_reg_table())
        other = LinearRegressionModel().set_model_data(model.get_model_data()[0])
        np.testing.assert_allclose(other.coefficient, model.coefficient)

    def test_distributed(self, mesh8):
        model = LinearRegression().set_max_iter(20).set_learning_rate(0.01).fit(_reg_table())
        assert model.coefficient.shape == (2,)
        assert np.all(np.isfinite(model.coefficient))


class TestLinearSVC:
    def test_param_defaults(self):
        svc = LinearSVC()
        assert svc.get_threshold() == 0.0
        assert svc.get_max_iter() == 20
        assert svc.get_raw_prediction_col() == "rawPrediction"

    def test_fit_and_predict(self):
        model = LinearSVC().set_max_iter(100).fit(_svc_table())
        out = model.transform(_svc_table())[0]
        pred = np.asarray(out.column("prediction"))
        np.testing.assert_array_equal(pred, SVC_LABELS)
        raw = np.asarray(out.column("rawPrediction"))
        assert raw.shape == (10, 2)
        # rawPrediction = [dot, -dot] (LinearSVCModel.java:173)
        np.testing.assert_allclose(raw[:, 0], -raw[:, 1], atol=1e-6)
        assert np.all((raw[:, 0] >= 0.0) == (pred == 1.0))

    def test_threshold(self):
        model = LinearSVC().set_max_iter(100).fit(_svc_table())
        model.set_threshold(1e9)
        out = model.transform(_svc_table())[0]
        np.testing.assert_array_equal(np.asarray(out.column("prediction")), np.zeros(10))

    def test_rejects_non_binomial_labels(self):
        t = Table({"features": SVC_FEATURES, "label": [float(i) for i in range(10)]})
        with pytest.raises(ValueError):
            LinearSVC().fit(t)

    def test_save_load(self, tmp_path):
        model = LinearSVC().set_max_iter(30).fit(_svc_table())
        path = str(tmp_path / "svc")
        model.save(path)
        loaded = LinearSVCModel.load(path)
        np.testing.assert_allclose(loaded.coefficient, model.coefficient)

    def test_get_set_model_data(self):
        model = LinearSVC().set_max_iter(30).fit(_svc_table())
        other = LinearSVCModel().set_model_data(model.get_model_data()[0])
        np.testing.assert_allclose(other.coefficient, model.coefficient)


class TestFlatTrainPath:
    """The single-data-shard fast path (`_sgd_train_flat`) must produce the
    same coefficients as the batched multi-shard layout (`_sgd_train`) for
    every padding/weight configuration."""

    def _run(self, mesh, n, with_weights, batch=16):
        import jax

        from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
        from flink_ml_tpu.ops.optimizer import SGD

        rng = np.random.default_rng(3)
        X = rng.random((n, 5), dtype=np.float32)
        y = (X @ np.arange(1, 6, dtype=np.float32) > 7.5).astype(np.float32)
        w = rng.random(n, dtype=np.float32) if with_weights else None
        sgd = SGD(max_iter=7, learning_rate=0.05, global_batch_size=batch, tol=0.0)
        coeff, loss, epochs = sgd.optimize(
            np.zeros(5, np.float32), X, y, w, BINARY_LOGISTIC_LOSS, mesh=mesh
        )
        assert epochs == 7
        return np.asarray(coeff), loss

    @pytest.mark.parametrize("with_weights", [False, True])
    @pytest.mark.parametrize("n", [64, 50, 10])  # even, ragged, n < batch
    def test_matches_batched_layout(self, n, with_weights):
        from flink_ml_tpu.parallel import mesh as mesh_lib

        mesh1 = mesh_lib.create_mesh(("data",), devices=jax_devices()[:1])
        coeff_flat, loss_flat = self._run(mesh1, n, with_weights)
        mesh8 = mesh_lib.create_mesh(("data",))
        coeff_sharded, loss_sharded = self._run(mesh8, n, with_weights)
        np.testing.assert_allclose(coeff_flat, coeff_sharded, rtol=2e-5, atol=2e-6)
        assert abs(loss_flat - loss_sharded) < 1e-5


def jax_devices():
    import jax

    return jax.devices()


class TestDeviceLabelValidation:
    """Device-resident labels take the fused-flag path (run_sgd packs the
    validity flag into the training result readback) — both outcomes must
    behave identically to the host-label eager validation."""

    def _table(self, labels):
        import jax.numpy as jnp

        from flink_ml_tpu.table import Table

        n = len(labels)
        rng = np.random.default_rng(1)
        return Table(
            {
                "features": jnp.asarray(rng.random((n, 4), dtype=np.float32)),
                "label": jnp.asarray(np.asarray(labels, np.float32)),
            }
        )

    def test_rejects_non_binomial_device_labels(self):
        from flink_ml_tpu.models.classification.logisticregression import (
            LogisticRegression,
        )

        with pytest.raises(ValueError, match="binomial"):
            LogisticRegression().set_max_iter(2).fit(self._table([0.0, 1.0, 2.0, 1.0]))

    def test_accepts_binomial_device_labels(self):
        from flink_ml_tpu.models.classification.logisticregression import (
            LogisticRegression,
        )

        model = LogisticRegression().set_max_iter(3).fit(
            self._table([0.0, 1.0, 0.0, 1.0])
        )
        assert model.coefficient.shape == (4,)
