"""Tier-1 gate for scripts/check_fusion_coverage.py: every concrete stage
must either expose a transform kernel or explicitly opt out of fusion with
a reason — a new stage cannot silently regress fusion coverage."""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_fusion_coverage",
        os.path.join(REPO, "scripts", "check_fusion_coverage.py"),
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_every_stage_declares_fusion_contract():
    checker = _load_checker()
    violations = checker.find_violations()
    assert not violations, "stages violating the fusion contract:\n" + "\n".join(
        f"  {name}: {problem}" for name, problem in violations
    )


def test_fusable_stages_are_nontrivial():
    # the protocol is real: a healthy fraction of the stage population runs
    # on the fused path (guards against mass opt-outs gaming the gate)
    checker = _load_checker()
    from flink_ml_tpu.api import AlgoOperator

    classes = list(checker._iter_stage_classes())
    with_kernel = [
        c for c in classes if c.transform_kernel is not AlgoOperator.transform_kernel
    ]
    assert len(with_kernel) >= 20, (
        f"only {len(with_kernel)} stages expose transform_kernel; "
        "the fusion protocol should cover the high-traffic device stages"
    )
