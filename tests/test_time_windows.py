"""Event-/processing-time window semantics (VERDICT r4 missing #2).

The reference windows on event/processing time throughout
(common/window/EventTimeTumblingWindows.java, consumed via HasWindows in
AgglomerativeClustering.java). Bounded analogue here: event-time windows
read each record's event time (ms) from a 'timestamp' column; processing-
time windows stamp batch arrival with an injectable clock."""

import numpy as np
import pytest

from flink_ml_tpu.common.window import (
    CountTumblingWindows,
    EventTimeSessionWindows,
    EventTimeTumblingWindows,
    ProcessingTimeSessionWindows,
    ProcessingTimeTumblingWindows,
)
from flink_ml_tpu.models.clustering.agglomerativeclustering import (
    AgglomerativeClustering,
)
from flink_ml_tpu.table import StreamTable, Table
from flink_ml_tpu.utils.datastream import (
    event_time_window_groups,
    window_all_and_process,
)


class TestEventTimeGroups:
    def test_tumbling_assignment_epoch_aligned(self):
        ts = np.array([0, 5, 10, 14, 20, 999])
        groups = event_time_window_groups(ts, EventTimeTumblingWindows.of(10))
        assert [g.tolist() for g in groups] == [[0, 1], [2, 3], [4], [5]][:3] + [[5]]

    def test_tumbling_negative_timestamps(self):
        # floor alignment: t=-1 belongs to window [-10, 0)
        ts = np.array([-1, -10, 1])
        groups = event_time_window_groups(ts, EventTimeTumblingWindows.of(10))
        assert [sorted(g.tolist()) for g in groups] == [[0, 1], [2]]

    def test_session_gap_merging(self):
        ts = np.array([0, 50, 300, 320, 1000])
        groups = event_time_window_groups(ts, EventTimeSessionWindows.with_gap(100))
        assert [g.tolist() for g in groups] == [[0, 1], [2, 3], [4]]

    def test_unsorted_input_rows(self):
        ts = np.array([320, 0, 1000, 50, 300])
        groups = event_time_window_groups(ts, EventTimeSessionWindows.with_gap(100))
        assert [sorted(g.tolist()) for g in groups] == [[1, 3], [0, 4], [2]]


class TestWindowAllAndProcess:
    def _table(self):
        return Table(
            {
                "x": np.arange(6, dtype=np.float64),
                "timestamp": np.array([0, 5, 10, 15, 20, 25]),
            }
        )

    def test_event_tumbling_window_size_changes_output(self):
        counts = lambda w: Table({"n": np.array([w.num_rows])})
        out10 = window_all_and_process(self._table(), EventTimeTumblingWindows.of(10), counts)
        out30 = window_all_and_process(self._table(), EventTimeTumblingWindows.of(30), counts)
        assert np.asarray(out10.column("n")).tolist() == [2, 2, 2]
        assert np.asarray(out30.column("n")).tolist() == [6]

    def test_event_windows_require_timestamp_column(self):
        with pytest.raises(ValueError, match="timestamp"):
            window_all_and_process(
                Table({"x": np.arange(3)}), EventTimeTumblingWindows.of(10), lambda w: w
            )

    def test_event_session_on_stream(self):
        batches = [
            Table({"x": np.array([1.0]), "timestamp": np.array([0])}),
            Table({"x": np.array([2.0]), "timestamp": np.array([5])}),
            Table({"x": np.array([3.0]), "timestamp": np.array([500])}),
        ]
        out = window_all_and_process(
            StreamTable.from_batches(batches),
            EventTimeSessionWindows.with_gap(100),
            lambda w: Table({"n": np.array([w.num_rows])}),
        )
        assert [int(np.asarray(t.column("n"))[0]) for t in out] == [2, 1]

    def test_processing_tumbling_with_fake_clock(self):
        # batches arrive at t=0.0, 0.1, 5.0, 5.1 -> two windows of two batches
        times = iter([0.0, 0.1, 5.0, 5.1])
        batches = [Table({"x": np.array([float(i)])}) for i in range(4)]
        out = window_all_and_process(
            StreamTable.from_batches(batches),
            ProcessingTimeTumblingWindows.of(1000),
            lambda w: Table({"n": np.array([w.num_rows])}),
            clock=lambda: next(times),
        )
        assert [int(np.asarray(t.column("n"))[0]) for t in out] == [2, 2]

    def test_processing_session_with_fake_clock(self):
        times = iter([0.0, 0.05, 10.0])
        batches = [Table({"x": np.array([float(i)])}) for i in range(3)]
        out = window_all_and_process(
            StreamTable.from_batches(batches),
            ProcessingTimeSessionWindows.with_gap(1000),
            lambda w: Table({"n": np.array([w.num_rows])}),
            clock=lambda: next(times),
        )
        assert [int(np.asarray(t.column("n"))[0]) for t in out] == [2, 1]

    def test_processing_time_bounded_table_is_one_window(self):
        out = window_all_and_process(
            Table({"x": np.arange(4, dtype=np.float64)}),
            ProcessingTimeTumblingWindows.of(10),
            lambda w: Table({"n": np.array([w.num_rows])}),
        )
        assert np.asarray(out.column("n")).tolist() == [4]


class TestAgglomerativeTimeWindows:
    """Changing the time window must change the clustering output —
    reference semantics: each window clusters LOCALLY."""

    def _table(self):
        rng = np.random.RandomState(0)
        # 3 time groups of 4 rows; rows within a group are two tight pairs
        X = rng.rand(12, 2) * 0.01
        X[::2] += 5.0  # every other row in a far blob
        ts = np.repeat([0, 1000, 2000], 4)
        return Table({"features": X, "timestamp": ts})

    def test_event_tumbling_size_changes_prediction(self):
        op = AgglomerativeClustering().set_num_clusters(2)
        small = op.set_windows(EventTimeTumblingWindows.of(500))
        out_small, merges_small = small.transform(self._table())
        # 3 windows x 4 rows, each clustered locally into 2 clusters
        assert merges_small.num_rows == 3 * 2
        big = op.set_windows(EventTimeTumblingWindows.of(5000))
        out_big, merges_big = big.transform(self._table())
        assert merges_big.num_rows == 10  # one window of 12 rows -> 10 merges
        assert merges_small.num_rows != merges_big.num_rows

    def test_event_session_windows(self):
        op = (
            AgglomerativeClustering()
            .set_num_clusters(2)
            .set_windows(EventTimeSessionWindows.with_gap(500))
        )
        _, merges = op.transform(self._table())
        assert merges.num_rows == 3 * 2  # gaps of 1000ms split 3 sessions

    def test_event_windows_need_timestamp(self):
        op = AgglomerativeClustering().set_windows(EventTimeTumblingWindows.of(10))
        with pytest.raises(ValueError, match="timestamp"):
            op.transform(Table({"features": np.random.rand(4, 2)}))

    def test_processing_time_bounded_degenerates_to_global(self):
        op = AgglomerativeClustering().set_num_clusters(2).set_windows(
            ProcessingTimeTumblingWindows.of(1000)
        )
        out, merges = op.transform(self._table())
        assert merges.num_rows == 10
        assert len(set(np.asarray(out.column("prediction")).tolist())) == 2

    def test_unsorted_timestamps_keep_rows_aligned(self):
        """Interleaved timestamps make kept_rows a full-cover PERMUTATION;
        predictions and merge-log row ids must follow the reordered output
        rows, not the input order (review finding: a length-only check
        skipped the reorder)."""
        X = np.array([[100.0, 100.0], [0.0, 0.0], [101.0, 101.0], [1.0, 1.0]])
        ts = np.array([1000, 0, 1000, 0])
        out, merges = (
            AgglomerativeClustering()
            .set_num_clusters(1)
            .set_windows(EventTimeTumblingWindows.of(500))
            .transform(Table({"features": X, "timestamp": ts}))
        )
        feats = np.asarray(out.column("features"))
        # output rows come in window order: ts=0 rows first
        np.testing.assert_array_equal(feats[:2], X[[1, 3]])
        # each window's single merge joins that window's two OUTPUT rows
        ids = set()
        for r in range(merges.num_rows):
            ids.add((int(merges.collect()[r]["clusterId1"]),
                     int(merges.collect()[r]["clusterId2"])))
        assert ids == {(0, 1), (2, 3)}
        # merged pairs really are the near rows (distance ~1.4, not ~141)
        dists = [float(row["distance"]) for row in merges.collect()]
        assert all(d < 5.0 for d in dists), dists

    def test_count_windows_unchanged(self):
        op = AgglomerativeClustering().set_num_clusters(2).set_windows(
            CountTumblingWindows.of(5)
        )
        out, merges = op.transform(self._table())
        # 12 rows -> 2 full windows of 5, tail of 2 dropped
        assert out.num_rows == 10
        assert merges.num_rows == 2 * 3