"""Stateless feature-transformer battery — golden values mirror the
reference tests under flink-ml-lib/src/test/java/org/apache/flink/ml/feature/
(BinarizerTest, BucketizerTest, NormalizerTest, ElementwiseProductTest,
PolynomialExpansionTest, InteractionTest, DCTTest, VectorAssemblerTest,
VectorSlicerTest, HashingTFTest, TokenizerTest, RegexTokenizerTest,
NGramTest, StopWordsRemoverTest, RandomSplitterTest)."""

import numpy as np
import pytest

from flink_ml_tpu.linalg import Vectors
from flink_ml_tpu.table import Table
from flink_ml_tpu.models.feature.binarizer import Binarizer
from flink_ml_tpu.models.feature.bucketizer import Bucketizer
from flink_ml_tpu.models.feature.dct import DCT
from flink_ml_tpu.models.feature.elementwiseproduct import ElementwiseProduct
from flink_ml_tpu.models.feature.hashingtf import HashingTF
from flink_ml_tpu.models.feature.interaction import Interaction
from flink_ml_tpu.models.feature.ngram import NGram
from flink_ml_tpu.models.feature.normalizer import Normalizer
from flink_ml_tpu.models.feature.polynomialexpansion import PolynomialExpansion
from flink_ml_tpu.models.feature.randomsplitter import RandomSplitter
from flink_ml_tpu.models.feature.regextokenizer import RegexTokenizer
from flink_ml_tpu.models.feature.stopwordsremover import StopWordsRemover
from flink_ml_tpu.models.feature.tokenizer import Tokenizer
from flink_ml_tpu.models.feature.vectorassembler import VectorAssembler
from flink_ml_tpu.models.feature.vectorslicer import VectorSlicer


class TestBinarizer:
    def test_transform(self):
        t = Table({"f0": [1.0, 2.0, 3.0], "v": [Vectors.dense(1, 2), Vectors.dense(2, 1), Vectors.dense(0, 0)]})
        out = Binarizer().set_input_cols("f0", "v").set_output_cols("o0", "ov").set_thresholds(1.5, 1.0).transform(t)[0]
        np.testing.assert_array_equal(np.asarray(out.column("o0")), [0.0, 1.0, 1.0])
        np.testing.assert_array_equal(np.asarray(out.column("ov")), [[0, 1], [1, 0], [0, 0]])

    def test_save_load(self, tmp_path):
        b = Binarizer().set_input_cols("a").set_output_cols("b").set_thresholds(0.5)
        b.save(str(tmp_path / "bin"))
        loaded = Binarizer.load(str(tmp_path / "bin"))
        assert loaded.get_thresholds() == [0.5]


class TestBucketizer:
    # BucketizerTest.java inputData/splitsArray
    SPLITS = [
        [-0.5, 0.0, 0.5],
        [-1.0, 0.0, 2.0],
        [float("-inf"), 10.0, float("inf")],
        [float("-inf"), 1.5, float("inf")],
    ]

    def _table(self):
        return Table(
            {
                "f1": [-0.5, float("-inf"), float("nan")],
                "f2": [0.0, 1.0, -0.5],
                "f3": [1.0, float("inf"), -0.5],
                "f4": [0.0, 1.0, 2.0],
            }
        )

    def _op(self, handle):
        return (
            Bucketizer()
            .set_input_cols("f1", "f2", "f3", "f4")
            .set_output_cols("o1", "o2", "o3", "o4")
            .set_splits_array(self.SPLITS)
            .set_handle_invalid(handle)
        )

    def test_keep(self):
        out = self._op("keep").transform(self._table())[0]
        np.testing.assert_array_equal(np.asarray(out.column("o1")), [0, 2, 2])
        np.testing.assert_array_equal(np.asarray(out.column("o2")), [1, 1, 0])
        np.testing.assert_array_equal(np.asarray(out.column("o3")), [0, 1, 0])
        np.testing.assert_array_equal(np.asarray(out.column("o4")), [0, 0, 1])

    def test_skip(self):
        out = self._op("skip").transform(self._table())[0]
        assert out.num_rows == 1  # only the first row is fully valid

    def test_error(self):
        with pytest.raises(ValueError):
            self._op("error").transform(self._table())

    def test_invalid_splits(self):
        with pytest.raises(ValueError):
            Bucketizer().set_splits_array([[0.0, 1.0]])

    def test_device_inexact_splits_match_host(self):
        """Splits that do not survive the float32 cast (e.g. 1 + 1e-10)
        would move boundary values into the wrong bucket on device — the
        column must fall back to the exact host path and bucket identically
        to a host column."""
        import jax

        boundary = 1.0 + 1e-10  # float32 rounds this down to exactly 1.0
        splits = [[0.0, boundary, 2.0]]
        values = np.asarray([0.5, 1.0, 1.5], np.float32)
        op = (
            Bucketizer()
            .set_input_cols("x")
            .set_output_cols("o")
            .set_splits_array(splits)
            .set_handle_invalid("keep")
        )
        host = op.transform(Table({"x": values.astype(np.float64)}))[0]
        dev = op.transform(Table({"x": jax.device_put(values)}))[0]
        # 1.0 < 1.0000000001 → bucket 0 (the f32 device compare would say 1)
        np.testing.assert_array_equal(np.asarray(host.column("o")), [0, 0, 1])
        np.testing.assert_array_equal(
            np.asarray(dev.column("o")), np.asarray(host.column("o"))
        )

    def test_device_exact_splits_stay_on_device(self):
        import jax

        values = jax.device_put(np.asarray([0.5, 1.0, 1.5], np.float32))
        out = (
            Bucketizer()
            .set_input_cols("x")
            .set_output_cols("o")
            .set_splits_array([[0.0, 1.0, 2.0]])
            .set_handle_invalid("keep")
        ).transform(Table({"x": values}))[0]
        assert isinstance(out.column("o"), jax.Array)  # no host fallback
        np.testing.assert_array_equal(np.asarray(out.column("o")), [0, 1, 1])


class TestNormalizer:
    def test_l2(self):
        t = Table({"vec": [Vectors.dense(3, 4), Vectors.dense(0, 5)]})
        out = Normalizer().set_input_col("vec").set_output_col("o").transform(t)[0]
        np.testing.assert_allclose(
            np.asarray(out.column("o")), [[0.6, 0.8], [0.0, 1.0]], atol=1e-6
        )

    def test_l1(self):
        t = Table({"vec": [Vectors.dense(1, 3)]})
        out = Normalizer().set_input_col("vec").set_output_col("o").set_p(1.0).transform(t)[0]
        np.testing.assert_allclose(np.asarray(out.column("o")), [[0.25, 0.75]], atol=1e-6)


class TestElementwiseProduct:
    def test_transform(self):
        t = Table({"vec": [Vectors.dense(2.1, 3.1), Vectors.dense(1.1, 3.3)]})
        op = (
            ElementwiseProduct()
            .set_input_col("vec")
            .set_output_col("o")
            .set_scaling_vec(Vectors.dense(1.1, 1.1))
        )
        out = op.transform(t)[0]
        np.testing.assert_allclose(
            np.asarray(out.column("o")), [[2.31, 3.41], [1.21, 3.63]], atol=1e-6
        )

    def test_save_load(self, tmp_path):
        op = ElementwiseProduct().set_scaling_vec(Vectors.dense(1.0, 2.0))
        op.save(str(tmp_path / "ewp"))
        loaded = ElementwiseProduct.load(str(tmp_path / "ewp"))
        np.testing.assert_array_equal(loaded.get_scaling_vec().to_array(), [1.0, 2.0])


class TestPolynomialExpansion:
    def test_degree2(self):
        # PolynomialExpansionTest EXPECTED_DENSE_OUTPUT
        t = Table({"vec": [Vectors.dense(1, 2, 3)]})
        out = PolynomialExpansion().set_input_col("vec").set_output_col("o").transform(t)[0]
        np.testing.assert_allclose(
            np.asarray(out.column("o"))[0], [1, 1, 2, 2, 4, 3, 3, 6, 9], atol=1e-9
        )

    def test_degree3(self):
        # EXPECTED_DENSE_OUTPUT_WITH_DEGREE_3 row 2
        t = Table({"vec": [Vectors.dense(2, 3)]})
        out = (
            PolynomialExpansion().set_input_col("vec").set_output_col("o").set_degree(3)
        ).transform(t)[0]
        np.testing.assert_allclose(
            np.asarray(out.column("o"))[0], [2, 4, 8, 3, 6, 12, 9, 18, 27], atol=1e-9
        )

    def test_invalid_degree(self):
        with pytest.raises(ValueError):
            PolynomialExpansion().set_degree(0)


class TestInteraction:
    def test_transform(self):
        # InteractionTest EXPECTED_DENSE_OUTPUT
        t = Table(
            {
                "f0": [1.0, 2.0],
                "vec1": [Vectors.dense(1, 2), Vectors.dense(2, 8)],
                "vec2": [Vectors.dense(3, 4), Vectors.dense(3, 4)],
            }
        )
        out = (
            Interaction().set_input_cols("f0", "vec1", "vec2").set_output_col("o")
        ).transform(t)[0]
        got = out.column("o")
        np.testing.assert_allclose(np.asarray(got)[0], [3, 4, 6, 8], atol=1e-9)
        np.testing.assert_allclose(np.asarray(got)[1], [12, 16, 48, 64], atol=1e-9)


class TestDCT:
    def test_forward(self):
        t = Table({"vec": [Vectors.dense(1, 1, 1, 1), Vectors.dense(1, 0, -1, 0)]})
        out = DCT().set_input_col("vec").set_output_col("o").transform(t)[0]
        got = np.asarray(out.column("o"))
        np.testing.assert_allclose(got[0], [2, 0, 0, 0], atol=1e-6)

    def test_roundtrip(self):
        x = np.random.RandomState(0).randn(5, 8)
        t = Table({"vec": x})
        fwd = DCT().set_input_col("vec").set_output_col("y").transform(t)[0]
        back = (
            DCT().set_input_col("y").set_output_col("z").set_inverse(True)
        ).transform(fwd)[0]
        np.testing.assert_allclose(np.asarray(back.column("z")), x, atol=1e-6)


class TestVectorAssembler:
    def test_transform(self):
        t = Table({"f0": [1.0, 2.0], "vec": [Vectors.dense(2, 3), Vectors.dense(4, 5)]})
        out = VectorAssembler().set_input_cols("f0", "vec").set_output_col("o").transform(t)[0]
        np.testing.assert_array_equal(np.asarray(out.column("o")), [[1, 2, 3], [2, 4, 5]])

    def test_handle_invalid(self):
        t = Table({"f0": [1.0, float("nan")], "f1": [2.0, 3.0]})
        op = VectorAssembler().set_input_cols("f0", "f1").set_output_col("o")
        with pytest.raises(ValueError):
            op.transform(t)
        out = op.set_handle_invalid("skip").transform(t)[0]
        assert out.num_rows == 1
        out = op.set_handle_invalid("keep").transform(t)[0]
        assert out.num_rows == 2

    def test_input_sizes_mismatch(self):
        t = Table({"vec": [Vectors.dense(1, 2)]})
        op = VectorAssembler().set_input_cols("vec").set_output_col("o").set_input_sizes(3)
        with pytest.raises(ValueError):
            op.transform(t)


class TestVectorSlicer:
    def test_transform(self):
        t = Table({"vec": [Vectors.dense(2.1, 3.1, 1.2, 3.1, 4.6)]})
        out = VectorSlicer().set_input_col("vec").set_output_col("o").set_indices(0, 2, 4).transform(t)[0]
        np.testing.assert_allclose(np.asarray(out.column("o")), [[2.1, 1.2, 4.6]])

    def test_out_of_range(self):
        t = Table({"vec": [Vectors.dense(1, 2)]})
        with pytest.raises(ValueError):
            VectorSlicer().set_input_col("vec").set_output_col("o").set_indices(5).transform(t)

    def test_duplicate_indices_rejected(self):
        with pytest.raises(ValueError):
            VectorSlicer().set_indices(1, 1)


class TestHashingTF:
    # HashingTFTest.java INPUT / EXPECTED_OUTPUT
    def _table(self):
        return Table(
            {
                "input": [
                    ["HashingTFTest", "Hashing", "Term", "Frequency", "Test"],
                    ["HashingTFTest", "Hashing", "Hashing", "Test", "Test"],
                ]
            }
        )

    def test_transform(self):
        out = HashingTF().transform(self._table())[0]
        batch = out.column("output")
        row0, row1 = batch.row(0), batch.row(1)
        assert row0.size() == 262144
        np.testing.assert_array_equal(
            row0.indices, [67564, 89917, 113827, 131486, 228971]
        )
        np.testing.assert_array_equal(row0.values, [1, 1, 1, 1, 1])
        np.testing.assert_array_equal(row1.indices, [67564, 131486, 228971])
        np.testing.assert_array_equal(row1.values, [1, 2, 2])

    def test_binary(self):
        out = HashingTF().set_binary(True).transform(self._table())[0]
        row1 = out.column("output").row(1)
        np.testing.assert_array_equal(row1.values, [1, 1, 1])

    def test_param_defaults(self):
        tf = HashingTF()
        assert tf.get_input_col() == "input"
        assert tf.get_num_features() == 262144
        assert not tf.get_binary()


class TestTokenizers:
    def test_tokenizer(self):
        t = Table({"input": ["Test for tokenization.", "Te,st. punct"]})
        out = Tokenizer().set_input_col("input").set_output_col("o").transform(t)[0]
        got = list(out.column("o"))
        assert got[0] == ["test", "for", "tokenization."]
        assert got[1] == ["te,st.", "punct"]

    def test_regex_tokenizer_gaps(self):
        t = Table({"input": ["Test for tokenization.", "Te,st. punct"]})
        out = (
            RegexTokenizer().set_input_col("input").set_output_col("o").set_pattern(r"\w+").set_gaps(False)
        ).transform(t)[0]
        assert list(out.column("o"))[0] == ["test", "for", "tokenization"]

    def test_regex_min_token_length(self):
        t = Table({"input": ["a ab abc"]})
        out = (
            RegexTokenizer().set_input_col("input").set_output_col("o").set_min_token_length(2)
        ).transform(t)[0]
        assert list(out.column("o"))[0] == ["ab", "abc"]


class TestNGram:
    def test_transform(self):
        t = Table({"input": [[], ["a", "b", "c"], ["a", "b", "c", "d"]]})
        out = NGram().set_input_col("input").set_output_col("o").transform(t)[0]
        got = list(out.column("o"))
        assert got[0] == []
        assert got[1] == ["a b", "b c"]
        assert got[2] == ["a b", "b c", "c d"]

    def test_n_larger_than_input(self):
        t = Table({"input": [["a", "b"]]})
        out = NGram().set_n(4).set_input_col("input").set_output_col("o").transform(t)[0]
        assert list(out.column("o"))[0] == []

    def test_dict_column_vocab_is_observed_only(self):
        """The dictionary path decodes only grams that actually occur — the
        u^n combinatorial vocabulary never materializes."""
        from flink_ml_tpu.table import DictTokenMatrix

        # u^n = 300^3 = 2.7e7: above the eager-vocab bound, inside int32
        vocab = np.array([f"t{i}" for i in range(300)])
        ids = np.array([[0, 1, 2, 3], [1, 2, 3, 299]], dtype=np.int32)
        t = Table({"input": DictTokenMatrix(vocab, ids)})
        out = NGram().set_n(3).set_input_col("input").set_output_col("o").transform(t)[0]
        col = out.column("o")
        assert isinstance(col, DictTokenMatrix)
        # 4 distinct observed trigrams, not 300^3
        assert set(col.vocab) == {"t0 t1 t2", "t1 t2 t3", "t2 t3 t299"}
        got = [
            [str(col.vocab[i]) for i in row if i >= 0] for row in np.asarray(col.ids)
        ]
        assert got == [["t0 t1 t2", "t1 t2 t3"], ["t1 t2 t3", "t2 t3 t299"]]


class TestStopWordsRemover:
    def test_transform(self):
        t = Table({"raw": [["I", "saw", "the", "red", "balloon"], ["Mary", "had", "a", "little", "lamb"]]})
        out = StopWordsRemover().set_input_cols("raw").set_output_cols("filtered").transform(t)[0]
        got = list(out.column("filtered"))
        assert got[0] == ["saw", "red", "balloon"]
        assert got[1] == ["Mary", "little", "lamb"]

    def test_case_sensitive(self):
        t = Table({"raw": [["The", "the"]]})
        op = (
            StopWordsRemover()
            .set_input_cols("raw")
            .set_output_cols("o")
            .set_case_sensitive(True)
            .set_stop_words("the")
        )
        assert list(op.transform(t)[0].column("o"))[0] == ["The"]

    def test_load_default_stop_words(self):
        for lang in ["english", "french", "german", "spanish"]:
            assert len(StopWordsRemover.load_default_stop_words(lang)) > 10
        with pytest.raises(ValueError):
            StopWordsRemover.load_default_stop_words("klingon")


class TestRandomSplitter:
    def test_split_fractions(self):
        t = Table({"f": np.arange(10000, dtype=np.float64)})
        parts = RandomSplitter().set_weights(4.0, 6.0).set_seed(0).transform(t)
        assert len(parts) == 2
        assert parts[0].num_rows + parts[1].num_rows == 10000
        assert abs(parts[0].num_rows / 10000 - 0.4) < 0.02

    def test_deterministic(self):
        t = Table({"f": np.arange(100, dtype=np.float64)})
        op = RandomSplitter().set_weights(1.0, 1.0).set_seed(42)
        a = np.asarray(op.transform(t)[0].column("f"))
        b = np.asarray(op.transform(t)[0].column("f"))
        np.testing.assert_array_equal(a, b)

    def test_invalid_weights(self):
        with pytest.raises(ValueError):
            RandomSplitter().set_weights(1.0)


class TestFeatureHasher:
    def test_golden_values(self):
        # FeatureHasherTest.java INPUT_DATA / EXPECTED_OUTPUT_DATA
        t = Table(
            {
                "f0": np.asarray(["a", "c"], dtype=object),
                "f1": [1.0, 1.0],
                "f2": np.asarray(["true", "false"], dtype=object),
            }
        )
        from flink_ml_tpu.models.feature.featurehasher import FeatureHasher

        out = (
            FeatureHasher()
            .set_input_cols("f0", "f1", "f2")
            .set_categorical_cols("f0", "f2")
            .set_num_features(1000)
        ).transform(t)[0]
        batch = out.column("output")
        np.testing.assert_array_equal(batch.row(0).indices, [607, 635, 913])
        np.testing.assert_array_equal(batch.row(0).values, [1, 1, 1])
        np.testing.assert_array_equal(batch.row(1).indices, [242, 869, 913])

    def test_numeric_value_kept(self):
        from flink_ml_tpu.models.feature.featurehasher import FeatureHasher

        t = Table({"x": [2.5]})
        out = FeatureHasher().set_input_cols("x").set_num_features(100).transform(t)[0]
        assert out.column("output").row(0).values[0] == 2.5


class TestSQLTransformerVectorized:
    """The columnwise projection fast path must agree with the sqlite path
    and additionally handle vector columns in expressions."""

    def _table(self):
        return Table(
            {"v1": np.array([-1.0, 2.0, -3.0]), "v2": np.array([4.0, 5.0, 6.0])}
        )

    def test_star_plus_expression_matches_sqlite(self):
        from flink_ml_tpu.models.feature.sqltransformer import (
            SQLTransformer,
            _try_vectorized_projection,
        )

        stmt = "SELECT *, ABS(v1) AS a, v1 + 2 * v2 AS b FROM __THIS__"
        t = self._table()
        fast = _try_vectorized_projection(stmt, t)
        assert fast is not None
        slow_stage = SQLTransformer().set_statement(stmt)
        # force sqlite by bypassing the fast path
        import flink_ml_tpu.models.feature.sqltransformer as mod

        orig = mod._try_vectorized_projection
        mod._try_vectorized_projection = lambda *_: None
        try:
            slow = slow_stage.transform(t)[0]
        finally:
            mod._try_vectorized_projection = orig
        for colname in ("v1", "v2", "a", "b"):
            np.testing.assert_allclose(
                np.asarray(fast.column(colname), dtype=np.float64),
                np.asarray(slow.column(colname), dtype=np.float64),
            )

    def test_vector_column_expression(self):
        from flink_ml_tpu.models.feature.sqltransformer import SQLTransformer

        t = Table({"vec": np.array([[1.0, -2.0], [3.0, -4.0]])})
        out = SQLTransformer().set_statement(
            "SELECT ABS(vec) * 2 AS scaled FROM __THIS__"
        ).transform(t)[0]
        np.testing.assert_array_equal(
            np.asarray(out.column("scaled")), [[2.0, 4.0], [6.0, 8.0]]
        )

    def test_where_scalar_filter(self):
        from flink_ml_tpu.models.feature.sqltransformer import SQLTransformer

        out = SQLTransformer().set_statement(
            "SELECT v1 FROM __THIS__ WHERE v1 > 0"
        ).transform(self._table())[0]
        assert out.num_rows == 1

    def test_where_keeps_vector_columns(self):
        """Filtered selects keep vector columns alive in the fast path —
        the sqlite fallback cannot represent them at all."""
        from flink_ml_tpu.models.feature.sqltransformer import (
            SQLTransformer,
            _try_vectorized_projection,
        )

        t = Table(
            {
                "vec": np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]]),
                "score": np.array([0.1, 0.9, 0.5]),
            }
        )
        stmt = "SELECT vec * 2 AS scaled, score FROM __THIS__ WHERE score >= 0.5"
        assert _try_vectorized_projection(stmt, t) is not None
        out = SQLTransformer().set_statement(stmt).transform(t)[0]
        np.testing.assert_array_equal(
            np.asarray(out.column("scaled")), [[6.0, 8.0], [10.0, 12.0]]
        )
        np.testing.assert_array_equal(np.asarray(out.column("score")), [0.9, 0.5])

    def test_where_boolean_combinators_match_sqlite(self):
        from flink_ml_tpu.models.feature.sqltransformer import SQLTransformer
        import flink_ml_tpu.models.feature.sqltransformer as mod

        t = self._table()
        stmt = (
            "SELECT v1, v2 FROM __THIS__ "
            "WHERE (v1 > 0 AND v2 < 6) OR NOT v2 >= 5"
        )
        fast = SQLTransformer().set_statement(stmt).transform(t)[0]
        orig = mod._try_vectorized_projection
        mod._try_vectorized_projection = lambda *_: None
        try:
            slow = SQLTransformer().set_statement(stmt).transform(t)[0]
        finally:
            mod._try_vectorized_projection = orig
        for c in ("v1", "v2"):
            np.testing.assert_allclose(
                np.asarray(fast.column(c), np.float64),
                np.asarray(slow.column(c), np.float64),
            )

    def test_where_nan_matches_sqlite_null_semantics(self):
        """sqlite stores NaN as NULL and NULL comparisons drop the row; the
        fast path's three-valued logic must agree — including under NOT,
        where naive boolean negation would resurrect NaN rows."""
        from flink_ml_tpu.models.feature.sqltransformer import SQLTransformer
        import flink_ml_tpu.models.feature.sqltransformer as mod

        t = Table(
            {
                "x": np.array([1.0, np.nan, 5.0, np.nan, 7.0]),
                "y": np.array([0.0, 1.0, np.nan, 2.0, 3.0]),
            }
        )
        for stmt in (
            "SELECT x FROM __THIS__ WHERE x != 5",
            "SELECT x FROM __THIS__ WHERE NOT x > 2",
            "SELECT x FROM __THIS__ WHERE x > 0 OR y > 0",
            "SELECT x FROM __THIS__ WHERE NOT (x > 2 AND y < 1)",
        ):
            fast = SQLTransformer().set_statement(stmt).transform(t)[0]
            orig = mod._try_vectorized_projection
            mod._try_vectorized_projection = lambda *_: None
            try:
                slow = SQLTransformer().set_statement(stmt).transform(t)[0]
            finally:
                mod._try_vectorized_projection = orig
            np.testing.assert_array_equal(
                np.asarray(fast.column("x"), np.float64),
                np.asarray(slow.column("x"), np.float64),
                err_msg=stmt,
            )

    def test_where_over_vector_column_falls_back(self):
        """A comparison over a (n, d) vector column is not a row mask; the
        fast path must decline rather than mis-filter."""
        from flink_ml_tpu.models.feature.sqltransformer import (
            _try_vectorized_projection,
        )

        t = Table({"vec": np.array([[1.0, -2.0], [3.0, 4.0]])})
        assert _try_vectorized_projection(
            "SELECT vec FROM __THIS__ WHERE vec > 0", t
        ) is None


class TestFeatureHasherVectorized:
    """The vectorized (batch-murmur) path must match the per-row dict path
    exactly, including categorical `col=value` hashing and bucket-collision
    summing."""

    def test_nul_bearing_strings_match_reference_hash(self):
        """Strings with embedded or TRAILING U+0000 reach the hash intact:
        Table keeps them object-dtype, the per-row path hashes them with
        the scalar reference-spec murmur (numpy U storage would strip the
        trailing NUL and diverge from Java's hashUnencodedChars)."""
        from flink_ml_tpu.models.feature.featurehasher import (
            FeatureHasher,
            _hash_index,
        )

        weird = ["a\x00b", "ab\x00", "plain", "\x00", "x"]
        t = Table({"cat": weird})
        out = (
            FeatureHasher()
            .set_input_cols("cat")
            .set_categorical_cols("cat")
            .set_num_features(64)
            .transform(t)[0]
        )
        vecs = out.column("output")
        for i, s in enumerate(weird):
            idx = _hash_index(f"cat={s}", 64)
            row = np.asarray(vecs.row(i).to_array())
            assert row[idx] == 1.0, (s, idx, row.nonzero())

    def test_matches_per_row_path(self):
        import flink_ml_tpu.models.feature.featurehasher as fh

        rng = np.random.RandomState(3)
        t = Table(
            {
                "f0": rng.rand(40),
                "f1": rng.randint(0, 3, 40).astype(np.float64),
                "f2": rng.rand(40),
            }
        )
        stage = (
            fh.FeatureHasher()
            .set_input_cols("f0", "f1", "f2")
            .set_categorical_cols("f0", "f1")
            .set_num_features(16)  # tiny: force collisions
        )
        fast = stage.transform(t)[0].column("output")
        # force the per-row path by making the vectorizable check fail
        obj = np.empty(40, dtype=object)
        obj[:] = [float(v) for v in np.asarray(t.column("f0"))]
        t_obj = Table({"f0": obj, "f1": t.column("f1"), "f2": t.column("f2")})
        slow = stage.transform(t_obj)[0].column("output")
        for r in range(40):
            assert fast.row(r).indices.tolist() == slow.row(r).indices.tolist()
            np.testing.assert_allclose(fast.row(r).values, slow.row(r).values)


def test_featurehasher_bool_categorical_java_lowercase():
    """Vectorized path must hash bool values as 'true'/'false' like
    Java Boolean.toString (and the per-row path)."""
    import flink_ml_tpu.models.feature.featurehasher as fh

    t = Table({"flag": np.array([True, False, True])})
    stage = fh.FeatureHasher().set_input_cols("flag").set_num_features(64)
    fast = stage.transform(t)[0].column("output")
    obj = np.empty(3, dtype=object)
    obj[:] = [True, False, True]
    slow = stage.transform(Table({"flag": obj}))[0].column("output")
    for r in range(3):
        assert fast.row(r).indices.tolist() == slow.row(r).indices.tolist()


def test_sqltransformer_string_column_falls_back():
    from flink_ml_tpu.models.feature.sqltransformer import SQLTransformer

    t = Table({"name": np.array(["a", "b"]), "v": np.array([1.0, 2.0])})
    out = SQLTransformer().set_statement(
        "SELECT v + 1 AS w FROM __THIS__"
    ).transform(t)[0]
    np.testing.assert_array_equal(np.asarray(out.column("w")), [2.0, 3.0])
    # a string column in the expression must not crash (sqlite fallback)
    out2 = SQLTransformer().set_statement(
        "SELECT name, v FROM __THIS__"
    ).transform(t)[0]
    assert out2.num_rows == 2


def test_sqltransformer_div_by_zero_falls_back_to_sqlite():
    from flink_ml_tpu.models.feature.sqltransformer import SQLTransformer

    t = Table({"v1": np.array([1.0, 2.0])})
    out = SQLTransformer().set_statement(
        "SELECT v1, 1/0 AS x FROM __THIS__"
    ).transform(t)[0]
    assert out.num_rows == 2  # sqlite path: x is NULL, no crash


class TestDeviceEdgeSemantics:
    """Device kernels must match host semantics on the awkward inputs the
    review process flagged: NaN binning and empty n-gram dictionaries."""

    def test_kbins_nan_bins_like_host(self):
        import jax

        from flink_ml_tpu.models.feature.kbinsdiscretizer import (
            KBinsDiscretizer,
            KBinsDiscretizerModel,
        )

        X = np.asarray([[0.25], [np.nan], [0.75], [2.0]], np.float32)
        train = Table({"input": np.asarray([[0.0], [0.5], [1.0]], np.float64)})
        model = KBinsDiscretizer().set_input_col("input").set_output_col("o") \
            .set_num_bins(2).set_strategy("uniform").fit(train)
        host = np.asarray(model.transform(Table({"input": X.astype(np.float64)}))[0].column("o"))
        dev = np.asarray(
            model.transform(Table({"input": jax.device_put(X)}))[0].column("o"),
            np.float64,
        )
        np.testing.assert_array_equal(dev, host)

    def test_ngram_empty_vocab(self):
        from flink_ml_tpu.models.feature.ngram import NGram
        from flink_ml_tpu.table import DictTokenMatrix

        t = Table({
            "t": DictTokenMatrix(np.zeros(0, "<U1"), np.full((3, 4), -1, np.int32))
        })
        out = NGram().set_input_col("t").set_output_col("o").transform(t)[0]
        col = out.column("o")
        rows = (
            [col.row(i) for i in range(len(col))]
            if isinstance(col, DictTokenMatrix)
            else [list(r) for r in col]
        )
        assert rows == [[], [], []]
