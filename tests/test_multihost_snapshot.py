"""Multi-host snapshot coordination battery (flink_ml_tpu/ckpt/coordinator.py):
the sharded-write + two-phase-commit-manifest protocol — per-host shard
layout, per-shard and per-leaf integrity digests, the torn-manifest battery
(kill mid-shard-write / mid-manifest-commit, manifest-without-shard, stale
digests), straggler abort-this-cut, retention GC, refusals-never-retried,
flaky-read retries, elastic N-host→M-host restore parity vs the single-file
path, and the single-file path's new per-leaf crc32 verification."""

import io
import json
import os
import warnings
import zlib

import numpy as np
import pytest

from flink_ml_tpu import config
from flink_ml_tpu.ckpt import (
    InjectedFault,
    SnapshotAborted,
    SnapshotIntegrityError,
    faults,
    load_job_snapshot,
    save_job_snapshot,
    snapshot_file,
    stage_section,
)
from flink_ml_tpu.ckpt import coordinator
from flink_ml_tpu.utils import metrics


def _jnp():
    import jax.numpy as jnp

    return jnp


def _save(path, key="j", epoch=1, scale=1.0, hosts=4, meta=None):
    jnp = _jnp()
    return save_job_snapshot(
        str(path),
        key,
        {
            "model": (
                jnp.arange(8.0) * scale,
                jnp.arange(32.0).reshape(8, 4) * scale,
                np.float64(scale),
            )
        },
        epoch=epoch,
        criteria=0.5,
        specs={"model": ("replicated", "data", "host")},
        meta=meta or {"numBatches": 4},
        hosts=hosts,
    )


def _template():
    jnp = _jnp()
    return {"model": (jnp.zeros(8), jnp.zeros((8, 4)), np.float64(0))}


def _load(path, key="j", **kw):
    return load_job_snapshot(str(path), key, templates=_template(), **kw)


def _corrupt(file, offset=60):
    with open(file, "r+b") as f:
        f.seek(offset)
        f.write(b"\xde\xad\xbe\xef")


# ---------------------------------------------------------------------------
# format: shard layout, digests, manifest contents
# ---------------------------------------------------------------------------

def test_sharded_roundtrip_and_manifest_inventory(tmp_path):
    target = _save(tmp_path, epoch=3, scale=2.0)
    assert os.path.basename(target) == "snap-j.c000001.manifest.json"
    with open(target) as f:
        manifest = json.load(f)
    assert manifest["formatVersion"] == coordinator.SHARDED_FORMAT_VERSION
    assert manifest["hosts"] == 4
    assert set(manifest["shards"]) == {
        f"snap-j.c000001.host{h}.npz" for h in range(4)
    }
    for info in manifest["shards"].values():
        assert {"crc32", "sha256", "bytes", "host"} <= set(info)
    # leaf→shard layout: the data-tagged (8, 4) leaf splits 2 rows/host
    parts = manifest["layout"]["s_model_1"]
    assert [(p["start"], p["stop"]) for p in parts] == [
        (0, 2), (2, 4), (4, 6), (6, 8)
    ]
    assert all(p["axis"] == 0 for p in parts)
    # replicated + host leaves are whole-array, owned by host 0
    assert manifest["layout"]["s_model_0"][0]["axis"] is None
    assert manifest["layout"]["s_model_0"][0]["shard"].endswith("host0.npz")

    snap = _load(tmp_path)
    assert (snap.epoch, snap.criteria) == (3, 0.5)
    c, r, host_leaf = snap.sections["model"]
    np.testing.assert_array_equal(c, 2.0 * np.arange(8, dtype=np.float32))
    np.testing.assert_array_equal(
        r, 2.0 * np.arange(32, dtype=np.float32).reshape(8, 4)
    )
    assert float(host_leaf) == 2.0 and host_leaf.dtype == np.float64
    assert snap.specs["model"] == ("replicated", "data", "host")


def test_each_host_shard_holds_only_its_slice(tmp_path):
    _save(tmp_path, scale=3.0)
    for h in range(4):
        with np.load(coordinator.shard_file(str(tmp_path), "j", 1, h)) as f:
            if h == 0:
                np.testing.assert_array_equal(
                    f["s_model_0"], 3.0 * np.arange(8, dtype=np.float32)
                )
            else:
                assert "s_model_0" not in f.files  # replicated: host 0 only
            np.testing.assert_array_equal(
                f["s_model_1"],
                3.0
                * np.arange(32, dtype=np.float32).reshape(8, 4)[
                    2 * h : 2 * h + 2
                ],
            )


def test_uneven_rows_and_surplus_hosts(tmp_path):
    jnp = _jnp()
    # 5 rows over 3 hosts (2/2/1) and 2 rows over 4 hosts (empty shards)
    save_job_snapshot(
        str(tmp_path),
        "u",
        {"model": (jnp.arange(10.0).reshape(5, 2), jnp.arange(2.0))},
        epoch=1,
        specs={"model": ("data", "data")},
        hosts=3,
    )
    snap = load_job_snapshot(
        str(tmp_path),
        "u",
        templates={"model": (jnp.zeros((5, 2)), jnp.zeros(2))},
    )
    np.testing.assert_array_equal(
        snap.sections["model"][0], np.arange(10, dtype=np.float32).reshape(5, 2)
    )
    np.testing.assert_array_equal(
        snap.sections["model"][1], np.arange(2, dtype=np.float32)
    )


def test_mesh_host_group_mapping():
    import jax

    from flink_ml_tpu.parallel import mesh as mesh_lib

    assert mesh_lib.host_slice_bounds(8, 4) == [(0, 2), (2, 4), (4, 6), (6, 8)]
    assert mesh_lib.host_slice_bounds(5, 3) == [(0, 2), (2, 4), (4, 5)]
    assert mesh_lib.host_slice_bounds(2, 4) == [(0, 1), (1, 2), (2, 2), (2, 2)]
    assert mesh_lib.shard_axis_for_tag("data", 2) == 0
    assert mesh_lib.shard_axis_for_tag("model", 2) == 1
    assert mesh_lib.shard_axis_for_tag("replicated", 2) is None
    assert mesh_lib.shard_axis_for_tag("data", 0) is None  # scalars: whole
    mesh = mesh_lib.create_mesh(("data",), devices=jax.devices()[:8])
    groups = mesh_lib.host_groups(mesh, 4)
    assert [len(g) for g in groups] == [2, 2, 2, 2]
    assert sum(groups, []) == list(mesh.devices.flat)
    with pytest.raises(ValueError):
        mesh_lib.host_slice_bounds(8, 0)


def test_model_tag_shards_trailing_axis(tmp_path):
    jnp = _jnp()
    save_job_snapshot(
        str(tmp_path),
        "m",
        {"model": jnp.arange(24.0).reshape(2, 12)},
        epoch=1,
        specs={"model": "model"},
        hosts=4,
    )
    with np.load(coordinator.shard_file(str(tmp_path), "m", 1, 2)) as f:
        np.testing.assert_array_equal(
            f["s_model_0"],
            np.arange(24, dtype=np.float32).reshape(2, 12)[:, 6:9],
        )
    snap = load_job_snapshot(
        str(tmp_path), "m", templates={"model": jnp.zeros((2, 12))}
    )
    np.testing.assert_array_equal(
        snap.sections["model"], np.arange(24, dtype=np.float32).reshape(2, 12)
    )


# ---------------------------------------------------------------------------
# torn-manifest battery
# ---------------------------------------------------------------------------

def test_kill_mid_shard_write_leaves_previous_cut_restorable(tmp_path):
    _save(tmp_path, epoch=1, scale=1.0)
    # host 2 (the third shard write) dies after its temp file, before its
    # rename — no manifest ever commits, the cut is torn, and the
    # exception-path sweep (ISSUE 15 satellite) removes its partials
    # IMMEDIATELY instead of leaving orphans for the next commit's GC
    with faults.inject("snapshot.shard.write", after=3) as plan:
        with pytest.raises(InjectedFault):
            _save(tmp_path, epoch=2, scale=9.0)
    assert plan.fired
    snap = _load(tmp_path)
    assert snap.epoch == 1
    np.testing.assert_array_equal(
        snap.sections["model"][0], np.arange(8, dtype=np.float32)
    )
    orphans = [
        n
        for n in os.listdir(tmp_path)
        if coordinator._cut_of(n, "snap-j") == 2
    ]
    assert orphans == []
    # the writer recovers: the next commit succeeds
    _save(tmp_path, epoch=2, scale=2.0)
    assert _load(tmp_path).epoch == 2


def test_kill_mid_manifest_commit_leaves_previous_cut_restorable(tmp_path):
    _save(tmp_path, epoch=1)
    with faults.inject("snapshot.commit") as plan:
        with pytest.raises(InjectedFault):
            _save(tmp_path, epoch=2, scale=9.0)
    assert plan.fired
    # every shard of the torn cut landed, but the cut never committed
    assert os.path.exists(coordinator.shard_file(str(tmp_path), "j", 2, 3))
    assert not os.path.exists(coordinator.manifest_file(str(tmp_path), "j", 2))
    assert _load(tmp_path).epoch == 1


def test_torn_first_commit_is_a_fresh_start(tmp_path):
    with faults.inject("snapshot.commit"):
        with pytest.raises(InjectedFault):
            _save(tmp_path, epoch=1)
    assert _load(tmp_path) is None  # no committed cut ever existed


def test_manifest_present_but_shard_missing_falls_back(tmp_path):
    _save(tmp_path, epoch=1)
    _save(tmp_path, epoch=2, scale=2.0)
    os.remove(coordinator.shard_file(str(tmp_path), "j", 2, 1))
    before = metrics.get_counter("checkpoint.restore.fallback", 0)
    with pytest.warns(UserWarning, match="missing"):
        snap = _load(tmp_path)
    assert snap.epoch == 1  # fell back to the last committed intact cut
    assert metrics.get_counter("checkpoint.restore.fallback", 0) == before + 1


def test_stale_digest_shard_falls_back_and_counts(tmp_path):
    _save(tmp_path, epoch=1)
    _save(tmp_path, epoch=2, scale=2.0)
    # "stale digest": the shard file is a VALID npz, just not the bytes
    # the manifest committed (e.g. an older generation restored by a
    # backup tool) — the digest refuses it
    victim = coordinator.shard_file(str(tmp_path), "j", 2, 1)
    np.savez(victim, s_model_1=np.zeros((2, 4), np.float32))
    before = metrics.get_counter("checkpoint.digest.mismatch", 0)
    with pytest.warns(UserWarning, match="mismatch"):
        snap = _load(tmp_path)
    assert snap.epoch == 1
    assert metrics.get_counter("checkpoint.digest.mismatch", 0) == before + 1


def test_all_cuts_corrupt_raises_loudly(tmp_path):
    with config.snapshot_retention_mode(2):
        _save(tmp_path, epoch=1)
        _save(tmp_path, epoch=2)
    for cut in (1, 2):
        _corrupt(coordinator.shard_file(str(tmp_path), "j", cut, 0))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(SnapshotIntegrityError, match="cannot produce"):
            _load(tmp_path)


def test_bit_rot_injection_mid_file(tmp_path):
    _save(tmp_path, epoch=1)
    _save(tmp_path, epoch=2, scale=5.0)
    _corrupt(coordinator.shard_file(str(tmp_path), "j", 2, 2))
    with pytest.warns(UserWarning, match="crc32 mismatch"):
        snap = _load(tmp_path)
    assert snap.epoch == 1


def test_future_manifest_format_version_falls_back(tmp_path):
    _save(tmp_path, epoch=1)
    _save(tmp_path, epoch=2)
    mfile = coordinator.manifest_file(str(tmp_path), "j", 2)
    with open(mfile) as f:
        manifest = json.load(f)
    manifest["formatVersion"] = 99
    with open(mfile, "w") as f:
        json.dump(manifest, f)
    with pytest.warns(UserWarning, match="format version 99"):
        snap = _load(tmp_path)
    assert snap.epoch == 1


def test_meta_cursor_mismatch_refused_not_fallen_back(tmp_path):
    """A meta refusal is about the JOB, not the cut: older cuts share the
    layout, so the loader must bail (None) instead of restoring an older
    cut that would be refused for the same reason."""
    _save(tmp_path, epoch=1, meta={"numBatches": 4})
    _save(tmp_path, epoch=2, meta={"numBatches": 4})
    with pytest.warns(UserWarning, match="numBatches"):
        snap = _load(tmp_path, expect_meta={"numBatches": 7})
    assert snap is None
    assert _load(tmp_path, expect_meta={"numBatches": 4}).epoch == 2


def test_sharded_state_is_authoritative_over_stale_single_file(tmp_path):
    """When committed sharded cuts exist, a refusal must NOT fall through
    to an older single-file snapshot left behind by a format switch."""
    jnp = _jnp()
    save_job_snapshot(
        str(tmp_path), "j", {"model": (jnp.zeros(8), jnp.zeros((8, 4)),
                                       np.float64(0))},
        epoch=7, meta={"numBatches": 4},
    )  # single-file, hosts=None
    assert os.path.exists(snapshot_file(str(tmp_path), "j"))
    _save(tmp_path, epoch=9)
    with pytest.warns(UserWarning, match="numBatches"):
        snap = _load(tmp_path, expect_meta={"numBatches": 7})
    assert snap is None  # NOT the epoch-7 single file


# ---------------------------------------------------------------------------
# straggler abort-this-cut
# ---------------------------------------------------------------------------

def test_straggler_host_aborts_cut_previous_restorable(tmp_path):
    _save(tmp_path, epoch=1)
    before = metrics.get_counter("checkpoint.abort", 0)
    with config.transient_retry_mode(1):
        with faults.flaky("snapshot.shard.write", times=99):
            with pytest.warns(UserWarning, match="aborted"):
                out = _save(tmp_path, epoch=2, scale=9.0)
    assert out is None  # the cut was abandoned, not committed
    assert metrics.get_counter("checkpoint.abort", 0) == before + 1
    # no partial files of the aborted cut survive
    leftovers = [
        n for n in os.listdir(tmp_path) if coordinator._cut_of(n, "snap-j") == 2
    ]
    assert leftovers == []
    assert _load(tmp_path).epoch == 1
    # the job recovered: the next boundary commits normally
    assert _save(tmp_path, epoch=3, scale=3.0) is not None
    assert _load(tmp_path).epoch == 3


def test_straggler_deadline_bounds_the_wait(tmp_path):
    """With a 0-second host deadline every transient failure exhausts
    immediately — the cut aborts on the first blip instead of spinning
    through the retry budget."""
    _save(tmp_path, epoch=1)
    prev = config.snapshot_host_deadline_s
    config.snapshot_host_deadline_s = 0.0
    try:
        with config.transient_retry_mode(50):
            with faults.flaky("snapshot.shard.write", times=1) as plan:
                with pytest.warns(UserWarning, match="aborted"):
                    assert _save(tmp_path, epoch=2) is None
    finally:
        config.snapshot_host_deadline_s = prev
    assert plan.failures == 1  # one attempt, no retry spin
    assert _load(tmp_path).epoch == 1


def test_unexpected_exception_mid_cut_sweeps_partials(tmp_path):
    """Satellite (ISSUE 15): a NON-SnapshotAborted failure mid-cut — an
    injected kill inside host 2's shard write — must sweep the partial
    shard files immediately, not leave them for the next commit's GC."""
    _save(tmp_path, epoch=1)
    before = metrics.get_counter("checkpoint.sweep", 0)
    with faults.inject("snapshot.shard.write", after=3):
        with pytest.raises(InjectedFault):
            _save(tmp_path, epoch=2, scale=9.0)
    # hosts 0 and 1 landed their shards before the kill; host 2 left a
    # temp — ALL of it is gone, and the previous cut is untouched
    leftovers = [
        n for n in os.listdir(tmp_path) if coordinator._cut_of(n, "snap-j") == 2
    ]
    assert leftovers == []
    assert metrics.get_counter("checkpoint.sweep", 0) == before + 1
    assert _load(tmp_path).epoch == 1


def test_mid_commit_kill_keeps_torn_2pc_shape_and_sweep_cancels_it(tmp_path):
    """A kill mid-MANIFEST-commit models a crash between the two phases:
    the torn-2PC artifact (shards landed, no manifest) deliberately
    survives the in-process sweep — it is what a real crash leaves — and
    `sweep_uncommitted` (the supervisor's abort path) cancels it."""
    _save(tmp_path, epoch=1)
    with faults.inject("snapshot.commit"):
        with pytest.raises(InjectedFault):
            _save(tmp_path, epoch=2, scale=9.0)
    assert os.path.exists(coordinator.shard_file(str(tmp_path), "j", 2, 0))
    removed = coordinator.sweep_uncommitted(str(tmp_path), "j")
    assert removed >= 4  # the torn cut's shards (+ the manifest temp)
    leftovers = [
        n for n in os.listdir(tmp_path) if coordinator._cut_of(n, "snap-j") == 2
    ]
    assert leftovers == []
    assert _load(tmp_path).epoch == 1
    # committed state is never touched: sweeping again removes nothing
    assert coordinator.sweep_uncommitted(str(tmp_path), "j") == 0


def test_sweep_uncommitted_spares_reused_stable_shards(tmp_path):
    """Stable-section files referenced by a committed manifest survive
    `sweep_uncommitted` (only cuts NEWER than the last commit die)."""
    jnp = _jnp()
    arrays = {"model": (jnp.arange(8.0),)}

    def save(epoch):
        return save_job_snapshot(
            str(tmp_path), "j", arrays, epoch=epoch,
            specs={"model": ("data",), "cache": "data"},
            meta={"numBatches": 2},
            hosts=2,
            stable_sections={"cache": lambda: (np.arange(16.0),)},
        )

    save(1)
    stable = coordinator.stable_shard_file(str(tmp_path), "j", "cache", 0)
    assert os.path.exists(stable)
    with faults.inject("snapshot.commit"):
        with pytest.raises(InjectedFault):
            save(2)
    coordinator.sweep_uncommitted(str(tmp_path), "j")
    assert os.path.exists(stable)  # referenced by the committed cut
    snap = load_job_snapshot(
        str(tmp_path), "j", templates={"model": (jnp.zeros(8),)}
    )
    assert snap.epoch == 1
    np.testing.assert_array_equal(
        np.asarray(snap.sections["cache"][0]), np.arange(16.0)
    )


def test_concurrent_straggler_abort_racing_retention_gc(tmp_path):
    """Satellite (ISSUE 15): a straggler abort racing a retention GC
    must leave the previous cut restorable — the abort sweeps ONLY its
    own cut's files, GC only unretained ones, so neither can victimize
    the last committed manifest regardless of interleaving."""
    import threading

    _save(tmp_path, epoch=1)
    _save(tmp_path, epoch=2, scale=2.0)
    stop = threading.Event()
    errors = []

    def gc_loop():
        try:
            while not stop.is_set():
                coordinator.gc_snapshots(str(tmp_path), "j")
        except BaseException as e:  # noqa: BLE001 — surfaced to the assert below
            errors.append(e)

    worker = threading.Thread(target=gc_loop, daemon=True)  # tpulint: disable=unbounded-queue -- test-local racer, joined below
    worker.start()
    try:
        for k in range(4):
            with config.transient_retry_mode(0):
                with faults.flaky("snapshot.shard.write", times=99):
                    with pytest.warns(UserWarning, match="aborted"):
                        assert _save(tmp_path, epoch=3 + k, scale=9.0) is None
    finally:
        stop.set()
        worker.join(timeout=10.0)
    assert not worker.is_alive()
    assert errors == []
    snap = _load(tmp_path)
    assert snap.epoch == 2  # the previous committed cut survived the race
    np.testing.assert_array_equal(
        snap.sections["model"][0], np.arange(8, dtype=np.float32) * 2.0
    )
    # and the directory holds no aborted-cut debris
    cuts = coordinator.committed_cuts(str(tmp_path), "j")
    stray = [
        n
        for n in os.listdir(tmp_path)
        if (coordinator._cut_of(n, "snap-j") or 0) not in cuts
        and coordinator._cut_of(n, "snap-j") is not None
    ]
    assert stray == []


def test_transient_shard_write_retried_within_budget(tmp_path):
    with config.transient_retry_mode(3):
        with faults.flaky("snapshot.shard.write", times=2) as plan:
            assert _save(tmp_path, epoch=4, scale=4.0) is not None
    assert plan.failures == 2
    assert _load(tmp_path).epoch == 4


# ---------------------------------------------------------------------------
# retention + GC
# ---------------------------------------------------------------------------

def test_retention_keeps_last_n_cuts(tmp_path):
    with config.snapshot_retention_mode(3):
        for e in range(1, 6):
            _save(tmp_path, epoch=e, scale=float(e))
    cuts = coordinator.committed_cuts(str(tmp_path), "j")
    assert cuts == [3, 4, 5]
    files = os.listdir(tmp_path)
    assert not any(coordinator._cut_of(n, "snap-j") in (1, 2) for n in files)
    # rollback-to-previous-cut is possible: corrupt newest, get cut 4
    _corrupt(coordinator.shard_file(str(tmp_path), "j", 5, 0))
    with pytest.warns(UserWarning):
        snap = _load(tmp_path)
    assert snap.epoch == 4


def test_gc_removes_stale_temps_and_unreferenced_stable_shards(tmp_path):
    _save(tmp_path, epoch=1)
    stray_tmp = os.path.join(
        str(tmp_path), "snap-j.c000001.host9.tmp.npz"
    )
    stray_stable = os.path.join(
        str(tmp_path), "snap-j.stable-cache.host0.npz"
    )
    np.savez(stray_tmp, x=np.zeros(1))
    np.savez(stray_stable, x=np.zeros(1))
    _save(tmp_path, epoch=2)
    assert not os.path.exists(stray_tmp)
    assert not os.path.exists(stray_stable)
    assert _load(tmp_path).epoch == 2


# ---------------------------------------------------------------------------
# retries: flaky reads retried, refusals NEVER retried
# ---------------------------------------------------------------------------

def test_flaky_manifest_and_shard_reads_retried_to_success(tmp_path):
    _save(tmp_path, epoch=6, scale=6.0)
    with config.transient_retry_mode(3):
        with faults.flaky("snapshot.manifest.read", times=2) as mplan:
            snap = _load(tmp_path)
        assert snap.epoch == 6
        with faults.flaky("snapshot.shard.read", times=2) as splan:
            snap = _load(tmp_path)
        assert snap.epoch == 6
    assert mplan.failures == 2 and splan.failures == 2
    np.testing.assert_array_equal(
        snap.sections["model"][0], 6.0 * np.arange(8, dtype=np.float32)
    )


def test_flaky_read_budget_exhausted_reraises_original(tmp_path):
    from flink_ml_tpu.ckpt.faults import TransientFault

    _save(tmp_path, epoch=1)
    with config.transient_retry_mode(1):
        with faults.flaky("snapshot.shard.read", times=10):
            with pytest.raises(TransientFault) as ei:
                _load(tmp_path)
    assert ei.value.retry_attempts == 2


def test_refusals_are_never_retried(tmp_path):
    """Digest mismatch and format-version refusals are decisions — the
    retry counters must not move while the loader falls back."""
    _save(tmp_path, epoch=1)
    _save(tmp_path, epoch=2)
    _corrupt(coordinator.shard_file(str(tmp_path), "j", 2, 0))
    before = metrics.get_counter("flow.retry", 0)
    with config.transient_retry_mode(5):
        with pytest.warns(UserWarning, match="mismatch"):
            snap = _load(tmp_path)
    assert snap.epoch == 1
    assert metrics.get_counter("flow.retry", 0) == before


# ---------------------------------------------------------------------------
# single-file path: per-leaf crc32 digests (satellite)
# ---------------------------------------------------------------------------

def _rewrite_single_file_leaf(file, leaf_key, new_array):
    with np.load(file) as f:
        arrays = {k: f[k] for k in f.files}
    arrays[leaf_key] = new_array  # the manifest (and its crc32s) stay put
    manifest = arrays.pop("manifest")
    np.savez(file, manifest=manifest, **arrays)


def test_single_file_corrupt_leaf_fails_loudly_naming_leaf(tmp_path):
    jnp = _jnp()
    file = save_job_snapshot(
        str(tmp_path),
        "sf",
        {"model": (jnp.arange(4.0), jnp.ones(3))},
        epoch=2,
    )
    _rewrite_single_file_leaf(file, "s_model_1", np.full(3, 7.0, np.float32))
    with pytest.raises(SnapshotIntegrityError, match="s_model_1"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            load_job_snapshot(
                str(tmp_path),
                "sf",
                templates={"model": (jnp.zeros(4), jnp.zeros(3))},
            )


def test_single_file_digest_failure_not_retried(tmp_path):
    jnp = _jnp()
    file = save_job_snapshot(
        str(tmp_path), "sf", {"model": jnp.arange(4.0)}, epoch=1
    )
    _rewrite_single_file_leaf(file, "s_model_0", np.zeros(4, np.float32))
    before = metrics.get_counter("flow.retry.snapshot.read", 0)
    with config.transient_retry_mode(5):
        with pytest.raises(SnapshotIntegrityError):
            load_job_snapshot(
                str(tmp_path), "sf", templates={"model": jnp.zeros(4)}
            )
    assert metrics.get_counter("flow.retry.snapshot.read", 0) == before


def test_single_file_pre_digest_snapshot_still_loads(tmp_path):
    """Snapshots written before the digest satellite (no crc32 entries)
    load without verification — additive format evolution."""
    jnp = _jnp()
    file = save_job_snapshot(
        str(tmp_path), "old", {"model": jnp.arange(4.0)}, epoch=3
    )
    with np.load(file) as f:
        arrays = {k: f[k] for k in f.files}
    manifest = json.loads(str(arrays.pop("manifest")))
    for section in manifest["sections"].values():
        for entry in section["leaves"]:
            entry.pop("crc32", None)
    np.savez(file, manifest=np.asarray(json.dumps(manifest)), **arrays)
    snap = load_job_snapshot(
        str(tmp_path), "old", templates={"model": jnp.zeros(4)}
    )
    assert snap is not None and snap.epoch == 3


def test_legacy_reader_warns_it_cannot_verify(tmp_path):
    from flink_ml_tpu.parallel.iteration import save_iteration_checkpoint

    jnp = _jnp()
    carry = (jnp.asarray([1.0, 2.0]),)
    save_iteration_checkpoint(str(tmp_path), carry, epoch=3, criteria=0.5,
                              job_key="lg")
    with pytest.warns(UserWarning, match="CANNOT be verified"):
        snap = load_job_snapshot(str(tmp_path), "lg", templates={"model": carry})
    assert snap is not None and snap.epoch == 3


# ---------------------------------------------------------------------------
# elastic: N-host shards onto M-host meshes, parity vs single-file
# ---------------------------------------------------------------------------

def test_stage_section_reshards_sharded_snapshot_onto_other_meshes(tmp_path):
    import jax

    from flink_ml_tpu.parallel import mesh as mesh_lib

    _save(tmp_path, epoch=1, scale=4.0, hosts=8)
    snap = _load(tmp_path)
    for n_dev in (1, 2, 8):
        mesh = mesh_lib.create_mesh(("data",), devices=jax.devices()[:n_dev])
        c, r, host_leaf = stage_section(snap, "model", mesh=mesh)
        assert isinstance(c, jax.Array) and isinstance(r, jax.Array)
        assert r.sharding.spec == mesh_lib.data_sharding(mesh, 2).spec
        np.testing.assert_array_equal(
            np.asarray(r),
            4.0 * np.arange(32, dtype=np.float32).reshape(8, 4),
        )
        assert isinstance(host_leaf, np.ndarray)


@pytest.mark.parametrize("from_hosts,to_hosts", [(1, 8), (8, 2)])
def test_sharded_snapshot_rewrites_across_host_counts(tmp_path, from_hosts, to_hosts):
    """Write on N hosts, restore, re-save on M hosts, restore again: the
    leaves survive both transports bit-for-bit (elastic N→M, both
    directions, independent of mesh device count)."""
    _save(tmp_path / "a", epoch=1, scale=7.0, hosts=from_hosts)
    snap = _load(tmp_path / "a")
    jnp = _jnp()
    save_job_snapshot(
        str(tmp_path / "b"),
        "j",
        {"model": tuple(jnp.asarray(leaf) if i < 2 else leaf
                        for i, leaf in enumerate(snap.sections["model"]))},
        epoch=1,
        specs={"model": ("replicated", "data", "host")},
        hosts=to_hosts,
    )
    again = _load(tmp_path / "b")
    for a, b in zip(snap.sections["model"], again.sections["model"]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("from_dev,to_dev", [(1, 8), (8, 2)])
def test_elastic_sharded_resume_parity_with_single_file(tmp_path, from_dev, to_dev):
    """THE elastic acceptance: a dense SGD fit killed on an N-device mesh
    with 4-host SHARDED snapshots, resumed on an M-device mesh, lands on
    the exact coefficients of the same kill/resume through the
    single-file path — the sharded transport is lossless end to end."""
    import jax

    from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD
    from flink_ml_tpu.parallel import mesh as mesh_lib

    rng = np.random.RandomState(4)
    X = rng.randn(384, 8).astype(np.float32)
    y = (X @ np.linspace(1, -1, 8) > 0).astype(np.float32)

    def fit_on(n_dev, ckpt, max_iter):
        mesh = mesh_lib.create_mesh(("data",), devices=jax.devices()[:n_dev])
        with mesh_lib.use_mesh(mesh):
            return SGD(
                max_iter=max_iter, global_batch_size=96, tol=0.0,
                checkpoint_dir=ckpt, checkpoint_key="el",
            ).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)

    single = str(tmp_path / "single")
    with faults.inject("chunk", after=6):
        with pytest.raises(InjectedFault):
            fit_on(from_dev, single, 12)
    single_coeff, _, single_epochs = fit_on(to_dev, single, 12)

    sharded = str(tmp_path / "sharded")
    with config.snapshot_hosts_mode(4):
        with faults.inject("chunk", after=6):
            with pytest.raises(InjectedFault):
                fit_on(from_dev, sharded, 12)
        assert coordinator.has_sharded(sharded, "el")
        sharded_coeff, _, sharded_epochs = fit_on(to_dev, sharded, 12)
    assert single_epochs == sharded_epochs == 12
    np.testing.assert_array_equal(
        np.asarray(sharded_coeff), np.asarray(single_coeff)
    )


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

def test_sharded_counters(tmp_path):
    before_shards = metrics.get_counter("checkpoint.shard.count", 0)
    before_manifests = metrics.get_counter("checkpoint.manifest.count", 0)
    before_count = metrics.get_counter("checkpoint.count", 0)
    _save(tmp_path, epoch=1)
    assert metrics.get_counter("checkpoint.shard.count", 0) == before_shards + 4
    assert (
        metrics.get_counter("checkpoint.manifest.count", 0)
        == before_manifests + 1
    )
    assert metrics.get_counter("checkpoint.count", 0) == before_count + 1
    assert metrics.get_counter("checkpoint.shard.bytes", 0) > 0


# ---------------------------------------------------------------------------
# unbounded (online) loop: sharded resume + completion purge
# ---------------------------------------------------------------------------

def test_online_unbounded_sharded_resume_and_completion_purge(tmp_path):
    """The online loop under sharded snapshots: a kill between global
    batches resumes from the committed cut (replayed prefix skipped), and
    a COMPLETED stream purges every sharded file so a new job cannot
    resume past a finished run."""
    from flink_ml_tpu.parallel.iteration import iterate_unbounded

    jnp = _jnp()
    d = str(tmp_path / "online")
    batches = [np.full(3, float(i)) for i in range(1, 6)]

    def run(n_batches=5):
        return list(
            iterate_unbounded(
                iter(batches[:n_batches]),
                lambda s, b: s + jnp.asarray(b),
                jnp.zeros(3),
                checkpoint_dir=d,
                job_key="ol",
            )
        )

    expected = [np.asarray(s) for _, s in run()]  # uninterrupted (and purged)
    assert coordinator.committed_cuts(d, "ol") == []  # completion purge

    with config.snapshot_hosts_mode(2):
        with faults.inject("batch", after=3):
            with pytest.raises(InjectedFault):
                run()
        assert coordinator.committed_cuts(d, "ol") != []
        versions_states = run()
    # the restored version is republished first, then the remainder folds
    assert versions_states[0][0] == 3
    np.testing.assert_array_equal(
        np.asarray(versions_states[-1][1]), expected[-1]
    )
    assert versions_states[-1][0] == 5
    # completed again: every sharded file purged
    assert coordinator.committed_cuts(d, "ol") == []
    assert not any(
        n.startswith("snap-ol.") for n in os.listdir(d)
    )
