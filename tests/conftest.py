"""Test harness configuration.

Runs the whole test suite on a virtual 8-device CPU mesh
(XLA_FLAGS=--xla_force_host_platform_device_count=8) — the analogue of the
reference's in-JVM MiniCluster test substrate (SURVEY.md §4): collectives,
sharding, and iteration paths execute multi-device without TPU hardware.
Must set env vars before jax initializes, hence the top-of-file placement.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon sitecustomize calls jax.config.update("jax_platforms", "axon,cpu")
# at interpreter start, which wins over the env var — override it back.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Concurrency sanitizer (docs/static_analysis.md): FLINK_ML_TPU_SANITIZE=1
# wraps every flow-layer lock/channel/worker and fails the session on
# recorded lock-order cycles, leaked workers, or unclosed pump channels —
# the runtime cross-check of the static lock-order/channel-protocol rules.
from flink_ml_tpu.analysis import sanitizer  # noqa: E402

if sanitizer.enabled_by_env():
    sanitizer.enable(register_atexit=False)


def pytest_sessionfinish(session, exitstatus):
    if not (sanitizer.enabled_by_env() and exitstatus == 0):
        return
    problems = sanitizer.recorder.problems()
    sanitizer.mark_exit_checked()
    reporter = session.config.pluginmanager.get_plugin("terminalreporter")
    emit = reporter.write_line if reporter else print
    if problems:
        for problem in problems:
            emit(f"FLINK_ML_TPU_SANITIZE: {problem}")
        session.exitstatus = 1
    else:
        stats = sanitizer.recorder.stats()
        emit(
            "FLINK_ML_TPU_SANITIZE: clean — "
            f"{stats['acquisitions']} acquisitions, {stats['workers']} workers, "
            f"{stats['channelsClosed']}/{stats['channels']} channels closed, "
            f"{stats['collectives']} collectives in {stats['collectiveGroups']} "
            "scope group(s)"
        )


@pytest.fixture
def mesh8():
    from flink_ml_tpu.parallel import mesh as mesh_lib

    m = mesh_lib.create_mesh((mesh_lib.DATA_AXIS,))
    with mesh_lib.use_mesh(m):
        yield m


@pytest.fixture
def mesh_2d():
    """4x2 (data, model) mesh for feature-sharded tests."""
    from flink_ml_tpu.parallel import mesh as mesh_lib

    m = mesh_lib.create_mesh(
        (mesh_lib.DATA_AXIS, mesh_lib.MODEL_AXIS), shape=(4, 2)
    )
    with mesh_lib.use_mesh(m):
        yield m


@pytest.fixture(autouse=True)
def _reset_default_mesh():
    from flink_ml_tpu.parallel import mesh as mesh_lib

    yield
    mesh_lib.set_default_mesh(None)
