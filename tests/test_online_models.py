"""Online-algorithm batteries — mirror OnlineKMeansTest.java and
OnlineLogisticRegressionTest.java: per-batch model versions, decayed
centroid updates, FTRL convergence, save/load."""

import numpy as np
import pytest

from flink_ml_tpu.table import StreamTable, Table
from flink_ml_tpu.models.classification.logisticregression import LogisticRegression
from flink_ml_tpu.models.classification.onlinelogisticregression import (
    OnlineLogisticRegression,
    OnlineLogisticRegressionModel,
)
from flink_ml_tpu.models.clustering.kmeans import KMeans
from flink_ml_tpu.models.clustering.onlinekmeans import (
    OnlineKMeans,
    OnlineKMeansModel,
    generate_random_model_data,
)


def _blob_batches(num_batches, batch_size, seed=0):
    rng = np.random.RandomState(seed)
    batches = []
    for _ in range(num_batches):
        a = rng.randn(batch_size // 2, 2) * 0.1 + [0, 0]
        b = rng.randn(batch_size // 2, 2) * 0.1 + [10, 10]
        batches.append(Table({"features": np.vstack([a, b])}))
    return batches


class TestOnlineKMeans:
    def test_requires_stream_and_init(self):
        with pytest.raises(TypeError):
            OnlineKMeans().set_initial_model_data(
                generate_random_model_data(2, 2, 1.0)
            ).fit(Table({"features": [[0.0, 0.0]]}))
        with pytest.raises(ValueError):
            OnlineKMeans().fit(StreamTable.from_batches([]))

    def test_online_updates_and_versions(self):
        batches = _blob_batches(4, 10)
        okm = (
            OnlineKMeans()
            .set_global_batch_size(10)
            .set_initial_model_data(generate_random_model_data(2, 2, 0.0, seed=5))
        )
        model = okm.fit(StreamTable.from_batches(batches))
        assert model.model_version == 0
        model.process_updates(max_batches=1)
        assert model.model_version == 1
        model.process_updates()
        assert model.model_version == 4
        # centroids converge near the blob centers
        sorted_c = model.centroids[np.argsort(model.centroids[:, 0])]
        np.testing.assert_allclose(sorted_c[0], [0, 0], atol=0.5)
        np.testing.assert_allclose(sorted_c[1], [10, 10], atol=0.5)
        out = model.transform(Table({"features": [[0.1, 0.0], [9.9, 10.0]]}))[0]
        pred = np.asarray(out.column("prediction"))
        assert pred[0] != pred[1]

    def test_init_from_batch_kmeans(self):
        t = Table({"features": np.vstack([np.zeros((5, 2)), np.ones((5, 2)) * 10])})
        batch_model = KMeans().set_seed(1).fit(t)
        okm = (
            OnlineKMeans()
            .set_global_batch_size(10)
            .set_initial_model_data(batch_model.get_model_data()[0])
        )
        model = okm.fit(StreamTable.from_batches(_blob_batches(2, 10)))
        model.process_updates()
        assert model.model_version == 2

    def test_decay_factor_full_forget(self):
        # decay 0 -> old centroids forgotten when a batch hits the cluster
        okm = (
            OnlineKMeans()
            .set_global_batch_size(4)
            .set_decay_factor(0.0)
            .set_initial_model_data(generate_random_model_data(2, 2, 100.0, seed=3))
        )
        batch = Table({"features": [[0.0, 0.0], [0.1, 0.1], [10.0, 10.0], [10.1, 10.1]]})
        model = okm.fit(StreamTable.from_batches([batch]))
        model.process_updates()
        sorted_c = model.centroids[np.argsort(model.centroids[:, 0])]
        np.testing.assert_allclose(sorted_c[0], [0.05, 0.05], atol=0.2)

    def test_save_load(self, tmp_path):
        okm = (
            OnlineKMeans()
            .set_global_batch_size(10)
            .set_initial_model_data(generate_random_model_data(2, 2, 0.0, seed=5))
        )
        model = okm.fit(StreamTable.from_batches(_blob_batches(2, 10)))
        model.process_updates()
        model.save(str(tmp_path / "okm"))
        loaded = OnlineKMeansModel.load(str(tmp_path / "okm"))
        np.testing.assert_allclose(loaded.centroids, model.centroids)
        assert loaded.model_version == 2


def _classification_batches(num_batches, batch_size, dim=4, seed=0):
    rng = np.random.RandomState(seed)
    truth = np.linspace(1, -1, dim)
    batches = []
    for _ in range(num_batches):
        X = rng.randn(batch_size, dim)
        y = (X @ truth > 0).astype(np.float64)
        batches.append(Table({"features": X, "label": y}))
    return batches


class TestOnlineLogisticRegression:
    def _initial_model(self, dim=4):
        from flink_ml_tpu.linalg import DenseVector

        return Table({"coefficient": [DenseVector(np.zeros(dim))]})

    def test_param_defaults(self):
        olr = OnlineLogisticRegression()
        assert olr.get_alpha() == 0.1
        assert olr.get_beta() == 0.1
        assert olr.get_batch_strategy() == "count"

    def test_online_training_improves(self):
        batches = _classification_batches(30, 32)
        olr = (
            OnlineLogisticRegression()
            .set_global_batch_size(32)
            .set_initial_model_data(self._initial_model())
        )
        model = olr.fit(StreamTable.from_batches(batches))
        model.process_updates()
        assert model.model_version == 30
        test = _classification_batches(1, 200, seed=99)[0]
        out = model.transform(test)[0]
        acc = (np.asarray(out.column("prediction")) == np.asarray(test.column("label"))).mean()
        assert acc > 0.9, acc
        # model version column attached (OnlineLogisticRegressionModel.java:133)
        assert np.all(np.asarray(out.column("modelVersion")) == 30)

    def test_version_increments_per_batch(self):
        olr = (
            OnlineLogisticRegression()
            .set_global_batch_size(8)
            .set_initial_model_data(self._initial_model())
        )
        model = olr.fit(StreamTable.from_batches(_classification_batches(3, 8)))
        versions = []
        for _ in range(3):
            model.process_updates(max_batches=1)
            versions.append(model.model_version)
        assert versions == [1, 2, 3]

    def test_regularization_sparsifies(self):
        batches = _classification_batches(20, 32)
        olr = (
            OnlineLogisticRegression()
            .set_global_batch_size(32)
            .set_reg(2.0)
            .set_elastic_net(1.0)  # pure l1
            .set_initial_model_data(self._initial_model())
        )
        model = olr.fit(StreamTable.from_batches(batches))
        model.process_updates()
        assert np.sum(model.coefficient == 0.0) > 0

    def test_save_load(self, tmp_path):
        olr = (
            OnlineLogisticRegression()
            .set_global_batch_size(8)
            .set_initial_model_data(self._initial_model())
        )
        model = olr.fit(StreamTable.from_batches(_classification_batches(2, 8)))
        model.process_updates()
        model.save(str(tmp_path / "olr"))
        loaded = OnlineLogisticRegressionModel.load(str(tmp_path / "olr"))
        np.testing.assert_allclose(loaded.coefficient, model.coefficient)
        assert loaded.model_version == 2

    def test_init_from_batch_lr(self):
        t = _classification_batches(1, 100)[0]
        batch_model = LogisticRegression().set_max_iter(10).fit(t)
        olr = (
            OnlineLogisticRegression()
            .set_global_batch_size(16)
            .set_initial_model_data(batch_model.get_model_data()[0])
        )
        model = olr.fit(StreamTable.from_batches(_classification_batches(2, 16)))
        model.process_updates()
        assert model.model_version == 2
