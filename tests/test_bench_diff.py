"""bench_diff regression gate (scripts/bench_diff.py) — file-shape
normalization (headline / driver wrapper / truncated-tail recovery),
direction + threshold policy, and the two acceptance cases: the
synthetic 20% wallMs regression exits nonzero, the real checked-in
BENCH_r04 -> BENCH_r05 pair exits zero."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXDIR = os.path.join(_ROOT, "tests", "fixtures", "bench_diff")


def _load_module():
    spec = importlib.util.spec_from_file_location(
        "bench_diff", os.path.join(_ROOT, "scripts", "bench_diff.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench_diff = _load_module()


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join(_ROOT, "scripts", "bench_diff.py"), *args],
        capture_output=True,
        text=True,
        cwd=_ROOT,
    )


# ---------------------------------------------------------------------------
# policy units
# ---------------------------------------------------------------------------

def test_metric_directions():
    assert bench_diff.metric_direction("totalTimeMs") == "lower"
    assert bench_diff.metric_direction("wallMs") == "lower"
    assert bench_diff.metric_direction("epochMsAmortized") == "lower"
    assert bench_diff.metric_direction("hostSyncCount") == "lower"
    assert bench_diff.metric_direction("relDiff") == "lower"
    # the elastic supervisor's SLO leaves (ISSUE 15): detection latency
    # and recovery wall regress upward
    assert bench_diff.metric_direction("elasticRecovery.detectionMs") == "lower"
    assert bench_diff.metric_direction("elasticRecovery.recoveryWallMs") == "lower"
    assert bench_diff.metric_direction("inputThroughput") == "higher"
    assert bench_diff.metric_direction("trainedExamplesPerSec") == "higher"
    assert bench_diff.metric_direction("trainLoopMFU_trace") == "higher"
    assert bench_diff.metric_direction("vsPublishedBaseline") == "higher"
    assert bench_diff.metric_direction("numChips") is None
    assert bench_diff.metric_direction("h2dBytes") is None  # info by default
    # the whole-fit dispatch gate: sync/dispatch counts and host-dispatch
    # wall are direction-gated, so a resident-path regression fails CI
    assert bench_diff.metric_direction("hostDispatchMs") == "lower"
    assert bench_diff.metric_direction("dispatchCount") == "lower"
    assert bench_diff.metric_direction("wholeFitFallbacks") == "lower"
    # the chunked reference side of wholeFitDispatch is informational
    assert bench_diff.metric_direction("hostSyncCountChunked") is None
    assert bench_diff.metric_direction("dispatchCountChunked") is None
    # device-memory leaves (the HBM ledger, ISSUE 16): a fit holding more
    # HBM or a fatter resident model regresses upward
    assert bench_diff.metric_direction("peakHbmBytes") == "lower"
    assert bench_diff.metric_direction("residentModelBytes") == "lower"
    assert bench_diff.metric_direction("kmeans.peakHbmBytes") == "lower"
    # AOT program bank (docs/performance.md §12): a slower banked cold
    # start or any miss on the declared program space gates by default
    assert bench_diff.metric_direction("aotColdStart.coldStartMs") == "lower"
    assert bench_diff.metric_direction("aotColdStart.baselineColdStartMs") == "lower"
    assert bench_diff.metric_direction("aotColdStart.bankMisses") == "lower"


def test_hbm_memory_regression_fails_gate():
    """A fit whose peak HBM footprint doubles must REGRESS at the default
    threshold — memory is gated like latency, no explicit --rule needed."""
    rows = bench_diff.diff_entries(
        {"lr": {"peakHbmBytes": 1_000_000.0, "residentModelBytes": 4096.0}},
        {"lr": {"peakHbmBytes": 2_000_000.0, "residentModelBytes": 4096.0}},
        0.15,
        [],
    )
    verdicts = {r["path"]: r["verdict"] for r in rows}
    assert verdicts["lr.peakHbmBytes"] == "REGRESSED"
    assert verdicts["lr.residentModelBytes"] == "ok"
    # shrinking memory is an improvement, never a regression
    improved = bench_diff.diff_entries(
        {"lr": {"peakHbmBytes": 2_000_000.0}},
        {"lr": {"peakHbmBytes": 1_000_000.0}},
        0.15,
        [],
    )
    assert improved[0]["verdict"] != "REGRESSED"


def test_whole_fit_dispatch_regressions_fail_gate():
    """A whole-fit entry whose fit stops being resident (hostSyncCount
    1 -> 61, dispatchCount 1 -> 60, hostDispatchMs up) must REGRESS even
    at the default threshold — these leaves are gated by direction, no
    explicit --rule needed."""
    old = {
        "wholeFitDispatch": {
            "hostSyncCount": 1.0,
            "dispatchCount": 1.0,
            "hostDispatchMs": 6.0,
        }
    }
    new = {
        "wholeFitDispatch": {
            "hostSyncCount": 61.0,
            "dispatchCount": 60.0,
            "hostDispatchMs": 300.0,
        }
    }
    rows = bench_diff.diff_entries(old, new, 0.15, [])
    verdicts = {r["path"]: r["verdict"] for r in rows}
    assert verdicts["wholeFitDispatch.hostSyncCount"] == "REGRESSED"
    assert verdicts["wholeFitDispatch.dispatchCount"] == "REGRESSED"
    assert verdicts["wholeFitDispatch.hostDispatchMs"] == "REGRESSED"
    # the zero-tolerance CI rule pins hostSyncCount exactly
    strict = bench_diff.diff_entries(
        {"wholeFitDispatch": {"hostSyncCount": 1.0}},
        {"wholeFitDispatch": {"hostSyncCount": 2.0}},
        0.15,
        [("*.hostSyncCount", 0.0)],
    )
    assert strict[0]["verdict"] == "REGRESSED"


def test_multihost_checkpoint_gating_directions():
    """multiHostCheckpoint (ISSUE 14): the per-host-count save walls and
    the kill@commit resume wall are direction-gated (lower); shard sizing
    is informational (bytes-per-host is a layout fact, not a speed)."""
    assert (
        bench_diff.metric_direction("multiHostCheckpoint.host4.savePerEpochMs")
        == "lower"
    )
    assert (
        bench_diff.metric_direction("multiHostCheckpoint.resumeWallMs")
        == "lower"
    )
    assert (
        bench_diff.metric_direction("multiHostCheckpoint.host4.shardBytesPerHost")
        is None
    )
    old = {
        "multiHostCheckpoint": bench_diff.flatten(
            {
                "host4": {"savePerEpochMs": 20.0, "shardBytesPerHost": 300.0},
                "resumeWallMs": 100.0,
            }
        )
    }
    new = {
        "multiHostCheckpoint": bench_diff.flatten(
            {
                "host4": {"savePerEpochMs": 30.0, "shardBytesPerHost": 600.0},
                "resumeWallMs": 150.0,
            }
        )
    }
    rows = bench_diff.diff_entries(old, new, 0.15, [])
    verdicts = {r["path"]: r["verdict"] for r in rows}
    assert verdicts["multiHostCheckpoint.host4.savePerEpochMs"] == "REGRESSED"
    assert verdicts["multiHostCheckpoint.resumeWallMs"] == "REGRESSED"
    assert verdicts["multiHostCheckpoint.host4.shardBytesPerHost"] == "info"


def test_cold_time_informational_by_default():
    rows = bench_diff.diff_entries(
        {"e": {"coldTimeMs": 100.0}}, {"e": {"coldTimeMs": 200.0}}, 0.15, []
    )
    assert rows[0]["verdict"] == "info"
    # ...unless an explicit rule gates it
    rows = bench_diff.diff_entries(
        {"e": {"coldTimeMs": 100.0}},
        {"e": {"coldTimeMs": 200.0}},
        0.15,
        [("e.coldTimeMs", 0.5)],
    )
    assert rows[0]["verdict"] == "REGRESSED"


def test_threshold_and_direction_semantics():
    old = {"e": {"totalTimeMs": 100.0, "inputThroughput": 1000.0}}
    ok = {"e": {"totalTimeMs": 110.0, "inputThroughput": 900.0}}
    bad = {"e": {"totalTimeMs": 130.0, "inputThroughput": 700.0}}
    rows = {r["path"]: r for r in bench_diff.diff_entries(old, ok, 0.15, [])}
    assert rows["e.totalTimeMs"]["verdict"] == "ok"
    assert rows["e.inputThroughput"]["verdict"] == "ok"
    rows = {r["path"]: r for r in bench_diff.diff_entries(old, bad, 0.15, [])}
    assert rows["e.totalTimeMs"]["verdict"] == "REGRESSED"
    assert rows["e.inputThroughput"]["verdict"] == "REGRESSED"
    # improvements never fail
    better = {"e": {"totalTimeMs": 50.0, "inputThroughput": 2000.0}}
    rows = bench_diff.diff_entries(old, better, 0.15, [])
    assert all(r["verdict"] == "improved" for r in rows)


def test_small_time_jitter_not_gated():
    rows = bench_diff.diff_entries(
        {"e": {"fitTimeMs": 1.0}}, {"e": {"fitTimeMs": 3.0}}, 0.15, []
    )
    assert rows[0]["verdict"] == "ok"  # below the 5ms jitter floor


def test_cpu_baseline_entry_informational():
    rows = bench_diff.diff_entries(
        {"cpuBaseline": {"totalTimeMs": 20000.0}},
        {"cpuBaseline": {"totalTimeMs": 90000.0}},
        0.15,
        [],
    )
    assert rows[0]["verdict"] == "info"  # host speed is not our regression


# ---------------------------------------------------------------------------
# normalization + recovery
# ---------------------------------------------------------------------------

def test_normalize_headline_and_wrapper():
    headline = {"value": 1.0, "vs_baseline": 2.0, "details": {"kmeans": {"totalTimeMs": 5.0}}}
    entries = bench_diff.normalize(headline)
    assert entries["headline"] == {"value": 1.0, "vs_baseline": 2.0}
    assert entries["kmeans"]["totalTimeMs"] == 5.0
    wrapper = {"n": 1, "cmd": "x", "rc": 0, "tail": "", "parsed": headline}
    assert bench_diff.normalize(wrapper) == entries


def test_tail_recovery_outermost_fragments():
    """A truncated driver tail (headline JSON cut mid-line) still yields
    the complete per-entry fragments — outermost only, so a nested dict
    inside a recovered entry is not double-reported."""
    tail = (
        '4810.43, "unit": "records/s/chip", "det'  # cut headline
        '"kmeans": {"coldTimeMs": 800.0, "totalTimeMs": 200.0, '
        '"inner": {"x": 1.0}}, '
        '"sweep": {"file": "benchmarks/SWEEP.json"}'
    )
    wrapper = {"n": 5, "cmd": "x", "rc": 0, "tail": tail, "parsed": None}
    entries = bench_diff.normalize(wrapper)
    assert "kmeans" in entries
    assert entries["kmeans"]["totalTimeMs"] == 200.0
    assert "inner" not in entries  # nested fragment folded into kmeans
    assert "sweep" not in entries  # no numeric leaves -> not an entry


def test_real_r05_tail_recovers_entries():
    with open(os.path.join(_ROOT, "BENCH_r05.json")) as f:
        entries = bench_diff.normalize(json.load(f))
    assert "sparseWideLR" in entries and "kmeans" in entries
    assert entries["kmeans"]["totalTimeMs"] > 0


def test_flatten_skips_registry_and_bounds_depth():
    entry = {
        "totalTimeMs": 5.0,
        "ok": True,
        "metrics": {"counters": {"x": 1}},
        "dispatchAttribution": {"windowMs": 4.0, "perEpoch": {"wallMs": 1.0}},
    }
    flat = bench_diff.flatten(entry)
    assert flat["totalTimeMs"] == 5.0
    assert "ok" not in flat  # bools are not metrics
    assert not any(k.startswith("metrics") for k in flat)
    assert flat["dispatchAttribution.windowMs"] == 4.0


# ---------------------------------------------------------------------------
# acceptance: CLI exit codes
# ---------------------------------------------------------------------------

def test_cli_synthetic_20pct_wallms_regression_exits_nonzero():
    out = _run_cli(
        os.path.join(_FIXDIR, "BENCH_base.json"),
        os.path.join(_FIXDIR, "BENCH_regressed.json"),
        "--check",
    )
    assert out.returncode == 1, out.stdout + out.stderr
    assert "REGRESSED" in out.stdout
    assert "wallMs" in out.stdout


def test_cli_real_r04_r05_pair_exits_zero():
    out = _run_cli("BENCH_r04.json", "BENCH_r05.json", "--check")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 regression(s)" in out.stdout


def test_cli_json_format_and_rules():
    out = _run_cli(
        os.path.join(_FIXDIR, "BENCH_base.json"),
        os.path.join(_FIXDIR, "BENCH_regressed.json"),
        "--format", "json",
        "--rule", "logisticregressionTrace.*=0.5",
    )
    assert out.returncode == 0, out.stdout + out.stderr  # 20% < 50% override
    doc = json.loads(out.stdout)
    assert doc["regressions"] == 0
    assert any(r["path"] == "logisticregressionTrace.wallMs" for r in doc["rows"])


def test_cli_latest_pair_and_usage_errors(tmp_path):
    for name, wall in (("BENCH_r01.json", 100.0), ("BENCH_r02.json", 101.0)):
        with open(tmp_path / name, "w") as f:
            json.dump({"e": {"totalTimeMs": wall}}, f)
    out = _run_cli("--latest", "--dir", str(tmp_path))
    assert out.returncode == 0, out.stdout + out.stderr
    assert "BENCH_r01.json" in out.stdout and "BENCH_r02.json" in out.stdout
    assert _run_cli().returncode == 0  # no args -> usage text, rc 0
    assert _run_cli("only_one.json").returncode == 2
    assert _run_cli("missing_a.json", "missing_b.json").returncode == 2


def test_aot_cold_start_regressions_fail_gate():
    """A banked cold start that slows past threshold, or any bank miss
    appearing on the declared program space, must REGRESS by default;
    the CI --rule pins serveTraceCount at exactly zero (the no-compile
    serving SLA, docs/performance.md §12)."""
    rows = bench_diff.diff_entries(
        {"aotColdStart": {"coldStartMs": 400.0, "bankMisses": 0.0}},
        {"aotColdStart": {"coldStartMs": 900.0, "bankMisses": 2.0}},
        0.15,
        [],
    )
    verdicts = {r["path"]: r["verdict"] for r in rows}
    assert verdicts["aotColdStart.coldStartMs"] == "REGRESSED"
    assert verdicts["aotColdStart.bankMisses"] == "REGRESSED"
    strict = bench_diff.diff_entries(
        {"aotColdStart": {"serveTraceCount": 0.0}},
        {"aotColdStart": {"serveTraceCount": 1.0}},
        0.15,
        [("aotColdStart.serveTraceCount", 0.0)],
    )
    assert strict[0]["verdict"] == "REGRESSED"
