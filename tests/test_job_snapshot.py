"""JobSnapshot format battery (flink_ml_tpu/ckpt/snapshot.py): roundtrip
fidelity, the atomicity contract under torn writes (kill injected DURING a
save leaves the previous snapshot intact and restorable), format
versioning, the foreign-job guards, one-way legacy migration, elastic
re-staging across meshes, and the checkpoint.* observability."""

import json
import os
import warnings

import numpy as np
import pytest

from flink_ml_tpu.ckpt import (
    InjectedFault,
    faults,
    load_job_snapshot,
    save_job_snapshot,
    snapshot_file,
    stage_section,
)


def _jnp():
    import jax.numpy as jnp

    return jnp


# ---------------------------------------------------------------------------
# format roundtrip
# ---------------------------------------------------------------------------

def test_roundtrip_multisection(tmp_path):
    jnp = _jnp()
    model = (jnp.arange(6, dtype=jnp.float32), np.float64([1.5, -2.5]), jnp.asarray(3, jnp.int32))
    rng = (np.arange(8, dtype=np.uint32),)
    target = save_job_snapshot(
        str(tmp_path),
        "job-a",
        {"model": model, "rng": rng},
        epoch=4,
        criteria=0.125,
        specs={"model": ("replicated", "replicated", "replicated"), "rng": "host"},
        meta={"numBatches": 7, "streamOffset": 4},
    )
    assert os.path.basename(target) == "snap-job-a.npz"

    template = (jnp.zeros(6, jnp.float32), np.zeros(2), jnp.asarray(0, jnp.int32))
    snap = load_job_snapshot(str(tmp_path), "job-a", templates={"model": template})
    assert snap is not None
    assert (snap.epoch, snap.criteria) == (4, 0.125)
    assert snap.meta == {"numBatches": 7, "streamOffset": 4}
    assert snap.specs["rng"] == ("host",)
    c, f64, e = snap.sections["model"]
    np.testing.assert_array_equal(c, np.arange(6, dtype=np.float32))
    assert f64.dtype == np.float64  # cast back to the template's dtype
    np.testing.assert_array_equal(f64, [1.5, -2.5])
    assert int(e) == 3
    # untemplated section comes back as a flat leaf list
    np.testing.assert_array_equal(snap.sections["rng"][0], rng[0])


def test_save_gathers_device_leaves_in_one_sync(tmp_path):
    from flink_ml_tpu.utils import metrics

    jnp = _jnp()
    before = metrics.get_counter("iteration.host_sync.checkpoint")
    save_job_snapshot(
        str(tmp_path), "k", {"model": (jnp.zeros(4), jnp.ones(3))}, epoch=1
    )
    assert metrics.get_counter("iteration.host_sync.checkpoint") == before + 1


# ---------------------------------------------------------------------------
# atomicity: torn writes
# ---------------------------------------------------------------------------

def test_torn_save_leaves_previous_snapshot_intact(tmp_path):
    jnp = _jnp()
    template = jnp.zeros(5)
    save_job_snapshot(str(tmp_path), "j", {"model": jnp.arange(5.0)}, epoch=1)

    with faults.inject("snapshot.write"):
        with pytest.raises(InjectedFault):
            save_job_snapshot(
                str(tmp_path), "j", {"model": jnp.arange(5.0) * 10}, epoch=2
            )
    snap = load_job_snapshot(str(tmp_path), "j", templates={"model": template})
    assert snap.epoch == 1  # the committed snapshot, not the torn one
    np.testing.assert_array_equal(snap.sections["model"], np.arange(5.0, dtype=np.float32))

    # the writer recovers: the next save overwrites the stale temp file
    save_job_snapshot(str(tmp_path), "j", {"model": jnp.arange(5.0) * 10}, epoch=2)
    snap = load_job_snapshot(str(tmp_path), "j", templates={"model": template})
    assert snap.epoch == 2
    np.testing.assert_array_equal(
        snap.sections["model"], 10 * np.arange(5.0, dtype=np.float32)
    )


def test_kill_during_snapshot_save_resumes_from_previous(tmp_path):
    """Satellite: a fit killed DURING a snapshot write (after the temp
    file, before the atomic rename) resumes from the previous epoch's
    snapshot and still lands on the uninterrupted run's exact model."""
    from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD

    rng = np.random.RandomState(3)
    X = rng.randn(300, 6).astype(np.float32)
    y = (X @ np.linspace(1, -1, 6) > 0).astype(np.float32)

    def fit(ckpt=None):
        sgd = SGD(
            max_iter=12, global_batch_size=100, tol=0.0,
            checkpoint_dir=ckpt, checkpoint_key="torn",
        )
        return sgd.optimize(np.zeros(6), X, y, None, BINARY_LOGISTIC_LOSS)

    ckpt = str(tmp_path / "ckpt")
    expected, _, _ = fit(ckpt)  # uninterrupted reference (chunked layout)
    os.remove(snapshot_file(ckpt, "torn"))

    with faults.inject("snapshot.write", after=5):
        with pytest.raises(InjectedFault):
            fit(ckpt)
    # epoch-5's write tore; epoch 4's snapshot must still be restorable
    import jax.numpy as jnp

    template = (jnp.zeros(6), jnp.zeros(6), jnp.asarray(0.0), jnp.asarray(0))
    snap = load_job_snapshot(ckpt, "torn", templates={"model": template})
    assert snap is not None and snap.epoch == 4

    resumed, _, epochs = fit(ckpt)
    assert epochs == 12
    np.testing.assert_array_equal(np.asarray(resumed), np.asarray(expected))


# ---------------------------------------------------------------------------
# guards: versioning, structure, meta cursors
# ---------------------------------------------------------------------------

def _rewrite_manifest(file, mutate):
    with np.load(file) as f:
        arrays = {k: f[k] for k in f.files}
    manifest = json.loads(str(arrays.pop("manifest")))
    mutate(manifest)
    np.savez(file, manifest=np.asarray(json.dumps(manifest)), **arrays)


def test_future_format_version_refused(tmp_path):
    jnp = _jnp()
    file = save_job_snapshot(str(tmp_path), "v", {"model": jnp.zeros(3)}, epoch=2)
    _rewrite_manifest(file, lambda m: m.update(version=99))
    with pytest.warns(UserWarning, match="format version 99"):
        snap = load_job_snapshot(str(tmp_path), "v", templates={"model": jnp.zeros(3)})
    assert snap is None


def test_foreign_structure_refused(tmp_path):
    jnp = _jnp()
    save_job_snapshot(str(tmp_path), "s", {"model": jnp.zeros(4)}, epoch=1)
    with pytest.warns(UserWarning, match="structurally incompatible"):
        snap = load_job_snapshot(str(tmp_path), "s", templates={"model": jnp.zeros(5)})
    assert snap is None


def test_meta_cursor_mismatch_refused(tmp_path):
    jnp = _jnp()
    save_job_snapshot(
        str(tmp_path), "m", {"model": jnp.zeros(4)}, epoch=1, meta={"numBatches": 10}
    )
    with pytest.warns(UserWarning, match="numBatches"):
        snap = load_job_snapshot(
            str(tmp_path),
            "m",
            templates={"model": jnp.zeros(4)},
            expect_meta={"numBatches": 7},
        )
    assert snap is None
    # matching cursors restore fine
    snap = load_job_snapshot(
        str(tmp_path),
        "m",
        templates={"model": jnp.zeros(4)},
        expect_meta={"numBatches": 10},
    )
    assert snap is not None


def test_unkeyed_restore_warns_keyed_does_not(tmp_path):
    jnp = _jnp()
    save_job_snapshot(str(tmp_path), None, {"model": jnp.zeros(2)}, epoch=1)
    with pytest.warns(UserWarning, match="un-keyed"):
        assert load_job_snapshot(str(tmp_path), None, templates={"model": jnp.zeros(2)})
    save_job_snapshot(str(tmp_path), "keyed", {"model": jnp.zeros(2)}, epoch=1)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert load_job_snapshot(
            str(tmp_path), "keyed", templates={"model": jnp.zeros(2)}
        )


# ---------------------------------------------------------------------------
# legacy migration (one-way)
# ---------------------------------------------------------------------------

def test_legacy_checkpoint_reads_through_snapshot_loader(tmp_path):
    from flink_ml_tpu.parallel.iteration import save_iteration_checkpoint

    jnp = _jnp()
    carry = (jnp.asarray([1.0, 2.0]), jnp.asarray(7, jnp.int32))
    save_iteration_checkpoint(str(tmp_path), carry, epoch=3, criteria=0.5, job_key="lg")
    snap = load_job_snapshot(str(tmp_path), "lg", templates={"model": carry})
    assert snap is not None
    assert (snap.epoch, snap.criteria) == (3, 0.5)
    assert snap.version == 0  # pre-JobSnapshot
    assert snap.meta["migratedFrom"].startswith("ckpt-")
    np.testing.assert_array_equal(snap.sections["model"][0], [1.0, 2.0])


def test_legacy_sgd_checkpoint_resumes_and_migrates(tmp_path):
    """A checkpoint_dir left behind by the pre-JobSnapshot carry-only
    writer resumes (instead of restarting) and the resumed run's next
    save writes the NEW format — one-way migration."""
    from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD
    from flink_ml_tpu.parallel.iteration import save_iteration_checkpoint

    rng = np.random.RandomState(5)
    X = rng.randn(300, 6).astype(np.float32)
    y = (X @ np.linspace(-1, 1, 6) > 0).astype(np.float32)

    def fit(ckpt, max_iter):
        sgd = SGD(
            max_iter=max_iter, global_batch_size=100, tol=0.0,
            checkpoint_dir=ckpt, checkpoint_key="mig",
        )
        return sgd.optimize(np.zeros(6), X, y, None, BINARY_LOGISTIC_LOSS)

    ref_dir = str(tmp_path / "ref")
    expected, _, _ = fit(ref_dir, 15)

    # emulate the legacy layout: run to epoch 6, convert the snapshot to
    # the old carry-only file, and delete the new-format file
    leg_dir = str(tmp_path / "legacy")
    fit(leg_dir, 6)
    import jax.numpy as jnp

    template = (jnp.zeros(6), jnp.zeros(6), jnp.asarray(0.0), jnp.asarray(0))
    snap = load_job_snapshot(leg_dir, "mig", templates={"model": template})
    assert snap.epoch == 6
    save_iteration_checkpoint(
        leg_dir, snap.sections["model"], snap.epoch, snap.criteria, "mig"
    )
    os.remove(snapshot_file(leg_dir, "mig"))

    resumed, _, epochs = fit(leg_dir, 15)
    assert epochs == 15
    np.testing.assert_array_equal(np.asarray(resumed), np.asarray(expected))
    assert os.path.exists(snapshot_file(leg_dir, "mig"))  # migrated forward


# ---------------------------------------------------------------------------
# elastic re-staging
# ---------------------------------------------------------------------------

def test_stage_section_reshards_onto_other_meshes(tmp_path):
    import jax

    from flink_ml_tpu.parallel import mesh as mesh_lib

    jnp = _jnp()
    coeff = jnp.arange(16.0)
    rows = jnp.arange(32.0).reshape(8, 4)
    save_job_snapshot(
        str(tmp_path),
        "el",
        {"model": (coeff, rows, np.float64(2.0))},
        epoch=1,
        specs={"model": ("replicated", "data", "host")},
    )
    snap = load_job_snapshot(
        str(tmp_path),
        "el",
        templates={"model": (jnp.zeros(16), jnp.zeros((8, 4)), np.float64(0))},
    )
    for n_dev in (1, 2, 8):
        mesh = mesh_lib.create_mesh(("data",), devices=jax.devices()[:n_dev])
        c, r, host_leaf = stage_section(snap, "model", mesh=mesh)
        assert isinstance(c, jax.Array) and isinstance(r, jax.Array)
        assert c.sharding.mesh.shape["data"] == n_dev
        assert c.sharding.spec == mesh_lib.replicated_sharding(mesh).spec
        assert r.sharding.spec == mesh_lib.data_sharding(mesh, 2).spec
        np.testing.assert_array_equal(np.asarray(c), np.arange(16.0, dtype=np.float32))
        np.testing.assert_array_equal(
            np.asarray(r), np.arange(32.0, dtype=np.float32).reshape(8, 4)
        )
        assert isinstance(host_leaf, np.ndarray)  # "host" tag stays off-device
        assert float(host_leaf) == 2.0


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

def test_checkpoint_counters_and_spans(tmp_path):
    from flink_ml_tpu.obs import tracing
    from flink_ml_tpu.utils import metrics

    jnp = _jnp()
    count0 = metrics.get_counter("checkpoint.count")
    bytes0 = metrics.get_counter("checkpoint.bytes")
    restore0 = metrics.get_counter("checkpoint.restore.count")
    tracing.configure(ring_size=64)
    try:
        save_job_snapshot(str(tmp_path), "obs", {"model": jnp.zeros(8)}, epoch=1)
        assert load_job_snapshot(
            str(tmp_path), "obs", templates={"model": jnp.zeros(8)}
        )
        names = [r["name"] for r in tracing.drain_ring()]
    finally:
        tracing.configure()
    assert "checkpoint.save" in names
    assert "checkpoint.restore" in names
    assert metrics.get_counter("checkpoint.count") == count0 + 1
    assert metrics.get_counter("checkpoint.bytes") == bytes0 + 8 * 4
    assert metrics.get_counter("checkpoint.restore.count") == restore0 + 1
