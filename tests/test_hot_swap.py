"""Versioned zero-pause model hot-swap (lifecycle.py + the fused swap
path in pipeline.py/api.py):

- swap-capable online models serve through the FUSED path and a live
  publication is zero-recompile (jit compile counter pinned across N
  swaps) with every served row stamped by exactly one version;
- an in-flight batch keeps the version it was dispatched with (no torn
  reads across a swap);
- `(version, arrays)` publication is ONE atomic reference swap (hammered
  by a concurrent trainer/server pair — sanitizer-clean under
  FLINK_ML_TPU_SANITIZE=1);
- the promotion gate refuses NaN/shape/dtype/canary-regressed candidates
  (`lifecycle.promoteRejected`), the version ring rolls back bit-exactly
  and quarantines the trainer, and the JobSnapshot meta contract makes a
  killed+resumed train-while-serve job re-publish the same version;
- the chaos soak composes ckpt fault sites with the new
  lifecycle.promote/lifecycle.swap sites — the deterministic tier-1
  variant of bench.py's `hotSwapSoak`.
"""

import numpy as np
import pytest

import jax

from flink_ml_tpu import config, flow
from flink_ml_tpu.ckpt import faults
from flink_ml_tpu.ckpt.faults import InjectedFault
from flink_ml_tpu.lifecycle import (
    ModelLifecycle,
    PromotionRejected,
    TrainerQuarantined,
)
from flink_ml_tpu.models.classification.onlinelogisticregression import (
    OnlineLogisticRegressionModel,
)
from flink_ml_tpu.models.clustering.onlinekmeans import OnlineKMeansModel
from flink_ml_tpu.models.feature.standardscaler import StandardScalerModel
from flink_ml_tpu.obs import tracing
from flink_ml_tpu.pipeline import PipelineModel
from flink_ml_tpu.serving import MicroBatchServer
from flink_ml_tpu.table import Table
from flink_ml_tpu.utils import metrics

RNG = np.random.RandomState(11)
DIM = 4


def _olr_model(coeff=None, version=0):
    m = OnlineLogisticRegressionModel()
    m.publish_model_arrays((np.zeros(DIM) if coeff is None else coeff,), version)
    m.set_features_col("features").set_prediction_col("pred")
    return m


def _scaler():
    m = StandardScalerModel()
    m.mean = np.zeros(DIM)
    m.std = np.ones(DIM)
    m.set_input_col("features").set_output_col("features")
    return m


def _device_batch(n=8, seed=0):
    rng = np.random.RandomState(seed)
    return Table({"features": jax.device_put(rng.randn(n, DIM).astype(np.float32))})


# ---------------------------------------------------------------------------
# satellite: explicit constants-cache invalidation on set_model_data
# ---------------------------------------------------------------------------

class TestModelDataVersionBump:
    def test_scaler_in_place_mutation_cannot_serve_stale_uploads(self):
        """`device_constants` is keyed on array identity, which in-place
        mutation defeats (and GC id-reuse could too). Every
        `set_model_data` now routes through an explicit version bump —
        the memoized upload refreshes even when the array OBJECTS (and
        thus their ids) are unchanged."""
        from flink_ml_tpu.linalg import DenseVector

        m = _scaler()
        mean = np.zeros(DIM)
        m.mean = mean
        before = np.asarray(m.device_constants()["mean"])
        np.testing.assert_array_equal(before, np.zeros(DIM))
        mean[:] = 5.0  # in-place: same object identity, same params version
        m.set_model_data(
            Table({"mean": [DenseVector(mean)], "std": [DenseVector(np.ones(DIM))]})
        )
        assert m.model_data_version > 0
        after = np.asarray(m.device_constants()["mean"])
        np.testing.assert_array_equal(after, np.full(DIM, 5.0))

    def test_online_model_publication_bumps_and_refreshes(self):
        m = _olr_model(np.ones(DIM), version=1)
        v0 = m.model_data_version
        c0 = np.asarray(m.device_constants()["coefficient"])
        m.publish_model_arrays((np.full(DIM, 2.0),), 2)
        assert m.model_data_version > v0
        c1 = np.asarray(m.device_constants()["coefficient"])
        np.testing.assert_array_equal(c0, np.ones(DIM))
        np.testing.assert_array_equal(c1, np.full(DIM, 2.0))


# ---------------------------------------------------------------------------
# tentpole: fused serving with live swaps — zero recompile, no torn reads
# ---------------------------------------------------------------------------

def test_fused_swap_zero_recompile_version_stamped():
    """N live publications against a served fused plan: the compiled
    program is reused (compile counter pinned), every output batch is
    scored by exactly the just-published version, and the fused plan
    object itself survives the swaps (no plan-cache thrash)."""
    model = _olr_model()
    pm = PipelineModel([_scaler(), model])
    batch = _device_batch()
    out = pm.transform(batch)[0]  # warm: compiles the segment once
    assert metrics.get_gauge("pipeline.fused_stages") == 2
    assert np.unique(np.asarray(out.column("modelVersion"))).tolist() == [0]
    plan_before = pm._fusion_plan()

    from flink_ml_tpu.linalg import DenseVector

    tracing.install_jax_hooks()
    compiles_before = metrics.get_counter("jit.compiles", 0)
    for v in range(1, 6):
        coeff = RNG.randn(DIM)
        if v % 2:  # the reference's actual publication API, live
            model.set_model_data(
                Table({"coefficient": [DenseVector(coeff)], "modelVersion": [v]})
            )
        else:
            model.publish_model_arrays((coeff,), v)
        out = pm.transform(batch)[0]
        versions = np.unique(np.asarray(out.column("modelVersion")))
        assert versions.tolist() == [v], "a served batch must carry ONE version"
        # the swap actually reached the compiled program: predictions
        # match the freshly-published coefficients
        X = np.asarray(batch.column("features"))
        want = (X.astype(np.float32) @ coeff.astype(np.float32) >= 0).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(out.column("pred")), want)
    assert metrics.get_counter("jit.compiles", 0) == compiles_before, (
        "a live model swap must not recompile the fused plan"
    )
    assert pm._fusion_plan() is plan_before, "swaps must reuse the cached plan"


def test_inflight_batch_keeps_its_dispatch_version():
    """A swap landing while a batch sits in the serving window must not
    rewrite that batch: each batch retires with the version it was
    DISPATCHED with (the no-torn-read contract of the swap path)."""
    model = _olr_model(np.ones(DIM), version=7)
    pm = PipelineModel([model])
    server = MicroBatchServer(pm, in_flight=2, device_input=True)

    def stream():
        yield Table({"features": RNG.randn(8, DIM).astype(np.float32)})
        # batch 0 is now dispatched (still in flight); swap before batch 1
        model.publish_model_arrays((np.full(DIM, -1.0),), 8)
        yield Table({"features": RNG.randn(8, DIM).astype(np.float32)})

    outs = list(server.serve(stream()))
    assert np.unique(np.asarray(outs[0].column("modelVersion"))).tolist() == [7]
    assert np.unique(np.asarray(outs[1].column("modelVersion"))).tolist() == [8]


def test_concurrent_publish_is_atomic():
    """Trainer thread hammering publications vs a reader thread snapping
    the published record: every snapshot is a consistent (version,
    centroids, weights) triple — value == version by construction, so a
    torn (new arrays, old version) read would be caught. Runs
    sanitizer-clean under FLINK_ML_TPU_SANITIZE=1."""
    model = OnlineKMeansModel()
    model.publish_model_arrays((np.zeros((3, DIM)), np.zeros(3)), 0)
    model.set_features_col("features").set_prediction_col("pred")
    stop = []
    tears = []

    def trainer():
        for v in range(1, 400):
            model.publish_model_arrays(
                (np.full((3, DIM), float(v)), np.full(3, float(v))), v
            )
        stop.append(True)

    def reader():
        while not stop:
            c, w = model.model_arrays()
            if c[0, 0] != w[0]:
                tears.append((c[0, 0], w[0]))
            pub = model._published
            if pub.centroids[0, 0] != float(pub.version) and pub.version > 0:
                tears.append((pub.version, pub.centroids[0, 0]))

    t1 = flow.spawn(trainer, name="hotswap.trainer")
    t2 = flow.spawn(reader, name="hotswap.reader")
    t1.join(timeout=60)
    t2.join(timeout=60)
    assert not t1.is_alive() and not t2.is_alive()
    assert tears == [], f"torn publication observed: {tears[:3]}"
    assert model.model_version == 399


# ---------------------------------------------------------------------------
# promotion gate
# ---------------------------------------------------------------------------

class TestPromotionGate:
    def test_nan_candidate_rejected_and_counted(self):
        model = _olr_model(np.ones(DIM), version=1)
        lc = ModelLifecycle(model)
        before = metrics.get_counter("lifecycle.promoteRejected", 0)
        bad = np.ones(DIM)
        bad[2] = np.nan
        with pytest.raises(PromotionRejected) as ei:
            lc.promote((bad,))
        assert ei.value.reason == "nonfinite"
        assert metrics.get_counter("lifecycle.promoteRejected", 0) == before + 1
        # serving model untouched
        np.testing.assert_array_equal(model.coefficient, np.ones(DIM))
        assert model.model_version == 1

    def test_shape_and_arity_rejected(self):
        model = _olr_model(np.ones(DIM), version=1)
        lc = ModelLifecycle(model)
        with pytest.raises(PromotionRejected) as ei:
            lc.promote((np.ones(DIM + 1),))
        assert ei.value.reason == "shape"
        with pytest.raises(PromotionRejected) as ei:
            lc.promote((np.ones(DIM), np.ones(DIM)))
        assert ei.value.reason == "arity"

    def test_canary_regression_rejected_but_healthy_step_promotes(self):
        coeff = np.full(DIM, 0.5)
        model = _olr_model(coeff, version=1)
        canary = {"features": RNG.randn(16, DIM).astype(np.float32)}
        lc = ModelLifecycle(model, canary=canary, canary_rtol=0.2)
        promoted = lc.promote((coeff + 0.001,))  # tiny move: passes
        assert promoted.version_id == 2
        flipped = -5.0 * coeff  # sign-flips every canary prediction
        with pytest.raises(PromotionRejected) as ei:
            lc.promote((flipped,))
        assert ei.value.reason == "canary"
        assert model.model_version == 2

    def test_device_candidate_accepted(self):
        """Trainer updates arrive as device arrays (the online loop yields
        jnp carries); the gate pulls them in one packed readback."""
        model = _olr_model(np.zeros(DIM), version=0)
        lc = ModelLifecycle(model)
        entry = lc.promote((jax.device_put(np.full(DIM, 0.25)),))
        assert entry.version_id == 1
        np.testing.assert_array_equal(model.coefficient, np.full(DIM, 0.25))


# ---------------------------------------------------------------------------
# version ring + automatic rollback + quarantine
# ---------------------------------------------------------------------------

class TestRollback:
    def _lifecycle(self, model):
        return ModelLifecycle(model, retained=3, health_window=4, error_rate_trigger=0.5)

    def test_guard_error_window_triggers_bit_exact_rollback(self):
        model = _olr_model(np.zeros(DIM), version=0)
        lc = self._lifecycle(model)
        good = RNG.randn(DIM)
        lc.promote((good,))  # v1
        lc.record_serve_ok()  # v1 proven good
        lc.promote((RNG.randn(DIM),))  # v2: the bad one
        rollbacks = metrics.get_counter("lifecycle.rollback", 0)
        for _ in range(4):
            lc.record_guard_error(ValueError("guard fired"))
        assert metrics.get_counter("lifecycle.rollback", 0) == rollbacks + 1
        # bit-exact restore of the retained last-good version, original id
        assert model.model_version == 1
        np.testing.assert_array_equal(model.coefficient, good)
        assert lc.quarantined
        with pytest.raises(TrainerQuarantined):
            lc.promote((RNG.randn(DIM),))
        assert any(e.kind == "quarantined" for e in lc.events)
        lc.release_quarantine()
        assert lc.promote((good + 0.1,)).version_id > 2

    def test_ring_is_bounded(self):
        model = _olr_model(np.zeros(DIM), version=0)
        lc = self._lifecycle(model)  # retained=3
        for _ in range(6):
            lc.promote((RNG.randn(DIM),))
        assert len(lc.retained_versions()) == 3
        assert lc.retained_versions() == [4, 5, 6]

    def test_manual_rollback_without_serve_evidence_targets_seed(self):
        """With no serve outcome recorded since the seed, last-good is the
        seed version the server started on — rollback restores it."""
        model = _olr_model(np.zeros(DIM), version=0)
        lc = self._lifecycle(model)
        lc.promote((RNG.randn(DIM),))
        lc.promote((RNG.randn(DIM),))
        lc.rollback("operator")
        assert model.model_version == 0
        np.testing.assert_array_equal(model.coefficient, np.zeros(DIM))


# ---------------------------------------------------------------------------
# persistence: the JobSnapshot meta contract
# ---------------------------------------------------------------------------

def test_resume_republishes_persisted_version_not_zero(tmp_path):
    model = _olr_model(np.zeros(DIM), version=0)
    lc = ModelLifecycle(model, checkpoint_dir=str(tmp_path), job_key="tws")
    final = RNG.randn(DIM)
    lc.promote((RNG.randn(DIM),))
    lc.record_serve_ok()
    lc.promote((final,))
    # "restart": a fresh process builds the model from initial data again
    model2 = _olr_model(np.zeros(DIM), version=0)
    lc2 = ModelLifecycle(model2, checkpoint_dir=str(tmp_path), job_key="tws")
    assert model2.model_version == 2, "resume must re-publish the persisted version"
    np.testing.assert_array_equal(model2.coefficient, final)
    assert lc2.last_good == 1
    next_entry = lc2.promote((final + 1.0,))
    assert next_entry.version_id == 3, "version ids must continue, not restart"


# ---------------------------------------------------------------------------
# the chaos soak (deterministic tier-1 variant of bench.py hotSwapSoak)
# ---------------------------------------------------------------------------

def test_train_while_serving_chaos_soak(tmp_path):
    """Trainer thread promoting through the gated lifecycle (with NaN
    poisonings, flaky snapshot I/O and a mid-publish kill) vs a serving
    loop on the fused plan. Invariants, independent of interleaving:

    - every served batch carries exactly ONE model version;
    - only gate-accepted versions are ever served (a poisoned candidate's
      coefficients never reach traffic: no NaN output rows);
    - served versions are monotone non-decreasing (pre-rollback phase)
      and staleness is bounded: after the trainer finishes, the next
      served batch carries the newest promoted version;
    - zero recompiles after warmup (the swaps reuse the compiled plan);
    - the post-soak rollback restores the retained last-good bit-exactly.
    """
    model = _olr_model()
    lc = ModelLifecycle(
        model,
        retained=4,
        health_window=4,
        error_rate_trigger=0.5,
        checkpoint_dir=str(tmp_path),
        job_key="soak",
    )
    pm = PipelineModel([_scaler(), model])
    server = MicroBatchServer(pm, in_flight=2, device_input=True, lifecycle=lc)

    accepted: list = []
    rejections = []
    base = np.zeros(DIM)

    def trainer():
        for i in range(1, 13):
            candidate = base + 0.05 * i
            if i % 4 == 0:  # NaN-poisoned update: the gate must eat it
                poisoned = candidate.copy()
                poisoned[i % DIM] = np.nan
                try:
                    lc.promote((poisoned,))
                except PromotionRejected as e:
                    rejections.append(e)
                continue
            if i == 5:  # flaky snapshot I/O under the retry budget
                with faults.flaky("snapshot.write", times=2):
                    accepted.append(lc.promote((candidate,)).version_id)
                continue
            if i == 9:  # trainer killed mid-publish (after persist, pre-swap)
                with faults.inject("lifecycle.swap", after=1):
                    try:
                        lc.promote((candidate,))
                    except InjectedFault:
                        pass
                # the recovered trainer re-promotes; ids stay monotone
                accepted.append(lc.promote((candidate,)).version_id)
                continue
            accepted.append(lc.promote((candidate,)).version_id)

    trainer_thread = flow.spawn(trainer, name="soak.trainer")

    def stream(n=24):
        for i in range(n):
            yield Table({"features": RNG.randn(8, DIM).astype(np.float32)})

    pm.transform(_device_batch())  # warm the fused plan before pinning compiles
    tracing.install_jax_hooks()
    compiles_before = metrics.get_counter("jit.compiles", 0)

    served_versions = []
    for out in server.serve(stream()):
        versions = np.unique(np.asarray(out.column("modelVersion")))
        assert len(versions) == 1, "torn read: one batch served by two versions"
        served_versions.append(int(versions[0]))
        assert np.all(np.isfinite(np.asarray(out.column("pred")))), (
            "a rejected (NaN) candidate reached traffic"
        )
    trainer_thread.join(timeout=120)
    assert not trainer_thread.is_alive(), "trainer wedged"

    assert len(rejections) == 3, "every poisoned candidate must be rejected"
    assert metrics.get_counter("jit.compiles", 0) == compiles_before, (
        f"{metrics.get_counter('jit.compiles', 0) - compiles_before} recompiles "
        "during the soak — swaps must be zero-recompile"
    )
    valid = set(accepted) | {0}
    assert set(served_versions) <= valid, (
        f"served versions {sorted(set(served_versions) - valid)} were never promoted"
    )
    assert served_versions == sorted(served_versions), (
        "served versions went backwards without a rollback"
    )
    # staleness bound: with the trainer done, the next batch serves the tip
    tip = list(server.serve(stream(n=1)))[0]
    assert np.unique(np.asarray(tip.column("modelVersion"))).tolist() == [accepted[-1]]
    lc.record_serve_ok()

    # rollback leg: a bad-but-finite promotion slips the gate, guard errors
    # accumulate, traffic rolls back bit-exactly to the retained last-good
    good_arrays = tuple(np.copy(a) for a in model.model_arrays())
    good_version = model.model_version
    lc.promote((base + 99.0,))
    for _ in range(4):
        lc.record_guard_error(ValueError("downstream guard fired"))
    assert model.model_version == good_version
    np.testing.assert_array_equal(model.coefficient, good_arrays[0])
    assert lc.quarantined and lc.rollback_count == 1
    after = list(server.serve(stream(n=1)))[0]
    assert np.unique(np.asarray(after.column("modelVersion"))).tolist() == [good_version]
