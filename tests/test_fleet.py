"""FitFleet: N fits as ONE vmapped resident dispatch (fleet.py, the
`_sgd_fleet_*` kernels in ops/optimizer.py, `_lloyd_fleet_train` in
models/clustering/kmeans.py).

The pinned contract (docs/performance.md §11):

- every fleet member's fitted model is BIT-IDENTICAL to the model its
  estimator would produce solo — dense/sparse SGD (all three losses),
  stream SGD, and Lloyd, in both the replicated and the
  fleet-axis-sharded regime;
- an N-member fleet fit is ONE whole-fit dispatch and ONE blocking
  host sync (`dispatch.whole_fit.fleet`, `iteration.host_sync.fit`);
- the per-member convergence mask freezes early-stoppers at their solo
  stop epoch while later members keep training;
- checkpointed fleet fits cut ONE fleet-axis snapshot and resume onto
  the uninterrupted run's exact final models;
- the fleet winner promotes into a `ModelLifecycle` version ring through
  the unchanged promotion gate.
"""

import numpy as np
import pytest

from flink_ml_tpu import config
from flink_ml_tpu.fleet import FitFleet, fleet_model_arrays, promote_fleet_winner
from flink_ml_tpu.models.classification.linearsvc import LinearSVC
from flink_ml_tpu.models.classification.logisticregression import LogisticRegression
from flink_ml_tpu.models.clustering.kmeans import KMeans
from flink_ml_tpu.models.regression.linearregression import LinearRegression
from flink_ml_tpu.table import StreamTable, Table
from flink_ml_tpu.utils import metrics


def _classif_data(n=344, d=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ np.linspace(1, -1, d) > 0).astype(np.float32)
    return X, y


def _regression_data(n=300, d=6, seed=1):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ np.linspace(-1, 1, d)).astype(np.float32)
    return X, y


def _lr(max_iter=10, tol=0.0, lr=0.1, reg=0.0, en=0.0, gbs=86):
    return (
        LogisticRegression()
        .set_max_iter(max_iter)
        .set_tol(tol)
        .set_learning_rate(lr)
        .set_reg(reg)
        .set_elastic_net(en)
        .set_global_batch_size(gbs)
    )


def _fleet_counters():
    snap = metrics.snapshot()
    return {
        "wholeFit": snap["counters"].get("dispatch.whole_fit", 0),
        "wholeFitFleet": snap["counters"].get("dispatch.whole_fit.fleet", 0),
        "hostSync": snap["counters"].get("iteration.host_sync", 0),
        "hostSyncFit": snap["counters"].get("iteration.host_sync.fit", 0),
        "models": snap["counters"].get("fleet.modelsTrained", 0),
        "fits": snap["counters"].get("fleet.fits", 0),
    }


# ---------------------------------------------------------------------------
# dispatch amortization: N fits, ONE dispatch, ONE sync
# ---------------------------------------------------------------------------

class TestFleetDispatch:
    def test_lr_fleet_one_dispatch_one_sync_bit_identical(self, mesh8):
        """The acceptance contract: a varied-hyper LR fleet trains in ONE
        whole-fit dispatch + ONE blocking sync, each member bit-identical
        to its solo fit (including an early tol-stopper and a shorter
        maxIter member — the convergence mask at work)."""
        X, y = _classif_data()
        table = Table({"features": X, "label": y})
        makers = [
            lambda: _lr(max_iter=12, lr=0.1),
            lambda: _lr(max_iter=12, lr=0.05, reg=0.1),
            lambda: _lr(max_iter=5, lr=0.2),  # freezes 7 epochs early
            lambda: _lr(max_iter=12, tol=0.5, lr=0.1),  # tol early-stop
        ]
        solo = [m().fit(table).coefficient for m in makers]

        before = _fleet_counters()
        models = FitFleet([m() for m in makers]).fit(table)
        after = _fleet_counters()

        assert after["wholeFit"] - before["wholeFit"] == 1
        assert after["wholeFitFleet"] - before["wholeFitFleet"] == 1
        assert after["hostSync"] - before["hostSync"] == 1
        assert after["hostSyncFit"] - before["hostSyncFit"] == 1
        assert after["models"] - before["models"] == 4
        assert after["fits"] - before["fits"] == 1
        assert metrics.snapshot()["gauges"].get("fleet.size") == 4
        for got, want in zip(models, solo):
            np.testing.assert_array_equal(np.asarray(got.coefficient), np.asarray(want))

    def test_single_member_fleet(self, mesh8):
        X, y = _classif_data(seed=5)
        table = Table({"features": X, "label": y})
        solo = _lr(max_iter=8).fit(table)
        (model,) = FitFleet([_lr(max_iter=8)]).fit(table)
        np.testing.assert_array_equal(
            np.asarray(model.coefficient), np.asarray(solo.coefficient)
        )

    def test_member_peak_gauges_namespaced(self, mesh8):
        X, y = _classif_data(seed=6)
        table = Table({"features": X, "label": y})
        FitFleet([_lr(max_iter=3), _lr(max_iter=4), _lr(max_iter=5)]).fit(table)
        gauges = metrics.snapshot()["gauges"]
        assert gauges.get("hbm.peak.fit", 0) > 0
        for i in range(3):
            assert gauges.get(f"hbm.peak.fit.member.{i}", 0) > 0


# ---------------------------------------------------------------------------
# solo-fit bit-parity across estimators and data paths
# ---------------------------------------------------------------------------

class TestFleetParity:
    def test_linearsvc_fleet_parity(self, mesh8):
        X, y = _classif_data(seed=2)
        table = Table({"features": X, "label": y})
        makers = [
            lambda: LinearSVC().set_max_iter(9).set_global_batch_size(86),
            lambda: LinearSVC().set_max_iter(9).set_reg(0.05).set_global_batch_size(86),
            lambda: LinearSVC().set_max_iter(4).set_global_batch_size(86),
        ]
        solo = [m().fit(table).coefficient for m in makers]
        models = FitFleet([m() for m in makers]).fit(table)
        for got, want in zip(models, solo):
            np.testing.assert_array_equal(np.asarray(got.coefficient), np.asarray(want))

    def test_linear_regression_fleet_parity(self, mesh8):
        X, y = _regression_data()
        table = Table({"features": X, "label": y})
        makers = [
            lambda: LinearRegression().set_max_iter(11).set_global_batch_size(75),
            lambda: (
                LinearRegression()
                .set_max_iter(11)
                .set_reg(0.1)
                .set_elastic_net(0.5)
                .set_global_batch_size(75)
            ),
        ]
        solo = [m().fit(table).coefficient for m in makers]
        models = FitFleet([m() for m in makers]).fit(table)
        for got, want in zip(models, solo):
            np.testing.assert_array_equal(np.asarray(got.coefficient), np.asarray(want))

    def test_weighted_fleet_parity(self, mesh8):
        X, y = _classif_data(seed=3)
        w = np.random.RandomState(4).rand(X.shape[0]).astype(np.float32)
        table = Table({"features": X, "label": y, "weight": w})
        makers = [
            lambda: _lr(max_iter=7).set_weight_col("weight"),
            lambda: _lr(max_iter=7, lr=0.3).set_weight_col("weight"),
        ]
        solo = [m().fit(table).coefficient for m in makers]
        models = FitFleet([m() for m in makers]).fit(table)
        for got, want in zip(models, solo):
            np.testing.assert_array_equal(np.asarray(got.coefficient), np.asarray(want))

    def test_sparse_fleet_parity(self, mesh8):
        """Padded-CSR sparse features ride the fleet program un-densified."""
        from flink_ml_tpu.table import SparseVector

        rng = np.random.RandomState(7)
        n, dim, nnz = 256, 500, 6
        rows, y = [], []
        truth = rng.randn(dim).astype(np.float32)
        for _ in range(n):
            idx = np.sort(rng.choice(dim, size=nnz, replace=False))
            val = rng.randn(nnz).astype(np.float32)
            rows.append(SparseVector(dim, idx.astype(np.int64), val))
            y.append(float(val @ truth[idx] > 0))
        table = Table({"features": rows, "label": np.asarray(y, np.float32)})
        makers = [
            lambda: _lr(max_iter=6, gbs=64),
            lambda: _lr(max_iter=6, lr=0.02, reg=0.01, gbs=64),
            lambda: _lr(max_iter=3, gbs=64),
        ]
        solo = [m().fit(table).coefficient for m in makers]
        models = FitFleet([m() for m in makers]).fit(table)
        for got, want in zip(models, solo):
            np.testing.assert_array_equal(np.asarray(got.coefficient), np.asarray(want))

    def test_stream_fleet_parity(self, mesh8):
        """Out-of-core members: the stream's segments are staged ONCE and
        the fleet trains in one `_sgd_fleet_stream_whole_fit` dispatch."""
        X, y = _classif_data(n=320, seed=8)
        batches = [
            Table({"features": X[i : i + 80], "label": y[i : i + 80]})
            for i in range(0, 320, 80)
        ]
        makers = [
            lambda: _lr(max_iter=8, gbs=80),
            lambda: _lr(max_iter=8, lr=0.02, gbs=80),
            lambda: _lr(max_iter=4, gbs=80),
        ]
        solo = [
            m().fit(StreamTable.from_batches(batches)).coefficient for m in makers
        ]
        before = _fleet_counters()
        models = FitFleet([m() for m in makers]).fit(StreamTable.from_batches(batches))
        after = _fleet_counters()
        assert after["hostSync"] - before["hostSync"] == 1
        for got, want in zip(models, solo):
            np.testing.assert_array_equal(np.asarray(got.coefficient), np.asarray(want))

    def test_kmeans_fleet_parity(self, mesh8):
        """N Lloyd fits (per-member seed/maxIter) == their solo fits,
        centroids and weights bit-exact."""
        rng = np.random.RandomState(9)
        X = np.concatenate(
            [rng.randn(60, 5).astype(np.float32) + c for c in (-4.0, 0.0, 4.0)]
        )
        table = Table({"features": X})
        makers = [
            lambda: KMeans().set_k(3).set_seed(11).set_max_iter(8),
            lambda: KMeans().set_k(3).set_seed(29).set_max_iter(8),
            lambda: KMeans().set_k(3).set_seed(11).set_max_iter(3),
        ]
        solo = [m().fit(table) for m in makers]
        models = FitFleet([m() for m in makers]).fit(table)
        for got, want in zip(models, solo):
            np.testing.assert_array_equal(
                np.asarray(got.centroids), np.asarray(want.centroids)
            )
            np.testing.assert_array_equal(
                np.asarray(got.weights), np.asarray(want.weights)
            )


# ---------------------------------------------------------------------------
# fleet-axis sharding: whole members per device, data replicated
# ---------------------------------------------------------------------------

class TestFleetSharding:
    def test_forced_fleet_sharded_parity(self, mesh8):
        """N=8 over 8 data shards: each device owns whole members over
        REPLICATED data, so a member's reductions run in single-shard
        order — the pinned contract is bit-identity to the member's solo
        fit on ONE data shard (and allclose to any shard count; the same
        across-mesh doctrine as elastic resume, docs/fault_tolerance.md)."""
        import jax

        from flink_ml_tpu.parallel import mesh as mesh_lib

        X, y = _classif_data(seed=10)
        table = Table({"features": X, "label": y})
        makers = [lambda i=i: _lr(max_iter=6, lr=0.05 * (i + 1)) for i in range(8)]
        solo8 = [m().fit(table).coefficient for m in makers]
        mesh1 = mesh_lib.create_mesh(
            (mesh_lib.DATA_AXIS,), devices=jax.devices()[:1]
        )
        with mesh_lib.use_mesh(mesh1):
            solo1 = [m().fit(table).coefficient for m in makers]
        models = FitFleet(
            [m() for m in makers], shard_fleet_axis=True
        ).fit(table)
        assert metrics.snapshot()["gauges"].get("fleet.sharded") == 1.0
        for got, bit_ref, close_ref in zip(models, solo1, solo8):
            np.testing.assert_array_equal(
                np.asarray(got.coefficient), np.asarray(bit_ref)
            )
            np.testing.assert_allclose(
                np.asarray(got.coefficient), np.asarray(close_ref),
                rtol=1e-5, atol=1e-6,
            )

    def test_auto_shard_threshold(self, mesh8):
        """Crossing `config.fleet_shard_state_bytes` flips the regime
        automatically; under it the fleet stays replicated."""
        X, y = _classif_data(seed=11)
        table = Table({"features": X, "label": y})
        makers = [lambda i=i: _lr(max_iter=4, lr=0.1 + 0.01 * i) for i in range(8)]
        with config.fleet_shard_threshold(1):  # 8*2*8*4 bytes >> 1
            FitFleet([m() for m in makers]).fit(table)
            assert metrics.snapshot()["gauges"].get("fleet.sharded") == 1.0
        FitFleet([m() for m in makers]).fit(table)
        assert metrics.snapshot()["gauges"].get("fleet.sharded") == 0.0

    def test_forced_shard_indivisible_fleet_raises(self, mesh8):
        X, y = _classif_data(seed=12)
        with pytest.raises(ValueError, match="cannot shard"):
            FitFleet(
                [_lr(max_iter=3) for _ in range(3)], shard_fleet_axis=True
            ).fit(Table({"features": X, "label": y}))

    def test_sharded_kmeans_parity(self, mesh8):
        """Fleet-sharded Lloyd: bit-identical to single-shard solo fits,
        allclose to the 8-shard solo fits (reduction-order doctrine)."""
        import jax

        from flink_ml_tpu.parallel import mesh as mesh_lib

        rng = np.random.RandomState(13)
        X = np.concatenate(
            [rng.randn(40, 4).astype(np.float32) + c for c in (-3.0, 3.0)]
        )
        table = Table({"features": X})
        makers = [
            lambda i=i: KMeans().set_k(2).set_seed(3 + i).set_max_iter(6)
            for i in range(8)
        ]
        solo8 = [m().fit(table) for m in makers]
        mesh1 = mesh_lib.create_mesh(
            (mesh_lib.DATA_AXIS,), devices=jax.devices()[:1]
        )
        with mesh_lib.use_mesh(mesh1):
            solo1 = [m().fit(table) for m in makers]
        models = FitFleet([m() for m in makers], shard_fleet_axis=True).fit(table)
        for got, bit_ref, close_ref in zip(models, solo1, solo8):
            np.testing.assert_array_equal(
                np.asarray(got.centroids), np.asarray(bit_ref.centroids)
            )
            np.testing.assert_allclose(
                np.asarray(got.centroids), np.asarray(close_ref.centroids),
                rtol=1e-5, atol=1e-6,
            )


# ---------------------------------------------------------------------------
# checkpointing: one fleet-axis cut, resume onto exact final models
# ---------------------------------------------------------------------------

class TestFleetCheckpointing:
    def test_chunked_fleet_matches_whole(self, mesh8, tmp_path):
        """A checkpoint cadence mid-fit forces the chunked fleet path;
        its models must equal the uncheckpointed whole-fit fleet's."""
        X, y = _classif_data(seed=14)
        table = Table({"features": X, "label": y})
        makers = [
            lambda: _lr(max_iter=9),
            lambda: _lr(max_iter=9, lr=0.05),
            lambda: _lr(max_iter=4, lr=0.2),
        ]
        whole = FitFleet([m() for m in makers]).fit(table)
        with config.iteration_checkpointing(str(tmp_path / "fleet"), interval=4):
            chunked = FitFleet([m() for m in makers]).fit(table)
        for got, want in zip(chunked, whole):
            np.testing.assert_array_equal(
                np.asarray(got.coefficient), np.asarray(want.coefficient)
            )

    def test_resume_from_mid_fit_snapshot(self, mesh8, tmp_path):
        """A fleet killed after its first snapshot resumes from the cut
        and lands on the uninterrupted fleet's exact models."""
        from flink_ml_tpu.ckpt import InjectedFault, faults

        X, y = _classif_data(seed=15)
        table = Table({"features": X, "label": y})
        makers = [
            lambda: _lr(max_iter=10),
            lambda: _lr(max_iter=10, lr=0.02),
            lambda: _lr(max_iter=6, lr=0.15),
        ]
        expected = FitFleet([m() for m in makers]).fit(table)
        with config.iteration_checkpointing(str(tmp_path / "kill"), interval=3):
            with faults.inject("chunk", after=2) as plan:
                with pytest.raises(InjectedFault):
                    FitFleet([m() for m in makers]).fit(table)
            assert plan.fired
            resumed = FitFleet([m() for m in makers]).fit(table)
        for got, want in zip(resumed, expected):
            np.testing.assert_array_equal(
                np.asarray(got.coefficient), np.asarray(want.coefficient)
            )


# ---------------------------------------------------------------------------
# construction / validation errors
# ---------------------------------------------------------------------------

class TestFleetValidation:
    def test_empty_fleet_rejected(self):
        with pytest.raises(ValueError, match="at least one"):
            FitFleet([])

    def test_mixed_classes_rejected(self):
        with pytest.raises(ValueError, match="same estimator class"):
            FitFleet([LogisticRegression(), LinearSVC()])

    def test_unsupported_estimator_rejected(self):
        from flink_ml_tpu.models.feature.standardscaler import StandardScaler

        with pytest.raises(ValueError, match="does not support"):
            FitFleet([StandardScaler()])

    def test_structural_param_mismatch_rejected(self, mesh8):
        X, y = _classif_data(seed=16)
        table = Table({"features": X, "label": y})
        fleet = FitFleet([_lr(gbs=32), _lr(gbs=64)])
        with pytest.raises(ValueError, match="globalBatchSize"):
            fleet.fit(table)

    def test_multinomial_rejected(self, mesh8):
        X, y = _classif_data(seed=17)
        table = Table({"features": X, "label": y})
        fleet = FitFleet([_lr(), _lr().set_multi_class("multinomial")])
        with pytest.raises(ValueError, match="[Mm]ultinomial"):
            fleet.fit(table)

    def test_invalid_labels_rejected(self, mesh8):
        X, _ = _classif_data(seed=18)
        y = np.full(X.shape[0], 2.0, np.float32)
        fleet = FitFleet([_lr(max_iter=2), _lr(max_iter=3)])
        with pytest.raises(ValueError, match="binomial"):
            fleet.fit(Table({"features": X, "label": y}))


# ---------------------------------------------------------------------------
# fleet -> lifecycle bridge: winner promotion
# ---------------------------------------------------------------------------

class TestWinnerPromotion:
    def _serving_model(self, d):
        from flink_ml_tpu.models.classification.onlinelogisticregression import (
            OnlineLogisticRegressionModel,
        )

        m = OnlineLogisticRegressionModel()
        m.publish_model_arrays((np.zeros(d, np.float32),), 0)
        m.set_features_col("features").set_prediction_col("pred")
        return m

    def test_winner_promotes_into_version_ring(self, mesh8):
        from flink_ml_tpu.lifecycle import ModelLifecycle

        X, y = _classif_data(seed=19)
        table = Table({"features": X, "label": y})
        models = FitFleet(
            [_lr(max_iter=6), _lr(max_iter=6, lr=0.02), _lr(max_iter=6, lr=0.3)]
        ).fit(table)
        scores = [0.71, 0.64, 0.83]
        lc = ModelLifecycle(self._serving_model(X.shape[1]))
        winner, version = promote_fleet_winner(lc, models, scores)
        assert winner == 2
        np.testing.assert_array_equal(
            version.arrays[0], np.asarray(models[2].coefficient, np.float32)
        )
        assert lc.model.model_version == version.version_id
        gauges = metrics.snapshot()["gauges"]
        assert gauges.get("fleet.winnerIndex") == 2.0
        assert gauges.get("fleet.winnerScore") == pytest.approx(0.83)

    def test_min_mode_and_score_validation(self, mesh8):
        from flink_ml_tpu.lifecycle import ModelLifecycle

        X, y = _classif_data(seed=20)
        models = FitFleet([_lr(max_iter=3), _lr(max_iter=4)]).fit(
            Table({"features": X, "label": y})
        )
        lc = ModelLifecycle(self._serving_model(X.shape[1]))
        winner, _ = promote_fleet_winner(lc, models, [0.4, 0.1], mode="min")
        assert winner == 1
        with pytest.raises(ValueError, match="scores"):
            promote_fleet_winner(lc, models, [0.4])
        with pytest.raises(ValueError, match="NaN"):
            promote_fleet_winner(lc, models, [0.4, float("nan")])
        with pytest.raises(ValueError, match="mode"):
            promote_fleet_winner(lc, models, [0.4, 0.1], mode="median")

    def test_fleet_model_arrays_kmeans(self, mesh8):
        rng = np.random.RandomState(21)
        X = np.concatenate(
            [rng.randn(30, 3).astype(np.float32) + c for c in (-2.0, 2.0)]
        )
        (model,) = FitFleet([KMeans().set_k(2).set_seed(1).set_max_iter(4)]).fit(
            Table({"features": X})
        )
        centroids, weights = fleet_model_arrays(model)
        assert centroids.shape == (2, 3) and weights.shape == (2,)
        assert centroids.dtype == np.float32
