"""Metrics/profiling surface (utils/metrics.py) — the analogue of the
reference's Flink metric groups + modelDataVersion gauge + the benchmark
module's wall-clock accounting (SURVEY.md §5)."""

import numpy as np
import pytest

from flink_ml_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _clean_registry():
    metrics.reset()
    yield
    metrics.reset()


def test_timed_accumulates():
    with metrics.timed("phase.a"):
        pass
    with metrics.timed("phase.a"):
        pass
    snap = metrics.snapshot()
    assert snap["timers"]["phase.a"]["count"] == 2
    assert snap["timers"]["phase.a"]["totalMs"] >= 0.0
    assert metrics.timer_totals()["phase.a"] >= 0.0


def test_gauges_and_counters():
    metrics.set_gauge("g", 7.5)
    metrics.inc_counter("c")
    metrics.inc_counter("c", 2)
    snap = metrics.snapshot()
    assert snap["gauges"]["g"] == 7.5
    assert snap["counters"]["c"] == 3
    assert metrics.get_gauge("g") == 7.5
    assert metrics.get_gauge("missing", -1) == -1


def test_iteration_epoch_timing():
    """Host-driven iterations record per-epoch wall clock; the on-device
    while_loop records the loop total + epoch gauge."""
    import jax.numpy as jnp

    from flink_ml_tpu.parallel.iteration import IterationListener, iterate_bounded

    def body(carry, epoch):
        return carry + 1.0, jnp.asarray(1.0, jnp.float32)

    class L(IterationListener):
        pass

    iterate_bounded(body, jnp.asarray(0.0), max_iter=3, listener=L())
    snap = metrics.snapshot()
    assert snap["timers"]["iteration.epoch"]["count"] == 3
    assert snap["gauges"]["iteration.epochs"] == 3

    metrics.reset()
    iterate_bounded(body, jnp.asarray(0.0), max_iter=4)
    snap = metrics.snapshot()
    assert snap["timers"]["iteration.device_loop"]["count"] == 1
    assert snap["gauges"]["iteration.epochs"] == 4


def test_benchmark_phase_breakdown(mesh8):
    from flink_ml_tpu.benchmark.runner import run_benchmark

    entry = {
        "stage": {
            "className": "org.apache.flink.ml.clustering.kmeans.KMeans",
            "paramMap": {"k": 2, "maxIter": 2},
        },
        "inputData": {
            "className": "org.apache.flink.ml.benchmark.datagenerator.common.DenseVectorGenerator",
            "paramMap": {"colNames": [["features"]], "numValues": 64, "vectorDim": 3},
        },
    }
    result = run_benchmark("KMeans-phase", entry)
    assert set(result["phaseTimesMs"]) == {"datagen", "fit", "transform", "collect"}
    assert all(v >= 0.0 for v in result["phaseTimesMs"].values())
    # phases also land in the process-wide registry
    assert "benchmark.KMeans-phase.fit" in metrics.snapshot()["timers"]


def test_online_model_version_gauge(mesh8):
    from flink_ml_tpu.models.clustering.onlinekmeans import (
        OnlineKMeans,
        generate_random_model_data,
    )
    from flink_ml_tpu.table import StreamTable, Table

    rng = np.random.default_rng(0)
    batches = [
        Table({"features": rng.standard_normal((16, 2)).astype(np.float32)})
        for _ in range(3)
    ]
    model = (
        OnlineKMeans()
        .set_global_batch_size(16)
        .set_initial_model_data(generate_random_model_data(2, 2, 0.0, seed=5))
    ).fit(StreamTable.from_batches(batches))
    model.process_updates()
    assert metrics.get_gauge("OnlineKMeansModel.modelDataVersion") == model.model_version
