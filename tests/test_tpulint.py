"""Tier-1 tpulint gate: the full rule pass over flink_ml_tpu/ must report
zero unsuppressed findings — this is the static rail the dispatch-bound
perf work runs on (docs/static_analysis.md). Also pins the CLI contract:
exit 0 on the clean tree, exit 1 with file:line + rule id when any single
known-bad fixture is seeded, and a working --changed fast path."""

import os
import shutil
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TPULINT = os.path.join(REPO, "scripts", "tpulint.py")


def _run_cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, TPULINT, *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def test_package_is_clean_full_rule_pass():
    """THE gate: every rule over the whole package, zero unsuppressed
    findings (suppressions carry reasons and are the audited sync census;
    an unused suppression would itself fail this)."""
    from flink_ml_tpu.analysis import engine

    report = engine.run()
    assert report.findings == [], "\n".join(f.format() for f in report.findings)
    # the census is non-empty: deliberate sync/compile points are annotated
    assert len(report.suppressed) >= 5


def test_cli_exit_zero_on_clean_tree():
    result = _run_cli()
    assert result.returncode == 0, result.stdout + result.stderr
    assert "clean" in result.stdout


def test_cli_list_rules_catalogue():
    result = _run_cli("--list-rules")
    assert result.returncode == 0
    for rule_id in (
        "host-sync-leak",
        "retrace-hazard",
        "donation-after-use",
        "sharding-tags",
        "collective-accounting",
        "upload-accounting",
        "fusion-coverage",
        "checkpoint-coverage",
        "lock-order",
        "channel-protocol",
        "unused-suppression",
    ):
        assert rule_id in result.stdout, rule_id


def _seed_tree(tmp_path, rel, source, extra=None):
    """A minimal fixture package containing one known-bad file."""
    files = {
        "__init__.py": "",
        "utils/__init__.py": "",
        "utils/lazyjit.py": "def lazy_jit(fn, **kw):\n    return fn\n",
        "models/__init__.py": "",
        rel: textwrap.dedent(source),
    }
    files.update(extra or {})
    for name, src in files.items():
        path = tmp_path / "flink_ml_tpu" / name
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
    return tmp_path


#: minimal mesh+collectives pair for the SPMD-rule seeds (same relative
#: paths the real package anchors on); referenced by name in SEED_CASES
#: so four cases share one copy
SPMD_STUB_FILES = {
    "parallel/__init__.py": "",
    "parallel/mesh.py": (
        'DATA_AXIS = "data"\n'
        'MODEL_AXIS = "model"\n'
        "def create_mesh(axis_names=(DATA_AXIS,), shape=None, devices=None):\n"
        "    pass\n"
    ),
    "parallel/collectives.py": (
        "from jax import lax\n"
        "from .mesh import DATA_AXIS, MODEL_AXIS\n"
        "def all_reduce_sum(x, axis_name=DATA_AXIS):\n"
        "    return lax.psum(x, axis_name)\n"
        "def all_gather(x, axis_name=DATA_AXIS, axis=0, tiled=True):\n"
        "    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)\n"
        "def ppermute_ring(x, axis_name=DATA_AXIS, shift=1):\n"
        "    return lax.ppermute(x, axis_name, [(0, 0)])\n"
        "def axis_index(axis_name=DATA_AXIS):\n"
        "    return lax.axis_index(axis_name)\n"
        "def shard_map_over(mesh, in_specs, out_specs, fn=None, check_vma=False):\n"
        "    return fn\n"
    ),
}

SEED_CASES = [
    (
        "raw-jax-jit",
        "models/bad.py",
        """
        import jax

        def _impl(x):
            return x

        _kernel = jax.jit(_impl)
        """,
        "retrace-hazard",
        "flink_ml_tpu/models/bad.py:7",
        None,
    ),
    (
        "unaccounted-item",
        "models/bad.py",
        """
        import jax.numpy as jnp

        def fit(X):
            return jnp.mean(X).item()
        """,
        "host-sync-leak",
        "flink_ml_tpu/models/bad.py:5",
        None,
    ),
    (
        "donated-then-read",
        "models/bad.py",
        """
        import jax

        def _impl(a, b):
            return a + b

        _step_donating = jax.jit(_impl, donate_argnums=(0,))

        def fit(carry, other):
            out = _step_donating(carry, other)
            return out + carry
        """,
        "donation-after-use",
        "flink_ml_tpu/models/bad.py:11",
        None,
    ),
    (
        "unknown-mesh-axis",
        "models/bad.py",
        """
        from ..parallel.collectives import all_reduce_sum

        def reduce(x):
            return all_reduce_sum(x, "dta")
        """,
        "mesh-axis",
        "flink_ml_tpu/models/bad.py:5",
        "SPMD_STUB",
    ),
    (
        "divergent-branch-psum",
        "models/bad.py",
        """
        from jax.sharding import PartitionSpec as P
        from ..parallel import collectives
        from ..parallel.mesh import DATA_AXIS

        def build(mesh):
            def body(x):
                i = collectives.axis_index(DATA_AXIS)
                if i == 0:
                    x = collectives.all_reduce_sum(x, DATA_AXIS)
                return x
            return collectives.shard_map_over(
                mesh, (P(DATA_AXIS),), P(DATA_AXIS), fn=body)
        """,
        "collective-divergence",
        "flink_ml_tpu/models/bad.py:10",
        "SPMD_STUB",
    ),
    (
        "replicated-output-never-reduced",
        "models/bad.py",
        """
        from jax.sharding import PartitionSpec as P
        from ..parallel import collectives
        from ..parallel.mesh import DATA_AXIS

        def build(mesh):
            def body(x):
                return x * 2.0
            return collectives.shard_map_over(
                mesh, (P(DATA_AXIS),), P(), fn=body)
        """,
        "spec-consistency",
        "flink_ml_tpu/models/bad.py:8",
        "SPMD_STUB",
    ),
    (
        "downcast-before-reduce",
        "models/bad.py",
        """
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from ..parallel import collectives
        from ..parallel.mesh import DATA_AXIS

        def build(mesh):
            def body(x):
                return collectives.all_reduce_sum(
                    x.astype(jnp.bfloat16), DATA_AXIS)
            return collectives.shard_map_over(
                mesh, (P(DATA_AXIS),), P(), fn=body)
        """,
        "precision-determinism",
        "flink_ml_tpu/models/bad.py:9",
        "SPMD_STUB",
    ),
    (
        "unknown-ckpt-tag",
        "models/bad.py",
        """
        from ..ckpt.snapshot import save_job_snapshot

        def checkpoint(path, carry):
            save_job_snapshot(path, "job", {"model": carry},
                              specs={"model": "fully_sharded"})
        """,
        "sharding-tags",
        "flink_ml_tpu/models/bad.py:6",
        {
            "ckpt/__init__.py": "",
            "ckpt/snapshot.py": (
                '_SPEC_TAGS = ("replicated", "data", "model", "host")\n'
                "def _sharding_for(tag, mesh, ndim):\n"
                '    if tag == "data":\n'
                "        return 1\n"
                '    if tag == "model":\n'
                "        return 2\n"
                "    return 0\n"
                "def save_job_snapshot(path, key, sections, specs=None, **kw):\n"
                "    pass\n"
                "def stage_section(snap, name, mesh=None, specs=None):\n"
                "    pass\n"
            ),
            "parallel/__init__.py": "",
            "parallel/mesh.py": (
                "def replicated_sharding(mesh):\n    pass\n"
                "def data_sharding(mesh, ndim=1):\n    pass\n"
                "def model_sharding(mesh, ndim=1):\n    pass\n"
            ),
        },
    ),
]


@pytest.mark.parametrize(
    "name,rel,source,rule,location,extra",
    SEED_CASES,
    ids=[c[0] for c in SEED_CASES],
)
def test_seeded_known_bad_fixture_fails_with_location(
    tmp_path, name, rel, source, rule, location, extra
):
    """Acceptance contract: seeding any single known-bad fixture makes the
    CLI exit 1 and name the file:line and rule id."""
    if extra == "SPMD_STUB":
        extra = SPMD_STUB_FILES
    root = _seed_tree(tmp_path, rel, source, extra)
    result = _run_cli("--root", str(root), "--rule", rule)
    assert result.returncode == 1, result.stdout + result.stderr
    assert location in result.stdout, result.stdout
    assert rule in result.stdout


def test_changed_mode_reports_only_changed_files(tmp_path):
    """--changed lints files differing from HEAD (here: a fresh git repo
    whose HEAD lacks the planted bad file)."""
    root = _seed_tree(
        tmp_path,
        "models/bad.py",
        """
        import jax

        def _impl(x):
            return x

        _kernel = jax.jit(_impl)
        """,
    )
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "GIT_AUTHOR_NAME": "t",
        "GIT_AUTHOR_EMAIL": "t@t",
        "GIT_COMMITTER_NAME": "t",
        "GIT_COMMITTER_EMAIL": "t@t",
    }

    def git(*args):
        subprocess.run(
            ["git", *args], cwd=str(root), check=True, capture_output=True, env=env
        )

    git("init", "-q")
    # only the clean files are committed: bad.py stays untracked, i.e.
    # "changed relative to HEAD"
    git("add", "flink_ml_tpu/__init__.py", "flink_ml_tpu/utils")
    git("commit", "-q", "-m", "seed")
    result = _run_cli("--root", str(root), "--changed", "--rule", "retrace-hazard")
    assert result.returncode == 1, result.stdout + result.stderr
    assert "flink_ml_tpu/models/bad.py:7" in result.stdout

    # everything committed -> nothing differs from HEAD -> exit 0 fast
    git("add", "-A")
    git("commit", "-q", "-m", "rest")
    result = _run_cli("--root", str(root), "--changed", "--rule", "retrace-hazard")
    assert result.returncode == 0, result.stdout + result.stderr
    assert "no files differ" in result.stdout


# ---------------------------------------------------------------------------
# tpulint v2: interprocedural taint (acceptance + recall-superset gates)
# ---------------------------------------------------------------------------

#: a device->host pull laundered through TWO helper layers — the shape the
#: per-function v1 engine provably cannot see (every call laundered taint)
TWO_LAYER_LAUNDER = """
import jax.numpy as jnp
import numpy as np


def _to_host(x):
    return np.asarray(x)


def _helper(x):
    return _to_host(x)


def fit(X):
    dev = jnp.sum(X, axis=0)
    return _helper(dev)
"""

#: direct violations both engines must agree on (the recall baseline)
DIRECT_VIOLATIONS = """
import jax.numpy as jnp
import numpy as np


def fit(X):
    dev = jnp.sum(X, axis=0)
    a = np.asarray(dev)
    b = dev.item()
    c = float(dev)
    return a, b, c
"""


def _hostsync_reports(tmp_path, files):
    """(per-function v1 report, interprocedural v2 report) over the same
    fixture tree, same rule class, only the `interprocedural` flag differs."""
    import textwrap as _tw

    from flink_ml_tpu.analysis import engine as _engine
    from flink_ml_tpu.analysis.engine import Project

    for rel, src in files.items():
        path = tmp_path / "flink_ml_tpu" / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(_tw.dedent(src))
    rule_cls = type(_engine.get_rule("host-sync-leak"))
    reports = []
    for interprocedural in (False, True):
        rule = rule_cls()
        rule.interprocedural = interprocedural
        project = Project.load(root=str(tmp_path), scope=("flink_ml_tpu",))
        reports.append(
            _engine.run(root=str(tmp_path), rules=[rule], project=project)
        )
    return reports


def test_interprocedural_catches_two_layer_laundering(tmp_path):
    """THE v2 acceptance case: np.asarray buried two helpers deep. The old
    per-function engine provably misses it; the interprocedural engine
    flags the top-level call site with the full chain."""
    legacy, v2 = _hostsync_reports(
        tmp_path,
        {"models/bad.py": TWO_LAYER_LAUNDER, "models/__init__.py": "", "__init__.py": "",
         "utils/__init__.py": "", "utils/lazyjit.py": "def lazy_jit(f, **k):\n    return f\n"},
    )
    assert legacy.findings == []  # v1 blind spot, demonstrated
    assert len(v2.findings) == 1
    f = v2.findings[0]
    assert f.path == "flink_ml_tpu/models/bad.py"
    assert f.line == 16  # `return _helper(dev)` in fit
    assert f.data[0] == "np-pull-chain"
    assert list(f.data[2:]) == ["_helper", "_to_host"]  # the full chain
    assert "models/bad.py:7" in f.message  # the sink line


def test_interprocedural_findings_superset_of_per_function(tmp_path):
    """No recall regressions: on seeded fixtures mixing direct violations
    with laundered ones, every v1 finding location survives in v2."""
    legacy, v2 = _hostsync_reports(
        tmp_path,
        {
            "models/direct.py": DIRECT_VIOLATIONS,
            "models/laundered.py": TWO_LAYER_LAUNDER,
            "models/__init__.py": "",
            "__init__.py": "",
            "utils/__init__.py": "",
            "utils/lazyjit.py": "def lazy_jit(f, **k):\n    return f\n",
        },
    )
    legacy_keys = {(f.path, f.line, f.data) for f in legacy.findings}
    v2_keys = {(f.path, f.line, f.data) for f in v2.findings}
    assert legacy_keys, "the baseline must find the direct violations"
    assert legacy_keys <= v2_keys, legacy_keys - v2_keys
    assert len(v2_keys) > len(legacy_keys)  # and v2 sees strictly more


def test_repo_is_clean_under_interprocedural_pass_with_concurrency_rules():
    """Tier-1 acceptance: the FULL v2 rule set — interprocedural
    host-sync + donation plus the lock-order and channel-protocol
    concurrency rules — runs over the real package and is clean."""
    from flink_ml_tpu.analysis import engine

    rule_ids = {r.id for r in engine.all_rules()}
    assert {"lock-order", "channel-protocol"} <= rule_ids
    assert engine.get_rule("host-sync-leak").interprocedural is True
    assert engine.get_rule("donation-after-use").interprocedural is True
    report = engine.run()  # every rule: subsets would orphan suppressions
    assert report.findings == [], "\n".join(f.format() for f in report.findings)


# ---------------------------------------------------------------------------
# CLI: --format json, --changed robustness
# ---------------------------------------------------------------------------

def test_format_json_machine_readable(tmp_path):
    import json

    root = _seed_tree(
        tmp_path,
        "models/bad.py",
        TWO_LAYER_LAUNDER,
    )
    result = _run_cli("--root", str(root), "--rule", "host-sync-leak", "--format", "json")
    assert result.returncode == 1, result.stdout + result.stderr
    payload = json.loads(result.stdout)
    assert payload["clean"] is False
    (finding,) = payload["findings"]
    assert finding["file"] == "flink_ml_tpu/models/bad.py"
    assert finding["line"] == 16
    assert finding["rule"] == "host-sync-leak"
    assert finding["chain"] == ["_helper", "_to_host"]


def test_format_json_clean_tree(tmp_path):
    import json

    root = _seed_tree(tmp_path, "models/ok.py", "x = 1\n")
    result = _run_cli("--root", str(root), "--rule", "host-sync-leak", "--format", "json")
    assert result.returncode == 0, result.stdout + result.stderr
    payload = json.loads(result.stdout)
    assert payload["clean"] is True and payload["findings"] == []


def test_changed_mode_survives_renames_and_deletes(tmp_path):
    """--changed with a renamed file (old path exists only in HEAD) and a
    deleted file must lint the NEW path and skip the gone ones."""
    root = _seed_tree(tmp_path, "models/old_name.py", "x = 1\n")
    (root / "flink_ml_tpu" / "models" / "doomed.py").write_text("y = 2\n")
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "GIT_AUTHOR_NAME": "t",
        "GIT_AUTHOR_EMAIL": "t@t",
        "GIT_COMMITTER_NAME": "t",
        "GIT_COMMITTER_EMAIL": "t@t",
    }

    def git(*args):
        subprocess.run(
            ["git", *args], cwd=str(root), check=True, capture_output=True, env=env
        )

    git("init", "-q")
    git("add", "-A")
    git("commit", "-q", "-m", "seed")
    # rename + inject a violation into the renamed file; delete the other
    old = root / "flink_ml_tpu" / "models" / "old_name.py"
    new = root / "flink_ml_tpu" / "models" / "new_name.py"
    old.rename(new)
    new.write_text(
        "import jax\n\ndef _impl(x):\n    return x\n\n_kernel = jax.jit(_impl)\n"
    )
    (root / "flink_ml_tpu" / "models" / "doomed.py").unlink()
    result = _run_cli("--root", str(root), "--changed", "--rule", "retrace-hazard")
    assert result.returncode == 1, result.stdout + result.stderr
    assert "flink_ml_tpu/models/new_name.py:6" in result.stdout
    assert "doomed" not in result.stdout
    assert "old_name" not in result.stdout


def test_changed_mode_outside_git_falls_back_to_full_lint(tmp_path):
    root = _seed_tree(
        tmp_path,
        "models/bad.py",
        "import jax\n\ndef _impl(x):\n    return x\n\n_kernel = jax.jit(_impl)\n",
    )
    result = _run_cli("--root", str(root), "--changed", "--rule", "retrace-hazard")
    assert result.returncode == 1, result.stdout + result.stderr
    assert "linting the whole tree" in result.stderr
    assert "flink_ml_tpu/models/bad.py:6" in result.stdout


# ---------------------------------------------------------------------------
# incremental lint: the summary cache must be finding-identical to cold
# ---------------------------------------------------------------------------

HELPER_CLEAN = """
def prepare(x):
    return x
"""

#: the edit: the helper gains a host sync, so the UNCHANGED caller module
#: must be re-analyzed (reverse-dependency invalidation) to inherit the
#: interprocedural finding
HELPER_SYNCING = """
import numpy as np


def prepare(x):
    return np.asarray(x)
"""

CALLER = """
import jax.numpy as jnp

from .helper import prepare


def fit(X):
    dev = jnp.sum(X, axis=0)
    return prepare(dev)
"""


def _cache_tree(tmp_path, helper_src):
    import textwrap as _tw

    files = {
        "__init__.py": "",
        "utils/__init__.py": "",
        "utils/lazyjit.py": "def lazy_jit(fn, **kw):\n    return fn\n",
        "models/__init__.py": "",
        "models/helper.py": _tw.dedent(helper_src),
        "models/caller.py": _tw.dedent(CALLER),
    }
    for name, src in files.items():
        path = tmp_path / "flink_ml_tpu" / name
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
    return tmp_path


def _findings_with_cache(root, cache):
    from flink_ml_tpu.analysis import engine as _engine
    from flink_ml_tpu.analysis.engine import Project

    project = Project.load(root=str(root), scope=("flink_ml_tpu",))
    rule = _engine.get_rule("host-sync-leak")
    report = _engine.run(
        root=str(root), rules=[rule], project=project, summary_cache=cache
    )
    return sorted((f.path, f.line, f.rule) for f in report.findings)


def test_cache_reverse_dependency_invalidation_keeps_parity(tmp_path):
    """THE cache-vs-cold parity pin: warm the cache on a clean tree, edit
    ONLY the helper so its summary changes, and the warm incremental run
    must produce exactly the cold run's findings — including the
    interprocedural finding in the UNCHANGED caller module, which only
    appears if reverse-dependency invalidation re-analyzed it."""
    from flink_ml_tpu.analysis import cache as cache_mod

    root = _cache_tree(tmp_path, HELPER_CLEAN)
    cache_file = str(tmp_path / ".tpulint_cache.json")

    warm = cache_mod.SummaryCache.load(cache_file)
    assert _findings_with_cache(root, warm) == []  # clean tree, cache warmed
    assert os.path.exists(cache_file)

    # the edit: helper gains a sync; caller.py is byte-identical
    (root / "flink_ml_tpu" / "models" / "helper.py").write_text(
        __import__("textwrap").dedent(HELPER_SYNCING)
    )

    cold = _findings_with_cache(root, None)
    warm2 = cache_mod.SummaryCache.load(cache_file)
    cached = _findings_with_cache(root, warm2)
    assert cold == cached
    # and the finding set is the interesting one: the unchanged caller
    # carries the lifted finding; the helper's own param-sink is not a
    # device-sourced finding
    assert ("flink_ml_tpu/models/caller.py", 9, "host-sync-leak") in cold
    # the dirty set was exactly the helper; the caller was invalidated by
    # the reverse-import closure, everything else served from cache
    assert warm2.dirty == {"flink_ml_tpu/models/helper.py"}
    assert "flink_ml_tpu/models/caller.py" not in warm2.servable
    assert "flink_ml_tpu/utils/lazyjit.py" in warm2.servable


def test_cache_warm_full_run_identical_and_serving(tmp_path):
    """Same tree, no edits: the warm run serves every analysis from the
    cache and the findings are byte-identical."""
    from flink_ml_tpu.analysis import cache as cache_mod

    root = _cache_tree(tmp_path, HELPER_SYNCING)
    cache_file = str(tmp_path / ".tpulint_cache.json")

    cold = _findings_with_cache(root, cache_mod.SummaryCache.load(cache_file))
    warm = cache_mod.SummaryCache.load(cache_file)
    warmed = _findings_with_cache(root, warm)
    assert cold == warmed != []
    assert warm.dirty == set()
    assert warm.hits > 0


def test_cache_corrupt_file_treated_as_empty(tmp_path):
    from flink_ml_tpu.analysis import cache as cache_mod

    path = tmp_path / ".tpulint_cache.json"
    path.write_text("{not json")
    cache = cache_mod.SummaryCache.load(str(path))
    assert cache.files == {}


def test_cli_changed_cached_vs_cold_parity(tmp_path):
    """End-to-end --changed parity: a git tree with a planted laundered
    sync, cold (--no-cache) vs warmed cache runs emit identical JSON."""
    import json as _json

    root = _seed_tree(tmp_path, "models/bad.py", TWO_LAYER_LAUNDER)
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "GIT_AUTHOR_NAME": "t",
        "GIT_AUTHOR_EMAIL": "t@t",
        "GIT_COMMITTER_NAME": "t",
        "GIT_COMMITTER_EMAIL": "t@t",
    }

    def git(*args):
        subprocess.run(
            ["git", *args], cwd=str(root), check=True, capture_output=True, env=env
        )

    git("init", "-q")
    git("add", "flink_ml_tpu/__init__.py", "flink_ml_tpu/utils")
    git("commit", "-q", "-m", "seed")

    cold = _run_cli(
        "--root", str(root), "--changed", "--no-cache",
        "--rule", "host-sync-leak", "--format", "json",
    )
    first = _run_cli(  # populates the cache
        "--root", str(root), "--changed", "--rule", "host-sync-leak",
        "--format", "json",
    )
    warm = _run_cli(  # serves from it
        "--root", str(root), "--changed", "--rule", "host-sync-leak",
        "--format", "json",
    )
    assert cold.returncode == first.returncode == warm.returncode == 1
    payloads = [_json.loads(r.stdout) for r in (cold, first, warm)]
    assert payloads[0] == payloads[1] == payloads[2]
    assert payloads[0]["findings"], "the planted finding must survive caching"
    assert "analyses served" in warm.stderr


# ---------------------------------------------------------------------------
# CLI: --format sarif
# ---------------------------------------------------------------------------

def test_format_sarif_findings_and_rule_metadata(tmp_path):
    import json as _json

    root = _seed_tree(tmp_path, "models/bad.py", TWO_LAYER_LAUNDER)
    result = _run_cli(
        "--root", str(root), "--rule", "host-sync-leak", "--format", "sarif"
    )
    assert result.returncode == 1, result.stdout + result.stderr
    payload = _json.loads(result.stdout)
    assert payload["version"] == "2.1.0"
    run = payload["runs"][0]
    assert run["tool"]["driver"]["name"] == "tpulint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"host-sync-leak", "mesh-axis", "spec-consistency"} <= rule_ids
    unsuppressed = [r for r in run["results"] if "suppressions" not in r]
    (finding,) = unsuppressed
    assert finding["ruleId"] == "host-sync-leak"
    loc = finding["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "flink_ml_tpu/models/bad.py"
    assert loc["region"]["startLine"] == 16


def test_format_sarif_clean_tree_exit_zero(tmp_path):
    import json as _json

    root = _seed_tree(tmp_path, "models/ok.py", "x = 1\n")
    result = _run_cli(
        "--root", str(root), "--rule", "host-sync-leak", "--format", "sarif"
    )
    assert result.returncode == 0, result.stdout + result.stderr
    payload = _json.loads(result.stdout)
    assert payload["runs"][0]["results"] == []
