"""Tier-1 tpulint gate: the full rule pass over flink_ml_tpu/ must report
zero unsuppressed findings — this is the static rail the dispatch-bound
perf work runs on (docs/static_analysis.md). Also pins the CLI contract:
exit 0 on the clean tree, exit 1 with file:line + rule id when any single
known-bad fixture is seeded, and a working --changed fast path."""

import os
import shutil
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TPULINT = os.path.join(REPO, "scripts", "tpulint.py")


def _run_cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, TPULINT, *args],
        capture_output=True,
        text=True,
        cwd=cwd,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )


def test_package_is_clean_full_rule_pass():
    """THE gate: every rule over the whole package, zero unsuppressed
    findings (suppressions carry reasons and are the audited sync census;
    an unused suppression would itself fail this)."""
    from flink_ml_tpu.analysis import engine

    report = engine.run()
    assert report.findings == [], "\n".join(f.format() for f in report.findings)
    # the census is non-empty: deliberate sync/compile points are annotated
    assert len(report.suppressed) >= 5


def test_cli_exit_zero_on_clean_tree():
    result = _run_cli()
    assert result.returncode == 0, result.stdout + result.stderr
    assert "clean" in result.stdout


def test_cli_list_rules_catalogue():
    result = _run_cli("--list-rules")
    assert result.returncode == 0
    for rule_id in (
        "host-sync-leak",
        "retrace-hazard",
        "donation-after-use",
        "sharding-tags",
        "collective-accounting",
        "upload-accounting",
        "fusion-coverage",
        "checkpoint-coverage",
        "unused-suppression",
    ):
        assert rule_id in result.stdout, rule_id


def _seed_tree(tmp_path, rel, source, extra=None):
    """A minimal fixture package containing one known-bad file."""
    files = {
        "__init__.py": "",
        "utils/__init__.py": "",
        "utils/lazyjit.py": "def lazy_jit(fn, **kw):\n    return fn\n",
        "models/__init__.py": "",
        rel: textwrap.dedent(source),
    }
    files.update(extra or {})
    for name, src in files.items():
        path = tmp_path / "flink_ml_tpu" / name
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
    return tmp_path


SEED_CASES = [
    (
        "raw-jax-jit",
        "models/bad.py",
        """
        import jax

        def _impl(x):
            return x

        _kernel = jax.jit(_impl)
        """,
        "retrace-hazard",
        "flink_ml_tpu/models/bad.py:7",
        None,
    ),
    (
        "unaccounted-item",
        "models/bad.py",
        """
        import jax.numpy as jnp

        def fit(X):
            return jnp.mean(X).item()
        """,
        "host-sync-leak",
        "flink_ml_tpu/models/bad.py:5",
        None,
    ),
    (
        "donated-then-read",
        "models/bad.py",
        """
        import jax

        def _impl(a, b):
            return a + b

        _step_donating = jax.jit(_impl, donate_argnums=(0,))

        def fit(carry, other):
            out = _step_donating(carry, other)
            return out + carry
        """,
        "donation-after-use",
        "flink_ml_tpu/models/bad.py:11",
        None,
    ),
    (
        "unknown-ckpt-tag",
        "models/bad.py",
        """
        from ..ckpt.snapshot import save_job_snapshot

        def checkpoint(path, carry):
            save_job_snapshot(path, "job", {"model": carry},
                              specs={"model": "fully_sharded"})
        """,
        "sharding-tags",
        "flink_ml_tpu/models/bad.py:6",
        {
            "ckpt/__init__.py": "",
            "ckpt/snapshot.py": (
                '_SPEC_TAGS = ("replicated", "data", "model", "host")\n'
                "def _sharding_for(tag, mesh, ndim):\n"
                '    if tag == "data":\n'
                "        return 1\n"
                '    if tag == "model":\n'
                "        return 2\n"
                "    return 0\n"
                "def save_job_snapshot(path, key, sections, specs=None, **kw):\n"
                "    pass\n"
                "def stage_section(snap, name, mesh=None, specs=None):\n"
                "    pass\n"
            ),
            "parallel/__init__.py": "",
            "parallel/mesh.py": (
                "def replicated_sharding(mesh):\n    pass\n"
                "def data_sharding(mesh, ndim=1):\n    pass\n"
                "def model_sharding(mesh, ndim=1):\n    pass\n"
            ),
        },
    ),
]


@pytest.mark.parametrize(
    "name,rel,source,rule,location,extra",
    SEED_CASES,
    ids=[c[0] for c in SEED_CASES],
)
def test_seeded_known_bad_fixture_fails_with_location(
    tmp_path, name, rel, source, rule, location, extra
):
    """Acceptance contract: seeding any single known-bad fixture makes the
    CLI exit 1 and name the file:line and rule id."""
    root = _seed_tree(tmp_path, rel, source, extra)
    result = _run_cli("--root", str(root), "--rule", rule)
    assert result.returncode == 1, result.stdout + result.stderr
    assert location in result.stdout, result.stdout
    assert rule in result.stdout


def test_changed_mode_reports_only_changed_files(tmp_path):
    """--changed lints files differing from HEAD (here: a fresh git repo
    whose HEAD lacks the planted bad file)."""
    root = _seed_tree(
        tmp_path,
        "models/bad.py",
        """
        import jax

        def _impl(x):
            return x

        _kernel = jax.jit(_impl)
        """,
    )
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "GIT_AUTHOR_NAME": "t",
        "GIT_AUTHOR_EMAIL": "t@t",
        "GIT_COMMITTER_NAME": "t",
        "GIT_COMMITTER_EMAIL": "t@t",
    }

    def git(*args):
        subprocess.run(
            ["git", *args], cwd=str(root), check=True, capture_output=True, env=env
        )

    git("init", "-q")
    # only the clean files are committed: bad.py stays untracked, i.e.
    # "changed relative to HEAD"
    git("add", "flink_ml_tpu/__init__.py", "flink_ml_tpu/utils")
    git("commit", "-q", "-m", "seed")
    result = _run_cli("--root", str(root), "--changed", "--rule", "retrace-hazard")
    assert result.returncode == 1, result.stdout + result.stderr
    assert "flink_ml_tpu/models/bad.py:7" in result.stdout

    # everything committed -> nothing differs from HEAD -> exit 0 fast
    git("add", "-A")
    git("commit", "-q", "-m", "rest")
    result = _run_cli("--root", str(root), "--changed", "--rule", "retrace-hazard")
    assert result.returncode == 0, result.stdout + result.stderr
    assert "no files differ" in result.stdout
