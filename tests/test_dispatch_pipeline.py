"""Dispatch-pipeline battery: epoch chunking must be invisible.

The chunked loops (docs/performance.md) fuse K epochs per device program,
drain convergence scalars through a bounded-depth queue, and donate
carries between chunks — but the tol check still runs at every epoch
inside the chunk program, so the final carry, stop epoch, and stop
criteria must be BIT-IDENTICAL to the unchunked (K=1) loop for any K.
These tests pin that guarantee, and the host-sync budget the pipeline
exists to enforce.
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flink_ml_tpu import config
from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS, SPARSE_BINARY_LOGISTIC_LOSS
from flink_ml_tpu.ops.optimizer import SGD
from flink_ml_tpu.parallel import dispatch
from flink_ml_tpu.parallel.iteration import iterate_bounded
from flink_ml_tpu.table import Table
from flink_ml_tpu.utils import metrics

K_VALUES = [1, 4, 32, "maxIter"]


@pytest.fixture
def chunk_size():
    """Restore the process-wide chunk knob after each test."""
    yield None
    config.iteration_chunk_size = None


def _dense_problem(n=400, d=8, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ np.linspace(1, -1, d) > 0).astype(np.float32)
    return X, y


def _sparse_problem(n=96, d=12, seed=7):
    rng = np.random.RandomState(seed)
    nnz = 4
    indices = np.stack([rng.choice(d, nnz, replace=False) for _ in range(n)]).astype(
        np.int32
    )
    values = rng.randn(n, nnz).astype(np.float32)
    w_true = np.linspace(1, -1, d)
    dense = np.zeros((n, d), np.float32)
    np.put_along_axis(dense, indices, values, axis=1)
    y = (dense @ w_true > 0).astype(np.float32)
    return (indices, values), y


def _fit_chunked(X, y, loss, d, tmp_path, k, max_iter=40, tol=0.0):
    """One checkpointed (= chunked host-driven) SGD fit at chunk size k."""
    config.iteration_chunk_size = max_iter if k == "maxIter" else k
    sgd = SGD(
        max_iter=max_iter,
        global_batch_size=100,
        tol=tol,
        checkpoint_dir=str(tmp_path / f"ck_{k}"),
    )
    return sgd.optimize(np.zeros(d), X, y, None, loss)


class TestChunkParity:
    """Chunked vs unchunked: K=1 IS the old per-epoch loop; every other K
    must reproduce it bit for bit, including the stop epoch."""

    def test_sgd_dense_all_chunk_sizes(self, tmp_path, chunk_size):
        X, y = _dense_problem()
        base = _fit_chunked(X, y, BINARY_LOGISTIC_LOSS, 8, tmp_path, 1)
        assert base[2] == 40
        for k in K_VALUES[1:]:
            got = _fit_chunked(X, y, BINARY_LOGISTIC_LOSS, 8, tmp_path, k)
            np.testing.assert_array_equal(got[0], base[0])
            assert got[1] == base[1]
            assert got[2] == base[2]

    def test_sgd_dense_tol_fires_mid_chunk(self, tmp_path, chunk_size):
        """Stop epoch when tol fires INSIDE a chunk: identical for any K —
        the chunk program's while condition checks tol every epoch, it
        does not overshoot to the chunk boundary."""
        X, y = _dense_problem()
        # the criteria value at epoch 10 becomes tol: the full run then
        # stops at the first epoch at or below it — mid-run by construction
        probe = _fit_chunked(X, y, BINARY_LOGISTIC_LOSS, 8, tmp_path, 1, max_iter=10)
        tol = float(probe[1])
        base = _fit_chunked(X, y, BINARY_LOGISTIC_LOSS, 8, tmp_path, 1, tol=tol)
        assert 0 < base[2] < 40, "tol must fire mid-run for this test to bite"
        for k in K_VALUES[1:]:
            got = _fit_chunked(X, y, BINARY_LOGISTIC_LOSS, 8, tmp_path, k, tol=tol)
            np.testing.assert_array_equal(got[0], base[0])
            assert got[2] == base[2], f"stop epoch diverged at K={k}"

    def test_sgd_sparse_all_chunk_sizes(self, tmp_path, chunk_size):
        Xs, y = _sparse_problem()
        base = _fit_chunked(Xs, y, SPARSE_BINARY_LOGISTIC_LOSS, 12, tmp_path, 1)
        for k in K_VALUES[1:]:
            got = _fit_chunked(Xs, y, SPARSE_BINARY_LOGISTIC_LOSS, 12, tmp_path, k)
            np.testing.assert_array_equal(got[0], base[0])
            assert got[2] == base[2]

    def test_checkpoint_resume_matches_uninterrupted(self, tmp_path, chunk_size):
        """Kill mid-training, resume with a different chunk size: the
        resumed run must land on the uninterrupted run's exact result."""
        X, y = _dense_problem()
        full = _fit_chunked(X, y, BINARY_LOGISTIC_LOSS, 8, tmp_path, 1)

        ck = str(tmp_path / "resume")
        config.iteration_chunk_size = 4
        SGD(
            max_iter=13, global_batch_size=100, tol=0.0, checkpoint_dir=ck
        ).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
        config.iteration_chunk_size = 32
        got = SGD(
            max_iter=40, global_batch_size=100, tol=0.0, checkpoint_dir=ck
        ).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
        np.testing.assert_array_equal(got[0], full[0])
        assert got[2] == 40

    def test_chunk_ends_clamp_to_checkpoint_boundaries(self, tmp_path, chunk_size):
        """checkpoint_interval=5 with K=32: snapshots still land at the
        exact epoch cadence (chunk ends clamp to boundaries)."""
        from flink_ml_tpu.parallel.iteration import load_iteration_checkpoint

        X, y = _dense_problem()
        ck = str(tmp_path / "cadence")
        config.iteration_chunk_size = 32
        SGD(
            max_iter=12,
            global_batch_size=100,
            tol=0.0,
            checkpoint_dir=ck,
            checkpoint_interval=5,
        ).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
        carry_like = (jnp.zeros(8), jnp.zeros(8), jnp.asarray(0.0), jnp.asarray(0))
        restored = load_iteration_checkpoint(ck, carry_like)
        assert restored is not None
        assert restored[1] == 10  # last multiple of 5 <= 12


class TestIterateBoundedChunked:
    """The generic iteration runtime: host-driven chunked loop vs the pure
    on-device while_loop, Lloyd-style body included."""

    @staticmethod
    def _lloyd_body(X):
        def body(carry, epoch):
            centroids = carry
            d2 = ((X[:, None, :] - centroids[None, :, :]) ** 2).sum(-1)
            assign = jnp.argmin(d2, axis=1)
            one_hot = jax.nn.one_hot(assign, centroids.shape[0], dtype=X.dtype)
            counts = one_hot.sum(0)
            sums = one_hot.T @ X
            new = jnp.where(
                counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1e-30),
                centroids,
            )
            shift = jnp.max(jnp.abs(new - centroids))
            return new, shift

        return body

    def test_lloyd_body_chunked_matches_on_device(self, tmp_path, chunk_size):
        rng = np.random.RandomState(0)
        X = jnp.asarray(rng.randn(60, 3).astype(np.float32))
        init = X[:4]
        body = self._lloyd_body(X)
        on_device = iterate_bounded(body, init, max_iter=25, tol=1e-4)
        assert 0 < on_device.num_epochs <= 25
        for k in [1, 4, 32, 25]:
            res = iterate_bounded(
                body, init, max_iter=25, tol=1e-4,
                checkpoint_dir=str(tmp_path / f"lloyd_{k}"), chunk_size=k,
            )
            np.testing.assert_array_equal(
                np.asarray(res.carry), np.asarray(on_device.carry)
            )
            assert res.num_epochs == on_device.num_epochs

    def test_listener_still_sees_every_epoch(self, tmp_path):
        """A listener forces per-epoch dispatch (K=1) — the listener
        contract exposes every (epoch, carry) pair, chunking must not
        swallow callbacks."""
        from flink_ml_tpu.parallel.iteration import IterationListener

        seen = []

        class Rec(IterationListener):
            def on_epoch_watermark_incremented(self, epoch, carry):
                seen.append(epoch)

            def on_iteration_terminated(self, carry):
                seen.append("end")

        body = lambda c, e: (c + 1.0, jnp.asarray(1.0, jnp.float32))
        res = iterate_bounded(body, jnp.zeros(2), max_iter=5, tol=None, listener=Rec())
        assert seen == [1, 2, 3, 4, 5, "end"]
        assert res.num_epochs == 5

    def test_lloyd_donating_variant_bit_identical(self):
        """KMeans' donating Lloyd kernel (HBM ping-pong) computes exactly
        what the borrowing one does."""
        from flink_ml_tpu.models.clustering.kmeans import (
            _lloyd_train,
            _lloyd_train_donating,
        )

        rng = np.random.RandomState(1)
        X = rng.randn(50, 4).astype(np.float32)
        w = np.ones(50, np.float32)
        init = X[:3]
        mi = jnp.asarray(10, jnp.int32)
        c_b, n_b = _lloyd_train(jnp.asarray(X), jnp.asarray(w), jnp.asarray(init), mi, "euclidean")
        c_d, n_d = _lloyd_train_donating(
            jnp.asarray(X), jnp.asarray(w), jnp.asarray(init), mi, "euclidean"
        )
        np.testing.assert_array_equal(np.asarray(c_b), np.asarray(c_d))
        np.testing.assert_array_equal(np.asarray(n_b), np.asarray(n_d))


class TestHostSyncBudget:
    """The acceptance metric: a maxIter=200 LR fit must not sync O(200)
    times. Fused path: exactly 1. Chunked checkpointed path: the
    convergence drains stay within ceil(200/K) + dispatch_depth."""

    MAX_ITER = 200

    def _delta(self, fn):
        before = metrics.snapshot()
        fn()
        return metrics.snapshot_delta(before, metrics.snapshot())["counters"]

    def test_fused_lr_fit_is_one_sync(self):
        from flink_ml_tpu.models.classification.logisticregression import (
            LogisticRegression,
        )

        X, y = _dense_problem(n=600)
        t = Table({"features": X.astype(np.float64), "label": y.astype(np.float64)})
        lr = (
            LogisticRegression()
            .set_max_iter(self.MAX_ITER)
            .set_global_batch_size(200)
            .set_reg(0.01)
        )
        counters = self._delta(lambda: lr.fit(t))
        k = config.iteration_chunk_for(self.MAX_ITER)
        budget = math.ceil(self.MAX_ITER / k) + 2
        assert counters.get("iteration.host_sync", 0) == 1 <= budget

    def test_chunked_lr_fit_within_budget(self, tmp_path, chunk_size):
        # whole_fit off: this pins the CHUNKED path's drain budget (the
        # fit-end-only snapshot cadence would otherwise go resident)
        with config.whole_fit_mode("off"):
            for k in [4, 32, self.MAX_ITER]:
                config.iteration_chunk_size = k
                X, y = _dense_problem()
                sgd = SGD(
                    max_iter=self.MAX_ITER,
                    global_batch_size=100,
                    tol=0.0,
                    checkpoint_dir=str(tmp_path / f"budget_{k}"),
                    checkpoint_interval=self.MAX_ITER,  # snapshot only at the end
                )
                counters = self._delta(
                    lambda: sgd.optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
                )
                budget = math.ceil(self.MAX_ITER / k) + 2
                drains = counters.get("iteration.host_sync.drain", 0)
                assert drains <= budget, f"K={k}: {drains} drains > budget {budget}"
                # total syncs = drains + 1 end checkpoint + 1 packed fit readback
                assert counters.get("iteration.host_sync", 0) <= budget + 2

    def test_per_epoch_regression_guard(self, tmp_path, chunk_size):
        """K=1 (the old behavior) really is O(maxIter) — the counter
        measures what it claims, so a regression cannot hide in it.
        whole_fit off: the resident path would collapse this to 1."""
        config.iteration_chunk_size = 1
        X, y = _dense_problem()
        sgd = SGD(
            max_iter=50, global_batch_size=100, tol=0.0,
            checkpoint_dir=str(tmp_path / "k1"), checkpoint_interval=50,
        )
        with config.whole_fit_mode("off"):
            counters = self._delta(
                lambda: sgd.optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
            )
        assert counters.get("iteration.host_sync.drain", 0) == 50


class TestDispatchPrimitives:
    def test_chunk_for_adaptive(self):
        assert config.iteration_chunk_for(1) == 1
        assert config.iteration_chunk_for(8) == 1
        assert config.iteration_chunk_for(80) == 10
        assert config.iteration_chunk_for(200) == 25
        assert config.iteration_chunk_for(10_000) == 32  # clamped
        assert config.iteration_chunk_for(100, chunk_size=7) == 7
        assert config.iteration_chunk_for(5, chunk_size=64) == 5  # <= maxIter

    def test_chunk_for_respects_process_knob(self):
        config.iteration_chunk_size = 16
        try:
            assert config.iteration_chunk_for(200) == 16
        finally:
            config.iteration_chunk_size = None

    def test_next_boundary(self):
        assert dispatch.next_boundary(0, 5) == 5
        assert dispatch.next_boundary(4, 5) == 5
        assert dispatch.next_boundary(5, 5) == 10
        assert dispatch.next_boundary(7, None) is None
        assert dispatch.next_boundary(7, 0) is None

    def test_drain_queue_depth(self):
        q = dispatch.DrainQueue(2)
        entries = [
            dispatch.InFlight(i, i + 1, None, jnp.asarray([float(i + 1), 0.5]))
            for i in range(4)
        ]
        assert q.push(entries[0]) == []
        assert q.push(entries[1]) == []
        drained = q.push(entries[2])  # over depth: oldest comes back
        assert len(drained) == 1 and drained[0][1] == 1
        rest = q.drain_all()
        assert [e for _, e, _ in rest] == [2, 3]
        assert len(q) == 0

    def test_supports_donation_is_false_on_cpu(self):
        assert jax.default_backend() == "cpu"
        assert dispatch.supports_donation() is False

    def test_drain_accounting(self):
        before = metrics.snapshot()
        q = dispatch.DrainQueue(1)
        q.push(dispatch.InFlight(0, 1, None, jnp.asarray([1.0, 0.5])))
        q.drain_all()
        delta = metrics.snapshot_delta(before, metrics.snapshot())["counters"]
        assert delta.get("iteration.host_sync.drain", 0) == 1


# ---------------------------------------------------------------------------
# whole-fit resident programs (config.whole_fit, docs/performance.md)
# ---------------------------------------------------------------------------

def _counters(fn):
    before = metrics.snapshot()
    out = fn()
    return out, metrics.snapshot_delta(before, metrics.snapshot())["counters"]


def _stream_chunks(X, y, chunk=160):
    for i in range(0, X.shape[0], chunk):
        yield X[i : i + chunk], y[i : i + chunk], None


WHOLE_FIT_ITERS = [1, 7, 200]


class TestWholeFitParity:
    """The whole-fit resident path must be INVISIBLE: carries, stop
    epochs, and final packs bit-identical to the chunked/per-epoch
    reference (`whole_fit` off) for every covered loop, including
    tol-early-stop — while collapsing the fit to one dispatch + one
    packed readback."""

    def _ckpt_fit(self, X, y, loss, d, tmp_path, tag, max_iter, tol=0.0):
        sgd = SGD(
            max_iter=max_iter,
            global_batch_size=100,
            tol=tol,
            checkpoint_dir=str(tmp_path / tag),
            checkpoint_key=tag,
            checkpoint_interval=max_iter,  # fit-end boundary only
        )
        return sgd.optimize(np.zeros(d), X, y, None, loss)

    @pytest.mark.parametrize("max_iter", WHOLE_FIT_ITERS)
    def test_checkpointed_dense_sgd(self, tmp_path, max_iter):
        X, y = _dense_problem()
        with config.whole_fit_mode("off"):
            ref = self._ckpt_fit(X, y, BINARY_LOGISTIC_LOSS, 8, tmp_path, "off", max_iter)
        got, counters = _counters(
            lambda: self._ckpt_fit(X, y, BINARY_LOGISTIC_LOSS, 8, tmp_path, "on", max_iter)
        )
        np.testing.assert_array_equal(got[0], ref[0])
        assert got[1] == ref[1] and got[2] == ref[2] == max_iter
        assert counters.get("dispatch.whole_fit.sgd", 0) == 1
        assert counters.get("iteration.host_sync.drain", 0) == 0
        assert counters.get("iteration.host_sync.fit", 0) == 1

    @pytest.mark.parametrize("max_iter", WHOLE_FIT_ITERS)
    def test_checkpointed_sparse_sgd(self, tmp_path, max_iter):
        Xs, y = _sparse_problem()
        with config.whole_fit_mode("off"):
            ref = self._ckpt_fit(
                Xs, y, SPARSE_BINARY_LOGISTIC_LOSS, 12, tmp_path, "soff", max_iter
            )
        got = self._ckpt_fit(
            Xs, y, SPARSE_BINARY_LOGISTIC_LOSS, 12, tmp_path, "son", max_iter
        )
        np.testing.assert_array_equal(got[0], ref[0])
        assert got[2] == ref[2]

    def test_checkpointed_tol_early_stop(self, tmp_path):
        """tol fires mid-fit: the resident program's per-epoch convergence
        check must land on the chunked path's exact stop epoch."""
        X, y = _dense_problem()
        probe = self._ckpt_fit(X, y, BINARY_LOGISTIC_LOSS, 8, tmp_path, "probe", 10)
        tol = float(probe[1])
        with config.whole_fit_mode("off"):
            ref = self._ckpt_fit(X, y, BINARY_LOGISTIC_LOSS, 8, tmp_path, "toff", 40, tol)
        assert 0 < ref[2] < 40, "tol must fire mid-run for this test to bite"
        got = self._ckpt_fit(X, y, BINARY_LOGISTIC_LOSS, 8, tmp_path, "ton", 40, tol)
        np.testing.assert_array_equal(got[0], ref[0])
        assert got[1] == ref[1] and got[2] == ref[2]

    @pytest.mark.parametrize("max_iter", WHOLE_FIT_ITERS)
    def test_stream_sgd(self, max_iter):
        X, y = _dense_problem()
        sgd = lambda: SGD(max_iter=max_iter, global_batch_size=100, tol=0.0)
        with config.whole_fit_mode("off"):
            ref = sgd().optimize_stream(
                np.zeros(8), _stream_chunks(X, y), BINARY_LOGISTIC_LOSS
            )
        got, counters = _counters(
            lambda: sgd().optimize_stream(
                np.zeros(8), _stream_chunks(X, y), BINARY_LOGISTIC_LOSS
            )
        )
        np.testing.assert_array_equal(got[0], ref[0])
        assert got[1] == ref[1] and got[2] == ref[2] == max_iter
        assert got[3]["wholeFit"] is True
        assert counters.get("dispatch.whole_fit.stream", 0) == 1
        # THE acceptance pin: the whole out-of-core fit is one blocking
        # host<->device sync — one dispatch, one packed readback
        assert counters.get("iteration.host_sync", 0) == 1

    def test_stream_sgd_tol_early_stop(self):
        X, y = _dense_problem()
        with config.whole_fit_mode("off"):
            probe = SGD(max_iter=10, global_batch_size=100, tol=0.0).optimize_stream(
                np.zeros(8), _stream_chunks(X, y), BINARY_LOGISTIC_LOSS
            )
            tol = float(probe[1])
            ref = SGD(max_iter=40, global_batch_size=100, tol=tol).optimize_stream(
                np.zeros(8), _stream_chunks(X, y), BINARY_LOGISTIC_LOSS
            )
        assert 0 < ref[2] < 40
        got = SGD(max_iter=40, global_batch_size=100, tol=tol).optimize_stream(
            np.zeros(8), _stream_chunks(X, y), BINARY_LOGISTIC_LOSS
        )
        np.testing.assert_array_equal(got[0], ref[0])
        assert got[1] == ref[1] and got[2] == ref[2]

    def test_stream_lloyd(self):
        from flink_ml_tpu.models.clustering.kmeans import KMeans
        from flink_ml_tpu.table import StreamTable

        rng = np.random.RandomState(0)
        X = rng.randn(320, 3).astype(np.float64)
        batches = [Table({"features": X[i : i + 64]}) for i in range(0, 320, 64)]
        km = lambda: (
            KMeans().set_k(4).set_seed(11).set_max_iter(7)
        )
        with config.whole_fit_mode("off"):
            ref = km().fit(StreamTable.from_batches(batches))
        got, counters = _counters(
            lambda: km().fit(StreamTable.from_batches(batches))
        )
        np.testing.assert_array_equal(got.centroids, ref.centroids)
        np.testing.assert_array_equal(got.weights, ref.weights)
        assert counters.get("dispatch.whole_fit.lloyd", 0) == 1
        assert counters.get("iteration.host_sync", 0) == 1

    def test_iterate_bounded_whole_fit(self, tmp_path):
        """The generic runtime: fit-end-only snapshot cadence goes
        resident (one dispatch + one drain), bit-identical to chunked."""
        body = TestIterateBoundedChunked._lloyd_body(
            jnp.asarray(np.random.RandomState(0).randn(60, 3).astype(np.float32))
        )
        init = jnp.zeros((4, 3))
        with config.whole_fit_mode("off"):
            ref = iterate_bounded(
                body, init, max_iter=25, tol=1e-4,
                checkpoint_dir=str(tmp_path / "off"), checkpoint_interval=25,
            )
        got, counters = _counters(
            lambda: iterate_bounded(
                body, init, max_iter=25, tol=1e-4,
                checkpoint_dir=str(tmp_path / "on"), checkpoint_interval=25,
            )
        )
        np.testing.assert_array_equal(np.asarray(got.carry), np.asarray(ref.carry))
        assert got.num_epochs == ref.num_epochs
        assert counters.get("dispatch.whole_fit.iterate", 0) == 1
        assert counters.get("iteration.host_sync.drain", 0) == 1


class TestWholeFitFallbacks:
    """Ineligible fits fall back to the chunked path, counted per reason
    (`dispatch.whole_fit_fallback.<reason>`) — and still compute the
    reference result."""

    def test_mid_fit_checkpoint_interval_falls_back(self, tmp_path):
        X, y = _dense_problem()
        sgd = SGD(
            max_iter=12, global_batch_size=100, tol=0.0,
            checkpoint_dir=str(tmp_path / "mid"), checkpoint_key="mid",
            checkpoint_interval=4,
        )
        _, counters = _counters(
            lambda: sgd.optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
        )
        assert counters.get("dispatch.whole_fit_fallback.checkpoint_interval", 0) == 1
        assert counters.get("dispatch.whole_fit.sgd", 0) == 0
        assert counters.get("iteration.host_sync.drain", 0) >= 1

    def test_stream_over_budget_falls_back(self):
        X, y = _dense_problem()
        with config.device_cache_budget(1024):  # stack ≫ 1KB
            got, counters = _counters(
                lambda: SGD(
                    max_iter=6, global_batch_size=100, tol=0.0
                ).optimize_stream(np.zeros(8), _stream_chunks(X, y), BINARY_LOGISTIC_LOSS)
            )
        assert counters.get("dispatch.whole_fit_fallback.device_cache_budget", 0) == 1
        assert "wholeFit" not in got[3]
        with config.whole_fit_mode("off"):
            ref = SGD(max_iter=6, global_batch_size=100, tol=0.0).optimize_stream(
                np.zeros(8), _stream_chunks(X, y), BINARY_LOGISTIC_LOSS
            )
        np.testing.assert_array_equal(got[0], ref[0])

    def test_ragged_kmeans_stream_falls_back(self):
        from flink_ml_tpu.models.clustering.kmeans import KMeans
        from flink_ml_tpu.table import StreamTable

        rng = np.random.RandomState(1)
        # 64-row and 200-row batches bucket to different row counts
        batches = [
            Table({"features": rng.randn(rows, 3).astype(np.float64)})
            for rows in (64, 200, 64)
        ]
        km = KMeans().set_k(3).set_seed(5).set_max_iter(4)
        _, counters = _counters(
            lambda: km.fit(StreamTable.from_batches(batches))
        )
        assert counters.get("dispatch.whole_fit_fallback.ragged_batches", 0) == 1
        assert counters.get("dispatch.whole_fit.lloyd", 0) == 0

    def test_listener_falls_back(self):
        from flink_ml_tpu.parallel.iteration import IterationListener

        seen = []

        class Rec(IterationListener):
            def on_epoch_watermark_incremented(self, epoch, carry):
                seen.append(epoch)

        body = lambda c, e: (c + 1.0, jnp.asarray(1.0, jnp.float32))
        _, counters = _counters(
            lambda: iterate_bounded(
                body, jnp.zeros(2), max_iter=3, tol=None, listener=Rec()
            )
        )
        assert seen == [1, 2, 3]
        assert counters.get("dispatch.whole_fit_fallback.listener", 0) == 1

    def test_off_mode_counts_nothing(self, tmp_path):
        X, y = _dense_problem()
        with config.whole_fit_mode("off"):
            _, counters = _counters(
                lambda: SGD(
                    max_iter=6, global_batch_size=100, tol=0.0,
                    checkpoint_dir=str(tmp_path / "off2"), checkpoint_key="o",
                ).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
            )
        assert counters.get("dispatch.whole_fit", 0) == 0
        assert counters.get("dispatch.whole_fit_fallback", 0) == 0


class TestPallasSparseKernels:
    """ops/sparsekernels.py: the Pallas gather-dot and segment-sum must be
    bit-identical to the lax path — same masking, same accumulation
    order — and the flag routes fits through them."""

    def _matrix(self, n=64, d=24, nnz=5, seed=9):
        rng = np.random.RandomState(seed)
        indices = np.stack(
            [rng.choice(d, nnz, replace=False) for _ in range(n)]
        ).astype(np.int32)
        values = rng.randn(n, nnz).astype(np.float32)
        indices[-3:, -2:] = -1  # padding rows exercise the mask
        return indices, values

    def test_row_dots_bit_identical(self):
        from flink_ml_tpu.ops.losses import sparse_dot
        from flink_ml_tpu.ops.sparsekernels import sparse_row_dots

        indices, values = self._matrix()
        coeff = jnp.asarray(np.random.RandomState(2).randn(24).astype(np.float32))
        ref, _, _ = sparse_dot(jnp.asarray(indices), jnp.asarray(values), coeff)
        got = sparse_row_dots(jnp.asarray(indices), jnp.asarray(values), coeff)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_grad_matches_lax_segment_sum(self):
        from flink_ml_tpu.ops.sparsekernels import sparse_grad

        indices, values = self._matrix()
        d = 24
        mult = jnp.asarray(np.random.RandomState(4).randn(64).astype(np.float32))
        coeff = jnp.zeros((d,), jnp.float32)
        valid = indices >= 0
        safe = np.where(valid, indices, 0)
        vals = np.where(valid, values, 0.0)
        ref = (
            jnp.zeros_like(coeff)
            .at[jnp.asarray(safe)]
            .add(jnp.asarray(vals) * mult[:, None], mode="drop")
        )
        got = sparse_grad(jnp.asarray(indices), jnp.asarray(values), mult, coeff)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_sparse_fit_bit_identical_and_flag_routes(self):
        from flink_ml_tpu.ops.losses import (
            PALLAS_SPARSE_BINARY_LOGISTIC_LOSS,
            sparse_variant,
        )
        from flink_ml_tpu.parallel import mesh as mesh_lib

        assert sparse_variant("binary_logistic").name == "sparse_binary_logistic"
        with config.pallas_sparse_mode():
            assert (
                sparse_variant("binary_logistic")
                is PALLAS_SPARSE_BINARY_LOGISTIC_LOSS
            )
        Xs, y = _sparse_problem()
        # single data shard: the whole fit must be BIT-identical (same
        # masking + accumulation order). Across a sharded mesh GSPMD
        # partitions the two formulations with different cross-shard
        # reduction orders (the documented cross-shard caveat), so the
        # default-mesh check is allclose.
        mesh1 = mesh_lib.create_mesh(
            (mesh_lib.DATA_AXIS,), devices=jax.devices()[:1]
        )
        sgd = lambda loss, mesh: SGD(
            max_iter=9, global_batch_size=32, tol=0.0
        ).optimize(np.zeros(12), Xs, y, None, loss, mesh=mesh)
        ref = sgd(SPARSE_BINARY_LOGISTIC_LOSS, mesh1)
        got = sgd(PALLAS_SPARSE_BINARY_LOGISTIC_LOSS, mesh1)
        np.testing.assert_array_equal(got[0], ref[0])
        assert got[1] == ref[1] and got[2] == ref[2]
        ref8 = sgd(SPARSE_BINARY_LOGISTIC_LOSS, None)
        got8 = sgd(PALLAS_SPARSE_BINARY_LOGISTIC_LOSS, None)
        np.testing.assert_allclose(got8[0], ref8[0], rtol=1e-6, atol=1e-7)
        assert got8[2] == ref8[2]
