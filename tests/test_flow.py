"""flow.py battery — the flow-control and transient-fault contracts.

Pins: credit-based BoundedChannel semantics per overload policy (block =
lossless in-order backpressure; shed_oldest = bounded memory AND bounded
staleness; sample = bounded memory, prefix sample; reject = typed
fast-fail with live depth), in-order error propagation from a pump worker
(a dead producer can never stall a blocked consumer), with_retries'
taxonomy (transient-only, original error re-raised with attempt count,
deadline/budget bounds), the flaky fault mode that makes retry paths
injectable, and the straggler watchdog's counters.
"""

import time

import pytest

from flink_ml_tpu import config, flow
from flink_ml_tpu.ckpt import faults
from flink_ml_tpu.ckpt.faults import InjectedFault, TransientFault
from flink_ml_tpu.utils import metrics


# ---------------------------------------------------------------------------
# BoundedChannel policies
# ---------------------------------------------------------------------------

class TestBoundedChannel:
    def test_block_policy_lossless_in_order(self):
        chan = flow.BoundedChannel(3, name="t.block")
        flow.pump(range(50), chan, transform=lambda i: i * i)
        assert list(chan) == [i * i for i in range(50)]
        assert chan.stats.puts == 50 and chan.stats.gets == 50
        assert chan.stats.shed == 0 and chan.stats.rejected == 0
        assert chan.stats.peak_depth <= 3

    def test_block_policy_backpressures_producer(self):
        """The producer cannot run more than `capacity` items ahead."""
        staged = []
        chan = flow.BoundedChannel(2, name="t.credit")
        flow.pump(range(100), chan, transform=lambda i: staged.append(i) or i)
        assert chan.get() == 0
        time.sleep(0.05)
        # consumed 1; at most capacity staged beyond it + 1 in flight
        assert len(staged) <= 1 + 2 + 1
        assert chan.credits() >= 0
        chan.cancel()

    def test_shed_oldest_bounds_memory_and_staleness(self):
        capacity = 4
        chan = flow.BoundedChannel(capacity, policy=flow.SHED_OLDEST, name="t.shed")
        for burst in range(8):
            for i in range(capacity * 25):
                assert chan.put(burst * 100 + i)
            chan.get()
        assert len(chan) <= capacity
        assert chan.stats.shed > 0
        # the staleness contract: a consumed item is always one of the
        # newest `capacity` accepted at its dequeue instant
        assert chan.stats.max_lag < capacity

    def test_sample_policy_keeps_prefix(self):
        chan = flow.BoundedChannel(2, policy=flow.SAMPLE, name="t.sample")
        assert chan.put("a") and chan.put("b")
        assert not chan.put("c")  # dropped, queue keeps the prefix
        assert chan.stats.shed == 1
        assert chan.get() == "a" and chan.get() == "b"

    def test_reject_policy_typed_fast_fail_with_depth(self):
        chan = flow.BoundedChannel(2, policy=flow.REJECT, name="t.reject")
        chan.put(1)
        chan.put(2)
        with pytest.raises(flow.ChannelRejected) as ei:
            chan.put(3)
        assert ei.value.depth == 2 and ei.value.capacity == 2
        assert ei.value.channel == "t.reject"
        assert chan.stats.rejected == 1
        # a freed credit re-admits
        chan.get()
        assert chan.put(3)

    def test_put_get_timeouts(self):
        chan = flow.BoundedChannel(1, name="t.timeout")
        with pytest.raises(TimeoutError):
            chan.get(timeout=0.01)
        chan.put("x")
        with pytest.raises(TimeoutError):
            chan.put("y", timeout=0.01)

    def test_close_then_drain_then_stop(self):
        chan = flow.BoundedChannel(4, name="t.close")
        chan.put(1)
        chan.put(2)
        chan.close()
        assert chan.get() == 1 and chan.get() == 2
        with pytest.raises(flow.ChannelClosed):
            chan.get()
        with pytest.raises(flow.ChannelClosed):
            chan.put(3)

    def test_cancel_returns_queued_items(self):
        chan = flow.BoundedChannel(4, name="t.cancel")
        chan.put("a")
        chan.put("b")
        assert chan.cancel() == ["a", "b"]
        assert len(chan) == 0

    def test_error_delivered_in_order_after_staged_items(self):
        chan = flow.BoundedChannel(8, name="t.err")
        chan.put(1)
        chan.close(error=RuntimeError("boom"))
        assert chan.get() == 1  # staged-before-failure items deliver first
        with pytest.raises(RuntimeError, match="boom"):
            chan.get()

    def test_metrics_counters(self):
        before_shed = metrics.get_counter("flow.shed", 0)
        before_rej = metrics.get_counter("flow.reject", 0)
        chan = flow.BoundedChannel(1, policy=flow.SHED_OLDEST, name="t.metrics")
        chan.put(1)
        chan.put(2)
        assert metrics.get_counter("flow.shed", 0) == before_shed + 1
        chan2 = flow.BoundedChannel(1, policy=flow.REJECT, name="t.metrics2")
        chan2.put(1)
        with pytest.raises(flow.ChannelRejected):
            chan2.put(2)
        assert metrics.get_counter("flow.reject", 0) == before_rej + 1
        assert metrics.get_gauge("flow.peakQueueDepth", 0) >= 1


# ---------------------------------------------------------------------------
# pump: worker lifecycle + error propagation
# ---------------------------------------------------------------------------

class TestPump:
    def test_source_error_propagates_not_stalls(self):
        def items():
            yield 1
            yield 2
            raise OSError("source died")

        chan = flow.BoundedChannel(8, name="p.err")
        flow.pump(items(), chan)
        got = []
        with pytest.raises(OSError, match="source died"):
            for x in chan:
                got.append(x)
        assert got == [1, 2]

    def test_transform_error_propagates(self):
        chan = flow.BoundedChannel(8, name="p.terr")
        flow.pump(range(10), chan, transform=lambda i: 1 // (3 - i) and i)
        with pytest.raises(ZeroDivisionError):
            list(chan)

    def test_consumer_cancel_stops_producer(self):
        staged = []

        def stage(i):
            staged.append(i)
            return i

        chan = flow.BoundedChannel(2, name="p.cancel")
        worker = flow.pump(range(1000), chan, transform=stage)
        assert chan.get() == 0
        chan.cancel()
        worker.join(timeout=5.0)
        assert not worker.is_alive()
        assert len(staged) <= 6  # bounded speculation, no runaway staging

    def test_worker_completes_before_clean_exhaustion(self):
        chan = flow.BoundedChannel(4, name="p.done")
        worker = flow.pump(range(5), chan)
        assert list(chan) == list(range(5))
        worker.join(timeout=5.0)
        assert not worker.is_alive()


# ---------------------------------------------------------------------------
# with_retries: taxonomy, budget, deadline
# ---------------------------------------------------------------------------

class TestWithRetries:
    def test_transient_retried_to_success(self):
        calls = {"n": 0}

        def flaky_fn():
            calls["n"] += 1
            if calls["n"] < 3:
                raise flow.TransientError("blip")
            return "ok"

        before = metrics.get_counter("flow.retry", 0)
        assert flow.with_retries(flaky_fn, retries=5, base_delay_s=1e-4) == "ok"
        assert calls["n"] == 3
        assert metrics.get_counter("flow.retry", 0) == before + 2

    def test_budget_exhaustion_reraises_original_with_attempts(self):
        err = flow.TransientError("persistent")

        def always():
            raise err

        with pytest.raises(flow.TransientError) as ei:
            flow.with_retries(always, retries=2, base_delay_s=1e-4)
        assert ei.value is err  # the ORIGINAL error, not a wrapper
        assert ei.value.retry_attempts == 3  # 1 try + 2 retries

    def test_non_retryable_propagates_immediately(self):
        calls = {"n": 0}

        def data_error():
            calls["n"] += 1
            raise ValueError("bad data")

        with pytest.raises(ValueError):
            flow.with_retries(data_error, retries=5)
        assert calls["n"] == 1

    def test_injected_fault_is_a_crash_not_a_blip(self):
        """InjectedFault models a kill: a retry wrapper must NOT eat it,
        whatever the budget — retrying a crash would un-test resume."""
        calls = {"n": 0}

        def killed():
            calls["n"] += 1
            raise InjectedFault("site", 1)

        with pytest.raises(InjectedFault):
            flow.with_retries(killed, retries=10)
        assert calls["n"] == 1

    def test_zero_budget_is_fail_fast(self):
        with config.transient_retry_mode(0):
            with pytest.raises(flow.TransientError):
                flow.with_retries(
                    lambda: (_ for _ in ()).throw(flow.TransientError("x"))
                )

    def test_deadline_bounds_total_time(self):
        def always():
            raise flow.TransientError("slow")

        t0 = time.perf_counter()
        with pytest.raises(flow.TransientError) as ei:
            flow.with_retries(
                always, retries=10_000, base_delay_s=0.02, deadline_s=0.05
            )
        assert time.perf_counter() - t0 < 2.0
        assert ei.value.retry_attempts < 10_000

    def test_oserror_is_transient(self):
        calls = {"n": 0}

        def io():
            calls["n"] += 1
            if calls["n"] == 1:
                raise OSError("fs blip")
            return 7

        assert flow.with_retries(io, retries=2, base_delay_s=1e-4) == 7


# ---------------------------------------------------------------------------
# flaky fault mode (ckpt/faults.py) — the injectable transient
# ---------------------------------------------------------------------------

class TestFlakyFaults:
    def test_flaky_fails_n_times_then_succeeds(self):
        with faults.flaky("soak.site", times=2) as plan:
            for expected_fail in (True, True, False, False):
                if expected_fail:
                    with pytest.raises(TransientFault):
                        faults.tick("soak.site")
                else:
                    faults.tick("soak.site")
            assert plan.failures == 2 and plan.hits == 4

    def test_transient_fault_is_retryable_injected_is_not(self):
        assert issubclass(TransientFault, flow.TransientError)
        assert not issubclass(InjectedFault, flow.TransientError)
        with faults.flaky("retry.site", times=2):
            assert flow.with_retries(
                lambda: faults.tick("retry.site") or "ok",
                retries=3,
                base_delay_s=1e-4,
            ) == "ok"

    def test_flaky_and_inject_coexist(self):
        """A flaky plan and a fatal plan on different sites don't shadow
        each other — the mid-write-kill-then-flaky-read scenario."""
        with faults.inject("fatal.site", after=1):
            with faults.flaky("blip.site", times=1):
                with pytest.raises(TransientFault):
                    faults.tick("blip.site")
                with pytest.raises(InjectedFault):
                    faults.tick("fatal.site")

    def test_unmatched_site_passes(self):
        with faults.flaky("somewhere", times=5):
            faults.tick("elsewhere")  # no raise


# ---------------------------------------------------------------------------
# straggler watchdog
# ---------------------------------------------------------------------------

class TestStragglerWatchdog:
    def test_flags_beyond_factor_of_trailing_mean(self):
        wd = flow.StragglerWatchdog("t.stage", factor=3.0, warmup=3)
        before = metrics.get_counter("flow.straggler.t.stage", 0)
        for _ in range(5):
            assert not wd.record(0.010)
        assert wd.record(0.050)  # 5x the trailing mean
        assert metrics.get_counter("flow.straggler.t.stage", 0) == before + 1
        assert metrics.get_gauge("flow.straggler.t.stage.lastMs") == pytest.approx(50.0)

    def test_warmup_never_flags(self):
        wd = flow.StragglerWatchdog("t.warm", factor=2.0, warmup=10)
        assert not any(wd.record(t) for t in (0.001, 0.5, 0.001, 0.9))

    def test_mean_adapts_to_new_normal(self):
        """A stage that got permanently slower stops being flagged once
        the EMA catches up."""
        wd = flow.StragglerWatchdog("t.adapt", factor=3.0, warmup=2, alpha=0.5)
        for _ in range(4):
            wd.record(0.01)
        assert wd.record(0.2)  # the jump is flagged
        for _ in range(6):
            wd.record(0.2)
        assert not wd.record(0.2)  # the new normal is not

    def test_observe_context_manager(self):
        wd = flow.StragglerWatchdog("t.obs", warmup=1)
        with wd.observe():
            pass
        assert wd.trailing_mean_s >= 0.0


class TestStragglerEscalation:
    """Satellite (ISSUE 15): repeated flags on one stage can raise a
    typed PersistentStraggler instead of only bumping counters — opt-in
    via config.straggler_escalate or the ctor arg."""

    def test_counter_only_by_default(self):
        wd = flow.StragglerWatchdog("t.noesc", factor=2.0, warmup=2)
        for _ in range(3):
            wd.record(0.01)
        for k in range(6):  # 6 consecutive flags, never an exception
            # 3x the previous sample always clears factor x EMA
            assert wd.record(0.03 * (3 ** k))
        assert wd.consecutive_flags == 6

    def test_consecutive_flags_escalate_with_evidence(self):
        wd = flow.StragglerWatchdog("t.esc", factor=2.0, warmup=2, escalate=3)
        before = metrics.get_counter("flow.straggler.t.esc.escalated", 0)
        for _ in range(3):
            wd.record(0.01)
        assert wd.record(0.5)
        assert wd.record(0.5)
        with pytest.raises(flow.PersistentStraggler) as ei:
            wd.record(0.5)
        assert ei.value.stage == "t.esc"
        assert ei.value.consecutive == 3
        assert ei.value.seconds == pytest.approx(0.5)
        assert ei.value.mean_s > 0.0
        assert (
            metrics.get_counter("flow.straggler.t.esc.escalated", 0) == before + 1
        )
        # a caller that catches and continues is re-armed, not dead
        assert wd.consecutive_flags == 0

    def test_healthy_sample_resets_the_streak(self):
        wd = flow.StragglerWatchdog(
            "t.reset", factor=3.0, warmup=2, alpha=0.05, escalate=3
        )
        for _ in range(4):
            wd.record(0.01)
        assert wd.record(0.1)
        assert wd.record(0.1)
        assert not wd.record(0.01)  # healthy: streak resets
        assert wd.consecutive_flags == 0
        assert wd.record(0.2)  # two flags again — still below threshold
        assert wd.record(0.2)

    def test_opt_in_via_config(self):
        wd = flow.StragglerWatchdog("t.cfg", factor=2.0, warmup=2)
        for _ in range(3):
            wd.record(0.01)
        with config.straggler_escalation_mode(2):
            assert wd.escalate_after == 2
            assert wd.record(0.5)
            with pytest.raises(flow.PersistentStraggler):
                wd.record(0.5)
        assert wd.escalate_after == 0  # scoped override restored


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------

class TestConfig:
    def test_overload_mode_scoped(self):
        assert config.online_overload_policy == "block"
        with config.online_overload_mode("shed_oldest"):
            assert config.online_overload_policy == "shed_oldest"
        assert config.online_overload_policy == "block"
        with pytest.raises(ValueError):
            with config.online_overload_mode("nope"):
                pass

    def test_retry_mode_scoped(self):
        prev = config.transient_retries
        with config.transient_retry_mode(7):
            assert config.transient_retries == 7
        assert config.transient_retries == prev

    def test_unknown_policy_rejected_by_channel(self):
        with pytest.raises(ValueError):
            flow.BoundedChannel(2, policy="nope")
