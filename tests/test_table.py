"""Table / SparseBatch behavior."""

import numpy as np
import pytest

from flink_ml_tpu.linalg import DenseVector, Vectors
from flink_ml_tpu.table import SparseBatch, StreamTable, Table, as_dense_matrix, as_sparse_batch


def test_table_from_dict_and_accessors():
    t = Table({"a": [1.0, 2.0], "b": ["x", "y"]})
    assert t.num_rows == 2
    assert t.column_names == ["a", "b"]
    assert t.column("a").tolist() == [1.0, 2.0]
    assert t.column("b")[1] == "y"
    with pytest.raises(KeyError):
        t.column("c")


def test_row_count_mismatch():
    with pytest.raises(ValueError):
        Table({"a": [1.0], "b": [1.0, 2.0]})


def test_dense_vector_column_batches():
    t = Table({"features": [Vectors.dense(1.0, 2.0), Vectors.dense(3.0, 4.0)]})
    col = t.column("features")
    assert isinstance(col, np.ndarray) and col.shape == (2, 2)
    rows = t.collect()
    assert rows[0]["features"] == Vectors.dense(1.0, 2.0)


def test_sparse_vector_column_batches():
    t = Table(
        {
            "features": [
                Vectors.sparse(4, [0], [1.0]),
                Vectors.sparse(4, [1, 3], [2.0, 3.0]),
            ]
        }
    )
    col = t.column("features")
    assert isinstance(col, SparseBatch)
    assert col.size == 4
    np.testing.assert_array_equal(
        col.to_dense(), [[1.0, 0, 0, 0], [0, 2.0, 0, 3.0]]
    )
    assert t.collect()[1]["features"] == Vectors.sparse(4, [1, 3], [2.0, 3.0])


def test_with_column_select_drop_rename():
    t = Table({"a": [1.0, 2.0]})
    t2 = t.with_column("b", np.array([3.0, 4.0]))
    assert t2.column_names == ["a", "b"]
    assert t2.select("b").column_names == ["b"]
    assert t2.drop("a").column_names == ["b"]
    assert t2.rename({"a": "z"}).column_names == ["z", "b"]


def test_take_head_concat():
    t = Table({"a": np.arange(10.0)})
    assert t.head(3).column("a").tolist() == [0.0, 1.0, 2.0]
    assert t.take(np.array([9, 0])).column("a").tolist() == [9.0, 0.0]
    both = t.head(2).concat(t.head(1))
    assert both.column("a").tolist() == [0.0, 1.0, 0.0]


def test_as_dense_matrix_coercions():
    assert as_dense_matrix(np.array([1.0, 2.0])).shape == (2, 1)
    sb = as_sparse_batch(np.array([[1.0, 0.0], [0.0, 2.0]]))
    assert isinstance(sb, SparseBatch)
    np.testing.assert_array_equal(sb.to_dense(), [[1.0, 0.0], [0.0, 2.0]])


def test_stream_table():
    batches = [Table({"a": [1.0]}), Table({"a": [2.0]})]
    st = StreamTable.from_batches(batches)
    assert [b.column("a")[0] for b in st] == [1.0, 2.0]
    # re-iterable when built from a list
    assert [b.column("a")[0] for b in st] == [1.0, 2.0]


class TestFunctions:
    """vector_to_array / array_to_vector (Functions.java:10-38 parity)."""

    def test_vector_to_array_roundtrip(self):
        from flink_ml_tpu import array_to_vector, vector_to_array
        from flink_ml_tpu.linalg import Vectors

        vecs = np.empty(2, dtype=object)
        vecs[0] = Vectors.dense([1.0, 2.0])
        vecs[1] = Vectors.sparse(2, [1], [3.0])
        arrs = vector_to_array(vecs)
        np.testing.assert_array_equal(arrs, [[1.0, 2.0], [0.0, 3.0]])
        back = array_to_vector(arrs)
        np.testing.assert_array_equal(back, arrs)  # canonical dense batch

    def test_sparse_batch_densifies(self):
        from flink_ml_tpu import SparseBatch, vector_to_array

        sb = SparseBatch(3, [[0, 2], [1, -1]], [[1.0, 2.0], [5.0, 0.0]])
        np.testing.assert_array_equal(
            vector_to_array(sb), [[1.0, 0.0, 2.0], [0.0, 5.0, 0.0]]
        )

    def test_ragged_arrays_become_dense_vectors(self):
        from flink_ml_tpu import array_to_vector
        from flink_ml_tpu.linalg import DenseVector

        col = np.empty(2, dtype=object)
        col[0] = [1.0, 2.0]
        col[1] = [3.0]
        out = array_to_vector(col)
        assert isinstance(out[0], DenseVector) and out[1].size() == 1

    def test_device_passthrough(self):
        import jax.numpy as jnp

        from flink_ml_tpu import array_to_vector, vector_to_array

        X = jnp.ones((4, 3))
        assert vector_to_array(X) is X
        assert array_to_vector(X) is X
