"""Table / SparseBatch behavior."""

import numpy as np
import pytest

from flink_ml_tpu.linalg import DenseVector, Vectors
from flink_ml_tpu.table import SparseBatch, StreamTable, Table, as_dense_matrix, as_sparse_batch


def test_table_from_dict_and_accessors():
    t = Table({"a": [1.0, 2.0], "b": ["x", "y"]})
    assert t.num_rows == 2
    assert t.column_names == ["a", "b"]
    assert t.column("a").tolist() == [1.0, 2.0]
    assert t.column("b")[1] == "y"
    with pytest.raises(KeyError):
        t.column("c")


def test_row_count_mismatch():
    with pytest.raises(ValueError):
        Table({"a": [1.0], "b": [1.0, 2.0]})


def test_dense_vector_column_batches():
    t = Table({"features": [Vectors.dense(1.0, 2.0), Vectors.dense(3.0, 4.0)]})
    col = t.column("features")
    assert isinstance(col, np.ndarray) and col.shape == (2, 2)
    rows = t.collect()
    assert rows[0]["features"] == Vectors.dense(1.0, 2.0)


def test_sparse_vector_column_batches():
    t = Table(
        {
            "features": [
                Vectors.sparse(4, [0], [1.0]),
                Vectors.sparse(4, [1, 3], [2.0, 3.0]),
            ]
        }
    )
    col = t.column("features")
    assert isinstance(col, SparseBatch)
    assert col.size == 4
    np.testing.assert_array_equal(
        col.to_dense(), [[1.0, 0, 0, 0], [0, 2.0, 0, 3.0]]
    )
    assert t.collect()[1]["features"] == Vectors.sparse(4, [1, 3], [2.0, 3.0])


def test_with_column_select_drop_rename():
    t = Table({"a": [1.0, 2.0]})
    t2 = t.with_column("b", np.array([3.0, 4.0]))
    assert t2.column_names == ["a", "b"]
    assert t2.select("b").column_names == ["b"]
    assert t2.drop("a").column_names == ["b"]
    assert t2.rename({"a": "z"}).column_names == ["z", "b"]


def test_take_head_concat():
    t = Table({"a": np.arange(10.0)})
    assert t.head(3).column("a").tolist() == [0.0, 1.0, 2.0]
    assert t.take(np.array([9, 0])).column("a").tolist() == [9.0, 0.0]
    both = t.head(2).concat(t.head(1))
    assert both.column("a").tolist() == [0.0, 1.0, 0.0]


def test_as_dense_matrix_coercions():
    assert as_dense_matrix(np.array([1.0, 2.0])).shape == (2, 1)
    sb = as_sparse_batch(np.array([[1.0, 0.0], [0.0, 2.0]]))
    assert isinstance(sb, SparseBatch)
    np.testing.assert_array_equal(sb.to_dense(), [[1.0, 0.0], [0.0, 2.0]])


def test_stream_table():
    batches = [Table({"a": [1.0]}), Table({"a": [2.0]})]
    st = StreamTable.from_batches(batches)
    assert [b.column("a")[0] for b in st] == [1.0, 2.0]
    # re-iterable when built from a list
    assert [b.column("a")[0] for b in st] == [1.0, 2.0]


class TestFunctions:
    """vector_to_array / array_to_vector (Functions.java:10-38 parity)."""

    def test_vector_to_array_roundtrip(self):
        from flink_ml_tpu import array_to_vector, vector_to_array
        from flink_ml_tpu.linalg import Vectors

        vecs = np.empty(2, dtype=object)
        vecs[0] = Vectors.dense([1.0, 2.0])
        vecs[1] = Vectors.sparse(2, [1], [3.0])
        arrs = vector_to_array(vecs)
        np.testing.assert_array_equal(arrs, [[1.0, 2.0], [0.0, 3.0]])
        back = array_to_vector(arrs)
        np.testing.assert_array_equal(back, arrs)  # canonical dense batch

    def test_sparse_batch_densifies(self):
        from flink_ml_tpu import SparseBatch, vector_to_array

        sb = SparseBatch(3, [[0, 2], [1, -1]], [[1.0, 2.0], [5.0, 0.0]])
        np.testing.assert_array_equal(
            vector_to_array(sb), [[1.0, 0.0, 2.0], [0.0, 5.0, 0.0]]
        )

    def test_ragged_arrays_become_dense_vectors(self):
        from flink_ml_tpu import array_to_vector
        from flink_ml_tpu.linalg import DenseVector

        col = np.empty(2, dtype=object)
        col[0] = [1.0, 2.0]
        col[1] = [3.0]
        out = array_to_vector(col)
        assert isinstance(out[0], DenseVector) and out[1].size() == 1

    def test_device_passthrough(self):
        import jax.numpy as jnp

        from flink_ml_tpu import array_to_vector, vector_to_array

        X = jnp.ones((4, 3))
        assert vector_to_array(X) is X
        assert array_to_vector(X) is X


class TestDataStreamUtils:
    def test_map_partition_table_and_stream(self):
        from flink_ml_tpu import StreamTable
        from flink_ml_tpu.utils.datastream import map_partition

        double = lambda t: t.with_column("x", np.asarray(t.column("x")) * 2)
        t = Table({"x": [1.0, 2.0]})
        np.testing.assert_array_equal(
            np.asarray(map_partition(t, double).column("x")), [2.0, 4.0]
        )
        out = list(map_partition(StreamTable.from_batches([t, t]), double))
        assert len(out) == 2
        np.testing.assert_array_equal(np.asarray(out[1].column("x")), [2.0, 4.0])

    def test_reduce(self):
        from flink_ml_tpu import StreamTable
        from flink_ml_tpu.utils.datastream import reduce

        batches = [Table({"x": [float(i)]}) for i in range(4)]
        out = reduce(StreamTable.from_batches(batches), lambda a, b: a.concat(b))
        assert out.num_rows == 4

    def test_window_all_and_process_count_tumbling(self):
        from flink_ml_tpu import StreamTable
        from flink_ml_tpu.common.window import CountTumblingWindows, GlobalWindows
        from flink_ml_tpu.utils.datastream import window_all_and_process

        count_rows = lambda t: Table({"n": [float(t.num_rows)]})
        t = Table({"x": np.arange(10.0)})
        out = window_all_and_process(t, CountTumblingWindows.of(4), count_rows)
        np.testing.assert_array_equal(np.asarray(out.column("n")), [4.0, 4.0])
        # windows span batch boundaries; tail dropped
        stream = StreamTable.from_batches(
            [Table({"x": np.arange(3.0)}), Table({"x": np.arange(7.0)})]
        )
        out2 = list(window_all_and_process(stream, CountTumblingWindows.of(4), count_rows))
        assert len(out2) == 2
        g = window_all_and_process(t, GlobalWindows(), count_rows)
        np.testing.assert_array_equal(np.asarray(g.column("n")), [10.0])
        # GlobalWindows over a stream = ONE window over the whole input
        stream2 = StreamTable.from_batches(
            [Table({"x": np.arange(3.0)}), Table({"x": np.arange(7.0)})]
        )
        gs = list(window_all_and_process(stream2, GlobalWindows(), count_rows))
        assert len(gs) == 1
        np.testing.assert_array_equal(np.asarray(gs[0].column("n")), [10.0])


def test_window_all_and_process_empty_stream():
    from flink_ml_tpu import StreamTable
    from flink_ml_tpu.common.window import GlobalWindows
    from flink_ml_tpu.utils.datastream import window_all_and_process

    out = window_all_and_process(
        StreamTable.from_batches([]), GlobalWindows(), lambda t: t
    )
    assert list(out) == []
