"""Param system behavior — mirrors the battery of
flink-ml-core/src/test/java/org/apache/flink/ml/api/StageTest.java."""

import json

import pytest

from flink_ml_tpu.param import (
    BooleanParam,
    FloatParam,
    IntArrayParam,
    IntParam,
    ParamValidators,
    StringArrayParam,
    StringParam,
    VectorParam,
    WithParams,
)
from flink_ml_tpu.linalg import Vectors


class MyStage(WithParams):
    ALPHA = FloatParam("alpha", "Alpha value.", 1.0, ParamValidators.gt(0.0))
    COUNT = IntParam("count", "A count.", 5, ParamValidators.in_range(0, 100))
    NAME = StringParam("name", "A name.", "default")
    FLAG = BooleanParam("flag", "A flag.", False)
    IDS = IntArrayParam("ids", "Some ids.", [1, 2])
    TAGS = StringArrayParam("tags", "Some tags.", None)
    VEC = VectorParam("vec", "A vector.", None)


def test_defaults():
    s = MyStage()
    assert s.get(MyStage.ALPHA) == 1.0
    assert s.get(MyStage.COUNT) == 5
    assert s.get(MyStage.NAME) == "default"
    assert s.get(MyStage.FLAG) is False
    assert s.get(MyStage.IDS) == [1, 2]
    assert s.get(MyStage.TAGS) is None


def test_set_get():
    s = MyStage()
    s.set(MyStage.ALPHA, 2.5).set(MyStage.NAME, "x")
    assert s.get(MyStage.ALPHA) == 2.5
    assert s.get(MyStage.NAME) == "x"


def test_validator_rejects():
    s = MyStage()
    with pytest.raises(ValueError):
        s.set(MyStage.ALPHA, -1.0)
    with pytest.raises(ValueError):
        s.set(MyStage.COUNT, 1000)


def test_invalid_default_rejected():
    with pytest.raises(ValueError):
        IntParam("bad", "invalid default", -5, ParamValidators.gt(0))


def test_get_param_by_name():
    s = MyStage()
    assert s.get_param("alpha") is MyStage.ALPHA
    assert s.get_param("nope") is None


def test_undefined_param_rejected():
    other = IntParam("other", "not on stage", 1)
    with pytest.raises(ValueError):
        MyStage().set(other, 3)
    with pytest.raises(ValueError):
        MyStage().get(other)


def test_json_roundtrip_all_types():
    s = MyStage()
    s.set(MyStage.VEC, Vectors.dense(1.0, 2.0))
    s.set(MyStage.TAGS, ["a", "b"])
    encoded = {p.name: p.json_encode(v) for p, v in s.get_param_map().items()}
    # must survive real JSON serialization
    encoded = json.loads(json.dumps(encoded))
    t = MyStage()
    for name, value in encoded.items():
        p = t.get_param(name)
        t.set(p, p.json_decode(value))
    assert t.get(MyStage.VEC) == Vectors.dense(1.0, 2.0)
    assert t.get(MyStage.TAGS) == ["a", "b"]
    assert t.get(MyStage.IDS) == [1, 2]


def test_sparse_vector_param_roundtrip():
    s = MyStage()
    sv = Vectors.sparse(5, [1, 3], [0.5, 1.5])
    s.set(MyStage.VEC, sv)
    p = MyStage.VEC
    decoded = p.json_decode(json.loads(json.dumps(p.json_encode(sv))))
    assert decoded == sv


def test_validators():
    assert ParamValidators.gt(0).validate(1)
    assert not ParamValidators.gt(0).validate(0)
    assert not ParamValidators.gt(0).validate(None)
    assert ParamValidators.lt_eq(3).validate(3)
    assert ParamValidators.in_range(0, 1).validate(0.5)
    assert not ParamValidators.in_range(0, 1, lower_inclusive=False).validate(0)
    assert ParamValidators.in_array(["a", "b"]).validate("a")
    assert not ParamValidators.in_array(["a"]).validate("c")
    assert ParamValidators.non_empty_array().validate([1])
    assert not ParamValidators.non_empty_array().validate([])
    assert ParamValidators.is_sub_set(["a", "b", "c"]).validate(["a", "c"])
    assert not ParamValidators.is_sub_set(["a"]).validate(["z"])


def test_param_equality_by_name():
    a = IntParam("p", "one", 1)
    b = IntParam("p", "two", 2)
    assert a == b and hash(a) == hash(b)
