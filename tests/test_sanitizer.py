"""Runtime concurrency sanitizer (flink_ml_tpu/analysis/sanitizer.py):
the FLINK_ML_TPU_SANITIZE=1 recorder must (a) catch a real ABBA deadlock
pattern provoked on a throwaway pair of locks, (b) stay quiet on
consistently-ordered acquisitions, (c) balance the channel/worker ledger
(leaked workers and unclosed pump channels fail at exit), and (d) do all
of it end-to-end through the instrumented flow layer in a subprocess."""

import os
import subprocess
import sys
import textwrap
import threading

import pytest

from flink_ml_tpu.analysis import sanitizer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestLockOrderRecorder:
    def test_abba_cycle_detected_sequentially(self):
        rec = sanitizer.Recorder()
        a = sanitizer.TrackedLock("test.A", rec)
        b = sanitizer.TrackedLock("test.B", rec)

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        for fn in (t1, t2):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
        cycles = rec.cycles()
        assert cycles, "inverted acquisition order must record a cycle"
        assert sorted(cycles[0]) == ["test.A", "test.B"]
        with pytest.raises(sanitizer.SanitizerError) as err:
            rec.check()
        assert "test.A" in str(err.value) and "test.B" in str(err.value)

    def test_real_abba_deadlock_pattern(self):
        """Both threads take their first lock, THEN attempt the other —
        the genuine deadlock interleaving. Timed second acquires keep the
        test finite; the attempt-time edges still pin the cycle."""
        rec = sanitizer.Recorder()
        a = sanitizer.TrackedLock("abba.A", rec)
        b = sanitizer.TrackedLock("abba.B", rec)
        barrier = threading.Barrier(2)
        blocked = []

        def worker(first, second):
            with first:
                barrier.wait()  # both hold their first lock: deadlock is live
                got = second.acquire(timeout=0.2)
                if got:
                    second.release()
                else:
                    blocked.append(second._name)

        t1 = threading.Thread(target=worker, args=(a, b))
        t2 = threading.Thread(target=worker, args=(b, a))
        t1.start(), t2.start()
        t1.join(), t2.join()
        assert blocked, "at least one second acquire must have been blocked"
        cycles = rec.cycles()
        assert cycles and sorted(cycles[0]) == ["abba.A", "abba.B"]

    def test_consistent_order_is_clean(self):
        rec = sanitizer.Recorder()
        a = sanitizer.TrackedLock("ok.A", rec)
        b = sanitizer.TrackedLock("ok.B", rec)
        for _ in range(3):
            with a:
                with b:
                    pass
        assert rec.cycles() == []
        rec.check()  # no raise

    def test_reentrant_reacquire_is_not_an_edge(self):
        rec = sanitizer.Recorder()
        r = sanitizer.TrackedRLock("re.R", rec)
        with r:
            with r:
                pass
        assert rec.edges == {}
        assert rec.cycles() == []

    def test_condition_wait_keeps_held_stack_truthful(self):
        rec = sanitizer.Recorder()
        cv = sanitizer.TrackedCondition("cv.C", rec)
        other = sanitizer.TrackedLock("cv.L", rec)
        with cv:
            cv.wait(timeout=0.01)
        with other:
            pass
        # cv was fully released before `other` was taken: no edge
        assert ("cv.C", "cv.L") not in rec.edges


class _FakeChannel:
    def __init__(self, name):
        self.name = name


class TestLedger:
    def test_unclosed_pump_channel_is_a_problem(self):
        rec = sanitizer.Recorder()
        ch = _FakeChannel("leaky")
        rec.register_channel(ch)
        rec.channel_pumped(ch)
        problems = rec.problems(join_timeout=0.01)
        assert any("leaky" in p and "unclosed" in p for p in problems)
        rec.channel_closed(ch)
        assert rec.problems(join_timeout=0.01) == []

    def test_unpumped_channel_needs_no_close(self):
        rec = sanitizer.Recorder()
        ch = _FakeChannel("scratch")
        rec.register_channel(ch)
        assert rec.problems(join_timeout=0.01) == []

    def test_leaked_worker_is_a_problem(self):
        rec = sanitizer.Recorder()
        release = threading.Event()
        t = threading.Thread(target=release.wait, daemon=True)
        t.start()
        rec.register_worker(t, "spawn")
        problems = rec.problems(join_timeout=0.05)
        assert any("leaked worker" in p for p in problems)
        release.set()
        t.join(2.0)
        assert rec.problems(join_timeout=0.5) == []


def _run_script(source: str):
    return subprocess.run(
        [sys.executable, "-c", textwrap.dedent(source)],
        capture_output=True,
        text=True,
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "FLINK_ML_TPU_SANITIZE": "1"},
        timeout=120,
    )


class TestInstrumentedFlowEndToEnd:
    def test_clean_pump_drain_exits_zero(self):
        result = _run_script(
            """
            from flink_ml_tpu.analysis import sanitizer
            sanitizer.enable()
            from flink_ml_tpu import flow

            ch = flow.BoundedChannel(4, name="t.clean")
            flow.pump(range(32), ch, transform=lambda x: x * 2)
            assert list(ch) == [x * 2 for x in range(32)]
            """
        )
        assert result.returncode == 0, result.stderr
        assert "FLINK_ML_TPU_SANITIZE: clean" in result.stderr

    def test_abandoned_pump_worker_fails_at_exit(self):
        result = _run_script(
            """
            import itertools
            from flink_ml_tpu.analysis import sanitizer
            sanitizer.enable()
            from flink_ml_tpu import flow

            ch = flow.BoundedChannel(2, name="t.leak")
            flow.pump(itertools.count(), ch)  # unbounded producer
            ch.get()  # consume one, then abandon WITHOUT cancel/close
            """
        )
        assert result.returncode == 66, result.stdout + result.stderr
        assert "leaked worker" in result.stderr
        assert "unclosed pump channel" in result.stderr

    def test_cancel_releases_the_worker(self):
        result = _run_script(
            """
            import itertools
            from flink_ml_tpu.analysis import sanitizer
            sanitizer.enable()
            from flink_ml_tpu import flow

            ch = flow.BoundedChannel(2, name="t.cancelled")
            flow.pump(itertools.count(), ch)
            ch.get()
            ch.cancel()  # the consumer-side handshake
            """
        )
        assert result.returncode == 0, result.stdout + result.stderr
        assert "FLINK_ML_TPU_SANITIZE: clean" in result.stderr
