"""Runtime concurrency sanitizer (flink_ml_tpu/analysis/sanitizer.py):
the FLINK_ML_TPU_SANITIZE=1 recorder must (a) catch a real ABBA deadlock
pattern provoked on a throwaway pair of locks, (b) stay quiet on
consistently-ordered acquisitions, (c) balance the channel/worker ledger
(leaked workers and unclosed pump channels fail at exit), and (d) do all
of it end-to-end through the instrumented flow layer in a subprocess."""

import os
import subprocess
import sys
import textwrap
import threading

import pytest

from flink_ml_tpu.analysis import sanitizer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestLockOrderRecorder:
    def test_abba_cycle_detected_sequentially(self):
        rec = sanitizer.Recorder()
        a = sanitizer.TrackedLock("test.A", rec)
        b = sanitizer.TrackedLock("test.B", rec)

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with a:
                    pass

        for fn in (t1, t2):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
        cycles = rec.cycles()
        assert cycles, "inverted acquisition order must record a cycle"
        assert sorted(cycles[0]) == ["test.A", "test.B"]
        with pytest.raises(sanitizer.SanitizerError) as err:
            rec.check()
        assert "test.A" in str(err.value) and "test.B" in str(err.value)

    def test_real_abba_deadlock_pattern(self):
        """Both threads take their first lock, THEN attempt the other —
        the genuine deadlock interleaving. Timed second acquires keep the
        test finite; the attempt-time edges still pin the cycle."""
        rec = sanitizer.Recorder()
        a = sanitizer.TrackedLock("abba.A", rec)
        b = sanitizer.TrackedLock("abba.B", rec)
        barrier = threading.Barrier(2)
        blocked = []

        def worker(first, second):
            with first:
                barrier.wait()  # both hold their first lock: deadlock is live
                got = second.acquire(timeout=0.2)
                if got:
                    second.release()
                else:
                    blocked.append(second._name)

        t1 = threading.Thread(target=worker, args=(a, b))
        t2 = threading.Thread(target=worker, args=(b, a))
        t1.start(), t2.start()
        t1.join(), t2.join()
        assert blocked, "at least one second acquire must have been blocked"
        cycles = rec.cycles()
        assert cycles and sorted(cycles[0]) == ["abba.A", "abba.B"]

    def test_consistent_order_is_clean(self):
        rec = sanitizer.Recorder()
        a = sanitizer.TrackedLock("ok.A", rec)
        b = sanitizer.TrackedLock("ok.B", rec)
        for _ in range(3):
            with a:
                with b:
                    pass
        assert rec.cycles() == []
        rec.check()  # no raise

    def test_reentrant_reacquire_is_not_an_edge(self):
        rec = sanitizer.Recorder()
        r = sanitizer.TrackedRLock("re.R", rec)
        with r:
            with r:
                pass
        assert rec.edges == {}
        assert rec.cycles() == []

    def test_condition_wait_keeps_held_stack_truthful(self):
        rec = sanitizer.Recorder()
        cv = sanitizer.TrackedCondition("cv.C", rec)
        other = sanitizer.TrackedLock("cv.L", rec)
        with cv:
            cv.wait(timeout=0.01)
        with other:
            pass
        # cv was fully released before `other` was taken: no edge
        assert ("cv.C", "cv.L") not in rec.edges


class _FakeChannel:
    def __init__(self, name):
        self.name = name


class TestLedger:
    def test_unclosed_pump_channel_is_a_problem(self):
        rec = sanitizer.Recorder()
        ch = _FakeChannel("leaky")
        rec.register_channel(ch)
        rec.channel_pumped(ch)
        problems = rec.problems(join_timeout=0.01)
        assert any("leaky" in p and "unclosed" in p for p in problems)
        rec.channel_closed(ch)
        assert rec.problems(join_timeout=0.01) == []

    def test_unpumped_channel_needs_no_close(self):
        rec = sanitizer.Recorder()
        ch = _FakeChannel("scratch")
        rec.register_channel(ch)
        assert rec.problems(join_timeout=0.01) == []

    def test_leaked_worker_is_a_problem(self):
        rec = sanitizer.Recorder()
        release = threading.Event()
        t = threading.Thread(target=release.wait, daemon=True)
        t.start()
        rec.register_worker(t, "spawn")
        problems = rec.problems(join_timeout=0.05)
        assert any("leaked worker" in p for p in problems)
        release.set()
        t.join(2.0)
        assert rec.problems(join_timeout=0.5) == []


def _run_script(source: str):
    return subprocess.run(
        [sys.executable, "-c", textwrap.dedent(source)],
        capture_output=True,
        text=True,
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "FLINK_ML_TPU_SANITIZE": "1"},
        timeout=120,
    )


class TestInstrumentedFlowEndToEnd:
    def test_clean_pump_drain_exits_zero(self):
        result = _run_script(
            """
            from flink_ml_tpu.analysis import sanitizer
            sanitizer.enable()
            from flink_ml_tpu import flow

            ch = flow.BoundedChannel(4, name="t.clean")
            flow.pump(range(32), ch, transform=lambda x: x * 2)
            assert list(ch) == [x * 2 for x in range(32)]
            """
        )
        assert result.returncode == 0, result.stderr
        assert "FLINK_ML_TPU_SANITIZE: clean" in result.stderr

    def test_abandoned_pump_worker_fails_at_exit(self):
        result = _run_script(
            """
            import itertools
            from flink_ml_tpu.analysis import sanitizer
            sanitizer.enable()
            from flink_ml_tpu import flow

            ch = flow.BoundedChannel(2, name="t.leak")
            flow.pump(itertools.count(), ch)  # unbounded producer
            ch.get()  # consume one, then abandon WITHOUT cancel/close
            """
        )
        assert result.returncode == 66, result.stdout + result.stderr
        assert "leaked worker" in result.stderr
        assert "unclosed pump channel" in result.stderr

    def test_cancel_releases_the_worker(self):
        result = _run_script(
            """
            import itertools
            from flink_ml_tpu.analysis import sanitizer
            sanitizer.enable()
            from flink_ml_tpu import flow

            ch = flow.BoundedChannel(2, name="t.cancelled")
            flow.pump(itertools.count(), ch)
            ch.get()
            ch.cancel()  # the consumer-side handshake
            """
        )
        assert result.returncode == 0, result.stdout + result.stderr
        assert "FLINK_ML_TPU_SANITIZE: clean" in result.stderr


class TestCollectiveSequenceRecorder:
    """The dynamic dual of the collective-divergence lint rule: per-shard
    (op, axis, shape, dtype) sequences recorded through the
    parallel/collectives accounting funnel must agree across the shards
    of a scope group at exit."""

    def test_matching_sequences_are_clean(self):
        rec = sanitizer.Recorder()
        for shard in (0, 1, 2):
            with rec.shard_scope(shard, group="hosts"):
                rec.record_collective("psum", "data", (128,), "float32")
                rec.record_collective("all_gather", "data", (16,), "float32")
        assert rec.collective_divergences() == []
        rec.check(join_timeout=0.01)  # no raise

    def test_mismatched_op_is_a_divergence(self):
        rec = sanitizer.Recorder()
        with rec.shard_scope(0, group="hosts"):
            rec.record_collective("psum", "data", (128,), "float32")
        with rec.shard_scope(1, group="hosts"):
            rec.record_collective("all_gather", "data", (128,), "float32")
        problems = rec.problems(join_timeout=0.01)
        assert any("collective-sequence divergence" in p for p in problems)
        with pytest.raises(sanitizer.SanitizerError):
            rec.check(join_timeout=0.01)

    def test_missing_trailing_collective_is_a_divergence(self):
        # the deadlock shape: one shard issues an extra collective the
        # others never arrive at
        rec = sanitizer.Recorder()
        with rec.shard_scope("host0", group="dcn"):
            rec.record_collective("psum", "data", (4,), "float32")
            rec.record_collective("psum", "data", (4,), "float32")
        with rec.shard_scope("host1", group="dcn"):
            rec.record_collective("psum", "data", (4,), "float32")
        problems = rec.problems(join_timeout=0.01)
        assert any("deadlock" in p for p in problems)

    def test_shape_dtype_mismatch_is_a_divergence(self):
        rec = sanitizer.Recorder()
        with rec.shard_scope(0, group="hosts"):
            rec.record_collective("psum", "data", (128,), "float32")
        with rec.shard_scope(1, group="hosts"):
            rec.record_collective("psum", "data", (128,), "bfloat16")
        assert rec.collective_divergences()

    def test_single_scope_and_default_trace_context_cannot_diverge(self):
        rec = sanitizer.Recorder()
        rec.record_collective("psum", "data", (8,), "float32")
        rec.record_collective("all_gather", "data", (8,), "float32")
        with rec.shard_scope(0, group="solo"):
            rec.record_collective("psum", "data", (8,), "float32")
        assert rec.collective_divergences() == []

    def test_real_collectives_record_through_the_accounting_funnel(self, mesh8):
        """An actual traced shard_map program: the accounted wrapper
        feeds the ledger with the op, axis, and trace-time shape/dtype."""
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from flink_ml_tpu.parallel import collectives, mesh as mesh_lib

        with sanitizer.collective_recording() as rec:
            fn = collectives.shard_map_over(
                mesh8,
                P(mesh_lib.DATA_AXIS),
                P(),
                fn=lambda x: collectives.all_reduce_sum(x, mesh_lib.DATA_AXIS),
            )
            out = fn(jnp.arange(8, dtype=jnp.float32))
        assert float(out.sum()) == 28.0
        seqs = rec.collective_sequences["trace"]["0"]
        assert ("psum", "data", (1,), "float32") in seqs
        # scoped recording detaches afterwards: nothing else records
        before = rec.collective_count
        collectives.payload_bytes(jnp.zeros(4))
        assert rec.collective_count == before

    def test_divergence_provocation_fails_at_exit_code_66(self):
        """Subprocess provocation: two emulated hosts drive DIFFERENT
        collective sequences under FLINK_ML_TPU_SANITIZE=1 — the process
        must die with the sanitizer's exit code and name the divergence."""
        result = _run_script(
            """
            from flink_ml_tpu.analysis import sanitizer
            sanitizer.enable()
            rec = sanitizer.recorder
            with rec.shard_scope("host0", group="dcn"):
                sanitizer.record_collective("psum", "data", (1024,), "float32")
                sanitizer.record_collective("all_gather", "data", (64,), "float32")
            with rec.shard_scope("host1", group="dcn"):
                sanitizer.record_collective("psum", "data", (1024,), "float32")
            """
        )
        assert result.returncode == 66, result.stdout + result.stderr
        assert "collective-sequence divergence" in result.stderr

    def test_matching_sequences_exit_clean_with_ledger_stats(self):
        result = _run_script(
            """
            from flink_ml_tpu.analysis import sanitizer
            sanitizer.enable()
            rec = sanitizer.recorder
            for host in ("host0", "host1"):
                with rec.shard_scope(host, group="dcn"):
                    sanitizer.record_collective("psum", "data", (1024,), "float32")
            """
        )
        assert result.returncode == 0, result.stdout + result.stderr
        assert "FLINK_ML_TPU_SANITIZE: clean" in result.stderr
        assert "2 collectives" in result.stderr
