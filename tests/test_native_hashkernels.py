"""Native hashing-trick kernels (native/src/hashkernels.cc) vs the
pure-Python oracles — bit-identical bucketing is the contract
(FeatureHasher.java:60-118 hashes guava murmur3_32(0) over
"col=" + String.valueOf(cell))."""

import numpy as np
import pytest

import flink_ml_tpu.native as nat
from flink_ml_tpu.native import hashkernels as hk
from flink_ml_tpu.models.feature.featurehasher import (
    _combine_hashed,
    _hash_categorical_column,
    _hash_index,
    _render_java_doubles,
)
from flink_ml_tpu.models.feature.stringindexer import _java_double_to_string
from flink_ml_tpu.table import Table
from flink_ml_tpu.utils.hashing import (
    murmur3_batch_unencoded_chars,
    murmur3_hash_unencoded_chars,
)

pytestmark = pytest.mark.skipif(not nat.available(), reason="no native toolchain")


def _double_fixture():
    rng = np.random.default_rng(42)
    return np.concatenate(
        [
            rng.random(500),  # benchmark regime: uniform [0, 1)
            rng.random(50) * 1e-4,  # scientific form below 1e-3
            rng.random(50) * 1e9,  # scientific form at/above 1e7
            -rng.random(50),
            np.array(
                [0.0, -0.0, 1.0, -1.5, 1e-3, 1e7, 12345678.0, 1e-4,
                 np.nan, np.inf, -np.inf, 4.9e-324, 2.0**31, 2.0**63]
            ),
        ]
    )


def test_double_hash_matches_scalar_oracle():
    v = _double_fixture()
    got = hk.hash_categorical_doubles(v, "f0=", 1000)
    exp = [_hash_index("f0=" + _java_double_to_string(float(x)), 1000) for x in v]
    assert got.tolist() == exp


def test_string_hash_matches_scalar_oracle():
    strs = np.array(["hello", "a\x00b", "emoji\U0001F600x", "", "x", "true", "0.5"])
    got = hk.hash_categorical_strings(strs, "c=", 997)
    exp = [_hash_index("c=" + s, 997) for s in strs]
    assert got.tolist() == exp


def test_combine_matches_numpy():
    rng = np.random.default_rng(1)
    idxs = rng.integers(0, 20, size=(300, 5)).astype(np.int64)
    vals = rng.random((300, 5))
    ci, cv = hk.combine_hashed(idxs, vals)
    ri, rv = _combine_hashed(idxs, vals)
    assert np.array_equal(ci, ri)
    np.testing.assert_allclose(cv, rv)


def test_render_java_doubles_fallback_matches_scalar():
    v = _double_fixture()
    rendered = _render_java_doubles(v)
    exp = [_java_double_to_string(float(x)) for x in v]
    assert rendered.tolist() == exp


def test_column_path_native_and_fallback_agree(monkeypatch):
    v = _double_fixture()
    native = _hash_categorical_column(v, "f2=", 263)
    monkeypatch.setattr(hk, "_load_native", lambda: None)
    fallback = _hash_categorical_column(v, "f2=", 263)
    assert native.tolist() == fallback.tolist()


def test_numpy_batch_murmur_embedded_nul():
    strs = np.array(["a\x00b", "\x00x", "hello", "x"])
    got = murmur3_batch_unencoded_chars(strs)
    exp = [murmur3_hash_unencoded_chars(s) for s in strs]
    assert got.tolist() == exp


def test_float32_columns_hash_java_float_form():
    """float32 categorical cells must hash Java Float.toString (float32
    shortest digits, scientific form outside [1e-3, 1e7)) — not the
    widened-double repr, and identically in vectorized and scalar paths."""
    from flink_ml_tpu.models.feature.featurehasher import FeatureHasher
    from flink_ml_tpu.models.feature.stringindexer import _java_float_to_string

    vals = [0.1, 1e8, 1e-4, float("nan"), 0.5]
    assert _java_float_to_string(np.float32(1e8)) == "1.0E8"
    assert _java_float_to_string(np.float32(0.1)) == "0.1"
    col = np.array(vals, dtype=np.float32)
    got = _hash_categorical_column(col, "f=", 1 << 18)
    exp = [_hash_index("f=" + _java_float_to_string(v), 1 << 18) for v in col]
    assert got.tolist() == exp
    # scalar (dict) path agrees: object column forces it
    obj = np.empty(len(vals), dtype=object)
    obj[:] = [np.float32(v) for v in vals]
    out = (
        FeatureHasher().set_input_cols("f").set_categorical_cols("f")
        .set_num_features(1 << 18)
        .transform(Table({"f": obj}))[0].column("output")
    )
    for r, e in enumerate(exp):
        assert out.row(r).indices.tolist() == [e]


def test_string_columns_use_vectorized_path():
    """'U'-dtype columns are vectorizable: same buckets as the per-row
    dict path, without the minutes-long host loop."""
    from flink_ml_tpu.models.feature.featurehasher import FeatureHasher

    strs = ["red", "green", "blue", "red"]
    t = Table({"c": np.array(strs), "x": np.array([1.0, 2.0, 3.0, 4.0])})
    stage = FeatureHasher().set_input_cols("c", "x").set_num_features(128)
    out = stage.transform(t)[0].column("output")
    obj = np.empty(4, dtype=object)
    obj[:] = strs
    slow = stage.transform(
        Table({"c": obj, "x": np.array([1.0, 2.0, 3.0, 4.0])})
    )[0].column("output")
    for r in range(4):
        assert out.row(r).indices.tolist() == slow.row(r).indices.tolist()
        np.testing.assert_allclose(out.row(r).values, slow.row(r).values)


def test_featurehasher_java_form_small_values():
    """Values below 1e-3 must hash their Java scientific rendering
    ('1.0E-4'), not the Python decimal form ('0.0001')."""
    from flink_ml_tpu.models.feature.featurehasher import FeatureHasher

    t = Table({"f0": np.array([1e-4, 0.0005, 12345678.0, 0.5])})
    out = (
        FeatureHasher()
        .set_input_cols("f0")
        .set_categorical_cols("f0")
        .set_num_features(1 << 18)
        .transform(t)[0]
        .column("output")
    )
    exp = [
        _hash_index("f0=" + _java_double_to_string(v), 1 << 18)
        for v in [1e-4, 0.0005, 12345678.0, 0.5]
    ]
    for r, e in enumerate(exp):
        assert out.row(r).indices.tolist() == [e]
