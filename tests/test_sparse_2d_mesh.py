"""Feature-sharded (data × feature) 2D-mesh sparse training.

The beyond-HBM layout of PAPER §"sparseWideLR": batches shard over the
`data` axis, the coefficient and the SGD optimizer carry shard over the
`model` (feature) axis, gradients reduce over `data` only (SparCML pair
exchange — wire bytes ∝ nnz), and the forward pass all-gathers just the
ACTIVE feature slices over `model`. These tests pin:

1. the 2D mesh constructor + sharding-spec layer (`create_mesh_2d`,
   `data_model_sharding`, host-group alignment),
2. the snapshot host-mapping contract on 2D shards
   (`shard_axis_for_tag` × `host_slice_bounds`),
3. per-axis collective accounting — sparse reduce bytes attributed to
   `data`, activation psums to `model` (satellite: 2-axis accounting),
4. 1D-vs-2D parity (bitwise on a single feature shard; allclose across
   shards, where only the reduction order differs),
5. whole-fit residency: the entire 2D fit is ONE dispatch + ONE packed
   readback,
6. the acceptance: a model whose replicated residency exceeds
   `config.hbm_budget_bytes` trains on the 2D mesh while the replicated
   layout is refused at admission (`HbmBudgetExceeded`),
7. 2D feature-shard checkpoints round-trip through the multi-host
   snapshot coordinator, including elastic resume onto a different host
   count AND a different mesh factorization.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from flink_ml_tpu import config
from flink_ml_tpu.ckpt import InjectedFault, coordinator, faults
from flink_ml_tpu.obs import memledger
from flink_ml_tpu.ops.losses import SPARSE_BINARY_LOGISTIC_LOSS
from flink_ml_tpu.ops.optimizer import SGD
from flink_ml_tpu.parallel import collectives
from flink_ml_tpu.parallel import mesh as mesh_lib
from flink_ml_tpu.utils import metrics


def _sparse_problem(n=96, d=30, nnz=5, seed=0):
    """Ragged padded-CSR rows (-1 padding) + separable {0,1} labels."""
    rng = np.random.default_rng(seed)
    indices = np.full((n, nnz), -1, np.int32)
    values = np.zeros((n, nnz), np.float64)
    for i in range(n):
        k = rng.integers(1, nnz + 1)
        cols = rng.choice(d, size=k, replace=False)
        cols.sort()
        indices[i, :k] = cols
        values[i, :k] = rng.random(k)
    truth = rng.random(d) - 0.5
    dense = np.zeros((n, d))
    np.add.at(dense, (np.arange(n)[:, None], np.clip(indices, 0, d - 1)),
              np.where(indices >= 0, values, 0.0))
    y = (dense @ truth > 0).astype(np.float64)
    return indices, values, y


def _fit(mesh, indices, values, y, d, max_iter=6, **kw):
    kw.setdefault("global_batch_size", 32)
    kw.setdefault("tol", 0.0)
    with mesh_lib.use_mesh(mesh):
        return SGD(max_iter=max_iter, shard_features=True, **kw).optimize(
            np.zeros(d), (indices, values), y, None,
            SPARSE_BINARY_LOGISTIC_LOSS, mesh=mesh,
        )


# ---------------------------------------------------------------------------
# mesh constructor + sharding specs
# ---------------------------------------------------------------------------

class TestCreateMesh2D:
    def test_factorizes_model_innermost(self):
        m = mesh_lib.create_mesh_2d(2)
        assert dict(m.shape) == {"data": 4, "model": 2}
        # model-minor: flat mesh order IS the device order, so contiguous
        # host slabs own whole data rows
        assert list(m.devices.flat) == jax.devices()
        assert mesh_lib.num_model_shards(m) == 2
        assert mesh_lib.num_data_shards(m) == 4

    def test_rejects_non_dividing_model_shards(self):
        with pytest.raises(ValueError, match="does not divide"):
            mesh_lib.create_mesh_2d(3)
        with pytest.raises(ValueError, match="must be >= 1"):
            mesh_lib.create_mesh_2d(0)

    def test_host_alignment_validation(self):
        # 4 hosts x 2 devices, model_shards=2: every slab holds whole rows
        m = mesh_lib.create_mesh_2d(2, num_hosts=4)
        assert dict(m.shape) == {"data": 4, "model": 2}
        # 3 hosts over 8 devices -> slabs of 3/3/2; a 4-wide model row
        # would straddle host boundaries
        with pytest.raises(ValueError, match="straddle"):
            mesh_lib.create_mesh_2d(4, num_hosts=3)

    def test_data_model_sharding_specs(self):
        m2 = mesh_lib.create_mesh_2d(2)
        assert mesh_lib.data_model_sharding(m2).spec == P("data", "model")
        assert mesh_lib.data_model_sharding(m2, ndim=3).spec == P(
            "data", None, "model"
        )
        with pytest.raises(ValueError, match="ndim >= 2"):
            mesh_lib.data_model_sharding(m2, ndim=1)
        # no model axis -> falls back to plain data layout / replication
        m1 = mesh_lib.create_mesh(("data",))
        assert mesh_lib.data_model_sharding(m1).spec == P("data", None)
        assert mesh_lib.model_sharding(m1).spec == P()
        assert mesh_lib.model_sharding(m2).spec == P("model")

    def test_host_groups_own_whole_data_rows(self):
        m = mesh_lib.create_mesh_2d(2)
        groups = mesh_lib.host_groups(m, 4)
        for i, group in enumerate(groups):
            assert group == list(m.devices[i])  # host i == data row i


# ---------------------------------------------------------------------------
# satellite: snapshot host-mapping on 2D shards
# ---------------------------------------------------------------------------

class TestHostMapping2D:
    def test_shard_axis_for_tag_2d(self):
        assert mesh_lib.shard_axis_for_tag("data", 2) == 0
        assert mesh_lib.shard_axis_for_tag("model", 2) == 1
        assert mesh_lib.shard_axis_for_tag("model", 1) == 0
        assert mesh_lib.shard_axis_for_tag("model", 3) == 2
        assert mesh_lib.shard_axis_for_tag("replicated", 2) is None
        assert mesh_lib.shard_axis_for_tag("host", 2) is None
        assert mesh_lib.shard_axis_for_tag("model", 0) is None

    def test_host_slice_bounds_array_split_semantics(self):
        assert mesh_lib.host_slice_bounds(30, 4) == [
            (0, 8), (8, 16), (16, 23), (23, 30)
        ]
        # hosts may outnumber elements: trailing slices are empty
        assert mesh_lib.host_slice_bounds(3, 5) == [
            (0, 1), (1, 2), (2, 3), (3, 3), (3, 3)
        ]
        with pytest.raises(ValueError):
            mesh_lib.host_slice_bounds(8, 0)

    def test_model_tag_slices_reassemble_2d_leaf(self):
        """A rank-2 model-tagged leaf (e.g. a future multi-class coeff
        matrix) splits along its TRAILING dim; concatenating every host's
        slice along `shard_axis_for_tag` reconstructs the array exactly."""
        arr = np.arange(6 * 30, dtype=np.float32).reshape(6, 30)
        axis = mesh_lib.shard_axis_for_tag("model", arr.ndim)
        assert axis == 1
        parts = [
            arr.take(range(lo, hi), axis=axis)
            for lo, hi in mesh_lib.host_slice_bounds(arr.shape[axis], 3)
        ]
        np.testing.assert_array_equal(np.concatenate(parts, axis=axis), arr)

    def test_data_tag_slices_reassemble_leading_axis(self):
        arr = np.arange(10 * 4, dtype=np.float32).reshape(10, 4)
        axis = mesh_lib.shard_axis_for_tag("data", arr.ndim)
        assert axis == 0
        parts = [
            arr[lo:hi]
            for lo, hi in mesh_lib.host_slice_bounds(arr.shape[0], 4)
        ]
        np.testing.assert_array_equal(np.concatenate(parts, axis=0), arr)


# ---------------------------------------------------------------------------
# satellite: per-axis collective accounting on a 2-axis mesh
# ---------------------------------------------------------------------------

class TestTwoAxisAccounting:
    def test_sparse_bytes_attribute_to_data_axis_only(self, mesh_2d):
        """One program with a sparse pair-exchange over `data` and a dense
        psum over `model`: the wire accounting must keep the axes apart —
        sparse counters live under `collective.axis.data.*`, the model
        axis sees only its dense bytes, and `axis_wire_bytes` splits the
        delta per axis."""
        dim = 64

        def body(idx, val):
            g = collectives.sparse_all_reduce_sum(
                idx, val, dim, collectives.DATA_AXIS
            )
            s = collectives.all_reduce_sum(jnp.sum(g), collectives.MODEL_AXIS)
            return g + s

        mapped = collectives.shard_map_over(
            mesh_2d, (P(), P()), P(), fn=body
        )
        idx = jnp.arange(4, dtype=jnp.int32)
        val = jnp.ones(4, jnp.float32)
        before = metrics.snapshot()
        np.asarray(jax.jit(mapped)(idx, val))  # trace-time accounting
        delta = metrics.snapshot_delta(before, metrics.snapshot())

        counters = delta["counters"]
        assert counters["collective.axis.data.sparse.bytes"] > 0
        assert counters["collective.axis.data.bytes"] > 0
        assert counters["collective.axis.model.bytes"] > 0
        # nothing sparse ever ran on the model axis
        assert not any(
            name.startswith("collective.axis.model.sparse")
            for name in counters
        )
        wire = collectives.axis_wire_bytes(delta)
        assert set(wire) >= {"data", "model"}
        assert wire["data"] == counters["collective.axis.data.bytes"]
        assert wire["model"] == counters["collective.axis.model.bytes"]
        # pair exchange beats the dense-equivalent it replaced
        assert (
            counters["collective.axis.data.sparse.bytes"]
            < counters["collective.axis.data.sparse.dense_equiv_bytes"]
        )
        ratio = delta["gauges"].get("collective.sparse_ratio.data")
        assert ratio is not None and 0.0 < ratio < 1.0
        assert "collective.sparse_ratio.model" not in delta["gauges"]

    def test_2d_fit_routes_traffic_to_both_axes(self, mesh_2d):
        """End-to-end: a 2D fit's trace must account model-axis traffic
        (active-feature assembly) separately from data-axis traffic
        (gradient + loss reduces)."""
        from flink_ml_tpu.parallel import overlap

        overlap.clear_program_cache()  # force a fresh trace to count
        indices, values, y = _sparse_problem(n=64, d=16, nnz=4, seed=2)
        before = metrics.snapshot()
        _fit(mesh_2d, indices, values, y, 16, max_iter=2)
        delta = metrics.snapshot_delta(before, metrics.snapshot())
        wire = collectives.axis_wire_bytes(delta)
        assert wire.get("data", 0) > 0
        assert wire.get("model", 0) > 0


# ---------------------------------------------------------------------------
# 1D-vs-2D parity
# ---------------------------------------------------------------------------

class TestParity:
    def test_single_feature_shard_is_bitwise_equal(self):
        """On an (8, 1) mesh the 2D program owns every feature, so the
        active-feature assembly is the identity and the data-axis sparse
        reduce is the SAME association as the GSPMD reference — the
        coefficients must agree BITWISE, not merely closely."""
        m = mesh_lib.create_mesh_2d(1)  # (data=8, model=1)
        indices, values, y = _sparse_problem(n=128, d=30, seed=7)
        with config.sparse_2d_mode("off"):
            ref = _fit(m, indices, values, y, 30)
        auto = _fit(m, indices, values, y, 30)
        np.testing.assert_array_equal(np.asarray(auto[0]), np.asarray(ref[0]))
        assert auto[2] == ref[2] == 6

    def test_multi_shard_allclose(self, mesh_2d):
        """Across real feature shards only the REDUCTION ORDER differs
        (per-shard scatter partials fold in a different association), so
        the contract is allclose, not bit equality — the same caveat as
        docs/performance.md "2D mesh"."""
        indices, values, y = _sparse_problem(n=128, d=30, seed=7)
        with config.sparse_2d_mode("off"):
            ref = _fit(mesh_2d, indices, values, y, 30)
        auto = _fit(mesh_2d, indices, values, y, 30)
        np.testing.assert_allclose(
            np.asarray(auto[0]), np.asarray(ref[0]), rtol=3e-5, atol=3e-6
        )
        assert auto[2] == ref[2] == 6

    def test_mode_off_disables_2d_routing(self, mesh_2d):
        sgd = SGD(max_iter=2, shard_features=True)
        with config.sparse_2d_mode("off"):
            assert not sgd._use_2d(mesh_2d, SPARSE_BINARY_LOGISTIC_LOSS)
        assert sgd._use_2d(mesh_2d, SPARSE_BINARY_LOGISTIC_LOSS)
        # dense losses never route 2D
        from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS

        assert not sgd._use_2d(mesh_2d, BINARY_LOGISTIC_LOSS)


# ---------------------------------------------------------------------------
# whole-fit residency: ONE dispatch, ONE readback
# ---------------------------------------------------------------------------

class TestWholeFit2D:
    def test_2d_fit_is_one_dispatch(self, mesh_2d):
        indices, values, y = _sparse_problem(n=128, d=24, nnz=4, seed=3)
        before = metrics.snapshot()
        coeff, _, epochs = _fit(mesh_2d, indices, values, y, 24, max_iter=5)
        delta = metrics.snapshot_delta(before, metrics.snapshot())
        assert delta["timers"]["iteration.dispatch"]["count"] == 1
        assert epochs == 5
        assert coeff.shape == (24,)


# ---------------------------------------------------------------------------
# acceptance: beyond-HBM model trains only feature-sharded
# ---------------------------------------------------------------------------

class TestBeyondBudget:
    def test_wide_model_trains_2d_but_not_replicated(self):
        """d=200k f32: replicated coeff staging alone is 800 KB. Under a
        600 KB budget the (2, 4) mesh admits 2 × 200 KB per-shard carries
        and trains; the replicated layout is refused at admission before
        any dispatch — the HbmBudgetExceeded contract of ISSUE 17."""
        d = 200_000
        rng = np.random.default_rng(11)
        n, nnz = 256, 4
        indices = rng.integers(0, d, size=(n, nnz)).astype(np.int32)
        values = rng.random((n, nnz))
        y = rng.integers(0, 2, size=n).astype(np.float64)

        memledger.reset()
        with config.hbm_budget_mode(3 * d):  # 600 KB, < one f32 replica
            m2 = mesh_lib.create_mesh_2d(4)  # (data=2, model=4)
            coeff, _, epochs = _fit(
                m2, indices, values, y, d, max_iter=2, global_batch_size=128
            )
            assert epochs == 2 and coeff.shape == (d,)
            assert np.all(np.isfinite(coeff))
            # per-shard residency is what the ledger sees: both sharded
            # carries fit where ONE replicated copy would not
            assert memledger.live_bytes("optimizer") <= 3 * d

            memledger.reset()
            m1 = mesh_lib.create_mesh(("data",))  # no model axis: replicated
            with pytest.raises(memledger.HbmBudgetExceeded):
                _fit(m1, indices, values, y, d, max_iter=2,
                     global_batch_size=128)
        memledger.reset()


# ---------------------------------------------------------------------------
# 2D checkpoints through the multi-host coordinator + elastic resume
# ---------------------------------------------------------------------------

class TestCheckpoint2D:
    @pytest.mark.parametrize(
        "resume_shape,resume_hosts",
        [((2, 2), 2),   # fewer hosts, same model factorization
         ((2, 4), 4)],  # same device count, model axis refactored 2 -> 4
    )
    def test_elastic_sharded_resume_parity_with_single_file(
        self, tmp_path, resume_shape, resume_hosts
    ):
        """A 2D fit killed mid-run with SHARDED (4-host) snapshots resumes
        on a different mesh — fewer hosts or a re-factored model axis —
        and lands on the exact coefficients of the same kill/resume
        through the single-file snapshot path: the sharded transport of
        feature-sharded carries is lossless end to end."""
        indices, values, y = _sparse_problem(n=128, d=24, nnz=4, seed=5)

        def fit_on(shape, ckpt, max_iter):
            nd, nm = shape
            mesh = mesh_lib.create_mesh(
                ("data", "model"), shape=shape,
                devices=jax.devices()[: nd * nm],
            )
            return _fit(
                mesh, indices, values, y, 24, max_iter=max_iter,
                checkpoint_dir=ckpt, checkpoint_key="el2d",
            )

        single = str(tmp_path / "single")
        with faults.inject("chunk", after=6):
            with pytest.raises(InjectedFault):
                fit_on((4, 2), single, 12)
        single_coeff, _, single_epochs = fit_on(resume_shape, single, 12)

        sharded = str(tmp_path / "sharded")
        with config.snapshot_hosts_mode(4):
            with faults.inject("chunk", after=6):
                with pytest.raises(InjectedFault):
                    fit_on((4, 2), sharded, 12)
            assert coordinator.has_sharded(sharded, "el2d")
        with config.snapshot_hosts_mode(resume_hosts):
            sharded_coeff, _, sharded_epochs = fit_on(resume_shape, sharded, 12)

        assert single_epochs == sharded_epochs == 12
        np.testing.assert_array_equal(
            np.asarray(sharded_coeff), np.asarray(single_coeff)
        )

    def test_checkpointed_2d_matches_uncheckpointed(self, tmp_path, mesh_2d):
        """The chunked 2D checkpoint path must reproduce the whole-fit 2D
        coefficients exactly — chunking is a dispatch schedule, not a
        different optimization."""
        indices, values, y = _sparse_problem(n=96, d=16, nnz=4, seed=9)
        plain = _fit(mesh_2d, indices, values, y, 16, max_iter=4)
        ckpt = _fit(
            mesh_2d, indices, values, y, 16, max_iter=4,
            checkpoint_dir=str(tmp_path), checkpoint_key="c2d",
            checkpoint_interval=2,
        )
        np.testing.assert_array_equal(
            np.asarray(ckpt[0]), np.asarray(plain[0])
        )
        assert ckpt[2] == plain[2] == 4
