"""MicroBatchServer — double-buffered fused micro-batch serving tests.

Pins the serving contract: in-order bit-identical outputs under bucket
padding, bounded in-flight deferral of guard errors (late by at most the
window, never dropped or reordered), and per-batch host syncs independent
of pipeline depth.
"""

import numpy as np
import pytest

import jax

from flink_ml_tpu import config
from flink_ml_tpu.pipeline import PipelineModel
from flink_ml_tpu.serving import MicroBatchServer, _next_bucket, serve_stream
from flink_ml_tpu.table import SparseBatch, StreamTable, Table
from flink_ml_tpu.utils import metrics

RNG = np.random.RandomState(11)


def _scaler_pipeline(d=4):
    from flink_ml_tpu.models.feature.normalizer import Normalizer
    from flink_ml_tpu.models.feature.standardscaler import StandardScalerModel

    ss = StandardScalerModel()
    ss.mean = RNG.randn(d)
    ss.std = np.abs(RNG.randn(d)) + 0.1
    ss.set_input_col("features").set_output_col("scaled")
    norm = Normalizer().set_p(2.0).set_input_col("scaled").set_output_col("norm")
    return PipelineModel([ss, norm])


def _batches(sizes, d=4):
    return [Table({"features": RNG.randn(n, d).astype(np.float32)}) for n in sizes]


def test_bucket_schedule():
    assert _next_bucket(1, None) == 8
    assert _next_bucket(8, None) == 8
    assert _next_bucket(9, None) == 16
    assert _next_bucket(700, None) == 1024
    assert _next_bucket(5, [16, 64]) == 16
    assert _next_bucket(65, [16, 64]) == 65  # beyond largest bucket: exact
    assert _next_bucket(0, None) == 0


def test_serve_in_order_parity():
    pm = _scaler_pipeline()
    batches = _batches([5, 13, 16, 3, 40])
    outs = serve_stream(pm, StreamTable.from_batches(batches))
    assert [t.num_rows for t in outs] == [5, 13, 16, 3, 40]
    with config.pipeline_fusion_mode("off"):
        for batch, out in zip(batches, outs):
            # reference: the eager per-stage path on the SAME device-born
            # batch (a host-table transform computes the scaler in numpy
            # f64 — a different, legitimate answer)
            dev = Table(
                {name: jax.device_put(batch.column(name)) for name in batch.column_names}
            )
            ref = pm.transform(dev)[0]
            assert np.array_equal(
                np.asarray(ref.column("norm")), np.asarray(out.column("norm"))
            ), "padded+fused serving output differs from eager per-batch transform"


def test_padding_bounds_compiles():
    """Batches sharing a bucket share the compiled segment program."""
    from flink_ml_tpu.obs import tracing

    pm = _scaler_pipeline()
    tracing.install_jax_hooks()

    def compiles():
        return metrics.snapshot()["counters"].get("jit.compile", 0)

    warm = _batches([7])  # bucket 8
    list(MicroBatchServer(pm).serve(StreamTable.from_batches(warm)))
    before = compiles()
    more = _batches([5, 3, 8, 6, 2])  # all bucket 8: zero new compiles
    outs = list(MicroBatchServer(pm).serve(StreamTable.from_batches(more)))
    assert [t.num_rows for t in outs] == [5, 3, 8, 6, 2]
    assert compiles() == before, "same-bucket batches must not recompile"
    assert metrics.get_gauge("serving.buckets") == 1


def test_guard_error_deferred_not_dropped():
    """A bad batch raises when IT is retired from the window — later than
    eager by at most in_flight batches, with every prior batch's output
    already yielded correctly."""
    from flink_ml_tpu.models.feature.bucketizer import Bucketizer

    stage = (
        Bucketizer()
        .set_input_cols("a")
        .set_output_cols("oa")
        .set_splits_array([[0.0, 1.0, 2.0]])
    )
    pm = PipelineModel([stage])
    good = Table({"a": np.array([0.5, 1.5], dtype=np.float32)})
    bad = Table({"a": np.array([0.5, 99.0], dtype=np.float32)})  # out of range
    stream = StreamTable.from_batches([good, bad, good])
    got = []
    with pytest.raises(ValueError, match="invalid value"):
        for out in MicroBatchServer(pm, in_flight=2).serve(stream):
            got.append(np.asarray(out.column("oa")))
    assert len(got) == 1  # the batch before the bad one came through intact
    assert got[0].tolist() == [0.0, 1.0]


def test_per_batch_syncs_independent_of_stage_count():
    """The double-buffer claim: a deep all-device pipeline with guard
    stages pays ONE transform sync per batch — not one per stage."""
    from flink_ml_tpu.models.feature.binarizer import Binarizer
    from flink_ml_tpu.models.feature.bucketizer import Bucketizer
    from flink_ml_tpu.models.feature.normalizer import Normalizer
    from flink_ml_tpu.models.feature.standardscaler import StandardScalerModel
    from flink_ml_tpu.models.feature.vectorassembler import VectorAssembler

    ss = StandardScalerModel()
    ss.mean = RNG.randn(5)
    ss.std = np.abs(RNG.randn(5)) + 0.1
    ss.set_input_col("assembled").set_output_col("scaled")
    pm = PipelineModel(
        [
            VectorAssembler().set_input_cols("va", "vb").set_output_col("assembled"),
            ss,
            Normalizer().set_p(2.0).set_input_col("scaled").set_output_col("norm"),
            Bucketizer()
            .set_input_cols("raw")
            .set_output_cols("bucket")
            .set_splits_array([[-100.0, 0.0, 100.0]]),
            Binarizer().set_input_cols("bucket").set_output_cols("bin").set_thresholds(0.5),
        ]
    )

    def batch(n):
        return Table(
            {
                "va": RNG.randn(n, 2).astype(np.float32),
                "vb": RNG.randn(n, 3).astype(np.float32),
                "raw": RNG.randn(n).astype(np.float32),
            }
        )

    batches = [batch(6) for _ in range(4)]
    # warm the compile for bucket 8
    list(MicroBatchServer(pm).serve(StreamTable.from_batches([batch(6)])))

    before = metrics.snapshot()["counters"].get("iteration.host_sync.transform", 0)
    outs = list(MicroBatchServer(pm).serve(StreamTable.from_batches(batches)))
    after = metrics.snapshot()["counters"].get("iteration.host_sync.transform", 0)
    assert len(outs) == 4
    assert after - before == len(batches), (
        f"wanted 1 sync per batch (4), got {after - before} — "
        "per-batch syncs must not scale with stage count"
    )


def test_sparse_column_through_serving():
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegressionModel,
    )

    m = LogisticRegressionModel()
    m.coefficient = RNG.randn(16)
    m.set_features_col("features").set_prediction_col("pred")
    pm = PipelineModel([m])

    def sparse_batch(n):
        return Table(
            {
                "features": SparseBatch(
                    16,
                    RNG.randint(0, 16, size=(n, 3)).astype(np.int32),
                    RNG.rand(n, 3).astype(np.float32),
                )
            }
        )

    batches = [sparse_batch(5), sparse_batch(11)]
    outs = serve_stream(pm, StreamTable.from_batches(batches))
    assert [t.num_rows for t in outs] == [5, 11]
    with config.pipeline_fusion_mode("off"):
        for batch, out in zip(batches, outs):
            ref = pm.transform(batch)[0]
            assert np.array_equal(
                np.asarray(ref.column("pred")), np.asarray(out.column("pred"))
            )


def test_empty_stream_and_empty_batch():
    pm = _scaler_pipeline()
    assert serve_stream(pm, StreamTable.from_batches([])) == []
    outs = serve_stream(pm, StreamTable.from_batches(_batches([0, 4])))
    assert [t.num_rows for t in outs] == [0, 4]


def test_server_rejects_non_pipeline():
    with pytest.raises(TypeError):
        MicroBatchServer(object())


# ---------------------------------------------------------------------------
# flow-control sweep: early-exit cleanup, admission, deadlines, retries
# ---------------------------------------------------------------------------

def test_early_termination_releases_window():
    """A consumer that stops after 2 batches must not leak the staged
    in-flight window: closing the generator drains/releases every pending
    batch and frees its queue slots (serving.cancelled counts them)."""
    pm = _scaler_pipeline()
    server = MicroBatchServer(pm, in_flight=3)
    stream = StreamTable.from_batches(_batches([4, 4, 4, 4, 4, 4]))
    before = metrics.get_counter("serving.cancelled", 0)
    got = []
    it = server.serve(stream)
    for out in it:
        got.append(out)
        if len(got) == 2:
            break
    it.close()  # the consumer walks away mid-stream
    assert len(got) == 2
    assert server._window is not None and len(server._window) == 0, (
        "in-flight batches leaked past generator close"
    )
    assert server._window.closed
    released = metrics.get_counter("serving.cancelled", 0) - before
    assert released > 0, "the pending window must be accounted as released"
    assert server.health().cancelled == released


def test_deferred_guard_error_releases_window():
    """When a deferred guard error terminates serve(), the batches still
    parked behind the failing one are released too — no staged buffers or
    slots survive the raise."""
    from flink_ml_tpu.models.feature.bucketizer import Bucketizer

    stage = (
        Bucketizer()
        .set_input_cols("a")
        .set_output_cols("oa")
        .set_splits_array([[0.0, 1.0, 2.0]])
    )
    pm = PipelineModel([stage])
    good = Table({"a": np.array([0.5, 1.5], dtype=np.float32)})
    bad = Table({"a": np.array([0.5, 99.0], dtype=np.float32)})
    server = MicroBatchServer(pm, in_flight=3)
    with pytest.raises(ValueError, match="invalid value"):
        # bad retires first in the drain loop; good batches queue behind it
        list(server.serve(StreamTable.from_batches([bad, good, good])))
    assert len(server._window) == 0 and server._window.closed


def test_submit_rejects_when_admission_full():
    """The push API's admission control: a burst beyond the admission
    queue fast-fails with a typed ServerOverloaded carrying the live
    depth — bounded memory instead of grow-until-OOM."""
    from flink_ml_tpu.serving import ServerOverloaded

    pm = _scaler_pipeline()
    server = MicroBatchServer(pm, in_flight=2, admission=3)
    submitted, rejected = 0, 0
    for _ in range(40):
        try:
            server.submit(Table({"features": RNG.randn(8, 4).astype(np.float32)}))
            submitted += 1
        except ServerOverloaded as e:
            rejected += 1
            assert e.depth <= e.capacity == 3
    server.close()
    results = list(server.results())
    assert len(results) == submitted, "every admitted request must retire"
    assert [r.seq for r in results] == sorted(r.seq for r in results)
    assert rejected > 0, "an unpaced 40-burst must overflow admission=3"
    h = server.health()
    assert h.rejected == rejected and h.submitted == submitted
    assert server._requests.stats.peak_depth <= 3
    assert server._window.stats.peak_depth <= 2


def test_submit_deadline_expires_before_dispatch():
    pm = _scaler_pipeline()
    server = MicroBatchServer(pm, in_flight=2, admission=8)
    seqs = [
        server.submit(
            Table({"features": RNG.randn(8, 4).astype(np.float32)}), deadline_ms=0.0
        )
        for _ in range(3)
    ]
    server.close()
    results = {r.seq: r for r in server.results()}
    assert set(results) == set(seqs)
    assert all(r.status in ("expired", "late") for r in results.values())
    h = server.health()
    assert h.expired + h.late == 3
    assert metrics.get_counter("serving.deadlineMiss", 0) >= 3


def test_push_per_request_error_does_not_kill_stream():
    """One bad batch surfaces as a status='error' result; later requests
    still retire ok."""
    from flink_ml_tpu.models.feature.bucketizer import Bucketizer

    stage = (
        Bucketizer()
        .set_input_cols("a")
        .set_output_cols("oa")
        .set_splits_array([[0.0, 1.0, 2.0]])
    )
    pm = PipelineModel([stage])
    server = MicroBatchServer(pm, in_flight=2, admission=8)
    server.submit(Table({"a": np.array([0.5, 1.5], dtype=np.float32)}))
    server.submit(Table({"a": np.array([0.5, 99.0], dtype=np.float32)}))  # guard fires
    server.submit(Table({"a": np.array([1.5, 0.5], dtype=np.float32)}))
    server.close()
    results = list(server.results())
    assert [r.status for r in results] == ["ok", "error", "ok"]
    assert isinstance(results[1].error, ValueError)
    assert server.health().errors == 1


def test_serving_batch_transient_fault_retried_bit_identical():
    """A flaky batch dispatch under the retry budget is invisible to the
    results; with the budget at 0 the same fault is fatal."""
    from flink_ml_tpu.ckpt import faults
    from flink_ml_tpu.ckpt.faults import TransientFault

    pm = _scaler_pipeline()
    batches = _batches([5, 9, 7])
    clean = serve_stream(pm, StreamTable.from_batches(batches))
    with config.transient_retry_mode(3):
        with faults.flaky("serving.batch", times=2) as plan:
            retried = serve_stream(pm, StreamTable.from_batches(batches))
    assert plan.failures == 2
    for a, b in zip(clean, retried):
        np.testing.assert_array_equal(
            np.asarray(a.column("norm")), np.asarray(b.column("norm"))
        )
    with config.transient_retry_mode(0):
        with faults.flaky("serving.batch", times=1):
            with pytest.raises(TransientFault):
                serve_stream(pm, StreamTable.from_batches(batches))


def test_health_snapshot_shape():
    pm = _scaler_pipeline()
    server = MicroBatchServer(pm, in_flight=2)
    list(server.serve(StreamTable.from_batches(_batches([4, 4]))))
    h = server.health()
    assert h.inFlight == 2 and h.windowDepth == 0
    assert h.bucketsSeen == 1
    assert h.emaBatchMs >= 0.0


def test_health_reports_hbm_ledger(mesh8):
    """ROADMAP item 3 memory surface: ServerHealth carries the HBM
    ledger's live bytes and peak watermark (docs/observability.md
    "Device memory") — serving uploads ride the `serving` category."""
    from flink_ml_tpu.obs import memledger

    memledger.reset()
    try:
        pm = _scaler_pipeline()
        server = MicroBatchServer(pm, in_flight=2)
        list(server.serve(StreamTable.from_batches(_batches([4, 4]))))
        h = server.health()
        assert h.hbmLiveBytes == memledger.live_bytes()
        assert h.hbmPeakBytes == memledger.peak_bytes()
        # staged serving batches + published model constants went through
        # the accounted funnels, so the fit's peak is nonzero
        assert h.hbmPeakBytes > 0
        assert h.hbmLiveBytes <= h.hbmPeakBytes
    finally:
        memledger.reset()


# ---------------------------------------------------------------------------
# SLO surface: per-stage latency histograms (obs/hist.py) — ISSUE 12
# ---------------------------------------------------------------------------

@pytest.fixture
def _clean_hist():
    from flink_ml_tpu.obs import hist

    hist.reset()
    hist.configure(True)
    yield hist
    hist.reset()
    hist.configure(True)


def test_server_health_stage_latency_percentiles(_clean_hist):
    """ISSUE 12 acceptance: ServerHealth reports p50/p99/p999 per-stage
    latency (queue-wait, batch-form, dispatch, readback) from the
    obs/hist.py histograms."""
    pm = _scaler_pipeline()
    server = MicroBatchServer(pm, in_flight=2, admission=16)
    for _ in range(8):
        server.submit(Table({"features": RNG.randn(8, 4).astype(np.float32)}))
    server.close()
    results = list(server.results())
    assert all(r.status == "ok" for r in results)
    h = server.health()
    for stage in ("queueWait", "batchForm", "dispatch", "readback"):
        p = h.stageLatencyMs[stage]
        assert p["count"] >= 8, stage
        assert 0.0 <= p["p50"] <= p["p99"] <= p["p999"], stage
    # no deadline was set, so no margin histogram
    assert "deadlineMargin" not in h.stageLatencyMs
    # with a generous deadline the margin distribution appears too
    server2 = MicroBatchServer(pm, in_flight=2, admission=16)
    server2.submit(
        Table({"features": RNG.randn(8, 4).astype(np.float32)}), deadline_ms=60_000.0
    )
    server2.close()
    assert [r.status for r in server2.results()] == ["ok"]
    assert server2.health().stageLatencyMs["deadlineMargin"]["count"] >= 1


def test_serving_bit_identical_with_histograms_on_vs_off(_clean_hist):
    """ISSUE 12 acceptance: bit-for-bit identical serving results with
    histograms on vs off (the SLO surface never touches the data path)."""
    from flink_ml_tpu.obs import hist

    pm = _scaler_pipeline()
    batches = _batches([5, 13, 9])
    on = serve_stream(pm, StreamTable.from_batches(batches))
    assert hist.percentiles("serving.dispatchMs")["count"] >= 3
    hist.reset()
    hist.configure(False)
    off = serve_stream(pm, StreamTable.from_batches(batches))
    assert hist.snapshot() == {}  # recording really was off
    for a, b in zip(on, off):
        np.testing.assert_array_equal(
            np.asarray(a.column("norm")), np.asarray(b.column("norm"))
        )


def test_deadline_miss_cause_attribution(_clean_hist):
    """`serving.deadlineMiss` splits into expired-in-queue vs
    late-after-dispatch; the old name stays as their sum."""
    import time as _time

    from flink_ml_tpu import flow
    from flink_ml_tpu.obs import hist

    base_sum = metrics.get_counter("serving.deadlineMiss", 0)
    base_expired = metrics.get_counter("serving.deadlineMiss.expired", 0)
    base_late = metrics.get_counter("serving.deadlineMiss.late", 0)

    # expired IN QUEUE: 0ms deadline passes before dispatch
    pm = _scaler_pipeline()
    server = MicroBatchServer(pm, in_flight=2, admission=8)
    server.submit(
        Table({"features": RNG.randn(8, 4).astype(np.float32)}), deadline_ms=0.0
    )
    server.close()
    (r,) = list(server.results())
    assert r.status == "expired"
    assert metrics.get_counter("serving.deadlineMiss.expired", 0) == base_expired + 1

    # late AFTER dispatch: retire a really-transformed batch whose
    # deadline already passed (white-box: deterministic, no sleep races)
    late_server = MicroBatchServer(pm, in_flight=2)
    late_server._out = flow.BoundedChannel(4, name="test.results")
    staged, n = late_server._stage_batch(
        Table({"features": RNG.randn(8, 4).astype(np.float32)})
    )
    out, pending = pm.transform_deferred(staged)
    late_server._retire((0, _time.monotonic() - 1.0, out, pending, n))
    result = late_server._out.get()
    assert result.status == "late"
    assert metrics.get_counter("serving.deadlineMiss.late", 0) == base_late + 1
    assert hist.percentiles("serving.lateByMs")["count"] >= 1

    # compatibility: the old counter is exactly the sum of the causes
    assert metrics.get_counter("serving.deadlineMiss", 0) == base_sum + 2
