"""MicroBatchServer — double-buffered fused micro-batch serving tests.

Pins the serving contract: in-order bit-identical outputs under bucket
padding, bounded in-flight deferral of guard errors (late by at most the
window, never dropped or reordered), and per-batch host syncs independent
of pipeline depth.
"""

import numpy as np
import pytest

import jax

from flink_ml_tpu import config
from flink_ml_tpu.pipeline import PipelineModel
from flink_ml_tpu.serving import MicroBatchServer, _next_bucket, serve_stream
from flink_ml_tpu.table import SparseBatch, StreamTable, Table
from flink_ml_tpu.utils import metrics

RNG = np.random.RandomState(11)


def _scaler_pipeline(d=4):
    from flink_ml_tpu.models.feature.normalizer import Normalizer
    from flink_ml_tpu.models.feature.standardscaler import StandardScalerModel

    ss = StandardScalerModel()
    ss.mean = RNG.randn(d)
    ss.std = np.abs(RNG.randn(d)) + 0.1
    ss.set_input_col("features").set_output_col("scaled")
    norm = Normalizer().set_p(2.0).set_input_col("scaled").set_output_col("norm")
    return PipelineModel([ss, norm])


def _batches(sizes, d=4):
    return [Table({"features": RNG.randn(n, d).astype(np.float32)}) for n in sizes]


def test_bucket_schedule():
    assert _next_bucket(1, None) == 8
    assert _next_bucket(8, None) == 8
    assert _next_bucket(9, None) == 16
    assert _next_bucket(700, None) == 1024
    assert _next_bucket(5, [16, 64]) == 16
    assert _next_bucket(65, [16, 64]) == 65  # beyond largest bucket: exact
    assert _next_bucket(0, None) == 0


def test_serve_in_order_parity():
    pm = _scaler_pipeline()
    batches = _batches([5, 13, 16, 3, 40])
    outs = serve_stream(pm, StreamTable.from_batches(batches))
    assert [t.num_rows for t in outs] == [5, 13, 16, 3, 40]
    with config.pipeline_fusion_mode("off"):
        for batch, out in zip(batches, outs):
            # reference: the eager per-stage path on the SAME device-born
            # batch (a host-table transform computes the scaler in numpy
            # f64 — a different, legitimate answer)
            dev = Table(
                {name: jax.device_put(batch.column(name)) for name in batch.column_names}
            )
            ref = pm.transform(dev)[0]
            assert np.array_equal(
                np.asarray(ref.column("norm")), np.asarray(out.column("norm"))
            ), "padded+fused serving output differs from eager per-batch transform"


def test_padding_bounds_compiles():
    """Batches sharing a bucket share the compiled segment program."""
    from flink_ml_tpu.obs import tracing

    pm = _scaler_pipeline()
    tracing.install_jax_hooks()

    def compiles():
        return metrics.snapshot()["counters"].get("jit.compile", 0)

    warm = _batches([7])  # bucket 8
    list(MicroBatchServer(pm).serve(StreamTable.from_batches(warm)))
    before = compiles()
    more = _batches([5, 3, 8, 6, 2])  # all bucket 8: zero new compiles
    outs = list(MicroBatchServer(pm).serve(StreamTable.from_batches(more)))
    assert [t.num_rows for t in outs] == [5, 3, 8, 6, 2]
    assert compiles() == before, "same-bucket batches must not recompile"
    assert metrics.get_gauge("serving.buckets") == 1


def test_guard_error_deferred_not_dropped():
    """A bad batch raises when IT is retired from the window — later than
    eager by at most in_flight batches, with every prior batch's output
    already yielded correctly."""
    from flink_ml_tpu.models.feature.bucketizer import Bucketizer

    stage = (
        Bucketizer()
        .set_input_cols("a")
        .set_output_cols("oa")
        .set_splits_array([[0.0, 1.0, 2.0]])
    )
    pm = PipelineModel([stage])
    good = Table({"a": np.array([0.5, 1.5], dtype=np.float32)})
    bad = Table({"a": np.array([0.5, 99.0], dtype=np.float32)})  # out of range
    stream = StreamTable.from_batches([good, bad, good])
    got = []
    with pytest.raises(ValueError, match="invalid value"):
        for out in MicroBatchServer(pm, in_flight=2).serve(stream):
            got.append(np.asarray(out.column("oa")))
    assert len(got) == 1  # the batch before the bad one came through intact
    assert got[0].tolist() == [0.0, 1.0]


def test_per_batch_syncs_independent_of_stage_count():
    """The double-buffer claim: a deep all-device pipeline with guard
    stages pays ONE transform sync per batch — not one per stage."""
    from flink_ml_tpu.models.feature.binarizer import Binarizer
    from flink_ml_tpu.models.feature.bucketizer import Bucketizer
    from flink_ml_tpu.models.feature.normalizer import Normalizer
    from flink_ml_tpu.models.feature.standardscaler import StandardScalerModel
    from flink_ml_tpu.models.feature.vectorassembler import VectorAssembler

    ss = StandardScalerModel()
    ss.mean = RNG.randn(5)
    ss.std = np.abs(RNG.randn(5)) + 0.1
    ss.set_input_col("assembled").set_output_col("scaled")
    pm = PipelineModel(
        [
            VectorAssembler().set_input_cols("va", "vb").set_output_col("assembled"),
            ss,
            Normalizer().set_p(2.0).set_input_col("scaled").set_output_col("norm"),
            Bucketizer()
            .set_input_cols("raw")
            .set_output_cols("bucket")
            .set_splits_array([[-100.0, 0.0, 100.0]]),
            Binarizer().set_input_cols("bucket").set_output_cols("bin").set_thresholds(0.5),
        ]
    )

    def batch(n):
        return Table(
            {
                "va": RNG.randn(n, 2).astype(np.float32),
                "vb": RNG.randn(n, 3).astype(np.float32),
                "raw": RNG.randn(n).astype(np.float32),
            }
        )

    batches = [batch(6) for _ in range(4)]
    # warm the compile for bucket 8
    list(MicroBatchServer(pm).serve(StreamTable.from_batches([batch(6)])))

    before = metrics.snapshot()["counters"].get("iteration.host_sync.transform", 0)
    outs = list(MicroBatchServer(pm).serve(StreamTable.from_batches(batches)))
    after = metrics.snapshot()["counters"].get("iteration.host_sync.transform", 0)
    assert len(outs) == 4
    assert after - before == len(batches), (
        f"wanted 1 sync per batch (4), got {after - before} — "
        "per-batch syncs must not scale with stage count"
    )


def test_sparse_column_through_serving():
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegressionModel,
    )

    m = LogisticRegressionModel()
    m.coefficient = RNG.randn(16)
    m.set_features_col("features").set_prediction_col("pred")
    pm = PipelineModel([m])

    def sparse_batch(n):
        return Table(
            {
                "features": SparseBatch(
                    16,
                    RNG.randint(0, 16, size=(n, 3)).astype(np.int32),
                    RNG.rand(n, 3).astype(np.float32),
                )
            }
        )

    batches = [sparse_batch(5), sparse_batch(11)]
    outs = serve_stream(pm, StreamTable.from_batches(batches))
    assert [t.num_rows for t in outs] == [5, 11]
    with config.pipeline_fusion_mode("off"):
        for batch, out in zip(batches, outs):
            ref = pm.transform(batch)[0]
            assert np.array_equal(
                np.asarray(ref.column("pred")), np.asarray(out.column("pred"))
            )


def test_empty_stream_and_empty_batch():
    pm = _scaler_pipeline()
    assert serve_stream(pm, StreamTable.from_batches([])) == []
    outs = serve_stream(pm, StreamTable.from_batches(_batches([0, 4])))
    assert [t.num_rows for t in outs] == [0, 4]


def test_server_rejects_non_pipeline():
    with pytest.raises(TypeError):
        MicroBatchServer(object())


# ---------------------------------------------------------------------------
# flow-control sweep: early-exit cleanup, admission, deadlines, retries
# ---------------------------------------------------------------------------

def test_early_termination_releases_window():
    """A consumer that stops after 2 batches must not leak the staged
    in-flight window: closing the generator drains/releases every pending
    batch and frees its queue slots (serving.cancelled counts them)."""
    pm = _scaler_pipeline()
    server = MicroBatchServer(pm, in_flight=3)
    stream = StreamTable.from_batches(_batches([4, 4, 4, 4, 4, 4]))
    before = metrics.get_counter("serving.cancelled", 0)
    got = []
    it = server.serve(stream)
    for out in it:
        got.append(out)
        if len(got) == 2:
            break
    it.close()  # the consumer walks away mid-stream
    assert len(got) == 2
    assert server._window is not None and len(server._window) == 0, (
        "in-flight batches leaked past generator close"
    )
    assert server._window.closed
    released = metrics.get_counter("serving.cancelled", 0) - before
    assert released > 0, "the pending window must be accounted as released"
    assert server.health().cancelled == released


def test_deferred_guard_error_releases_window():
    """When a deferred guard error terminates serve(), the batches still
    parked behind the failing one are released too — no staged buffers or
    slots survive the raise."""
    from flink_ml_tpu.models.feature.bucketizer import Bucketizer

    stage = (
        Bucketizer()
        .set_input_cols("a")
        .set_output_cols("oa")
        .set_splits_array([[0.0, 1.0, 2.0]])
    )
    pm = PipelineModel([stage])
    good = Table({"a": np.array([0.5, 1.5], dtype=np.float32)})
    bad = Table({"a": np.array([0.5, 99.0], dtype=np.float32)})
    server = MicroBatchServer(pm, in_flight=3)
    with pytest.raises(ValueError, match="invalid value"):
        # bad retires first in the drain loop; good batches queue behind it
        list(server.serve(StreamTable.from_batches([bad, good, good])))
    assert len(server._window) == 0 and server._window.closed


def test_submit_rejects_when_admission_full():
    """The push API's admission control: a burst beyond the admission
    queue fast-fails with a typed ServerOverloaded carrying the live
    depth — bounded memory instead of grow-until-OOM."""
    from flink_ml_tpu.serving import ServerOverloaded

    pm = _scaler_pipeline()
    server = MicroBatchServer(pm, in_flight=2, admission=3)
    submitted, rejected = 0, 0
    for _ in range(40):
        try:
            server.submit(Table({"features": RNG.randn(8, 4).astype(np.float32)}))
            submitted += 1
        except ServerOverloaded as e:
            rejected += 1
            assert e.depth <= e.capacity == 3
    server.close()
    results = list(server.results())
    assert len(results) == submitted, "every admitted request must retire"
    assert [r.seq for r in results] == sorted(r.seq for r in results)
    assert rejected > 0, "an unpaced 40-burst must overflow admission=3"
    h = server.health()
    assert h.rejected == rejected and h.submitted == submitted
    assert server._requests.stats.peak_depth <= 3
    assert server._window.stats.peak_depth <= 2


def test_submit_deadline_expires_before_dispatch():
    pm = _scaler_pipeline()
    server = MicroBatchServer(pm, in_flight=2, admission=8)
    seqs = [
        server.submit(
            Table({"features": RNG.randn(8, 4).astype(np.float32)}), deadline_ms=0.0
        )
        for _ in range(3)
    ]
    server.close()
    results = {r.seq: r for r in server.results()}
    assert set(results) == set(seqs)
    assert all(r.status in ("expired", "late") for r in results.values())
    h = server.health()
    assert h.expired + h.late == 3
    assert metrics.get_counter("serving.deadlineMiss", 0) >= 3


def test_push_per_request_error_does_not_kill_stream():
    """One bad batch surfaces as a status='error' result; later requests
    still retire ok."""
    from flink_ml_tpu.models.feature.bucketizer import Bucketizer

    stage = (
        Bucketizer()
        .set_input_cols("a")
        .set_output_cols("oa")
        .set_splits_array([[0.0, 1.0, 2.0]])
    )
    pm = PipelineModel([stage])
    server = MicroBatchServer(pm, in_flight=2, admission=8)
    server.submit(Table({"a": np.array([0.5, 1.5], dtype=np.float32)}))
    server.submit(Table({"a": np.array([0.5, 99.0], dtype=np.float32)}))  # guard fires
    server.submit(Table({"a": np.array([1.5, 0.5], dtype=np.float32)}))
    server.close()
    results = list(server.results())
    assert [r.status for r in results] == ["ok", "error", "ok"]
    assert isinstance(results[1].error, ValueError)
    assert server.health().errors == 1


def test_serving_batch_transient_fault_retried_bit_identical():
    """A flaky batch dispatch under the retry budget is invisible to the
    results; with the budget at 0 the same fault is fatal."""
    from flink_ml_tpu.ckpt import faults
    from flink_ml_tpu.ckpt.faults import TransientFault

    pm = _scaler_pipeline()
    batches = _batches([5, 9, 7])
    clean = serve_stream(pm, StreamTable.from_batches(batches))
    with config.transient_retry_mode(3):
        with faults.flaky("serving.batch", times=2) as plan:
            retried = serve_stream(pm, StreamTable.from_batches(batches))
    assert plan.failures == 2
    for a, b in zip(clean, retried):
        np.testing.assert_array_equal(
            np.asarray(a.column("norm")), np.asarray(b.column("norm"))
        )
    with config.transient_retry_mode(0):
        with faults.flaky("serving.batch", times=1):
            with pytest.raises(TransientFault):
                serve_stream(pm, StreamTable.from_batches(batches))


def test_health_snapshot_shape():
    pm = _scaler_pipeline()
    server = MicroBatchServer(pm, in_flight=2)
    list(server.serve(StreamTable.from_batches(_batches([4, 4]))))
    h = server.health()
    assert h.inFlight == 2 and h.windowDepth == 0
    assert h.bucketsSeen == 1
    assert h.emaBatchMs >= 0.0


def test_health_reports_hbm_ledger(mesh8):
    """ROADMAP item 3 memory surface: ServerHealth carries the HBM
    ledger's live bytes and peak watermark (docs/observability.md
    "Device memory") — serving uploads ride the `serving` category."""
    from flink_ml_tpu.obs import memledger

    memledger.reset()
    try:
        pm = _scaler_pipeline()
        server = MicroBatchServer(pm, in_flight=2)
        list(server.serve(StreamTable.from_batches(_batches([4, 4]))))
        h = server.health()
        assert h.hbmLiveBytes == memledger.live_bytes()
        assert h.hbmPeakBytes == memledger.peak_bytes()
        # staged serving batches + published model constants went through
        # the accounted funnels, so the fit's peak is nonzero
        assert h.hbmPeakBytes > 0
        assert h.hbmLiveBytes <= h.hbmPeakBytes
    finally:
        memledger.reset()


# ---------------------------------------------------------------------------
# SLO surface: per-stage latency histograms (obs/hist.py) — ISSUE 12
# ---------------------------------------------------------------------------

@pytest.fixture
def _clean_hist():
    from flink_ml_tpu.obs import hist

    hist.reset()
    hist.configure(True)
    yield hist
    hist.reset()
    hist.configure(True)


def test_server_health_stage_latency_percentiles(_clean_hist):
    """ISSUE 12 acceptance: ServerHealth reports p50/p99/p999 per-stage
    latency (queue-wait, batch-form, dispatch, readback) from the
    obs/hist.py histograms."""
    pm = _scaler_pipeline()
    server = MicroBatchServer(pm, in_flight=2, admission=16)
    for _ in range(8):
        server.submit(Table({"features": RNG.randn(8, 4).astype(np.float32)}))
    server.close()
    results = list(server.results())
    assert all(r.status == "ok" for r in results)
    h = server.health()
    for stage in ("queueWait", "batchForm", "dispatch", "readback"):
        p = h.stageLatencyMs[stage]
        assert p["count"] >= 8, stage
        assert 0.0 <= p["p50"] <= p["p99"] <= p["p999"], stage
    # no deadline was set: the stage is reported, but with no
    # observations its percentile summary is None (never fabricated)
    assert h.stageLatencyMs["deadlineMargin"] is None
    # with a generous deadline the margin distribution appears too
    server2 = MicroBatchServer(pm, in_flight=2, admission=16)
    server2.submit(
        Table({"features": RNG.randn(8, 4).astype(np.float32)}), deadline_ms=60_000.0
    )
    server2.close()
    assert [r.status for r in server2.results()] == ["ok"]
    assert server2.health().stageLatencyMs["deadlineMargin"]["count"] >= 1


def test_serving_bit_identical_with_histograms_on_vs_off(_clean_hist):
    """ISSUE 12 acceptance: bit-for-bit identical serving results with
    histograms on vs off (the SLO surface never touches the data path)."""
    from flink_ml_tpu.obs import hist

    pm = _scaler_pipeline()
    batches = _batches([5, 13, 9])
    on = serve_stream(pm, StreamTable.from_batches(batches))
    assert hist.percentiles("serving.dispatchMs")["count"] >= 3
    hist.reset()
    hist.configure(False)
    off = serve_stream(pm, StreamTable.from_batches(batches))
    assert hist.snapshot() == {}  # recording really was off
    for a, b in zip(on, off):
        np.testing.assert_array_equal(
            np.asarray(a.column("norm")), np.asarray(b.column("norm"))
        )


def test_deadline_miss_cause_attribution(_clean_hist):
    """`serving.deadlineMiss` splits into expired-in-queue vs
    late-after-dispatch; the old name stays as their sum."""
    import time as _time

    from flink_ml_tpu import flow
    from flink_ml_tpu.obs import hist

    base_sum = metrics.get_counter("serving.deadlineMiss", 0)
    base_expired = metrics.get_counter("serving.deadlineMiss.expired", 0)
    base_late = metrics.get_counter("serving.deadlineMiss.late", 0)

    # expired IN QUEUE: 0ms deadline passes before dispatch
    pm = _scaler_pipeline()
    server = MicroBatchServer(pm, in_flight=2, admission=8)
    server.submit(
        Table({"features": RNG.randn(8, 4).astype(np.float32)}), deadline_ms=0.0
    )
    server.close()
    (r,) = list(server.results())
    assert r.status == "expired"
    assert metrics.get_counter("serving.deadlineMiss.expired", 0) == base_expired + 1

    # late AFTER dispatch: retire a really-transformed batch whose
    # deadline already passed (white-box: deterministic, no sleep races)
    late_server = MicroBatchServer(pm, in_flight=2)
    late_server._out = flow.BoundedChannel(4, name="test.results")
    staged, n = late_server._stage_batch(
        Table({"features": RNG.randn(8, 4).astype(np.float32)})
    )
    out, pending = pm.transform_deferred(staged)
    late_server._retire(
        (((0, _time.monotonic() - 1.0, 0, n, None),), out, pending, n)
    )
    result = late_server._out.get()
    assert result.status == "late"
    assert metrics.get_counter("serving.deadlineMiss.late", 0) == base_late + 1
    assert hist.percentiles("serving.lateByMs")["count"] >= 1

    # compatibility: the old counter is exactly the sum of the causes
    assert metrics.get_counter("serving.deadlineMiss", 0) == base_sum + 2


# ---------------------------------------------------------------------------
# continuous batching (ISSUE 19 tentpole): mid-flight forming, budget flush
# ---------------------------------------------------------------------------

def _push_all(server, batches, tenant=None):
    """Submit every batch, close, and collect results keyed by seq."""
    seqs = [server.submit(b, tenant=tenant) for b in batches]
    server.close()
    return seqs, {r.seq: r for r in server.results()}


def test_continuous_bit_identical_to_request_mode():
    """ISSUE 19 acceptance: continuous batching returns bit-identical
    per-request rows — coalescing is a scheduling decision, never a
    numerics decision (same bucket padding, same fused plan)."""
    pm = _scaler_pipeline()
    sizes = [3, 5, 2, 8, 1, 4, 7, 2]
    batches = _batches(sizes)
    ref_server = MicroBatchServer(pm, in_flight=2, admission=16, buckets=(8, 32))
    _, ref = _push_all(ref_server, batches)
    cont = MicroBatchServer(
        pm,
        in_flight=2,
        admission=16,
        buckets=(8, 32),
        batching="continuous",
        form_rows=32,
        form_budget_ms=20.0,
    )
    _, got = _push_all(cont, batches)
    assert sorted(got) == sorted(ref) == list(range(len(sizes)))
    for seq in ref:
        assert ref[seq].status == "ok" and got[seq].status == "ok"
        assert got[seq].table.num_rows == sizes[seq]
        np.testing.assert_array_equal(
            np.asarray(ref[seq].table.column("norm")),
            np.asarray(got[seq].table.column("norm")),
        )


def test_continuous_bucket_full_flushes_immediately():
    """A forming batch that reaches `form_rows` dispatches NOW — it does
    not sit out the rest of its forming budget."""
    import time as _time

    pm = _scaler_pipeline()
    server = MicroBatchServer(
        pm,
        in_flight=2,
        admission=16,
        buckets=(8,),
        batching="continuous",
        form_rows=8,
        form_budget_ms=10_000.0,  # a budget flush would blow the timing assert
    )
    before = metrics.get_counter("serving.coalesced", 0)
    t0 = _time.monotonic()
    server.submit(Table({"features": RNG.randn(4, 4).astype(np.float32)}))
    server.submit(Table({"features": RNG.randn(4, 4).astype(np.float32)}))
    it = server.results()
    results = [next(it), next(it)]
    dt = _time.monotonic() - t0
    server.close()
    assert [r.status for r in results] == ["ok", "ok"]
    assert [r.table.num_rows for r in results] == [4, 4]
    assert dt < 5.0, "bucket-full flush must not wait for the forming budget"
    assert metrics.get_counter("serving.coalesced", 0) >= before + 2


def test_continuous_form_budget_flushes_partial_batch():
    """A lone request in a huge bucket dispatches once its forming budget
    expires — continuous batching never strands a partial batch."""
    import time as _time

    pm = _scaler_pipeline()
    server = MicroBatchServer(
        pm,
        in_flight=2,
        admission=16,
        batching="continuous",
        form_rows=64,
        form_budget_ms=30.0,
    )
    t0 = _time.monotonic()
    server.submit(Table({"features": RNG.randn(2, 4).astype(np.float32)}))
    r = next(server.results())
    dt = _time.monotonic() - t0
    server.close()
    assert r.status == "ok" and r.table.num_rows == 2
    assert dt < 5.0, "the forming-budget flush must fire without more arrivals"


def test_fixed_batching_waits_for_full_bucket():
    """The fixed baseline only flushes on a full bucket (or close) —
    the structural latency continuous batching removes."""
    import time as _time

    pm = _scaler_pipeline()
    server = MicroBatchServer(
        pm,
        in_flight=2,
        admission=16,
        batching="fixed",
        form_rows=8,
    )
    server.submit(Table({"features": RNG.randn(4, 4).astype(np.float32)}))
    _time.sleep(0.25)  # many forming budgets; fixed mode must still hold it
    assert len(server._out) == 0, "fixed batching must not flush a partial bucket"
    server.close()  # drain flush: the partial batch still dispatches
    (r,) = list(server.results())
    assert r.status == "ok" and r.table.num_rows == 4


def test_continuous_never_coalesces_across_tenants():
    """Two tenants' signature-identical requests stay separate forming
    batches — results carry their tenant, and `serving.coalesced` stays
    flat (a merged dispatch would route one tenant through the other's
    model)."""
    pm = _scaler_pipeline()
    server = MicroBatchServer(
        pm,
        in_flight=2,
        admission=16,
        batching="continuous",
        form_rows=8,
        form_budget_ms=60.0,
    )
    before = metrics.get_counter("serving.coalesced", 0)
    server.submit(Table({"features": RNG.randn(4, 4).astype(np.float32)}), tenant="a")
    server.submit(Table({"features": RNG.randn(4, 4).astype(np.float32)}), tenant="b")
    server.close()
    results = list(server.results())
    assert sorted(r.tenant for r in results) == ["a", "b"]
    assert all(r.status == "ok" for r in results)
    assert metrics.get_counter("serving.coalesced", 0) == before


def test_continuous_incompatible_signature_flushes_old_first():
    """An arriving request whose columns don't match the forming batch
    flushes the OLD batch first — per-tenant FIFO survives coalescing."""
    pm = _scaler_pipeline()
    server = MicroBatchServer(
        pm,
        in_flight=2,
        admission=16,
        batching="continuous",
        form_rows=64,
        form_budget_ms=60.0,
    )
    before = metrics.get_counter("serving.coalesced", 0)
    server.submit(Table({"features": RNG.randn(3, 4).astype(np.float32)}))
    server.submit(Table({"features": RNG.randn(3, 4).astype(np.float64)}))  # new sig
    server.close()
    results = list(server.results())
    assert [r.seq for r in results] == [0, 1], "old forming batch must retire first"
    assert all(r.status == "ok" for r in results)
    assert [r.table.num_rows for r in results] == [3, 3]
    assert metrics.get_counter("serving.coalesced", 0) == before


def test_continuous_expired_while_forming_is_shed():
    """A request whose deadline passes inside the forming buffer is shed
    as expired at flush time — it never pays dispatch."""
    import time as _time

    pm = _scaler_pipeline()
    server = MicroBatchServer(
        pm,
        in_flight=2,
        admission=16,
        batching="fixed",  # never budget-flushes: the deadline passes forming
        form_rows=64,
    )
    server.submit(
        Table({"features": RNG.randn(2, 4).astype(np.float32)}), deadline_ms=30.0
    )
    _time.sleep(0.08)
    server.close()
    (r,) = list(server.results())
    assert r.status == "expired"


# ---------------------------------------------------------------------------
# multi-tenant admission: per-tenant quota gates + fairness under flood
# ---------------------------------------------------------------------------

def test_tenant_quota_rejects_are_typed_and_attributed():
    from flink_ml_tpu.serving import ServerOverloaded

    pm = _scaler_pipeline()
    server = MicroBatchServer(
        pm,
        in_flight=1,
        admission=32,
        batching="continuous",
        form_rows=4,
        tenant_quotas={"A": 2},
    )
    before = metrics.get_counter("serving.rejected.tenant.A", 0)
    accepted, rejected = 0, 0
    for _ in range(12):
        try:
            server.submit(
                Table({"features": RNG.randn(4, 4).astype(np.float32)}), tenant="A"
            )
            accepted += 1
        except ServerOverloaded as e:
            rejected += 1
            assert e.channel == "serving.tenant.A"
            assert e.capacity == 2
    assert rejected > 0, "an unpaced 12-burst must overflow quota=2"
    server.close()
    results = list(server.results())
    assert len(results) == accepted
    assert all(r.tenant == "A" for r in results)
    assert metrics.get_counter("serving.rejected.tenant.A", 0) == before + rejected
    h = server.health()
    assert h.tenantAdmission["A"]["rejected"] == rejected
    assert h.tenantAdmission["A"]["capacity"] == 2


def test_tenant_fairness_soak():
    """ISSUE 19 satellite: tenant A floods past its quota; its overflow
    fast-fails with the typed per-tenant reject while tenant B's
    closed-loop latency stays within tolerance of B running alone."""
    import time as _time

    from flink_ml_tpu.serving import ServerOverloaded

    pm = _scaler_pipeline()

    def b_batch():
        return Table({"features": RNG.randn(4, 4).astype(np.float32)})

    def closed_loop_b(server, rounds, flood_a=None):
        """Submit one B request at a time, waiting for ITS result; returns
        per-request client latencies (ms)."""
        it = server.results()
        lat = []
        for _ in range(rounds):
            if flood_a is not None:
                flood_a()
            t0 = _time.monotonic()
            seq = server.submit(b_batch(), tenant="B")
            while True:
                r = next(it)
                if r.tenant == "B" and r.seq == seq:
                    break
            assert r.status == "ok"
            lat.append((_time.monotonic() - t0) * 1000.0)
        return lat

    def make_server():
        return MicroBatchServer(
            pm,
            in_flight=2,
            admission=32,
            buckets=(8,),
            batching="continuous",
            form_rows=8,
            form_budget_ms=2.0,
            tenant_quotas={"A": 4, "B": 8},
        )

    # solo baseline: B alone (first round also absorbs any compile)
    solo = make_server()
    solo_lat = closed_loop_b(solo, 20)
    solo.close()
    list(solo.results())
    solo_p99 = float(np.percentile(solo_lat[1:], 99))

    # soak: A floods past quota=4 before every B submit
    soak = make_server()
    a_rejects = [0]

    def flood_a():
        for _ in range(8):
            try:
                soak.submit(b_batch(), tenant="A")
            except ServerOverloaded as e:
                assert e.channel == "serving.tenant.A"
                a_rejects[0] += 1

    soak_lat = closed_loop_b(soak, 20, flood_a=flood_a)
    soak.close()
    list(soak.results())
    soak_p99 = float(np.percentile(soak_lat[1:], 99))

    assert a_rejects[0] > 0, "the flood must overflow tenant A's quota"
    h = soak.health()
    assert h.tenantAdmission["A"]["rejected"] == a_rejects[0]
    # fairness: B's p99 under flood stays within a generous envelope of
    # its solo p99 (A's overflow was shed at admission, not queued ahead)
    assert soak_p99 <= 5.0 * solo_p99 + 100.0, (
        f"tenant B p99 {soak_p99:.1f}ms vs solo {solo_p99:.1f}ms — "
        "a quota'd flood must not starve the well-behaved tenant"
    )
