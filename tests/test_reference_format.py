"""Cross-loading reference-written model directories.

The fixtures under tests/fixtures/ are reference-layout directories
(metadata JSON with org.apache class names + binary model data in the
formats of KMeansModelData.ModelDataEncoder /
LogisticRegressionModelData.ModelDataEncoder / DenseVectorSerializer —
see utils/javacodec.py for the byte-level spec and
scripts/make_reference_fixture.py for provenance). Loading them must
resolve the Java class names, decode the binary part files, and predict.
"""

import io
import os

import numpy as np
import pytest

from flink_ml_tpu.table import Table
from flink_ml_tpu.utils import javacodec, read_write

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


class TestJavaCodec:
    def test_dense_vector_round_trip(self):
        v = np.array([1.0, -2.5, 3e300, 0.0])
        decoded = javacodec.read_dense_vector(
            io.BufferedReader(io.BytesIO(javacodec.encode_dense_vector(v)))
        )
        np.testing.assert_array_equal(decoded, v)

    def test_dense_vector_wire_bytes_are_big_endian(self):
        # int32 length (BE) then float64 values (BE) — DenseVectorSerializer
        raw = javacodec.encode_dense_vector(np.array([1.0]))
        assert raw[:4] == b"\x00\x00\x00\x01"
        assert raw[4:] == b"\x3f\xf0\x00\x00\x00\x00\x00\x00"  # 1.0 as BE f64

    def test_kmeans_round_trip(self):
        c = np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
        w = np.array([1.0, 2.0, 3.0])
        payload = javacodec.encode_kmeans_model_data(c, w)
        dc, dw = javacodec.read_kmeans_model_data(io.BufferedReader(io.BytesIO(payload)))
        np.testing.assert_array_equal(dc, c)
        np.testing.assert_array_equal(dw, w)

    def test_lr_round_trip(self):
        payload = javacodec.encode_logisticregression_model_data(
            np.array([1.0, 2.0]), model_version=7
        )
        coeff, version = javacodec.read_logisticregression_model_data(
            io.BufferedReader(io.BytesIO(payload))
        )
        np.testing.assert_array_equal(coeff, [1.0, 2.0])
        assert version == 7

    def test_truncated_payload_raises(self):
        with pytest.raises(EOFError):
            javacodec.read_dense_vector(
                io.BufferedReader(io.BytesIO(b"\x00\x00\x00\x02" + b"\x00" * 8))
            )


class TestReferenceFixtures:
    def test_kmeans_model_loads_and_predicts(self):
        model = read_write.load_stage(os.path.join(FIXTURES, "reference_kmeans_model"))
        from flink_ml_tpu.models.clustering.kmeans import KMeansModel

        assert isinstance(model, KMeansModel)
        np.testing.assert_array_equal(
            model.centroids, [[0.0, 0.0], [10.0, 10.0]]
        )
        np.testing.assert_array_equal(model.weights, [3.0, 2.0])
        assert model.get_k() == 2
        out = model.transform(Table({"features": [[1.0, 1.0], [9.0, 9.0]]}))[0]
        np.testing.assert_array_equal(np.asarray(out.column("prediction")), [0, 1])

    def test_lr_pipelinemodel_loads_and_predicts(self):
        from flink_ml_tpu.pipeline import PipelineModel

        model = PipelineModel.load(
            os.path.join(FIXTURES, "reference_lr_pipelinemodel")
        )
        coeff = np.array([1.5, -2.0, 0.25, 3.0])
        X = np.array([[1.0, 0.0, 0.0, 0.0], [0.0, 1.0, 0.0, 0.0]])
        out = model.transform(Table({"features": X}))[0]
        pred = np.asarray(out.column("prediction"))
        np.testing.assert_array_equal(pred, (X @ coeff >= 0).astype(float))

    def test_missing_model_data_error_is_clear(self, tmp_path):
        """A directory with metadata but no model data fails with a message
        naming both accepted formats, not a bare npz FileNotFoundError
        (VERDICT r3 missing #4)."""
        import json

        stage_dir = tmp_path / "empty_model"
        stage_dir.mkdir()
        (stage_dir / "metadata").write_text(
            json.dumps(
                {
                    "className": "org.apache.flink.ml.clustering.kmeans.KMeansModel",
                    "paramMap": {},
                }
            )
        )
        with pytest.raises(FileNotFoundError, match="npz|reference-format"):
            read_write.load_stage(str(stage_dir))


def test_all_family_fixtures_load():
    """Every committed reference_{family}_model directory (one per
    model-data codec family, scripts/make_reference_fixture.py) must
    resolve its Java class name and decode its binary part file."""
    import glob as _glob

    dirs = sorted(_glob.glob(os.path.join(FIXTURES, "reference_*_model")))
    assert len(dirs) >= 17  # kmeans + 16 codec families
    for d in dirs:
        stage = read_write.load_stage(d)
        assert stage is not None, d


class TestPartFileHandling:
    def test_numeric_part_order(self, tmp_path):
        """part-0-10 must sort after part-0-9 so the LAST record wins."""
        stage = tmp_path / "m"
        for i in range(11):
            javacodec.write_reference_data_file(
                str(stage),
                javacodec.encode_logisticregression_model_data(
                    np.array([float(i)]), model_version=i
                ),
                part=i,
            )
        coeff, version = javacodec.load_reference_logisticregression(str(stage))
        assert version == 10 and coeff[0] == 10.0

    def test_corrupt_part_file_raises(self, tmp_path):
        stage = tmp_path / "m"
        path = javacodec.write_reference_data_file(
            str(stage), javacodec.encode_dense_vector(np.array([1.0, 2.0]))[:-3]
        )
        with pytest.raises(IOError, match="Corrupt"):
            javacodec.load_reference_coefficient(str(stage))
        assert os.path.exists(path)
