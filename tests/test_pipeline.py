"""Pipeline / PipelineModel composition + persistence — mirrors
flink-ml-core PipelineTest and the Python core tests
(pyflink/ml/core/tests/test_pipeline.py)."""

import numpy as np

from flink_ml_tpu import Pipeline, PipelineModel, Table
from flink_ml_tpu.linalg import Vectors
from flink_ml_tpu.models.classification.logisticregression import LogisticRegression
from flink_ml_tpu.models.feature.standardscaler import StandardScaler

FEATURES = [Vectors.dense(float(i), 2.0) for i in range(1, 6)] + [
    Vectors.dense(float(i), 2.0) for i in range(11, 16)
]
LABELS = [0.0] * 5 + [1.0] * 5


def _table():
    return Table({"features": FEATURES, "label": LABELS})


def test_scaler_then_lr_pipeline():
    pipeline = Pipeline(
        [
            StandardScaler().set_input_col("features").set_output_col("scaled"),
            LogisticRegression().set_features_col("scaled").set_max_iter(60),
        ]
    )
    model = pipeline.fit(_table())
    assert isinstance(model, PipelineModel)
    out = model.transform(_table())[0]
    np.testing.assert_array_equal(np.asarray(out.column("prediction")), LABELS)


def test_pipeline_save_load(tmp_path):
    pipeline = Pipeline(
        [
            StandardScaler().set_input_col("features").set_output_col("scaled"),
            LogisticRegression().set_features_col("scaled").set_max_iter(60),
        ]
    )
    model = pipeline.fit(_table())
    path = str(tmp_path / "pm")
    model.save(path)
    loaded = PipelineModel.load(path)
    out = loaded.transform(_table())[0]
    np.testing.assert_array_equal(np.asarray(out.column("prediction")), LABELS)


def test_pipeline_estimator_save_load(tmp_path):
    pipeline = Pipeline(
        [
            StandardScaler().set_input_col("features").set_output_col("scaled"),
            LogisticRegression().set_features_col("scaled").set_max_iter(15),
        ]
    )
    path = str(tmp_path / "p")
    pipeline.save(path)
    loaded = Pipeline.load(path)
    assert len(loaded.stages) == 2
    assert loaded.stages[1].get_max_iter() == 15
    model = loaded.fit(_table())
    out = model.transform(_table())[0]
    assert "prediction" in out


def test_pipeline_of_transformers_is_model_like():
    sc1 = StandardScaler().set_input_col("features").set_output_col("s1")
    model1 = sc1.fit(_table())
    pm = PipelineModel([model1])
    out = pm.transform(_table())[0]
    assert "s1" in out


def test_standard_scaler_values():
    t = Table({"input": [Vectors.dense(-2.5, 9.0, 1.0), Vectors.dense(-5.0, 0.0, 1.0), Vectors.dense(2.0, -3.0, 1.0)]})
    model = StandardScaler().fit(t)
    out = model.transform(t)[0]
    got = np.asarray(out.column("output"))
    # expected values from the reference's StandardScalerTest (std-only default)
    expect_std = np.std([[-2.5, 9, 1], [-5, 0, 1], [2, -3, 1]], axis=0, ddof=1)
    np.testing.assert_allclose(
        got, np.array([[-2.5, 9, 1], [-5, 0, 1], [2, -3, 1]]) / np.where(expect_std > 0, expect_std, 1.0),
        rtol=1e-5,
    )
    model2 = StandardScaler().set_with_mean(True).fit(t)
    out2 = np.asarray(model2.transform(t)[0].column("output"))
    np.testing.assert_allclose(out2.mean(axis=0), 0.0, atol=1e-6)
