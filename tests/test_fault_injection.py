"""Fault-injection matrix — the reference's `FailingMap`
checkpoint-under-failure ITs rebuilt for the TPU runtime
(BoundedAllRoundCheckpointITCase.java:75-168): a fit killed at an
arbitrary chunk/record/batch boundary by `flink_ml_tpu.ckpt.faults`
resumes from its last JobSnapshot and lands on the uninterrupted run's
EXACT final model, across dense SGD, sparse SGD, out-of-core KMeans, and
an online estimator — plus elastic resume: kill on one virtual-device
count, resume on another (1→8 and 8→2), with the snapshot re-sharded
through `ckpt.snapshot.stage_section`.

Elastic bit-identity contract (docs/fault_tolerance.md): arithmetic is
only allclose-comparable ACROSS device counts (reduction orders differ),
so the pinned claim is that an injected kill + elastic resume is
bit-identical to a PLANNED rescale at the same epoch boundary — i.e. the
snapshot transports the job state across meshes losslessly, and the
fault changes nothing the planned handoff would not."""

import numpy as np
import pytest

from flink_ml_tpu import config
from flink_ml_tpu.ckpt import InjectedFault, failing_map, faults
from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS, SPARSE_VARIANTS
from flink_ml_tpu.ops.optimizer import SGD
from flink_ml_tpu.table import Table


def _dense_problem(n=384, d=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ np.linspace(1, -1, d) > 0).astype(np.float32)
    return X, y


def _sgd(ckpt=None, max_iter=12, key="fault", **kw):
    return SGD(
        max_iter=max_iter, global_batch_size=96, tol=0.0,
        checkpoint_dir=ckpt, checkpoint_key=key, **kw,
    )


def _replayable_stream(X, y=None, chunk=60):
    from flink_ml_tpu.table import StreamTable

    batches = []
    for i in range(0, X.shape[0], chunk):
        cols = {"features": X[i : i + chunk]}
        if y is not None:
            cols["label"] = y[i : i + chunk]
        batches.append(Table(cols))
    return StreamTable.from_batches(batches)


# ---------------------------------------------------------------------------
# dense SGD: kill at an arbitrary chunk boundary
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kill_after", [2, 7])
def test_dense_sgd_kill_resume_bit_identical(tmp_path, kill_after):
    X, y = _dense_problem()
    ref = str(tmp_path / "ref")
    expected, _, _ = _sgd(ref).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)

    ckpt = str(tmp_path / "kill")
    with faults.inject("chunk", after=kill_after) as plan:
        with pytest.raises(InjectedFault):
            _sgd(ckpt).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
    assert plan.fired and plan.hits == kill_after

    got, _, epochs = _sgd(ckpt).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
    assert epochs == 12
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


# ---------------------------------------------------------------------------
# sparse SGD (padded-CSR features, no densification)
# ---------------------------------------------------------------------------

def test_sparse_sgd_kill_resume_bit_identical(tmp_path):
    rng = np.random.RandomState(1)
    n, d, nnz = 384, 24, 4
    indices = np.full((n, nnz), -1, np.int32)
    values = np.zeros((n, nnz), np.float32)
    for i in range(n):
        cols = np.sort(rng.choice(d, size=nnz, replace=False))
        indices[i] = cols
        values[i] = rng.rand(nnz)
    dense = np.zeros((n, d), np.float32)
    np.put_along_axis(dense, indices, values, axis=1)
    y = (dense @ (rng.rand(d) - 0.5) > 0).astype(np.float32)
    loss = SPARSE_VARIANTS[BINARY_LOGISTIC_LOSS.name]
    Xs = (indices, values)

    ref = str(tmp_path / "ref")
    expected, _, _ = _sgd(ref).optimize(np.zeros(d), Xs, y, None, loss)

    ckpt = str(tmp_path / "kill")
    with faults.inject("chunk", after=5):
        with pytest.raises(InjectedFault):
            _sgd(ckpt).optimize(np.zeros(d), Xs, y, None, loss)
    got, _, epochs = _sgd(ckpt).optimize(np.zeros(d), Xs, y, None, loss)
    assert epochs == 12
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


# ---------------------------------------------------------------------------
# out-of-core stream SGD: record- and epoch-boundary kills
# ---------------------------------------------------------------------------

def test_stream_sgd_failing_map_record_kill_then_rerun(tmp_path):
    """FailingMap on the input stream itself: the kill lands at a record
    boundary DURING ingest (before training, so before any snapshot); the
    rerun over the intact stream must match the uninterrupted fit."""
    X, y = _dense_problem(n=480)

    def chunks():
        return iter([(X[i : i + 120], y[i : i + 120], None) for i in range(0, 480, 120)])

    expected, _, _, _ = _sgd(max_iter=8).optimize_stream(None, chunks(), BINARY_LOGISTIC_LOSS)

    ckpt = str(tmp_path / "stream")
    with pytest.raises(InjectedFault):
        _sgd(ckpt, max_iter=8).optimize_stream(
            None, failing_map(chunks(), after_records=300), BINARY_LOGISTIC_LOSS
        )
    got, _, _, _ = _sgd(ckpt, max_iter=8).optimize_stream(
        None, chunks(), BINARY_LOGISTIC_LOSS
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_stream_sgd_epoch_kill_resume_bit_identical(tmp_path):
    X, y = _dense_problem(n=480)

    def chunks():
        return iter([(X[i : i + 120], y[i : i + 120], None) for i in range(0, 480, 120)])

    expected, _, _, _ = _sgd(max_iter=10).optimize_stream(None, chunks(), BINARY_LOGISTIC_LOSS)

    ckpt = str(tmp_path / "stream")
    with faults.inject("epoch", after=4):
        with pytest.raises(InjectedFault):
            _sgd(ckpt, max_iter=10).optimize_stream(None, chunks(), BINARY_LOGISTIC_LOSS)
    got, _, epochs, _ = _sgd(ckpt, max_iter=10).optimize_stream(
        None, chunks(), BINARY_LOGISTIC_LOSS
    )
    assert epochs == 10
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


# ---------------------------------------------------------------------------
# KMeans out-of-core (StreamTable) fit
# ---------------------------------------------------------------------------

def test_kmeans_out_of_core_kill_resume_bit_identical(tmp_path):
    from flink_ml_tpu.models.clustering.kmeans import KMeans

    rng = np.random.RandomState(7)
    X = np.concatenate([rng.randn(200, 4) + 3.0, rng.randn(200, 4) - 3.0])
    rng.shuffle(X)

    def fit():
        return (
            KMeans().set_k(3).set_seed(11).set_max_iter(6)
            .fit(_replayable_stream(X, chunk=80))
        )

    full = fit()

    ckpt = str(tmp_path / "km")
    with config.iteration_checkpointing(ckpt):
        with faults.inject("epoch", after=3):
            with pytest.raises(InjectedFault):
                fit()
        resumed = fit()
    np.testing.assert_array_equal(resumed.centroids, full.centroids)
    np.testing.assert_array_equal(resumed.weights, full.weights)


# ---------------------------------------------------------------------------
# online estimator (unbounded loop): kill between global batches
# ---------------------------------------------------------------------------

def test_online_lr_batch_kill_resume_bit_identical(tmp_path):
    from flink_ml_tpu.linalg import DenseVector
    from flink_ml_tpu.models.classification.onlinelogisticregression import (
        OnlineLogisticRegression,
    )

    X, y = _dense_problem(n=600, seed=2)
    init = Table({"coefficient": [DenseVector(np.zeros(8))]})

    def est():
        return (
            OnlineLogisticRegression()
            .set_global_batch_size(100)
            .set_reg(0.1)
            .set_elastic_net(0.5)
            .set_initial_model_data(init)
        )

    full = est().fit(_replayable_stream(X, y))
    full.process_updates()
    assert full.model_version == 6

    ckpt = str(tmp_path / "online")
    with config.iteration_checkpointing(ckpt):
        part = est().fit(_replayable_stream(X, y))
        with faults.inject("batch", after=3):
            with pytest.raises(InjectedFault):
                part.process_updates()
        # the kill landed after batch 3's snapshot but before its publish
        assert part.model_version == 2
        res = est().fit(_replayable_stream(X, y))
        res.process_updates()
    assert res.model_version == 6
    np.testing.assert_array_equal(res.coefficient, full.coefficient)


# ---------------------------------------------------------------------------
# elastic resume: different virtual-device counts (1→8, 8→2)
# ---------------------------------------------------------------------------

def _mesh(n):
    import jax

    from flink_ml_tpu.parallel import mesh as mesh_lib

    return mesh_lib.create_mesh(("data",), devices=jax.devices()[:n])


def _fit_on(mesh_devices, ckpt, max_iter, X, y):
    from flink_ml_tpu.parallel import mesh as mesh_lib

    with mesh_lib.use_mesh(_mesh(mesh_devices)):
        return _sgd(ckpt, max_iter=max_iter, key="elastic").optimize(
            np.zeros(X.shape[1]), X, y, None, BINARY_LOGISTIC_LOSS
        )


@pytest.mark.parametrize("from_dev,to_dev", [(1, 8), (8, 2)])
def test_elastic_kill_resume_across_device_counts(tmp_path, from_dev, to_dev):
    from flink_ml_tpu.ckpt import load_job_snapshot

    import jax.numpy as jnp

    X, y = _dense_problem(n=384, seed=4)
    kill_epoch, max_iter = 6, 12

    # planned rescale: run to the boundary on mesh A (clean stop); the
    # preempted job is the same fit killed mid-flight at the same boundary
    planned = str(tmp_path / "planned")
    _fit_on(from_dev, planned, kill_epoch, X, y)
    killed = str(tmp_path / "killed")
    with faults.inject("chunk", after=kill_epoch):
        with pytest.raises(InjectedFault):
            _fit_on(from_dev, killed, max_iter, X, y)

    # the two directories hold the same cut: snapshot leaves bit-identical
    template = (jnp.zeros(8), jnp.zeros(8), jnp.asarray(0.0), jnp.asarray(0))
    s_planned = load_job_snapshot(planned, "elastic", templates={"model": template})
    s_killed = load_job_snapshot(killed, "elastic", templates={"model": template})
    assert s_planned.epoch == s_killed.epoch == kill_epoch
    for a, b in zip(s_planned.sections["model"], s_killed.sections["model"]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # resume both on mesh B (the elastic re-shard)
    planned_coeff, _, planned_epochs = _fit_on(to_dev, planned, max_iter, X, y)
    killed_coeff, _, killed_epochs = _fit_on(to_dev, killed, max_iter, X, y)
    assert planned_epochs == killed_epochs == max_iter
    # THE elastic contract: kill + re-sharded resume == planned rescale
    np.testing.assert_array_equal(np.asarray(killed_coeff), np.asarray(planned_coeff))

    # numeric sanity vs a single-mesh uninterrupted run (allclose only:
    # reduction order differs across device counts)
    single = str(tmp_path / "single")
    single_coeff, _, _ = _fit_on(from_dev, single, max_iter, X, y)
    np.testing.assert_allclose(
        np.asarray(killed_coeff), np.asarray(single_coeff), rtol=3e-5, atol=3e-6
    )
