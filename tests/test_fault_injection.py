"""Fault-injection matrix — the reference's `FailingMap`
checkpoint-under-failure ITs rebuilt for the TPU runtime
(BoundedAllRoundCheckpointITCase.java:75-168): a fit killed at an
arbitrary chunk/record/batch boundary by `flink_ml_tpu.ckpt.faults`
resumes from its last JobSnapshot and lands on the uninterrupted run's
EXACT final model, across dense SGD, sparse SGD, out-of-core KMeans, and
an online estimator — plus elastic resume: kill on one virtual-device
count, resume on another (1→8 and 8→2), with the snapshot re-sharded
through `ckpt.snapshot.stage_section`.

Elastic bit-identity contract (docs/fault_tolerance.md): arithmetic is
only allclose-comparable ACROSS device counts (reduction orders differ),
so the pinned claim is that an injected kill + elastic resume is
bit-identical to a PLANNED rescale at the same epoch boundary — i.e. the
snapshot transports the job state across meshes losslessly, and the
fault changes nothing the planned handoff would not."""

import warnings

import numpy as np
import pytest

from flink_ml_tpu import config
from flink_ml_tpu.ckpt import InjectedFault, failing_map, faults
from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS, SPARSE_VARIANTS
from flink_ml_tpu.ops.optimizer import SGD
from flink_ml_tpu.table import Table


def _dense_problem(n=384, d=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ np.linspace(1, -1, d) > 0).astype(np.float32)
    return X, y


def _sgd(ckpt=None, max_iter=12, key="fault", **kw):
    return SGD(
        max_iter=max_iter, global_batch_size=96, tol=0.0,
        checkpoint_dir=ckpt, checkpoint_key=key, **kw,
    )


def _replayable_stream(X, y=None, chunk=60):
    from flink_ml_tpu.table import StreamTable

    batches = []
    for i in range(0, X.shape[0], chunk):
        cols = {"features": X[i : i + chunk]}
        if y is not None:
            cols["label"] = y[i : i + chunk]
        batches.append(Table(cols))
    return StreamTable.from_batches(batches)


# ---------------------------------------------------------------------------
# dense SGD: kill at an arbitrary chunk boundary
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kill_after", [2, 7])
def test_dense_sgd_kill_resume_bit_identical(tmp_path, kill_after):
    X, y = _dense_problem()
    ref = str(tmp_path / "ref")
    expected, _, _ = _sgd(ref).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)

    ckpt = str(tmp_path / "kill")
    with faults.inject("chunk", after=kill_after) as plan:
        with pytest.raises(InjectedFault):
            _sgd(ckpt).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
    assert plan.fired and plan.hits == kill_after

    got, _, epochs = _sgd(ckpt).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
    assert epochs == 12
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


# ---------------------------------------------------------------------------
# sparse SGD (padded-CSR features, no densification)
# ---------------------------------------------------------------------------

def test_sparse_sgd_kill_resume_bit_identical(tmp_path):
    rng = np.random.RandomState(1)
    n, d, nnz = 384, 24, 4
    indices = np.full((n, nnz), -1, np.int32)
    values = np.zeros((n, nnz), np.float32)
    for i in range(n):
        cols = np.sort(rng.choice(d, size=nnz, replace=False))
        indices[i] = cols
        values[i] = rng.rand(nnz)
    dense = np.zeros((n, d), np.float32)
    np.put_along_axis(dense, indices, values, axis=1)
    y = (dense @ (rng.rand(d) - 0.5) > 0).astype(np.float32)
    loss = SPARSE_VARIANTS[BINARY_LOGISTIC_LOSS.name]
    Xs = (indices, values)

    ref = str(tmp_path / "ref")
    expected, _, _ = _sgd(ref).optimize(np.zeros(d), Xs, y, None, loss)

    ckpt = str(tmp_path / "kill")
    with faults.inject("chunk", after=5):
        with pytest.raises(InjectedFault):
            _sgd(ckpt).optimize(np.zeros(d), Xs, y, None, loss)
    got, _, epochs = _sgd(ckpt).optimize(np.zeros(d), Xs, y, None, loss)
    assert epochs == 12
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


# ---------------------------------------------------------------------------
# out-of-core stream SGD: record- and epoch-boundary kills
# ---------------------------------------------------------------------------

def test_stream_sgd_failing_map_record_kill_then_rerun(tmp_path):
    """FailingMap on the input stream itself: the kill lands at a record
    boundary DURING ingest (before training, so before any snapshot); the
    rerun over the intact stream must match the uninterrupted fit."""
    X, y = _dense_problem(n=480)

    def chunks():
        return iter([(X[i : i + 120], y[i : i + 120], None) for i in range(0, 480, 120)])

    expected, _, _, _ = _sgd(max_iter=8).optimize_stream(None, chunks(), BINARY_LOGISTIC_LOSS)

    ckpt = str(tmp_path / "stream")
    with pytest.raises(InjectedFault):
        _sgd(ckpt, max_iter=8).optimize_stream(
            None, failing_map(chunks(), after_records=300), BINARY_LOGISTIC_LOSS
        )
    got, _, _, _ = _sgd(ckpt, max_iter=8).optimize_stream(
        None, chunks(), BINARY_LOGISTIC_LOSS
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_stream_sgd_epoch_kill_resume_bit_identical(tmp_path):
    X, y = _dense_problem(n=480)

    def chunks():
        return iter([(X[i : i + 120], y[i : i + 120], None) for i in range(0, 480, 120)])

    expected, _, _, _ = _sgd(max_iter=10).optimize_stream(None, chunks(), BINARY_LOGISTIC_LOSS)

    ckpt = str(tmp_path / "stream")
    with faults.inject("epoch", after=4):
        with pytest.raises(InjectedFault):
            _sgd(ckpt, max_iter=10).optimize_stream(None, chunks(), BINARY_LOGISTIC_LOSS)
    got, _, epochs, _ = _sgd(ckpt, max_iter=10).optimize_stream(
        None, chunks(), BINARY_LOGISTIC_LOSS
    )
    assert epochs == 10
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


# ---------------------------------------------------------------------------
# KMeans out-of-core (StreamTable) fit
# ---------------------------------------------------------------------------

def test_kmeans_out_of_core_kill_resume_bit_identical(tmp_path):
    from flink_ml_tpu.models.clustering.kmeans import KMeans

    rng = np.random.RandomState(7)
    X = np.concatenate([rng.randn(200, 4) + 3.0, rng.randn(200, 4) - 3.0])
    rng.shuffle(X)

    def fit():
        return (
            KMeans().set_k(3).set_seed(11).set_max_iter(6)
            .fit(_replayable_stream(X, chunk=80))
        )

    full = fit()

    ckpt = str(tmp_path / "km")
    with config.iteration_checkpointing(ckpt):
        with faults.inject("epoch", after=3):
            with pytest.raises(InjectedFault):
                fit()
        resumed = fit()
    np.testing.assert_array_equal(resumed.centroids, full.centroids)
    np.testing.assert_array_equal(resumed.weights, full.weights)


# ---------------------------------------------------------------------------
# online estimator (unbounded loop): kill between global batches
# ---------------------------------------------------------------------------

def test_online_lr_batch_kill_resume_bit_identical(tmp_path):
    from flink_ml_tpu.linalg import DenseVector
    from flink_ml_tpu.models.classification.onlinelogisticregression import (
        OnlineLogisticRegression,
    )

    X, y = _dense_problem(n=600, seed=2)
    init = Table({"coefficient": [DenseVector(np.zeros(8))]})

    def est():
        return (
            OnlineLogisticRegression()
            .set_global_batch_size(100)
            .set_reg(0.1)
            .set_elastic_net(0.5)
            .set_initial_model_data(init)
        )

    full = est().fit(_replayable_stream(X, y))
    full.process_updates()
    assert full.model_version == 6

    ckpt = str(tmp_path / "online")
    with config.iteration_checkpointing(ckpt):
        part = est().fit(_replayable_stream(X, y))
        with faults.inject("batch", after=3):
            with pytest.raises(InjectedFault):
                part.process_updates()
        # the kill landed after batch 3's snapshot but before its publish
        assert part.model_version == 2
        res = est().fit(_replayable_stream(X, y))
        res.process_updates()
    assert res.model_version == 6
    np.testing.assert_array_equal(res.coefficient, full.coefficient)


# ---------------------------------------------------------------------------
# model lifecycle: kill mid-publish (after persist, before the swap)
# ---------------------------------------------------------------------------

def test_lifecycle_kill_during_promote_resume_republishes_same_version(tmp_path):
    """The `lifecycle.swap` fault site sits between the promotion's
    JobSnapshot write and the pointer swap. A trainer killed in that
    window never published — the serving model keeps the old version —
    but the snapshot's `publishedVersion` meta makes the RESUMED job
    re-publish the validated version instead of regressing to 0."""
    from flink_ml_tpu.lifecycle import ModelLifecycle
    from flink_ml_tpu.models.classification.onlinelogisticregression import (
        OnlineLogisticRegressionModel,
    )

    def fresh_model():
        m = OnlineLogisticRegressionModel()
        m.publish_model_arrays((np.zeros(6),), 0)
        return m

    ckpt = str(tmp_path / "lifecycle")
    model = fresh_model()
    lc = ModelLifecycle(model, checkpoint_dir=ckpt, job_key="tws-kill")
    lc.promote((np.full(6, 0.5),))  # v1, published + persisted
    lc.record_serve_ok()
    killed = np.full(6, 0.75)
    with faults.inject("lifecycle.swap", after=1):
        with pytest.raises(InjectedFault):
            lc.promote((killed,))  # v2: persisted, swap never happened
    assert model.model_version == 1, "a mid-publish kill must not tear the swap"

    # "restarted" job: fresh model from initial data, same checkpoint dir
    resumed = fresh_model()
    lc2 = ModelLifecycle(resumed, checkpoint_dir=ckpt, job_key="tws-kill")
    assert resumed.model_version == 2, "resume must re-publish the persisted version"
    np.testing.assert_array_equal(resumed.coefficient, killed)
    assert lc2.last_good == 1
    assert lc2.promote((np.full(6, 1.0),)).version_id == 3


# ---------------------------------------------------------------------------
# elastic resume: different virtual-device counts (1→8, 8→2)
# ---------------------------------------------------------------------------

def _mesh(n):
    import jax

    from flink_ml_tpu.parallel import mesh as mesh_lib

    return mesh_lib.create_mesh(("data",), devices=jax.devices()[:n])


def _fit_on(mesh_devices, ckpt, max_iter, X, y):
    from flink_ml_tpu.parallel import mesh as mesh_lib

    with mesh_lib.use_mesh(_mesh(mesh_devices)):
        return _sgd(ckpt, max_iter=max_iter, key="elastic").optimize(
            np.zeros(X.shape[1]), X, y, None, BINARY_LOGISTIC_LOSS
        )


@pytest.mark.parametrize("from_dev,to_dev", [(1, 8), (8, 2)])
def test_elastic_kill_resume_across_device_counts(tmp_path, from_dev, to_dev):
    from flink_ml_tpu.ckpt import load_job_snapshot

    import jax.numpy as jnp

    X, y = _dense_problem(n=384, seed=4)
    kill_epoch, max_iter = 6, 12

    # planned rescale: run to the boundary on mesh A (clean stop); the
    # preempted job is the same fit killed mid-flight at the same boundary
    planned = str(tmp_path / "planned")
    _fit_on(from_dev, planned, kill_epoch, X, y)
    killed = str(tmp_path / "killed")
    with faults.inject("chunk", after=kill_epoch):
        with pytest.raises(InjectedFault):
            _fit_on(from_dev, killed, max_iter, X, y)

    # the two directories hold the same cut: snapshot leaves bit-identical
    template = (jnp.zeros(8), jnp.zeros(8), jnp.asarray(0.0), jnp.asarray(0))
    s_planned = load_job_snapshot(planned, "elastic", templates={"model": template})
    s_killed = load_job_snapshot(killed, "elastic", templates={"model": template})
    assert s_planned.epoch == s_killed.epoch == kill_epoch
    for a, b in zip(s_planned.sections["model"], s_killed.sections["model"]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # resume both on mesh B (the elastic re-shard)
    planned_coeff, _, planned_epochs = _fit_on(to_dev, planned, max_iter, X, y)
    killed_coeff, _, killed_epochs = _fit_on(to_dev, killed, max_iter, X, y)
    assert planned_epochs == killed_epochs == max_iter
    # THE elastic contract: kill + re-sharded resume == planned rescale
    np.testing.assert_array_equal(np.asarray(killed_coeff), np.asarray(planned_coeff))

    # numeric sanity vs a single-mesh uninterrupted run (allclose only:
    # reduction order differs across device counts)
    single = str(tmp_path / "single")
    single_coeff, _, _ = _fit_on(from_dev, single, max_iter, X, y)
    np.testing.assert_allclose(
        np.asarray(killed_coeff), np.asarray(single_coeff), rtol=3e-5, atol=3e-6
    )


# ---------------------------------------------------------------------------
# flaky (transient) snapshot I/O: the retry paths under injection
# (flow.with_retries + ckpt.faults.flaky — docs/flow_control.md)
# ---------------------------------------------------------------------------

def _save_snap(path, epoch, scale=1.0, key="flaky"):
    from flink_ml_tpu.ckpt import snapshot as snap

    return snap.save_job_snapshot(
        str(path), key,
        {"model": (np.full(4, scale, np.float64), np.arange(4, dtype=np.float32))},
        epoch=epoch,
    )


def _load_snap(path, key="flaky"):
    from flink_ml_tpu.ckpt import snapshot as snap

    return snap.load_job_snapshot(
        str(path), key,
        templates={"model": (np.zeros(4), np.zeros(4, np.float32))},
    )


def test_flaky_snapshot_read_retried_to_success(tmp_path):
    """A restore that hits a transiently-failing read retries through the
    budget and still returns the snapshot."""
    from flink_ml_tpu.utils import metrics

    _save_snap(tmp_path, epoch=5)
    before = metrics.get_counter("flow.retry.snapshot.read", 0)
    with config.transient_retry_mode(3):
        with faults.flaky("snapshot.read", times=2) as plan:
            got = _load_snap(tmp_path)
    assert plan.failures == 2
    assert got is not None and got.epoch == 5
    np.testing.assert_array_equal(got.sections["model"][0], np.full(4, 1.0))
    assert metrics.get_counter("flow.retry.snapshot.read", 0) == before + 2


def test_flaky_snapshot_read_budget_exhausted_reraises_original(tmp_path):
    """An exhausted retry budget re-raises the ORIGINAL TransientFault —
    not a wrapper — with the attempt count attached as evidence."""
    from flink_ml_tpu.ckpt.faults import TransientFault

    _save_snap(tmp_path, epoch=3)
    with config.transient_retry_mode(2):
        with faults.flaky("snapshot.read", times=10):
            with pytest.raises(TransientFault) as ei:
                _load_snap(tmp_path)
    assert ei.value.site == "snapshot.read"  # the original error object
    assert ei.value.retry_attempts == 3  # 1 try + 2 retries


def test_flaky_snapshot_write_retried_then_readable(tmp_path):
    from flink_ml_tpu.ckpt.faults import TransientFault

    with config.transient_retry_mode(3):
        with faults.flaky("snapshot.write", times=2) as plan:
            _save_snap(tmp_path, epoch=7, scale=2.5)
    assert plan.failures == 2
    got = _load_snap(tmp_path)
    assert got.epoch == 7
    np.testing.assert_array_equal(got.sections["model"][0], np.full(4, 2.5))
    # budget exhausted: the original fault surfaces
    with config.transient_retry_mode(1):
        with faults.flaky("snapshot.write", times=5):
            with pytest.raises(TransientFault) as ei:
                _save_snap(tmp_path, epoch=8)
    assert ei.value.retry_attempts == 2


def test_midwrite_kill_then_flaky_reads_still_restore_previous(tmp_path):
    """The composed failure: a crash mid-checkpoint (torn write — temp
    file written, commit rename never ran) followed by transiently-failing
    reads on restart. The previous snapshot must still restore, through
    the retries."""
    _save_snap(tmp_path, epoch=4, scale=1.0)
    with faults.inject("snapshot.write", after=1):
        with pytest.raises(InjectedFault):
            _save_snap(tmp_path, epoch=9, scale=9.0)  # dies before commit
    with config.transient_retry_mode(3):
        with faults.flaky("snapshot.read", times=2) as plan:
            got = _load_snap(tmp_path)
    assert plan.failures == 2
    assert got is not None and got.epoch == 4, "torn write must not be visible"
    np.testing.assert_array_equal(got.sections["model"][0], np.full(4, 1.0))


def test_injected_write_kill_not_retried(tmp_path):
    """InjectedFault models a crash: the snapshot-write retry wrapper must
    let it through on the FIRST hit, whatever the budget."""
    with config.transient_retry_mode(10):
        with faults.inject("snapshot.write", after=1) as plan:
            with pytest.raises(InjectedFault):
                _save_snap(tmp_path, epoch=1)
    assert plan.hits == 1  # one attempt: the kill was not swallowed/retried


def test_flaky_datacache_read_inside_stream_fit_bit_identical(tmp_path):
    """Transient spill-read faults under the retry budget are invisible
    to an out-of-core fit's result; with the budget at 0 the same fault
    is fatal (the pre-flow behavior)."""
    from flink_ml_tpu.ckpt.faults import TransientFault

    X, y = _dense_problem(n=480, seed=6)

    def chunks():
        return iter(
            [(X[i : i + 120], y[i : i + 120], None) for i in range(0, 480, 120)]
        )

    clean, _, _, _ = _sgd(max_iter=6).optimize_stream(
        None, chunks(), BINARY_LOGISTIC_LOSS
    )
    with config.transient_retry_mode(4):
        with faults.flaky("datacache.read", times=3) as plan:
            got, _, _, _ = _sgd(max_iter=6).optimize_stream(
                None, chunks(), BINARY_LOGISTIC_LOSS
            )
    assert plan.failures == 3
    np.testing.assert_array_equal(np.asarray(got), np.asarray(clean))
    with config.transient_retry_mode(0):
        with faults.flaky("datacache.read", times=1):
            with pytest.raises(TransientFault):
                _sgd(max_iter=6).optimize_stream(
                    None, chunks(), BINARY_LOGISTIC_LOSS
                )


# ---------------------------------------------------------------------------
# whole-fit resident programs x checkpointing (config.whole_fit)
# ---------------------------------------------------------------------------

def _whole_fit_sgd(ckpt, max_iter, interval, key="wf"):
    return SGD(
        max_iter=max_iter, global_batch_size=96, tol=0.0,
        checkpoint_dir=ckpt, checkpoint_key=key, checkpoint_interval=interval,
    )


def test_whole_fit_kill_after_end_snapshot_resumes_bit_identical(tmp_path):
    """Whole-fit + checkpoint_job_key: a fit-end-only cadence stays on the
    resident path and snapshots AFTER its single packed readback — a kill
    at the (one) chunk tick lands after the snapshot commit, and the
    resumed run restores the completed carry and reproduces the
    uninterrupted result bit for bit."""
    from flink_ml_tpu.utils import metrics

    X, y = _dense_problem()
    ref = str(tmp_path / "ref")
    expected, _, _ = _whole_fit_sgd(ref, 12, 12).optimize(
        np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS
    )

    ckpt = str(tmp_path / "kill")
    before = metrics.snapshot()
    with faults.inject("chunk", after=1) as plan:
        with pytest.raises(InjectedFault):
            _whole_fit_sgd(ckpt, 12, 12).optimize(
                np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS
            )
    assert plan.fired
    delta = metrics.snapshot_delta(before, metrics.snapshot())["counters"]
    assert delta.get("dispatch.whole_fit.sgd", 0) == 1  # resident path taken

    got, _, epochs = _whole_fit_sgd(ckpt, 12, 12).optimize(
        np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS
    )
    assert epochs == 12
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_whole_fit_resume_extends_max_iter_bit_identical(tmp_path):
    """The canonical resume pattern on the resident path: train to 6 with
    a fit-end snapshot, resume with maxIter=12 — the second whole-fit
    program starts from the restored carry and lands on the
    uninterrupted 12-epoch run's exact result."""
    X, y = _dense_problem()
    ref = str(tmp_path / "ref")
    expected, _, _ = _whole_fit_sgd(ref, 12, 12).optimize(
        np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS
    )

    ckpt = str(tmp_path / "resume")
    _whole_fit_sgd(ckpt, 6, 6).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
    got, _, epochs = _whole_fit_sgd(ckpt, 12, 12).optimize(
        np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS
    )
    assert epochs == 12
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_whole_fit_mid_fit_cadence_falls_back_and_preserves_kill_resume(tmp_path):
    """A mid-fit checkpoint cadence must NOT go resident: the fallback is
    visible in obs (`dispatch.whole_fit_fallback.checkpoint_interval`) and
    the chunked path's kill->resume bit-identity (PR 6) is preserved
    unchanged under whole_fit auto."""
    from flink_ml_tpu.utils import metrics

    X, y = _dense_problem()
    ref = str(tmp_path / "ref")
    expected, _, _ = _sgd(ref).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)

    ckpt = str(tmp_path / "kill")
    before = metrics.snapshot()
    with faults.inject("chunk", after=3) as plan:
        with pytest.raises(InjectedFault):
            _sgd(ckpt).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
    assert plan.fired
    delta = metrics.snapshot_delta(before, metrics.snapshot())["counters"]
    assert delta.get("dispatch.whole_fit_fallback.checkpoint_interval", 0) == 1
    assert delta.get("dispatch.whole_fit.sgd", 0) == 0

    got, _, epochs = _sgd(ckpt).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
    assert epochs == 12
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_whole_fit_stream_end_snapshot_resume(tmp_path):
    """Stream whole-fit + fit-end cadence: the snapshot written after the
    single readback restores into a longer rerun bit-identically (the
    cacheCursor meta keeps the epoch->segment mapping)."""
    X, y = _dense_problem(n=480)

    def chunks():
        return iter(
            [(X[i : i + 120], y[i : i + 120], None) for i in range(0, 480, 120)]
        )

    expected, _, _, _ = _sgd(max_iter=12).optimize_stream(
        None, chunks(), BINARY_LOGISTIC_LOSS
    )

    ckpt = str(tmp_path / "stream_wf")
    first = SGD(
        max_iter=6, global_batch_size=96, tol=0.0,
        checkpoint_dir=ckpt, checkpoint_key="swf", checkpoint_interval=6,
    ).optimize_stream(None, chunks(), BINARY_LOGISTIC_LOSS)
    assert first[3]["wholeFit"] is True
    got = SGD(
        max_iter=12, global_batch_size=96, tol=0.0,
        checkpoint_dir=ckpt, checkpoint_key="swf", checkpoint_interval=12,
    ).optimize_stream(None, chunks(), BINARY_LOGISTIC_LOSS)
    assert got[2] == 12
    np.testing.assert_array_equal(np.asarray(got[0]), np.asarray(expected))


# ---------------------------------------------------------------------------
# multi-host sharded snapshots: the host-failure chaos matrix
# (ckpt/coordinator.py — per-host shard writes + two-phase commit manifest;
# hosts are simulated mesh groups, config.snapshot_hosts)
# ---------------------------------------------------------------------------

def _dense_ref(tmp_path):
    X, y = _dense_problem()
    ref = str(tmp_path / "ref")
    expected, _, _ = _sgd(ref).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
    return X, y, expected


def test_mh_dense_kill_mid_shard_write_resume_bit_identical(tmp_path):
    """Host 2 dies mid-shard-write (temp written, rename never ran): the
    cut is torn, the job crashes, and the resumed run restores the last
    COMMITTED cut and lands on the uninterrupted run's exact model."""
    X, y, expected = _dense_ref(tmp_path)
    ckpt = str(tmp_path / "kill")
    with config.snapshot_hosts_mode(4):
        with faults.inject("snapshot.shard.write", after=4 * 4 + 3) as plan:
            with pytest.raises(InjectedFault):
                _sgd(ckpt).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
        assert plan.fired  # died on cut 5's host-2 write
        got, _, epochs = _sgd(ckpt).optimize(
            np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS
        )
    assert epochs == 12
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_mh_dense_kill_mid_manifest_commit_resume_bit_identical(tmp_path):
    """The two-phase-commit torn window: every shard of the cut landed,
    the manifest rename never ran — restore must treat the cut as never
    having happened."""
    from flink_ml_tpu.ckpt import coordinator

    X, y, expected = _dense_ref(tmp_path)
    ckpt = str(tmp_path / "kill")
    with config.snapshot_hosts_mode(4):
        with faults.inject("snapshot.commit", after=5) as plan:
            with pytest.raises(InjectedFault):
                _sgd(ckpt).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
        assert plan.fired
        # the torn cut left shards but no manifest
        assert 5 not in coordinator.committed_cuts(ckpt, "fault")
        got, _, epochs = _sgd(ckpt).optimize(
            np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS
        )
    assert epochs == 12
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_mh_dense_straggler_abort_then_kill_resume_bit_identical(tmp_path):
    """A straggler host aborts ONE cut (training continues, warned); a
    later kill resumes from the last cut that DID commit — the aborted
    boundary is simply re-covered by recomputation."""
    X, y, expected = _dense_ref(tmp_path)
    ckpt = str(tmp_path / "kill")
    with config.snapshot_hosts_mode(4), config.transient_retry_mode(2):
        # 3 transient failures = 1 attempt + 2 retries: exactly one save
        # (cut 3) exhausts its budget and aborts; later saves are healthy
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            with faults.flaky("snapshot.shard.write", times=3):
                with faults.inject("chunk", after=4):
                    with pytest.raises(InjectedFault):
                        _sgd(ckpt).optimize(
                            np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS
                        )
        assert any("aborted" in str(w.message) for w in caught)
        got, _, epochs = _sgd(ckpt).optimize(
            np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS
        )
    assert epochs == 12
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_mh_dense_digest_mismatch_falls_back_resume_bit_identical(tmp_path):
    """Bit rot on the newest committed cut: restore refuses it (digest
    mismatch), falls back to the previous cut, and the resume still lands
    on the uninterrupted model — more recomputation, zero corruption."""
    from flink_ml_tpu.ckpt import coordinator

    X, y, expected = _dense_ref(tmp_path)
    ckpt = str(tmp_path / "kill")
    with config.snapshot_hosts_mode(4):
        with faults.inject("chunk", after=7):
            with pytest.raises(InjectedFault):
                _sgd(ckpt).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
        newest = coordinator.committed_cuts(ckpt, "fault")[-1]
        with open(coordinator.shard_file(ckpt, "fault", newest, 0), "r+b") as f:
            f.seek(40)
            f.write(b"\xde\xad\xbe\xef")
        with pytest.warns(UserWarning, match="mismatch"):
            got, _, epochs = _sgd(ckpt).optimize(
                np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS
            )
    assert epochs == 12
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_mh_dense_flaky_reads_on_resume_bit_identical(tmp_path):
    """Transient manifest/shard read faults during the restore retry
    through the budget and the resume is indistinguishable from a clean
    one."""
    X, y, expected = _dense_ref(tmp_path)
    ckpt = str(tmp_path / "kill")
    with config.snapshot_hosts_mode(4):
        with faults.inject("chunk", after=6):
            with pytest.raises(InjectedFault):
                _sgd(ckpt).optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
        with config.transient_retry_mode(3):
            with faults.flaky("snapshot.shard.read", times=2) as plan:
                got, _, epochs = _sgd(ckpt).optimize(
                    np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS
                )
    assert plan.failures == 2
    assert epochs == 12
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_mh_sparse_sgd_kill_mid_commit_resume_bit_identical(tmp_path):
    rng = np.random.RandomState(1)
    n, d, nnz = 384, 24, 4
    indices = np.full((n, nnz), -1, np.int32)
    values = np.zeros((n, nnz), np.float32)
    for i in range(n):
        cols = np.sort(rng.choice(d, size=nnz, replace=False))
        indices[i] = cols
        values[i] = rng.rand(nnz)
    dense = np.zeros((n, d), np.float32)
    np.put_along_axis(dense, indices, values, axis=1)
    y = (dense @ (rng.rand(d) - 0.5) > 0).astype(np.float32)
    loss = SPARSE_VARIANTS[BINARY_LOGISTIC_LOSS.name]
    Xs = (indices, values)

    ref = str(tmp_path / "ref")
    expected, _, _ = _sgd(ref).optimize(np.zeros(d), Xs, y, None, loss)

    ckpt = str(tmp_path / "kill")
    with config.snapshot_hosts_mode(4):
        with faults.inject("snapshot.commit", after=5):
            with pytest.raises(InjectedFault):
                _sgd(ckpt).optimize(np.zeros(d), Xs, y, None, loss)
        got, _, epochs = _sgd(ckpt).optimize(np.zeros(d), Xs, y, None, loss)
    assert epochs == 12
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_mh_stream_sgd_kill_resumes_without_reingest_bit_identical(tmp_path):
    """Stream SGD with cache-CONTENTS shards: the kill-resumed fit is fed
    an EMPTY stream — everything (model carry AND the packed data
    segments) comes back from the sharded snapshot, bit-identically."""
    from flink_ml_tpu.utils import metrics

    X, y = _dense_problem(n=480)

    def chunks():
        return iter(
            [(X[i : i + 120], y[i : i + 120], None) for i in range(0, 480, 120)]
        )

    expected, _, _, _ = _sgd(max_iter=10).optimize_stream(
        None, chunks(), BINARY_LOGISTIC_LOSS
    )

    ckpt = str(tmp_path / "stream")
    with config.snapshot_hosts_mode(4):
        with faults.inject("snapshot.shard.write", after=4 * 3 + 2):
            with pytest.raises(InjectedFault):
                _sgd(ckpt, max_iter=10).optimize_stream(
                    None, chunks(), BINARY_LOGISTIC_LOSS
                )
        before = metrics.get_counter("devicecache.contents.restored", 0)
        got, _, epochs, _ = _sgd(ckpt, max_iter=10).optimize_stream(
            None, iter([]), BINARY_LOGISTIC_LOSS  # resume never re-ingests
        )
        assert metrics.get_counter("devicecache.contents.restored", 0) > before
    assert epochs == 10
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_mh_stream_sgd_model_cut_bit_rot_falls_back_bit_identical(tmp_path):
    """Bit rot on the newest model cut of a stream fit: fallback to the
    previous cut, whose manifest still references the SAME stable cache
    shards — data survives, resume is bit-identical (and still needs no
    re-ingest)."""
    from flink_ml_tpu.ckpt import coordinator

    X, y = _dense_problem(n=480, seed=3)

    def chunks():
        return iter(
            [(X[i : i + 120], y[i : i + 120], None) for i in range(0, 480, 120)]
        )

    expected, _, _, _ = _sgd(max_iter=10).optimize_stream(
        None, chunks(), BINARY_LOGISTIC_LOSS
    )
    ckpt = str(tmp_path / "stream")
    with config.snapshot_hosts_mode(4):
        with faults.inject("epoch", after=6):
            with pytest.raises(InjectedFault):
                _sgd(ckpt, max_iter=10).optimize_stream(
                    None, chunks(), BINARY_LOGISTIC_LOSS
                )
        newest = coordinator.committed_cuts(ckpt, "fault")[-1]
        with open(coordinator.shard_file(ckpt, "fault", newest, 1), "r+b") as f:
            f.seek(40)
            f.write(b"\xde\xad\xbe\xef")
        with pytest.warns(UserWarning, match="mismatch"):
            got, _, epochs, _ = _sgd(ckpt, max_iter=10).optimize_stream(
                None, iter([]), BINARY_LOGISTIC_LOSS
            )
    assert epochs == 10
    np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))


def test_mh_stream_sgd_corrupt_stable_cache_shard_fails_loudly(tmp_path):
    """Bit rot on the DATA itself (a stable cache shard every manifest
    references) leaves nothing trustworthy: the restore must refuse
    loudly instead of silently training on corrupt bytes."""
    from flink_ml_tpu.ckpt import SnapshotIntegrityError, coordinator

    X, y = _dense_problem(n=480, seed=5)

    def chunks():
        return iter(
            [(X[i : i + 120], y[i : i + 120], None) for i in range(0, 480, 120)]
        )

    ckpt = str(tmp_path / "stream")
    with config.snapshot_hosts_mode(4):
        with faults.inject("epoch", after=4):
            with pytest.raises(InjectedFault):
                _sgd(ckpt, max_iter=10).optimize_stream(
                    None, chunks(), BINARY_LOGISTIC_LOSS
                )
        with open(
            coordinator.stable_shard_file(ckpt, "fault", "cache", 0), "r+b"
        ) as f:
            f.seek(40)
            f.write(b"\xde\xad\xbe\xef")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            with pytest.raises(SnapshotIntegrityError):
                _sgd(ckpt, max_iter=10).optimize_stream(
                    None, iter([]), BINARY_LOGISTIC_LOSS
                )


def test_mh_kmeans_out_of_core_kill_mid_commit_resume_bit_identical(tmp_path):
    from flink_ml_tpu.models.clustering.kmeans import KMeans

    rng = np.random.RandomState(7)
    X = np.concatenate([rng.randn(200, 4) + 3.0, rng.randn(200, 4) - 3.0])
    rng.shuffle(X)

    def fit():
        return (
            KMeans().set_k(3).set_seed(11).set_max_iter(6)
            .fit(_replayable_stream(X, chunk=80))
        )

    full = fit()

    ckpt = str(tmp_path / "km")
    with config.iteration_checkpointing(ckpt), config.snapshot_hosts_mode(4):
        with faults.inject("snapshot.commit", after=3):
            with pytest.raises(InjectedFault):
                fit()
        resumed = fit()
    np.testing.assert_array_equal(resumed.centroids, full.centroids)
    np.testing.assert_array_equal(resumed.weights, full.weights)


# ---------------------------------------------------------------------------
# fleet x fault matrix (fleet.py): a kill mid-fleet-fit resumes from the
# ONE fleet-axis snapshot cut and every member lands on the unkilled
# fleet's exact coefficients — across chunk-boundary and snapshot-commit
# kill sites, in both the replicated and the fleet-axis-sharded regime
# ---------------------------------------------------------------------------

def _fleet_lr_makers():
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression,
    )

    def lr(max_iter, rate):
        return (
            LogisticRegression().set_max_iter(max_iter).set_tol(0.0)
            .set_learning_rate(rate).set_global_batch_size(96)
        )

    return [
        lambda: lr(10, 0.1),
        lambda: lr(10, 0.02),
        lambda: lr(5, 0.2),  # converged member frozen across the kill
    ]


@pytest.mark.parametrize("kill_after", [1, 2])
def test_fleet_kill_at_chunk_boundary_resume_bit_identical(tmp_path, kill_after):
    from flink_ml_tpu.fleet import FitFleet

    X, y = _dense_problem(seed=31)
    table = Table({"features": X, "label": y})
    makers = _fleet_lr_makers()
    expected = FitFleet([m() for m in makers]).fit(table)

    with config.iteration_checkpointing(str(tmp_path / "fleet"), interval=3):
        with faults.inject("chunk", after=kill_after) as plan:
            with pytest.raises(InjectedFault):
                FitFleet([m() for m in makers]).fit(table)
        assert plan.fired
        resumed = FitFleet([m() for m in makers]).fit(table)
    for got, want in zip(resumed, expected):
        np.testing.assert_array_equal(
            np.asarray(got.coefficient), np.asarray(want.coefficient)
        )


def test_fleet_kill_mid_snapshot_commit_resume_bit_identical(tmp_path):
    """The kill lands INSIDE the multi-host manifest commit of a fleet
    cut: the torn cut must be invisible on resume (restart from the last
    durable cut)."""
    from flink_ml_tpu.fleet import FitFleet

    X, y = _dense_problem(seed=32)
    table = Table({"features": X, "label": y})
    makers = _fleet_lr_makers()
    expected = FitFleet([m() for m in makers]).fit(table)

    with config.iteration_checkpointing(
        str(tmp_path / "commit"), interval=3
    ), config.snapshot_hosts_mode(4):
        with faults.inject("snapshot.commit", after=2) as plan:
            with pytest.raises(InjectedFault):
                FitFleet([m() for m in makers]).fit(table)
        assert plan.fired
        resumed = FitFleet([m() for m in makers]).fit(table)
    for got, want in zip(resumed, expected):
        np.testing.assert_array_equal(
            np.asarray(got.coefficient), np.asarray(want.coefficient)
        )


def test_fleet_sharded_kill_resume_bit_identical(tmp_path):
    """Fleet-axis-sharded regime: the snapshot cut is sharded over the
    fleet axis (section tag `data`); a kill + resume must restore every
    device's members losslessly — all 8 bit-identical to the unkilled
    sharded fleet."""
    from flink_ml_tpu.fleet import FitFleet
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression,
    )

    X, y = _dense_problem(seed=33)
    table = Table({"features": X, "label": y})

    def makers():
        return [
            LogisticRegression().set_max_iter(8).set_tol(0.0)
            .set_learning_rate(0.02 * (i + 1)).set_global_batch_size(96)
            for i in range(8)
        ]

    expected = FitFleet(makers(), shard_fleet_axis=True).fit(table)

    with config.iteration_checkpointing(str(tmp_path / "shard"), interval=3):
        with faults.inject("chunk", after=1) as plan:
            with pytest.raises(InjectedFault):
                FitFleet(makers(), shard_fleet_axis=True).fit(table)
        assert plan.fired
        resumed = FitFleet(makers(), shard_fleet_axis=True).fit(table)
    for got, want in zip(resumed, expected):
        np.testing.assert_array_equal(
            np.asarray(got.coefficient), np.asarray(want.coefficient)
        )
