"""Input-pipeline battery: the device epoch cache, the shared prefetcher,
and batch-shape bucketing must be invisible to the math.

The contract (docs/performance.md §8, same shape as the dispatch-pipeline
and collective-chunking guarantees): caching/prefetching/bucketing change
WHEN bytes move and how many programs compile, never what is computed.
Cached epochs are bit-identical to the eager re-upload path for any HBM
budget; prefetched batches arrive in order with no drops whatever the
producer speed; bucketed staging pins the compile count to the bucket
count. The acceptance metric rides along: a bounded stream fit within
budget moves ZERO H2D bytes on epochs >= 1 (`h2d.bytes` counter).
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from flink_ml_tpu import config
from flink_ml_tpu.data.devicecache import CachedEpochLoader, DeviceEpochCache
from flink_ml_tpu.models.clustering.kmeans import KMeans
from flink_ml_tpu.obs import tracing
from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
from flink_ml_tpu.ops.optimizer import SGD
from flink_ml_tpu.parallel import prefetch
from flink_ml_tpu.table import SparseBatch, StreamTable, Table
from flink_ml_tpu.utils import metrics

# "tiny" fits roughly one staged batch (a 104x8 f32 pack is ~3.3KB), so a
# multi-batch stream is forced to evict and re-stage every epoch
BUDGETS = {"disabled": 0, "tiny": 4_000, "unbounded": None}


@pytest.fixture(autouse=True)
def _per_epoch_input_pipeline():
    """This battery probes the per-epoch replay pipeline (cache hit/miss
    traffic, prefetch overlap, per-batch staging); the whole-fit resident
    path bypasses it by design — stacked upload, zero cache lookups — so
    the probes run against the chunked reference mode. Whole-fit's own
    parity/traffic pins live in tests/test_dispatch_pipeline.py."""
    with config.whole_fit_mode("off"):
        yield


@pytest.fixture
def cache_budget():
    """Restore the process-wide budget/bucketing knobs after each test."""
    prev = (config.device_cache_bytes, config.input_bucketing)
    yield
    config.device_cache_bytes, config.input_bucketing = prev


def _counters(fn):
    before = metrics.snapshot()
    out = fn()
    return out, metrics.snapshot_delta(before, metrics.snapshot())["counters"]


def _dense_chunks(n=512, d=6, chunk=96, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d).astype(np.float32) > 0).astype(np.float32)
    return [(X[i : i + chunk], y[i : i + chunk], None) for i in range(0, n, chunk)]


def _fit_stream(chunks, budget, max_iter=12):
    with config.device_cache_budget(budget):
        sgd = SGD(max_iter=max_iter, global_batch_size=100, tol=0.0)
        return sgd.optimize_stream(None, iter(chunks), BINARY_LOGISTIC_LOSS)


class TestCachedEpochParity:
    """Budget 0 IS the eager re-upload path; every other budget must
    reproduce it bit for bit — eviction/re-staging included."""

    def test_stream_sgd_all_budgets(self, mesh8, cache_budget):
        chunks = _dense_chunks()
        base, counters = _counters(lambda: _fit_stream(chunks, 0))
        assert base[2] == 12
        # the disabled-budget reference really re-uploads: one staged
        # transfer per epoch (plus none cached)
        assert counters.get("devicecache.hit", 0) == 0
        for name, budget in BUDGETS.items():
            if budget == 0:
                continue
            got, cc = _counters(lambda: _fit_stream(chunks, budget))
            np.testing.assert_array_equal(got[0], base[0], err_msg=f"budget={name}")
            assert got[1] == base[1] and got[2] == base[2], f"budget={name}"
        # tiny budget (~1 batch of ~43KB) forces evictions; unbounded doesn't
        _, tiny_c = _counters(lambda: _fit_stream(chunks, BUDGETS["tiny"]))
        assert tiny_c.get("devicecache.evictBytes", 0) > 0
        _, unb_c = _counters(lambda: _fit_stream(chunks, None))
        assert unb_c.get("devicecache.evictBytes", 0) == 0
        assert unb_c.get("devicecache.hit", 0) > 0

    def test_stream_kmeans_all_budgets(self, mesh8, cache_budget):
        rng = np.random.default_rng(3)
        X = rng.standard_normal((300, 5)).astype(np.float32)
        batches = [Table({"features": X[i : i + 64]}) for i in range(0, 300, 64)]

        def fit(budget):
            with config.device_cache_budget(budget):
                return (
                    KMeans().set_k(3).set_seed(7).set_max_iter(6)
                ).fit(StreamTable.from_batches(batches))

        base = fit(0)
        for name, budget in BUDGETS.items():
            if budget == 0:
                continue
            got = fit(budget)
            np.testing.assert_array_equal(
                got.centroids, base.centroids, err_msg=f"budget={name}"
            )
            np.testing.assert_array_equal(got.weights, base.weights)

    def test_sparse_batches_roundtrip_cache(self, cache_budget):
        """Sparse (indices, values) pytrees ride the cache/stager tier
        bit-exactly across budgets — including re-staging after a spill."""
        from flink_ml_tpu.table import register_device_pytrees

        register_device_pytrees()
        rng = np.random.default_rng(5)
        host = [
            SparseBatch(
                16,
                rng.integers(-1, 16, (32, 4)).astype(np.int32),
                rng.standard_normal((32, 4)),
            )
            for _ in range(3)
        ]
        for budget in (0, host[0].indices.nbytes + 1, None):
            cache = DeviceEpochCache(budget)
            loader = CachedEpochLoader(
                lambda k: prefetch.stage_to_device(host[k]), cache=cache
            )
            for _ in range(3):  # three epochs, any budget: same bits out
                for k, sb in enumerate(loader.epoch(range(3))):
                    np.testing.assert_array_equal(
                        np.asarray(sb.indices), host[k].indices
                    )
                    np.testing.assert_array_equal(
                        np.asarray(sb.values),
                        np.asarray(jnp.asarray(host[k].values)),
                    )

    def test_stream_sgd_tol_stop_identical(self, mesh8, cache_budget):
        """A mid-run tol stop lands on the same epoch and coefficients
        whether batches come from HBM or re-upload."""
        chunks = _dense_chunks(seed=8)
        probe = _fit_stream(chunks, 0, max_iter=6)
        tol = float(probe[1])

        def fit(budget):
            with config.device_cache_budget(budget):
                return SGD(max_iter=30, global_batch_size=100, tol=tol).optimize_stream(
                    None, iter(chunks), BINARY_LOGISTIC_LOSS
                )

        base = fit(0)
        assert 0 < base[2] < 30, "tol must fire mid-run for this test to bite"
        for budget in (BUDGETS["tiny"], None):
            got = fit(budget)
            np.testing.assert_array_equal(got[0], base[0])
            assert got[2] == base[2]


class TestZeroUploadEpochs:
    """The acceptance criterion: within budget, epochs >= 1 of a bounded
    stream fit move ZERO host→device bytes."""

    def test_epochs_after_first_are_upload_free(self, mesh8, cache_budget):
        chunks = _dense_chunks(n=400, chunk=100)  # 4 exact batches
        _, one_pass = _counters(lambda: _fit_stream(chunks, None, max_iter=4))
        _, three_pass = _counters(lambda: _fit_stream(chunks, None, max_iter=12))
        assert one_pass.get("h2d.bytes", 0) > 0
        assert three_pass.get("h2d.bytes") == one_pass.get("h2d.bytes"), (
            "epochs >= 1 must re-read device-resident shards, not re-upload"
        )
        # the disabled path really pays per-epoch uploads (the counter bites)
        _, eager = _counters(lambda: _fit_stream(chunks, 0, max_iter=12))
        assert eager.get("h2d.bytes", 0) == 3 * one_pass.get("h2d.bytes")

    def test_single_batch_stream_uploads_once_even_disabled(self, mesh8, cache_budget):
        """nb == 1 keeps the historical upload-once behavior at ANY budget
        (the consecutive-key reuse path in CachedEpochLoader)."""
        chunks = _dense_chunks(n=100, chunk=100)
        _, c = _counters(lambda: _fit_stream(chunks, 0, max_iter=10))
        assert c.get("h2d.count", 0) == 1


class TestPrefetcher:
    def test_ordering_and_no_drop_under_slow_producer(self):
        """A producer 10x slower than the consumer: every item arrives,
        in input order."""
        def slow_stage(i):
            time.sleep(0.01)
            return i * i

        got = list(prefetch.Prefetcher(slow_stage, depth=3).iterate(range(40)))
        assert got == [i * i for i in range(40)]

    def test_runs_ahead_of_consumer(self):
        """The worker stages ahead: total wall for N slow stages under a
        slow consumer is ~max(producer, consumer), not the sum."""
        def stage(i):
            time.sleep(0.02)
            return i

        t0 = time.perf_counter()
        for _ in prefetch.Prefetcher(stage, depth=2).iterate(range(10)):
            time.sleep(0.02)  # consumer work the staging should hide under
        wall = time.perf_counter() - t0
        assert wall < 0.34, f"prefetch appears serialized: {wall:.3f}s"

    def test_early_close_stops_worker(self):
        staged = []

        def stage(i):
            staged.append(i)
            return i

        it = prefetch.Prefetcher(stage, depth=2).iterate(range(100))
        assert next(it) == 0
        it.close()  # tol-stop analogue: abandon mid-stream
        time.sleep(0.05)
        assert len(staged) <= 4  # bounded speculation, no runaway staging

    def test_depth_gauge_published(self):
        list(prefetch.Prefetcher(lambda i: i, depth=3).iterate(range(2)))
        assert metrics.get_gauge("prefetch.depth") == 3

    def test_raising_stage_surfaces_to_consumer(self):
        """The worker-error contract: an exception inside the stage
        callable re-raises at the consuming iterator (after the items
        staged before it, in order) instead of silently terminating the
        worker and stalling the consumer forever."""
        def stage(i):
            if i == 3:
                raise RuntimeError("stage died on item 3")
            return i * 10

        got = []
        it = prefetch.Prefetcher(stage, depth=2).iterate(range(10))
        with pytest.raises(RuntimeError, match="stage died on item 3"):
            for x in it:
                got.append(x)
        assert got == [0, 10, 20], "items staged before the failure deliver first"

    def test_raising_source_surfaces_to_consumer(self):
        def items():
            yield 1
            raise OSError("source died")

        with pytest.raises(OSError, match="source died"):
            list(prefetch.Prefetcher(lambda x: x, depth=2).iterate(items()))

    def test_raising_stage_does_not_hang_blocked_consumer(self):
        """Regression for the stall mode: the consumer is already blocked
        in __next__ when the worker dies — the error must wake it."""
        import threading

        def stage(i):
            if i == 0:
                time.sleep(0.05)  # consumer blocks on item 0 first
                raise RuntimeError("died while consumer waits")
            return i

        outcome = {}

        def consume():
            try:
                list(prefetch.Prefetcher(stage, depth=2).iterate(range(5)))
            except RuntimeError as e:
                outcome["error"] = str(e)

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        t.join(timeout=10.0)
        assert not t.is_alive(), "consumer stalled on a dead worker"
        assert outcome["error"] == "died while consumer waits"

    def test_loader_stage_error_surfaces(self):
        """CachedEpochLoader shares the same contract through its pump."""
        def stage(k):
            raise ValueError(f"cannot stage {k}")

        loader = CachedEpochLoader(stage, cache=DeviceEpochCache(0))
        with pytest.raises(ValueError, match="cannot stage 0"):
            list(loader.epoch(range(3)))


class TestDeviceEpochCache:
    def test_lru_eviction_and_counters(self):
        a = jnp.zeros(1000, jnp.float32)  # 4000 bytes
        cache = DeviceEpochCache(9000)
        _, c = _counters(
            lambda: [cache.put(k, a) for k in range(3)] and None
        )
        assert len(cache) == 2  # third insert evicted the LRU entry (key 0)
        assert c.get("devicecache.evictBytes") == 4000
        assert cache.get(0) is None and cache.get(2) is not None
        # a get refreshes LRU order: key 1 survives the next insert
        cache.get(1)
        cache.put(3, a)
        assert cache.get(1) is not None and cache.get(2) is None

    def test_budget_zero_disables(self):
        cache = DeviceEpochCache(0)
        assert not cache.enabled
        assert cache.put("k", jnp.zeros(4)) is False
        assert len(cache) == 0

    def test_oversized_entry_refused_but_usable(self):
        cache = DeviceEpochCache(100)
        arr = jnp.zeros(1000, jnp.float32)
        assert cache.put("big", arr) is False
        np.testing.assert_array_equal(np.asarray(arr), 0)  # caller's ref fine


class TestBucketing:
    def test_stream_sgd_compile_count_pinned_under_jitter(self, mesh8, cache_budget):
        """Micro-batch jitter in the incoming stream must not recompile:
        every ragged chunking of the same rows re-chunks to the same
        b_pad-shaped batches, so a warm engine compiles NOTHING new."""
        tracing.install_jax_hooks()
        rng = np.random.default_rng(11)
        # d=11 keeps these staged shapes unique to this test, so the
        # warm-up fit demonstrably compiles (before > 0 below) and the
        # jittered fits demonstrably don't
        X = rng.standard_normal((500, 11)).astype(np.float32)
        y = (X.sum(axis=1) > 0).astype(np.float32)

        def chunks_of(sizes):
            out, off = [], 0
            for s in sizes:
                out.append((X[off : off + s], y[off : off + s], None))
                off += s
            assert off == 500
            return out

        def fit(sizes):
            return SGD(max_iter=6, global_batch_size=100, tol=0.0).optimize_stream(
                None, iter(chunks_of(sizes)), BINARY_LOGISTIC_LOSS
            )

        fit([100] * 5)  # warm every kernel at the staged batch shapes
        before = metrics.get_counter("jit.compiles")
        fit([97, 103, 60, 140, 100])  # jittered producer, same 100-row batches
        fit([250, 250])
        assert metrics.get_counter("jit.compiles") == before, (
            "micro-batch jitter recompiled the stream-SGD kernels"
        )
        assert before > 0, "jit.compiles hook not counting — vacuous pin"

    def test_kmeans_stream_bucketed_vs_exact(self, mesh8, cache_budget):
        """Bucketed staging (repeat-last-row pad at weight 0) is exact in
        exact arithmetic — weight-0 rows contribute +0.0 everywhere — but
        growing the reduction shape reassociates the f32 segment sums
        (like changing the shard padding), so vs the exact-shape path the
        comparison is float-tight, not bitwise. Bitwise identity holds
        where the acceptance demands it: cached vs eager re-upload AT the
        bucketed shapes (test_stream_kmeans_all_budgets runs with default
        bucketing on). Weights (pure counts) stay exact."""
        rng = np.random.default_rng(13)
        X = rng.standard_normal((290, 4)).astype(np.float32)
        # deliberately ragged stream: 64, 64, 64, 64, 34
        batches = [Table({"features": X[i : i + 64]}) for i in range(0, 290, 64)]

        def fit(bucketing):
            with config.input_bucketing_mode(bucketing):
                return (
                    KMeans().set_k(3).set_seed(5).set_max_iter(5)
                ).fit(StreamTable.from_batches(batches))

        exact = fit(False)
        bucketed = fit(True)
        np.testing.assert_allclose(
            bucketed.centroids, exact.centroids, rtol=1e-6, atol=1e-7
        )
        np.testing.assert_array_equal(bucketed.weights, exact.weights)

    def test_online_kmeans_transform_bucketed_sliced_back(self, cache_budget):
        from flink_ml_tpu.models.clustering.onlinekmeans import OnlineKMeansModel

        model = OnlineKMeansModel()
        model.centroids = np.asarray([[0.0, 0.0], [10.0, 10.0]])
        model.weights = np.asarray([1.0, 1.0])
        rng = np.random.default_rng(17)
        for n in (5, 13, 64, 100):  # jittery serving shapes
            X = rng.standard_normal((n, 2))
            X[0] = [9.0, 9.0]
            (out,) = model.transform(Table({"features": X}))
            pred = out.column("prediction")
            assert pred.shape == (n,)  # pad sliced back off
            with config.input_bucketing_mode(False):
                (ref,) = model.transform(Table({"features": X}))
            np.testing.assert_array_equal(pred, ref.column("prediction"))

    def test_bucket_helpers_shared_with_serving(self):
        """serving.py consumes the ONE shared implementation."""
        from flink_ml_tpu import serving

        assert serving._next_bucket is prefetch.next_bucket
        assert serving._pad_rows is prefetch.pad_rows
        assert serving._slice_rows is prefetch.slice_rows
        assert prefetch.next_bucket(9) == 16
        assert prefetch.next_bucket(100, buckets=[64, 128]) == 128
        assert prefetch.next_bucket(200, buckets=[64, 128]) == 200


class TestStagerAccounting:
    def test_host_upload_counted_device_repl_not(self):
        a = np.zeros((10, 4), np.float32)
        _, c = _counters(lambda: prefetch.stage_to_device(a))
        assert c.get("h2d.bytes") == a.nbytes and c.get("h2d.count") == 1
        dev = jnp.zeros((10, 4))
        _, c2 = _counters(lambda: prefetch.stage_to_device(dev))
        assert c2.get("h2d.bytes", 0) == 0  # device->device: no host bytes
