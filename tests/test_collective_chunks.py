"""Parity + accounting suite for the comm layer (parallel/collectives.py,
parallel/overlap.py): the chunked/ring/sparse reductions and the
overlap-scheduled training loops must be BIT-identical to the eager dense
path — chunking and scheduling change when bytes move, never the result
(the contract docs/performance.md §7 documents, the analogue of the
reference's 32KB AllReduceImpl chunks reassembling to the exact sum)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import PartitionSpec as P

from flink_ml_tpu import config
from flink_ml_tpu.obs import tracing
from flink_ml_tpu.parallel import collectives as coll
from flink_ml_tpu.parallel import mesh as mesh_lib
from flink_ml_tpu.parallel import overlap
from flink_ml_tpu.utils import metrics


def _mesh(n):
    return mesh_lib.create_mesh(("data",), devices=jax.devices()[:n])


def _tree(v):
    """Mixed pytree: multi-dim leaf + a nested (pair) tuple — exercises
    dtype grouping, flatten/unflatten, and the nested-leaf accounting."""
    return {"a": v[:, :1000].reshape(-1, 10, 100), "b": (v[:, 1000:1003], v[:, 1003:])}


def _flat(tree, rows):
    return np.concatenate(
        [np.asarray(leaf).reshape(rows, -1) for leaf in jax.tree_util.tree_leaves(tree)],
        axis=1,
    )


class TestChunkedParity:
    """all_reduce_sum_chunked == lax.psum, bitwise, for every chunk size,
    ring mode, and shard count."""

    @pytest.mark.parametrize("ndev", [1, 2, 8])
    @pytest.mark.parametrize("chunk_bytes", [1024, 32 * 1024, None])
    @pytest.mark.parametrize("ring", [False, True])
    def test_bit_identical_to_psum(self, ndev, chunk_bytes, ring):
        mesh = _mesh(ndev)
        rng = np.random.default_rng(0)
        # wide dynamic range so any reassociation of the sum would show
        x = (
            rng.standard_normal((ndev, 4096)).astype(np.float32)
            * np.logspace(-6, 6, 4096, dtype=np.float32)
        )

        def run(fn):
            f = coll.shard_map_over(
                mesh, in_specs=P("data", None), out_specs=P("data", None)
            )(fn)
            return jax.jit(f)(x)

        whole = np.asarray(run(lambda v: lax.psum(v, "data")))
        chunked = run(
            lambda v: coll.all_reduce_sum_chunked(
                _tree(v), chunk_bytes=chunk_bytes, ring=ring
            )
        )
        np.testing.assert_array_equal(_flat(chunked, ndev), _flat(_tree(whole), ndev))

    def test_bucket_count_follows_chunk_bytes(self, mesh8):
        """1KB buckets over a 16KB payload really decompose (≥16 buckets),
        and the accounted chunk count reports the decomposition."""
        x = np.ones((8, 4096), np.float32)
        before = metrics.snapshot()
        f = coll.shard_map_over(mesh8, in_specs=P("data", None), out_specs=P("data", None))(
            lambda v: coll.all_reduce_sum_chunked(v, chunk_bytes=1024)
        )
        jax.block_until_ready(jax.jit(f)(x))
        delta = metrics.snapshot_delta(before, metrics.snapshot())
        assert delta["counters"].get("collective.chunked.chunks", 0) >= 16
        assert delta["counters"].get("collective.chunked.bytes", 0) == 4096 * 4

    def test_heterogeneous_dtypes(self, mesh8):
        """f32 + i32 leaves group into per-dtype buckets and still match."""
        xf = np.arange(8 * 64, dtype=np.float32).reshape(8, 64)
        xi = np.arange(8 * 32, dtype=np.int32).reshape(8, 32)
        f = coll.shard_map_over(
            mesh8,
            in_specs=(P("data", None), P("data", None)),
            out_specs=(P("data", None), P("data", None)),
        )(lambda a, b: coll.all_reduce_sum_chunked((a, b), chunk_bytes=128))
        out_f, out_i = jax.jit(f)(xf, xi)
        np.testing.assert_array_equal(np.asarray(out_f), np.tile(xf.sum(0), (8, 1)))
        np.testing.assert_array_equal(np.asarray(out_i), np.tile(xi.sum(0), (8, 1)))


class TestSparseParity:
    """sparse_all_reduce_sum == psum of the densified operand, bitwise,
    including dropped padding indices."""

    @pytest.mark.parametrize("ndev", [1, 2, 8])
    def test_matches_densified_psum(self, ndev):
        mesh = _mesh(ndev)
        dim, m = 512, 64
        rng = np.random.default_rng(1)
        idx = rng.integers(0, dim, size=(ndev, m)).astype(np.int32)
        idx[:, -3:] = -1  # padding entries must drop on both paths
        val = rng.standard_normal((ndev, m)).astype(np.float32)
        in_specs = (P("data", None), P("data", None))

        dense = coll.shard_map_over(mesh, in_specs=in_specs, out_specs=P())(
            lambda i, v: lax.psum(
                jnp.zeros((dim,), jnp.float32).at[i[0]].add(v[0], mode="drop"), "data"
            )
        )
        sparse = coll.shard_map_over(mesh, in_specs=in_specs, out_specs=P())(
            lambda i, v: coll.sparse_all_reduce_sum(i[0], v[0], dim)
        )
        np.testing.assert_array_equal(
            np.asarray(jax.jit(sparse)(idx, val)), np.asarray(jax.jit(dense)(idx, val))
        )

    def test_wire_bytes_scale_with_nnz(self, mesh8):
        """The acceptance shape (dim=1M, nnz=39): traced sparse pair bytes
        must sit ≥10x below the dense-equivalent psum payload."""
        dim, rows, nnz = 1_000_000, 128, 39
        idx = np.zeros((8, rows * nnz), np.int32)
        val = np.zeros((8, rows * nnz), np.float32)
        before = metrics.snapshot()
        f = coll.shard_map_over(
            mesh8, in_specs=(P("data", None), P("data", None)), out_specs=P()
        )(lambda i, v: coll.sparse_all_reduce_sum(i[0], v[0], dim))
        jax.block_until_ready(jax.jit(f)(idx, val))
        delta = metrics.snapshot_delta(before, metrics.snapshot())
        sparse_bytes = delta["counters"]["collective.sparse.bytes"]
        dense_equiv = delta["counters"]["collective.sparse.dense_equiv_bytes"]
        assert sparse_bytes * 10 <= dense_equiv
        assert 0 < metrics.snapshot()["gauges"]["collective.sparse_ratio"] < 1

    def test_threshold_routing(self):
        # sparseWideLR shape: pairs win by far
        assert coll.sparse_reduce_wins(128 * 39, 1_000_000, itemsize=4)
        # dense-ish gradient: pairs would exceed the dense payload
        assert not coll.sparse_reduce_wins(900, 1000, itemsize=4)


class TestOverlapSgdParity:
    """Overlap-scheduled SGD (carry-delayed apply) bit-identical to the
    eager program: coefficients, final loss, stop epoch."""

    def _fit(self, mesh, X, y, loss, d, overlap_on, **kw):
        from flink_ml_tpu.ops.optimizer import SGD

        sgd = SGD(collective_overlap=overlap_on, **kw)
        return sgd.optimize(np.zeros(d, np.float32), X, y, None, loss, mesh=mesh)

    @pytest.mark.parametrize("ndev", [1, 2, 8])
    @pytest.mark.parametrize("loss_name", ["binary_logistic", "least_square"])
    def test_dense(self, ndev, loss_name):
        from flink_ml_tpu.ops import losses

        loss = {
            "binary_logistic": losses.BINARY_LOGISTIC_LOSS,
            "least_square": losses.LEAST_SQUARE_LOSS,
        }[loss_name]
        mesh = _mesh(ndev)
        rng = np.random.RandomState(0)
        X = rng.randn(256, 10).astype(np.float32)
        y = (X @ np.linspace(1, -1, 10) > 0).astype(np.float32)
        kw = dict(max_iter=12, global_batch_size=64, tol=0.0, reg=0.05, elastic_net=0.3)
        with mesh_lib.use_mesh(mesh):
            c0, l0, e0 = self._fit(mesh, X, y, loss, 10, False, **kw)
            c1, l1, e1 = self._fit(mesh, X, y, loss, 10, True, **kw)
        np.testing.assert_array_equal(c0, c1)
        assert (l0, e0) == (l1, e1)

    @pytest.mark.parametrize("ndev", [2, 8])
    def test_tol_early_stop(self, ndev):
        from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS

        mesh = _mesh(ndev)
        rng = np.random.RandomState(3)
        X = rng.randn(256, 10).astype(np.float32)
        y = (X @ np.linspace(1, -1, 10) > 0).astype(np.float32)
        kw = dict(max_iter=50, global_batch_size=64, tol=0.4)
        with mesh_lib.use_mesh(mesh):
            c0, l0, e0 = self._fit(mesh, X, y, BINARY_LOGISTIC_LOSS, 10, False, **kw)
            c1, l1, e1 = self._fit(mesh, X, y, BINARY_LOGISTIC_LOSS, 10, True, **kw)
        assert e0 < 50  # the tol stop actually engaged
        np.testing.assert_array_equal(c0, c1)
        assert (l0, e0) == (l1, e1)

    @pytest.mark.parametrize("ndev", [1, 2, 8])
    def test_sparse(self, ndev):
        """Sparse losses: at 8 shards the per-shard pair bytes beat the
        threshold and the index-value reduction engages; at 1-2 shards the
        gradient densifies onto the chunked path — both bit-identical."""
        from flink_ml_tpu.ops.losses import SPARSE_BINARY_LOGISTIC_LOSS

        mesh = _mesh(ndev)
        dim, n, nnz = 500, 256, 5
        rng = np.random.RandomState(1)
        indices = rng.randint(0, dim, size=(n, nnz)).astype(np.int32)
        indices[::7, -1] = -1  # padded-CSR empty slots
        values = rng.rand(n, nnz).astype(np.float32)
        y = (rng.rand(n) > 0.5).astype(np.float32)
        kw = dict(max_iter=10, global_batch_size=64, tol=0.0)
        with mesh_lib.use_mesh(mesh):
            c0, l0, e0 = self._fit(
                mesh, (indices, values), y, SPARSE_BINARY_LOGISTIC_LOSS, dim, False, **kw
            )
            c1, l1, e1 = self._fit(
                mesh, (indices, values), y, SPARSE_BINARY_LOGISTIC_LOSS, dim, True, **kw
            )
        np.testing.assert_array_equal(c0, c1)
        assert (l0, e0) == (l1, e1)

    def test_sparse_pairs_route_engages(self, mesh8):
        """The trace-time router picks index-value pairs exactly when the
        pair bytes beat the threshold at the current shard count."""
        X_b = (
            np.zeros((4, 64, 5), np.int32),
            np.zeros((4, 64, 5), np.float32),
        )
        assert overlap.sgd_use_sparse_pairs(X_b, 500, mesh8)  # 8 shards: 320B < 1KB
        assert not overlap.sgd_use_sparse_pairs(X_b, 500, _mesh(2))  # 1280B > 1KB
        assert not overlap.sgd_use_sparse_pairs(X_b, 500, _mesh(1))  # nothing to reduce
        assert not overlap.sgd_use_sparse_pairs(np.zeros((4, 64, 5)), 500, mesh8)  # dense


class TestOverlapKMeans:
    def test_lloyd_bit_identical(self):
        from flink_ml_tpu.models.clustering.kmeans import KMeans
        from flink_ml_tpu.table import Table

        rng = np.random.RandomState(0)
        X = np.concatenate([rng.randn(64, 6) + 3, rng.randn(64, 6) - 3]).astype(np.float64)

        def fit():
            return KMeans().set_k(3).set_seed(2).set_max_iter(7).fit(Table({"features": X}))

        m0 = fit()
        with config.collective_overlap_mode(True):
            m1 = fit()
        np.testing.assert_array_equal(m0.centroids, m1.centroids)
        np.testing.assert_array_equal(m0.weights, m1.weights)


class TestHostReduceCompileOnce:
    def test_compiles_once_per_mesh_shape_dtype(self, mesh8):
        """host_all_reduce_sum's jitted reducer is cached per (mesh, shape,
        dtype): repeated same-shape reduces re-enter the same executable
        (the round-5 bug rebuilt the closure per call and recompiled every
        time — ~10ms of XLA work per reduce in the host-driven loops)."""
        tracing.install_jax_hooks()
        shape = (37,)  # unlikely to collide with another test's executable
        partials = [np.full(shape, float(i), np.float32) for i in range(8)]
        out = coll.host_all_reduce_sum(mesh8, partials)  # warm: one compile
        np.testing.assert_array_equal(np.asarray(out), np.full(shape, 28.0))

        before = metrics.get_counter("jit.compiles")
        for _ in range(5):
            coll.host_all_reduce_sum(mesh8, partials)
        assert metrics.get_counter("jit.compiles") == before  # zero recompiles

        key = (mesh8, (8,) + shape, np.dtype(np.float32).str)
        assert key in coll._HOST_REDUCE_CACHE
        # a different shape is a different executable, not a cache hit
        coll.host_all_reduce_sum(mesh8, [p[:5] for p in partials])
        assert (mesh8, (8, 5), np.dtype(np.float32).str) in coll._HOST_REDUCE_CACHE


class TestAccounting:
    def test_payload_bytes_counts_nested_pairs(self):
        """A sparse (indices, values) tuple nested inside a gradient pytree
        contributes BOTH leaves (the round-5 `_account` undercounted these
        to zero: tree_leaves treated the inner tuple as one non-array)."""
        tree = {
            "dense": np.zeros((10,), np.float32),  # 40B
            "sparse": (np.zeros((6,), np.int32), np.zeros((6,), np.float32)),  # 48B
        }
        assert coll.payload_bytes(tree) == 40 + 48
        assert coll.payload_bytes([tree, tree]) == 2 * (40 + 48)

    def test_sparse_ratio_gauge(self):
        before_s = metrics.get_counter("collective.sparse.bytes")
        before_d = metrics.get_counter("collective.sparse.dense_equiv_bytes")
        tracing.account_collective(
            "sparse_allreduce", 100, 1, "data", dense_equiv_bytes=1000
        )
        assert metrics.get_counter("collective.sparse.bytes") == before_s + 100
        assert (
            metrics.get_counter("collective.sparse.dense_equiv_bytes")
            == before_d + 1000
        )
        ratio = metrics.snapshot()["gauges"]["collective.sparse_ratio"]
        assert ratio == (before_s + 100) / (before_d + 1000)
