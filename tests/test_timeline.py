"""Flight recorder (obs/timeline.py) — ring semantics, Chrome export,
dispatch-wall attribution, and the acceptance workload: a traced chunked
LR fit exports a valid >=4-lane Perfetto timeline and the benchmark
runner's `dispatchGapMs` agrees with `wallMs - hostDispatchMs`."""

import json
import threading
import time

import numpy as np
import pytest

from flink_ml_tpu import config
from flink_ml_tpu.obs import timeline, tracing
from flink_ml_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _clean():
    timeline.configure()
    tracing.configure()
    metrics.reset()
    yield
    timeline.configure()
    tracing.configure()
    metrics.reset()
    config.iteration_chunk_size = None


# ---------------------------------------------------------------------------
# ring core
# ---------------------------------------------------------------------------

def test_ring_orders_and_bounds():
    ring = timeline.TimelineRing(16)
    for i in range(40):
        ring.append(("i", "flow", f"e{i}", i, 0, None, None))
    events, truncated = ring.events()
    assert len(events) == 16
    assert truncated == 40 - 16
    # the ring keeps the NEWEST events, in order
    assert [e[2] for e in events] == [f"e{i}" for i in range(24, 40)]


def test_ring_concurrent_writers_lose_nothing():
    """8 threads x 500 events into a large ring: every event lands
    exactly once (the lock-free slot-claim contract)."""
    timeline.configure(ring_size=8192)
    n_threads, per_thread = 8, 500

    def writer(tid):
        for i in range(per_thread):
            timeline.record_instant("flow", f"w{tid}", i=i)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events, truncated = timeline.snapshot_events()
    assert truncated == 0
    assert len(events) == n_threads * per_thread
    by_writer = {}
    for e in events:
        by_writer.setdefault(e["name"], []).append(e["args"]["i"])
    assert all(sorted(v) == list(range(per_thread)) for v in by_writer.values())


def test_drain_resets():
    timeline.configure(ring_size=64)
    timeline.record_instant("flow", "a")
    assert len(timeline.drain()) == 1
    assert timeline.drain() == []
    assert timeline.enabled()  # drain keeps recording


def test_spans_flow_to_timeline_without_trace_sink():
    """Configuring ONLY the timeline still activates span tracing, and
    spans land as begin/end pairs on the thread's host lane."""
    timeline.configure(ring_size=256)
    assert tracing.enabled()
    with tracing.span("outer", kind="fit"):
        with tracing.span("inner"):
            pass
    events, _ = timeline.snapshot_events()
    phases = [(e["ph"], e["name"]) for e in events]
    assert ("B", "outer") in phases and ("E", "outer") in phases
    assert ("B", "inner") in phases and ("E", "inner") in phases
    ends = {e["name"]: e for e in events if e["ph"] == "E"}
    assert ends["outer"]["args"] == {"kind": "fit"}
    assert all(e["lane"].startswith("host:") for e in events)


def test_noop_cost_under_1us():
    """Disabled flight recorder: one module-global load per call (the
    pinned always-on budget, alongside the span no-op test)."""
    assert not timeline.enabled()
    n = 100_000
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            timeline.record_instant("flow", "noop")
        best = min(best, (time.perf_counter() - t0) / n)
    assert best < 1e-6, f"no-op timeline record costs {best * 1e9:.0f}ns/call"


# ---------------------------------------------------------------------------
# chrome export
# ---------------------------------------------------------------------------

def test_chrome_export_schema_and_lanes():
    timeline.configure(ring_size=256)
    timeline.record_begin("host:MainThread", "fit", ref=1)
    timeline.record_complete(timeline.LANE_DISPATCH, "dispatch.chunk", 0, 10_000, start=0, end=4)
    timeline.record_complete(timeline.LANE_READBACK, "readback", 10_000, 2_000, bytes=8)
    timeline.record_instant(timeline.LANE_FLOW, "q.put", depth=1)
    timeline.record_end("host:MainThread", "fit", ref=1)
    doc = timeline.to_chrome()
    json.dumps(doc)  # serializable = loadable
    assert doc["otherData"]["unmatchedDropped"] == 0
    lanes = {
        e["args"]["name"]
        for e in doc["traceEvents"]
        if e.get("name") == "thread_name"
    }
    assert lanes == {"host:MainThread", "dispatch", "readback", "flow"}
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"fit", "dispatch.chunk", "readback"}
    for e in xs:
        assert set(e) >= {"ph", "pid", "tid", "name", "ts", "dur"}
    instants = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert instants and instants[0]["s"] == "t"


def test_chrome_export_drops_unmatched_pairs():
    """Ring truncation breaks B/E pairs; the export drops them with a
    count instead of crashing or emitting a broken trace."""
    timeline.configure(ring_size=256)
    timeline.record_end("host:t", "lostBegin", ref=7)  # B fell off the ring
    timeline.record_begin("host:t", "neverEnded", ref=8)
    timeline.record_begin("host:t", "ok", ref=9)
    timeline.record_end("host:t", "ok", ref=9)
    doc = timeline.to_chrome()
    names = [e["name"] for e in doc["traceEvents"] if e["ph"] == "X"]
    assert names == ["ok"]
    assert doc["otherData"]["unmatchedDropped"] == 2


def test_dump_and_load_roundtrip(tmp_path):
    timeline.configure(ring_size=64)
    timeline.record_complete(timeline.LANE_DISPATCH, "dispatch.chunk", 0, 1000, start=0, end=1)
    timeline.record_instant(timeline.LANE_FLOW, "q.put", depth=2)
    path = str(tmp_path / "events.jsonl")
    assert timeline.dump_jsonl(path) == 2
    loaded = timeline.load_events(path)
    assert [e["name"] for e in loaded] == ["dispatch.chunk", "q.put"]
    # a truncated final line (killed process) is skipped, not fatal
    with open(path, "a") as f:
        f.write('{"ph": "i", "lane": "flow", "na')
    assert len(timeline.load_events(path)) == 2


# ---------------------------------------------------------------------------
# dispatch-wall attribution
# ---------------------------------------------------------------------------

def test_attribution_identity_synthetic():
    """wall = dispatch + device + readback + idle-gap, exactly, with
    overlapping intervals counted once (priority dispatch > readback >
    device)."""
    ms = 1_000_000  # ns per ms
    events = [
        # chunk 0: dispatch [0,2ms), device [2,6ms), readback [6,7ms);
        # next dispatch at 10ms -> idle [7,10) = 3ms
        {"ph": "X", "lane": "dispatch", "name": "dispatch.chunk", "tsUs": 0.0,
         "durUs": 2000.0, "args": {"start": 0, "end": 4}},
        {"ph": "X", "lane": "device", "name": "device.chunk(est)", "tsUs": 2000.0,
         "durUs": 4000.0},
        {"ph": "X", "lane": "readback", "name": "readback", "tsUs": 6000.0,
         "durUs": 1000.0},
        # chunk 1: dispatch [10,11ms), device overlapping dispatch
        # [10,13ms) -> device contributes only [11,13) = 2ms
        {"ph": "X", "lane": "dispatch", "name": "dispatch.chunk", "tsUs": 10000.0,
         "durUs": 1000.0, "args": {"start": 4, "end": 8}},
        {"ph": "X", "lane": "device", "name": "device.chunk(est)", "tsUs": 10000.0,
         "durUs": 3000.0},
    ]
    attr = timeline.dispatch_attribution(events)
    assert attr["gapCount"] == 2
    assert attr["epochs"] == 8
    assert attr["windowMs"] == pytest.approx(13.0)
    assert attr["dispatchMs"] == pytest.approx(3.0)
    assert attr["deviceMs"] == pytest.approx(6.0)
    assert attr["readbackMs"] == pytest.approx(1.0)
    assert attr["idleGapMs"] == pytest.approx(3.0)
    total = sum(attr[k] for k in ("dispatchMs", "deviceMs", "readbackMs", "idleGapMs"))
    assert total == pytest.approx(attr["wallMs"])
    assert attr["perEpoch"]["wallMs"] == pytest.approx(attr["wallMs"] / 8)


def test_attribution_empty_without_dispatch_lane():
    assert timeline.dispatch_attribution([]) == {}
    assert timeline.dispatch_attribution(
        [{"ph": "i", "lane": "flow", "name": "x", "tsUs": 0.0, "durUs": 0.0}]
    ) == {}


# ---------------------------------------------------------------------------
# the acceptance workload: traced chunked LR fit
# ---------------------------------------------------------------------------

def _chunked_lr_fit(tmp_path, max_iter=56, chunk=8):
    from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD

    config.iteration_chunk_size = chunk
    rng = np.random.RandomState(3)
    X = rng.randn(400, 8).astype(np.float32)
    y = (X @ np.linspace(1, -1, 8) > 0).astype(np.float32)
    sgd = SGD(
        max_iter=max_iter,
        global_batch_size=100,
        tol=0.0,
        checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_interval=chunk,
    )
    return sgd.optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)


def test_traced_chunked_fit_exports_four_lanes(tmp_path):
    """ISSUE 12 acceptance: a traced chunked LR fit (maxIter >= 50)
    exports valid Chrome trace JSON with at least the host-dispatch,
    device, readback and flow lanes, and the attribution identity holds
    over the fit's dispatch window."""
    timeline.configure(ring_size=16384)
    _, _, epochs = _chunked_lr_fit(tmp_path)
    assert epochs == 56
    doc = timeline.to_chrome()
    json.dumps(doc)
    lanes = {
        e["args"]["name"]
        for e in doc["traceEvents"]
        if e.get("name") == "thread_name"
    }
    assert {"dispatch", "device", "readback", "flow"} <= lanes
    assert any(lane.startswith("host:") for lane in lanes)
    assert doc["otherData"]["unmatchedDropped"] == 0

    attr = timeline.dispatch_attribution()
    assert attr["gapCount"] == 56 // 8
    assert attr["epochs"] == 56
    parts = sum(attr[k] for k in ("dispatchMs", "deviceMs", "readbackMs", "idleGapMs"))
    assert parts == pytest.approx(attr["wallMs"], rel=1e-6)
    assert attr["dispatchMs"] > 0 and attr["readbackMs"] > 0

    # the dump -> CLI -> Perfetto path works on the same recording
    events_path = str(tmp_path / "events.jsonl")
    timeline.dump_jsonl(events_path)
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "scripts/obs_timeline.py", events_path,
         "-o", str(tmp_path / "t.json"), "--attribution"],
        capture_output=True, text=True, cwd=str(_repo_root()),
    )
    assert out.returncode == 0, out.stderr
    assert "lanes" in out.stdout and "idleGapMs" in out.stdout
    exported = json.load(open(tmp_path / "t.json"))
    assert exported["traceEvents"]


def _repo_root():
    import os

    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_runner_dispatch_gap_consistent_with_wall(mesh8):
    """ISSUE 12 acceptance: the benchmark runner emits dispatchGapMs
    consistent with wallMs - hostDispatchMs within 5% (wall = the work
    phases), plus gapCount/hostDispatchMs as first-class fields, and the
    timeline attribution embeds when the flight recorder is on."""
    from flink_ml_tpu.benchmark.runner import run_benchmark

    timeline.configure(ring_size=32768)
    entry = {
        "stage": {
            "className": "org.apache.flink.ml.classification.logisticregression.LogisticRegression",
            "paramMap": {"maxIter": 50, "globalBatchSize": 512},
        },
        "inputData": {
            "className": "org.apache.flink.ml.benchmark.datagenerator.common.LabeledPointWithWeightGenerator",
            "paramMap": {
                "colNames": [["features", "label", "weight"]],
                "numValues": 1024,
                "vectorDim": 8,
            },
        },
    }
    result = run_benchmark("LR-dispatch-gap", entry)
    wall_ms = (
        result["phaseTimesMs"].get("fit", 0.0)
        + result["phaseTimesMs"].get("transform", 0.0)
    )
    assert result["gapCount"] >= 1
    assert result["hostDispatchMs"] > 0
    expected = wall_ms - result["hostDispatchMs"]
    assert abs(result["dispatchGapMs"] - expected) <= 0.05 * wall_ms + 1e-6
    attr = result["dispatchAttribution"]
    assert attr is not None and attr["gapCount"] >= 1
    assert "chunks" not in attr  # bounded BENCH payload
    json.dumps(result)  # BENCH payload stays serializable
