"""Feature-estimator battery — mirrors the reference tests under
flink-ml-lib/src/test/java/org/apache/flink/ml/feature/ (MinMaxScalerTest,
MaxAbsScalerTest, RobustScalerTest, ImputerTest, StringIndexerTest,
IndexToStringModelTest, OneHotEncoderTest, VectorIndexerTest,
CountVectorizerTest, IDFTest, KBinsDiscretizerTest,
VarianceThresholdSelectorTest, UnivariateFeatureSelectorTest,
MinHashLSHTest, SQLTransformerTest)."""

import numpy as np
import pytest

from flink_ml_tpu.linalg import Vectors
from flink_ml_tpu.table import Table
from flink_ml_tpu.models.feature.countvectorizer import CountVectorizer, CountVectorizerModel
from flink_ml_tpu.models.feature.idf import IDF, IDFModel
from flink_ml_tpu.models.feature.imputer import Imputer, ImputerModel
from flink_ml_tpu.models.feature.kbinsdiscretizer import KBinsDiscretizer, KBinsDiscretizerModel
from flink_ml_tpu.models.feature.lsh import MinHashLSH, MinHashLSHModel
from flink_ml_tpu.models.feature.maxabsscaler import MaxAbsScaler, MaxAbsScalerModel
from flink_ml_tpu.models.feature.minmaxscaler import MinMaxScaler, MinMaxScalerModel
from flink_ml_tpu.models.feature.onehotencoder import OneHotEncoder, OneHotEncoderModel
from flink_ml_tpu.models.feature.robustscaler import RobustScaler, RobustScalerModel
from flink_ml_tpu.models.feature.sqltransformer import SQLTransformer
from flink_ml_tpu.models.feature.stringindexer import (
    IndexToStringModel,
    StringIndexer,
    StringIndexerModel,
)
from flink_ml_tpu.models.feature.univariatefeatureselector import UnivariateFeatureSelector
from flink_ml_tpu.models.feature.variancethresholdselector import VarianceThresholdSelector
from flink_ml_tpu.models.feature.vectorindexer import VectorIndexer, VectorIndexerModel


class TestMinMaxScaler:
    def test_fit_transform(self):
        train = Table({"input": [Vectors.dense(0, 3), Vectors.dense(2.1, 0), Vectors.dense(4.1, 5.1)]})
        model = MinMaxScaler().fit(train)
        out = model.transform(Table({"input": [Vectors.dense(4.1, 5.1), Vectors.dense(0, 3)]}))[0]
        got = np.asarray(out.column("output"))
        np.testing.assert_allclose(got[0], [1.0, 1.0], atol=1e-7)
        np.testing.assert_allclose(got[1], [0.0, 3 / 5.1], atol=1e-7)

    def test_output_range(self):
        train = Table({"input": [Vectors.dense(0.0), Vectors.dense(10.0)]})
        model = MinMaxScaler().set_min(-1.0).set_max(1.0).fit(train)
        got = np.asarray(model.transform(Table({"input": [Vectors.dense(5.0)]}))[0].column("output"))
        np.testing.assert_allclose(got, [[0.0]], atol=1e-7)

    def test_constant_feature_maps_to_midpoint(self):
        train = Table({"input": [Vectors.dense(3.0), Vectors.dense(3.0)]})
        model = MinMaxScaler().fit(train)
        got = np.asarray(model.transform(train)[0].column("output"))
        np.testing.assert_allclose(got, [[0.5], [0.5]])

    def test_save_load(self, tmp_path):
        train = Table({"input": [Vectors.dense(0.0, 1.0), Vectors.dense(2.0, 3.0)]})
        model = MinMaxScaler().fit(train)
        model.save(str(tmp_path / "mms"))
        loaded = MinMaxScalerModel.load(str(tmp_path / "mms"))
        np.testing.assert_allclose(loaded.min_vector, model.min_vector)
        other = MinMaxScalerModel().set_model_data(model.get_model_data()[0])
        np.testing.assert_allclose(other.max_vector, model.max_vector)


class TestMaxAbsScaler:
    def test_fit_transform(self):
        train = Table({"input": [Vectors.dense(2, -8), Vectors.dense(-4, 4)]})
        model = MaxAbsScaler().fit(train)
        got = np.asarray(model.transform(train)[0].column("output"))
        np.testing.assert_allclose(got, [[0.5, -1.0], [-1.0, 0.5]])

    def test_save_load(self, tmp_path):
        train = Table({"input": [Vectors.dense(2, -8)]})
        model = MaxAbsScaler().fit(train)
        model.save(str(tmp_path / "mas"))
        loaded = MaxAbsScalerModel.load(str(tmp_path / "mas"))
        np.testing.assert_allclose(loaded.max_abs, [2, 8])


class TestRobustScaler:
    def test_fit_transform(self):
        X = np.arange(1, 9, dtype=np.float64)[:, None]  # 1..8, q25=2.75, q75=6.25
        model = RobustScaler().fit(Table({"input": X}))
        out = np.asarray(model.transform(Table({"input": X}))[0].column("output"))
        np.testing.assert_allclose(out[:, 0], X[:, 0] / (model.ranges[0]), atol=1e-7)

    def test_centering(self):
        X = np.arange(1, 10, dtype=np.float64)[:, None]
        model = RobustScaler().set_with_centering(True).fit(Table({"input": X}))
        out = np.asarray(model.transform(Table({"input": X}))[0].column("output"))
        assert abs(out[4, 0]) < 1e-7  # median row maps to 0

    def test_save_load(self, tmp_path):
        X = np.arange(8, dtype=np.float64)[:, None]
        model = RobustScaler().fit(Table({"input": X}))
        model.save(str(tmp_path / "rs"))
        loaded = RobustScalerModel.load(str(tmp_path / "rs"))
        np.testing.assert_allclose(loaded.medians, model.medians)


class TestImputer:
    def _table(self):
        return Table(
            {
                "f1": [1.0, 4.0, float("nan"), 7.0],
                "f2": [2.0, float("nan"), 6.0, 10.0],
            }
        )

    def _op(self):
        return Imputer().set_input_cols("f1", "f2").set_output_cols("o1", "o2")

    def test_mean(self):
        model = self._op().fit(self._table())
        out = model.transform(self._table())[0]
        np.testing.assert_allclose(np.asarray(out.column("o1")), [1, 4, 4, 7])
        np.testing.assert_allclose(np.asarray(out.column("o2")), [2, 6, 6, 10])

    def test_median(self):
        model = self._op().set_strategy("median").fit(self._table())
        out = model.transform(self._table())[0]
        np.testing.assert_allclose(np.asarray(out.column("o1")), [1, 4, 4, 7])

    def test_most_frequent(self):
        t = Table({"f1": [1.0, 1.0, 2.0, float("nan")], "f2": [3.0, 3.0, 3.0, 4.0]})
        model = Imputer().set_input_cols("f1", "f2").set_output_cols("o1", "o2").set_strategy("most_frequent").fit(t)
        out = model.transform(t)[0]
        np.testing.assert_allclose(np.asarray(out.column("o1")), [1, 1, 2, 1])

    def test_custom_missing_value(self):
        t = Table({"f1": [1.0, -1.0, 3.0]})
        model = (
            Imputer().set_input_cols("f1").set_output_cols("o1").set_missing_value(-1.0)
        ).fit(t)
        out = model.transform(t)[0]
        np.testing.assert_allclose(np.asarray(out.column("o1")), [1, 2, 3])

    def test_save_load(self, tmp_path):
        model = self._op().fit(self._table())
        model.save(str(tmp_path / "imp"))
        loaded = ImputerModel.load(str(tmp_path / "imp"))
        assert loaded.surrogates == model.surrogates


class TestStringIndexer:
    def _table(self):
        return Table({"f1": ["a", "b", "b", "c"], "f2": [2.0, 1.0, 1.0, 3.0]})

    def test_java_double_to_string(self):
        """Numeric keys format like Java Double.toString so models written
        by the reference index identically (scientific form outside
        [1e-3, 1e7), StringIndexer.java uses String.valueOf)."""
        from flink_ml_tpu.models.feature.stringindexer import _java_double_to_string as f

        assert f(1.0) == "1.0"
        assert f(-2.5) == "-2.5"
        assert f(0.001) == "0.001"
        assert f(9999999.0) == "9999999.0"
        assert f(1e7) == "1.0E7"
        assert f(12345678.0) == "1.2345678E7"
        assert f(1e-4) == "1.0E-4"
        assert f(-1.5e-5) == "-1.5E-5"
        assert f(0.0) == "0.0"
        assert f(-0.0) == "-0.0"
        assert f(float("nan")) == "NaN"
        assert f(float("inf")) == "Infinity"
        assert f(float("-inf")) == "-Infinity"
        assert f(1.23456789e100) == "1.23456789E100"

    def test_alphabet_asc(self):
        model = (
            StringIndexer()
            .set_input_cols("f1", "f2")
            .set_output_cols("o1", "o2")
            .set_string_order_type("alphabetAsc")
        ).fit(self._table())
        out = model.transform(self._table())[0]
        np.testing.assert_array_equal(np.asarray(out.column("o1")), [0, 1, 1, 2])

    def test_frequency_desc(self):
        model = (
            StringIndexer()
            .set_input_cols("f1")
            .set_output_cols("o1")
            .set_string_order_type("frequencyDesc")
        ).fit(self._table())
        out = model.transform(self._table())[0]
        got = np.asarray(out.column("o1"))
        assert got[1] == 0 and got[2] == 0  # "b" is most frequent

    def test_handle_invalid(self):
        model = (
            StringIndexer().set_input_cols("f1").set_output_cols("o1").set_string_order_type("alphabetAsc")
        ).fit(self._table())
        unseen = Table({"f1": ["a", "z"]})
        with pytest.raises(ValueError):
            model.transform(unseen)
        got = np.asarray(model.set_handle_invalid("keep").transform(unseen)[0].column("o1"))
        np.testing.assert_array_equal(got, [0, 3])
        out = model.set_handle_invalid("skip").transform(unseen)[0]
        assert out.num_rows == 1

    def test_index_to_string(self):
        model = (
            StringIndexer().set_input_cols("f1").set_output_cols("o1").set_string_order_type("alphabetAsc")
        ).fit(self._table())
        reverse = IndexToStringModel().set_input_cols("idx").set_output_cols("str")
        reverse.set_model_data(*model.get_model_data())
        out = reverse.transform(Table({"idx": [0.0, 2.0]}))[0]
        assert list(out.column("str")) == ["a", "c"]

    def test_save_load(self, tmp_path):
        model = (
            StringIndexer().set_input_cols("f1").set_output_cols("o1").set_string_order_type("alphabetAsc")
        ).fit(self._table())
        model.save(str(tmp_path / "si"))
        loaded = StringIndexerModel.load(str(tmp_path / "si"))
        assert loaded.string_arrays == model.string_arrays


class TestOneHotEncoder:
    def test_fit_transform(self):
        train = Table({"input": [0.0, 1.0, 2.0, 0.0]})
        model = OneHotEncoder().set_input_cols("input").set_output_cols("output").fit(train)
        out = model.transform(train)[0]
        batch = out.column("output")
        assert batch.size == 2  # dropLast: 3 categories -> size 2
        np.testing.assert_array_equal(batch.to_dense(), [[1, 0], [0, 1], [0, 0], [1, 0]])

    def test_no_drop_last(self):
        train = Table({"input": [0.0, 1.0]})
        model = (
            OneHotEncoder().set_input_cols("input").set_output_cols("output").set_drop_last(False)
        ).fit(train)
        batch = model.transform(train)[0].column("output")
        np.testing.assert_array_equal(batch.to_dense(), [[1, 0], [0, 1]])

    def test_save_load(self, tmp_path):
        train = Table({"input": [0.0, 1.0, 2.0]})
        model = OneHotEncoder().set_input_cols("input").set_output_cols("output").fit(train)
        model.save(str(tmp_path / "ohe"))
        loaded = OneHotEncoderModel.load(str(tmp_path / "ohe"))
        np.testing.assert_array_equal(loaded.category_sizes, model.category_sizes)


class TestVectorIndexer:
    def test_fit_transform(self):
        train = Table(
            {"input": [Vectors.dense(1, 11), Vectors.dense(2, 12), Vectors.dense(1, 13), Vectors.dense(2, 14)]}
        )
        model = VectorIndexer().set_max_categories(3).fit(train)
        # column 0 has 2 distinct -> categorical {1->0, 2->1}; column 1 has 4 -> continuous
        out = model.transform(train)[0]
        got = np.asarray(out.column("output"))
        np.testing.assert_array_equal(got[:, 0], [0, 1, 0, 1])
        np.testing.assert_array_equal(got[:, 1], [11, 12, 13, 14])

    def test_zero_first(self):
        train = Table({"input": [Vectors.dense(3.0), Vectors.dense(0.0), Vectors.dense(-1.0)]})
        model = VectorIndexer().set_max_categories(5).fit(train)
        assert model.category_maps[0][0.0] == 0

    def test_handle_invalid(self):
        train = Table({"input": [Vectors.dense(1.0), Vectors.dense(2.0)]})
        model = VectorIndexer().set_max_categories(5).fit(train)
        unseen = Table({"input": [Vectors.dense(9.0)]})
        with pytest.raises(ValueError):
            model.transform(unseen)
        got = np.asarray(model.set_handle_invalid("keep").transform(unseen)[0].column("output"))
        np.testing.assert_array_equal(got, [[2.0]])

    def test_save_load(self, tmp_path):
        train = Table({"input": [Vectors.dense(1.0), Vectors.dense(2.0)]})
        model = VectorIndexer().fit(train)
        model.save(str(tmp_path / "vi"))
        loaded = VectorIndexerModel.load(str(tmp_path / "vi"))
        assert loaded.category_maps == model.category_maps


class TestCountVectorizer:
    def test_fit_transform(self):
        t = Table({"input": [["a", "b", "c"], ["a", "b", "b", "c", "a"]]})
        model = CountVectorizer().fit(t)
        assert model.vocabulary[0] in ("a", "b")  # both appear 3x; ties alphabetic -> "a"
        out = model.transform(t)[0].column("output")
        dense = out.to_dense()
        assert dense.shape == (2, 3)
        # row 1: a=2, b=2, c=1
        vocab_idx = {v: i for i, v in enumerate(model.vocabulary)}
        assert dense[1, vocab_idx["a"]] == 2
        assert dense[1, vocab_idx["b"]] == 2
        assert dense[1, vocab_idx["c"]] == 1

    def test_min_tf(self):
        t = Table({"input": [["a", "a", "b"]]})
        model = CountVectorizer().set_min_tf(2.0).fit(t)
        dense = model.transform(t)[0].column("output").to_dense()
        vocab_idx = {v: i for i, v in enumerate(model.vocabulary)}
        assert dense[0, vocab_idx["a"]] == 2 and dense[0, vocab_idx["b"]] == 0

    def test_save_load(self, tmp_path):
        t = Table({"input": [["x", "y"]]})
        model = CountVectorizer().fit(t)
        model.save(str(tmp_path / "cv"))
        loaded = CountVectorizerModel.load(str(tmp_path / "cv"))
        assert loaded.vocabulary == model.vocabulary


class TestIDF:
    def test_fit_transform(self):
        # IDFTest.java-style data: df over 3 docs
        t = Table(
            {"input": [Vectors.dense(1, 2, 0), Vectors.dense(1, 0, 3), Vectors.dense(1, 4, 5)]}
        )
        model = IDF().fit(t)
        expected_idf = np.log(np.array([4 / 4, 4 / 3, 4 / 3]))
        np.testing.assert_allclose(model.idf, expected_idf, atol=1e-7)
        out = np.asarray(model.transform(t)[0].column("output"))
        np.testing.assert_allclose(out[0], [0.0, 2 * expected_idf[1], 0.0], atol=1e-7)

    def test_min_doc_freq(self):
        t = Table(
            {"input": [Vectors.dense(1, 0), Vectors.dense(1, 2), Vectors.dense(0, 0)]}
        )
        model = IDF().set_min_doc_freq(2).fit(t)
        # feature 1 (df=1 < 2) filtered to 0; feature 0 (df=2) keeps log(4/3)
        assert model.idf[1] == 0.0
        np.testing.assert_allclose(model.idf[0], np.log(4 / 3), atol=1e-7)

    def test_save_load(self, tmp_path):
        t = Table({"input": [Vectors.dense(1, 0)]})
        model = IDF().fit(t)
        model.save(str(tmp_path / "idf"))
        loaded = IDFModel.load(str(tmp_path / "idf"))
        np.testing.assert_allclose(loaded.idf, model.idf)
        assert loaded.num_docs == 1


class TestKBinsDiscretizer:
    def test_uniform(self):
        X = np.asarray([[0.0], [1.0], [2.0], [10.0]])
        model = KBinsDiscretizer().set_strategy("uniform").set_num_bins(5).fit(Table({"input": X}))
        out = np.asarray(model.transform(Table({"input": X}))[0].column("output"))
        np.testing.assert_array_equal(out[:, 0], [0, 0, 1, 4])

    def test_quantile(self):
        X = np.arange(100, dtype=np.float64)[:, None]
        model = KBinsDiscretizer().set_num_bins(4).fit(Table({"input": X}))
        out = np.asarray(model.transform(Table({"input": X}))[0].column("output"))
        counts = np.bincount(out[:, 0].astype(int))
        assert len(counts) == 4 and all(20 <= c <= 30 for c in counts)

    def test_kmeans(self):
        X = np.concatenate([np.zeros(50), np.ones(50) * 10])[:, None]
        model = KBinsDiscretizer().set_strategy("kmeans").set_num_bins(2).fit(Table({"input": X}))
        out = np.asarray(model.transform(Table({"input": X}))[0].column("output"))
        assert set(out[:50, 0]) == {0.0} and set(out[50:, 0]) == {1.0}

    def test_out_of_range_clamps(self):
        X = np.asarray([[0.0], [1.0]])
        model = KBinsDiscretizer().set_strategy("uniform").set_num_bins(2).fit(Table({"input": X}))
        out = np.asarray(model.transform(Table({"input": [[-5.0], [99.0]]}))[0].column("output"))
        np.testing.assert_array_equal(out[:, 0], [0, 1])

    def test_save_load(self, tmp_path):
        X = np.arange(10, dtype=np.float64)[:, None]
        model = KBinsDiscretizer().fit(Table({"input": X}))
        model.save(str(tmp_path / "kb"))
        loaded = KBinsDiscretizerModel.load(str(tmp_path / "kb"))
        np.testing.assert_allclose(loaded.bin_edges[0], model.bin_edges[0])


class TestVarianceThresholdSelector:
    def test_fit_transform(self):
        X = np.asarray([[1.0, 5.0, 0.0], [2.0, 5.0, 0.0], [3.0, 5.0, 0.0]])
        model = VarianceThresholdSelector().fit(Table({"input": X}))
        out = np.asarray(model.transform(Table({"input": X}))[0].column("output"))
        np.testing.assert_array_equal(model.indices, [0])
        np.testing.assert_array_equal(out, [[1], [2], [3]])

    def test_threshold(self):
        X = np.asarray([[0.0, 0.0], [1.0, 10.0]])
        model = VarianceThresholdSelector().set_variance_threshold(1.0).fit(Table({"input": X}))
        np.testing.assert_array_equal(model.indices, [1])


class TestUnivariateFeatureSelector:
    def test_anova_num_top(self):
        rng = np.random.RandomState(0)
        y = np.repeat([0.0, 1.0], 50)
        X = rng.randn(100, 4)
        X[:, 2] += y * 5  # only feature 2 is informative
        t = Table({"features": X, "label": y})
        model = (
            UnivariateFeatureSelector()
            .set_feature_type("continuous")
            .set_label_type("categorical")
            .set_selection_threshold(1)
        ).fit(t)
        np.testing.assert_array_equal(model.indices, [2])
        out = np.asarray(model.transform(t)[0].column("output"))
        np.testing.assert_allclose(out[:, 0], X[:, 2])

    def test_fdr_cutoff_is_strict(self):
        """BH cutoff uses strict < (UnivariateFeatureSelector.java:236-237):
        a p-value exactly equal to k/d * alpha is NOT selected."""
        from flink_ml_tpu.models.feature.univariatefeatureselector import (
            select_indices_from_p_values,
        )

        # d=4, alpha=0.4: cutoffs are 0.1, 0.2, 0.3, 0.4
        p = np.asarray([0.1, 0.5, 0.6, 0.7])  # p_(1) == 1/4*0.4 exactly
        assert select_indices_from_p_values(p, "fdr", 0.4).size == 0
        p = np.asarray([0.0999, 0.5, 0.6, 0.7])  # strictly below
        np.testing.assert_array_equal(
            select_indices_from_p_values(p, "fdr", 0.4), [0]
        )

    def test_fpr_chisq(self):
        rng = np.random.RandomState(1)
        y = np.repeat([0.0, 1.0], 100)
        X = rng.randint(0, 3, size=(200, 3)).astype(float)
        X[:, 0] = y  # perfectly dependent
        t = Table({"features": X, "label": y})
        model = (
            UnivariateFeatureSelector()
            .set_feature_type("categorical")
            .set_label_type("categorical")
            .set_selection_mode("fpr")
            .set_selection_threshold(0.01)
        ).fit(t)
        assert 0 in model.indices

    def test_requires_types(self):
        with pytest.raises(ValueError):
            UnivariateFeatureSelector().fit(Table({"features": [[1.0]], "label": [1.0]}))


class TestMinHashLSH:
    def _table(self):
        return Table(
            {
                "id": [0, 1, 2],
                "vec": [
                    Vectors.sparse(6, [0, 1, 2], [1.0, 1.0, 1.0]),
                    Vectors.sparse(6, [2, 3, 4], [1.0, 1.0, 1.0]),
                    Vectors.sparse(6, [0, 2, 4], [1.0, 1.0, 1.0]),
                ],
            }
        )

    def _model(self):
        return (
            MinHashLSH()
            .set_input_col("vec")
            .set_output_col("hashes")
            .set_num_hash_tables(5)
            .set_seed(2022)
        ).fit(self._table())

    def test_transform_shape(self):
        model = self._model()
        out = model.transform(self._table())[0]
        hashes = list(out.column("hashes"))
        assert len(hashes) == 3 and len(hashes[0]) == 5

    def test_deterministic_model(self):
        a1 = self._model().rand_coefficient_a
        a2 = self._model().rand_coefficient_a
        np.testing.assert_array_equal(a1, a2)

    def test_reference_golden_hashes(self):
        """Seed-for-seed parity with the reference: fitted at seed 2022 with
        5 tables x 3 functions, transform must reproduce MinHashLSHTest's
        outputRows exactly (MinHashLSHTest.java:61-83; the reference
        compares unordered, so we sort both sides)."""
        expected = [
            [[1.73046954e8, 1.57275425e8, 6.90717571e8],
             [5.02301169e8, 7.967141e8, 4.06089319e8],
             [2.83652171e8, 1.97714719e8, 6.04731316e8],
             [5.2181506e8, 6.36933726e8, 6.13894128e8],
             [3.04301769e8, 1.113672955e9, 6.1388711e8]],
            [[1.73046954e8, 1.57275425e8, 6.7798584e7],
             [6.38582806e8, 1.78703694e8, 4.06089319e8],
             [6.232638e8, 9.28867e7, 9.92010642e8],
             [2.461064e8, 1.12787481e8, 1.92180297e8],
             [2.38162496e8, 1.552933319e9, 2.77995137e8]],
            [[1.73046954e8, 1.57275425e8, 6.90717571e8],
             [1.453197722e9, 7.967141e8, 4.06089319e8],
             [6.232638e8, 1.97714719e8, 6.04731316e8],
             [2.461064e8, 1.12787481e8, 1.92180297e8],
             [1.224130231e9, 1.113672955e9, 2.77995137e8]],
        ]
        out = self._model_5x3().transform(self._table())[0]
        got = sorted(
            tuple(map(tuple, np.asarray([np.asarray(x) for x in h])))
            for h in out.column("hashes")
        )
        assert got == sorted(tuple(map(tuple, e)) for e in expected)

    def _model_5x3(self):
        return (
            MinHashLSH()
            .set_input_col("vec")
            .set_output_col("hashes")
            .set_seed(2022)
            .set_num_hash_tables(5)
            .set_num_hash_functions_per_table(3)
        ).fit(self._table())

    def test_nearest_neighbors(self):
        model = self._model()
        result = model.approx_nearest_neighbors(
            self._table(), Vectors.sparse(6, [0, 1, 2], [1.0, 1.0, 1.0]), 2
        )
        ids = list(result.column("id"))
        assert ids[0] == 0  # exact match first
        dists = np.asarray(result.column("distCol"))
        assert dists[0] == 0.0

    def test_similarity_join(self):
        model = self._model()
        joined = model.approx_similarity_join(self._table(), self._table(), 0.9, "id")
        pairs = set(zip(joined.column("idA"), joined.column("idB")))
        assert (0, 0) in pairs

    def test_save_load(self, tmp_path):
        model = self._model()
        model.save(str(tmp_path / "lsh"))
        loaded = MinHashLSHModel.load(str(tmp_path / "lsh"))
        np.testing.assert_array_equal(loaded.rand_coefficient_a, model.rand_coefficient_a)


class TestSQLTransformer:
    def test_select(self):
        t = Table({"id": [1, 2], "v1": [1.0, 2.0], "v2": [3.0, 4.0]})
        out = (
            SQLTransformer().set_statement("SELECT *, (v1 + v2) AS v3 FROM __THIS__")
        ).transform(t)[0]
        np.testing.assert_allclose(np.asarray(out.column("v3")), [4.0, 6.0])

    def test_aggregate(self):
        t = Table({"g": [1, 1, 2], "v": [1.0, 3.0, 10.0]})
        out = (
            SQLTransformer().set_statement("SELECT g, SUM(v) AS s FROM __THIS__ GROUP BY g")
        ).transform(t)[0]
        assert out.num_rows == 2
        np.testing.assert_allclose(sorted(np.asarray(out.column("s"))), [4.0, 10.0])

    def test_requires_this(self):
        with pytest.raises(ValueError):
            SQLTransformer().set_statement("SELECT 1")


def test_select_columns_exact_on_device():
    """MXU selection must reproduce float32 values bit-exactly (default
    matmul precision would round through bfloat16)."""
    import jax.numpy as jnp

    from flink_ml_tpu.ops.selection import select_columns

    rng = np.random.default_rng(0)
    X_host = rng.random((257, 9)).astype(np.float32) + 0.333333
    X_dev = jnp.asarray(X_host)
    idx = np.array([7, 0, 3])
    out = np.asarray(select_columns(X_dev, idx))
    np.testing.assert_array_equal(out, X_host[:, idx])
    assert select_columns(X_dev, np.array([], np.int64)).shape == (257, 0)
