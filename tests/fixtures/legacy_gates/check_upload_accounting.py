#!/usr/bin/env python
"""Upload-accounting gate: no raw host→device transfers in models/ or ops/.

Every host→device upload a model or op makes must ride the accounted
stager in flink_ml_tpu/parallel/prefetch.py (`stage_to_device` /
`stage_from_callback`) — that is what keeps the `h2d.bytes` / `h2d.count`
counters (and the BENCH `h2dBytes` field, and the inputPipeline entry's
zero-upload-epochs claim) an exhaustive answer to "what bytes crossed the
tunnel host→device". A raw `jax.device_put` in a model would execute fine
and silently disappear from the accounting, so this gate fails the build
instead — the upload-side mirror of `check_collective_accounting.py`. It
scans every .py file under flink_ml_tpu/models and flink_ml_tpu/ops for
direct calls to the jax transfer entry points (comments and string
literals are stripped via tokenize, so docstrings that *mention*
device_put stay legal).

Implicit uploads (`jnp.asarray(host_array)` feeding a jitted kernel, jit
argument transfer) are invisible to source scanning and intentionally out
of scope — the gate covers the explicit bulk-transfer surface, where
bypassing the stager is a one-line mistake; the bulk data paths all stage
explicitly so their shards land pre-placed.

Run directly (exit code 1 on violations) or via
tests/test_upload_accounting.py, which keeps the gate in tier-1.
"""

from __future__ import annotations

import io
import os
import re
import sys
import tokenize
from typing import List, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCANNED_DIRS = ("flink_ml_tpu/models", "flink_ml_tpu/ops")

# the explicit host->device transfer entry points the stager wraps
_PRIMITIVES = (
    "device_put",
    "device_put_sharded",
    "device_put_replicated",
    "make_array_from_callback",
    "make_array_from_single_device_arrays",
)
_PATTERN = re.compile(
    r"\bjax\s*\.\s*(" + "|".join(_PRIMITIVES) + r")\s*\("
)


def _code_only(source: str) -> str:
    """Source with comments and string/docstring tokens blanked (newlines
    kept, so reported line numbers stay true)."""
    out = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except tokenize.TokenError:
        return source
    lines = source.splitlines(keepends=True)
    drop = []  # (srow, scol, erow, ecol) spans to blank
    for tok in tokens:
        if tok.type in (tokenize.COMMENT, tokenize.STRING):
            drop.append((tok.start, tok.end))
    for line_no, line in enumerate(lines, start=1):
        buf = list(line)
        for (srow, scol), (erow, ecol) in drop:
            if srow <= line_no <= erow:
                lo = scol if line_no == srow else 0
                hi = ecol if line_no == erow else len(buf)
                for i in range(lo, min(hi, len(buf))):
                    if buf[i] not in "\r\n":
                        buf[i] = " "
        out.append("".join(buf))
    return "".join(out)


def find_violations() -> List[Tuple[str, int, str]]:
    """(path, line, primitive) for every raw transfer call in scope."""
    violations = []
    for rel_dir in SCANNED_DIRS:
        base = os.path.join(ROOT, rel_dir)
        for dirpath, _, filenames in os.walk(base):
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                with open(path) as f:
                    code = _code_only(f.read())
                for i, line in enumerate(code.splitlines(), start=1):
                    for match in _PATTERN.finditer(line):
                        violations.append(
                            (os.path.relpath(path, ROOT), i, match.group(1))
                        )
    return violations


def main() -> int:
    violations = find_violations()
    if violations:
        print(
            f"upload accounting: {len(violations)} raw host->device transfer "
            "call(s) bypass the accounted stager "
            "(use flink_ml_tpu.parallel.prefetch.stage_to_device instead):"
        )
        for path, line, prim in violations:
            print(f"  {path}:{line}: jax.{prim}(...)")
        return 1
    print(
        "upload accounting: no raw host->device transfers in "
        + " or ".join(SCANNED_DIRS)
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
