#!/usr/bin/env python
"""Fusion-coverage gate: every concrete transform-capable stage must state
its fusion contract.

The transform-kernel protocol (flink_ml_tpu/api.py) is opt-in, which means
a newly added stage silently lands on the eager per-stage path — exactly
the per-stage dispatch overhead the fusion planner exists to remove. This
check makes that decision explicit and reviewable: every concrete
`AlgoOperator` subclass (Models included) must either

- override `transform_kernel` (and set `fusable = True`), or
- set `fusable = False` with a non-empty `fusable_reason` saying WHY the
  stage cannot run inside a fused device program.

Run directly (exit code 1 on violations) or via
tests/test_fusion_coverage.py, which keeps the gate in tier-1.
"""

from __future__ import annotations

import importlib
import inspect
import os
import pkgutil
import sys
from typing import List, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _iter_stage_classes():
    import flink_ml_tpu
    from flink_ml_tpu.api import AlgoOperator

    roots = [flink_ml_tpu]
    seen = set()
    for root in roots:
        for info in pkgutil.walk_packages(root.__path__, root.__name__ + "."):
            # extension build tree and CLI entrypoints are not stage modules
            # (importing a __main__ runs its CLI side effects)
            if ".native" in info.name or info.name.endswith("__main__"):
                continue
            try:
                module = importlib.import_module(info.name)
            except Exception as e:  # pragma: no cover - import rot is its own bug
                raise RuntimeError(f"cannot import {info.name}: {e!r}") from e
            for _, cls in inspect.getmembers(module, inspect.isclass):
                if (
                    issubclass(cls, AlgoOperator)
                    and not inspect.isabstract(cls)
                    and cls.__module__ == module.__name__
                    and cls not in seen
                ):
                    seen.add(cls)
                    yield cls


def find_violations() -> List[Tuple[str, str]]:
    """(qualified class name, problem) for every stage breaking the contract."""
    from flink_ml_tpu.api import AlgoOperator

    violations = []
    for cls in _iter_stage_classes():
        has_kernel = cls.transform_kernel is not AlgoOperator.transform_kernel
        fusable = cls.__dict__.get("fusable", None)
        # `fusable` must be declared on the class itself (or an own base that
        # overrode the AlgoOperator default) — inheriting the bare default
        # means nobody made the call for this stage
        declared = any("fusable" in k.__dict__ for k in cls.__mro__[:-1] if k is not AlgoOperator)
        name = f"{cls.__module__}.{cls.__name__}"
        if has_kernel:
            if not getattr(cls, "fusable", False) and cls.__dict__.get("supports_fusion") is None and not declared:
                violations.append((name, "has transform_kernel but fusable is not declared True"))
            continue
        if not declared:
            violations.append(
                (name, "no transform_kernel and no explicit fusable declaration")
            )
            continue
        if getattr(cls, "fusable", False):
            violations.append((name, "fusable = True but transform_kernel is not overridden"))
            continue
        reason = getattr(cls, "fusable_reason", "")
        if not isinstance(reason, str) or not reason.strip():
            violations.append(
                (name, "fusable = False without a non-empty fusable_reason")
            )
    return violations


def main() -> int:
    violations = find_violations()
    total = len(list(_iter_stage_classes()))
    if violations:
        print(f"fusion coverage: {len(violations)} of {total} stages violate the contract:")
        for name, problem in violations:
            print(f"  {name}: {problem}")
        return 1
    print(f"fusion coverage: all {total} concrete stages declare their fusion contract")
    return 0


if __name__ == "__main__":
    sys.exit(main())
