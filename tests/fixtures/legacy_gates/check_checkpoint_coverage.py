#!/usr/bin/env python
"""Checkpoint-coverage gate: every concrete estimator must state its
checkpoint contract.

The JobSnapshot subsystem (flink_ml_tpu/ckpt/) makes preemption-safe
resume a property of the fit paths that route through it — which means a
newly added estimator that does NOT route through it silently loses its
training progress on any preemption. This check makes that decision
explicit and reviewable (the sibling of check_fusion_coverage.py): every
concrete `Estimator` subclass must either

- set `checkpointable = True`, in which case its defining module must
  actually reference one of the sanctioned checkpoint funnels (`run_sgd`
  / `optimize_stream`, `iterate_unbounded`, or the JobSnapshot API
  directly) — a bare True with no wiring is a lie the gate rejects; or
- set `checkpointable = False` with a non-empty `checkpoint_reason`
  saying WHY there is no resumable mid-fit state (single-pass
  aggregations, seeded recomputes, composites).

Funnel references are detected on comment/string-stripped source
(tokenize), so a docstring that merely *mentions* `run_sgd` does not
satisfy the True contract.

Run directly (exit code 1 on violations) or via
tests/test_checkpoint_coverage.py, which keeps the gate in tier-1.
"""

from __future__ import annotations

import importlib
import inspect
import io
import os
import pkgutil
import sys
import tokenize
from typing import List, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# ways a fit path reaches the JobSnapshot API; referenced from the
# estimator's own module (directly or through the shared SGD wiring)
FUNNELS = (
    "run_sgd",
    "optimize_stream",
    "iterate_unbounded",
    "save_job_snapshot",
    "load_job_snapshot",
)


def _code_only(source: str) -> str:
    """Source with comments and string/docstring tokens blanked."""
    out = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except tokenize.TokenError:
        return source
    lines = source.splitlines(keepends=True)
    drop = []
    for tok in tokens:
        if tok.type in (tokenize.COMMENT, tokenize.STRING):
            drop.append((tok.start, tok.end))
    for line_no, line in enumerate(lines, start=1):
        buf = list(line)
        for (srow, scol), (erow, ecol) in drop:
            if srow <= line_no <= erow:
                lo = scol if line_no == srow else 0
                hi = ecol if line_no == erow else len(buf)
                for i in range(lo, min(hi, len(buf))):
                    if buf[i] not in "\r\n":
                        buf[i] = " "
        out.append("".join(buf))
    return "".join(out)


def _iter_estimator_classes():
    import flink_ml_tpu
    from flink_ml_tpu.api import Estimator

    seen = set()
    for info in pkgutil.walk_packages(
        flink_ml_tpu.__path__, flink_ml_tpu.__name__ + "."
    ):
        if ".native" in info.name or info.name.endswith("__main__"):
            continue
        try:
            module = importlib.import_module(info.name)
        except Exception as e:  # pragma: no cover - import rot is its own bug
            raise RuntimeError(f"cannot import {info.name}: {e!r}") from e
        for _, cls in inspect.getmembers(module, inspect.isclass):
            if (
                issubclass(cls, Estimator)
                and not inspect.isabstract(cls)
                and cls.__module__ == module.__name__
                and cls not in seen
            ):
                seen.add(cls)
                yield cls
    # the top-level package modules (pipeline.py, graph.py) are reached by
    # walk_packages too, but make the Estimator root explicit regardless
    for name in ("flink_ml_tpu.pipeline", "flink_ml_tpu.graph"):
        module = importlib.import_module(name)
        for _, cls in inspect.getmembers(module, inspect.isclass):
            if (
                issubclass(cls, Estimator)
                and not inspect.isabstract(cls)
                and cls.__module__ == module.__name__
                and cls not in seen
            ):
                seen.add(cls)
                yield cls


def _module_references_funnel(cls) -> bool:
    path = inspect.getsourcefile(cls)
    if path is None:  # pragma: no cover
        return False
    with open(path) as f:
        code = _code_only(f.read())
    return any(funnel in code for funnel in FUNNELS)


def find_violations() -> List[Tuple[str, str]]:
    """(qualified class name, problem) for every estimator breaking the
    contract."""
    from flink_ml_tpu.api import Estimator

    violations = []
    for cls in _iter_estimator_classes():
        name = f"{cls.__module__}.{cls.__name__}"
        declared = any(
            "checkpointable" in k.__dict__ for k in cls.__mro__[:-1] if k is not Estimator
        )
        if not declared:
            violations.append((name, "no explicit checkpointable declaration"))
            continue
        if getattr(cls, "checkpointable", None):
            if not _module_references_funnel(cls):
                violations.append(
                    (
                        name,
                        "checkpointable = True but its module references no "
                        f"checkpoint funnel ({', '.join(FUNNELS)})",
                    )
                )
            continue
        reason = getattr(cls, "checkpoint_reason", "")
        if not isinstance(reason, str) or not reason.strip():
            violations.append(
                (name, "checkpointable = False without a non-empty checkpoint_reason")
            )
    return violations


def main() -> int:
    violations = find_violations()
    total = len(list(_iter_estimator_classes()))
    if violations:
        print(
            f"checkpoint coverage: {len(violations)} of {total} estimators "
            "violate the contract:"
        )
        for name, problem in violations:
            print(f"  {name}: {problem}")
        return 1
    print(
        f"checkpoint coverage: all {total} concrete estimators declare "
        "their checkpoint contract"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
