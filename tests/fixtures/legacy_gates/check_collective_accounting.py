#!/usr/bin/env python
"""Collective-accounting gate: no raw lax collectives in models/ or ops/.

Every collective a model or op dispatches must ride the accounted wrappers
in flink_ml_tpu/parallel/collectives.py — that is what keeps the
`collective.*` counters (and the BENCH `collectiveBreakdown` field) an
exhaustive answer to "what traffic does this program move". A raw
`lax.psum` in a model would execute fine and silently disappear from the
accounting, so this gate fails the build instead: it scans every .py file
under flink_ml_tpu/models and flink_ml_tpu/ops for direct calls to the
collective lax primitives (comments and string literals are stripped via
tokenize, so docstrings that *mention* psum stay legal).

GSPMD-inserted collectives (sharded contractions letting XLA place the
all-reduce) are invisible to source scanning and intentionally out of
scope — the gate covers the explicit-SPMD surface, where bypassing the
wrappers is a one-line mistake.

Run directly (exit code 1 on violations) or via
tests/test_collective_accounting.py, which keeps the gate in tier-1.
"""

from __future__ import annotations

import io
import os
import re
import sys
import tokenize
from typing import List, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCANNED_DIRS = ("flink_ml_tpu/models", "flink_ml_tpu/ops")

# the collective primitives the accounted wrappers cover
_PRIMITIVES = (
    "psum",
    "pmean",
    "pmax",
    "pmin",
    "psum_scatter",
    "all_gather",
    "all_to_all",
    "ppermute",
)
_PATTERN = re.compile(
    r"\blax\s*\.\s*(" + "|".join(_PRIMITIVES) + r")\s*\("
)


def _code_only(source: str) -> str:
    """Source with comments and string/docstring tokens blanked (newlines
    kept, so reported line numbers stay true)."""
    out = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except tokenize.TokenError:
        return source
    lines = source.splitlines(keepends=True)
    drop = []  # (srow, scol, erow, ecol) spans to blank
    for tok in tokens:
        if tok.type in (tokenize.COMMENT, tokenize.STRING):
            drop.append((tok.start, tok.end))
    for line_no, line in enumerate(lines, start=1):
        buf = list(line)
        for (srow, scol), (erow, ecol) in drop:
            if srow <= line_no <= erow:
                lo = scol if line_no == srow else 0
                hi = ecol if line_no == erow else len(buf)
                for i in range(lo, min(hi, len(buf))):
                    if buf[i] not in "\r\n":
                        buf[i] = " "
        out.append("".join(buf))
    return "".join(out)


def find_violations() -> List[Tuple[str, int, str]]:
    """(path, line, primitive) for every raw collective call in scope."""
    violations = []
    for rel_dir in SCANNED_DIRS:
        base = os.path.join(ROOT, rel_dir)
        for dirpath, _, filenames in os.walk(base):
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                with open(path) as f:
                    code = _code_only(f.read())
                for i, line in enumerate(code.splitlines(), start=1):
                    for match in _PATTERN.finditer(line):
                        violations.append(
                            (os.path.relpath(path, ROOT), i, match.group(1))
                        )
    return violations


def main() -> int:
    violations = find_violations()
    if violations:
        print(
            f"collective accounting: {len(violations)} raw lax collective "
            "call(s) bypass the accounted wrappers "
            "(use flink_ml_tpu.parallel.collectives instead):"
        )
        for path, line, prim in violations:
            print(f"  {path}:{line}: lax.{prim}(...)")
        return 1
    print(
        "collective accounting: no raw lax collectives in "
        + " or ".join(SCANNED_DIRS)
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
