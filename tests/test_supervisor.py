"""Elastic training supervisor (ISSUE 15): live host-failure detection,
collective hang watchdog, automatic shrink-and-resume.

The chaos matrix injects `host.die` (heartbeat sender stops — detection
rides the heartbeat timeout) and `host.hang` (the fit thread wedges like
a stuck collective — detection rides the dispatch-progress deadline) at
each supervised boundary phase: mid-epoch (`dispatch`), mid-collective
(`collective`) and mid-commit (`commit`). Every scenario must
auto-recover within `config.recovery_budget`; a same-host-count resume
(hang + readmit) must be BIT-IDENTICAL to the unkilled fit, a shrink
resume allclose per the documented cross-count reduction-order caveat
(docs/fault_tolerance.md "Failure domains and automatic recovery")."""

import os

import numpy as np
import pytest

from flink_ml_tpu import config
from flink_ml_tpu.ckpt import coordinator, faults
from flink_ml_tpu.ckpt.faults import InjectedFault
from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
from flink_ml_tpu.ops.optimizer import SGD
from flink_ml_tpu.parallel import mesh as mesh_lib
from flink_ml_tpu.parallel import supervisor
from flink_ml_tpu.parallel.iteration import iterate_bounded
from flink_ml_tpu.utils import metrics

# crisp-but-robust detection knobs for the virtual substrate: heartbeat
# death must be seen well before the hang deadline floor (1s default)
FAST = dict(heartbeat_timeout_s=0.25, poll_interval_s=0.01, stall_safety_s=30.0)



def _dense_problem(n=384, d=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ np.linspace(1, -1, d) > 0).astype(np.float32)
    return X, y


def _sgd_fit(X, y, ckpt, key="sup", max_iter=12):
    def fit(mesh):
        return SGD(
            max_iter=max_iter, global_batch_size=96, tol=0.0,
            checkpoint_dir=ckpt, checkpoint_key=key,
        ).optimize(
            np.zeros(X.shape[1], np.float32), X, y, None,
            BINARY_LOGISTIC_LOSS, mesh=mesh,
        )

    return fit


@pytest.fixture(scope="module")
def problem():
    return _dense_problem()


@pytest.fixture(scope="module")
def reference(problem, tmp_path_factory):
    """The unkilled checkpointed fit — the parity target (checkpointed,
    so the code path matches the supervised runs exactly)."""
    X, y = problem
    ref_dir = str(tmp_path_factory.mktemp("ref"))
    coeff, _, epochs = _sgd_fit(X, y, ref_dir)(mesh_lib.default_mesh())
    assert epochs == 12
    return np.asarray(coeff)


def _no_uncommitted(path, key):
    cuts = coordinator.committed_cuts(path, key)
    newest = cuts[-1] if cuts else 0
    stray = [
        n
        for n in os.listdir(path)
        if (coordinator._cut_of(n, coordinator._base(key)) or 0) > newest
        or ".tmp" in n
    ]
    assert stray == [], f"in-flight cut not cancelled: {stray}"


# ---------------------------------------------------------------------------
# single-scenario behavior
# ---------------------------------------------------------------------------

class TestDetection:
    def test_host_death_detected_quarantined_and_shrink_resumed(
        self, problem, reference, tmp_path
    ):
        X, y = problem
        d = str(tmp_path)
        before = metrics.get_counter("supervisor.hostFailure", 0)
        with config.snapshot_hosts_mode(4):
            with faults.inject("host.die.dispatch", after=4):
                res = supervisor.supervise(
                    _sgd_fit(X, y, d), hosts=4,
                    checkpoint_dir=d, job_key="sup", **FAST,
                )
        assert res.attempts == 2 and res.recoveries == 1
        (ev,) = res.events
        assert ev.kind == "hostFailure" and ev.phase == "dispatch"
        assert ev.quarantined and res.hosts == 3
        assert 0.0 < ev.detection_ms < 5000.0
        assert ev.recovery_ms is not None and ev.recovery_ms < 30000.0
        assert metrics.get_counter("supervisor.hostFailure", 0) == before + 1
        coeff, _, epochs = res.value
        assert epochs == 12
        # cross-host-count resume: allclose per the reduction-order caveat
        np.testing.assert_allclose(
            np.asarray(coeff), reference, rtol=5e-4, atol=1e-6
        )
        _no_uncommitted(d, "sup")

    def test_collective_hang_detected_readmit_resume_bit_identical(
        self, problem, reference, tmp_path
    ):
        X, y = problem
        d = str(tmp_path)
        with config.snapshot_hosts_mode(4):
            with faults.inject("host.hang.collective", after=4):
                res = supervisor.supervise(
                    _sgd_fit(X, y, d), hosts=4, checkpoint_dir=d,
                    job_key="sup", heartbeat_timeout_s=10.0,
                    poll_interval_s=0.01, stall_safety_s=30.0,
                )
        (ev,) = res.events
        assert ev.kind == "collectiveHang" and ev.phase == "collective"
        assert not ev.quarantined and res.hosts == 4  # readmitted: same count
        coeff, _, epochs = res.value
        assert epochs == 12
        # SAME-host-count resume is bit-identical to the unkilled fit
        np.testing.assert_array_equal(np.asarray(coeff), reference)
        _no_uncommitted(d, "sup")

    def test_recovery_budget_exhausted_raises_typed(self, problem, tmp_path):
        X, y = problem
        d = str(tmp_path)
        with config.snapshot_hosts_mode(4):
            with faults.inject("host.die", after=2):
                with pytest.raises(supervisor.RecoveryBudgetExhausted) as ei:
                    supervisor.supervise(
                        _sgd_fit(X, y, d), hosts=4, checkpoint_dir=d,
                        job_key="sup", recovery_budget=0, **FAST,
                    )
        assert isinstance(ei.value.__cause__, supervisor.HostFailure)
        assert len(ei.value.events) == 1

    def test_non_supervised_errors_propagate_untouched(self, tmp_path):
        def bad_fit(mesh):
            raise ValueError("data bug")

        with pytest.raises(ValueError, match="data bug"):
            supervisor.supervise(bad_fit, hosts=2, **FAST)
        assert supervisor.active() is None

    def test_injected_crash_at_other_sites_is_not_laundered(
        self, problem, tmp_path
    ):
        """The supervisor recovers from HOST failures; an injected kill
        at a checkpoint boundary models a crash and must propagate."""
        X, y = problem
        with faults.inject("chunk", after=2):
            with pytest.raises(InjectedFault):
                supervisor.supervise(
                    _sgd_fit(X, y, str(tmp_path)), hosts=2, **FAST
                )

    def test_pulses_are_noops_outside_supervision(self):
        supervisor.pulse_boundary(supervisor.PHASE_DISPATCH)
        supervisor.pulse_boundary(supervisor.PHASE_COMMIT)
        supervisor.note_progress(0.01)
        assert supervisor.active() is None


class TestBoard:
    def test_form_mesh_over_survivors(self):
        import jax

        board = supervisor.HostBoard(mesh_lib.default_mesh(), 4)
        assert board.live() == [0, 1, 2, 3]
        board.quarantine(2)
        m = board.form_mesh()
        expected = [d for h, g in enumerate(
            mesh_lib.host_groups(mesh_lib.default_mesh(), 4)
        ) if h != 2 for d in g]
        assert list(m.devices.flat) == expected
        assert len(expected) == len(jax.devices()) * 3 // 4

    def test_overdue_tracks_only_stopped_senders(self):
        import time

        board = supervisor.HostBoard(mesh_lib.default_mesh(), 3)
        board.mark_dead(1, "dispatch")
        time.sleep(0.02)
        board.beat_live(time.monotonic())
        assert board.overdue(time.monotonic(), 0.5) == []  # not yet
        time.sleep(0.06)
        board.beat_live(time.monotonic())
        overdue = board.overdue(time.monotonic(), 0.05)
        assert [h for h, _ in overdue] == [1]


# ---------------------------------------------------------------------------
# THE chaos soak: kill and hang, mid-epoch / mid-collective / mid-commit
# ---------------------------------------------------------------------------

class TestChaosSoak:
    @pytest.mark.parametrize("phase", ["dispatch", "collective", "commit"])
    @pytest.mark.parametrize("kind", ["die", "hang"])
    def test_sgd_chaos_matrix(self, problem, reference, tmp_path, kind, phase):
        """Every (failure kind x boundary phase) cell auto-recovers
        within the budget, cancels the in-flight cut, and lands on the
        reference coefficients — bit-identical when the host count is
        unchanged (hang+readmit), allclose after a shrink (die)."""
        X, y = problem
        d = str(tmp_path)
        site = f"host.{kind}.{phase}"
        # commit boundaries pulse once per host per save: target host 1's
        # shard write of the third save so partial files exist on abort
        after = 6 if phase == "commit" else 4
        kwargs = dict(FAST)
        if kind == "hang":
            kwargs["heartbeat_timeout_s"] = 10.0  # hang watchdog must win
        with config.snapshot_hosts_mode(4):
            with faults.inject(site, after=after) as plan:
                res = supervisor.supervise(
                    _sgd_fit(X, y, d), hosts=4,
                    checkpoint_dir=d, job_key="sup", **kwargs,
                )
        assert plan.fired
        assert res.recoveries == 1 and res.attempts == 2
        (ev,) = res.events
        assert ev.phase == phase
        assert ev.kind == ("hostFailure" if kind == "die" else "collectiveHang")
        assert 0.0 < ev.detection_ms < 10000.0
        coeff, _, epochs = res.value
        assert epochs == 12
        if kind == "hang":
            assert res.hosts == 4
            np.testing.assert_array_equal(np.asarray(coeff), reference)
        else:
            assert res.hosts == 3 and ev.quarantined
            np.testing.assert_allclose(
                np.asarray(coeff), reference, rtol=5e-4, atol=1e-6
            )
        _no_uncommitted(d, "sup")

    def test_stream_sgd_host_death_resumes(self, tmp_path):
        """Out-of-core stream SGD under supervision: host death mid-fit,
        shrink, resume — parity with the unkilled stream fit."""
        X, y = _dense_problem(n=480, seed=3)

        def chunks():
            return iter(
                [(X[i:i + 120], y[i:i + 120], None) for i in range(0, 480, 120)]
            )

        def make_fit(ckpt):
            def fit(mesh):
                return SGD(
                    max_iter=8, global_batch_size=120, tol=0.0,
                    checkpoint_dir=ckpt, checkpoint_key="sup-stream",
                ).optimize_stream(None, chunks(), BINARY_LOGISTIC_LOSS, mesh=mesh)

            return fit

        expected, _, _, _ = make_fit(None)(mesh_lib.default_mesh())
        d = str(tmp_path)
        with config.snapshot_hosts_mode(4):
            with faults.inject("host.die", after=6):
                res = supervisor.supervise(
                    make_fit(d), hosts=4, checkpoint_dir=d,
                    job_key="sup-stream", **FAST,
                )
        assert res.recoveries == 1 and res.events[0].kind == "hostFailure"
        coeff, _, epochs, _ = res.value
        assert epochs == 8
        np.testing.assert_allclose(
            np.asarray(coeff), np.asarray(expected), rtol=5e-4, atol=1e-6
        )

    def test_kmeans_out_of_core_hang_resumes_bit_identical(self, tmp_path):
        """Out-of-core KMeans under supervision: collective hang,
        readmit, same-mesh resume bit-identical to the unkilled fit."""
        from flink_ml_tpu.models.clustering.kmeans import KMeans
        from flink_ml_tpu.table import StreamTable, Table

        rng = np.random.RandomState(7)
        X = np.concatenate([rng.randn(200, 4) + 3.0, rng.randn(200, 4) - 3.0])
        rng.shuffle(X)

        def stream():
            return StreamTable.from_batches(
                [Table({"features": X[i:i + 80]}) for i in range(0, 400, 80)]
            )

        def fit(mesh):
            with mesh_lib.use_mesh(mesh):
                return KMeans().set_k(3).set_seed(11).set_max_iter(6).fit(stream())

        full = fit(mesh_lib.default_mesh())
        d = str(tmp_path)
        with config.iteration_checkpointing(d):
            with faults.inject("host.hang", after=5):
                res = supervisor.supervise(
                    fit, hosts=4, checkpoint_dir=d,
                    heartbeat_timeout_s=10.0, poll_interval_s=0.01,
                    stall_safety_s=30.0,
                )
        assert res.recoveries == 1
        assert res.events[0].kind == "collectiveHang"
        np.testing.assert_array_equal(res.value.centroids, full.centroids)
        np.testing.assert_array_equal(res.value.weights, full.weights)

    def test_iterate_bounded_hang_resumes_bit_identical(self, tmp_path):
        """The raw iteration runtime under supervision."""
        import jax.numpy as jnp

        def body(carry, epoch):
            new = carry * 0.9 + 1.0
            return new, jnp.max(jnp.abs(new - carry))

        def make_fit(ckpt):
            def fit(mesh):
                return iterate_bounded(
                    body, jnp.zeros(4), max_iter=10, tol=None,
                    checkpoint_dir=ckpt, checkpoint_interval=2,
                    chunk_size=2, job_key="sup-it",
                )

            return fit

        ref = make_fit(None)(None)
        d = str(tmp_path)
        with faults.inject("host.hang", after=3):
            res = supervisor.supervise(
                make_fit(d), hosts=2, checkpoint_dir=d, job_key="sup-it",
                heartbeat_timeout_s=10.0, poll_interval_s=0.01,
                stall_safety_s=30.0,
            )
        assert res.recoveries == 1
        assert res.value.num_epochs == ref.num_epochs == 10
        np.testing.assert_array_equal(
            np.asarray(res.value.carry), np.asarray(ref.carry)
        )
