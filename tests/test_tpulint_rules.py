"""Per-rule unit tests for the tpulint framework: every rule proves a
true positive (known-bad source is flagged), a true negative (the
idiomatic good pattern is not), and — for the file-scanned rules — that a
``# tpulint: disable=<rule> -- reason`` suppression hides the finding
while an unmatched suppression is itself reported."""

import os
import textwrap

import pytest

from flink_ml_tpu.analysis import engine
from flink_ml_tpu.analysis.engine import Project
from flink_ml_tpu.analysis.source import SourceModule, code_only


def _make_tree(tmp_path, files):
    """Write a fixture package tree under tmp_path/flink_ml_tpu and load a
    Project over it. `files` maps package-relative paths to source."""
    for rel, src in files.items():
        path = tmp_path / "flink_ml_tpu" / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return Project.load(root=str(tmp_path), scope=("flink_ml_tpu",))


def _run(tmp_path, files, rule_ids):
    project = _make_tree(tmp_path, files)
    rules = [engine.get_rule(r) for r in rule_ids]
    return engine.run(root=str(tmp_path), rules=rules, project=project)


LAZYJIT_STUB = {
    "utils/lazyjit.py": """
        def lazy_jit(fn, **kw):
            return fn
        def keyed_jit(make, **kw):
            return make
    """,
    "utils/__init__.py": "",
    "__init__.py": "",
}


# ---------------------------------------------------------------------------
# host-sync-leak
# ---------------------------------------------------------------------------

class TestHostSyncLeak:
    def test_true_positive_np_asarray_on_device_value(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax.numpy as jnp
                import numpy as np

                def fit(X):
                    dev = jnp.sum(X, axis=0)
                    return np.asarray(dev)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        assert len(report.findings) == 1
        f = report.findings[0]
        assert f.rule == "host-sync-leak"
        assert f.path == "flink_ml_tpu/models/bad.py"
        assert f.line == 7

    def test_true_positive_item_and_casts(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax.numpy as jnp

                def fit(X):
                    loss = jnp.mean(X)
                    a = loss.item()
                    b = float(loss)
                    return a, b
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        kinds = sorted(f.data[0] for f in report.findings)
        assert kinds == ["cast", "item"]

    def test_true_positive_block_until_ready(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax

                def wait(x):
                    jax.block_until_ready(x)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        assert [f.data[0] for f in report.findings] == ["block_until_ready"]

    def test_true_negative_host_values_and_funnel(self, tmp_path):
        report = _run(tmp_path, {
            "models/good.py": """
                import jax.numpy as jnp
                import numpy as np

                def fit(X, hyper):
                    host = np.asarray(hyper)          # host in, host out
                    n = int(X.shape[0])               # shape metadata
                    dev = jnp.sum(X, axis=0)
                    from ..utils.packing import packed_device_get
                    out = packed_device_get(dev, sync_kind="fit")[0]
                    return np.asarray(out), host, n   # funnel output is host
            """,
            "utils/packing.py": "def packed_device_get(*a, **k):\n    return list(a)\n",
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        assert report.findings == []

    def test_suppression_hides_and_unused_is_reported(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax.numpy as jnp
                import numpy as np

                def fit(X):
                    dev = jnp.sum(X)
                    # tpulint: disable=host-sync-leak -- deliberate: tiny scalar, cold path
                    a = np.asarray(dev)
                    # tpulint: disable=host-sync-leak -- stale annotation
                    b = np.asarray(X.shape)
                    return a, b
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        assert len(report.suppressed) == 1
        assert [f.rule for f in report.findings] == ["unused-suppression"]


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------

class TestRetraceHazard:
    def test_true_positive_raw_jit_and_closure(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax

                def fit(X, lr):
                    def step(c):
                        return c * lr
                    return jax.jit(step)(X)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["retrace-hazard"])
        tags = sorted(f.data[0] for f in report.findings)
        assert tags == ["closure", "raw-jit"]

    def test_true_positive_static_key_fstring(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                from ..utils.lazyjit import lazy_jit

                def make(fn, name):
                    return lazy_jit(fn, static_argnames=f"{name}_arg")
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["retrace-hazard"])
        assert [f.data[0] for f in report.findings] == ["static-key"]

    def test_true_negative_lazyjit_module_level(self, tmp_path):
        report = _run(tmp_path, {
            "models/good.py": """
                from ..utils.lazyjit import keyed_jit, lazy_jit

                def _impl(x):
                    return x + 1

                _kernel = lazy_jit(_impl, static_argnames=("n",))
                _family = keyed_jit(lambda k: _impl)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["retrace-hazard"])
        assert report.findings == []

    def test_suppression(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax

                def _impl(x):
                    return x

                # tpulint: disable=retrace-hazard -- cached by the caller keyed on mesh
                _kernel = jax.jit(_impl)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["retrace-hazard"])
        assert report.findings == []
        assert len(report.suppressed) == 1
        assert report.suppressed[0].data[0] == "raw-jit"


# ---------------------------------------------------------------------------
# donation-after-use
# ---------------------------------------------------------------------------

DONATING_PRELUDE = (
    "import jax\n"
    "\n"
    "def _impl(a, b):\n"
    "    return a + b\n"
    "\n"
    "_step = jax.jit(_impl)\n"
    "_step_donating = jax.jit(_impl, donate_argnums=(0,))\n"
)


class TestDonationAfterUse:
    def test_true_positive_read_after_donate(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": DONATING_PRELUDE + (
                "def fit(carry, other):\n"
                "    out = _step_donating(carry, other)\n"
                "    return out + carry  # carry's buffer was donated\n"
            ),
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["donation-after-use"])
        assert len(report.findings) == 1
        assert report.findings[0].data == ("carry", "_step_donating")

    def test_true_positive_through_gating_alias(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": DONATING_PRELUDE + (
                "def fit(carry, other, ok):\n"
                "    step = _step_donating if ok else _step\n"
                "    out = step(carry, other)\n"
                "    return out + carry\n"
            ),
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["donation-after-use"])
        assert len(report.findings) == 1

    def test_true_negative_pingpong_rebind(self, tmp_path):
        report = _run(tmp_path, {
            "models/good.py": DONATING_PRELUDE + (
                "def fit(carry, other):\n"
                "    carry = _step_donating(carry, other)  # rebound: fine\n"
                "    keep = _step(carry, other)            # borrowing: fine\n"
                "    return carry + keep + other\n"
            ),
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["donation-after-use"])
        assert report.findings == []

    def test_suppression(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": DONATING_PRELUDE + (
                "def fit(carry, other):\n"
                "    out = _step_donating(carry, other)\n"
                "    # tpulint: disable=donation-after-use -- CPU-only debug helper\n"
                "    return out + carry\n"
            ),
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["donation-after-use"])
        assert report.findings == []
        assert len(report.suppressed) == 1


# ---------------------------------------------------------------------------
# sharding-tags
# ---------------------------------------------------------------------------

SNAPSHOT_FIXTURE = {
    "ckpt/snapshot.py": """
        _SPEC_TAGS = ("replicated", "data", "model", "host")

        def _sharding_for(tag, mesh, ndim):
            if tag == "data":
                return "D"
            if tag == "model":
                return "M"
            return "R"

        def save_job_snapshot(path, key, sections, specs=None, **kw):
            pass

        def stage_section(snap, name, mesh=None, specs=None):
            pass
    """,
    "ckpt/__init__.py": "",
    "parallel/mesh.py": """
        def replicated_sharding(mesh):
            pass

        def data_sharding(mesh, ndim=1):
            pass

        def model_sharding(mesh, ndim=1):
            pass
    """,
    "parallel/__init__.py": "",
}


class TestShardingTags:
    def test_true_positive_unknown_tag_at_call_site(self, tmp_path):
        report = _run(tmp_path, {
            **SNAPSHOT_FIXTURE,
            "models/bad.py": """
                from ..ckpt.snapshot import save_job_snapshot

                def checkpoint(path, carry):
                    save_job_snapshot(
                        path, "job", {"model": carry},
                        specs={"model": "fully_sharded"},
                    )
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["sharding-tags"])
        assert len(report.findings) == 1
        assert report.findings[0].data == ("fully_sharded",)
        assert report.findings[0].path == "flink_ml_tpu/models/bad.py"

    def test_true_positive_table_without_constructor(self, tmp_path):
        fixture = dict(SNAPSHOT_FIXTURE)
        fixture["ckpt/snapshot.py"] = fixture["ckpt/snapshot.py"].replace(
            '"replicated", "data", "model", "host"',
            '"replicated", "data", "model", "host", "striped"',
        )
        report = _run(tmp_path, {**fixture, **LAZYJIT_STUB}, ["sharding-tags"])
        tags = {f.data[0] for f in report.findings if f.data}
        assert "striped" in tags

    def test_true_negative_known_tags_and_local_indirection(self, tmp_path):
        report = _run(tmp_path, {
            **SNAPSHOT_FIXTURE,
            "models/good.py": """
                from ..ckpt.snapshot import save_job_snapshot, stage_section

                def checkpoint(path, carry, shard):
                    carry_specs = (
                        ("model", "replicated") if shard else "replicated"
                    )
                    save_job_snapshot(
                        path, "job", {"model": carry},
                        specs={"model": carry_specs, "rng": "host"},
                    )
                    stage_section(None, "model", specs=carry_specs)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["sharding-tags"])
        assert report.findings == []

    def test_suppression(self, tmp_path):
        report = _run(tmp_path, {
            **SNAPSHOT_FIXTURE,
            "models/bad.py": """
                from ..ckpt.snapshot import save_job_snapshot

                def checkpoint(path, carry):
                    save_job_snapshot(
                        path, "job", {"model": carry},
                        # tpulint: disable=sharding-tags -- forward-compat tag, staged by a plugin
                        specs={"model": "fully_sharded"},
                    )
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["sharding-tags"])
        assert report.findings == []
        assert len(report.suppressed) == 1


# ---------------------------------------------------------------------------
# ported accounting gates
# ---------------------------------------------------------------------------

class TestAccountingRules:
    def test_collective_true_positive_and_docstring_negative(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                '''lax.psum(x, axis) in a docstring is fine.'''
                from jax import lax

                # lax.psum(x) in a comment is fine
                def f(x):
                    return lax.psum(x, "data")
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["collective-accounting"])
        assert [(f.line, f.data[0]) for f in report.findings] == [(7, "psum")]

    def test_collective_out_of_scope_dir_is_clean(self, tmp_path):
        report = _run(tmp_path, {
            "parallel/infra.py": """
                from jax import lax

                def f(x):
                    return lax.psum(x, "data")
            """,
            "parallel/__init__.py": "",
            **LAZYJIT_STUB,
        }, ["collective-accounting"])
        assert report.findings == []

    def test_upload_true_positive_and_suppression(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax

                def stage(x):
                    a = jax.device_put(x)
                    # tpulint: disable=upload-accounting -- test-only helper
                    b = jax.device_put(x)
                    return a, b
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["upload-accounting"])
        assert [f.line for f in report.findings] == [5]
        assert [f.line for f in report.suppressed] == [7]


# ---------------------------------------------------------------------------
# ported coverage gates (import-based; synthetic class graph)
# ---------------------------------------------------------------------------

class TestCoverageRules:
    def test_fusion_true_positive_synthetic(self, monkeypatch):
        from flink_ml_tpu.analysis.rules import coverage

        class Silent:  # neither kernel nor declaration
            pass

        monkeypatch.setattr(
            coverage, "_iter_operator_classes", lambda base: iter(())
        )
        monkeypatch.setattr(
            coverage.FusionCoverageRule,
            "finder",
            staticmethod(
                lambda: [("fake.Silent", "no transform_kernel and no explicit "
                          "fusable declaration")]
            ),
        )
        rule = coverage.FusionCoverageRule()
        findings = list(rule.check_project(Project(root=os.getcwd())))
        assert len(findings) == 1
        assert findings[0].rule == "fusion-coverage"
        assert "Silent" in findings[0].message

    def test_fusion_and_checkpoint_true_negative_on_real_tree(self):
        from flink_ml_tpu.analysis.rules.coverage import (
            find_checkpoint_violations,
            find_fusion_violations,
        )

        assert find_fusion_violations() == []
        assert find_checkpoint_violations() == []

    def test_checkpoint_violation_logic_synthetic(self):
        from flink_ml_tpu.analysis.rules import coverage

        # the funnel check reads comment/string-stripped source
        assert not any(
            funnel in code_only('"""mentions run_sgd only in docs."""\n')
            for funnel in coverage.CHECKPOINT_FUNNELS
        )
        assert any(
            funnel in code_only("coeff = run_sgd(params)\n")
            for funnel in coverage.CHECKPOINT_FUNNELS
        )


# ---------------------------------------------------------------------------
# unbounded-queue
# ---------------------------------------------------------------------------

class TestUnboundedQueue:
    def test_true_positive_bare_deque_and_queue(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import queue
                from collections import deque

                pending = deque()
                inbox = queue.Queue()
                lifo = queue.LifoQueue(maxsize=0)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["unbounded-queue"])
        kinds = sorted(f.data[0] for f in report.findings)
        assert kinds == ["deque", "queue", "queue"]
        assert all(f.rule == "unbounded-queue" for f in report.findings)

    def test_true_positive_raw_thread_spawn(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import threading

                def go(fn):
                    t = threading.Thread(target=fn, daemon=True)
                    t.start()
                    return t
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["unbounded-queue"])
        assert [f.data[0] for f in report.findings] == ["thread"]
        assert "flow.pump" in report.findings[0].message

    def test_true_positive_simplequeue_and_from_imports(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                from queue import Queue, SimpleQueue
                from threading import Thread

                a = Queue()
                b = SimpleQueue()  # cannot be bounded at all
                c = Thread(target=print)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["unbounded-queue"])
        kinds = sorted(f.data[0] for f in report.findings)
        assert kinds == ["queue", "queue", "thread"]

    def test_true_negative_bounded_structures(self, tmp_path):
        report = _run(tmp_path, {
            "models/ok.py": """
                import queue
                import collections
                from collections import deque

                ring = deque(maxlen=128)
                ring2 = collections.deque([], 16)
                inbox = queue.Queue(maxsize=8)
                inbox2 = queue.Queue(cap)  # dynamic bound: trusted
                counts = collections.Counter()
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["unbounded-queue"])
        assert report.findings == []

    def test_true_negative_flow_and_prefetch_exempt(self, tmp_path):
        src = """
            import threading
            from collections import deque

            items = deque()
            worker = threading.Thread(target=print)
        """
        report = _run(tmp_path, {
            "flow.py": src,
            "parallel/prefetch.py": src,
            "parallel/__init__.py": "",
            **LAZYJIT_STUB,
        }, ["unbounded-queue"])
        assert report.findings == []

    def test_suppression_with_reason_hides_finding(self, tmp_path):
        report = _run(tmp_path, {
            "models/ok.py": """
                from collections import deque

                # tpulint: disable=unbounded-queue -- drained past depth in the same call
                q = deque()
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["unbounded-queue"])
        assert report.findings == []
        assert len(report.suppressed) == 1

    def test_unused_suppression_is_flagged(self, tmp_path):
        report = _run(tmp_path, {
            "models/ok.py": """
                from collections import deque

                # tpulint: disable=unbounded-queue -- stale
                q = deque(maxlen=4)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["unbounded-queue"])
        assert [f.rule for f in report.findings] == ["unused-suppression"]


# ---------------------------------------------------------------------------
# engine / suppression machinery
# ---------------------------------------------------------------------------

class TestEngine:
    def test_unknown_rule_suppression_is_flagged(self, tmp_path):
        report = _run(tmp_path, {
            "models/odd.py": """
                # tpulint: disable=no-such-rule -- whatever
                x = 1
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        assert [f.rule for f in report.findings] == ["unused-suppression"]
        assert "unknown rule" in report.findings[0].message

    def test_inline_and_preceding_line_suppressions(self):
        mod = SourceModule(
            path="m.py",
            abspath="m.py",
            source="",
        )
        src = (
            "import numpy as np\n"
            "# tpulint: disable=rule-a -- above\n"
            "x = 1\n"
            "y = 2  # tpulint: disable=rule-b -- inline\n"
        )
        from flink_ml_tpu.analysis.source import _parse_suppressions

        sups = _parse_suppressions(src)
        assert [(s.rule, s.line, s.reason) for s in sups] == [
            ("rule-a", 3, "above"),
            ("rule-b", 4, "inline"),
        ]
        del mod

    def test_code_only_blanks_strings_and_comments(self):
        stripped = code_only('x = "lax.psum"  # lax.psum\ny = 2\n')
        assert "psum" not in stripped
        assert "y = 2" in stripped
        # line structure is preserved for true line numbers
        assert stripped.count("\n") == 2

    def test_rule_catalogue_metadata_complete(self):
        for rule in engine.all_rules():
            assert rule.id and rule.title and rule.rationale, rule
            assert rule.scope, rule.id

    def test_findings_filtered_by_only_paths(self, tmp_path):
        project = _make_tree(tmp_path, {
            "models/a.py": "import jax\nf = jax.jit(int)\n",
            "models/b.py": "import jax\ng = jax.jit(int)\n",
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        })
        rules = [engine.get_rule("retrace-hazard")]
        full = engine.run(root=str(tmp_path), rules=rules, project=project)
        assert len(full.findings) == 2
        # reload (Suppression.used state is per-Project)
        project = Project.load(root=str(tmp_path), scope=("flink_ml_tpu",))
        partial = engine.run(
            root=str(tmp_path),
            rules=rules,
            project=project,
            only_paths=["flink_ml_tpu/models/a.py"],
        )
        assert [f.path for f in partial.findings] == ["flink_ml_tpu/models/a.py"]


# ---------------------------------------------------------------------------
# interprocedural host-sync-leak (the v2 call-graph rewiring)
# ---------------------------------------------------------------------------

class TestInterproceduralHostSync:
    def test_laundered_pull_flagged_at_call_site_with_chain(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax.numpy as jnp
                import numpy as np

                def _to_host(x):
                    return np.asarray(x)

                def fit(X):
                    dev = jnp.sum(X, axis=0)
                    return _to_host(dev)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        assert len(report.findings) == 1
        f = report.findings[0]
        assert f.line == 10  # the call site in fit, not the helper
        assert f.data[0] == "np-pull-chain"
        assert "_to_host" in f.message
        assert "models/bad.py:6" in f.message  # the sink's file:line

    def test_cross_module_laundering(self, tmp_path):
        report = _run(tmp_path, {
            "ops/helpers.py": """
                import numpy as np

                def to_host(x):
                    return np.asarray(x)
            """,
            "ops/__init__.py": "",
            "models/bad.py": """
                import jax.numpy as jnp

                from ..ops.helpers import to_host

                def fit(X):
                    dev = jnp.mean(X)
                    return to_host(dev)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        assert [f.path for f in report.findings] == ["flink_ml_tpu/models/bad.py"]
        assert report.findings[0].line == 8
        assert "ops/helpers.py:5" in report.findings[0].message

    def test_helper_returning_device_taints_caller(self, tmp_path):
        """A resolved helper that RETURNS a device value un-launders the
        old per-function blind spot: np.asarray on its result is flagged."""
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax.numpy as jnp
                import numpy as np

                def _make(X):
                    return jnp.sum(X, axis=0)

                def fit(X):
                    dev = _make(X)
                    return np.asarray(dev)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        assert len(report.findings) == 1
        assert report.findings[0].line == 10
        assert report.findings[0].data[0] == "np-pull"

    def test_method_helper_resolved_through_self(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax.numpy as jnp
                import numpy as np

                class Model:
                    def _pull(self, v):
                        return np.asarray(v)

                    def fit(self, X):
                        dev = jnp.sum(X)
                        return self._pull(dev)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        assert len(report.findings) == 1
        assert report.findings[0].line == 11
        assert "Model._pull" in report.findings[0].message

    def test_host_input_through_helper_is_clean(self, tmp_path):
        """The under-approximation survives: callers passing HOST values
        to a syncing helper are not flagged."""
        report = _run(tmp_path, {
            "models/good.py": """
                import numpy as np

                def _to_host(x):
                    return np.asarray(x)

                def fit(rows):
                    return _to_host(rows)  # rows is host data
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        assert report.findings == []

    def test_suppressed_sink_in_helper_covers_callers(self, tmp_path):
        """A suppression-with-reason ON the helper's sink line keeps the
        site out of the summary (callers inherit no finding) while the
        annotated helper still shows in the census."""
        report = _run(tmp_path, {
            "models/good.py": """
                import jax.numpy as jnp
                import numpy as np

                def _probe(x):
                    # tpulint: disable=host-sync-leak -- deliberate: tiny scalar probe
                    return np.asarray(x)

                def fit(X):
                    dev = jnp.sum(X)
                    return _probe(dev)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        assert report.findings == []  # no caller finding, no unused-suppression
        assert len(report.suppressed) == 1
        assert report.suppressed[0].path == "flink_ml_tpu/models/good.py"


# ---------------------------------------------------------------------------
# interprocedural donation-after-use
# ---------------------------------------------------------------------------

class TestInterproceduralDonation:
    def test_wrapper_around_donating_kernel_poisons_caller(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax

                def _impl(a, b):
                    return a + b

                _step_donating = jax.jit(_impl, donate_argnums=(0,))

                def wrapper(carry, other):
                    return _step_donating(carry, other)

                def fit(carry, other):
                    out = wrapper(carry, other)
                    return out + carry
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["donation-after-use"])
        assert len(report.findings) == 1
        f = report.findings[0]
        assert f.line == 14  # `return out + carry` in fit
        assert "wrapper" in f.message and "_step_donating" in f.message

    def test_wrapper_result_use_is_clean(self, tmp_path):
        report = _run(tmp_path, {
            "models/good.py": """
                import jax

                def _impl(a, b):
                    return a + b

                _step_donating = jax.jit(_impl, donate_argnums=(0,))

                def wrapper(carry, other):
                    return _step_donating(carry, other)

                def fit(carry, other):
                    carry = wrapper(carry, other)  # ping-pong rebind
                    return carry
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["donation-after-use"])
        assert report.findings == []


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------

class TestLockOrder:
    def test_true_positive_abba_inversion(self, tmp_path):
        report = _run(tmp_path, {
            "serving.py": """
                import threading

                class S:
                    def __init__(self):
                        self._a = threading.Lock()
                        self._b = threading.Lock()

                    def one(self):
                        with self._a:
                            with self._b:
                                pass

                    def two(self):
                        with self._b:
                            with self._a:
                                pass
            """,
            **LAZYJIT_STUB,
        }, ["lock-order"])
        assert len(report.findings) == 1
        f = report.findings[0]
        assert f.data[0] == "cycle"
        assert "S._a" in f.message and "S._b" in f.message

    def test_true_negative_consistent_order(self, tmp_path):
        report = _run(tmp_path, {
            "serving.py": """
                import threading

                class S:
                    def __init__(self):
                        self._a = threading.Lock()
                        self._b = threading.Lock()

                    def one(self):
                        with self._a:
                            with self._b:
                                pass

                    def two(self):
                        with self._a:
                            with self._b:
                                pass
            """,
            **LAZYJIT_STUB,
        }, ["lock-order"])
        assert report.findings == []

    def test_self_deadlock_through_transitive_call(self, tmp_path):
        report = _run(tmp_path, {
            "serving.py": """
                import threading

                class S:
                    def __init__(self):
                        self._m = threading.Lock()

                    def outer(self):
                        with self._m:
                            self.inner()

                    def inner(self):
                        with self._m:
                            pass
            """,
            **LAZYJIT_STUB,
        }, ["lock-order"])
        assert len(report.findings) == 1
        assert report.findings[0].data[0] == "self-deadlock"
        assert "S.inner" in report.findings[0].message

    def test_reentrant_condition_self_nesting_is_clean(self, tmp_path):
        report = _run(tmp_path, {
            "serving.py": """
                import threading

                class S:
                    def __init__(self):
                        self._cv = threading.Condition()

                    def outer(self):
                        with self._cv:
                            self.inner()

                    def inner(self):
                        with self._cv:
                            pass
            """,
            **LAZYJIT_STUB,
        }, ["lock-order"])
        assert report.findings == []

    def test_cross_module_cycle_via_imported_call(self, tmp_path):
        report = _run(tmp_path, {
            "data/devicecache.py": """
                import threading

                from ..serving import poke

                _cache_lock = threading.Lock()

                def refresh():
                    with _cache_lock:
                        poke()

                def touch():
                    with _cache_lock:
                        pass
            """,
            "data/__init__.py": "",
            "serving.py": """
                import threading

                _serve_lock = threading.Lock()

                def poke():
                    with _serve_lock:
                        pass

                def other():
                    from .data.devicecache import touch
                    with _serve_lock:
                        touch()
            """,
            **LAZYJIT_STUB,
        }, ["lock-order"])
        assert len(report.findings) == 1
        assert report.findings[0].data[0] == "cycle"
        assert "_cache_lock" in report.findings[0].message
        assert "_serve_lock" in report.findings[0].message

    def test_suppression_hides_cycle_finding(self, tmp_path):
        report = _run(tmp_path, {
            "serving.py": """
                import threading

                class S:
                    def __init__(self):
                        self._a = threading.Lock()
                        self._b = threading.Lock()

                    def one(self):
                        with self._a:
                            # tpulint: disable=lock-order -- fixture: order proven safe by external protocol
                            with self._b:
                                pass

                    def two(self):
                        with self._b:
                            with self._a:
                                pass
            """,
            **LAZYJIT_STUB,
        }, ["lock-order"])
        assert report.findings == []
        assert len(report.suppressed) == 1


# ---------------------------------------------------------------------------
# channel-protocol
# ---------------------------------------------------------------------------

FLOW_STUB = {
    "flow.py": """
        import threading

        class BoundedChannel:
            def __init__(self, capacity, policy="block", name="channel"):
                self._cv = threading.Condition()
                self.name = name
            def put(self, item, timeout=None):
                return True
            def get(self, timeout=None):
                return None
            def close(self, error=None):
                pass
            def cancel(self):
                return []
            def __iter__(self):
                return iter(())

        def pump(items, channel, transform=None, watchdog=None):
            pass

        def spawn(fn, name="worker"):
            pass
    """,
}


class TestChannelProtocol:
    def test_worker_never_closing_is_flagged(self, tmp_path):
        report = _run(tmp_path, {
            "serving.py": """
                from . import flow

                class Server:
                    def start(self):
                        self._out = flow.BoundedChannel(4, name="out")
                        self._worker = flow.spawn(self._run, name="d")

                    def _run(self):
                        while True:
                            self._out.put(1)
            """,
            **FLOW_STUB,
            **LAZYJIT_STUB,
        }, ["channel-protocol"])
        assert len(report.findings) == 1
        f = report.findings[0]
        assert f.data == ("worker", "Server._run")
        assert "never closes" in f.message

    def test_worker_without_error_path_is_flagged(self, tmp_path):
        report = _run(tmp_path, {
            "serving.py": """
                from . import flow

                class Server:
                    def start(self):
                        self._out = flow.BoundedChannel(4, name="out")
                        self._worker = flow.spawn(self._run, name="d")

                    def _run(self):
                        for item in (1, 2, 3):
                            self._out.put(item)
                        self._out.close()
            """,
            **FLOW_STUB,
            **LAZYJIT_STUB,
        }, ["channel-protocol"])
        assert len(report.findings) == 1
        assert "happy path" in report.findings[0].message

    def test_close_with_error_worker_is_clean(self, tmp_path):
        report = _run(tmp_path, {
            "serving.py": """
                from . import flow

                class Server:
                    def start(self):
                        self._out = flow.BoundedChannel(4, name="out")
                        self._worker = flow.spawn(self._run, name="d")

                    def _run(self):
                        try:
                            for item in (1, 2, 3):
                                self._out.put(item)
                            self._out.close()
                        except BaseException as e:
                            self._out.close(error=e)
            """,
            **FLOW_STUB,
            **LAZYJIT_STUB,
        }, ["channel-protocol"])
        assert report.findings == []

    def test_worker_closing_via_helper_in_finally_is_clean(self, tmp_path):
        report = _run(tmp_path, {
            "serving.py": """
                from . import flow

                class Server:
                    def start(self):
                        self._out = flow.BoundedChannel(4, name="out")
                        self._worker = flow.spawn(self._run, name="d")

                    def _release(self):
                        self._out.cancel()

                    def _run(self):
                        try:
                            self._out.put(1)
                        finally:
                            self._release()
            """,
            **FLOW_STUB,
            **LAZYJIT_STUB,
        }, ["channel-protocol"])
        assert report.findings == []

    def test_undrained_channel_is_flagged(self, tmp_path):
        report = _run(tmp_path, {
            "serving.py": """
                from . import flow

                def leak():
                    ch = flow.BoundedChannel(2, name="x")
                    ch.put(1)
                    ch.put(2)
            """,
            **FLOW_STUB,
            **LAZYJIT_STUB,
        }, ["channel-protocol"])
        assert len(report.findings) == 1
        assert report.findings[0].data == ("undrained-channel", "ch")

    def test_pumped_iterated_cancelled_channel_is_clean(self, tmp_path):
        report = _run(tmp_path, {
            "serving.py": """
                from . import flow

                def prefetch(items, stage):
                    ch = flow.BoundedChannel(4, name="p")
                    flow.pump(items, ch, transform=stage)
                    try:
                        yield from ch
                    finally:
                        ch.cancel()
            """,
            **FLOW_STUB,
            **LAZYJIT_STUB,
        }, ["channel-protocol"])
        assert report.findings == []

    def test_channel_closed_by_resolved_helper_is_clean(self, tmp_path):
        """param_closes: handing the channel to a helper that cancels it
        satisfies the contract through the call graph."""
        report = _run(tmp_path, {
            "serving.py": """
                from . import flow

                def _teardown(window):
                    window.cancel()

                def serve(stream):
                    window = flow.BoundedChannel(4, name="w")
                    for item in stream:
                        window.put(item)
                    _teardown(window)
            """,
            **FLOW_STUB,
            **LAZYJIT_STUB,
        }, ["channel-protocol"])
        assert report.findings == []

    def test_submit_without_results_is_flagged(self, tmp_path):
        report = _run(tmp_path, {
            "models/client.py": """
                def run(server, batches):
                    for b in batches:
                        server.submit(b)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["channel-protocol"])
        assert len(report.findings) == 1
        assert report.findings[0].data == ("submit-without-results",)

    def test_submit_with_results_loop_is_clean(self, tmp_path):
        report = _run(tmp_path, {
            "models/client.py": """
                def run(server, batches):
                    for b in batches:
                        server.submit(b)
                    server.close()
                    return list(server.results())
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["channel-protocol"])
        assert report.findings == []

    def test_suppression_hides_undrained_channel(self, tmp_path):
        report = _run(tmp_path, {
            "serving.py": """
                from . import flow

                def leak():
                    # tpulint: disable=channel-protocol -- fixture: drained by the caller via attribute
                    ch = flow.BoundedChannel(2, name="x")
                    ch.put(1)
            """,
            **FLOW_STUB,
            **LAZYJIT_STUB,
        }, ["channel-protocol"])
        assert report.findings == []
        assert len(report.suppressed) == 1


# ---------------------------------------------------------------------------
# hot-swap publication idioms (lifecycle.py / the online models)
# ---------------------------------------------------------------------------

class TestHotSwapPublishIdioms:
    """The fixture pair behind docs/model_lifecycle.md's publication
    contract: a torn publish guarded by TWO locks taken in inconsistent
    order is exactly the ABBA inversion `lock-order` exists for, while the
    shipped single-atomic-reference swap (one immutable record, no lock
    nesting) lints clean under both concurrency rules."""

    def test_torn_publish_antipattern_is_flagged(self, tmp_path):
        # anti-pattern: version and arrays live behind separate locks; the
        # trainer writes arrays-then-version, the server reads
        # version-then-arrays — a deadlock-or-torn-read protocol
        report = _run(tmp_path, {
            "models/torn.py": """
                import threading

                class TornModel:
                    def __init__(self):
                        self._version_lock = threading.Lock()
                        self._arrays_lock = threading.Lock()
                        self.version = 0
                        self.arrays = None

                    def publish(self, arrays, version):
                        with self._arrays_lock:
                            self.arrays = arrays
                            with self._version_lock:
                                self.version = version

                    def serve_snapshot(self):
                        with self._version_lock:
                            version = self.version
                            with self._arrays_lock:
                                return version, self.arrays
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["lock-order"])
        assert len(report.findings) == 1
        f = report.findings[0]
        assert f.data[0] == "cycle"
        assert "_arrays_lock" in f.message and "_version_lock" in f.message

    def test_atomic_swap_idiom_is_clean(self, tmp_path):
        # the shipped idiom: ONE immutable (version, arrays) record behind
        # ONE reference; the promote worker pumps through a flow channel
        # it closes on every path
        report = _run(tmp_path, {
            "models/swap.py": """
                from collections import namedtuple

                from .. import flow

                Published = namedtuple("Published", ["version", "arrays"])

                class SwapModel:
                    def __init__(self):
                        self._published = Published(0, None)

                    def publish(self, arrays, version):
                        self._published = Published(version, arrays)

                    def serve_snapshot(self):
                        pub = self._published
                        return pub.version, pub.arrays

                class Promoter:
                    def __init__(self, model, candidates):
                        self.model = model
                        self._in = flow.BoundedChannel(4, name="promote.in")
                        flow.pump(candidates, self._in)
                        self._worker = flow.spawn(self._run, name="promote")

                    def _run(self):
                        try:
                            for version, arrays in self._in:
                                self.model.publish(arrays, version)
                        finally:
                            self._in.cancel()
            """,
            **FLOW_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["lock-order", "channel-protocol"])
        assert report.findings == []


# ---------------------------------------------------------------------------
# tpulint v3: the SPMD mesh/axis verifier (analysis/spmd.py)
# ---------------------------------------------------------------------------

#: minimal mesh + collectives pair the SPMD layer anchors on (same relative
#: paths as the real package: parallel/mesh.py declares the *_AXIS
#: constants, parallel/collectives.py the accounted wrappers)
SPMD_STUB = {
    "parallel/__init__.py": "",
    "parallel/mesh.py": """
        DATA_AXIS = "data"
        MODEL_AXIS = "model"

        def create_mesh(axis_names=(DATA_AXIS,), shape=None, devices=None):
            pass
    """,
    "parallel/collectives.py": """
        from jax import lax

        from .mesh import DATA_AXIS, MODEL_AXIS

        def all_reduce_sum(x, axis_name=DATA_AXIS):
            return lax.psum(x, axis_name)

        def all_reduce_min(x, axis_name=DATA_AXIS):
            return lax.pmin(x, axis_name)

        def all_gather(x, axis_name=DATA_AXIS, axis=0, tiled=True):
            return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)

        def ppermute_ring(x, axis_name=DATA_AXIS, shift=1):
            return lax.ppermute(x, axis_name, [(0, 0)])

        def axis_index(axis_name=DATA_AXIS):
            return lax.axis_index(axis_name)

        def axis_size(axis_name=DATA_AXIS):
            return 1

        def shard_map_over(mesh, in_specs, out_specs, fn=None, check_vma=False):
            return fn
    """,
}


class TestMeshAxis:
    def test_true_positive_unknown_axis_literal(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                from ..parallel.collectives import all_reduce_sum

                def reduce(x):
                    return all_reduce_sum(x, "dta")
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["mesh-axis"])
        assert len(report.findings) == 1
        f = report.findings[0]
        assert f.data == ("unknown-axis", "dta")
        assert f.path == "flink_ml_tpu/models/bad.py" and f.line == 5

    def test_true_positive_constant_bypass_literal(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives

                def reduce(x):
                    return collectives.all_reduce_sum(x, "data")

                def spec():
                    return P("model", None)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["mesh-axis"])
        kinds = sorted((f.data[0], f.data[1]) for f in report.findings)
        assert kinds == [("axis-bypass", "data"), ("axis-bypass", "model")]
        assert "DATA_AXIS" in report.findings[0].message

    def test_true_positive_gather_over_unsharded_axis(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS, MODEL_AXIS

                def build(mesh):
                    def body(x):
                        return collectives.all_gather(x, MODEL_AXIS)
                    return collectives.shard_map_over(
                        mesh, (P(DATA_AXIS),), P(DATA_AXIS), fn=body)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["mesh-axis"])
        assert [f.data[0] for f in report.findings] == ["unsharded-collective"]
        assert report.findings[0].data[2] == "model"

    def test_true_negative_constants_and_known_axes(self, tmp_path):
        report = _run(tmp_path, {
            "models/good.py": """
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.collectives import DATA_AXIS, all_reduce_sum
                from ..parallel.mesh import MODEL_AXIS

                def reduce(x):
                    return all_reduce_sum(x, DATA_AXIS)

                def reduce_feature(x):
                    return collectives.all_reduce_sum(x, axis_name=MODEL_AXIS)

                def spec():
                    return P(DATA_AXIS, MODEL_AXIS)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["mesh-axis"])
        assert report.findings == []

    def test_suppression_hides_and_unused_is_reported(self, tmp_path):
        report = _run(tmp_path, {
            "models/mixed.py": """
                from ..parallel.collectives import all_reduce_sum

                def reduce(x):
                    # tpulint: disable=mesh-axis -- exercising a foreign mesh in a compat shim
                    return all_reduce_sum(x, "data")

                def clean(x):
                    # tpulint: disable=mesh-axis -- nothing here
                    return x
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["mesh-axis"])
        assert [f.rule for f in report.findings] == ["unused-suppression"]
        assert len(report.suppressed) == 1
        assert report.suppressed[0].data[0] == "axis-bypass"


class TestCollectiveDivergence:
    def test_true_positive_axis_index_branch(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS

                def build(mesh):
                    def body(x):
                        i = collectives.axis_index(DATA_AXIS)
                        if i == 0:
                            x = collectives.all_reduce_sum(x, DATA_AXIS)
                        return x
                    return collectives.shard_map_over(
                        mesh, (P(DATA_AXIS),), P(DATA_AXIS), fn=body)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["collective-divergence"])
        assert len(report.findings) == 1
        f = report.findings[0]
        assert f.data[0] == "divergent" and f.data[1] == "all_reduce_sum"
        assert f.line == 10  # the collective, not the branch
        assert "line 9" in f.message  # ... which is named in the message

    def test_true_positive_data_dependent_branch(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax.numpy as jnp
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS

                def build(mesh):
                    def body(x):
                        if jnp.sum(x) > 0:
                            x = collectives.all_gather(x, DATA_AXIS)
                        return x
                    return collectives.shard_map_over(
                        mesh, (P(DATA_AXIS),), P(DATA_AXIS), fn=body)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["collective-divergence"])
        assert [f.data[0] for f in report.findings] == ["divergent"]

    def test_true_negative_uniform_branch_and_masked_contribution(self, tmp_path):
        # branch on a REDUCED (uniform) value, collective outside any
        # branch, contribution masked — the sanctioned shape
        report = _run(tmp_path, {
            "models/good.py": """
                import jax.numpy as jnp
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS

                def build(mesh, epochs):
                    def body(x):
                        total = collectives.all_reduce_sum(jnp.sum(x), DATA_AXIS)
                        if total > 0:
                            scale = 2.0
                        else:
                            scale = 1.0
                        if epochs > 1:
                            scale = scale + 1.0
                        mask = x > 0
                        return collectives.all_reduce_sum(
                            jnp.where(mask, x, 0.0), DATA_AXIS)
                    return collectives.shard_map_over(
                        mesh, (P(DATA_AXIS),), P(), fn=body)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["collective-divergence"])
        assert report.findings == []

    def test_suppression_hides_finding(self, tmp_path):
        report = _run(tmp_path, {
            "models/mixed.py": """
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS

                def build(mesh):
                    def body(x):
                        i = collectives.axis_index(DATA_AXIS)
                        if i == 0:
                            # tpulint: disable=collective-divergence -- single-host probe, documented
                            x = collectives.all_reduce_sum(x, DATA_AXIS)
                        return x
                    return collectives.shard_map_over(
                        mesh, (P(DATA_AXIS),), P(DATA_AXIS), fn=body)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["collective-divergence"])
        assert report.findings == []
        assert len(report.suppressed) == 1


class TestSpecConsistency:
    def test_true_positive_replicated_output_never_reduced(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS

                def build(mesh):
                    def body(x):
                        return x * 2.0
                    return collectives.shard_map_over(
                        mesh, (P(DATA_AXIS),), P(), fn=body)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["spec-consistency"])
        assert len(report.findings) == 1
        f = report.findings[0]
        assert f.data[0] == "unreduced-output"
        assert "data" in f.message

    def test_true_positive_double_reduce(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS

                def build(mesh):
                    def body(x):
                        y = collectives.all_reduce_sum(x, DATA_AXIS)
                        return collectives.all_reduce_sum(y, DATA_AXIS)
                    return collectives.shard_map_over(
                        mesh, (P(DATA_AXIS),), P(), fn=body)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["spec-consistency"])
        assert [f.data[0] for f in report.findings] == ["double-reduce"]
        assert report.findings[0].data[2] == "data"

    def test_true_positive_spec_arity_mismatch(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS

                def build(mesh):
                    def body(x, y):
                        return collectives.all_reduce_sum(x + y, DATA_AXIS)
                    return collectives.shard_map_over(
                        mesh, (P(DATA_AXIS),), P(), fn=body)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["spec-consistency"])
        assert [f.data[0] for f in report.findings] == ["spec-arity"]

    def test_true_negative_reduced_output_and_carry_loop(self, tmp_path):
        # the overlap.py shape in miniature: sharded batch, carry-delayed
        # reduce through a lax.while_loop, replicated result
        report = _run(tmp_path, {
            "models/good.py": """
                import jax.numpy as jnp
                from jax import lax
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS

                def build(mesh):
                    axis = DATA_AXIS

                    def body(X, coeff):
                        def cond(state):
                            c, g, epoch = state
                            return epoch < 3

                        def step(state):
                            c, g, epoch = state
                            c = c - collectives.all_reduce_sum(g, axis)
                            g = X.T @ (X @ c)
                            return (c, g, epoch + 1)

                        init = (coeff, jnp.zeros_like(coeff), 0)
                        c, g, _ = lax.while_loop(cond, step, init)
                        return c - collectives.all_reduce_sum(g, axis)

                    return collectives.shard_map_over(
                        mesh, (P(DATA_AXIS, None), P()), P(), fn=body)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["spec-consistency"])
        assert report.findings == []

    def test_true_positive_2d_model_axis_unreduced(self, tmp_path):
        # the 2D (data x model) trap: a feature-sharded product reduced
        # over DATA only but declared fully replicated — the model-axis
        # variation silently survives into the "replicated" output
        report = _run(tmp_path, {
            "models/bad2d.py": """
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS, MODEL_AXIS

                def build(mesh):
                    def body(X, coeff):
                        grad = collectives.all_reduce_sum(X @ coeff, DATA_AXIS)
                        return grad
                    return collectives.shard_map_over(
                        mesh,
                        (P(DATA_AXIS, MODEL_AXIS), P(MODEL_AXIS)),
                        P(), fn=body)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["spec-consistency"])
        assert len(report.findings) == 1
        f = report.findings[0]
        assert f.data[0] == "unreduced-output"
        assert "model" in f.message

    def test_true_negative_2d_sharded_carry_out(self, tmp_path):
        # the sgd2d program in miniature: activations psum over MODEL,
        # gradient psum over DATA, the updated carry declared P(model) —
        # per-axis bookkeeping must see every axis resolved
        report = _run(tmp_path, {
            "models/good2d.py": """
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS, MODEL_AXIS

                def build(mesh):
                    def body(X, coeff):
                        act = collectives.all_reduce_sum(
                            X @ coeff, MODEL_AXIS)
                        grad = collectives.all_reduce_sum(
                            X.T @ act, DATA_AXIS)
                        loss = collectives.all_reduce_sum(act, DATA_AXIS)
                        return coeff - grad, loss
                    return collectives.shard_map_over(
                        mesh,
                        (P(DATA_AXIS, MODEL_AXIS), P(MODEL_AXIS)),
                        (P(MODEL_AXIS), P()), fn=body)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["spec-consistency"])
        assert report.findings == []

    def test_unknown_specs_suppress_findings(self, tmp_path):
        # unresolvable in_specs: the engine must stay quiet, not guess
        report = _run(tmp_path, {
            "models/opaque.py": """
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives

                def build(mesh, in_specs):
                    def body(x):
                        return x
                    return collectives.shard_map_over(mesh, in_specs, P(), fn=body)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["spec-consistency"])
        assert report.findings == []

    def test_suppression_hides_finding(self, tmp_path):
        report = _run(tmp_path, {
            "models/mixed.py": """
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS

                def build(mesh):
                    def body(x):
                        # tpulint: disable=spec-consistency -- shard 0's value IS the result here, documented
                        return x * 2.0
                    return collectives.shard_map_over(
                        mesh, (P(DATA_AXIS),), P(), fn=body)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["spec-consistency"])
        assert report.findings == []
        assert len(report.suppressed) == 1


class TestPrecisionDeterminism:
    def test_true_positive_downcast_before_reduce(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax.numpy as jnp
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS

                def build(mesh):
                    def body(x):
                        return collectives.all_reduce_sum(
                            x.astype(jnp.bfloat16), DATA_AXIS)
                    return collectives.shard_map_over(
                        mesh, (P(DATA_AXIS),), P(), fn=body)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["precision-determinism"])
        assert len(report.findings) == 1
        f = report.findings[0]
        assert f.data == ("downcast", "all_reduce_sum", "bfloat16")

    def test_true_positive_downcast_through_assignment(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax.numpy as jnp
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS

                def build(mesh):
                    def body(x):
                        small = x.astype(jnp.float16)
                        return collectives.all_reduce_sum(small, DATA_AXIS)
                    return collectives.shard_map_over(
                        mesh, (P(DATA_AXIS),), P(), fn=body)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["precision-determinism"])
        assert [f.data[0] for f in report.findings] == ["downcast"]
        assert report.findings[0].data[2] == "float16"

    def test_true_negative_f32_accumulator_cast(self, tmp_path):
        # the overlap.py tol-check shape: astype(jnp.float32) on the two
        # scalars is a WIDENING (or no-op) cast and must stay legal
        report = _run(tmp_path, {
            "models/good.py": """
                import jax.numpy as jnp
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS

                def build(mesh):
                    def body(x):
                        return collectives.all_reduce_sum(
                            jnp.sum(x).astype(jnp.float32), DATA_AXIS)
                    return collectives.shard_map_over(
                        mesh, (P(DATA_AXIS),), P(), fn=body)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["precision-determinism"])
        assert report.findings == []

    def test_true_positive_manual_ring_fold_outside_sanctioned(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS

                def fold(x, n):
                    acc = x
                    for _ in range(n - 1):
                        x = collectives.ppermute_ring(x, DATA_AXIS)
                        acc = acc + x
                    return acc
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["precision-determinism"])
        assert [f.data[0] for f in report.findings] == ["order-fold"]

    def test_true_negative_fold_inside_collectives_is_sanctioned(self, tmp_path):
        # the same fold INSIDE parallel/collectives.py is the sanctioned
        # replica-order implementation
        stub = dict(SPMD_STUB)
        stub["parallel/collectives.py"] = stub["parallel/collectives.py"] + (
            "\n"
            "def ring_fold(x, n, axis_name=DATA_AXIS):\n"
            "    acc = x\n"
            "    for _ in range(n - 1):\n"
            "        x = ppermute_ring(x, axis_name)\n"
            "        acc = acc + x\n"
            "    return acc\n"
        )
        report = _run(tmp_path, {
            **stub,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["precision-determinism"])
        assert report.findings == []

    def test_suppression_hides_finding(self, tmp_path):
        report = _run(tmp_path, {
            "models/mixed.py": """
                import jax.numpy as jnp
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS

                def build(mesh):
                    def body(x):
                        # tpulint: disable=precision-determinism -- wire-format bf16, error budget documented
                        return collectives.all_reduce_sum(
                            x.astype(jnp.bfloat16), DATA_AXIS)
                    return collectives.shard_map_over(
                        mesh, (P(DATA_AXIS),), P(), fn=body)
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["precision-determinism"])
        assert report.findings == []
        assert len(report.suppressed) == 1


# ---------------------------------------------------------------------------
# resident-program
# ---------------------------------------------------------------------------

class TestResidentProgram:
    def test_true_positive_debug_print_in_jitted_impl(self, tmp_path):
        report = _run(tmp_path, {
            "ops/bad.py": """
                import jax
                import jax.numpy as jnp
                from jax import lax
                from ..utils.lazyjit import lazy_jit

                def _train_impl(X, carry):
                    def step(state):
                        c, e = state
                        jax.debug.print("epoch {e}", e=e)
                        return c + jnp.sum(X), e + 1
                    return lax.while_loop(lambda s: s[1] < 10, step, carry)

                _train = lazy_jit(_train_impl)
            """,
            **LAZYJIT_STUB,
            "ops/__init__.py": "",
        }, ["resident-program"])
        assert len(report.findings) == 1
        assert report.findings[0].rule == "resident-program"
        assert "jax.debug.print" in report.findings[0].message

    def test_true_positive_io_callback_in_loop_body(self, tmp_path):
        report = _run(tmp_path, {
            "ops/bad2.py": """
                import jax.numpy as jnp
                from jax import lax
                from jax.experimental import io_callback

                def fit(X):
                    def body(state):
                        io_callback(print, None, state)
                        return state + 1
                    return lax.while_loop(lambda s: s < 5, body, jnp.asarray(0))
            """,
            **LAZYJIT_STUB,
            "ops/__init__.py": "",
        }, ["resident-program"])
        assert len(report.findings) == 1
        assert "io_callback" in report.findings[0].message

    def test_true_positive_print_in_decorated_kernel(self, tmp_path):
        report = _run(tmp_path, {
            "ops/bad3.py": """
                import jax
                import jax.numpy as jnp

                @jax.jit
                def kernel(x):
                    print("tracing side effect")
                    return jnp.sum(x)
            """,
            **LAZYJIT_STUB,
            "ops/__init__.py": "",
        }, ["resident-program"])
        assert len(report.findings) == 1
        assert "print" in report.findings[0].message

    def test_true_negative_host_functions(self, tmp_path):
        report = _run(tmp_path, {
            "ops/good.py": """
                import jax
                import jax.numpy as jnp
                from ..utils.lazyjit import lazy_jit

                def _kernel_impl(x):
                    return jnp.sum(x) * 2.0

                _kernel = lazy_jit(_kernel_impl)

                def host_driver(x):
                    out = _kernel(x)
                    print("fit done")  # host side: fine
                    jax.debug.print("host-side debug {o}", o=out)
                    return out
            """,
            **LAZYJIT_STUB,
            "ops/__init__.py": "",
        }, ["resident-program"])
        assert report.findings == []

    def test_suppression_hides_and_unused_is_reported(self, tmp_path):
        report = _run(tmp_path, {
            "ops/supp.py": """
                import jax
                import jax.numpy as jnp
                from jax import lax
                from ..utils.lazyjit import lazy_jit

                def _probe_impl(X, carry):
                    def step(state):
                        # tpulint: disable=resident-program -- diagnostic build, stripped before release
                        jax.debug.print("state {s}", s=state)
                        return state + jnp.sum(X)
                    return lax.while_loop(lambda s: s < 3, step, carry)

                _probe = lazy_jit(_probe_impl)
            """,
            **LAZYJIT_STUB,
            "ops/__init__.py": "",
        }, ["resident-program"])
        assert report.findings == []
        assert len(report.suppressed) == 1

        stale = _run(tmp_path, {
            "ops/stale.py": """
                def host_only():
                    # tpulint: disable=resident-program -- nothing resident here
                    print("plain host print")
            """,
            **LAZYJIT_STUB,
            "ops/__init__.py": "",
        }, ["resident-program"])
        assert any(f.rule == "unused-suppression" for f in stale.findings)


# ---------------------------------------------------------------------------
# snapshot-commit
# ---------------------------------------------------------------------------

class TestSnapshotCommit:
    def test_true_positive_raw_savez_and_replace_in_ckpt(self, tmp_path):
        report = _run(tmp_path, {
            "ckpt/rogue.py": """
                import json
                import os

                import numpy as np

                def hand_rolled_commit(target, arrays):
                    tmp = target + ".tmp"
                    np.savez(tmp, **arrays)
                    os.replace(tmp, target)
            """,
            "ckpt/__init__.py": "",
            **LAZYJIT_STUB,
        }, ["snapshot-commit"])
        assert len(report.findings) == 2
        kinds = {f.data[1] for f in report.findings}
        assert kinds == {"np.savez", "os.replace"}
        assert all(f.rule == "snapshot-commit" for f in report.findings)

    def test_true_positive_raw_json_dump_open_w(self, tmp_path):
        report = _run(tmp_path, {
            "ckpt/manifesto.py": """
                import json

                def write_manifest(path, manifest):
                    with open(path, "w") as f:
                        json.dump(manifest, f)
            """,
            "ckpt/__init__.py": "",
            **LAZYJIT_STUB,
        }, ["snapshot-commit"])
        assert len(report.findings) == 2  # the open(w) AND the dump
        assert {f.data[1] for f in report.findings} == {"open(..., 'w')", "json.dump"}

    def test_true_positive_os_rename_in_ckpt(self, tmp_path):
        report = _run(tmp_path, {
            "ckpt/mover.py": """
                import os

                def publish(tmp, target):
                    os.rename(tmp, target)
            """,
            "ckpt/__init__.py": "",
            **LAZYJIT_STUB,
        }, ["snapshot-commit"])
        assert len(report.findings) == 1
        assert report.findings[0].data == ("write", "os.rename")

    def test_true_negative_atomic_commit_machinery(self, tmp_path):
        """The helper itself, inline-lambda payloads, AND named payload
        writers referenced from an atomic_commit call are all sanctioned;
        reads and deletes are not commits."""
        report = _run(tmp_path, {
            "ckpt/coordinator.py": """
                import json
                import os

                import numpy as np

                def atomic_commit(target, write_payload, *, site):
                    tmp = target + ".tmp"
                    write_payload(tmp)
                    os.replace(tmp, target)

                def _dump_json(tmp, manifest):
                    with open(tmp, "w") as f:
                        json.dump(manifest, f)

                def save(target, arrays, manifest):
                    atomic_commit(
                        target, lambda tmp: np.savez(tmp, **arrays), site="s"
                    )
                    atomic_commit(
                        target + ".json",
                        lambda tmp: _dump_json(tmp, manifest),
                        site="s",
                    )

                def gc(path):
                    os.remove(path)  # a delete is not a commit

                def read(path):
                    with open(path, "rb") as f:
                        return f.read()
            """,
            "ckpt/__init__.py": "",
            **LAZYJIT_STUB,
        }, ["snapshot-commit"])
        assert report.findings == []

    def test_true_negative_writes_outside_ckpt(self, tmp_path):
        report = _run(tmp_path, {
            "parallel/iteration.py": """
                import os

                import numpy as np

                def legacy_writer(target, leaves):
                    tmp = target + ".tmp"
                    np.savez(tmp, **leaves)
                    os.replace(tmp, target)
            """,
            "parallel/__init__.py": "",
            **LAZYJIT_STUB,
        }, ["snapshot-commit"])
        assert report.findings == []

    def test_suppression_with_reason(self, tmp_path):
        report = _run(tmp_path, {
            "ckpt/debugdump.py": """
                import numpy as np

                def dump_for_postmortem(path, arrays):
                    # tpulint: disable=snapshot-commit -- postmortem scratch dump, never read back as a checkpoint
                    np.savez(path, **arrays)
            """,
            "ckpt/__init__.py": "",
            **LAZYJIT_STUB,
        }, ["snapshot-commit"])
        assert report.findings == []
        assert len(report.suppressed) == 1


# ---------------------------------------------------------------------------
# untimed-wait
# ---------------------------------------------------------------------------

class TestUntimedWait:
    def test_true_positive_wait_join_and_channel_get(self, tmp_path):
        report = _run(tmp_path, {
            "parallel/runner.py": """
                import threading

                from ..flow import BoundedChannel

                def drive(items):
                    done = threading.Event()
                    ch = BoundedChannel(4)
                    worker = threading.Thread(target=lambda: None)
                    done.wait()
                    worker.join()
                    return ch.get()
            """,
            "parallel/__init__.py": "",
            **LAZYJIT_STUB,
        }, ["untimed-wait"])
        assert len(report.findings) == 3
        assert {f.data[0] for f in report.findings} == {"wait", "join", "get"}
        assert all(f.rule == "untimed-wait" for f in report.findings)

    def test_true_positive_queueish_name_without_constructor(self, tmp_path):
        report = _run(tmp_path, {
            "serving2.py": """
                def pull(window, results_queue):
                    a = window.get()
                    b = results_queue.get()
                    return a, b
            """,
            **LAZYJIT_STUB,
        }, ["untimed-wait"])
        assert len(report.findings) == 2

    def test_true_negative_timeouts_strings_dicts_contextvars(self, tmp_path):
        report = _run(tmp_path, {
            "parallel/clean.py": """
                import contextvars
                import threading

                _current = contextvars.ContextVar("cur", default=None)

                def drive(parts, table, remaining):
                    done = threading.Event()
                    worker = threading.Thread(target=lambda: None)
                    while not done.wait(0.1):
                        pass
                    worker.join(timeout=2.0)
                    sep = ", ".join(parts)
                    ctx = _current.get()
                    return table.get("key"), sep, ctx
            """,
            "parallel/__init__.py": "",
            **LAZYJIT_STUB,
        }, ["untimed-wait"])
        assert report.findings == []

    def test_timeout_none_is_still_untimed(self, tmp_path):
        report = _run(tmp_path, {
            "parallel/nonewait.py": """
                import threading

                def drive():
                    done = threading.Event()
                    done.wait(timeout=None)
            """,
            "parallel/__init__.py": "",
            **LAZYJIT_STUB,
        }, ["untimed-wait"])
        assert len(report.findings) == 1
        assert report.findings[0].data == ("wait",)

    def test_flow_module_is_the_sanctioned_home(self, tmp_path):
        report = _run(tmp_path, {
            "flow.py": """
                import threading

                class Channel:
                    def block(self):
                        cv = threading.Condition()
                        with cv:
                            cv.wait()
            """,
            **LAZYJIT_STUB,
        }, ["untimed-wait"])
        assert report.findings == []

    def test_suppression_with_reason_and_stale_suppression(self, tmp_path):
        report = _run(tmp_path, {
            "serving3.py": """
                from .flow import BoundedChannel

                def pull(entry):
                    window = BoundedChannel(2)
                    if not window.offer(entry):
                        # tpulint: disable=untimed-wait -- offer() returned False, so the window is non-empty and get() cannot block
                        return window.get()
                    return None
            """,
            **LAZYJIT_STUB,
        }, ["untimed-wait"])
        assert report.findings == []
        assert len(report.suppressed) == 1
        stale = _run(tmp_path, {
            "serving4.py": """
                def pull(window):
                    # tpulint: disable=untimed-wait -- nothing here blocks
                    return window.credits()
            """,
            **LAZYJIT_STUB,
        }, ["untimed-wait"])
        assert any(f.rule == "unused-suppression" for f in stale.findings)

# ---------------------------------------------------------------------------
# unledgered-residency
# ---------------------------------------------------------------------------

class TestUnledgeredResidency:
    def test_true_positive_module_level_and_self_attr(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax
                import jax.numpy as jnp

                LUT = jnp.arange(1024)

                class Model:
                    def publish(self, weights):
                        self._weights = jax.device_put(weights)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["unledgered-residency"])
        assert len(report.findings) == 2
        by_binding = {f.data[1]: f.data[0] for f in report.findings}
        assert by_binding == {
            "module-level name": "jax.numpy.arange",
            "self._weights": "jax.device_put",
        }
        assert all(f.rule == "unledgered-residency" for f in report.findings)

    def test_true_positive_bare_import_and_from_jax_numpy(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad2.py": """
                from jax import device_put
                from jax import numpy as jnp

                class Model:
                    def __init__(self, k, d):
                        self._centroids = jnp.zeros((k, d))

                    def publish(self, w):
                        self._w = device_put(w)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["unledgered-residency"])
        creators = sorted(f.data[0] for f in report.findings)
        assert creators == ["jax.device_put", "jax.numpy.zeros"]

    def test_true_negative_transients_funnels_and_host_arrays(self, tmp_path):
        report = _run(tmp_path, {
            "models/good.py": """
                import jax.numpy as jnp
                import numpy as np

                from ..parallel import prefetch
                from ..obs import memledger

                HOST_TABLE = np.zeros(16)  # host memory, not HBM

                def step(X):
                    mask = jnp.ones(X.shape[0])  # function-local transient
                    return X * mask

                class Model:
                    def publish(self, weights):
                        # the accounted funnel ledgers this residency
                        self._weights = prefetch.stage_to_device(
                            weights, category="model"
                        )

                    def adopt(self, arrs):
                        self._arrs = memledger.track(arrs, "model")
            """,
            **LAZYJIT_STUB,
            "parallel/__init__.py": "",
            "obs/__init__.py": "",
            "models/__init__.py": "",
        }, ["unledgered-residency"])
        assert report.findings == []

    def test_suppression_with_reason_hides_finding(self, tmp_path):
        report = _run(tmp_path, {
            "models/tiny.py": """
                import jax.numpy as jnp

                class Probe:
                    def __init__(self):
                        # tpulint: disable=unledgered-residency -- 8-byte sentinel, below any budget's noise floor
                        self._sentinel = jnp.zeros(1)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["unledgered-residency"])
        assert report.findings == []
        assert len(report.suppressed) == 1

    def test_true_positive_raw_device_put_in_paging_helper(self, tmp_path):
        """A model-store-style paging helper that uploads with a raw
        `jax.device_put` bypasses the ledger: the resident model bytes
        never land in `hbm.live.model` (ISSUE 19 satellite)."""
        report = _run(tmp_path, {
            "data/badstore.py": """
                import jax

                class PagingStore:
                    def page_in_raw(self, key, host_arrays):
                        self._resident = jax.device_put(host_arrays)
                        return self._resident
            """,
            **LAZYJIT_STUB,
            "data/__init__.py": "",
        }, ["unledgered-residency"])
        assert len(report.findings) == 1
        assert report.findings[0].data == ("jax.device_put", "self._resident")

    def test_true_negative_model_store_page_in_funnel(self, tmp_path):
        """`ModelStore.page_in` is a sanctioned funnel: every byte it
        makes resident stages through `device_constants()` ->
        `stage_to_device(category="model")`, so bindings fed by it are
        ledgered by construction."""
        from flink_ml_tpu.analysis.rules.memledger import FUNNEL_CALLS

        assert "page_in" in FUNNEL_CALLS  # the ISSUE 19 sanction itself
        report = _run(tmp_path, {
            "data/goodstore.py": """
                import jax

                class Server:
                    def __init__(self, store):
                        self._store = store

                    def pin_tenant(self, key, fallback):
                        # resident + accounted: page_in rides the funnel
                        self._hot_entry = self._store.page_in(key)
                        return self._hot_entry

                    def pin_or_stage(self, key, fallback):
                        # funnel presence exempts the whole binding even
                        # with a raw constructor in the expression
                        self._entry = (
                            self._store.page_in(key)
                            if key in self._store
                            else jax.device_put(fallback)
                        )
                        return self._entry
            """,
            **LAZYJIT_STUB,
            "data/__init__.py": "",
        }, ["unledgered-residency"])
        assert report.findings == []


# ---------------------------------------------------------------------------
# vmap transparency: the fleet kernels wrap resident bodies in jax.vmap
# (fleet.py / ops/optimizer.py `_sgd_fleet_*`) — resident-program and
# spec-consistency must see THROUGH the batching wrapper: vmap changes
# the batch axis, not residency or reduction structure
# ---------------------------------------------------------------------------

class TestResidentProgramVmap:
    def test_true_positive_callback_in_vmapped_kernel(self, tmp_path):
        """`NAME = lazy_jit(jax.vmap(impl))` — the fleet-kernel binding
        idiom — is still ONE resident program; an in-body callback
        re-enters the host every epoch for every member."""
        report = _run(tmp_path, {
            "ops/fleetbad.py": """
                import jax
                import jax.numpy as jnp
                from jax import lax
                from ..utils.lazyjit import lazy_jit

                def _member_fit_impl(X, carry):
                    def step(state):
                        c, e = state
                        jax.debug.print("member epoch {e}", e=e)
                        return c + jnp.sum(X), e + 1
                    return lax.while_loop(lambda s: s[1] < 10, step, carry)

                _fleet_fit = lazy_jit(jax.vmap(_member_fit_impl))
            """,
            **LAZYJIT_STUB,
            "ops/__init__.py": "",
        }, ["resident-program"])
        assert len(report.findings) == 1
        assert "jax.debug.print" in report.findings[0].message

    def test_true_positive_callback_in_vmapped_loop_body(self, tmp_path):
        """A loop body handed to lax.while_loop THROUGH a vmap wrapper is
        resident for every fleet member."""
        report = _run(tmp_path, {
            "ops/fleetbad2.py": """
                import jax
                import jax.numpy as jnp
                from jax import lax
                from jax.experimental import io_callback

                def fleet_fit(X):
                    def cond(s):
                        return s < 5
                    def body(s):
                        io_callback(print, None, s)
                        return s + 1
                    return lax.while_loop(
                        jax.vmap(cond), jax.vmap(body), jnp.zeros(4))
            """,
            **LAZYJIT_STUB,
            "ops/__init__.py": "",
        }, ["resident-program"])
        assert len(report.findings) == 1
        assert "io_callback" in report.findings[0].message

    def test_true_negative_clean_vmapped_kernel(self, tmp_path):
        """A callback-free vmapped kernel with host-side logging OUTSIDE
        the program is the idiomatic fleet pattern — no finding."""
        report = _run(tmp_path, {
            "ops/fleetgood.py": """
                import jax
                import jax.numpy as jnp
                from jax import lax
                from ..utils.lazyjit import lazy_jit

                def _member_fit_impl(X, carry):
                    def step(state):
                        c, e = state
                        return c + jnp.sum(X), e + 1
                    return lax.while_loop(lambda s: s[1] < 10, step, carry)

                _fleet_fit = lazy_jit(jax.vmap(_member_fit_impl))

                def drive(X, carry):
                    out = _fleet_fit(X, carry)
                    print("fleet fit done")  # host side: fine
                    return out
            """,
            **LAZYJIT_STUB,
            "ops/__init__.py": "",
        }, ["resident-program"])
        assert report.findings == []


class TestSpecConsistencyVmap:
    def test_true_positive_unreduced_output_behind_vmap(self, tmp_path):
        """A vmapped shard_map body that never reduces still publishes a
        per-shard partial as the claimed-replicated result."""
        report = _run(tmp_path, {
            "models/fleetbad.py": """
                import jax
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS

                def build(mesh):
                    def member(x):
                        return x * 2.0
                    return collectives.shard_map_over(
                        mesh, (P(DATA_AXIS),), P(), fn=jax.vmap(member))
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["spec-consistency"])
        assert len(report.findings) == 1
        assert report.findings[0].data[0] == "unreduced-output"

    def test_true_negative_reduced_vmapped_body(self, tmp_path):
        report = _run(tmp_path, {
            "models/fleetgood.py": """
                import jax
                from jax.sharding import PartitionSpec as P
                from ..parallel import collectives
                from ..parallel.mesh import DATA_AXIS

                def build(mesh):
                    def member(x):
                        return collectives.all_reduce_sum(x, DATA_AXIS)
                    return collectives.shard_map_over(
                        mesh, (P(DATA_AXIS),), P(), fn=jax.vmap(member))
            """,
            **SPMD_STUB,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["spec-consistency"])
        assert report.findings == []


# ---------------------------------------------------------------------------
# serve-path-trace
# ---------------------------------------------------------------------------

SERVING_ROOT = {
    "serving.py": """
        from .pipeline import PipelineModel

        class MicroBatchServer:
            def __init__(self, model):
                self.model = model

            def _dispatch(self, batch):
                return self.model.transform(batch)
    """,
    "__init__.py": "",
}


class TestServePathTrace:
    def test_true_positive_raw_jit_reachable_via_cha(self, tmp_path):
        report = _run(tmp_path, {
            **SERVING_ROOT,
            "pipeline.py": """
                import jax

                class PipelineModel:
                    def transform(self, batch):
                        fn = jax.jit(lambda x: x * 2.0)
                        return fn(batch)
            """,
            **LAZYJIT_STUB,
        }, ["serve-path-trace"])
        assert len(report.findings) == 1
        f = report.findings[0]
        assert f.path == "flink_ml_tpu/pipeline.py"
        assert f.data[0] == "raw-jit"
        assert "MicroBatchServer._dispatch" in f.message

    def test_true_positive_on_path_wrapper_construction(self, tmp_path):
        report = _run(tmp_path, {
            **SERVING_ROOT,
            "pipeline.py": """
                from .utils.lazyjit import lazy_jit

                class PipelineModel:
                    def transform(self, batch):
                        fn = lazy_jit(lambda x: x * 2.0)
                        return fn(batch)
            """,
            **LAZYJIT_STUB,
        }, ["serve-path-trace"])
        assert len(report.findings) == 1
        assert report.findings[0].data[0] == "on-path-wrapper"

    def test_true_negative_module_level_wrapper(self, tmp_path):
        report = _run(tmp_path, {
            **SERVING_ROOT,
            "pipeline.py": """
                from .utils.lazyjit import lazy_jit

                def _scale(x):
                    return x * 2.0

                _kernel = lazy_jit(_scale)

                class PipelineModel:
                    def transform(self, batch):
                        return _kernel(batch)
            """,
            **LAZYJIT_STUB,
        }, ["serve-path-trace"])
        assert report.findings == []

    def test_true_negative_training_path_raw_jit_unreachable(self, tmp_path):
        report = _run(tmp_path, {
            **SERVING_ROOT,
            "pipeline.py": """
                class PipelineModel:
                    def transform(self, batch):
                        return batch
            """,
            "trainer.py": """
                import jax

                def fit(X):
                    return jax.jit(lambda x: x.sum())(X)
            """,
            **LAZYJIT_STUB,
        }, ["serve-path-trace"])
        assert report.findings == []

    def test_suppression_with_reason_is_the_census_entry(self, tmp_path):
        report = _run(tmp_path, {
            **SERVING_ROOT,
            "pipeline.py": """
                import jax

                class PipelineModel:
                    def transform(self, batch):
                        # tpulint: disable=serve-path-trace -- bank-off fallback, one compile per plan
                        fn = jax.jit(lambda x: x * 2.0)
                        return fn(batch)
            """,
            **LAZYJIT_STUB,
        }, ["serve-path-trace"])
        assert report.findings == []
        assert len(report.suppressed) == 1
        assert report.suppressed[0].rule == "serve-path-trace"
