"""Per-rule unit tests for the tpulint framework: every rule proves a
true positive (known-bad source is flagged), a true negative (the
idiomatic good pattern is not), and — for the file-scanned rules — that a
``# tpulint: disable=<rule> -- reason`` suppression hides the finding
while an unmatched suppression is itself reported."""

import os
import textwrap

import pytest

from flink_ml_tpu.analysis import engine
from flink_ml_tpu.analysis.engine import Project
from flink_ml_tpu.analysis.source import SourceModule, code_only


def _make_tree(tmp_path, files):
    """Write a fixture package tree under tmp_path/flink_ml_tpu and load a
    Project over it. `files` maps package-relative paths to source."""
    for rel, src in files.items():
        path = tmp_path / "flink_ml_tpu" / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return Project.load(root=str(tmp_path), scope=("flink_ml_tpu",))


def _run(tmp_path, files, rule_ids):
    project = _make_tree(tmp_path, files)
    rules = [engine.get_rule(r) for r in rule_ids]
    return engine.run(root=str(tmp_path), rules=rules, project=project)


LAZYJIT_STUB = {
    "utils/lazyjit.py": """
        def lazy_jit(fn, **kw):
            return fn
        def keyed_jit(make, **kw):
            return make
    """,
    "utils/__init__.py": "",
    "__init__.py": "",
}


# ---------------------------------------------------------------------------
# host-sync-leak
# ---------------------------------------------------------------------------

class TestHostSyncLeak:
    def test_true_positive_np_asarray_on_device_value(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax.numpy as jnp
                import numpy as np

                def fit(X):
                    dev = jnp.sum(X, axis=0)
                    return np.asarray(dev)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        assert len(report.findings) == 1
        f = report.findings[0]
        assert f.rule == "host-sync-leak"
        assert f.path == "flink_ml_tpu/models/bad.py"
        assert f.line == 7

    def test_true_positive_item_and_casts(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax.numpy as jnp

                def fit(X):
                    loss = jnp.mean(X)
                    a = loss.item()
                    b = float(loss)
                    return a, b
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        kinds = sorted(f.data[0] for f in report.findings)
        assert kinds == ["cast", "item"]

    def test_true_positive_block_until_ready(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax

                def wait(x):
                    jax.block_until_ready(x)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        assert [f.data[0] for f in report.findings] == ["block_until_ready"]

    def test_true_negative_host_values_and_funnel(self, tmp_path):
        report = _run(tmp_path, {
            "models/good.py": """
                import jax.numpy as jnp
                import numpy as np

                def fit(X, hyper):
                    host = np.asarray(hyper)          # host in, host out
                    n = int(X.shape[0])               # shape metadata
                    dev = jnp.sum(X, axis=0)
                    from ..utils.packing import packed_device_get
                    out = packed_device_get(dev, sync_kind="fit")[0]
                    return np.asarray(out), host, n   # funnel output is host
            """,
            "utils/packing.py": "def packed_device_get(*a, **k):\n    return list(a)\n",
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        assert report.findings == []

    def test_suppression_hides_and_unused_is_reported(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax.numpy as jnp
                import numpy as np

                def fit(X):
                    dev = jnp.sum(X)
                    # tpulint: disable=host-sync-leak -- deliberate: tiny scalar, cold path
                    a = np.asarray(dev)
                    # tpulint: disable=host-sync-leak -- stale annotation
                    b = np.asarray(X.shape)
                    return a, b
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        assert len(report.suppressed) == 1
        assert [f.rule for f in report.findings] == ["unused-suppression"]


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------

class TestRetraceHazard:
    def test_true_positive_raw_jit_and_closure(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax

                def fit(X, lr):
                    def step(c):
                        return c * lr
                    return jax.jit(step)(X)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["retrace-hazard"])
        tags = sorted(f.data[0] for f in report.findings)
        assert tags == ["closure", "raw-jit"]

    def test_true_positive_static_key_fstring(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                from ..utils.lazyjit import lazy_jit

                def make(fn, name):
                    return lazy_jit(fn, static_argnames=f"{name}_arg")
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["retrace-hazard"])
        assert [f.data[0] for f in report.findings] == ["static-key"]

    def test_true_negative_lazyjit_module_level(self, tmp_path):
        report = _run(tmp_path, {
            "models/good.py": """
                from ..utils.lazyjit import keyed_jit, lazy_jit

                def _impl(x):
                    return x + 1

                _kernel = lazy_jit(_impl, static_argnames=("n",))
                _family = keyed_jit(lambda k: _impl)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["retrace-hazard"])
        assert report.findings == []

    def test_suppression(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax

                def _impl(x):
                    return x

                # tpulint: disable=retrace-hazard -- cached by the caller keyed on mesh
                _kernel = jax.jit(_impl)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["retrace-hazard"])
        assert report.findings == []
        assert len(report.suppressed) == 1
        assert report.suppressed[0].data[0] == "raw-jit"


# ---------------------------------------------------------------------------
# donation-after-use
# ---------------------------------------------------------------------------

DONATING_PRELUDE = (
    "import jax\n"
    "\n"
    "def _impl(a, b):\n"
    "    return a + b\n"
    "\n"
    "_step = jax.jit(_impl)\n"
    "_step_donating = jax.jit(_impl, donate_argnums=(0,))\n"
)


class TestDonationAfterUse:
    def test_true_positive_read_after_donate(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": DONATING_PRELUDE + (
                "def fit(carry, other):\n"
                "    out = _step_donating(carry, other)\n"
                "    return out + carry  # carry's buffer was donated\n"
            ),
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["donation-after-use"])
        assert len(report.findings) == 1
        assert report.findings[0].data == ("carry", "_step_donating")

    def test_true_positive_through_gating_alias(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": DONATING_PRELUDE + (
                "def fit(carry, other, ok):\n"
                "    step = _step_donating if ok else _step\n"
                "    out = step(carry, other)\n"
                "    return out + carry\n"
            ),
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["donation-after-use"])
        assert len(report.findings) == 1

    def test_true_negative_pingpong_rebind(self, tmp_path):
        report = _run(tmp_path, {
            "models/good.py": DONATING_PRELUDE + (
                "def fit(carry, other):\n"
                "    carry = _step_donating(carry, other)  # rebound: fine\n"
                "    keep = _step(carry, other)            # borrowing: fine\n"
                "    return carry + keep + other\n"
            ),
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["donation-after-use"])
        assert report.findings == []

    def test_suppression(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": DONATING_PRELUDE + (
                "def fit(carry, other):\n"
                "    out = _step_donating(carry, other)\n"
                "    # tpulint: disable=donation-after-use -- CPU-only debug helper\n"
                "    return out + carry\n"
            ),
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["donation-after-use"])
        assert report.findings == []
        assert len(report.suppressed) == 1


# ---------------------------------------------------------------------------
# sharding-tags
# ---------------------------------------------------------------------------

SNAPSHOT_FIXTURE = {
    "ckpt/snapshot.py": """
        _SPEC_TAGS = ("replicated", "data", "model", "host")

        def _sharding_for(tag, mesh, ndim):
            if tag == "data":
                return "D"
            if tag == "model":
                return "M"
            return "R"

        def save_job_snapshot(path, key, sections, specs=None, **kw):
            pass

        def stage_section(snap, name, mesh=None, specs=None):
            pass
    """,
    "ckpt/__init__.py": "",
    "parallel/mesh.py": """
        def replicated_sharding(mesh):
            pass

        def data_sharding(mesh, ndim=1):
            pass

        def model_sharding(mesh, ndim=1):
            pass
    """,
    "parallel/__init__.py": "",
}


class TestShardingTags:
    def test_true_positive_unknown_tag_at_call_site(self, tmp_path):
        report = _run(tmp_path, {
            **SNAPSHOT_FIXTURE,
            "models/bad.py": """
                from ..ckpt.snapshot import save_job_snapshot

                def checkpoint(path, carry):
                    save_job_snapshot(
                        path, "job", {"model": carry},
                        specs={"model": "fully_sharded"},
                    )
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["sharding-tags"])
        assert len(report.findings) == 1
        assert report.findings[0].data == ("fully_sharded",)
        assert report.findings[0].path == "flink_ml_tpu/models/bad.py"

    def test_true_positive_table_without_constructor(self, tmp_path):
        fixture = dict(SNAPSHOT_FIXTURE)
        fixture["ckpt/snapshot.py"] = fixture["ckpt/snapshot.py"].replace(
            '"replicated", "data", "model", "host"',
            '"replicated", "data", "model", "host", "striped"',
        )
        report = _run(tmp_path, {**fixture, **LAZYJIT_STUB}, ["sharding-tags"])
        tags = {f.data[0] for f in report.findings if f.data}
        assert "striped" in tags

    def test_true_negative_known_tags_and_local_indirection(self, tmp_path):
        report = _run(tmp_path, {
            **SNAPSHOT_FIXTURE,
            "models/good.py": """
                from ..ckpt.snapshot import save_job_snapshot, stage_section

                def checkpoint(path, carry, shard):
                    carry_specs = (
                        ("model", "replicated") if shard else "replicated"
                    )
                    save_job_snapshot(
                        path, "job", {"model": carry},
                        specs={"model": carry_specs, "rng": "host"},
                    )
                    stage_section(None, "model", specs=carry_specs)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["sharding-tags"])
        assert report.findings == []

    def test_suppression(self, tmp_path):
        report = _run(tmp_path, {
            **SNAPSHOT_FIXTURE,
            "models/bad.py": """
                from ..ckpt.snapshot import save_job_snapshot

                def checkpoint(path, carry):
                    save_job_snapshot(
                        path, "job", {"model": carry},
                        # tpulint: disable=sharding-tags -- forward-compat tag, staged by a plugin
                        specs={"model": "fully_sharded"},
                    )
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["sharding-tags"])
        assert report.findings == []
        assert len(report.suppressed) == 1


# ---------------------------------------------------------------------------
# ported accounting gates
# ---------------------------------------------------------------------------

class TestAccountingRules:
    def test_collective_true_positive_and_docstring_negative(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                '''lax.psum(x, axis) in a docstring is fine.'''
                from jax import lax

                # lax.psum(x) in a comment is fine
                def f(x):
                    return lax.psum(x, "data")
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["collective-accounting"])
        assert [(f.line, f.data[0]) for f in report.findings] == [(7, "psum")]

    def test_collective_out_of_scope_dir_is_clean(self, tmp_path):
        report = _run(tmp_path, {
            "parallel/infra.py": """
                from jax import lax

                def f(x):
                    return lax.psum(x, "data")
            """,
            "parallel/__init__.py": "",
            **LAZYJIT_STUB,
        }, ["collective-accounting"])
        assert report.findings == []

    def test_upload_true_positive_and_suppression(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import jax

                def stage(x):
                    a = jax.device_put(x)
                    # tpulint: disable=upload-accounting -- test-only helper
                    b = jax.device_put(x)
                    return a, b
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["upload-accounting"])
        assert [f.line for f in report.findings] == [5]
        assert [f.line for f in report.suppressed] == [7]


# ---------------------------------------------------------------------------
# ported coverage gates (import-based; synthetic class graph)
# ---------------------------------------------------------------------------

class TestCoverageRules:
    def test_fusion_true_positive_synthetic(self, monkeypatch):
        from flink_ml_tpu.analysis.rules import coverage

        class Silent:  # neither kernel nor declaration
            pass

        monkeypatch.setattr(
            coverage, "_iter_operator_classes", lambda base: iter(())
        )
        monkeypatch.setattr(
            coverage.FusionCoverageRule,
            "finder",
            staticmethod(
                lambda: [("fake.Silent", "no transform_kernel and no explicit "
                          "fusable declaration")]
            ),
        )
        rule = coverage.FusionCoverageRule()
        findings = list(rule.check_project(Project(root=os.getcwd())))
        assert len(findings) == 1
        assert findings[0].rule == "fusion-coverage"
        assert "Silent" in findings[0].message

    def test_fusion_and_checkpoint_true_negative_on_real_tree(self):
        from flink_ml_tpu.analysis.rules.coverage import (
            find_checkpoint_violations,
            find_fusion_violations,
        )

        assert find_fusion_violations() == []
        assert find_checkpoint_violations() == []

    def test_checkpoint_violation_logic_synthetic(self):
        from flink_ml_tpu.analysis.rules import coverage

        # the funnel check reads comment/string-stripped source
        assert not any(
            funnel in code_only('"""mentions run_sgd only in docs."""\n')
            for funnel in coverage.CHECKPOINT_FUNNELS
        )
        assert any(
            funnel in code_only("coeff = run_sgd(params)\n")
            for funnel in coverage.CHECKPOINT_FUNNELS
        )


# ---------------------------------------------------------------------------
# unbounded-queue
# ---------------------------------------------------------------------------

class TestUnboundedQueue:
    def test_true_positive_bare_deque_and_queue(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import queue
                from collections import deque

                pending = deque()
                inbox = queue.Queue()
                lifo = queue.LifoQueue(maxsize=0)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["unbounded-queue"])
        kinds = sorted(f.data[0] for f in report.findings)
        assert kinds == ["deque", "queue", "queue"]
        assert all(f.rule == "unbounded-queue" for f in report.findings)

    def test_true_positive_raw_thread_spawn(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                import threading

                def go(fn):
                    t = threading.Thread(target=fn, daemon=True)
                    t.start()
                    return t
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["unbounded-queue"])
        assert [f.data[0] for f in report.findings] == ["thread"]
        assert "flow.pump" in report.findings[0].message

    def test_true_positive_simplequeue_and_from_imports(self, tmp_path):
        report = _run(tmp_path, {
            "models/bad.py": """
                from queue import Queue, SimpleQueue
                from threading import Thread

                a = Queue()
                b = SimpleQueue()  # cannot be bounded at all
                c = Thread(target=print)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["unbounded-queue"])
        kinds = sorted(f.data[0] for f in report.findings)
        assert kinds == ["queue", "queue", "thread"]

    def test_true_negative_bounded_structures(self, tmp_path):
        report = _run(tmp_path, {
            "models/ok.py": """
                import queue
                import collections
                from collections import deque

                ring = deque(maxlen=128)
                ring2 = collections.deque([], 16)
                inbox = queue.Queue(maxsize=8)
                inbox2 = queue.Queue(cap)  # dynamic bound: trusted
                counts = collections.Counter()
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["unbounded-queue"])
        assert report.findings == []

    def test_true_negative_flow_and_prefetch_exempt(self, tmp_path):
        src = """
            import threading
            from collections import deque

            items = deque()
            worker = threading.Thread(target=print)
        """
        report = _run(tmp_path, {
            "flow.py": src,
            "parallel/prefetch.py": src,
            "parallel/__init__.py": "",
            **LAZYJIT_STUB,
        }, ["unbounded-queue"])
        assert report.findings == []

    def test_suppression_with_reason_hides_finding(self, tmp_path):
        report = _run(tmp_path, {
            "models/ok.py": """
                from collections import deque

                # tpulint: disable=unbounded-queue -- drained past depth in the same call
                q = deque()
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["unbounded-queue"])
        assert report.findings == []
        assert len(report.suppressed) == 1

    def test_unused_suppression_is_flagged(self, tmp_path):
        report = _run(tmp_path, {
            "models/ok.py": """
                from collections import deque

                # tpulint: disable=unbounded-queue -- stale
                q = deque(maxlen=4)
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["unbounded-queue"])
        assert [f.rule for f in report.findings] == ["unused-suppression"]


# ---------------------------------------------------------------------------
# engine / suppression machinery
# ---------------------------------------------------------------------------

class TestEngine:
    def test_unknown_rule_suppression_is_flagged(self, tmp_path):
        report = _run(tmp_path, {
            "models/odd.py": """
                # tpulint: disable=no-such-rule -- whatever
                x = 1
            """,
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        }, ["host-sync-leak"])
        assert [f.rule for f in report.findings] == ["unused-suppression"]
        assert "unknown rule" in report.findings[0].message

    def test_inline_and_preceding_line_suppressions(self):
        mod = SourceModule(
            path="m.py",
            abspath="m.py",
            source="",
        )
        src = (
            "import numpy as np\n"
            "# tpulint: disable=rule-a -- above\n"
            "x = 1\n"
            "y = 2  # tpulint: disable=rule-b -- inline\n"
        )
        from flink_ml_tpu.analysis.source import _parse_suppressions

        sups = _parse_suppressions(src)
        assert [(s.rule, s.line, s.reason) for s in sups] == [
            ("rule-a", 3, "above"),
            ("rule-b", 4, "inline"),
        ]
        del mod

    def test_code_only_blanks_strings_and_comments(self):
        stripped = code_only('x = "lax.psum"  # lax.psum\ny = 2\n')
        assert "psum" not in stripped
        assert "y = 2" in stripped
        # line structure is preserved for true line numbers
        assert stripped.count("\n") == 2

    def test_rule_catalogue_metadata_complete(self):
        for rule in engine.all_rules():
            assert rule.id and rule.title and rule.rationale, rule
            assert rule.scope, rule.id

    def test_findings_filtered_by_only_paths(self, tmp_path):
        project = _make_tree(tmp_path, {
            "models/a.py": "import jax\nf = jax.jit(int)\n",
            "models/b.py": "import jax\ng = jax.jit(int)\n",
            **LAZYJIT_STUB,
            "models/__init__.py": "",
        })
        rules = [engine.get_rule("retrace-hazard")]
        full = engine.run(root=str(tmp_path), rules=rules, project=project)
        assert len(full.findings) == 2
        # reload (Suppression.used state is per-Project)
        project = Project.load(root=str(tmp_path), scope=("flink_ml_tpu",))
        partial = engine.run(
            root=str(tmp_path),
            rules=rules,
            project=project,
            only_paths=["flink_ml_tpu/models/a.py"],
        )
        assert [f.path for f in partial.findings] == ["flink_ml_tpu/models/a.py"]
