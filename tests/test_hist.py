"""Streaming histograms (obs/hist.py) — bucket math, percentile
monotonicity, mergeability, concurrent-writer exactness, and the pinned
record cost (< 2µs enabled, < 1µs disabled)."""

import math
import threading
import time

import numpy as np
import pytest

from flink_ml_tpu.obs import hist


@pytest.fixture(autouse=True)
def _clean():
    hist.reset()
    hist.configure(True)
    yield
    hist.reset()
    hist.configure(True)


def test_bucket_bounds_cover_value():
    for v in (1e-9, 0.001, 0.5, 1.0, 1.5, 2.0, 1000.0, 1e12):
        i = hist._bucket_index(v)
        hi = hist.bucket_upper_bound(i)
        lo = 0.0 if i == 0 else hist.bucket_upper_bound(i - 1)
        assert lo <= v <= hi, (v, lo, hi)
    # non-positive and extreme values clamp, never raise
    assert hist._bucket_index(0.0) == 0
    assert hist._bucket_index(-5.0) == 0
    assert hist._bucket_index(1e300) == hist.BUCKETS - 1


def test_percentiles_monotone_and_clamped():
    h = hist.Histogram("t")
    rng = np.random.default_rng(0)
    samples = rng.lognormal(mean=1.0, sigma=2.0, size=5000)
    for s in samples:
        h.record(float(s))
    p50, p90, p99, p999 = (h.percentile(q) for q in (0.5, 0.9, 0.99, 0.999))
    assert p50 <= p90 <= p99 <= p999
    assert h.vmin <= p50 and p999 <= h.vmax
    # log2 buckets: percentile within one bucket width (2x) of the truth
    true_p99 = float(np.quantile(samples, 0.99))
    assert true_p99 / 2 <= p99 <= true_p99 * 2
    assert h.percentile(0.0) >= h.vmin


def test_empty_and_single_sample():
    h = hist.Histogram("t")
    assert h.percentile(0.99) is None
    assert hist.percentiles("absent") is None
    h.record(42.0)
    assert h.percentile(0.5) == pytest.approx(42.0)
    assert h.percentile(0.999) == pytest.approx(42.0)
    d = h.to_dict()
    assert d["count"] == 1 and d["min"] == 42.0 and d["max"] == 42.0


def test_merge_equals_union():
    rng = np.random.default_rng(1)
    a_samples = rng.exponential(5.0, 800)
    b_samples = rng.exponential(50.0, 600)
    a, b, u = hist.Histogram("a"), hist.Histogram("b"), hist.Histogram("u")
    for s in a_samples:
        a.record(float(s))
        u.record(float(s))
    for s in b_samples:
        b.record(float(s))
        u.record(float(s))
    a.merge(b)
    assert a.count == u.count
    assert a.counts == u.counts
    assert a.total == pytest.approx(u.total)
    assert a.vmin == u.vmin and a.vmax == u.vmax
    for q in (0.5, 0.9, 0.99, 0.999):
        assert a.percentile(q) == pytest.approx(u.percentile(q))


def test_dict_roundtrip_merges_off_process():
    h = hist.Histogram("x")
    for v in (1.0, 2.0, 300.0):
        h.record(v)
    rebuilt = hist.Histogram.from_dict(h.to_dict(), "x")
    assert rebuilt.counts == h.counts
    assert rebuilt.percentile(0.5) == h.percentile(0.5)


def test_concurrent_writers_exact_counts():
    """The per-histogram lock means concurrent record() calls never lose
    counts (runs clean under FLINK_ML_TPU_SANITIZE=1 with the suite)."""
    h = hist.get("conc.ms")
    n_threads, per_thread = 8, 2000

    def writer(tid):
        for i in range(per_thread):
            h.record(float(tid * per_thread + i) + 0.5)

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert h.count == n_threads * per_thread
    assert sum(h.counts) == n_threads * per_thread


def test_registry_snapshot_and_reset():
    hist.record("a.ms", 3.0)
    hist.record("a.ms", 5.0)
    hist.record("b.bytes", 1024.0)
    snap = hist.snapshot()
    assert set(snap) == {"a.ms", "b.bytes"}
    assert snap["a.ms"]["count"] == 2
    assert snap["a.ms"]["sum"] == pytest.approx(8.0)
    assert snap["b.bytes"]["buckets"]  # sparse nonzero map present
    import json

    json.dumps(snap)
    hist.reset()
    assert hist.snapshot() == {}


def test_disabled_record_is_noop_and_under_1us():
    hist.configure(False)
    hist.record("gone.ms", 1.0)
    assert hist.snapshot() == {}
    n = 100_000
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n):
            hist.record("gone.ms", 1.0)
        best = min(best, (time.perf_counter() - t0) / n)
    assert best < 1e-6, f"disabled hist record costs {best * 1e9:.0f}ns/sample"


def test_enabled_record_under_2us():
    """ISSUE 12 acceptance: histogram record cost pinned < 2µs/sample in
    the ENABLED path (best-of-3 shields the bound from CI noise)."""
    h = hist.get("pin.ms")
    n = 50_000
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for i in range(n):
            h.record(1.5)
        best = min(best, (time.perf_counter() - t0) / n)
    assert best < 2e-6, f"enabled hist record costs {best * 1e9:.0f}ns/sample"


def test_chunk_wall_histogram_fed_by_drainqueue():
    """The dispatch pipeline feeds iteration.chunkWallMs per drained
    chunk (the chunk-latency distribution of docs/observability.md)."""
    import jax.numpy as jnp

    from flink_ml_tpu.parallel import dispatch

    queue = dispatch.DrainQueue(depth=1)
    for i in range(3):
        packed = jnp.asarray([float(i + 1), 0.5], jnp.float32)
        queue.push(dispatch.InFlight(i, i + 1, None, packed))
    queue.drain_all()
    p = hist.percentiles("iteration.chunkWallMs")
    assert p is not None and p["count"] == 3
