"""Tier-1 gate for scripts/check_collective_accounting.py: no raw lax
collective call in models/ or ops/ may bypass the accounted wrappers in
parallel/collectives.py — the `collective.*` counters (and the BENCH
`collectiveBreakdown`) must stay an exhaustive traffic inventory."""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_collective_accounting",
        os.path.join(REPO, "scripts", "check_collective_accounting.py"),
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_no_raw_collectives_in_models_or_ops():
    checker = _load_checker()
    violations = checker.find_violations()
    assert not violations, (
        "raw lax collectives bypassing the accounted wrappers:\n"
        + "\n".join(f"  {path}:{line}: lax.{prim}" for path, line, prim in violations)
    )


def test_gate_catches_a_planted_violation(tmp_path):
    """The scanner itself works: a planted raw psum (outside a comment or
    string) is reported; the same text inside a docstring is not."""
    checker = _load_checker()
    planted = tmp_path / "models"
    planted.mkdir()
    (planted / "bad.py").write_text(
        '"""lax.psum(x, axis) in a docstring is fine."""\n'
        "from jax import lax\n"
        "# lax.psum(x) in a comment is fine\n"
        "def f(x):\n"
        "    return lax.psum(x, 'data')\n"
    )
    old_root, old_dirs = checker.ROOT, checker.SCANNED_DIRS
    try:
        checker.ROOT = str(tmp_path)
        checker.SCANNED_DIRS = ("models",)
        violations = checker.find_violations()
    finally:
        checker.ROOT, checker.SCANNED_DIRS = old_root, old_dirs
    assert violations == [(os.path.join("models", "bad.py"), 5, "psum")]
