"""linalg value types + BLAS — mirrors BLASTest/DenseVectorTest/
SparseVectorTest in flink-ml-core."""

import numpy as np
import pytest

from flink_ml_tpu.linalg import (
    BLAS,
    DenseMatrix,
    DenseVector,
    SparseVector,
    Vectors,
    VectorWithNorm,
)


def test_dense_vector_basics():
    v = Vectors.dense(1.0, 2.0, 3.0)
    assert v.size() == 3
    assert v.get(1) == 2.0
    assert list(v) == [1.0, 2.0, 3.0]
    v.set(0, 9.0)
    assert v.get(0) == 9.0
    assert v.clone() == v and v.clone() is not v


def test_sparse_vector_sorts_and_checks():
    v = SparseVector(5, [3, 1], [30.0, 10.0])
    assert v.indices.tolist() == [1, 3]
    assert v.values.tolist() == [10.0, 30.0]
    assert v.get(3) == 30.0
    assert v.get(0) == 0.0
    np.testing.assert_array_equal(v.to_array(), [0, 10.0, 0, 30.0, 0])
    with pytest.raises(ValueError):
        SparseVector(2, [0, 5], [1.0, 2.0])
    with pytest.raises(ValueError):
        SparseVector(5, [1, 1], [1.0, 2.0])


def test_dense_sparse_conversion():
    d = Vectors.dense(0.0, 1.0, 0.0, 2.0)
    s = d.to_sparse()
    assert s.indices.tolist() == [1, 3]
    assert s.to_dense() == d


def test_blas_dot():
    d1 = Vectors.dense(1.0, 2.0, 3.0)
    d2 = Vectors.dense(4.0, 5.0, 6.0)
    s1 = Vectors.sparse(3, [0, 2], [1.0, 3.0])
    s2 = Vectors.sparse(3, [1, 2], [5.0, 6.0])
    assert BLAS.dot(d1, d2) == 32.0
    assert BLAS.dot(s1, d2) == 4.0 + 18.0
    assert BLAS.dot(d1, s2) == 10.0 + 18.0
    assert BLAS.dot(s1, s2) == 18.0


def test_blas_axpy():
    y = Vectors.dense(1.0, 1.0, 1.0)
    BLAS.axpy(2.0, Vectors.dense(1.0, 2.0, 3.0), y)
    np.testing.assert_array_equal(y.values, [3.0, 5.0, 7.0])
    y2 = Vectors.dense(0.0, 0.0, 0.0)
    BLAS.axpy(1.0, Vectors.sparse(3, [1], [4.0]), y2)
    np.testing.assert_array_equal(y2.values, [0.0, 4.0, 0.0])
    # k-limited variant (BLAS.java axpy with k)
    y3 = Vectors.dense(0.0, 0.0, 0.0)
    BLAS.axpy(1.0, Vectors.dense(1.0, 2.0, 3.0), y3, k=2)
    np.testing.assert_array_equal(y3.values, [1.0, 2.0, 0.0])


def test_blas_norms_scal_hdot():
    v = Vectors.dense(3.0, -4.0)
    assert BLAS.norm2(v) == 5.0
    assert BLAS.asum(v) == 7.0
    BLAS.scal(2.0, v)
    np.testing.assert_array_equal(v.values, [6.0, -8.0])
    y = Vectors.dense(2.0, 3.0, 4.0)
    BLAS.hdot(Vectors.sparse(3, [0, 2], [10.0, 10.0]), y)
    np.testing.assert_array_equal(y.values, [20.0, 0.0, 40.0])


def test_blas_gemv():
    m = DenseMatrix(np.array([[1.0, 2.0], [3.0, 4.0]]))
    y = Vectors.dense(1.0, 1.0)
    BLAS.gemv(1.0, m, False, Vectors.dense(1.0, 1.0), 0.5, y)
    np.testing.assert_array_equal(y.values, [3.5, 7.5])
    y2 = Vectors.dense(0.0, 0.0)
    BLAS.gemv(1.0, m, True, Vectors.dense(1.0, 0.0), 0.0, y2)
    np.testing.assert_array_equal(y2.values, [1.0, 2.0])


def test_dense_matrix_layouts():
    m = DenseMatrix(2, 3)
    assert m.num_rows == 2 and m.num_cols == 3
    m.set(0, 1, 5.0)
    assert m.get(0, 1) == 5.0
    # column-major flat array like the reference serializers
    m2 = DenseMatrix(2, 2, [1.0, 2.0, 3.0, 4.0])
    assert m2.get(0, 0) == 1.0 and m2.get(1, 0) == 2.0 and m2.get(0, 1) == 3.0


def test_vector_with_norm():
    vn = VectorWithNorm(Vectors.dense(3.0, 4.0))
    assert vn.l2_norm == 5.0
