"""Native sparse (padded-CSR) training for the linear models.

The reference trains sparse rows through dense x sparse BLAS kernels
(flink-ml-core/.../linalg/BLAS.java:69-117); here the batched equivalents
are a masked gather dot and a scatter-add gradient, and the SGD engine
treats features as a pytree so the same while-loop drivers run both
layouts. These tests pin (1) exact agreement with the dense path on the
same data, (2) wide-dimension training/prediction with no densified
matrix anywhere, (3) the feature-sharded (dp x tp) sparse layout on a
2-D mesh.
"""

import numpy as np
import pytest

from flink_ml_tpu.linalg import Vectors
from flink_ml_tpu.table import SparseBatch, Table


def _sparse_problem(n=96, d=30, nnz=5, seed=0):
    rng = np.random.default_rng(seed)
    indices = np.full((n, nnz), -1, np.int32)
    values = np.zeros((n, nnz), np.float64)
    for i in range(n):
        k = rng.integers(1, nnz + 1)
        cols = rng.choice(d, size=k, replace=False)
        cols.sort()
        indices[i, :k] = cols
        values[i, :k] = rng.random(k)
    sb = SparseBatch(d, indices, values)
    truth = rng.random(d) - 0.5
    y = (sb.to_dense() @ truth > 0).astype(np.float64)
    return sb, y


class TestSparseDenseParity:
    @pytest.mark.parametrize(
        "model_cls_name", ["LogisticRegression", "LinearSVC", "LinearRegression"]
    )
    def test_same_coefficients_as_dense(self, model_cls_name):
        from flink_ml_tpu.models.classification.linearsvc import LinearSVC
        from flink_ml_tpu.models.classification.logisticregression import (
            LogisticRegression,
        )
        from flink_ml_tpu.models.regression.linearregression import LinearRegression

        cls = {
            "LogisticRegression": LogisticRegression,
            "LinearSVC": LinearSVC,
            "LinearRegression": LinearRegression,
        }[model_cls_name]
        sb, y = _sparse_problem()
        dense_t = Table({"features": sb.to_dense(), "label": y})
        sparse_t = Table({"features": sb, "label": y})

        def fit(t):
            return cls().set_max_iter(6).set_global_batch_size(32).fit(t).coefficient

        np.testing.assert_allclose(
            np.asarray(fit(sparse_t)), np.asarray(fit(dense_t)), rtol=3e-5, atol=3e-6
        )

    def test_sparse_predictions_match_dense(self):
        from flink_ml_tpu.models.classification.logisticregression import (
            LogisticRegression,
        )

        sb, y = _sparse_problem(seed=3)
        model = (
            LogisticRegression()
            .set_max_iter(5)
            .set_global_batch_size(32)
            .fit(Table({"features": sb, "label": y}))
        )
        out_sparse = model.transform(Table({"features": sb, "label": y}))[0]
        out_dense = model.transform(Table({"features": sb.to_dense(), "label": y}))[0]
        np.testing.assert_allclose(
            np.asarray(out_sparse.column("prediction")),
            np.asarray(out_dense.column("prediction")),
        )
        np.testing.assert_allclose(
            np.asarray(out_sparse.column("rawPrediction")),
            np.asarray(out_dense.column("rawPrediction")),
            rtol=1e-6,
        )

    def test_sparse_vector_rows_train(self):
        """Object columns of SparseVector values batch into SparseBatch and
        take the sparse path end to end."""
        from flink_ml_tpu.models.classification.logisticregression import (
            LogisticRegression,
        )

        vecs = [
            Vectors.sparse(10, [0, 3], [1.0, 2.0]),
            Vectors.sparse(10, [1], [1.5]),
            Vectors.sparse(10, [2, 9], [0.5, 1.0]),
            Vectors.sparse(10, [0, 9], [2.0, 0.1]),
        ]
        t = Table({"features": vecs, "label": [1.0, 0.0, 0.0, 1.0]})
        model = LogisticRegression().set_max_iter(4).fit(t)
        assert model.coefficient.shape == (10,)
        out = model.transform(t)[0]
        assert np.asarray(out.column("prediction")).shape == (4,)


class TestWideSparse:
    DIM = 200_000

    def test_wide_lr_trains_without_densify(self):
        """dim 2e5 x 4096 rows: densified float32 would be ~3.3GB for this
        tiny row count (and 4TB at the benchmark's 10M rows) — the sparse
        path holds only (n, nnz) arrays + the (d,) model."""
        from flink_ml_tpu.models.classification.logisticregression import (
            LogisticRegression,
        )

        rng = np.random.default_rng(1)
        n, nnz = 4096, 8
        indices = rng.integers(0, self.DIM, size=(n, nnz)).astype(np.int32)
        values = rng.random((n, nnz))
        truth_support = rng.choice(self.DIM, 1000, replace=False)
        y = np.isin(indices, truth_support).any(axis=1).astype(np.float64)
        sb = SparseBatch(self.DIM, indices, values)
        t = Table({"features": sb, "label": y})
        model = (
            LogisticRegression().set_max_iter(5).set_global_batch_size(1024).fit(t)
        )
        assert model.coefficient.shape == (self.DIM,)
        assert np.isfinite(model.coefficient).all()
        out = model.transform(t)[0]
        assert np.asarray(out.column("prediction")).shape == (n,)


class TestShardedSparse:
    def test_dp_tp_mesh_matches_single_device(self, mesh_2d):
        """Feature-sharded (model-axis) sparse training on the 4x2 mesh must
        reproduce the single-device coefficients — the Criteo-style TP
        layout of SURVEY §2.3."""
        import jax

        from flink_ml_tpu.ops.losses import SPARSE_BINARY_LOGISTIC_LOSS
        from flink_ml_tpu.ops.optimizer import SGD
        from flink_ml_tpu.parallel import mesh as mesh_lib

        sb, y = _sparse_problem(n=128, d=30, seed=7)
        init = np.zeros(sb.size)
        args = ((sb.indices, sb.values), y, None, SPARSE_BINARY_LOGISTIC_LOSS)

        sharded = SGD(
            max_iter=6, global_batch_size=32, tol=0.0, shard_features=True
        ).optimize(init, *args, mesh=mesh_2d)
        single = SGD(max_iter=6, global_batch_size=32, tol=0.0).optimize(
            init,
            *args,
            mesh=mesh_lib.create_mesh(("data",), devices=jax.devices()[:1]),
        )
        np.testing.assert_allclose(sharded[0], single[0], rtol=3e-5, atol=3e-6)
        assert sharded[2] == single[2] == 6


class TestSparseCheckpointing:
    def test_checkpointed_sparse_fit(self, tmp_path):
        """Sparse + iteration checkpointing trains through the host-driven
        epoch path (review finding: it crashed on the tuple pytree)."""
        from flink_ml_tpu.ops.losses import SPARSE_BINARY_LOGISTIC_LOSS
        from flink_ml_tpu.ops.optimizer import SGD

        sb, y = _sparse_problem(n=64, d=12, seed=11)
        sgd = SGD(
            max_iter=4,
            global_batch_size=32,
            tol=0.0,
            checkpoint_dir=str(tmp_path),
        )
        coeff, loss, epochs = sgd.optimize(
            np.zeros(12), (sb.indices, sb.values), y, None,
            SPARSE_BINARY_LOGISTIC_LOSS,
        )
        assert epochs == 4 and coeff.shape == (12,)
        ref = SGD(max_iter=4, global_batch_size=32, tol=0.0).optimize(
            np.zeros(12), (sb.indices, sb.values), y, None,
            SPARSE_BINARY_LOGISTIC_LOSS,
        )
        np.testing.assert_allclose(coeff, ref[0], rtol=2e-5, atol=2e-6)
