"""Collectives over the virtual 8-device mesh — the analogue of the
reference's AllReduce/Broadcast tests (AllReduceImpl, BroadcastUtils)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from flink_ml_tpu.parallel import collectives as coll
from flink_ml_tpu.parallel import mesh as mesh_lib


def test_mesh_construction(mesh8):
    assert mesh_lib.num_data_shards(mesh8) == 8
    assert mesh8.axis_names == ("data",)


def test_all_reduce_sum(mesh8):
    x = np.arange(8.0, dtype=np.float32)

    fn = coll.shard_map_over(
        mesh8, in_specs=P("data"), out_specs=P("data"),
        fn=lambda v: coll.all_reduce_sum(v) * jnp.ones_like(v),
    )
    out = np.asarray(fn(x))
    np.testing.assert_allclose(out, np.full(8, x.sum()))


def test_all_gather_and_index(mesh8):
    x = np.arange(8.0, dtype=np.float32)

    def body(v):
        gathered = coll.all_gather(v)  # every shard sees all 8 values
        idx = coll.axis_index()
        return (jnp.sum(gathered) + 0 * idx) * jnp.ones_like(v)

    fn = coll.shard_map_over(mesh8, in_specs=P("data"), out_specs=P("data"), fn=body)
    np.testing.assert_allclose(np.asarray(fn(x)), np.full(8, 28.0))


def test_ppermute_ring(mesh8):
    x = np.arange(8.0, dtype=np.float32)
    fn = coll.shard_map_over(
        mesh8, in_specs=P("data"), out_specs=P("data"),
        fn=lambda v: coll.ppermute_ring(v, shift=1),
    )
    out = np.asarray(fn(x))
    # value from shard i lands on shard i+1
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))


def test_reduce_scatter(mesh8):
    x = np.tile(np.arange(8.0, dtype=np.float32), (8, 1)).reshape(64)

    fn = coll.shard_map_over(
        mesh8, in_specs=P("data"), out_specs=P("data"),
        fn=lambda v: coll.reduce_scatter(v),
    )
    out = np.asarray(fn(x))
    np.testing.assert_allclose(out, 8 * np.arange(8.0))


def test_shard_batch_and_padding(mesh8):
    arr = np.arange(10.0)
    dev, n = mesh_lib.shard_batch(mesh8, arr)
    assert n == 10
    assert dev.shape[0] == 16  # padded to multiple of 8
    np.testing.assert_allclose(np.asarray(dev)[:10], arr)


def test_sharded_matmul_auto_psum(mesh8):
    """Sharded-contraction gradient: XLA inserts the psum (the idiomatic
    replacement for AllReduceImpl)."""
    X = np.random.RandomState(0).randn(16, 4).astype(np.float32)
    v = np.random.RandomState(1).randn(16).astype(np.float32)
    Xs = jax.device_put(X, mesh_lib.data_sharding(mesh8, 2))
    vs = jax.device_put(v, mesh_lib.data_sharding(mesh8, 1))
    out = jax.jit(lambda a, b: a.T @ b)(Xs, vs)
    np.testing.assert_allclose(np.asarray(out), X.T @ v, rtol=1e-5)


def test_feature_sharded_sgd_matches_replicated(mesh_2d):
    """TP layout: coefficient sharded over the model axis must train to the
    same result as the replicated layout (the contraction all-reduces are
    numerically equivalent)."""
    import numpy as np
    from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD

    rng = np.random.RandomState(0)
    X = rng.randn(256, 10).astype(np.float32)  # 10 features pad to 2 shards
    y = (X @ np.linspace(1, -1, 10) > 0).astype(np.float32)

    plain = SGD(max_iter=10, global_batch_size=64, tol=0.0)
    c1, _, _ = plain.optimize(np.zeros(10), X, y, None, BINARY_LOGISTIC_LOSS)
    sharded = SGD(max_iter=10, global_batch_size=64, tol=0.0, shard_features=True)
    c2, _, _ = sharded.optimize(np.zeros(10), X, y, None, BINARY_LOGISTIC_LOSS)
    assert c2.shape == (10,)
    np.testing.assert_allclose(c1, c2, rtol=1e-4, atol=1e-5)


def test_host_all_reduce_sum(mesh8):
    """Host-side partials reduce to one replicated sum on device."""
    partials = [np.full(4, float(i), np.float32) for i in range(8)]
    out = coll.host_all_reduce_sum(mesh8, partials)
    np.testing.assert_allclose(np.asarray(out), np.full(4, 28.0))
    assert out.sharding.is_fully_replicated


def test_init_distributed_noop():
    """Single-process bring-up: no coordinator address means no-op (the
    DCN hook must be safe to call unconditionally at startup)."""
    mesh_lib.init_distributed()  # must not raise or touch jax.distributed
    mesh_lib.init_distributed(coordinator_address=None)


def test_feature_sharded_with_regularization(mesh_2d):
    import numpy as np
    from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD

    rng = np.random.RandomState(1)
    X = rng.randn(128, 7).astype(np.float32)
    y = (rng.rand(128) > 0.5).astype(np.float32)
    sharded = SGD(max_iter=5, global_batch_size=64, tol=0.0, shard_features=True,
                  reg=0.1, elastic_net=0.5)
    plain = SGD(max_iter=5, global_batch_size=64, tol=0.0, reg=0.1, elastic_net=0.5)
    c1, _, _ = plain.optimize(np.zeros(7), X, y, None, BINARY_LOGISTIC_LOSS)
    c2, _, _ = sharded.optimize(np.zeros(7), X, y, None, BINARY_LOGISTIC_LOSS)
    np.testing.assert_allclose(c1, c2, rtol=1e-4, atol=1e-5)
