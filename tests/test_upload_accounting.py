"""Tier-1 gate for scripts/check_upload_accounting.py: no raw
`jax.device_put` / `jax.make_array_from_callback` in models/ or ops/ may
bypass the accounted stager in parallel/prefetch.py — the `h2d.*`
counters (and the BENCH `h2dBytes` field) must stay an exhaustive
host→device traffic inventory."""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_upload_accounting",
        os.path.join(REPO, "scripts", "check_upload_accounting.py"),
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_no_raw_uploads_in_models_or_ops():
    checker = _load_checker()
    violations = checker.find_violations()
    assert not violations, (
        "raw host->device transfers bypassing the accounted stager:\n"
        + "\n".join(f"  {path}:{line}: jax.{prim}" for path, line, prim in violations)
    )


def test_gate_catches_a_planted_violation(tmp_path):
    """The scanner itself works: a planted raw device_put (outside a
    comment or string) is reported; the same text inside a docstring is
    not, and the stager's own name does not false-positive."""
    checker = _load_checker()
    planted = tmp_path / "models"
    planted.mkdir()
    (planted / "bad.py").write_text(
        '"""jax.device_put(x) in a docstring is fine."""\n'
        "import jax\n"
        "from flink_ml_tpu.parallel.prefetch import stage_to_device\n"
        "# jax.device_put(x) in a comment is fine\n"
        "def f(x):\n"
        "    y = stage_to_device(x)  # the sanctioned funnel\n"
        "    return jax.device_put(y)\n"
    )
    old_root, old_dirs = checker.ROOT, checker.SCANNED_DIRS
    try:
        checker.ROOT = str(tmp_path)
        checker.SCANNED_DIRS = ("models",)
        violations = checker.find_violations()
    finally:
        checker.ROOT, checker.SCANNED_DIRS = old_root, old_dirs
    assert violations == [(os.path.join("models", "bad.py"), 7, "device_put")]
