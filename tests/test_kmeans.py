"""KMeans battery — mirrors flink-ml-lib KMeansTest.java:34-56: param
defaults, fit+transform on the canonical tiny dataset, save/load,
get/set model data."""

import numpy as np

from flink_ml_tpu.linalg import Vectors
from flink_ml_tpu.models.clustering.kmeans import KMeans, KMeansModel
from flink_ml_tpu.table import Table

# KMeansTest.java DATA: two clusters around (0, 0.x) and (9, 0.x)
DATA = [
    Vectors.dense(0.0, 0.0),
    Vectors.dense(0.0, 0.3),
    Vectors.dense(0.3, 0.0),
    Vectors.dense(9.0, 0.0),
    Vectors.dense(9.0, 0.6),
    Vectors.dense(9.6, 0.0),
]


def _table():
    return Table({"features": DATA})


def test_param_defaults():
    km = KMeans()
    assert km.get_k() == 2
    assert km.get_max_iter() == 20
    assert km.get_init_mode() == "random"
    assert km.get_distance_measure() == "euclidean"
    assert km.get_features_col() == "features"
    assert km.get_prediction_col() == "prediction"


def _groups(table, pred_col="prediction"):
    out = {}
    for row in table.collect():
        out.setdefault(int(row[pred_col]), set()).add(tuple(row["features"].to_array()))
    return sorted(out.values(), key=lambda s: min(s))


def test_fit_and_predict():
    model = KMeans().set_seed(42).set_max_iter(10).fit(_table())
    out = model.transform(_table())[0]
    groups = _groups(out)
    assert groups == [
        {(0.0, 0.0), (0.0, 0.3), (0.3, 0.0)},
        {(9.0, 0.0), (9.0, 0.6), (9.6, 0.0)},
    ]
    # centroids converge to cluster means
    cents = np.sort(model.centroids[:, 0])
    np.testing.assert_allclose(cents, [0.1, 9.2], atol=1e-5)


def test_cosine_distance():
    data = [
        Vectors.dense(1.0, 1.0),
        Vectors.dense(2.0, 2.0),
        Vectors.dense(1.0, -1.0),
        Vectors.dense(3.0, -3.0),
    ]
    model = (
        KMeans().set_distance_measure("cosine").set_seed(0).set_max_iter(10)
    ).fit(Table({"features": data}))
    out = model.transform(Table({"features": data}))[0]
    pred = [int(r["prediction"]) for r in out.collect()]
    assert pred[0] == pred[1] and pred[2] == pred[3] and pred[0] != pred[2]


def test_fewer_points_than_clusters():
    import pytest

    with pytest.raises(ValueError):
        KMeans().set_k(5).fit(Table({"features": DATA[:3]}))


def test_save_load(tmp_path):
    model = KMeans().set_seed(7).fit(_table())
    path = str(tmp_path / "km")
    model.save(path)
    loaded = KMeansModel.load(path)
    np.testing.assert_allclose(loaded.centroids, model.centroids)
    out = loaded.transform(_table())[0]
    assert _groups(out) == _groups(model.transform(_table())[0])


def test_get_set_model_data():
    model = KMeans().set_seed(7).fit(_table())
    data = model.get_model_data()[0]
    other = KMeansModel().set_model_data(data)
    np.testing.assert_allclose(other.centroids, model.centroids)
    np.testing.assert_allclose(other.weights, model.weights)


def test_distributed_fit(mesh8):
    model = KMeans().set_seed(42).set_max_iter(10).fit(_table())
    out = model.transform(_table())[0]
    assert len(_groups(out)) == 2
