"""Checkpoint/resume fault-injection battery — the analogue of the
reference's multi-TM failure ITs (flink-ml-tests/.../BoundedAllRoundCheckpointITCase.java:75-168
with FailingMap forcing restore-from-checkpoint and asserting exactly-once
results). Here failure = killing the host loop mid-training; resume must
produce bit-identical results to an uninterrupted run."""

import numpy as np
import pytest

from flink_ml_tpu import config
from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
from flink_ml_tpu.ops.optimizer import SGD
from flink_ml_tpu.table import Table
from flink_ml_tpu.models.classification.logisticregression import LogisticRegression


def _data(n=500, d=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ np.linspace(1, -1, d) > 0).astype(np.float32)
    return X, y


def test_sgd_checkpoint_resume_exact(tmp_path):
    X, y = _data()
    ckpt = str(tmp_path / "ckpt")

    # uninterrupted run
    full = SGD(max_iter=20, global_batch_size=100, tol=0.0)
    expected, _, _ = full.optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)

    # run to epoch 7, "fail", then resume to completion
    part = SGD(max_iter=7, global_batch_size=100, tol=0.0, checkpoint_dir=ckpt)
    part.optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
    resumed = SGD(max_iter=20, global_batch_size=100, tol=0.0, checkpoint_dir=ckpt)
    got, _, epochs = resumed.optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)

    assert epochs == 20
    np.testing.assert_allclose(got, expected, rtol=1e-6, atol=1e-7)


def test_checkpoint_interval(tmp_path):
    X, y = _data()
    ckpt = str(tmp_path / "ckpt")
    sgd = SGD(max_iter=10, global_batch_size=100, tol=0.0,
              checkpoint_dir=ckpt, checkpoint_interval=4)
    sgd.optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
    from flink_ml_tpu.parallel.iteration import load_iteration_checkpoint
    import jax.numpy as jnp

    carry_like = (jnp.zeros(8), jnp.zeros(8), jnp.asarray(0.0), jnp.asarray(0))
    restored = load_iteration_checkpoint(ckpt, carry_like)
    assert restored is not None
    # last multiple of 4 <= 10
    assert restored[1] == 8


def test_estimator_level_checkpointing(tmp_path):
    """The process-wide config knob routes through LogisticRegression.fit."""
    X, y = _data()
    t = Table({"features": X, "label": y})
    baseline = LogisticRegression().set_max_iter(15).set_global_batch_size(100).set_tol(0.0)
    expected = baseline.fit(t).coefficient

    ckpt = str(tmp_path / "est_ckpt")
    with config.iteration_checkpointing(ckpt):
        # interrupted training: only 5 epochs before the "failure"
        LogisticRegression().set_max_iter(5).set_global_batch_size(100).set_tol(0.0).fit(t)
        # restart: resumes from epoch 5 and finishes the remaining 10
        model = (
            LogisticRegression().set_max_iter(15).set_global_batch_size(100).set_tol(0.0)
        ).fit(t)
    np.testing.assert_allclose(model.coefficient, expected, rtol=1e-6, atol=1e-7)
    assert config.iteration_checkpoint_dir is None  # context restored


def test_corrupt_checkpoint_is_ignored(tmp_path):
    import os

    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    with open(os.path.join(ckpt, "ckpt.npz"), "wb") as f:
        f.write(b"not a checkpoint")
    X, y = _data()
    sgd = SGD(max_iter=3, global_batch_size=100, tol=0.0, checkpoint_dir=ckpt)
    with pytest.raises(Exception):
        sgd.optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
