"""Checkpoint/resume fault-injection battery — the analogue of the
reference's multi-TM failure ITs (flink-ml-tests/.../BoundedAllRoundCheckpointITCase.java:75-168
with FailingMap forcing restore-from-checkpoint and asserting exactly-once
results). Here failure = killing the host loop mid-training; resume must
produce bit-identical results to an uninterrupted run."""

import numpy as np
import pytest

from flink_ml_tpu import config
from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
from flink_ml_tpu.ops.optimizer import SGD
from flink_ml_tpu.table import Table
from flink_ml_tpu.models.classification.logisticregression import LogisticRegression


def _data(n=500, d=8, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    y = (X @ np.linspace(1, -1, d) > 0).astype(np.float32)
    return X, y


def test_sgd_checkpoint_resume_exact(tmp_path):
    X, y = _data()
    ckpt = str(tmp_path / "ckpt")

    # uninterrupted run
    full = SGD(max_iter=20, global_batch_size=100, tol=0.0)
    expected, _, _ = full.optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)

    # run to epoch 7, "fail", then resume to completion
    part = SGD(max_iter=7, global_batch_size=100, tol=0.0, checkpoint_dir=ckpt)
    part.optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
    resumed = SGD(max_iter=20, global_batch_size=100, tol=0.0, checkpoint_dir=ckpt)
    got, _, epochs = resumed.optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)

    assert epochs == 20
    np.testing.assert_allclose(got, expected, rtol=1e-6, atol=1e-7)


def test_checkpoint_interval(tmp_path):
    X, y = _data()
    ckpt = str(tmp_path / "ckpt")
    sgd = SGD(max_iter=10, global_batch_size=100, tol=0.0,
              checkpoint_dir=ckpt, checkpoint_interval=4)
    sgd.optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
    from flink_ml_tpu.parallel.iteration import load_iteration_checkpoint
    import jax.numpy as jnp

    carry_like = (jnp.zeros(8), jnp.zeros(8), jnp.asarray(0.0), jnp.asarray(0))
    restored = load_iteration_checkpoint(ckpt, carry_like)
    assert restored is not None
    # last multiple of 4 <= 10
    assert restored[1] == 8


def test_estimator_level_checkpointing(tmp_path):
    """The process-wide config knob routes through LogisticRegression.fit."""
    X, y = _data()
    t = Table({"features": X, "label": y})
    baseline = LogisticRegression().set_max_iter(15).set_global_batch_size(100).set_tol(0.0)
    expected = baseline.fit(t).coefficient

    ckpt = str(tmp_path / "est_ckpt")
    with config.iteration_checkpointing(ckpt):
        # interrupted training: only 5 epochs before the "failure"
        LogisticRegression().set_max_iter(5).set_global_batch_size(100).set_tol(0.0).fit(t)
        # restart: resumes from epoch 5 and finishes the remaining 10
        model = (
            LogisticRegression().set_max_iter(15).set_global_batch_size(100).set_tol(0.0)
        ).fit(t)
    np.testing.assert_allclose(model.coefficient, expected, rtol=1e-6, atol=1e-7)
    assert config.iteration_checkpoint_dir is None  # context restored


def _replayable_stream(X, y=None, chunk=60):
    """A fresh StreamTable over the same batches — the replayed source an
    unbounded resume needs (the reference replays from the checkpointed
    source offset; here the offset is the global-batch count)."""
    from flink_ml_tpu.table import StreamTable

    batches = []
    for i in range(0, X.shape[0], chunk):
        cols = {"features": X[i : i + chunk]}
        if y is not None:
            cols["label"] = y[i : i + chunk]
        batches.append(Table(cols))
    return StreamTable.from_batches(batches)


def test_online_lr_checkpoint_resume(tmp_path):
    """Kill OnlineLogisticRegression mid-stream; resume reproduces the
    uninterrupted run exactly (model, FTRL z/n state, version counter,
    stream position all restored — Checkpoints.java:43-143 analogue)."""
    from flink_ml_tpu.linalg import DenseVector
    from flink_ml_tpu.models.classification.onlinelogisticregression import (
        OnlineLogisticRegression,
    )

    X, y = _data(n=600, d=8, seed=1)
    init = Table({"coefficient": [DenseVector(np.zeros(8))]})
    est = lambda: (  # noqa: E731
        OnlineLogisticRegression()
        .set_global_batch_size(100)
        .set_reg(0.1)
        .set_elastic_net(0.5)
    )

    full = est().set_initial_model_data(init).fit(_replayable_stream(X, y))
    full.process_updates()
    assert full.model_version == 6

    ckpt = str(tmp_path / "online_lr")
    with config.iteration_checkpointing(ckpt):
        # interrupted: only 3 of 6 global batches before the "failure"
        part = est().set_initial_model_data(init).fit(_replayable_stream(X, y))
        part.process_updates(max_batches=3)
        assert part.model_version == 3
        # restart against the replayed source: skips the folded prefix
        res = est().set_initial_model_data(init).fit(_replayable_stream(X, y))
        res.process_updates()
    assert res.model_version == 6
    np.testing.assert_allclose(res.coefficient, full.coefficient, rtol=0, atol=0)


def test_online_lr_resume_republishes_checkpoint(tmp_path):
    """A resumed model reaches the checkpointed version immediately, before
    consuming any live batch (the serving side never regresses)."""
    from flink_ml_tpu.linalg import DenseVector
    from flink_ml_tpu.models.classification.onlinelogisticregression import (
        OnlineLogisticRegression,
    )

    X, y = _data(n=600, d=8, seed=2)
    init = Table({"coefficient": [DenseVector(np.zeros(8))]})
    ckpt = str(tmp_path / "online_lr2")
    with config.iteration_checkpointing(ckpt):
        part = (
            OnlineLogisticRegression()
            .set_global_batch_size(100)
            .set_initial_model_data(init)
            .fit(_replayable_stream(X, y))
        )
        part.process_updates(max_batches=4)
        res = (
            OnlineLogisticRegression()
            .set_global_batch_size(100)
            .set_initial_model_data(init)
            .fit(_replayable_stream(X, y))
        )
        res.process_updates(max_batches=1)  # the republished checkpoint
    assert res.model_version == 4
    np.testing.assert_allclose(res.coefficient, part.coefficient, rtol=0, atol=0)


def test_online_kmeans_checkpoint_resume(tmp_path):
    from flink_ml_tpu.models.clustering.onlinekmeans import (
        OnlineKMeans,
        generate_random_model_data,
    )

    rng = np.random.RandomState(7)
    X = np.concatenate(
        [rng.randn(300, 4) + 3.0, rng.randn(300, 4) - 3.0]
    ).astype(np.float64)
    rng.shuffle(X)
    init = generate_random_model_data(k=2, dim=4, weight=1.0, seed=0)
    est = lambda: OnlineKMeans().set_global_batch_size(150).set_decay_factor(0.5)  # noqa: E731

    full = est().set_initial_model_data(init).fit(_replayable_stream(X, chunk=90))
    full.process_updates()
    assert full.model_version == 4

    ckpt = str(tmp_path / "online_km")
    with config.iteration_checkpointing(ckpt):
        part = est().set_initial_model_data(init).fit(_replayable_stream(X, chunk=90))
        part.process_updates(max_batches=2)
        res = est().set_initial_model_data(init).fit(_replayable_stream(X, chunk=90))
        res.process_updates()
    assert res.model_version == 4
    np.testing.assert_allclose(res.centroids, full.centroids, rtol=0, atol=0)
    np.testing.assert_allclose(res.weights, full.weights, rtol=0, atol=0)


def test_job_key_namespacing_prevents_cross_restore(tmp_path):
    """Two jobs with IDENTICAL carry structure (same k and d) but different
    hyper-parameters sharing one checkpoint dir must not cross-restore —
    the param-hash job key namespaces the checkpoint files (ADVICE round 5:
    the structural guard alone cannot tell these jobs apart)."""
    from flink_ml_tpu.models.clustering.onlinekmeans import (
        OnlineKMeans,
        generate_random_model_data,
    )

    rng = np.random.RandomState(11)
    X = rng.randn(400, 3).astype(np.float64)
    init = generate_random_model_data(k=2, dim=3, weight=1.0, seed=0)

    # uninterrupted reference run of job B (decay 0.9)
    full_b = (
        OnlineKMeans().set_global_batch_size(100).set_decay_factor(0.9)
        .set_initial_model_data(init).fit(_replayable_stream(X, chunk=50))
    )
    full_b.process_updates()

    ckpt = str(tmp_path / "shared")
    with config.iteration_checkpointing(ckpt):
        # job A (decay 0.1) stops mid-stream, leaving a checkpoint behind
        a = (
            OnlineKMeans().set_global_batch_size(100).set_decay_factor(0.1)
            .set_initial_model_data(init).fit(_replayable_stream(X, chunk=50))
        )
        a.process_updates(max_batches=2)
        assert a.model_version == 2
        # job B shares the dir but must start from scratch, not from A
        b = (
            OnlineKMeans().set_global_batch_size(100).set_decay_factor(0.9)
            .set_initial_model_data(init).fit(_replayable_stream(X, chunk=50))
        )
        b.process_updates()
    assert b.model_version == 4
    np.testing.assert_allclose(b.centroids, full_b.centroids, rtol=0, atol=0)


def test_checkpoint_job_key_stability():
    from flink_ml_tpu.models.clustering.onlinekmeans import OnlineKMeans
    from flink_ml_tpu.parallel.iteration import checkpoint_job_key

    a = OnlineKMeans().set_decay_factor(0.5)
    same = OnlineKMeans().set_decay_factor(0.5)
    other = OnlineKMeans().set_decay_factor(0.9)
    assert checkpoint_job_key(a) == checkpoint_job_key(same)
    assert checkpoint_job_key(a) != checkpoint_job_key(other)
    assert checkpoint_job_key(a).startswith("OnlineKMeans-")
    # termination-schedule params are excluded: raising maxIter to resume
    # an interrupted bounded run maps to the SAME job
    lr5 = LogisticRegression().set_max_iter(5)
    lr20 = LogisticRegression().set_max_iter(20)
    assert checkpoint_job_key(lr5) == checkpoint_job_key(lr20)


def test_unbounded_explicit_interval_wins_over_config(tmp_path):
    """An explicit checkpoint_interval is honored even when the directory
    comes from the process-wide config (previously the config interval
    silently won)."""
    import os

    from flink_ml_tpu.parallel.iteration import iterate_unbounded

    ckpt = str(tmp_path / "interval")
    with config.iteration_checkpointing(ckpt, interval=1):
        versions_seen = []
        for version, state in iterate_unbounded(
            iter([1.0, 2.0, 3.0]),
            lambda s, b: s + b,
            0.0,
            checkpoint_interval=5,  # larger than the stream: never snapshots
            job_key="job-x",
        ):
            versions_seen.append(version)
            # interval=5 means no checkpoint may appear at versions 1..3
            assert not os.listdir(ckpt) if os.path.isdir(ckpt) else True
    assert versions_seen == [1, 2, 3]


def test_corrupt_checkpoint_is_ignored(tmp_path):
    import os

    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    with open(os.path.join(ckpt, "ckpt.npz"), "wb") as f:
        f.write(b"not a checkpoint")
    X, y = _data()
    sgd = SGD(max_iter=3, global_batch_size=100, tol=0.0, checkpoint_dir=ckpt)
    with pytest.raises(Exception):
        sgd.optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
