"""Out-of-core training through the native spillable data cache.

The reference replays cached data into bounded iterations
(ReplayOperator.java:125-246) backed by the spillable DataCacheWriter
(datacache/nonkeyed/). Here: an Estimator fed a StreamTable caches the one
pass and replays per epoch (SGD.optimize_stream, KMeans._fit_stream), with
only one batch resident on device — the larger-than-memory story.
"""

import numpy as np
import pytest

from flink_ml_tpu import config
from flink_ml_tpu.models.classification.logisticregression import LogisticRegression
from flink_ml_tpu.models.clustering.kmeans import KMeans
from flink_ml_tpu.models.regression.linearregression import LinearRegression
from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
from flink_ml_tpu.ops.optimizer import SGD
from flink_ml_tpu.table import StreamTable, Table


def _make_data(n=512, d=7, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    truth = rng.standard_normal(d).astype(np.float32)
    y = (X @ truth > 0).astype(np.float32)
    return X, y


def _chunked_stream(X, y, chunk, weight=None):
    batches = []
    for i in range(0, X.shape[0], chunk):
        cols = {"features": X[i : i + chunk], "label": y[i : i + chunk]}
        if weight is not None:
            cols["weight"] = weight[i : i + chunk]
        batches.append(Table(cols))
    return StreamTable.from_batches(batches)


class TestStreamSGD:
    def test_stream_fit_matches_in_memory(self, mesh8):
        """LR fitted from a StreamTable == LR fitted from the concatenated
        Table (identical batch schedule through the cache)."""
        X, y = _make_data()
        lr = lambda: LogisticRegression().set_max_iter(15).set_global_batch_size(100)  # noqa: E731
        in_mem = lr().fit(Table({"features": X, "label": y}))
        # chunk size 96 deliberately misaligned with batch size 100
        streamed = lr().fit(_chunked_stream(X, y, chunk=96))
        np.testing.assert_allclose(
            streamed.coefficient, in_mem.coefficient, rtol=1e-6, atol=1e-7
        )

    def test_stream_fit_with_weights(self, mesh8):
        X, y = _make_data(seed=3)
        w = np.random.default_rng(4).random(X.shape[0]).astype(np.float32)
        table = Table({"features": X, "label": y, "weight": w})
        est = lambda: (  # noqa: E731
            LogisticRegression()
            .set_max_iter(10)
            .set_global_batch_size(128)
            .set_weight_col("weight")
        )
        in_mem = est().fit(table)
        streamed = est().fit(_chunked_stream(X, y, chunk=200, weight=w))
        np.testing.assert_allclose(
            streamed.coefficient, in_mem.coefficient, rtol=1e-6, atol=1e-7
        )

    def test_linear_regression_stream(self, mesh8):
        rng = np.random.default_rng(5)
        X = rng.standard_normal((300, 4)).astype(np.float32)
        y = (X @ np.asarray([1.0, -2.0, 0.5, 3.0], np.float32)).astype(np.float32)
        est = lambda: LinearRegression().set_max_iter(30).set_global_batch_size(64)  # noqa: E731
        in_mem = est().fit(Table({"features": X, "label": y}))
        streamed = est().fit(
            _chunked_stream(X, y, chunk=77)
        )
        np.testing.assert_allclose(
            streamed.coefficient, in_mem.coefficient, rtol=1e-6, atol=1e-7
        )

    def test_forced_spill_during_training(self, mesh8, tmp_path):
        """A memory budget far below the dataset size forces disk spill;
        training still matches the in-memory fit."""
        X, y = _make_data(n=2048, d=16, seed=6)
        sgd = SGD(max_iter=12, learning_rate=0.1, global_batch_size=256, tol=0.0)
        chunks = [(X[i : i + 256], y[i : i + 256], None) for i in range(0, 2048, 256)]
        coeff, _, epochs, stats = sgd.optimize_stream(
            None,
            iter(chunks),
            BINARY_LOGISTIC_LOSS,
            memory_budget_bytes=4096,  # << dataset (2048*16*4 bytes)
            spill_dir=str(tmp_path),
        )
        assert epochs == 12
        assert stats["spilledSegments"] > 0, stats
        ref, _, _ = SGD(
            max_iter=12, learning_rate=0.1, global_batch_size=256, tol=0.0
        ).optimize(np.zeros(16, np.float32), X, y, None, BINARY_LOGISTIC_LOSS)
        np.testing.assert_allclose(coeff, ref, rtol=1e-6, atol=1e-7)

    def test_prefetch_overlaps_cache_reads_with_compute(self, mesh8, monkeypatch):
        """Multi-batch stream epochs must NOT pay cache-read + H2D serially
        after compute (VERDICT r2 weak #5). Instrumented with known delays:
        each epoch 'computes' for 100ms while the next batch's three segment
        reads cost 90ms — overlapped wall-clock stays near max(100, 90) per
        epoch, serialized would be near the 190ms sum."""
        import time

        from flink_ml_tpu.native.datacache import DataCache
        from flink_ml_tpu.ops import optimizer as opt

        X, y = _make_data(n=256, d=4, seed=9)
        chunks = [(X[i : i + 64], y[i : i + 64], None) for i in range(0, 256, 64)]

        # warm the jit cache (same shapes) so the timed run has no compiles;
        # the SECOND post-compile run's wall-clock is the machine-load
        # estimate for the bound below (the first includes XLA compile on a
        # cold cache, which would widen the bound past the serialized wall
        # time and make the regression assertion vacuous)
        SGD(max_iter=8, global_batch_size=64, tol=0.0).optimize_stream(
            None, iter(chunks), BINARY_LOGISTIC_LOSS
        )
        t0 = time.perf_counter()
        SGD(max_iter=8, global_batch_size=64, tol=0.0).optimize_stream(
            None, iter(chunks), BINARY_LOGISTIC_LOSS
        )
        warm_wall = time.perf_counter() - t0

        real_read = DataCache.read_array
        real_epoch = opt._stream_epoch

        def slow_read(self, seg):
            time.sleep(0.03)
            return real_read(self, seg)

        def slow_epoch(*args, **kwargs):
            out = real_epoch(*args, **kwargs)
            jax.block_until_ready(out[1])
            time.sleep(0.10)
            return out

        import jax

        monkeypatch.setattr(DataCache, "read_array", slow_read)
        monkeypatch.setattr(opt, "_stream_epoch", slow_epoch)

        sgd = SGD(max_iter=8, global_batch_size=64, tol=0.0)
        t0 = time.perf_counter()
        _, _, epochs, _ = sgd.optimize_stream(None, iter(chunks), BINARY_LOGISTIC_LOSS)
        wall = time.perf_counter() - t0
        assert epochs == 8
        # serialized sleeps alone: 8 * (0.09 + 0.10) = 1.52s (+ overhead);
        # overlapped: ~8 * 0.10 + first read = ~0.99s (+ overhead). Bound =
        # overlapped floor + margin that scales with measured machine load
        # (warm_wall = the same job with no injected sleeps), so a slow CI
        # host widens the allowance while a serialized run still trips it.
        bound = 1.30 + 2.0 * warm_wall
        assert wall < bound, (
            f"stream epochs appear serialized: {wall:.2f}s "
            f"(bound {bound:.2f}s, warm overhead {warm_wall:.2f}s)"
        )

    def test_binomial_validation_per_chunk(self, mesh8):
        X, y = _make_data(n=64)
        y = y.copy()
        y[40] = 3.0  # bad label in the second chunk
        with pytest.raises(ValueError, match="binomial"):
            LogisticRegression().set_max_iter(2).set_global_batch_size(32).fit(
                _chunked_stream(X, y, chunk=32)
            )

    def test_empty_stream_raises(self, mesh8):
        with pytest.raises(ValueError, match="empty stream"):
            SGD().optimize_stream(None, iter([]), BINARY_LOGISTIC_LOSS)

    def test_shard_features_rejected(self, mesh8):
        with pytest.raises(NotImplementedError):
            SGD(shard_features=True).optimize_stream(
                None, iter([]), BINARY_LOGISTIC_LOSS
            )


class TestStreamKMeans:
    def test_stream_fit_matches_in_memory(self, mesh8):
        rng = np.random.default_rng(1)
        X = np.vstack(
            [rng.standard_normal((100, 5)) + c * 4 for c in range(3)]
        ).astype(np.float32)
        est = lambda: KMeans().set_k(3).set_seed(11).set_max_iter(8)  # noqa: E731
        in_mem = est().fit(Table({"features": X}))
        batches = [
            Table({"features": X[i : i + 64]}) for i in range(0, X.shape[0], 64)
        ]
        streamed = est().fit(StreamTable.from_batches(batches))
        np.testing.assert_allclose(
            streamed.centroids, in_mem.centroids, rtol=1e-5, atol=1e-6
        )
        np.testing.assert_allclose(streamed.weights, in_mem.weights)

    def test_stream_fit_spills(self, mesh8, tmp_path):
        prev = (config.datacache_memory_budget_bytes, config.datacache_spill_dir)
        config.datacache_memory_budget_bytes = 2048
        config.datacache_spill_dir = str(tmp_path)
        try:
            rng = np.random.default_rng(2)
            X = rng.standard_normal((600, 8)).astype(np.float32)
            batches = [
                Table({"features": X[i : i + 100]}) for i in range(0, 600, 100)
            ]
            model = (
                KMeans().set_k(4).set_seed(3).set_max_iter(3)
            ).fit(StreamTable.from_batches(batches))
            assert model.cache_stats["spilledSegments"] > 0, model.cache_stats
            assert model.centroids.shape == (4, 8)
        finally:
            config.datacache_memory_budget_bytes, config.datacache_spill_dir = prev

    def test_fewer_points_than_k(self, mesh8):
        batches = [Table({"features": np.zeros((2, 3), np.float32)})]
        with pytest.raises(ValueError, match="less than k"):
            KMeans().set_k(5).fit(StreamTable.from_batches(batches))
