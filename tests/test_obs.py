"""Observability layer (obs/) — span tracing, exporters, trace report.

Covers the tentpole contracts: span nesting + attribute propagation, the
JSONL schema round-trip, the always-on no-op overhead bound (<1µs/call),
counter correctness for collective bytes and datacache hit/miss/evict,
readback accounting, and a Pipeline.fit integration test asserting the
per-stage category breakdown sums to each stage's wall time."""

import json
import os
import time

import numpy as np
import pytest

from flink_ml_tpu.obs import exporters, report, tracing
from flink_ml_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _clean_tracing():
    tracing.configure()
    metrics.reset()
    yield
    tracing.configure()
    metrics.reset()


# ---------------------------------------------------------------------------
# span core
# ---------------------------------------------------------------------------

def test_span_nesting_and_attributes():
    tracing.configure(ring_size=64)
    with tracing.span("outer", kind="fit") as outer:
        outer.set_attr("late", 42)
        with tracing.span("inner") as inner:
            tracing.add_attr("via_helper", "yes")
            assert tracing.current_span() is inner
        with tracing.span("inner2"):
            pass
    records = {r["name"]: r for r in tracing.drain_ring()}
    assert set(records) == {"outer", "inner", "inner2"}
    assert records["outer"]["parentId"] == 0
    assert records["inner"]["parentId"] == records["outer"]["spanId"]
    assert records["inner2"]["parentId"] == records["outer"]["spanId"]
    assert records["outer"]["attrs"] == {"kind": "fit", "late": 42}
    assert records["inner"]["attrs"]["via_helper"] == "yes"
    # children are fully contained in the parent's [start, start+dur] window
    o, i = records["outer"], records["inner"]
    assert o["startUs"] <= i["startUs"]
    assert i["startUs"] + i["durUs"] <= o["startUs"] + o["durUs"] + 1e-3
    # spans also aggregate into the flat registry
    snap = metrics.snapshot()
    assert snap["timers"]["span.outer"]["count"] == 1
    assert snap["timers"]["span.inner"]["count"] == 1


def test_span_error_attribute():
    tracing.configure(ring_size=8)
    with pytest.raises(RuntimeError):
        with tracing.span("boom"):
            raise RuntimeError("x")
    (record,) = tracing.drain_ring()
    assert record["attrs"]["error"] == "RuntimeError"


def test_jsonl_schema_roundtrip(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    tracing.configure(trace_file=path)
    with tracing.span("stage.fit", stage="KMeans"):
        with tracing.span("iteration.epoch", epoch=0):
            pass
        tracing.event("collective.psum", category="collective", bytes=128)
    tracing.configure()  # closes the file

    records = report.load_trace(path)
    assert len(records) == 3
    for r in records:
        assert set(r) == {"name", "spanId", "parentId", "startUs", "durUs", "attrs"}
    by_name = {r["name"]: r for r in records}
    assert by_name["iteration.epoch"]["parentId"] == by_name["stage.fit"]["spanId"]
    assert by_name["collective.psum"]["durUs"] == 0.0
    assert by_name["collective.psum"]["attrs"]["bytes"] == 128
    # appending resumes cleanly (same process restart semantics)
    tracing.configure(trace_file=path)
    with tracing.span("again"):
        pass
    tracing.configure()
    assert len(report.load_trace(path)) == 4


def test_noop_span_overhead_under_1us():
    """The acceptance bound for always-on instrumentation: with no sink
    configured a span costs <1µs per call (global check + shared no-op)."""
    assert not tracing.enabled()
    n = 100_000
    best = float("inf")
    for _ in range(3):  # best-of-3 shields the bound from CI scheduling noise
        t0 = time.perf_counter()
        for _ in range(n):
            with tracing.span("bench.noop"):
                pass
        best = min(best, (time.perf_counter() - t0) / n)
    assert best < 1e-6, f"no-op span path costs {best * 1e9:.0f}ns/call"
    assert "span.bench.noop" not in metrics.snapshot()["timers"]


def test_ring_buffer_bounded():
    tracing.configure(ring_size=4)
    for i in range(10):
        with tracing.span("s", i=i):
            pass
    records = tracing.drain_ring()
    assert len(records) == 4
    assert [r["attrs"]["i"] for r in records] == [6, 7, 8, 9]
    assert tracing.drain_ring() == []


# ---------------------------------------------------------------------------
# runtime accounting: collectives, datacache, readback, compiles
# ---------------------------------------------------------------------------

def test_collective_byte_counters(mesh8):
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from flink_ml_tpu.parallel import collectives

    tracing.configure(ring_size=32)

    fn = collectives.shard_map_over(
        mesh8,
        in_specs=P("data", None),
        out_specs=P("data", None),
        fn=lambda v: collectives.all_reduce_sum(v) * jnp.ones_like(v),
    )
    x = jnp.ones((8, 4), jnp.float32)
    np.asarray(fn(x))
    snap = metrics.snapshot()
    assert snap["counters"]["collective.psum.calls"] == 1
    # per-shard payload: (1, 4) f32 rows after the 8-way split
    assert snap["counters"]["collective.psum.bytes"] == 4 * 4
    events = [r for r in tracing.drain_ring() if r["name"] == "collective.psum"]
    assert events and events[0]["attrs"]["category"] == "collective"
    assert events[0]["attrs"]["chunks"] == 1


def test_host_all_reduce_counters(mesh8):
    from flink_ml_tpu.parallel import collectives

    out = collectives.host_all_reduce_sum(
        mesh8, [np.full(16, float(i), np.float32) for i in range(3)]
    )
    np.testing.assert_allclose(np.asarray(out), np.full(16, 3.0))
    snap = metrics.snapshot()
    assert snap["counters"]["collective.host_all_reduce_sum.calls"] == 1
    assert snap["counters"]["collective.host_all_reduce_sum.bytes"] == 3 * 16 * 4


def test_datacache_hit_miss_evict_counters(tmp_path):
    from flink_ml_tpu.native import available
    from flink_ml_tpu.native.datacache import DataCache

    cache = DataCache(memory_budget_bytes=1024, spill_dir=str(tmp_path))
    resident = np.zeros(64, np.float64)  # 512B — fits
    big = np.zeros(128, np.float64)  # 1024B — second append exceeds budget
    s0 = cache.append_array(resident)
    s1 = cache.append_array(big)
    cache.read_array(s0)
    cache.read_array(s1)
    cache.read_array(s1)
    snap = metrics.snapshot()
    assert snap["counters"]["datacache.append"] == 2
    assert snap["counters"]["datacache.appendBytes"] == 512 + 1024
    assert snap["counters"]["datacache.readBytes"] == 512 + 2 * 1024
    if available():  # spill accounting needs the native budget enforcement
        assert snap["counters"]["datacache.evict"] == 1
        assert snap["counters"]["datacache.hit"] == 1
        assert snap["counters"]["datacache.miss"] == 2
    else:
        assert snap["counters"]["datacache.hit"] == 3
    cache.close()


def test_readback_accounting():
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.utils.packing import packed_device_get

    tracing.configure(ring_size=8)
    a = jnp.arange(8, dtype=jnp.float32)
    b = jnp.ones((2, 2), jnp.float32)
    out = packed_device_get(a, b)
    np.testing.assert_allclose(out[0], np.arange(8))
    snap = metrics.snapshot()
    assert snap["counters"]["readback.count"] == 1
    assert snap["counters"]["readback.bytes"] == (8 + 4) * 4
    spans = [r for r in tracing.drain_ring() if r["name"] == "readback"]
    assert spans and spans[0]["attrs"]["category"] == "readback"
    assert spans[0]["attrs"]["arrays"] == 2


def test_jit_compile_counters():
    import jax
    import jax.numpy as jnp

    from flink_ml_tpu.utils.lazyjit import lazy_jit

    kernel = lazy_jit(lambda x: x * 2.0)
    before = metrics.snapshot()["counters"].get("jit.compiles", 0)
    kernels_before = metrics.snapshot()["counters"].get("jit.kernels", 0)
    np.asarray(kernel(jnp.ones(7)))
    snap = metrics.snapshot()
    assert snap["counters"]["jit.kernels"] == kernels_before + 1
    assert snap["counters"].get("jit.compiles", 0) >= before + 1
    assert "jit.compile" in snap["timers"]


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def test_exporters_json_and_prometheus():
    metrics.inc_counter("readback.bytes", 2048)
    metrics.set_gauge("iteration.epochs", 5)
    metrics.record_time("span.stage.fit", 0.25)
    doc = json.loads(exporters.snapshot_json())
    assert doc["counters"]["readback.bytes"] == 2048
    text = exporters.snapshot_prometheus()
    assert "flink_ml_tpu_readback_bytes_total 2048" in text
    assert "flink_ml_tpu_iteration_epochs 5" in text
    assert "flink_ml_tpu_span_stage_fit_count 1" in text
    assert "# TYPE flink_ml_tpu_readback_bytes_total counter" in text


def test_snapshot_delta():
    metrics.inc_counter("c", 5)
    metrics.record_time("t", 0.5)
    before = metrics.snapshot()
    metrics.inc_counter("c", 2)
    metrics.inc_counter("fresh")
    metrics.record_time("t", 0.25)
    delta = metrics.snapshot_delta(before, metrics.snapshot())
    assert delta["counters"] == {"c": 2, "fresh": 1}
    assert delta["timers"]["t"]["count"] == 1
    assert abs(delta["timers"]["t"]["totalMs"] - 250.0) < 1.0


# ---------------------------------------------------------------------------
# iteration + pipeline integration
# ---------------------------------------------------------------------------

def test_iteration_epoch_spans_and_device_summary():
    import jax.numpy as jnp

    from flink_ml_tpu.parallel.iteration import IterationListener, iterate_bounded

    tracing.configure(ring_size=256)

    def body(carry, epoch):
        return carry + 1.0, jnp.asarray(1.0, jnp.float32)

    iterate_bounded(body, jnp.asarray(0.0), max_iter=3, listener=IterationListener())
    records = tracing.drain_ring()
    epochs = [r for r in records if r["name"] == "iteration.epoch"]
    runs = [r for r in records if r["name"] == "iteration.run"]
    assert [r["attrs"]["epoch"] for r in epochs] == [0, 1, 2]
    assert len(runs) == 1 and runs[0]["attrs"]["mode"] == "host"
    assert runs[0]["attrs"]["epochs"] == 3
    assert all(r["parentId"] == runs[0]["spanId"] for r in epochs)

    iterate_bounded(body, jnp.asarray(0.0), max_iter=4)  # on-device while_loop
    records = tracing.drain_ring()
    (run,) = [r for r in records if r["name"] == "iteration.run"]
    assert run["attrs"] == {
        "mode": "device",
        "epochs": 4,
        "finalCriteria": 1.0,
    }
    assert not [r for r in records if r["name"] == "iteration.epoch"]


def test_pipeline_fit_stage_breakdown_sums_to_wall(mesh8):
    """Integration: a traced Pipeline.fit yields per-stage spans whose
    category breakdown sums (exactly) to each stage's wall time, and the
    stages account for (almost) all of the pipeline.fit span."""
    from flink_ml_tpu import Pipeline
    from flink_ml_tpu.models.clustering.kmeans import KMeans
    from flink_ml_tpu.models.feature.standardscaler import StandardScaler

    rng = np.random.default_rng(0)
    from flink_ml_tpu import Table

    table = Table({"features": rng.standard_normal((256, 4)).astype(np.float32)})
    tracing.configure(ring_size=4096)
    pipeline = Pipeline(
        [
            StandardScaler().set_input_col("features").set_output_col("features"),
            KMeans().set_k(2).set_seed(1).set_max_iter(3),
        ]
    )
    pipeline.fit(table)
    records = tracing.drain_ring()
    trace = report.Trace(records)
    stages = report.stage_records(trace)
    assert [(r["attrs"]["stage"], r["attrs"]["index"]) for r in stages] == [
        ("StandardScaler", 0),
        ("KMeans", 1),
    ]
    outer = next(
        r
        for r in records
        if r["name"] == "stage.fit" and r["attrs"]["stage"] == "Pipeline"
    )
    stage_wall = 0.0
    for r in stages:
        b = trace.breakdown(r)
        total = b["compute"] + sum(b[c] for c in report.CATEGORIES)
        assert abs(total - b["wall"]) <= 0.05 * b["wall"] + 1e-6
        stage_wall += b["wall"]
    # the per-stage spans cover the pipeline fit minus orchestration slack
    assert stage_wall <= outer["durUs"] * 1.001
    assert stage_wall >= 0.90 * outer["durUs"]
    # the report renders without error and mentions both stages
    text = report.render_report(records)
    assert "StandardScaler" in text and "KMeans" in text
    assert "Dominant category:" in text


def test_stage_autoinstrumentation_single_span_per_call():
    """Inherited fit/transform definitions are wrapped exactly once."""
    from flink_ml_tpu import Table
    from flink_ml_tpu.models.feature.binarizer import Binarizer

    tracing.configure(ring_size=64)
    t = Table({"x": np.asarray([0.1, 0.9])})
    Binarizer().set_input_cols("x").set_output_cols("o").set_thresholds(0.5).transform(t)
    records = [r for r in tracing.drain_ring() if r["name"] == "stage.transform"]
    assert len(records) == 1
    assert records[0]["attrs"]["stage"] == "Binarizer"


def test_report_device_profile_crossref(tmp_path):
    """`--device-profile` reduces a chrome-format jax.profiler trace via
    traceprof.analyze_trace and renders the device-side totals."""
    import gzip

    trace = {
        "traceEvents": [
            {"ph": "M", "pid": 1, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "pid": 1, "tid": 1, "name": "thread_name",
             "args": {"name": "XLA Modules"}},
            {"ph": "M", "pid": 1, "tid": 2, "name": "thread_name",
             "args": {"name": "XLA Ops"}},
            {"ph": "X", "pid": 1, "tid": 1, "name": "jit_f", "dur": 1500.0},
            {"ph": "X", "pid": 1, "tid": 2, "name": "fusion.1", "dur": 900.0,
             "args": {"bytes_accessed": 4096, "model_flops": 1000,
                      "hlo_category": "fusion"}},
        ]
    }
    path = str(tmp_path / "t.trace.json.gz")
    with gzip.open(path, "wt") as f:
        json.dump(trace, f)
    text = report.render_device_profile(path)
    assert "deviceBusyMs: 1.5" in text
    assert "fusion 0.9ms" in text
    # a profiler log dir with no trace renders a graceful message
    assert "no *.trace.json.gz" in report.render_device_profile(str(tmp_path))


def test_benchmark_runner_embeds_metrics(mesh8):
    from flink_ml_tpu.benchmark.runner import run_benchmark

    entry = {
        "stage": {
            "className": "org.apache.flink.ml.clustering.kmeans.KMeans",
            "paramMap": {"k": 2, "maxIter": 2},
        },
        "inputData": {
            "className": "org.apache.flink.ml.benchmark.datagenerator.common.DenseVectorGenerator",
            "paramMap": {"colNames": [["features"]], "numValues": 64, "vectorDim": 3},
        },
    }
    result = run_benchmark("KMeans-obs", entry)
    embedded = result["metrics"]
    assert set(embedded) == {"timers", "gauges", "counters"}
    assert embedded["counters"]["readback.count"] >= 1
    assert embedded["counters"]["readback.bytes"] > 0
    assert "benchmark.KMeans-obs.fit" in embedded["timers"]
    # the BENCH payload stays json-serializable
    json.dumps(result)


# ---------------------------------------------------------------------------
# exporter gaps closed (ISSUE 12): histograms, collision check, BENCH fields
# ---------------------------------------------------------------------------

@pytest.fixture
def _clean_hist():
    from flink_ml_tpu.obs import hist

    hist.reset()
    hist.configure(True)
    yield hist
    hist.reset()
    hist.configure(True)


def test_prometheus_exports_flow_and_lifecycle_counters(_clean_hist):
    """The PR 8/10 counters stop being runner-JSON-only: once incremented
    they appear in the Prometheus exposition."""
    metrics.inc_counter("flow.retry", 3)
    metrics.inc_counter("flow.shed", 2)
    metrics.inc_counter("flow.reject", 1)
    metrics.inc_counter("lifecycle.swap", 4)
    metrics.inc_counter("lifecycle.rollback", 1)
    metrics.inc_counter("serving.deadlineMiss", 2)
    metrics.inc_counter("serving.deadlineMiss.expired", 1)
    metrics.inc_counter("serving.deadlineMiss.late", 1)
    metrics.set_gauge("flow.lag.online.ingest", 3)
    text = exporters.snapshot_prometheus()
    for line in (
        "flink_ml_tpu_flow_retry_total 3",
        "flink_ml_tpu_flow_shed_total 2",
        "flink_ml_tpu_flow_reject_total 1",
        "flink_ml_tpu_lifecycle_swap_total 4",
        "flink_ml_tpu_lifecycle_rollback_total 1",
        "flink_ml_tpu_serving_deadlineMiss_total 2",
        "flink_ml_tpu_serving_deadlineMiss_expired_total 1",
        "flink_ml_tpu_serving_deadlineMiss_late_total 1",
        "flink_ml_tpu_flow_lag_online_ingest 3",
    ):
        assert line in text, line


def test_prometheus_histogram_exposition(_clean_hist):
    from flink_ml_tpu.obs import hist

    for v in (1.0, 1.5, 3.0, 100.0):
        hist.record("serving.dispatchMs", v)
    text = exporters.snapshot_prometheus()
    assert "# TYPE flink_ml_tpu_serving_dispatchMs histogram" in text
    assert 'flink_ml_tpu_serving_dispatchMs_bucket{le="+Inf"} 4' in text
    assert "flink_ml_tpu_serving_dispatchMs_sum 105.5" in text
    assert "flink_ml_tpu_serving_dispatchMs_count 4" in text
    # buckets are cumulative and end at the total count
    import re as _re

    counts = [
        int(m.group(1))
        for m in _re.finditer(
            r'flink_ml_tpu_serving_dispatchMs_bucket\{le="[^+]+"\} (\d+)', text
        )
    ]
    assert counts == sorted(counts) and counts[-1] <= 4


def test_prometheus_name_collision_check(_clean_hist):
    from flink_ml_tpu.obs import hist

    metrics.inc_counter("a.b")
    metrics.inc_counter("a_b")  # sanitizes to the same series
    collisions = exporters.check_name_collisions()
    assert any("a_b_total" in c for c in collisions)
    with pytest.raises(ValueError, match="collision"):
        exporters.snapshot_prometheus()
    metrics.reset()
    # a timer and a histogram of the same name share a `_count` series
    metrics.record_time("dup.ms", 0.1)
    hist.record("dup.ms", 0.1)
    assert any("dup_ms_count" in c for c in exporters.check_name_collisions())
    # a clean registry passes
    metrics.reset()
    hist.reset()
    metrics.inc_counter("readback.bytes", 1)
    assert exporters.check_name_collisions() == []


def test_prometheus_exports_hbm_gauges(_clean_hist):
    """The HBM ledger gauges flow through the registry into a clean
    (collision-free) Prometheus exposition."""
    from flink_ml_tpu.obs import memledger

    metrics.reset()
    memledger.reset()
    try:
        h = memledger.register("model", 4096)
        memledger.register("batchCache", 1024)
        memledger.release(h)
        assert exporters.check_name_collisions() == []
        text = exporters.snapshot_prometheus()
        for line in (
            "flink_ml_tpu_hbm_live_model 0",
            "flink_ml_tpu_hbm_live_batchCache 1024",
            "flink_ml_tpu_hbm_live 1024",
            "flink_ml_tpu_hbm_peak 5120",
        ):
            assert line in text, line
    finally:
        memledger.reset()


def test_bench_entry_prometheus_first_class_fields():
    entry = {
        "name": "kmeans",
        "totalTimeMs": 12.5,
        "hostSyncCount": 1,
        "retryCount": 2,
        "shedCount": 0,
        "rejectCount": 5,
        "swapCount": 3,
        "rollbackCount": 1,
        "dispatchGapMs": 90.0,
        "gapCount": 7,
        "retriesBitIdentical": True,  # bools are not metrics
        "metrics": {"counters": {}},
    }
    text = exporters.bench_entry_prometheus(entry)
    assert 'flink_ml_tpu_bench_totalTimeMs{benchmark="kmeans"} 12.5' in text
    assert 'flink_ml_tpu_bench_retryCount{benchmark="kmeans"} 2' in text
    assert 'flink_ml_tpu_bench_rejectCount{benchmark="kmeans"} 5' in text
    assert 'flink_ml_tpu_bench_swapCount{benchmark="kmeans"} 3' in text
    assert 'flink_ml_tpu_bench_rollbackCount{benchmark="kmeans"} 1' in text
    assert 'flink_ml_tpu_bench_dispatchGapMs{benchmark="kmeans"} 90.0' in text
    assert "retriesBitIdentical" not in text


# ---------------------------------------------------------------------------
# obs_report robustness (ISSUE 12): truncated traces, --format json
# ---------------------------------------------------------------------------

def test_sanitize_records_drops_unmatched_with_count():
    records = [
        {"name": "ok", "spanId": 1, "parentId": 0, "startUs": 0.0, "durUs": 5.0,
         "attrs": {}},
        {"ph": "B", "lane": "host:t", "name": "pair", "tsUs": 10.0, "ref": 2},
        {"ph": "E", "lane": "host:t", "name": "pair", "tsUs": 30.0, "ref": 2,
         "args": {"k": 1}},
        {"ph": "E", "lane": "host:t", "name": "lost", "tsUs": 40.0, "ref": 3},
        {"ph": "B", "lane": "host:t", "name": "open", "tsUs": 50.0, "ref": 4},
        {"name": "no_span_id", "startUs": 1.0},
        "not even a dict",
    ]
    clean, dropped = report.sanitize_records(records)
    assert dropped == 4  # lost-E, open-B, schema-less record, non-dict
    by_name = {r["name"]: r for r in clean}
    assert set(by_name) == {"ok", "pair"}
    assert by_name["pair"]["durUs"] == 20.0
    assert by_name["pair"]["attrs"] == {"k": 1}
    report.render_report(clean)  # renders without error


def test_obs_report_cli_truncated_fixture():
    """Regression (ISSUE 12): a ring-/mid-span-truncated trace file must
    report with a warning, in both text and --format json."""
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    fixture = os.path.join(root, "tests", "fixtures", "traces", "truncated.jsonl")
    out = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "obs_report.py"), fixture],
        capture_output=True, text=True, cwd=root,
    )
    assert out.returncode == 0, out.stderr
    assert "dropped" in out.stderr and "truncated" in out.stderr
    assert "KMeans.fit" in out.stdout
    out_json = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "obs_report.py"), fixture,
         "--format", "json"],
        capture_output=True, text=True, cwd=root,
    )
    assert out_json.returncode == 0, out_json.stderr
    doc = json.loads(out_json.stdout)
    assert doc["stages"] and doc["stages"][0]["label"] == "KMeans.fit"
    bad = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "obs_report.py"), fixture,
         "--format", "xml"],
        capture_output=True, text=True, cwd=root,
    )
    assert bad.returncode == 2


# ---------------------------------------------------------------------------
# compile-cost section (AOT program bank, ISSUE 20)
# ---------------------------------------------------------------------------

def test_compile_cost_attribution_and_bank_split():
    records = [
        # attributed AOT compile; the nested backend-compile span is the
        # same cost and must NOT double-count into the unattributed row
        {"name": "bank.compile", "spanId": 1, "parentId": 0, "startUs": 0.0,
         "durUs": 5000.0, "attrs": {"kernel": "models.k1", "category": "compile"}},
        {"name": "jit.compile", "spanId": 2, "parentId": 1, "startUs": 100.0,
         "durUs": 4000.0, "attrs": {"category": "compile"}},
        # a backend compile the bank never saw
        {"name": "jit.compile", "spanId": 3, "parentId": 0, "startUs": 9000.0,
         "durUs": 2000.0, "attrs": {"category": "compile"}},
        # warm path: two loads, one hit
        {"name": "bank.load", "spanId": 4, "parentId": 0, "startUs": 0.0,
         "durUs": 0.0, "attrs": {"kernel": "models.k1"}},
        {"name": "bank.load", "spanId": 5, "parentId": 0, "startUs": 0.0,
         "durUs": 0.0, "attrs": {"kernel": "models.k2"}},
        {"name": "bank.hit", "spanId": 6, "parentId": 0, "startUs": 10.0,
         "durUs": 0.0, "attrs": {"kernel": "models.k1", "category": "cache"}},
    ]
    rows = {r["kernel"]: r for r in report.compile_cost(report.Trace(records))}
    assert rows["models.k1"]["compiles"] == 1
    assert rows["models.k1"]["compileMs"] == pytest.approx(5.0)
    assert rows["models.k1"]["bankHits"] == 1
    assert rows["models.k1"]["bankLoads"] == 1
    assert rows["models.k2"] == {"kernel": "models.k2", "compiles": 0,
                                 "compileMs": 0.0, "bankHits": 0, "bankLoads": 1}
    unattributed = rows["(unattributed XLA compile)"]
    assert unattributed["compiles"] == 1
    assert unattributed["compileMs"] == pytest.approx(2.0)
    text = report.render_report(records)
    assert "Compile cost" in text and "models.k1" in text


def test_compile_cost_survives_truncated_trace():
    """Regression (sanitize contract): a ring-truncated trace that loses
    a bank.compile end must still render the compile-cost section from
    the surviving spans — dropped records, never a crash."""
    records = [
        {"name": "bank.compile", "spanId": 1, "parentId": 0, "startUs": 0.0,
         "durUs": 3000.0, "attrs": {"kernel": "models.k1", "category": "compile"}},
        {"name": "bank.hit", "spanId": 2, "parentId": 0, "startUs": 10.0,
         "durUs": 0.0, "attrs": {"kernel": "models.k1"}},
        # mid-span truncation: a begin with no end, plus schema-less junk
        {"ph": "B", "lane": "host:t", "name": "bank.compile", "tsUs": 50.0,
         "ref": 9},
        {"name": "half a record"},
        "garbage line",
    ]
    clean, dropped = report.sanitize_records(records)
    assert dropped == 3
    rows = report.compile_cost(report.Trace(clean))
    assert [r["kernel"] for r in rows] == ["models.k1"]
    assert "Compile cost" in report.render_report(clean)
