"""Readback-budget contract for the hot fit paths.

Every first readback of a device array costs a full host round trip on a
remote-attached TPU, so a fit must pull its results in ONE packed
transfer. These tests run fits on device-born inputs under
``jax.transfer_guard_device_to_host("disallow")``, which raises on any IMPLICIT
device→host transfer (a stray ``np.asarray`` on a device array) while
letting the explicit `packed_device_get` / `jax.device_get` readback
through — and count that exactly one such explicit readback happens."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_ml_tpu.table import Table
from flink_ml_tpu.utils import packing


@pytest.fixture
def readback_counter(monkeypatch):
    calls = []
    real = jax.device_get

    def counting_device_get(x):
        calls.append(np.shape(x))
        return real(x)

    monkeypatch.setattr(jax, "device_get", counting_device_get)
    return calls


def _device_table_Xyw(n=512, d=8):
    key = jax.random.PRNGKey(0)
    k1, k2, k3 = jax.random.split(key, 3)
    X = jax.random.uniform(k1, (n, d), jnp.float32)
    y = (jax.random.uniform(k2, (n,)) > 0.5).astype(jnp.float32)
    w = jax.random.uniform(k3, (n,))
    return Table({"features": X, "label": y, "weight": w})


def test_kmeans_fit_single_packed_readback(readback_counter):
    from flink_ml_tpu.models.clustering.kmeans import KMeans

    table = _device_table_Xyw()
    with jax.transfer_guard_device_to_host("disallow"):
        model = KMeans().set_k(4).set_max_iter(5).set_seed(2).fit(table)
    assert len(readback_counter) == 1, readback_counter
    assert model.centroids.shape == (4, 8)


def test_logisticregression_fit_single_packed_readback(readback_counter):
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression,
    )

    table = _device_table_Xyw()
    with jax.transfer_guard_device_to_host("disallow"):
        model = LogisticRegression().set_max_iter(5).set_global_batch_size(
            256
        ).set_weight_col("weight").fit(table)
    assert len(readback_counter) == 1, readback_counter
    assert model.coefficient.shape == (8,)


def test_standardscaler_fit_single_packed_readback(readback_counter):
    from flink_ml_tpu.models.feature.standardscaler import StandardScaler

    table = _device_table_Xyw()
    with jax.transfer_guard_device_to_host("disallow"):
        StandardScaler().set_input_col("features").set_output_col("out").fit(table)
    assert len(readback_counter) == 1, readback_counter


def test_minmax_and_maxabs_fit_single_packed_readback(readback_counter):
    from flink_ml_tpu.models.feature.maxabsscaler import MaxAbsScaler
    from flink_ml_tpu.models.feature.minmaxscaler import MinMaxScaler

    table = _device_table_Xyw()
    with jax.transfer_guard_device_to_host("disallow"):
        MinMaxScaler().set_input_col("features").set_output_col("out").fit(table)
    assert len(readback_counter) == 1, readback_counter
    readback_counter.clear()
    with jax.transfer_guard_device_to_host("disallow"):
        MaxAbsScaler().set_input_col("features").set_output_col("out").fit(table)
    assert len(readback_counter) == 1, readback_counter


def test_packed_device_get_round_trips():
    a = jnp.arange(6, dtype=jnp.float32).reshape(2, 3)
    b = jnp.asarray([7.0, 8.0])
    c = jnp.asarray(9, jnp.int32)
    ha, hb, hc = packing.packed_device_get(a, b, c)
    np.testing.assert_array_equal(ha, np.arange(6).reshape(2, 3))
    np.testing.assert_array_equal(hb, [7.0, 8.0])
    assert hc == 9
    # host inputs pass through untouched
    (h,) = packing.packed_device_get(np.asarray([1.0]))
    np.testing.assert_array_equal(h, [1.0])

# ---------------------------------------------------------------------------
# host-input transforms: the pulls the tpulint host-sync-leak rule fixed
# ---------------------------------------------------------------------------
# Before the tpulint pass these paths pulled device results back with bare
# np.asarray — a silent, UNACCOUNTED device→host sync (hostSyncCount 0 on
# the estimator's BENCH entry despite a real tunnel round trip, and two
# round trips for the two-column predictors). Now they ride
# packed_device_get: exactly ONE accounted sync per transform.


def _transform_sync_delta(fn):
    from flink_ml_tpu.utils import metrics

    before = metrics.snapshot()["counters"].get("iteration.host_sync.transform", 0)
    fn()
    after = metrics.snapshot()["counters"].get("iteration.host_sync.transform", 0)
    return after - before


def test_kmeans_host_transform_sync_is_accounted(readback_counter):
    from flink_ml_tpu.models.clustering.kmeans import KMeans

    X = np.random.RandomState(0).rand(64, 4)
    table = Table({"features": X})
    model = KMeans().set_k(3).set_max_iter(3).fit(table)
    readback_counter.clear()
    delta = _transform_sync_delta(lambda: model.transform(table))
    assert delta == 1  # was 0 accounted (silent np.asarray) before the fix
    assert len(readback_counter) == 1  # ... and exactly one real transfer


def test_logreg_host_transform_is_one_packed_sync(readback_counter):
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression,
    )

    rng = np.random.RandomState(1)
    X = rng.rand(128, 6)
    y = (rng.rand(128) > 0.5).astype(np.float64)
    table = Table({"features": X, "label": y})
    model = LogisticRegression().set_max_iter(3).fit(table)
    readback_counter.clear()
    delta = _transform_sync_delta(lambda: model.transform(table))
    # prediction + rawPrediction come back in ONE packed transfer (two
    # bare np.asarray pulls would each pay their own tunnel round trip)
    assert delta == 1
    assert len(readback_counter) == 1
