"""Every examples/*.py script must run green — the user surface of the
framework (reference ships 47 Java + 52 Python runnable examples;
SURVEY.md §2.6). Scripts are executed in-process on the virtual CPU mesh
(conftest) with their asserts active."""

import glob
import os
import runpy

import pytest

EXAMPLES = sorted(
    glob.glob(os.path.join(os.path.dirname(__file__), "..", "examples", "*.py"))
)


def test_examples_exist():
    assert len(EXAMPLES) >= 15


@pytest.mark.parametrize("path", EXAMPLES, ids=[os.path.basename(p) for p in EXAMPLES])
def test_example_runs(path, capsys):
    runpy.run_path(path, run_name="__main__")
    # every example prints something it computed
    assert capsys.readouterr().out.strip()
