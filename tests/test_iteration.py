"""Iteration runtime — bounded while-loop, host-driven loop with listener,
checkpoint/resume, unbounded stepping. The analogue of the reference's
iteration ITs (BoundedAllRoundCheckpointITCase etc., SURVEY.md §4 tier 4)."""

import jax.numpy as jnp
import numpy as np

from flink_ml_tpu.parallel.iteration import (
    IterationListener,
    iterate_bounded,
    iterate_unbounded,
    load_iteration_checkpoint,
    save_iteration_checkpoint,
    scan_epochs,
)


def _halving_body(carry, epoch):
    new = carry * 0.5
    return new, jnp.abs(new)


def test_max_iter_termination():
    result = iterate_bounded(_halving_body, jnp.asarray(64.0), max_iter=3)
    assert result.num_epochs == 3
    assert float(result.carry) == 8.0


def test_tol_termination():
    result = iterate_bounded(_halving_body, jnp.asarray(64.0), max_iter=100, tol=10.0)
    # 64 -> 32 -> 16 -> 8 <= 10 stops
    assert result.num_epochs == 3
    assert float(result.carry) == 8.0


def test_listener_host_loop_matches_device_loop():
    seen = []

    class L(IterationListener):
        def on_epoch_watermark_incremented(self, epoch, carry):
            seen.append((epoch, float(carry)))

        def on_iteration_terminated(self, carry):
            seen.append(("done", float(carry)))

    result = iterate_bounded(
        _halving_body, jnp.asarray(64.0), max_iter=3, listener=L()
    )
    assert float(result.carry) == 8.0
    assert seen == [(1, 32.0), (2, 16.0), (3, 8.0), ("done", 8.0)]


def test_checkpoint_resume(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    r1 = iterate_bounded(
        _halving_body, jnp.asarray(64.0), max_iter=2, checkpoint_dir=ckpt
    )
    assert float(r1.carry) == 16.0
    # resume continues from epoch 2, not from scratch
    r2 = iterate_bounded(
        _halving_body, jnp.asarray(64.0), max_iter=4, checkpoint_dir=ckpt
    )
    assert r2.num_epochs == 4
    assert float(r2.carry) == 4.0


def test_checkpoint_pytree_roundtrip(tmp_path):
    carry = {"w": jnp.ones((3,)), "b": jnp.asarray(2.0)}
    save_iteration_checkpoint(str(tmp_path), carry, epoch=7, criteria=0.5)
    restored, epoch, criteria = load_iteration_checkpoint(str(tmp_path), carry)
    assert epoch == 7 and criteria == 0.5
    np.testing.assert_array_equal(np.asarray(restored["w"]), np.ones(3))


def test_scan_epochs_history():
    carry, history = scan_epochs(_halving_body, jnp.asarray(16.0), num_epochs=4)
    assert float(carry) == 1.0
    np.testing.assert_allclose(np.asarray(history), [8.0, 4.0, 2.0, 1.0])


def test_unbounded_iteration_versions():
    batches = [1.0, 2.0, 3.0]
    steps = list(
        iterate_unbounded(batches, lambda state, b: state + b, 0.0)
    )
    assert [v for v, _ in steps] == [1, 2, 3]
    assert [s for _, s in steps] == [1.0, 3.0, 6.0]
