"""Tier-1 gate for scripts/check_checkpoint_coverage.py: every concrete
Estimator either routes its fit through the JobSnapshot API
(flink_ml_tpu/ckpt/) — verified by a funnel reference in its defining
module — or declares `checkpointable = False` with a reason. A new
estimator that silently loses training progress on preemption fails the
build instead of failing in production."""

import importlib.util
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_checker():
    spec = importlib.util.spec_from_file_location(
        "check_checkpoint_coverage",
        os.path.join(REPO, "scripts", "check_checkpoint_coverage.py"),
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_all_estimators_declare_checkpoint_contract():
    checker = _load_checker()
    violations = checker.find_violations()
    assert not violations, (
        "estimators without an explicit checkpoint contract:\n"
        + "\n".join(f"  {name}: {problem}" for name, problem in violations)
    )


def test_known_contracts_hold():
    """The headline paths stay wired: the SGD-backed linear models, the
    out-of-core KMeans, and both online estimators are checkpointable;
    a representative single-pass estimator is declared not-checkpointable
    WITH a reason."""
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegression,
    )
    from flink_ml_tpu.models.classification.onlinelogisticregression import (
        OnlineLogisticRegression,
    )
    from flink_ml_tpu.models.clustering.kmeans import KMeans
    from flink_ml_tpu.models.clustering.onlinekmeans import OnlineKMeans
    from flink_ml_tpu.models.feature.standardscaler import StandardScaler

    for cls in (LogisticRegression, KMeans, OnlineKMeans, OnlineLogisticRegression):
        assert cls.checkpointable is True
    assert StandardScaler.checkpointable is False
    assert StandardScaler.checkpoint_reason.strip()


def test_gate_rejects_unwired_true_declaration(tmp_path):
    """A checkpointable=True class whose module never touches a funnel is
    a violation (the True declaration must be backed by wiring), and a
    funnel name in a docstring does not count."""
    checker = _load_checker()
    code = checker._code_only(
        '"""run_sgd mentioned in a docstring only."""\n'
        "x = 1  # iterate_unbounded in a comment\n"
    )
    assert not any(funnel in code for funnel in checker.FUNNELS)
    real = checker._code_only("coeff = run_sgd(params, table, loss, None)\n")
    assert "run_sgd" in real
