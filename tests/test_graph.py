"""Graph/GraphBuilder battery — mirrors flink-ml-core GraphTest.java /
GraphBuilderTest.java: DAG wiring, estimator+model semantics, model-data
edges, save/load."""

import numpy as np

from flink_ml_tpu.graph import Graph, GraphBuilder, GraphModel
from flink_ml_tpu.table import Table
from flink_ml_tpu.models.feature.standardscaler import StandardScaler
from flink_ml_tpu.models.feature.minmaxscaler import MinMaxScaler, MinMaxScalerModel
from flink_ml_tpu.models.classification.logisticregression import LogisticRegression


def _train_table():
    rng = np.random.RandomState(0)
    X = np.vstack([rng.randn(100, 4) + 2, rng.randn(100, 4) - 2])
    y = np.array([1.0] * 100 + [0.0] * 100)
    return Table({"features": X, "label": y})


def test_chained_estimators():
    """scaler -> lr chained through the builder behaves like a Pipeline."""
    builder = GraphBuilder()
    source = builder.create_table_id()
    scaler = StandardScaler().set_input_col("features").set_output_col("scaled")
    lr = LogisticRegression().set_features_col("scaled").set_max_iter(20)
    scaled = builder.add_estimator(scaler, [source])
    outputs = builder.add_estimator(lr, [scaled[0]])
    graph = builder.build_estimator([source], [outputs[0]])

    t = _train_table()
    model = graph.fit(t)
    assert isinstance(model, GraphModel)
    out = model.transform(t)[0]
    pred = np.asarray(out.column("prediction"))
    assert (pred == np.asarray(t.column("label"))).mean() > 0.95


def test_algo_operator_nodes():
    from flink_ml_tpu.models.feature.vectorassembler import VectorAssembler

    builder = GraphBuilder()
    source = builder.create_table_id()
    assembler = VectorAssembler().set_input_cols("a", "b").set_output_col("vec")
    outputs = builder.add_algo_operator(assembler, source)
    op = builder.build_algo_operator([source], [outputs[0]])
    t = Table({"a": [1.0, 2.0], "b": [3.0, 4.0]})
    out = op.transform(t)[0]
    np.testing.assert_array_equal(np.asarray(out.column("vec")), [[1, 3], [2, 4]])


def test_model_data_edges():
    """getModelDataFromEstimator -> setModelDataOnModel wiring
    (GraphBuilder.java:169-257)."""
    builder = GraphBuilder()
    source = builder.create_table_id()
    scaler = MinMaxScaler()
    builder.add_estimator(scaler, [source])
    model_data = builder.get_model_data_from_estimator(scaler)

    consumer = MinMaxScalerModel()
    builder.set_model_data_on_model(consumer, model_data[0])
    outputs = builder.add_algo_operator(consumer, source)
    graph = builder.build_estimator([source], [outputs[0]])

    t = Table({"input": np.arange(10, dtype=np.float64)[:, None]})
    model = graph.fit(t)
    out = model.transform(t)[0]
    got = np.asarray(out.column("output"))
    np.testing.assert_allclose(got[:, 0], np.arange(10) / 9.0, atol=1e-7)


def test_save_load_graph(tmp_path):
    builder = GraphBuilder()
    source = builder.create_table_id()
    scaler = StandardScaler().set_input_col("features").set_output_col("scaled")
    lr = LogisticRegression().set_features_col("scaled").set_max_iter(10)
    scaled = builder.add_estimator(scaler, [source])
    outputs = builder.add_estimator(lr, [scaled[0]])
    graph = builder.build_estimator([source], [outputs[0]])

    path = str(tmp_path / "graph")
    graph.save(path)
    loaded = Graph.load(path)
    t = _train_table()
    model = loaded.fit(t)
    out = model.transform(t)[0]
    assert "prediction" in out.column_names


def test_save_load_graph_model(tmp_path):
    builder = GraphBuilder()
    source = builder.create_table_id()
    scaler = StandardScaler().set_input_col("features").set_output_col("scaled")
    lr = LogisticRegression().set_features_col("scaled").set_max_iter(10)
    scaled = builder.add_estimator(scaler, [source])
    outputs = builder.add_estimator(lr, [scaled[0]])
    graph = builder.build_estimator([source], [outputs[0]])
    t = _train_table()
    model = graph.fit(t)
    expected = np.asarray(model.transform(t)[0].column("prediction"))

    path = str(tmp_path / "graph_model")
    model.save(path)
    loaded = GraphModel.load(path)
    got = np.asarray(loaded.transform(t)[0].column("prediction"))
    np.testing.assert_array_equal(got, expected)


def test_unsatisfiable_graph_raises():
    import pytest

    builder = GraphBuilder()
    source = builder.create_table_id()
    dangling = builder.create_table_id()  # never produced
    scaler = StandardScaler()
    outputs = builder.add_estimator(scaler, [dangling])
    graph = builder.build_estimator([source], [outputs[0]])
    with pytest.raises(ValueError):
        graph.fit(Table({"input": [[1.0]]}))


def test_duplicate_stage_rejected():
    import pytest

    builder = GraphBuilder()
    source = builder.create_table_id()
    scaler = StandardScaler()
    builder.add_estimator(scaler, [source])
    with pytest.raises(ValueError):
        builder.add_estimator(scaler, [source])
