"""Fused-vs-eager transform parity + sync-budget suite.

The fusion planner's contract (pipeline.py): compiling a run of fusable
stages into one device program changes WHEN work is dispatched, never WHAT
is computed — outputs are bit-identical to the eager per-stage path for
every fusable stage alone, for composed device-only pipelines, and for
mixed host/device pipelines that force segment breaks. The sync-budget
tests pin the perf claim itself: an all-device 5-stage pipeline transform
runs as ONE device program with ONE transform-path host sync, independent
of stage count.
"""

import numpy as np
import pytest

import jax

from flink_ml_tpu import config
from flink_ml_tpu.linalg import Vectors
from flink_ml_tpu.pipeline import PipelineModel
from flink_ml_tpu.table import SparseBatch, Table
from flink_ml_tpu.utils import metrics

RNG = np.random.RandomState(7)


# ---------------------------------------------------------------------------
# fixtures: one builder per fusable stage -> (stage, host input columns)
# ---------------------------------------------------------------------------

def _mat(n=9, d=4, scale=1.0):
    return (RNG.randn(n, d) * scale).astype(np.float32)


def _standard_scaler():
    from flink_ml_tpu.models.feature.standardscaler import StandardScalerModel

    m = StandardScalerModel()
    m.mean = RNG.randn(4)
    m.std = np.abs(RNG.randn(4)) + 0.1
    m.set_input_col("features").set_output_col("out")
    return m, {"features": _mat()}


def _minmax_scaler():
    from flink_ml_tpu.models.feature.minmaxscaler import MinMaxScalerModel

    m = MinMaxScalerModel()
    m.min_vector = np.array([-1.0, 0.0, -2.0, 0.5])
    m.max_vector = np.array([1.0, 0.0, 3.0, 2.5])  # col 1 constant-span
    m.set_input_col("features").set_output_col("out")
    return m, {"features": _mat()}


def _maxabs_scaler():
    from flink_ml_tpu.models.feature.maxabsscaler import MaxAbsScalerModel

    m = MaxAbsScalerModel()
    m.max_abs = np.array([2.0, 0.0, 1.5, 4.0])
    m.set_input_col("features").set_output_col("out")
    return m, {"features": _mat()}


def _robust_scaler():
    from flink_ml_tpu.models.feature.robustscaler import RobustScalerModel

    m = RobustScalerModel()
    m.medians = RNG.randn(4)
    m.ranges = np.abs(RNG.randn(4))
    m.set_input_col("features").set_output_col("out")
    return m, {"features": _mat()}


def _normalizer():
    from flink_ml_tpu.models.feature.normalizer import Normalizer

    return (
        Normalizer().set_p(3.0).set_input_col("features").set_output_col("out"),
        {"features": _mat()},
    )


def _binarizer():
    from flink_ml_tpu.models.feature.binarizer import Binarizer

    stage = (
        Binarizer()
        .set_input_cols("a", "b")
        .set_output_cols("oa", "ob")
        .set_thresholds(0.0, 0.5)
    )
    return stage, {
        "a": RNG.randn(9).astype(np.float32),
        "b": RNG.rand(9).astype(np.float32),
    }


def _bucketizer():
    from flink_ml_tpu.models.feature.bucketizer import Bucketizer

    stage = (
        Bucketizer()
        .set_input_cols("a")
        .set_output_cols("oa")
        .set_splits_array([[-10.0, -0.5, 0.0, 0.5, 10.0]])
    )
    return stage, {"a": RNG.randn(9).astype(np.float32)}


def _dct():
    from flink_ml_tpu.models.feature.dct import DCT

    return (
        DCT().set_input_col("features").set_output_col("out"),
        {"features": _mat(d=8)},
    )


def _elementwise_product():
    from flink_ml_tpu.models.feature.elementwiseproduct import ElementwiseProduct

    stage = (
        ElementwiseProduct()
        .set_scaling_vec(Vectors.dense(1.5, -2.0, 0.0, 4.0))
        .set_input_col("features")
        .set_output_col("out")
    )
    return stage, {"features": _mat()}


def _idf():
    from flink_ml_tpu.models.feature.idf import IDFModel

    m = IDFModel()
    m.idf = np.abs(RNG.randn(4))
    m.doc_freq = np.arange(1, 5).astype(np.float64)
    m.num_docs = 9
    m.set_input_col("features").set_output_col("out")
    return m, {"features": _mat()}


def _imputer():
    from flink_ml_tpu.models.feature.imputer import ImputerModel

    m = ImputerModel()
    m.surrogates = {"a": 1.25, "b": -3.0}
    m.set_input_cols("a", "b").set_output_cols("oa", "ob")
    a = RNG.randn(9).astype(np.float32)
    b = RNG.randn(9).astype(np.float32)
    a[::3] = np.nan
    b[1::4] = np.nan
    return m, {"a": a, "b": b}


def _interaction():
    from flink_ml_tpu.models.feature.interaction import Interaction

    stage = Interaction().set_input_cols("va", "vb").set_output_col("out")
    return stage, {"va": _mat(d=2), "vb": _mat(d=3)}


def _kbins():
    from flink_ml_tpu.models.feature.kbinsdiscretizer import KBinsDiscretizerModel

    m = KBinsDiscretizerModel()
    m.bin_edges = [
        np.array([-np.inf, -0.5, 0.5, np.inf]),
        np.array([-np.inf, 0.0, np.inf]),
    ]
    m.set_input_col("features").set_output_col("out")
    return m, {"features": _mat(d=2)}


def _onehot():
    from flink_ml_tpu.models.feature.onehotencoder import OneHotEncoderModel

    m = OneHotEncoderModel()
    m.category_sizes = np.array([4, 3])
    m.set_input_cols("a", "b").set_output_cols("oa", "ob")
    return m, {
        "a": RNG.randint(0, 4, size=9).astype(np.float32),
        "b": RNG.randint(0, 3, size=9).astype(np.float32),
    }


def _poly():
    from flink_ml_tpu.models.feature.polynomialexpansion import PolynomialExpansion

    return (
        PolynomialExpansion().set_degree(3).set_input_col("features").set_output_col("out"),
        {"features": _mat(d=3)},
    )


def _univariate_selector():
    from flink_ml_tpu.models.feature.univariatefeatureselector import (
        UnivariateFeatureSelectorModel,
    )

    m = UnivariateFeatureSelectorModel()
    m.indices = np.array([2, 0])
    m.set_features_col("features").set_output_col("out")
    return m, {"features": _mat()}


def _variance_selector():
    from flink_ml_tpu.models.feature.variancethresholdselector import (
        VarianceThresholdSelectorModel,
    )

    m = VarianceThresholdSelectorModel()
    m.indices = np.array([0, 3])
    m.set_input_col("features").set_output_col("out")
    return m, {"features": _mat()}


def _vector_assembler():
    from flink_ml_tpu.models.feature.vectorassembler import VectorAssembler

    stage = VectorAssembler().set_input_cols("va", "vb").set_output_col("out")
    return stage, {"va": _mat(d=2), "vb": _mat(d=3)}


def _vector_slicer():
    from flink_ml_tpu.models.feature.vectorslicer import VectorSlicer

    stage = VectorSlicer().set_indices(3, 1).set_input_col("features").set_output_col("out")
    return stage, {"features": _mat()}


def _linear_regression():
    from flink_ml_tpu.models.regression.linearregression import LinearRegressionModel

    m = LinearRegressionModel()
    m.coefficient = RNG.randn(4)
    m.set_features_col("features").set_prediction_col("pred")
    return m, {"features": _mat()}


def _logistic_regression():
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegressionModel,
    )

    m = LogisticRegressionModel()
    m.coefficient = RNG.randn(4)
    m.set_features_col("features").set_prediction_col("pred")
    return m, {"features": _mat()}


def _linear_svc():
    from flink_ml_tpu.models.classification.linearsvc import LinearSVCModel

    m = LinearSVCModel()
    m.coefficient = RNG.randn(4)
    m.set_features_col("features").set_prediction_col("pred")
    return m, {"features": _mat()}


def _kmeans():
    from flink_ml_tpu.models.clustering.kmeans import KMeansModel

    m = KMeansModel()
    m.centroids = RNG.randn(3, 4).astype(np.float64)
    m.weights = np.ones(3)
    m.set_features_col("features").set_prediction_col("pred")
    return m, {"features": _mat()}


def _online_kmeans():
    from flink_ml_tpu.models.clustering.onlinekmeans import OnlineKMeansModel

    m = OnlineKMeansModel()
    m.publish_model_arrays((RNG.randn(3, 4), np.ones(3)), 2)
    m.set_features_col("features").set_prediction_col("pred")
    return m, {"features": _mat()}


def _online_logistic_regression():
    from flink_ml_tpu.models.classification.onlinelogisticregression import (
        OnlineLogisticRegressionModel,
    )

    m = OnlineLogisticRegressionModel()
    m.publish_model_arrays((RNG.randn(4),), 3)
    m.set_features_col("features").set_prediction_col("pred")
    return m, {"features": _mat()}


STAGE_BUILDERS = {
    "StandardScalerModel": _standard_scaler,
    "MinMaxScalerModel": _minmax_scaler,
    "MaxAbsScalerModel": _maxabs_scaler,
    "RobustScalerModel": _robust_scaler,
    "Normalizer": _normalizer,
    "Binarizer": _binarizer,
    "Bucketizer": _bucketizer,
    "DCT": _dct,
    "ElementwiseProduct": _elementwise_product,
    "IDFModel": _idf,
    "ImputerModel": _imputer,
    "Interaction": _interaction,
    "KBinsDiscretizerModel": _kbins,
    "OneHotEncoderModel": _onehot,
    "PolynomialExpansion": _poly,
    "UnivariateFeatureSelectorModel": _univariate_selector,
    "VarianceThresholdSelectorModel": _variance_selector,
    "VectorAssembler": _vector_assembler,
    "VectorSlicer": _vector_slicer,
    "LinearRegressionModel": _linear_regression,
    "LogisticRegressionModel": _logistic_regression,
    "LinearSVCModel": _linear_svc,
    "KMeansModel": _kmeans,
    "OnlineKMeansModel": _online_kmeans,
    "OnlineLogisticRegressionModel": _online_logistic_regression,
}


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _device_table(cols):
    out = {}
    for name, col in cols.items():
        if isinstance(col, SparseBatch):
            out[name] = SparseBatch(
                col.size, jax.device_put(col.indices), jax.device_put(col.values)
            )
        else:
            out[name] = jax.device_put(col)
    return Table(out)


def _assert_columns_identical(fused: Table, eager: Table):
    assert sorted(fused.column_names) == sorted(eager.column_names)
    for name in fused.column_names:
        a, b = fused.column(name), eager.column(name)
        if isinstance(a, SparseBatch) or isinstance(b, SparseBatch):
            assert isinstance(a, SparseBatch) and isinstance(b, SparseBatch), name
            assert a.size == b.size, name
            assert np.array_equal(np.asarray(a.indices), np.asarray(b.indices)), name
            assert np.array_equal(np.asarray(a.values), np.asarray(b.values)), name
            continue
        a, b = np.asarray(a), np.asarray(b)
        assert a.shape == b.shape and a.dtype == b.dtype, (
            name, a.shape, b.shape, a.dtype, b.dtype
        )
        equal_nan = a.dtype.kind == "f"
        assert np.array_equal(a, b, equal_nan=equal_nan), (
            f"column {name} differs between fused and eager paths"
        )


def _run_both(stages, cols, expect_fused_stages=None):
    """Transform a device-born table through `stages` fused and eager;
    assert bit-identical outputs. Returns (fused, eager) tables."""
    pm = PipelineModel(stages)
    fused = pm.transform(_device_table(cols))[0]
    if expect_fused_stages is not None:
        # the parity claim is vacuous if the plan silently fell back
        assert metrics.get_gauge("pipeline.fused_stages") == expect_fused_stages
    with config.pipeline_fusion_mode("off"):
        eager = pm.transform(_device_table(cols))[0]
    _assert_columns_identical(fused, eager)
    return fused, eager


# ---------------------------------------------------------------------------
# parity: every fusable stage alone
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("name", sorted(STAGE_BUILDERS))
def test_single_stage_parity(name):
    stage, cols = STAGE_BUILDERS[name]()
    _run_both([stage], cols, expect_fused_stages=1)


def test_every_kernel_stage_is_covered():
    """The parametrized parity list tracks the actual kernel population:
    a stage gaining a transform_kernel must gain a parity builder."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "check_fusion_coverage",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts",
            "check_fusion_coverage.py",
        ),
    )
    checker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(checker)
    from flink_ml_tpu.api import AlgoOperator

    with_kernel = {
        cls.__name__
        for cls in checker._iter_stage_classes()
        if cls.transform_kernel is not AlgoOperator.transform_kernel
    }
    missing = with_kernel - set(STAGE_BUILDERS)
    assert not missing, f"stages with kernels but no parity builder: {sorted(missing)}"


def test_sparse_input_parity():
    """Sparse-capable kernels (linear models) keep SparseBatch columns in
    HBM through the fused program."""
    from flink_ml_tpu.models.classification.logisticregression import (
        LogisticRegressionModel,
    )

    m = LogisticRegressionModel()
    m.coefficient = RNG.randn(16)
    m.set_features_col("features").set_prediction_col("pred")
    indices = RNG.randint(0, 16, size=(9, 3)).astype(np.int32)
    values = RNG.rand(9, 3).astype(np.float32)
    batch = SparseBatch(16, indices, values)
    _run_both([m], {"features": batch}, expect_fused_stages=1)


# ---------------------------------------------------------------------------
# parity: composed pipelines
# ---------------------------------------------------------------------------

def _five_stage_device_pipeline():
    """All-device 5-stage pipeline, one fused segment, two guard stages
    (VectorAssembler handleInvalid=error + Bucketizer error): the eager
    path pays one probe sync per guard stage, the fused path exactly one
    packed drain at exit."""
    from flink_ml_tpu.models.feature.binarizer import Binarizer
    from flink_ml_tpu.models.feature.bucketizer import Bucketizer
    from flink_ml_tpu.models.feature.normalizer import Normalizer
    from flink_ml_tpu.models.feature.standardscaler import StandardScalerModel
    from flink_ml_tpu.models.feature.vectorassembler import VectorAssembler

    ss = StandardScalerModel()
    ss.mean = RNG.randn(5)
    ss.std = np.abs(RNG.randn(5)) + 0.1
    ss.set_input_col("assembled").set_output_col("scaled")
    stages = [
        VectorAssembler().set_input_cols("va", "vb").set_output_col("assembled"),
        ss,
        Normalizer().set_p(2.0).set_input_col("scaled").set_output_col("norm"),
        Bucketizer()
        .set_input_cols("raw")
        .set_output_cols("bucket")
        .set_splits_array([[-100.0, -1.0, 0.0, 1.0, 100.0]]),
        Binarizer().set_input_cols("bucket").set_output_cols("bin").set_thresholds(1.5),
    ]
    cols = {
        "va": _mat(d=2),
        "vb": _mat(d=3),
        "raw": RNG.randn(9).astype(np.float32),
    }
    return stages, cols


def test_five_stage_device_pipeline_parity():
    stages, cols = _five_stage_device_pipeline()
    _run_both(stages, cols, expect_fused_stages=5)
    assert metrics.get_gauge("pipeline.fused_segments") == 1


def test_chained_producer_consumer_parity():
    """Columns produced mid-segment feed later kernels without leaving the
    program (scaler -> normalizer -> slicer chain on the same column)."""
    from flink_ml_tpu.models.feature.normalizer import Normalizer
    from flink_ml_tpu.models.feature.standardscaler import StandardScalerModel
    from flink_ml_tpu.models.feature.vectorslicer import VectorSlicer

    ss = StandardScalerModel()
    ss.mean = RNG.randn(4)
    ss.std = np.abs(RNG.randn(4)) + 0.1
    ss.set_input_col("features").set_output_col("scaled")
    stages = [
        ss,
        Normalizer().set_p(2.0).set_input_col("scaled").set_output_col("norm"),
        VectorSlicer().set_indices(0, 2).set_input_col("norm").set_output_col("out"),
    ]
    _run_both(stages, {"features": _mat()}, expect_fused_stages=3)


def test_mixed_host_device_pipeline_segment_break():
    """A host-only stage mid-pipeline splits the plan into two fused
    segments; outputs still bit-identical to eager."""
    from flink_ml_tpu.models.feature.normalizer import Normalizer
    from flink_ml_tpu.models.feature.standardscaler import StandardScalerModel
    from flink_ml_tpu.models.feature.tokenizer import Tokenizer

    ss = StandardScalerModel()
    ss.mean = RNG.randn(4)
    ss.std = np.abs(RNG.randn(4)) + 0.1
    ss.set_input_col("features").set_output_col("scaled")
    stages = [
        ss,
        Tokenizer().set_input_col("text").set_output_col("tokens"),
        Normalizer().set_p(2.0).set_input_col("scaled").set_output_col("norm"),
    ]
    cols = {
        "features": _mat(),
        "text": np.array(["a b c"] * 9, dtype=object),
    }
    pm = PipelineModel(stages)
    table = _device_table({"features": cols["features"]}).with_column("text", cols["text"])
    fused = pm.transform(table)[0]
    assert metrics.get_gauge("pipeline.fused_segments") == 2
    assert metrics.get_gauge("pipeline.fused_stages") == 2
    with config.pipeline_fusion_mode("off"):
        eager = pm.transform(table)[0]
    for name in ("scaled", "norm"):
        assert np.array_equal(
            np.asarray(fused.column(name)), np.asarray(eager.column(name))
        )
    assert fused.column("tokens")[0] == eager.column("tokens")[0]


def test_host_input_falls_back_to_eager():
    """Host-born input can't feed a device program — the segment falls
    back to per-stage eager, still correct."""
    stage, cols = _standard_scaler()
    pm = PipelineModel([stage])
    host_out = pm.transform(Table(dict(cols)))[0]
    assert metrics.get_gauge("pipeline.fused_stages") == 0
    with config.pipeline_fusion_mode("off"):
        eager = pm.transform(Table(dict(cols)))[0]
    assert np.array_equal(np.asarray(host_out.column("out")), np.asarray(eager.column("out")))


def test_guard_error_parity():
    """A validation failure raises the same error from the fused drain as
    from the eager probe — deferred, not dropped."""
    from flink_ml_tpu.models.feature.bucketizer import Bucketizer

    stage = (
        Bucketizer()
        .set_input_cols("a")
        .set_output_cols("oa")
        .set_splits_array([[0.0, 1.0, 2.0]])
    )
    cols = {"a": np.array([0.5, 1.5, 99.0], dtype=np.float32)}  # 99 out of range
    pm = PipelineModel([stage])
    with pytest.raises(ValueError, match="invalid value"):
        pm.transform(_device_table(cols))
    with config.pipeline_fusion_mode("off"):
        with pytest.raises(ValueError, match="invalid value"):
            pm.transform(_device_table(cols))


def test_param_change_invalidates_plan():
    """A param change after the first fused transform must recompile the
    plan (params are trace-time constants), not serve stale outputs."""
    from flink_ml_tpu.models.feature.binarizer import Binarizer

    stage = Binarizer().set_input_cols("a").set_output_cols("oa").set_thresholds(0.0)
    cols = {"a": np.array([-1.0, 0.5, 2.0], dtype=np.float32)}
    pm = PipelineModel([stage])
    out1 = pm.transform(_device_table(cols))[0]
    assert np.asarray(out1.column("oa")).tolist() == [0.0, 1.0, 1.0]
    stage.set_thresholds(1.0)
    out2 = pm.transform(_device_table(cols))[0]
    assert np.asarray(out2.column("oa")).tolist() == [0.0, 0.0, 1.0]


def test_model_array_change_invalidates_plan():
    from flink_ml_tpu.models.feature.standardscaler import StandardScalerModel

    m = StandardScalerModel()
    m.mean = np.zeros(2)
    m.std = np.ones(2)
    m.set_with_mean(True).set_with_std(True).set_input_col("f").set_output_col("o")
    cols = {"f": np.ones((3, 2), dtype=np.float32)}
    pm = PipelineModel([m])
    out1 = np.asarray(pm.transform(_device_table(cols))[0].column("o"))
    m.mean = np.ones(2)  # re-assignment, the codebase's model-update idiom
    out2 = np.asarray(pm.transform(_device_table(cols))[0].column("o"))
    assert np.allclose(out1, 1.0) and np.allclose(out2, 0.0)


# ---------------------------------------------------------------------------
# sync budget: the perf claim itself
# ---------------------------------------------------------------------------

def _transform_sync_count(fn):
    before = metrics.snapshot()["counters"].get("iteration.host_sync.transform", 0)
    fn()
    after = metrics.snapshot()["counters"].get("iteration.host_sync.transform", 0)
    return after - before


def test_five_stage_sync_budget():
    """All-device 5-stage pipeline: ONE device program, ONE transform-path
    host sync fused (was one per guard-probing stage eagerly)."""
    stages, cols = _five_stage_device_pipeline()
    pm = PipelineModel(stages)
    table = _device_table(cols)
    pm.transform(table)  # warm: compile outside the measurement

    fused_syncs = _transform_sync_count(lambda: pm.transform(table))
    assert fused_syncs == 1, f"fused transform paid {fused_syncs} syncs, wanted 1"
    assert metrics.get_gauge("pipeline.fused_segments") == 1
    assert metrics.get_gauge("pipeline.fused_stages") == 5

    with config.pipeline_fusion_mode("off"):
        pm.transform(table)
        eager_syncs = _transform_sync_count(lambda: pm.transform(table))
    assert eager_syncs == 2, (
        f"eager path should pay one probe sync per guard stage (2), got {eager_syncs}"
    )


def test_guard_free_pipeline_is_sync_free():
    """With no validation guards in the segment, the fused transform
    dispatches asynchronously — zero blocking transform syncs."""
    stages, cols = _five_stage_device_pipeline()
    guard_free = [stages[1], stages[2]]  # scaler + normalizer only
    pm = PipelineModel(guard_free)
    table = _device_table({"assembled": _mat(d=5)})
    pm.transform(table)
    assert _transform_sync_count(lambda: pm.transform(table)) == 0
