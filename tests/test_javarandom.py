"""java.util.Random stream-compatibility tests (utils/javarandom.py).

Golden values are the publicly documented outputs of java.util.Random's
specified 48-bit LCG (e.g. new Random(0).nextLong()).
"""

import numpy as np

from flink_ml_tpu.utils.javarandom import JavaRandom


def test_next_int_golden():
    r = JavaRandom(0)
    assert r.next_int() == -1155484576  # new Random(0).nextInt()
    assert r.next_int() == -723955400


def test_next_int_bounded_regression():
    # Regression pins for the rejection-sampling bounded path; the bounded
    # path's Java-parity is independently proven by the MinHashLSH golden
    # test (reference-generated hashes reproduce exactly from next_int(bound)).
    r = JavaRandom(42)
    assert [r.next_int(100) for _ in range(4)] == [30, 63, 48, 84]


def test_next_long_golden():
    assert JavaRandom(0).next_long() == -4962768465676381896  # documented value


def test_next_long_wraps_to_signed_64():
    """hi == Integer.MIN_VALUE with negative lo overflows Java's long and
    wraps; the Python port must wrap identically instead of growing an
    unbounded int."""

    class Stub(JavaRandom):
        def __init__(self, values):
            self._values = list(values)

        def _next(self, bits):
            return self._values.pop(0)

    v = Stub([-(1 << 31), -1]).next_long()
    assert v == (1 << 63) - 1  # Java: (-2^63) + (-1) wraps to Long.MAX_VALUE
    assert -(1 << 63) <= v < (1 << 63)


def test_next_double_range():
    r = JavaRandom(7)
    xs = np.asarray([r.next_double() for _ in range(100)])
    assert np.all((xs >= 0.0) & (xs < 1.0))
