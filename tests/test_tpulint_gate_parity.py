"""Port parity for the four legacy gate scripts.

The standalone ``scripts/check_*.py`` gates were ported onto the tpulint
engine (flink_ml_tpu/analysis/) with the original CLIs kept as thin
shims. These tests pin the port: on the current tree every shim must
produce BYTE-IDENTICAL stdout and the same exit code as the pre-port
script (vendored verbatim under tests/fixtures/legacy_gates/), and the
structured ``find_violations()`` payloads must match element-for-element.
"""

import importlib.util
import io
import os
import sys
from contextlib import redirect_stdout

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LEGACY_DIR = os.path.join(REPO, "tests", "fixtures", "legacy_gates")
SHIM_DIR = os.path.join(REPO, "scripts")

GATES = [
    "check_collective_accounting",
    "check_upload_accounting",
    "check_fusion_coverage",
    "check_checkpoint_coverage",
]


def _load(path, name):
    spec = importlib.util.spec_from_file_location(name, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def _run_main(module):
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = module.main()
    return rc, buf.getvalue()


@pytest.mark.parametrize("gate", GATES)
def test_shim_reports_byte_identical_to_legacy(gate):
    legacy = _load(os.path.join(LEGACY_DIR, f"{gate}.py"), f"legacy_{gate}")
    shim = _load(os.path.join(SHIM_DIR, f"{gate}.py"), f"shim_{gate}")

    legacy_violations = legacy.find_violations()
    shim_violations = shim.find_violations()
    assert shim_violations == legacy_violations

    legacy_rc, legacy_out = _run_main(legacy)
    shim_rc, shim_out = _run_main(shim)
    assert shim_rc == legacy_rc == 0
    assert shim_out == legacy_out  # byte-identical report


def test_text_gate_shims_find_planted_violations(tmp_path):
    """The shim keeps the legacy ROOT/SCANNED_DIRS override surface AND
    still finds what the legacy scanner found."""
    planted = tmp_path / "models"
    planted.mkdir()
    (planted / "bad.py").write_text(
        '"""lax.psum(x, axis) and jax.device_put(y) in a docstring: fine."""\n'
        "import jax\n"
        "from jax import lax\n"
        "def f(x):\n"
        "    return jax.device_put(lax.psum(x, 'data'))\n"
    )
    for gate, expected in [
        ("check_collective_accounting", [(os.path.join("models", "bad.py"), 5, "psum")]),
        ("check_upload_accounting", [(os.path.join("models", "bad.py"), 5, "device_put")]),
    ]:
        legacy = _load(os.path.join(LEGACY_DIR, f"{gate}.py"), f"legacy2_{gate}")
        shim = _load(os.path.join(SHIM_DIR, f"{gate}.py"), f"shim2_{gate}")
        results = []
        for module in (legacy, shim):
            module.ROOT = str(tmp_path)
            module.SCANNED_DIRS = ("models",)
            results.append(module.find_violations())
        assert results[0] == results[1] == expected, gate


def test_shared_code_only_is_the_single_copy():
    """The four gates' duplicated ``_code_only`` helpers are gone: the
    shims re-export flink_ml_tpu.analysis.source.code_only."""
    from flink_ml_tpu.analysis.source import code_only

    for gate in ("check_collective_accounting", "check_upload_accounting",
                 "check_checkpoint_coverage"):
        shim = _load(os.path.join(SHIM_DIR, f"{gate}.py"), f"shim3_{gate}")
        assert shim._code_only is code_only, gate
    # and no shim carries its own tokenizer loop anymore
    for gate in GATES:
        with open(os.path.join(SHIM_DIR, f"{gate}.py")) as f:
            src = f.read()
        assert "generate_tokens" not in src, gate
