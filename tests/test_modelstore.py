"""ModelStore — multi-tenant HBM-paged model residency (ISSUE 19).

Pins the paging contract: LRU eviction under the byte budget with
`hbm.live.model` never exceeding it, deterministic page-out (ledger falls
when the store decides), zero recompiles across page cycles (constants
are runtime operands of the same compiled plan), and
lifecycle/quota/serving integration.
"""

import numpy as np
import pytest

from flink_ml_tpu.data.modelstore import ModelStore, ModelStoreBudgetExceeded
from flink_ml_tpu.obs import memledger
from flink_ml_tpu.pipeline import PipelineModel
from flink_ml_tpu.serving import MicroBatchServer
from flink_ml_tpu.table import Table
from flink_ml_tpu.utils import metrics

RNG = np.random.RandomState(7)
D = 64


@pytest.fixture(autouse=True)
def _clean_ledger():
    memledger.reset()
    yield
    memledger.reset()


def _scaler(d=D):
    from flink_ml_tpu.models.feature.standardscaler import StandardScalerModel

    ss = StandardScalerModel()
    ss.mean = RNG.randn(d)
    ss.std = np.abs(RNG.randn(d)) + 0.1
    ss.set_input_col("features").set_output_col("scaled")
    return ss


def _olr(d=16, version=0):
    from flink_ml_tpu.models.classification.onlinelogisticregression import (
        OnlineLogisticRegressionModel,
    )

    m = OnlineLogisticRegressionModel()
    m.publish_model_arrays((np.ones(d),), version)
    m.set_features_col("features").set_prediction_col("pred")
    return m


def _feature_batch(n, d=D):
    return Table({"features": RNG.randn(n, d).astype(np.float32)})


def _est(model) -> int:
    """One model's host-side admission estimate via a throwaway store."""
    probe = ModelStore(budget_bytes=None, name="probe")
    probe.register("x", model)
    est = probe.estimated_nbytes("x")
    probe.unregister("x")
    return est


def _dev(model) -> int:
    """One model's actual device-resident bytes (< the host estimate
    under default x64-disabled canonicalization)."""
    probe = ModelStore(budget_bytes=None, name="probe")
    probe.register("x", model)
    probe.page_in("x")
    dev = probe.stats["bytes"]
    probe.unregister("x")
    return dev


# ---------------------------------------------------------------------------
# registry + budget admission
# ---------------------------------------------------------------------------

def test_register_estimate_contains_unregister():
    store = ModelStore(budget_bytes=None)
    store.register("a", _scaler())
    assert "a" in store and store.keys() == ["a"]
    # mean + std, float64 host arrays
    assert store.estimated_nbytes("a") == 2 * D * 8
    store.unregister("a")
    assert "a" not in store and store.keys() == []
    with pytest.raises(KeyError):
        store.acquire("a")


def test_oversized_model_rejected_with_numbers():
    one = _est(_scaler())
    store = ModelStore(budget_bytes=one - 1)
    with pytest.raises(ModelStoreBudgetExceeded) as ei:
        store.register("big", _scaler())
    assert ei.value.key == "big"
    assert ei.value.nbytes == one
    assert ei.value.budget == one - 1


def test_rejects_non_model_types():
    store = ModelStore(budget_bytes=None)
    with pytest.raises(TypeError):
        store.register("x", object())


# ---------------------------------------------------------------------------
# LRU paging under the byte budget
# ---------------------------------------------------------------------------

def test_lru_eviction_and_budget_never_exceeded():
    est, dev = _est(_scaler()), _dev(_scaler())
    # admission is conservative (host estimate): a page-in fits while
    # `used + est <= budget`. Two residents fit; a third must evict.
    budget = 2 * dev + est - 1
    store = ModelStore(budget_bytes=budget)
    for key in ("a", "b", "c"):
        store.register(key, _scaler())

    def check_budget():
        assert memledger.live_bytes("model") <= budget
        assert store.stats["bytes"] <= budget
        store.check_ledger_parity()

    store.page_in("a")
    check_budget()
    store.page_in("b")
    check_budget()
    assert sorted(store.resident_keys()) == ["a", "b"]
    store.page_in("c")  # evicts a — the least recently used
    check_budget()
    assert sorted(store.resident_keys()) == ["b", "c"]
    store.acquire("b")  # touch: b becomes most recently used
    store.page_in("a")  # evicts c, not b
    check_budget()
    assert sorted(store.resident_keys()) == ["a", "b"]
    s = store.stats
    assert s["models"] == 3 and s["resident"] == 2
    assert s["evictions"] == 2
    assert s["misses"] == 4 and s["hits"] == 1


def test_page_out_releases_ledger_deterministically():
    store = ModelStore(budget_bytes=None)
    store.register("a", _scaler())
    base = memledger.live_bytes("model")
    store.page_in("a")
    resident = memledger.live_bytes("model")
    assert resident > base
    assert store.stats["bytes"] == resident - base
    store.page_out("a")
    # no GC grace: invalidation dropped the only reference, so the
    # tracked entries' finalizers already ran (CPython refcounting)
    assert memledger.live_bytes("model") == base
    assert store.stats["bytes"] == 0 and store.resident_keys() == []
    store.check_ledger_parity()


def test_prefetch_warms_off_the_dispatch_path():
    store = ModelStore(budget_bytes=None)
    store.register("a", _scaler())
    store.register("b", _scaler())
    before = metrics.get_counter("modelstore.prefetch", 0)
    store.prefetch(["a", "b"])  # wait=True
    assert sorted(store.resident_keys()) == ["a", "b"]
    assert metrics.get_counter("modelstore.prefetch", 0) == before + 2
    store.page_out("a")
    worker = store.prefetch(["a"], wait=False)
    worker.join(timeout=30)
    assert not worker.is_alive()
    assert sorted(store.resident_keys()) == ["a", "b"]
    # both already resident: a hit, not a restage
    s = store.stats
    store.prefetch(["a", "b"])
    assert store.stats["hits"] == s["hits"] + 2


# ---------------------------------------------------------------------------
# zero recompiles across page cycles (the servingSlo pin, in miniature)
# ---------------------------------------------------------------------------

def test_paging_cycles_never_recompile():
    """Page a model out and back N times while serving: the constants are
    runtime operands, so every cycle re-uploads into the SAME compiled
    program — `jit.compiles` stays flat after warmup."""
    from flink_ml_tpu.obs import tracing

    tracing.install_jax_hooks()
    pm_a = PipelineModel([_scaler()])
    pm_b = PipelineModel([_scaler()])
    est, dev = _est(_scaler()), _dev(_scaler())
    store = ModelStore(budget_bytes=dev + est - 1)  # only ONE fits
    store.register("a", pm_a)
    store.register("b", pm_b)

    def serve_once(key):
        server = MicroBatchServer(store.acquire(key), in_flight=1, buckets=(8,))
        outs = list(server.serve(iter([_feature_batch(8)])))
        assert outs[0].num_rows == 8

    serve_once("a")  # warmup: each pipeline owns its fused-segment jit
    serve_once("b")
    before = metrics.get_counter("jit.compiles", 0)
    page_ins_before = metrics.get_counter("modelstore.pageIn", 0)
    for _ in range(3):  # every serve evicts the other model
        serve_once("a")
        serve_once("b")
        assert memledger.live_bytes("model") <= store.budget_bytes
    assert metrics.get_counter("jit.compiles", 0) == before, (
        "steady-state paging must be recompile-free"
    )
    assert metrics.get_counter("modelstore.pageIn", 0) >= page_ins_before + 6
    store.check_ledger_parity()


# ---------------------------------------------------------------------------
# lifecycle + quota + serving integration
# ---------------------------------------------------------------------------

def test_promote_through_store_refreshes_residency():
    from flink_ml_tpu.lifecycle import ModelLifecycle

    model = _olr(d=16, version=1)
    store = ModelStore(budget_bytes=None)
    store.register("t", model, lifecycle=ModelLifecycle(model), quota=4)
    assert store.quota("t") == 4
    assert store.lifecycle("t") is not None
    store.page_in("t")
    c0 = np.asarray(store.acquire("t").device_constants()["coefficient"])
    np.testing.assert_array_equal(c0, np.ones(16))
    mv = store.promote("t", (np.full(16, 2.0),))
    assert mv.version_id == 2
    # the republish restaged under the store's accounting: still resident,
    # parity intact, and the compiled path sees the NEW coefficients
    assert store.resident_keys() == ["t"]
    store.check_ledger_parity()
    c1 = np.asarray(store.acquire("t").device_constants()["coefficient"])
    np.testing.assert_array_equal(c1, np.full(16, 2.0))


def test_promote_without_lifecycle_raises():
    store = ModelStore(budget_bytes=None)
    store.register("t", _olr())
    with pytest.raises(ValueError, match="no lifecycle"):
        store.promote("t", (np.zeros(16),))


def test_external_republish_heals_on_next_page_in():
    """A publish OUTSIDE `promote` invalidates the cached constants; the
    next page_in notices (resident flag vs missing cache), drops the
    stale accounting without counting an eviction, and restages."""
    model = _olr(d=16, version=1)
    store = ModelStore(budget_bytes=None)
    store.register("t", model)
    store.page_in("t")
    evictions = store.stats["evictions"]
    model.publish_model_arrays((np.full(16, 3.0),), 2)  # bypasses the store
    entry = store.page_in("t")  # miss: restage + re-measure
    assert entry.resident
    assert store.stats["evictions"] == evictions
    store.check_ledger_parity()
    np.testing.assert_array_equal(
        np.asarray(store.acquire("t").device_constants()["coefficient"]),
        np.full(16, 3.0),
    )


def test_server_submit_unregistered_tenant_is_typed():
    store = ModelStore(budget_bytes=None)
    store.register("known", PipelineModel([_scaler()]))
    server = MicroBatchServer(store=store, in_flight=1, admission=4)
    with pytest.raises(KeyError, match="ghost"):
        server.submit(_feature_batch(4), tenant="ghost")
    # a store-only server has no default model for tenantless submits
    server.submit(_feature_batch(4), tenant="known")
    server.close()
    results = list(server.results())
    assert [r.status for r in results] == ["ok"]
    assert results[0].tenant == "known"
    h = server.health()
    assert h.modelStore is not None and h.modelStore["models"] == 1


def test_server_requires_model_or_store():
    with pytest.raises(TypeError, match="model"):
        MicroBatchServer()
