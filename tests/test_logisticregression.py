"""LogisticRegression battery — mirrors
flink-ml-lib/src/test/java/org/apache/flink/ml/classification/LogisticRegressionTest.java:
params, fit+transform correctness, save/load, get/set model data."""

import numpy as np
import pytest

from flink_ml_tpu.linalg import Vectors
from flink_ml_tpu.models.classification.logisticregression import (
    LogisticRegression,
    LogisticRegressionModel,
)
from flink_ml_tpu.table import Table

# The reference test's train data: two linearly separable groups
# (LogisticRegressionTest.java binomialDataList).
FEATURES = [
    Vectors.dense(1, 2, 3, 4),
    Vectors.dense(2, 2, 3, 4),
    Vectors.dense(3, 2, 3, 4),
    Vectors.dense(4, 2, 3, 4),
    Vectors.dense(5, 2, 3, 4),
    Vectors.dense(11, 2, 3, 4),
    Vectors.dense(12, 2, 3, 4),
    Vectors.dense(13, 2, 3, 4),
    Vectors.dense(14, 2, 3, 4),
    Vectors.dense(15, 2, 3, 4),
]
LABELS = [0.0, 0.0, 0.0, 0.0, 0.0, 1.0, 1.0, 1.0, 1.0, 1.0]


def _train_table():
    return Table({"features": FEATURES, "label": LABELS, "weight": [1.0] * 10})


def test_param_defaults():
    lr = LogisticRegression()
    assert lr.get_features_col() == "features"
    assert lr.get_label_col() == "label"
    assert lr.get_weight_col() is None
    assert lr.get_max_iter() == 20
    assert lr.get_reg() == 0.0
    assert lr.get_learning_rate() == 0.1
    assert lr.get_global_batch_size() == 32
    assert lr.get_tol() == 1e-6
    assert lr.get_multi_class() == "auto"
    assert lr.get_prediction_col() == "prediction"
    assert lr.get_raw_prediction_col() == "rawPrediction"


def test_fit_and_predict():
    lr = LogisticRegression().set_weight_col("weight").set_max_iter(50)
    model = lr.fit(_train_table())
    out = model.transform(_train_table())[0]
    pred = np.asarray(out.column("prediction"))
    np.testing.assert_array_equal(pred, LABELS)
    raw = np.asarray(out.column("rawPrediction"))
    assert raw.shape == (10, 2)
    # probabilities sum to 1 and align with predictions
    np.testing.assert_allclose(raw.sum(axis=1), 1.0, atol=1e-6)
    assert np.all((raw[:, 1] >= 0.5) == (pred == 1.0))


def test_rejects_non_binomial_labels():
    t = Table({"features": FEATURES, "label": [float(i) for i in range(10)]})
    with pytest.raises(ValueError):
        LogisticRegression().fit(t)


def test_multinomial_rejected():
    with pytest.raises(ValueError):
        LogisticRegression().set_multi_class("multinomial").fit(_train_table())


def test_save_load_model(tmp_path):
    model = LogisticRegression().set_max_iter(30).fit(_train_table())
    path = str(tmp_path / "lr_model")
    model.save(path)
    loaded = LogisticRegressionModel.load(path)
    np.testing.assert_allclose(loaded.coefficient, model.coefficient)
    out = loaded.transform(_train_table())[0]
    np.testing.assert_array_equal(np.asarray(out.column("prediction")), LABELS)


def test_save_load_estimator(tmp_path):
    lr = LogisticRegression().set_max_iter(7).set_learning_rate(0.5)
    path = str(tmp_path / "lr_est")
    lr.save(path)
    loaded = LogisticRegression.load(path)
    assert loaded.get_max_iter() == 7
    assert loaded.get_learning_rate() == 0.5


def test_get_set_model_data():
    model = LogisticRegression().fit(_train_table())
    model_data = model.get_model_data()[0]
    assert "coefficient" in model_data
    other = LogisticRegressionModel().set_model_data(model_data)
    np.testing.assert_allclose(other.coefficient, model.coefficient)
    out = other.transform(_train_table())[0]
    np.testing.assert_array_equal(np.asarray(out.column("prediction")), LABELS)


def test_distributed_fit_matches_single_device(mesh8):
    """Sharded training must give the same coefficients as the math is
    synchronous-SPMD (loss parity across parallelism, as in the reference's
    MiniCluster tests)."""
    lr = LogisticRegression().set_max_iter(10).set_global_batch_size(10)
    model = lr.fit(_train_table())
    assert model.coefficient.shape == (4,)
    out = model.transform(_train_table())[0]
    np.testing.assert_array_equal(np.asarray(out.column("prediction")), LABELS)


def test_regularization_paths_run():
    for reg, en in [(0.1, 0.0), (0.1, 1.0), (0.1, 0.5)]:
        model = (
            LogisticRegression().set_reg(reg).set_elastic_net(en).set_max_iter(5)
        ).fit(_train_table())
        assert np.all(np.isfinite(model.coefficient))
