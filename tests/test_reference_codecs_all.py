"""Every reference model-data format loads and predicts.

Each case materializes a reference-layout stage directory from the shared
family spec table (scripts/make_reference_fixture.py FAMILIES — the same
specs that produce the committed tests/fixtures/reference_*_model
directories, so fixtures and tests cannot drift) and then drives
read_write.load_stage → transform. This is the VERDICT r4 'codecs for
every reference model-data format' done-criterion: any stage's
reference-layout directory loads and predicts."""

import numpy as np

from scripts.make_reference_fixture import FAMILIES, write_metadata
from flink_ml_tpu.table import SparseBatch, Table
from flink_ml_tpu.utils import javacodec as jc
from flink_ml_tpu.utils import read_write


def load_family(tmp_path, family):
    class_name, param_map, payload = FAMILIES[family]
    stage = str(tmp_path / family)
    write_metadata(stage, class_name, param_map)
    jc.write_reference_data_file(stage, payload)
    return read_write.load_stage(stage)


def test_standardscaler(tmp_path):
    out = load_family(tmp_path, "standardscaler").transform(
        Table({"input": np.array([[3.0, 6.0]])})
    )[0]
    np.testing.assert_allclose(np.asarray(out.column("output")), [[1.0, 1.0]])


def test_minmaxscaler(tmp_path):
    out = load_family(tmp_path, "minmaxscaler").transform(
        Table({"input": np.array([[5.0, 20.0]])})
    )[0]
    np.testing.assert_allclose(np.asarray(out.column("output")), [[0.5, 0.5]])


def test_maxabsscaler(tmp_path):
    out = load_family(tmp_path, "maxabsscaler").transform(
        Table({"input": np.array([[2.0, -4.0]])})
    )[0]
    np.testing.assert_allclose(np.asarray(out.column("output")), [[0.5, -0.5]])


def test_robustscaler(tmp_path):
    out = load_family(tmp_path, "robustscaler").transform(
        Table({"input": np.array([[3.0, 6.0]])})
    )[0]
    np.testing.assert_allclose(np.asarray(out.column("output")), [[1.0, 1.0]])


def test_idf(tmp_path):
    out = load_family(tmp_path, "idf").transform(
        Table({"input": np.array([[2.0, 1.0]])})
    )[0]
    np.testing.assert_allclose(
        np.asarray(out.column("output")), [[2.0 * 0.405465, 1.0 * 1.098612]]
    )


def test_imputer(tmp_path):
    out = load_family(tmp_path, "imputer").transform(
        Table({"a": np.array([np.nan, 2.0]), "b": np.array([3.0, np.nan])})
    )[0]
    np.testing.assert_allclose(np.asarray(out.column("ao")), [1.5, 2.0])
    np.testing.assert_allclose(np.asarray(out.column("bo")), [3.0, 9.0])


def test_kbinsdiscretizer(tmp_path):
    out = load_family(tmp_path, "kbinsdiscretizer").transform(
        Table({"input": np.array([[0.5], [1.5]])})
    )[0]
    np.testing.assert_allclose(np.asarray(out.column("output")), [[0.0], [1.0]])


def test_stringindexer(tmp_path):
    out = load_family(tmp_path, "stringindexer").transform(
        Table({"c": np.array(["a", "b"])})
    )[0]
    np.testing.assert_allclose(np.asarray(out.column("ci")), [1.0, 0.0])


def test_indextostring(tmp_path):
    # not a FAMILIES entry: same StringIndexerModelData payload, reverse model
    stage = str(tmp_path / "m")
    write_metadata(
        stage,
        "org.apache.flink.ml.feature.stringindexer.IndexToStringModel",
        {"inputCols": ["ci"], "outputCols": ["c"]},
    )
    jc.write_reference_data_file(stage, jc.encode_stringindexer_model_data([["b", "a"]]))
    out = read_write.load_stage(stage).transform(Table({"ci": np.array([1.0, 0.0])}))[0]
    assert [str(v) for v in out.column("c")] == ["a", "b"]


def test_onehotencoder(tmp_path):
    out = load_family(tmp_path, "onehotencoder").transform(
        Table({"c": np.array([0.0, 2.0])})
    )[0]
    col = out.column("v")
    assert col.row(0).indices.tolist() == [0] and col.row(0).values.tolist() == [1.0]
    assert col.row(1).indices.tolist() == []  # dropLast drops the final category


def test_vectorindexer(tmp_path):
    out = load_family(tmp_path, "vectorindexer").transform(
        Table({"input": np.array([[7.0], [5.0]])})
    )[0]
    np.testing.assert_allclose(np.asarray(out.column("output")), [[1.0], [0.0]])


def test_countvectorizer(tmp_path):
    tokens = np.empty(1, dtype=object)
    tokens[0] = ["pear", "apple", "pear"]
    out = load_family(tmp_path, "countvectorizer").transform(
        Table({"input": tokens})
    )[0]
    row = out.column("output").row(0)
    assert row.indices.tolist() == [0, 1] and row.values.tolist() == [1.0, 2.0]


def test_minhashlsh(tmp_path):
    model = load_family(tmp_path, "minhashlsh")
    np.testing.assert_array_equal(model.rand_coefficient_a, [1, 2, 3, 4, 5, 6])
    np.testing.assert_array_equal(model.rand_coefficient_b, [11, 12, 13, 14, 15, 16])
    vec = SparseBatch(10, np.array([[0, 3]]), np.array([[1.0, 1.0]]))
    out = model.transform(Table({"vec": vec}))[0]
    assert np.asarray(out.column("hashes")).shape[-1] >= 1


def test_univariatefeatureselector(tmp_path):
    out = load_family(tmp_path, "univariatefeatureselector").transform(
        Table({"features": np.array([[1.0, 2.0, 3.0]])})
    )[0]
    np.testing.assert_allclose(np.asarray(out.column("output")), [[2.0]])


def test_variancethresholdselector(tmp_path):
    out = load_family(tmp_path, "variancethresholdselector").transform(
        Table({"input": np.array([[1.0, 2.0, 3.0]])})
    )[0]
    np.testing.assert_allclose(np.asarray(out.column("output")), [[1.0, 3.0]])


def test_naivebayes(tmp_path):
    out = load_family(tmp_path, "naivebayes").transform(
        Table({"features": np.array([[0.0], [1.0]])})
    )[0]
    np.testing.assert_allclose(np.asarray(out.column("prediction")), [10.0, 20.0])


def test_knn(tmp_path):
    out = load_family(tmp_path, "knn").transform(
        Table({"features": np.array([[1.0, 1.0], [9.0, 9.0]])})
    )[0]
    np.testing.assert_allclose(np.asarray(out.column("prediction")), [1.0, 2.0])


def test_knn_multiple_part_records_concatenate(tmp_path):
    """Knn writes one packed record per task bundle; all concatenate."""
    stage = str(tmp_path / "m")
    write_metadata(
        stage,
        "org.apache.flink.ml.classification.knn.KnnModel",
        {"featuresCol": "features", "predictionCol": "prediction", "k": 1},
    )
    jc.write_reference_data_file(
        stage, jc.encode_knn_model_data(np.array([[0.0, 0.0]]), np.array([1.0])), part=0
    )
    jc.write_reference_data_file(
        stage, jc.encode_knn_model_data(np.array([[10.0, 10.0]]), np.array([2.0])), part=1
    )
    out = read_write.load_stage(stage).transform(
        Table({"features": np.array([[9.0, 9.0]])})
    )[0]
    np.testing.assert_allclose(np.asarray(out.column("prediction")), [2.0])


def test_family_table_covers_every_codec():
    """The shared spec table names all 16 non-linear model-data codecs."""
    assert len(FAMILIES) == 16