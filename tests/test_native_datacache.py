"""Native data-cache battery — mirrors the reference's
DataCacheWriteReadTest.java / DataCacheSnapshotTest.java /
ReplayOperatorTest.java shapes: segment roundtrips, spill-under-budget,
replayable streams."""

import os

import numpy as np
import pytest

from flink_ml_tpu.native import available
from flink_ml_tpu.native.datacache import (
    DataCache,
    ReplayableStreamTable,
    parse_csv_doubles,
)
from flink_ml_tpu.table import SparseBatch, Table


def test_native_library_builds():
    assert available(), "g++ toolchain expected in this environment"


def test_append_read_roundtrip():
    cache = DataCache(memory_budget_bytes=1 << 20)
    arrays = [
        np.arange(100, dtype=np.float64).reshape(10, 10),
        np.asarray([1, -2, 3], dtype=np.int32),
        np.random.RandomState(0).rand(5, 7).astype(np.float32),
    ]
    segs = [cache.append_array(a) for a in arrays]
    for seg, a in zip(segs, arrays):
        got = cache.read_array(seg)
        assert got.dtype == a.dtype and got.shape == a.shape
        np.testing.assert_array_equal(got, a)
    assert cache.num_segments == 3
    cache.close()


def test_spill_when_over_budget(tmp_path):
    cache = DataCache(memory_budget_bytes=1024, spill_dir=str(tmp_path))
    small = np.zeros(64, dtype=np.float64)  # 512 bytes
    big = np.arange(512, dtype=np.float64)  # 4096 bytes -> must spill
    s1 = cache.append_array(small)
    s2 = cache.append_array(big)
    s3 = cache.append_array(big * 2)
    assert cache.spilled_segments >= 2
    assert cache.memory_used <= 1024
    np.testing.assert_array_equal(cache.read_array(s1), small)
    np.testing.assert_array_equal(cache.read_array(s2), big)
    np.testing.assert_array_equal(cache.read_array(s3), big * 2)
    cache.close()


def test_replayable_stream(tmp_path):
    batches = [
        Table({"x": np.random.RandomState(i).rand(50, 4), "y": np.arange(50, dtype=np.float64)})
        for i in range(3)
    ]
    replay = ReplayableStreamTable(iter(batches), memory_budget_bytes=1 << 10,
                                  spill_dir=str(tmp_path))
    first = [np.asarray(t.column("x")).copy() for t in replay]
    assert len(first) == 3
    assert replay.stats["spilledSegments"] > 0  # tiny budget forces spill
    # second and third passes replay from the cache
    for _ in range(2):
        second = [np.asarray(t.column("x")) for t in replay]
        for a, b in zip(first, second):
            np.testing.assert_array_equal(a, b)


def test_replayable_sparse_columns():
    sb = SparseBatch(10, [[0, 3], [1, -1]], [[1.0, 2.0], [3.0, 0.0]])
    replay = ReplayableStreamTable(iter([Table({"s": sb})]))
    list(replay)
    (restored,) = list(replay)
    got = restored.column("s")
    np.testing.assert_array_equal(got.indices, sb.indices)
    np.testing.assert_array_equal(got.values, sb.values)


def test_object_columns_rejected():
    t = Table({"words": np.asarray([["a"], ["b"]], dtype=object)})
    replay = ReplayableStreamTable(iter([t]))
    with pytest.raises(TypeError):
        list(replay)


def test_close_removes_spill_file(tmp_path):
    cache = DataCache(memory_budget_bytes=128, spill_dir=str(tmp_path))
    for i in range(4):
        cache.append_array(np.arange(100, dtype=np.float64))
    assert cache.spilled_segments > 0
    spill_path = cache._spill_path
    assert os.path.exists(spill_path), "spill must hit disk for this test to bite"
    cache.close()
    assert not os.path.exists(spill_path), "stale spill file left behind on close"
    cache.close()  # idempotent


def test_del_removes_spill_file(tmp_path):
    import gc

    cache = DataCache(memory_budget_bytes=128, spill_dir=str(tmp_path))
    cache.append_array(np.arange(200, dtype=np.float64))
    spill_path = cache._spill_path
    assert os.path.exists(spill_path)
    del cache
    gc.collect()
    assert not os.path.exists(spill_path), "stale spill file survived __del__"


def test_close_removes_file_even_without_native_destroy(tmp_path):
    """The host-side cleanup holds even when the native teardown did not
    remove the file (crashed native side / older library): close() with a
    dead handle still deletes the segment store."""
    cache = DataCache(memory_budget_bytes=128, spill_dir=str(tmp_path))
    cache.append_array(np.arange(200, dtype=np.float64))
    spill_path = cache._spill_path
    cache._lib.dc_destroy(cache._handle)  # native gone, file still tracked
    cache._handle = None
    with open(spill_path, "wb") as f:  # simulate the leftover store
        f.write(b"stale")
    cache.close()
    assert not os.path.exists(spill_path)


def test_read_array_is_writable_native_and_fallback():
    """In-place consumers (scalers normalizing a replayed batch) mutate
    the returned array; a read-only frombuffer view would crash them."""
    native = DataCache(memory_budget_bytes=1 << 20)
    fallback = DataCache.__new__(DataCache)
    fallback._lib, fallback._handle = None, None
    fallback._segments, fallback._meta, fallback._spilled = [], [], []
    for cache in (native, fallback):
        seg = cache.append_array(np.arange(6, dtype=np.float64).reshape(2, 3))
        got = cache.read_array(seg)
        assert got.flags.writeable
        got *= 2.0  # must not raise
        # the stored segment is untouched: a second read sees the original
        np.testing.assert_array_equal(
            cache.read_array(seg), np.arange(6, dtype=np.float64).reshape(2, 3)
        )
    native.close()


def test_parse_csv_doubles():
    got = parse_csv_doubles("1.5, 2.25\n-3e2; 4,abc,5.5")
    np.testing.assert_array_equal(got, [1.5, 2.25, -300.0, 4.0, 5.5])


def test_parse_csv_performance_smoke():
    text = ",".join(str(float(i)) for i in range(100_000))
    got = parse_csv_doubles(text, expected=100_000)
    assert got.shape == (100_000,)
    np.testing.assert_allclose(got[:5], [0, 1, 2, 3, 4])
