"""Visualize / tabulate benchmark results.

Analogue of the reference's benchmark-results-visualize.py
(flink-ml-dist/src/main/flink-ml-bin/bin/benchmark-results-visualize.py):
same CLI surface (file, --pattern, --x-field, --y-field with dotted
nested-field paths, matplotlib scatter), extended with a --table mode
that renders a throughput-ranked markdown table (the form the sweep
results are reviewed in — this host is often headless).

Accepts either the runner's `--output-file` JSON ({name: {..., results}})
or scripts/bench_sweep.py's benchmarks/SWEEP.json ({meta, entries}).

Usage:
  python scripts/bench_visualize.py benchmarks/SWEEP.json --table
  python scripts/bench_visualize.py results.json --pattern 'kmeans.*' \
      --x-field inputData.paramMap.numValues --y-field results.inputThroughput
"""

from __future__ import annotations

import argparse
import json
import re
import sys


def get_nested_field_value(nested, field_names):
    for field_name in field_names:
        if not isinstance(nested, dict) or field_name not in nested:
            return None
        nested = nested[field_name]
    return nested


def load_rows(file_name: str):
    """-> list of (name, record) with sweep/runner formats unified."""
    with open(file_name) as f:
        data = json.load(f)
    if "entries" in data and "meta" in data:  # bench_sweep.py format
        rows = []
        for key, rec in data["entries"].items():
            row = dict(rec.get("result") or {})
            if "error" in rec:
                row["error"] = rec["error"]
            rows.append((key, {"results": row, **row}))
        return rows
    return [(k, v) for k, v in data.items() if k != "version"]


def print_table(rows) -> None:
    def thr(rec):
        v = get_nested_field_value(rec, ["results", "inputThroughput"])
        return v if isinstance(v, (int, float)) else -1.0

    rows = sorted(rows, key=lambda kv: -thr(kv[1]))
    print(f"| {'benchmark':58s} | {'totalTimeMs':>12s} | {'rec/s':>14s} | phases |")
    print(f"|{'-' * 60}|{'-' * 14}|{'-' * 16}|--------|")
    for name, rec in rows:
        r = rec.get("results", rec)
        if "error" in r and "totalTimeMs" not in r:
            print(f"| {name:58s} | {'ERROR':>12s} | {'-':>14s} | {r['error'][:60]} |")
            continue
        phases = r.get("phaseTimesMs", {})
        phase_str = " ".join(f"{k}:{v:.0f}" for k, v in phases.items())
        print(
            f"| {name:58s} | {r.get('totalTimeMs', 0):12.1f} |"
            f" {r.get('inputThroughput', 0):14.1f} | {phase_str} |"
        )


def main(argv) -> None:
    parser = argparse.ArgumentParser(description="Visualizes benchmark results.")
    parser.add_argument("file_name", help="Json file to acquire benchmark results.")
    parser.add_argument(
        "--pattern",
        default=".*",
        help="Regex of benchmark names to select (default: all).",
    )
    parser.add_argument(
        "--x-field", default="inputData.paramMap.numValues", help="Independent field."
    )
    parser.add_argument(
        "--y-field", default="results.inputThroughput", help="Dependent field."
    )
    parser.add_argument(
        "--table",
        action="store_true",
        help="Print a throughput-ranked markdown table instead of plotting.",
    )
    parser.add_argument(
        "--save", default=None, help="Save the plot to a file instead of showing it."
    )
    args = parser.parse_args(argv)
    pattern = re.compile(args.pattern)
    rows = [(k, v) for k, v in load_rows(args.file_name) if pattern.match(k)]
    if args.table:
        print_table(rows)
        return
    import matplotlib

    if args.save:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    xs, ys = [], []
    for _, rec in rows:
        x = get_nested_field_value(rec, args.x_field.split("."))
        y = get_nested_field_value(rec, args.y_field.split("."))
        if x is not None and y is not None:
            xs.append(x)
            ys.append(y)
    plt.scatter(xs, ys)
    plt.xlabel(args.x_field)
    plt.ylabel(args.y_field)
    plt.title("Benchmark Results Visualization")
    if args.save:
        plt.savefig(args.save, dpi=120, bbox_inches="tight")
        print(f"saved {args.save}")
    else:
        plt.show()


if __name__ == "__main__":
    main(sys.argv[1:])
