#!/usr/bin/env python
"""Multichip collectives microbench — one JSON line per run.

Standalone driver for the `multichipCollectives` BENCH entry (bench.py):
self-provisions an N-virtual-device CPU platform (the dryrun_multichip
substrate — env vars must win before jax's backend initializes, hence a
separate process per device count) and measures, for that N:

- the bucketed all-reduce (`all_reduce_sum_chunked`): bucket count and
  per-participant payload bytes at the configured chunk size, plus warm
  wall time vs the monolithic psum;
- the SparCML index-value gradient reduce at the sparseWideLR shape
  (dim=1M, nnz=39): sparse wire bytes vs the dense-equivalent psum
  payload — the traffic-proportionality number;
- a dense SGD fit with `config.collective_overlap` off vs on (bit-identical
  coefficients asserted) — the overlap schedule's end-to-end wall delta.

Usage: python scripts/bench_collectives.py [--devices N]
Prints exactly one JSON object on the LAST stdout line.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time


def _provision(n_devices: int) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    os.environ["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()


def main(argv) -> int:
    n_devices = 8
    if "--devices" in argv:
        n_devices = int(argv[argv.index("--devices") + 1])
    _provision(n_devices)

    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import numpy as np
    from jax.sharding import PartitionSpec as P

    from flink_ml_tpu import config
    from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD
    from flink_ml_tpu.parallel import collectives as coll
    from flink_ml_tpu.parallel import mesh as mesh_lib
    from flink_ml_tpu.utils import metrics

    mesh = mesh_lib.create_mesh(("data",), devices=jax.devices()[:n_devices])
    result = {"devices": n_devices, "chunkBytes": config.resolve_chunk_bytes(None)}

    def timed_best(fn, repeats=5):
        times = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            times.append(time.perf_counter() - t0)
        return min(times) * 1000.0

    # --- bucketed dense all-reduce: an 8MB f32 gradient ----------------------
    vec = np.random.default_rng(0).standard_normal((n_devices, 2 << 20)).astype(np.float32)
    chunked = jax.jit(
        coll.shard_map_over(
            mesh, P("data", None), P("data", None),
            fn=lambda v: coll.all_reduce_sum_chunked(v),
        )
    )
    mono = jax.jit(
        coll.shard_map_over(
            mesh, P("data", None), P("data", None),
            fn=lambda v: coll.all_reduce_sum(v),
        )
    )
    before = metrics.snapshot()
    out_c, out_m = chunked(vec), mono(vec)  # traces fire the accounting
    assert np.array_equal(np.asarray(out_c), np.asarray(out_m)), "chunked != psum"
    delta = metrics.snapshot_delta(before, metrics.snapshot())
    result["denseAllReduce"] = {
        "payloadBytes": int(vec[0].nbytes),
        "chunkCount": int(delta["counters"].get("collective.chunked.chunks", 1)),
        "collectiveBytes": int(delta["counters"].get("collective.chunked.bytes", 0)),
        "chunkedMs": timed_best(lambda: chunked(vec)),
        "monolithicMs": timed_best(lambda: mono(vec)),
    }

    # --- sparse index-value gradient reduce at the sparseWideLR shape --------
    dim, nnz, rows_per_shard = 1_000_000, 39, 1024
    rng = np.random.default_rng(1)
    idx = rng.integers(0, dim, size=(n_devices, rows_per_shard * nnz)).astype(np.int32)
    val = rng.standard_normal((n_devices, rows_per_shard * nnz)).astype(np.float32)
    sparse_fn = jax.jit(
        coll.shard_map_over(
            mesh, (P("data", None), P("data", None)), P(),
            fn=lambda i, v: coll.sparse_all_reduce_sum(i[0], v[0], dim),
        )
    )
    dense_fn = jax.jit(
        coll.shard_map_over(
            mesh, (P("data", None), P("data", None)), P(),
            fn=lambda i, v: coll.all_reduce_sum_chunked(
                jax.numpy.zeros((dim,), v.dtype).at[i[0]].add(v[0], mode="drop")
            ),
        )
    )
    before = metrics.snapshot()
    out_s, out_d = sparse_fn(idx, val), dense_fn(idx, val)
    assert np.array_equal(np.asarray(out_s), np.asarray(out_d)), "sparse != dense"
    delta = metrics.snapshot_delta(before, metrics.snapshot())
    sparse_bytes = int(delta["counters"].get("collective.sparse.bytes", 0))
    dense_equiv = int(delta["counters"].get("collective.sparse.dense_equiv_bytes", 0))
    result["sparseGradReduce"] = {
        "dim": dim,
        "nnzPerRow": nnz,
        "rowsPerShard": rows_per_shard,
        "sparseBytes": sparse_bytes,
        "denseEquivalentBytes": dense_equiv,
        "sparseRatio": sparse_bytes / dense_equiv if dense_equiv else None,
        "sparseMs": timed_best(lambda: sparse_fn(idx, val)),
        "denseMs": timed_best(lambda: dense_fn(idx, val)),
    }

    # --- overlap-scheduled SGD: off vs on, bit-identical ---------------------
    n_rows, d = 8192, 256
    X = rng.standard_normal((n_rows, d)).astype(np.float32)
    y = (X @ rng.standard_normal(d) > 0).astype(np.float32)
    kw = dict(max_iter=30, global_batch_size=2048, tol=0.0, learning_rate=0.1)
    with mesh_lib.use_mesh(mesh):
        fits = {}
        for overlap in (False, True):
            sgd = SGD(collective_overlap=overlap, **kw)

            def run(sgd=sgd):
                return sgd.optimize(
                    np.zeros(d, np.float32), X, y, None, BINARY_LOGISTIC_LOSS,
                    mesh=mesh,
                )

            coeff, loss, epochs = run()  # warm (compile)
            fits[overlap] = (coeff, timed_best(run, repeats=3))
        assert np.array_equal(fits[False][0], fits[True][0]), "overlap != eager"
    result["overlapSgd"] = {
        "rows": n_rows,
        "dim": d,
        "maxIter": kw["max_iter"],
        "eagerMs": fits[False][1],
        "overlapMs": fits[True][1],
        "bitIdentical": True,
    }

    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
