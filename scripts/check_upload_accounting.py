#!/usr/bin/env python
"""Upload-accounting gate: no raw host→device transfers in models/ or ops/.

THIN SHIM over the tpulint rule `upload-accounting`
(flink_ml_tpu/analysis/rules/accounting.py) — the scanning engine, the
shared comment/string-stripping source model, and the rule documentation
live there now (docs/static_analysis.md has the catalogue; run
`scripts/tpulint.py` for the full rule set). This entry point keeps the
historical CLI contract: same output lines, same exit code, and the same
`find_violations()` / `ROOT` / `SCANNED_DIRS` module surface that
tests/test_upload_accounting.py exercises.
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flink_ml_tpu.analysis.engine import Project  # noqa: E402
from flink_ml_tpu.analysis.rules.accounting import (  # noqa: E402
    UploadAccountingRule,
)
from flink_ml_tpu.analysis.source import code_only as _code_only  # noqa: E402,F401

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCANNED_DIRS = ("flink_ml_tpu/models", "flink_ml_tpu/ops")


def find_violations() -> List[Tuple[str, int, str]]:
    """(path, line, primitive) for every raw transfer call in scope."""
    rule = UploadAccountingRule()
    rule.scope = tuple(SCANNED_DIRS)
    project = Project.load(root=ROOT, scope=SCANNED_DIRS)
    return [
        (f.path.replace("/", os.sep), f.line, f.data[0])
        for f in sorted(
            rule.check_project(project), key=lambda f: (f.path, f.line)
        )
    ]


def main() -> int:
    violations = find_violations()
    if violations:
        print(
            f"upload accounting: {len(violations)} raw host->device transfer "
            "call(s) bypass the accounted stager "
            "(use flink_ml_tpu.parallel.prefetch.stage_to_device instead):"
        )
        for path, line, prim in violations:
            print(f"  {path}:{line}: jax.{prim}(...)")
        return 1
    print(
        "upload accounting: no raw host->device transfers in "
        + " or ".join(SCANNED_DIRS)
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
