#!/usr/bin/env python
"""Checkpoint-coverage gate: every concrete estimator must state its
checkpoint contract.

THIN SHIM over the tpulint rule `checkpoint-coverage`
(flink_ml_tpu/analysis/rules/coverage.py) — the class-graph walk, the
funnel-reference check on comment/string-stripped source, and the
contract logic live there now (docs/static_analysis.md has the
catalogue; run `scripts/tpulint.py` for the full rule set). This entry
point keeps the historical CLI contract: same output lines, same exit
code, and the same `find_violations()` / `FUNNELS` / `_code_only()`
module surface that tests/test_checkpoint_coverage.py exercises.
"""

from __future__ import annotations

import os
import sys
from typing import List, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flink_ml_tpu.analysis.rules.coverage import (  # noqa: E402
    CHECKPOINT_FUNNELS as FUNNELS,
)
from flink_ml_tpu.analysis.rules.coverage import (  # noqa: E402
    find_checkpoint_violations,
)
from flink_ml_tpu.analysis.source import code_only as _code_only  # noqa: E402,F401


def _iter_estimator_classes():
    from flink_ml_tpu.analysis.rules.coverage import _iter_operator_classes

    return _iter_operator_classes("Estimator")


def find_violations() -> List[Tuple[str, str]]:
    """(qualified class name, problem) for every estimator breaking the
    contract."""
    return find_checkpoint_violations()


def main() -> int:
    violations = find_violations()
    total = len(list(_iter_estimator_classes()))
    if violations:
        print(
            f"checkpoint coverage: {len(violations)} of {total} estimators "
            "violate the contract:"
        )
        for name, problem in violations:
            print(f"  {name}: {problem}")
        return 1
    print(
        f"checkpoint coverage: all {total} concrete estimators declare "
        "their checkpoint contract"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
