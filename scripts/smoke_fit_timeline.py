#!/usr/bin/env python
"""smoke_fit_timeline — traced LR fits for the CI flight-recorder artifact.

Runs TWO small SGD fits with the timeline ring enabled and dumps each
event JSONL (FLINK_ML_TPU_TIMELINE_FILE wins for the first if set):

1. a chunked (checkpointed, `whole_fit` off) fit — the multi-chunk
   dispatch pipeline timeline, -> EVENTS_OUT.jsonl;
2. the SAME fit through the whole-fit resident program (`whole_fit`
   auto, fit-end-only snapshot cadence) — the single-dispatch timeline,
   -> EVENTS_OUT base + "-wholefit.jsonl".

CI renders both with scripts/obs_timeline.py and uploads them as the
per-run Perfetto artifacts (docs/observability.md), so the one-dispatch
claim is visually checkable on every run. The chunked timeline carries
the `memory` counter lane (hbm.live per category) — the HBM track in
Perfetto.

After the fits, a third probe re-runs the chunked fit under a deliberately
tiny HBM budget (config.hbm_budget_mode) and asserts it fails with the
*typed* HbmBudgetExceeded carrying a category breakdown — budget
admission stays deterministic and clean (no raw RESOURCE_EXHAUSTED, no
partial dispatch) on every CI run.

Usage: python scripts/smoke_fit_timeline.py [EVENTS_OUT.jsonl]
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fit(timeline, config, out_path, mode, checkpoint_interval, label):
    import numpy as np

    from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD

    rng = np.random.RandomState(3)
    X = rng.randn(400, 8).astype(np.float32)
    y = (X @ np.linspace(1, -1, 8) > 0).astype(np.float32)
    timeline.configure(ring_size=65536)
    with config.whole_fit_mode(mode), tempfile.TemporaryDirectory() as ckpt_dir:
        sgd = SGD(
            max_iter=56,
            global_batch_size=100,
            tol=0.0,
            checkpoint_dir=ckpt_dir,
            checkpoint_interval=checkpoint_interval,
        )
        _, _, epochs = sgd.optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
    n = timeline.dump_jsonl(out_path)
    attr = timeline.dispatch_attribution()
    timeline.configure()  # reset the ring between the two fits
    print(f"smoke fit ({label}): {epochs} epochs, {n} timeline events -> {out_path}")
    if attr:
        print(
            "attribution: "
            + ", ".join(
                f"{k} {attr[k]:.1f}ms"
                for k in ("windowMs", "dispatchMs", "deviceMs", "readbackMs", "idleGapMs")
            )
            + f" over {attr['gapCount']} chunks"
        )
    return attr


def _budget_probe(config):
    """Re-run the chunked fit under a ~1 KiB HBM budget and demand the
    typed, breakdown-carrying HbmBudgetExceeded — never a raw allocator
    error or a silent success."""
    from flink_ml_tpu.obs import memledger, timeline

    with config.hbm_budget_mode(1024):
        try:
            _fit(timeline, config, os.devnull, "off", 8, "budget-probe")
        except memledger.HbmBudgetExceeded as e:
            if not e.breakdown and e.requested_bytes <= 0:
                print(f"ERROR: HbmBudgetExceeded carries no forensics: {e}")
                return 1
            print(f"budget probe: clean typed rejection: {e}")
            return 0
        except Exception as e:  # noqa: BLE001 — the probe exists to type-check this
            print(f"ERROR: budget probe raised {type(e).__name__}, "
                  f"expected HbmBudgetExceeded: {e}")
            return 1
    print("ERROR: budget probe fit succeeded under a 1 KiB HBM budget")
    return 1


def main(argv):
    out_path = argv[0] if argv else os.environ.get(
        "FLINK_ML_TPU_TIMELINE_FILE", "timeline-events.jsonl"
    )
    from flink_ml_tpu import config
    from flink_ml_tpu.obs import timeline

    config.iteration_chunk_size = 8
    _fit(timeline, config, out_path, "off", 8, "chunked")

    base, ext = os.path.splitext(out_path)
    whole_path = f"{base}-wholefit{ext or '.jsonl'}"
    attr = _fit(timeline, config, whole_path, "auto", 56, "whole-fit")
    if attr and attr.get("gapCount", 0) != 1:
        print(
            f"ERROR: whole-fit timeline recorded {attr.get('gapCount')} "
            "dispatch->drain cycles, expected the single-dispatch timeline"
        )
        return 1
    return _budget_probe(config)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
