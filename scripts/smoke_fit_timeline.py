#!/usr/bin/env python
"""smoke_fit_timeline — one chunked traced LR fit for the CI flight
recorder artifact.

Runs a small chunked (checkpointed) SGD fit with the timeline ring
enabled, dumps the event JSONL (FLINK_ML_TPU_TIMELINE_FILE wins if set),
and prints the dispatch-wall attribution. CI renders the dump with
scripts/obs_timeline.py and uploads both as the per-run Perfetto
artifact (docs/observability.md).

Usage: python scripts/smoke_fit_timeline.py [EVENTS_OUT.jsonl]
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv):
    out_path = argv[0] if argv else os.environ.get(
        "FLINK_ML_TPU_TIMELINE_FILE", "timeline-events.jsonl"
    )
    import numpy as np

    from flink_ml_tpu import config
    from flink_ml_tpu.obs import timeline
    from flink_ml_tpu.ops.losses import BINARY_LOGISTIC_LOSS
    from flink_ml_tpu.ops.optimizer import SGD

    timeline.configure(ring_size=65536)
    config.iteration_chunk_size = 8
    rng = np.random.RandomState(3)
    X = rng.randn(400, 8).astype(np.float32)
    y = (X @ np.linspace(1, -1, 8) > 0).astype(np.float32)
    with tempfile.TemporaryDirectory() as ckpt_dir:
        sgd = SGD(
            max_iter=56,
            global_batch_size=100,
            tol=0.0,
            checkpoint_dir=ckpt_dir,
            checkpoint_interval=8,
        )
        _, _, epochs = sgd.optimize(np.zeros(8), X, y, None, BINARY_LOGISTIC_LOSS)
    n = timeline.dump_jsonl(out_path)
    attr = timeline.dispatch_attribution()
    print(f"smoke fit: {epochs} epochs, {n} timeline events -> {out_path}")
    if attr:
        print(
            "attribution: "
            + ", ".join(
                f"{k} {attr[k]:.1f}ms"
                for k in ("windowMs", "dispatchMs", "deviceMs", "readbackMs", "idleGapMs")
            )
            + f" over {attr['gapCount']} chunks"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
