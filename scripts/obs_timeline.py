#!/usr/bin/env python
"""obs_timeline — render a flight-recorder dump as a Perfetto timeline.

Usage:
    python scripts/obs_timeline.py EVENTS.jsonl [options]

Options:
    -o, --output PATH    Write the Chrome trace-event JSON here (default:
                         EVENTS.timeline.json next to the input). Open it
                         directly in https://ui.perfetto.dev or
                         chrome://tracing.
    --attribution        Also print the dispatch-wall attribution
                         (wall = dispatch + device + readback + idle-gap,
                         totals + per-epoch means) derived from the
                         dispatch/device/readback lanes.
    --json               Print the attribution as JSON instead of text
                         (implies --attribution).

Input: the JSONL a run dumps when `FLINK_ML_TPU_TIMELINE_FILE` is set
(obs/timeline.py writes the ring at process exit), or a span-trace JSONL
from `FLINK_ML_TPU_TRACE_FILE` — span records are converted to complete
events on a single host lane so either capture opens in Perfetto.

Robustness contract: ring truncation and files cut mid-line are expected
inputs — unmatched begin/end events and unparseable lines are dropped
with a warning on stderr, never a crash.

Capture example (a traced chunked fit):

    FLINK_ML_TPU_TIMELINE_FILE=/tmp/fit.events.jsonl \\
        python examples/logisticregression_example.py
    python scripts/obs_timeline.py /tmp/fit.events.jsonl -o /tmp/fit.json
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flink_ml_tpu.obs import timeline  # noqa: E402


def _span_records_to_events(records):
    """Convert span-trace JSONL records ({name, startUs, durUs, attrs})
    into timeline X events on one host lane (Perfetto nests by duration)."""
    events = []
    for r in records:
        if not isinstance(r, dict) or "startUs" not in r:
            continue
        events.append(
            {
                "ph": "X",
                "lane": "host:trace",
                "name": r.get("name", "?"),
                "tsUs": float(r.get("startUs", 0.0)),
                "durUs": float(r.get("durUs", 0.0)),
                "args": r.get("attrs") or None,
            }
        )
    return events


def load_any(path: str):
    """Timeline-event JSONL or span-trace JSONL -> timeline events,
    skipping unparseable (truncated) lines with a count."""
    events, spans, skipped = [], [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                skipped += 1
                continue
            if not isinstance(rec, dict):
                skipped += 1
            elif "ph" in rec and "lane" in rec:
                events.append(rec)
            elif "startUs" in rec and "name" in rec:
                spans.append(rec)
            else:
                skipped += 1
    events.extend(_span_records_to_events(spans))
    events.sort(key=lambda e: e.get("tsUs", 0.0))
    return events, skipped


def main(argv):
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    in_path = argv[0]
    out_path = None
    for flag in ("-o", "--output"):
        if flag in argv:
            out_path = argv[argv.index(flag) + 1]
    if out_path is None:
        base = in_path[:-6] if in_path.endswith(".jsonl") else in_path
        out_path = base + ".timeline.json"

    try:
        events, skipped = load_any(in_path)
    except OSError as e:
        print(f"obs_timeline: cannot read {in_path}: {e}", file=sys.stderr)
        return 2
    if skipped:
        print(
            f"warning: skipped {skipped} unparseable line(s) (truncated capture?)",
            file=sys.stderr,
        )
    if not events:
        print(f"No timeline events in {in_path}.", file=sys.stderr)
        return 1

    doc = timeline.to_chrome(events)
    dropped = doc.get("otherData", {}).get("unmatchedDropped", 0)
    if dropped:
        print(
            f"warning: dropped {dropped} unmatched begin/end event(s) "
            "(ring truncation)",
            file=sys.stderr,
        )
    with open(out_path, "w") as f:
        json.dump(doc, f)
    lanes = sum(1 for e in doc["traceEvents"] if e.get("name") == "thread_name")
    print(
        f"Wrote {out_path}: {len(doc['traceEvents'])} trace events on "
        f"{lanes} lanes (open in https://ui.perfetto.dev)."
    )

    if "--attribution" in argv or "--json" in argv:
        attr = timeline.dispatch_attribution(events)
        if not attr:
            print("No dispatch-lane events: attribution unavailable.")
            return 0
        if "--json" in argv:
            print(json.dumps(attr, indent=2))
        else:
            print(
                "\nDispatch-wall attribution "
                "(wall = dispatch + device + readback + idle-gap):"
            )
            print(
                f"  window {attr['windowMs']:.1f} ms over {attr['gapCount']} "
                f"chunk(s)"
                + (f", {attr['epochs']} epochs" if "epochs" in attr else "")
            )
            for key in ("dispatchMs", "deviceMs", "readbackMs", "idleGapMs"):
                share = 100.0 * attr[key] / attr["windowMs"] if attr["windowMs"] else 0.0
                print(f"  {key:12s} {attr[key]:10.1f} ms  ({share:.0f}%)")
            if "perEpoch" in attr:
                per = attr["perEpoch"]
                print(
                    "  per epoch: "
                    + ", ".join(f"{k} {v:.3f} ms" for k, v in per.items())
                )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
