#!/usr/bin/env python
"""tpulint CLI — run the flink_ml_tpu static-analysis rules.

Usage:
  scripts/tpulint.py                 # lint flink_ml_tpu/ with every rule
  scripts/tpulint.py --changed       # only report findings in files that
                                     # differ from HEAD (fast pre-commit);
                                     # project-wide rules still see the
                                     # whole tree
  scripts/tpulint.py --list-rules    # print the rule catalogue
  scripts/tpulint.py --rule host-sync-leak [--rule ...]   # subset of rules
  scripts/tpulint.py path/to/file.py [...]                # subset of files
  scripts/tpulint.py --show-suppressed   # also print what suppressions hid

Exit status: 0 when there are no unsuppressed findings, 1 otherwise.
Suppress a deliberate finding with an inline (or preceding-line) comment:

    # tpulint: disable=<rule-id> -- <reason>

Unused suppressions are themselves findings (unused-suppression). The
rule catalogue with rationale and examples lives in
docs/static_analysis.md.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from flink_ml_tpu.analysis import engine  # noqa: E402


def _changed_files(root: str) -> list:
    """Repo-relative .py files differing from HEAD (staged, unstaged, and
    untracked)."""
    out = subprocess.run(
        ["git", "diff", "--name-only", "HEAD"],
        cwd=root,
        capture_output=True,
        text=True,
        check=True,
    ).stdout
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        cwd=root,
        capture_output=True,
        text=True,
        check=True,
    ).stdout
    files = []
    for line in (out + untracked).splitlines():
        line = line.strip()
        if line.endswith(".py") and os.path.exists(os.path.join(root, line)):
            files.append(line)
    return sorted(set(files))


def _list_rules() -> int:
    for rule in engine.all_rules():
        print(f"{rule.id}: {rule.title}")
        print(f"  scope: {', '.join(rule.scope)}")
        for line in textwrap.wrap(rule.rationale, width=74):
            print(f"  {line}")
        if rule.example:
            for line in rule.example.splitlines():
                print(f"  e.g. {line}")
        print()
    print(
        f"{engine.UNUSED_SUPPRESSION}: a `# tpulint: disable=` comment that "
        "matches no finding\n  (built-in; stale annotations rot the audit "
        "trail and are errors)"
    )
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpulint", description="flink_ml_tpu static analysis"
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="repo-relative files to report on (default: whole package)",
    )
    parser.add_argument(
        "--changed",
        action="store_true",
        help="report only files differing from HEAD (fast pre-commit mode)",
    )
    parser.add_argument(
        "--rule",
        action="append",
        dest="rules",
        metavar="RULE_ID",
        help="run only the given rule id (repeatable)",
    )
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print findings hidden by suppressions (the sync census)",
    )
    parser.add_argument(
        "--root",
        default=None,
        help="lint a different tree root (fixture trees in tests; the "
        "scanned scope is still <root>/flink_ml_tpu)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        return _list_rules()

    root = os.path.abspath(args.root) if args.root else engine.REPO_ROOT
    rules = None
    if args.rules:
        known = {r.id for r in engine.all_rules()}
        for rule_id in args.rules:
            if rule_id not in known:
                parser.error(
                    f"unknown rule {rule_id!r} (see --list-rules)"
                )
        rules = [engine.get_rule(rule_id) for rule_id in args.rules]

    only_paths = None
    if args.changed:
        only_paths = _changed_files(root)
        if not only_paths:
            print("tpulint: no files differ from HEAD")
            return 0
    if args.paths:
        normalized = [
            os.path.relpath(os.path.abspath(p), root).replace(os.sep, "/")
            for p in args.paths
        ]
        only_paths = (
            normalized
            if only_paths is None
            else sorted(set(only_paths) & set(normalized))
        )

    report = engine.run(root=root, rules=rules, only_paths=only_paths)

    if args.show_suppressed and report.suppressed:
        print(f"-- {len(report.suppressed)} suppressed finding(s):")
        for finding in report.suppressed:
            print(f"   {finding.format()}")
    for finding in report.findings:
        print(finding.format())
    if report.findings:
        print(
            f"tpulint: {len(report.findings)} finding(s) "
            f"({len(report.suppressed)} suppressed)"
        )
        return 1
    print(
        f"tpulint: clean ({len(report.suppressed)} suppressed finding(s) "
        "— run --show-suppressed for the census)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
